"""Adaptive execution: the feedback loop from recorded plan-actuals and
measured compile costs to plan decisions.

Reference: the reference engine's adaptive planning (FaultTolerantExecution
re-plans from runtime stats) and TQP (arxiv 2203.01877), which selects tensor
execution strategies from runtime shapes.  Rounds 15-17 built both halves of
a runtime cost model — per-node est-vs-actual cardinalities
(``PlanHistoryStore``) supply the benefit side, per-compilation measured
durations (``CompileLog``) supply the price side — and this module is THE
chokepoint where that record turns into a decision.  Nothing under exec/ or
sql/ reads ``plan_history``/``compile_log`` directly (test_boundary_lint
enforces it): decision logic lives here, the planner merely consumes the
emitted correction facts.

``AdaptiveAdvisor`` is host-only: consult/observe are dict walks over
snapshots the engine already holds — zero ``_jit`` dispatches, zero ``_host``
pulls (the budget suite runs with the advisor enabled and its ceilings pin
that).

Decision model
--------------
At statement admission the engine asks ``consult(key)`` with the statement's
plan-cache key.  The advisor keeps per-statement state fed by ``observe()``
(called on every clean completion with the execution's structural plan
fingerprint): the UNCORRECTED fingerprint is the history address, its
recorded per-node walls are the win model, and its observed cold
``compile_s`` is the primary re-plan price.

A statement becomes a re-plan candidate when its history holds a MATERIAL
misestimate: worst per-node ratio >= ``threshold`` (default 4x) on the
EWMA-backed ratio (``actual_rows_ewma`` vs est — one outlier execution is
damped by EWMA_ALPHA and cannot flip a plan), where the node has a real
estimate (``unestimated`` nodes — CBO-blind, not CBO-wrong — never produce a
correction) and the direction is actionable: "under" anywhere (the expensive
failure mode: undersized hash tables, missed partitioned joins), or "over"
on a join BUILD side (a partitioned build that measured tiny should flip
back to broadcast).

Corrections emitted (all host facts, applied by sql/exchanges at plan time):
  rows:           {node_path: observed EWMA rows} — cardinality facts the
                  estimator treats as CONFIDENT, so the existing
                  DetermineJoinDistributionType thresholds re-decide
                  broadcast vs partitioned from truth (correction (b) falls
                  out of correction (a));
  capacity:       {Aggregate path: pow2 slot count} seeded from observed
                  group counts (generalizes r11's exact-spilled-rows seed);
  grace_parts:    {Aggregate path: pow2 partitions} when the node spilled;
  dispatch_batch: K tuned up from observed split counts.

Win-vs-price: predicted win = sum over material nodes of their average
recorded wall x (1 - 1/min(ratio, 10)), amortized over ``horizon`` expected
re-executions; the price is the statement's own observed cold compile
seconds (fallback: per-op mean durations from the compile log).  Unknown
price = assume expensive, hold.  ``price_scale`` is the test hook (0 forces
re-plan, huge forces hold).

Probation (the r14 template pattern): a fresh correction freezes its token +
corrections (a drifting EWMA must never re-key a new plan every run) and
enters "probation"; the first WARM corrected run (compiles == 0) confirms it
when its wall is no worse than the uncorrected EWMA, else demotes.  A
demoted or failed correction enters a negative-cache cooldown counted in
uncorrected executions before the statement is reconsidered.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from typing import Optional

__all__ = ["AdaptiveAdvisor", "ADAPTIVE_THRESHOLD"]

# material-misestimate bar for a correction (2x merely counts as a
# misestimate in history; 4x is where a re-plan pays for itself)
ADAPTIVE_THRESHOLD = 4.0

# expected warm re-executions a correction's win amortizes its recompile over
DEFAULT_HORIZON = 8.0

# uncorrected executions a demoted statement sits out before reconsideration
DEFAULT_COOLDOWN = 8

# a warm corrected run regresses when its wall exceeds the uncorrected EWMA
# by this factor (plus a small absolute floor so millisecond statements do
# not demote on scheduler noise)
REGRESS_FACTOR = 1.5
REGRESS_FLOOR_S = 0.005

WALL_EWMA_ALPHA = 0.25  # same damping the history store uses for rows

MAX_CAPACITY = 1 << 24  # mirror of the executor's capacity-estimate cap
MAX_DISPATCH_BATCH = 16

_RATIO_CAP = 10.0  # win model: beyond 10x the extra ratio buys nothing


def _env_float(name: str, default: float) -> float:
    try:
        v = os.environ.get(name, "")
        return float(v) if v != "" else default
    except ValueError:
        return default


def _pow2_at_least(n: float) -> int:
    return 1 << max(int(n) - 1, 1).bit_length()


def correction_token(corrections: dict) -> str:
    """Stable short token for one frozen corrections dict — the plan-cache /
    result-cache key component that keys corrected plans separately."""
    return hashlib.blake2b(repr(sorted(
        (k, sorted(v.items()) if isinstance(v, dict) else v)
        for k, v in corrections.items())).encode(),
        digest_size=6).hexdigest()


class AdaptiveAdvisor:
    """Per-statement adaptive state machine over the plan-history store and
    the compile log.  Thread-safe; bounded LRU over statement keys."""

    MAX_STATEMENTS = 256

    def __init__(self, history=None, compile_log=None,
                 threshold: Optional[float] = None,
                 horizon: Optional[float] = None,
                 cooldown: Optional[int] = None,
                 price_scale: float = 1.0):
        self.history = history
        self.compile_log = compile_log
        self.threshold = threshold if threshold is not None else _env_float(
            "TRINO_TPU_ADAPTIVE_THRESHOLD", ADAPTIVE_THRESHOLD)
        self.horizon = horizon if horizon is not None else _env_float(
            "TRINO_TPU_ADAPTIVE_HORIZON", DEFAULT_HORIZON)
        self.cooldown = cooldown if cooldown is not None else int(_env_float(
            "TRINO_TPU_ADAPTIVE_COOLDOWN", DEFAULT_COOLDOWN))
        # test/ops hook: multiplies the compile price in the comparison
        # (0.0 = re-plan whenever material, large = always hold)
        self.price_scale = price_scale
        self._lock = threading.Lock()
        self._states: OrderedDict = OrderedDict()  # stmt key -> state dict
        self.replans_total = 0
        self.holds_total = 0
        self.demotions_total = 0
        self.confirms_total = 0

    # ------------------------------------------------------------- state
    def _state(self, key) -> dict:
        st = self._states.get(key)
        if st is None:
            st = self._states[key] = {
                "state": "watching", "base_fp": None, "sql": None,
                "base_wall_ewma": None, "base_execs": 0,
                "compile_s_obs": 0.0, "corrected_execs": 0,
                "corrections": None, "token": None, "decision": None,
                "cooldown": 0, "last_verdict": None}
            while len(self._states) > self.MAX_STATEMENTS:
                self._states.popitem(last=False)
        else:
            self._states.move_to_end(key)
        return st

    # ------------------------------------------------------------ consult
    def consult(self, key, peek: bool = False) -> Optional[dict]:
        """The admission-time question: should this statement's next
        execution run a corrected plan?  Returns None (no opinion — no
        history, nothing material, or cooling down without a counted hold
        when ``peek``), or a decision dict:

          {"verdict": "replan"|"hold", "token", "corrections",
           "predicted_win_s", "compile_price_s", "horizon",
           "fingerprint", "reasons": [...]}

        "replan" decisions are FROZEN: once emitted, the same token and
        corrections return on every consult until the correction confirms,
        demotes or fails — recomputing from a drifting EWMA would re-key (and
        recompile) a fresh plan every run.  ``peek`` is the read-only form
        (plain EXPLAIN): no state transition, no hold accounting."""
        hist = self.history
        if hist is None or not getattr(hist, "enabled", False):
            return None
        with self._lock:
            st = self._states.get(key)
            if st is None:
                return None
            self._states.move_to_end(key)
            if st["state"] in ("probation", "confirmed"):
                return dict(st["decision"])
            if st["state"] == "demoted":
                if peek:
                    return None
                dec = self._decision(st, "hold", reasons=[
                    f"demoted correction cooling down "
                    f"({st['cooldown']} uncorrected executions left)"])
                self.holds_total += 1
                st["last_verdict"] = "hold"
                return dec
            base_fp = st["base_fp"]
        if base_fp is None:
            return None
        ent = hist.get(base_fp)
        if ent is None or not ent.get("nodes"):
            return None
        material = self._material(base_fp)
        if not material:
            return None
        corrections, reasons = self._corrections(ent, material)
        if not corrections:
            return None
        win = self._predicted_win_s(material)
        with self._lock:
            st = self._state(key)
            if st["state"] != "watching":  # raced another thread
                return dict(st["decision"]) \
                    if st["state"] in ("probation", "confirmed") else None
            price = self._compile_price(st, ent)
            if peek:
                return self._decision(
                    st, "hold", corrections=corrections, win=win,
                    price=price, fingerprint=ent.get("fingerprint"),
                    reasons=reasons + ["peek: no state transition"])
            if price is None:
                dec = self._decision(
                    st, "hold", corrections=corrections, win=win,
                    price=None, fingerprint=ent.get("fingerprint"),
                    reasons=reasons + [
                        "compile price unknown — assume expensive"])
                self.holds_total += 1
                st["last_verdict"] = "hold"
                return dec
            scaled = price * self.price_scale
            if win * self.horizon <= scaled:
                dec = self._decision(
                    st, "hold", corrections=corrections, win=win,
                    price=price, fingerprint=ent.get("fingerprint"),
                    reasons=reasons + [
                        f"predicted win {win:.4f}s x {self.horizon:g} <= "
                        f"compile price {scaled:.4f}s"])
                self.holds_total += 1
                st["last_verdict"] = "hold"
                return dec
            # take the re-plan: freeze the corrections + token, enter
            # probation (r14 template pattern — unconfirmed until the first
            # warm corrected run measures no worse than the base EWMA)
            st["state"] = "probation"
            st["corrections"] = corrections
            st["token"] = correction_token(corrections)
            st["corrected_execs"] = 0
            dec = self._decision(
                st, "replan", corrections=corrections, win=win, price=price,
                fingerprint=ent.get("fingerprint"),
                reasons=reasons + [
                    f"predicted win {win:.4f}s x {self.horizon:g} > "
                    f"compile price {price * self.price_scale:.4f}s"])
            st["decision"] = dec
            self.replans_total += 1
            st["last_verdict"] = "replan"
            return dict(dec)

    def _decision(self, st, verdict, corrections=None, win=None, price=None,
                  fingerprint=None, reasons=None) -> dict:
        return {"verdict": verdict,
                "token": st.get("token") if verdict == "replan" else None,
                "corrections": corrections or st.get("corrections"),
                "predicted_win_s": None if win is None else round(win, 6),
                "compile_price_s": None if price is None else round(price, 6),
                "horizon": self.horizon,
                "fingerprint": fingerprint,
                "reasons": list(reasons or [])}

    # ------------------------------------------------------- the cost model
    def _material(self, fingerprint: str) -> dict:
        """{path: node record} for nodes whose misestimate is both LARGE
        (``history.misestimated`` — EWMA ratio >= threshold on a REAL
        estimate; CBO-blind nodes never qualify) and ACTIONABLE: direction
        "under" anywhere, or "over" on a join build side."""
        qualifying = self.history.misestimated(fingerprint, self.threshold)
        return {path: r for path, r in qualifying.items()
                if r.get("direction") == "under"
                or (r.get("direction") == "over" and r.get("build"))}

    def _corrections(self, ent: dict, material: dict) -> tuple:
        corrections: dict = {"rows": {}}
        reasons: list = []
        for path, r in sorted(material.items()):
            rows = max(float(r.get("actual_rows_ewma", 0.0)), 1.0)
            corrections["rows"][path] = rows
            reasons.append(
                f"{path}: est {r['est_rows']:.0f} -> observed {rows:.0f} "
                f"({r.get('misestimate_ratio')}x {r.get('direction')})")
            if r.get("op") == "Aggregate" and r.get("direction") == "under":
                # capacity seeded at 2x observed groups (the executor's own
                # estimate-to-capacity rule), pow2, capped like the executor
                cap = min(_pow2_at_least(2.0 * rows), MAX_CAPACITY)
                corrections.setdefault("capacity", {})[path] = cap
                reasons.append(f"{path}: capacity {cap}")
                if r.get("spill_tiers") or r.get("spilled_bytes"):
                    parts = max(4, _pow2_at_least(rows / float(1 << 20)))
                    corrections.setdefault("grace_parts", {})[path] = parts
                    reasons.append(f"{path}: grace_parts {parts}")
        # dispatch_batch K from observed split counts: rides along only when
        # a re-plan is already triggered — more splits per dispatch means
        # fewer device round-trips on deep scans
        splits = max((int(r.get("splits") or 0)
                      for r in ent.get("nodes", {}).values()), default=0)
        if splits:
            from ..exec.local_executor import _dispatch_batch_default

            cur = _dispatch_batch_default()
            if splits > 2 * cur:
                k = min(MAX_DISPATCH_BATCH,
                        max(cur, _pow2_at_least(splits / 4.0)))
                if k > cur:
                    corrections["dispatch_batch"] = k
                    reasons.append(f"dispatch_batch {cur} -> {k} "
                                   f"({splits} splits)")
        if not corrections["rows"]:
            return {}, []
        return corrections, reasons

    def _predicted_win_s(self, material: dict) -> float:
        win = 0.0
        for r in material.values():
            execs = max(int(r.get("executions", 1)), 1)
            avg_wall = float(r.get("wall_s_total", 0.0)) / execs
            ratio = min(float(r.get("misestimate_ratio", 1.0)), _RATIO_CAP)
            win += avg_wall * (1.0 - 1.0 / max(ratio, 1.0))
        return win

    def _compile_price(self, st: dict, ent: dict) -> Optional[float]:
        """Re-plan price in seconds: the statement's own observed cold
        compile cost when we saw one, else per-op mean compile durations
        from the census for the operators this plan holds.  None = unknown
        (assume expensive — the caller holds)."""
        if st.get("compile_s_obs", 0.0) > 0.0:
            return float(st["compile_s_obs"])
        log = self.compile_log
        if log is None:
            return None
        ops = {r.get("op") or p.partition("#")[0]
               for p, r in ent.get("nodes", {}).items()}
        sums: dict = {}
        counts: dict = {}
        try:
            recs = log.snapshot()
        except Exception:
            return None
        for rec in recs:
            op = str(rec.get("label", "")).partition("#")[0]
            if op in ops:
                sums[op] = sums.get(op, 0.0) + float(
                    rec.get("duration_s") or 0.0)
                counts[op] = counts.get(op, 0) + 1
        if not counts:
            return None
        return sum(sums[op] / counts[op] for op in counts)

    # ------------------------------------------------------------ feedback
    def observe(self, key, fingerprint: str, corrected: bool,
                wall_s: float, compiles: int = 0,
                compile_s: float = 0.0, sql: Optional[str] = None) -> None:
        """One clean completion's feedback (engine._record_plan_history).
        Uncorrected executions anchor the statement's history address (the
        base fingerprint), its wall EWMA (the regression yardstick) and its
        observed cold compile price; corrected executions drive the
        probation verdict — the first WARM corrected run (compiles == 0)
        confirms or demotes against the base EWMA."""
        with self._lock:
            st = self._state(key)
            if sql is not None and st["sql"] is None:
                st["sql"] = sql
            if not corrected:
                st["base_fp"] = fingerprint
                st["base_execs"] += 1
                w = float(wall_s)
                st["base_wall_ewma"] = w if st["base_wall_ewma"] is None \
                    else (WALL_EWMA_ALPHA * w
                          + (1.0 - WALL_EWMA_ALPHA) * st["base_wall_ewma"])
                if compiles > 0 and compile_s > st["compile_s_obs"]:
                    st["compile_s_obs"] = float(compile_s)
                if st["state"] == "demoted":
                    st["cooldown"] -= 1
                    if st["cooldown"] <= 0:
                        st["state"] = "watching"
                        st["corrections"] = None
                        st["token"] = None
                return
            st["corrected_execs"] += 1
            if st["state"] not in ("probation", "confirmed"):
                return
            if compiles > 0:
                return  # cold corrected run: its wall is compile-dominated
            base = st["base_wall_ewma"]
            if base is not None and float(wall_s) > (
                    base * REGRESS_FACTOR + REGRESS_FLOOR_S):
                self._demote(st)
            elif st["state"] == "probation":
                st["state"] = "confirmed"
                self.confirms_total += 1

    def failed(self, key) -> None:
        """A corrected execution RAISED: demote immediately (probation or
        confirmed — a correction that breaks a working statement is worse
        than any misestimate)."""
        with self._lock:
            st = self._states.get(key)
            if st is not None and st["state"] in ("probation", "confirmed"):
                self._demote(st)

    def _demote(self, st: dict) -> None:
        st["state"] = "demoted"
        st["cooldown"] = self.cooldown
        st["token"] = None
        self.demotions_total += 1

    # ------------------------------------------------------------ surfaces
    def decision_trace(self) -> list:
        """Per-statement decision state, LRU-oldest first — what
        ``scripts/query_counters.py --adaptive`` prints and the flight
        viewer summarizes."""
        with self._lock:
            out = []
            for key, st in self._states.items():
                dec = st.get("decision") or {}
                out.append({
                    "sql": st.get("sql"),
                    "state": st["state"],
                    "base_executions": st["base_execs"],
                    "corrected_executions": st["corrected_execs"],
                    "base_wall_ewma_s": st["base_wall_ewma"],
                    "compile_price_s": st["compile_s_obs"] or None,
                    "token": st.get("token"),
                    "cooldown": st.get("cooldown"),
                    "last_verdict": st.get("last_verdict"),
                    "corrections": st.get("corrections"),
                    "predicted_win_s": dec.get("predicted_win_s"),
                    "reasons": dec.get("reasons"),
                })
            return out

    def info(self) -> dict:
        with self._lock:
            return {"statements": len(self._states),
                    "replans_total": self.replans_total,
                    "holds_total": self.holds_total,
                    "demotions_total": self.demotions_total,
                    "confirms_total": self.confirms_total,
                    "threshold": self.threshold,
                    "horizon": self.horizon}


def describe_decision(dec: Optional[dict]) -> Optional[str]:
    """One-line human rendering of a decision dict (EXPLAIN ANALYZE's
    "Adaptive:" line, scripts/flight.py)."""
    if not dec:
        return None
    win = dec.get("predicted_win_s")
    price = dec.get("compile_price_s")
    arith = ""
    if win is not None:
        arith = f" predicted win {win:.4f}s x {dec.get('horizon', 0):g}" + (
            f" vs compile price {price:.4f}s" if price is not None
            else " vs unknown compile price")
    corr = dec.get("corrections") or {}
    parts = []
    for path, rows in sorted((corr.get("rows") or {}).items()):
        parts.append(f"rows {path} -> {rows:.0f}")
    for path, cap in sorted((corr.get("capacity") or {}).items()):
        parts.append(f"capacity {path} -> {cap}")
    for path, gp in sorted((corr.get("grace_parts") or {}).items()):
        parts.append(f"grace_parts {path} -> {gp}")
    if corr.get("dispatch_batch"):
        parts.append(f"dispatch_batch -> {corr['dispatch_batch']}")
    detail = ("; " + ", ".join(parts)) if parts else ""
    return f"{dec.get('verdict', '?')}{arith}{detail}"
