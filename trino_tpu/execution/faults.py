"""Deterministic fault injection at every device-boundary chokepoint.

Rounds 6-9 funneled every device dispatch, host pull, split generation, H2D
staging pass, cache store/checkout, exchange segment and memory reservation
through a handful of chokepoints (``_jit``/``_host``/``_scan_pages_source``/
``_page_to_device``/``DeviceBufferPool``/``SpoolingExchange``/
``MemoryPool.try_reserve``) — which means ONE injector hooked inside those
chokepoints can fault the whole engine, and the boundary lint that forces new
executor code through them guarantees new code is injectable too (the same
trick round 8 used for the in-flight registry).  Reference:
execution/FailureInjector.java (TASK_FAILURE / GET_RESULTS_FAILURE points,
deterministic per-task arming); TQP (arxiv 2203.01877) and "Accelerating
Presto with GPUs" (arxiv 2606.24647) both call accelerator-resident state the
hard part of failure handling — the chaos suite in tests/test_chaos.py drives
these faults through exactly that state.

Design rules:

- **Deterministic.**  Triggers are counter-based ("the Nth match", "every
  Nth") or seeded-hash probabilities (splitmix64 over (seed, match index)) —
  never wall clock, never the global RNG.  Two identical runs inject
  identically.
- **Zero cost when disarmed.**  ``maybe_inject`` is one module-global read
  and a ``None`` test; it adds no dispatches, pulls, or allocations, so the
  warm-path budget ceilings (tests/test_query_budgets.py) are untouched.
- **Typed outcomes.**  ``action=error`` raises :class:`InjectedFaultError`
  (retryable — the FTE/cluster classify it like transient connector IO);
  ``action=fatal`` raises :class:`FatalInjectedFaultError` (classified
  deterministic, never retried).  ``delay`` sleeps inline; ``drop``, ``deny``
  and ``kill_worker`` return the action string for the chokepoint to enact
  (skip a commit, refuse a reservation/cache admission, crash the worker).

Arming:

- ``TRINO_TPU_FAULTS`` (read once at import): rules separated by ``;``,
  ``key=value`` fields separated by ``,``.  Example::

      TRINO_TPU_FAULTS="point=dispatch,site=Aggregate*,nth=3,action=error;
                        point=reserve,site=join-build,action=deny,every=2"

  Fields: ``point`` (required — one of POINTS below), ``site`` (fnmatch glob
  matched against BOTH the bare site tag, e.g. ``agg.finalize`` or
  ``join-build``, and the composed "<Op>#<k>/<site>" label when an operator
  scope is active — so ``site=Aggregate*`` targets an operator and
  ``site=join-build`` targets a tag; default ``*``), ``query`` (glob over the
  active query/task id), ``action`` (``error``/``fatal``/``delay``/``drop``/
  ``deny``/``kill_worker``, default ``error``), ``s`` (delay seconds),
  ``nth``/``every``/``p``+``seed`` (trigger), ``times`` (max fires; default 1
  for ``nth``, unlimited otherwise).
- Test API: ``faults.arm(FaultPlan.parse(spec))`` / ``faults.disarm()`` or
  the ``faults.injected(spec)`` context manager — no monkeypatching.

Injection points (the ``point`` vocabulary)::

    dispatch       exec/local_executor._jit     (every compiled-fn invocation)
    host_pull      exec/local_executor._host    (every batched D2H pull)
    generate       _scan_pages_source           (per-split connector generate)
    h2d            _page_to_device              (H2D staging chokepoint)
    cache_store    DeviceBufferPool.put_page/put_build/put_result
                   (sites: page.<table> | build | result)
    cache_checkout DeviceBufferPool.get_page/get_build/get_result
                   (sites: page.<table> | build | result)
    exchange_write exec/fte.SpoolingExchange.commit; mesh exchange route/merge
                   steps (exec/distributed._exchange_fault — sites
                   dist.exchange.route, dist.agg.merge,
                   dist.join.build_exchange)
    exchange_read  exec/fte.SpoolingExchange.read; mesh exchange consumer
                   boundary (sites dist.exchange.read, dist.agg.groups).
                   On the mesh any RETURNED action (drop/deny) raises typed:
                   an all-to-all is one SPMD program, it cannot drop a
                   commit or defer a reader
    task           server/cluster worker task body
    reserve        memory.MemoryPool.try_reserve
    spill_write    exec/spill tier admission/write (site spill.hbm/host/disk)
    spill_read     exec/spill partition readback (site spill.<tier>.read)

Round 12's result-cache tier reuses the cache points with site ``result``:
a checkout ``deny`` serves a miss (the statement executes — recoverable,
byte-identical), a store ``deny``/``error`` skips the admission (the engine's
store guard keeps the query successful and the entry absent either way).

Round 11 adds the spill ladder's points and the ``disk_full`` action: a
``deny`` at ``spill_write`` makes that TIER refuse (the chunk overflows to
the next rung — recoverable by construction), while ``disk_full`` at the
disk tier (the last rung) surfaces as the typed
``exec.spill.SpillCapacityError``; at ``spill_read`` any non-raising action
is enacted as a typed read failure (the data is only in that tier —
there is nothing to fall back to locally).
"""

from __future__ import annotations

import contextlib
import dataclasses
import fnmatch
import threading
import time
from typing import Optional

__all__ = ["InjectedFaultError", "FatalInjectedFaultError", "FaultRule",
           "FaultPlan", "POINTS", "ACTIVE", "arm", "disarm", "active",
           "injected", "maybe_inject"]

POINTS = ("dispatch", "host_pull", "generate", "h2d", "cache_store",
          "cache_checkout", "exchange_write", "exchange_read", "task",
          "reserve", "spill_write", "spill_read")

ACTIONS = ("error", "fatal", "delay", "drop", "deny", "kill_worker",
           "disk_full")


class InjectedFaultError(RuntimeError):
    """A RETRYABLE injected fault — classified like transient connector IO by
    exec/fte.is_retryable_failure, so retry/replay/speculation paths engage."""


class FatalInjectedFaultError(InjectedFaultError):
    """A NON-RETRYABLE injected fault — classified deterministic; every retry
    path must surface it immediately instead of burning its budget."""


_M64 = (1 << 64) - 1


def _mix64(seed: int, i: int) -> int:
    """splitmix64-style mix of (seed, match index): the seeded-probability
    trigger's only randomness source — reproducible across runs/processes."""
    x = (seed * 0x9E3779B97F4A7C15 + i * 0xBF58476D1CE4E5B9 + 1) & _M64
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & _M64
    x ^= x >> 27
    x = (x * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


@dataclasses.dataclass
class FaultRule:
    point: str
    site: str = "*"            # fnmatch glob over the site label
    query: str = "*"           # fnmatch glob over the active query/task id
    action: str = "error"
    seconds: float = 0.0       # delay duration for action=delay
    nth: Optional[int] = None    # fire exactly on the Nth match (1-based)
    every: Optional[int] = None  # fire on every Nth match
    p: Optional[float] = None    # seeded probability per match
    seed: int = 0
    times: Optional[int] = None  # max fires (None = unlimited)
    # runtime state (not part of the spec)
    matches: int = 0
    fires: int = 0

    def __post_init__(self):
        if self.point not in POINTS:
            raise ValueError(f"unknown fault point {self.point!r} "
                             f"(expected one of {POINTS})")
        if self.action not in ACTIONS:
            raise ValueError(f"unknown fault action {self.action!r} "
                             f"(expected one of {ACTIONS})")
        if self.times is None and self.nth is not None:
            self.times = 1  # "the Nth match" is inherently a single fire

    def should_fire(self) -> bool:
        """Caller holds the plan lock and has already bumped ``matches``."""
        if self.times is not None and self.fires >= self.times:
            return False
        if self.nth is not None:
            return self.matches == self.nth
        if self.every is not None:
            return self.matches % self.every == 0
        if self.p is not None:
            return _mix64(self.seed, self.matches) < int(self.p * (_M64 + 1))
        return True

    def spec(self) -> str:
        parts = [f"point={self.point}"]
        if self.site != "*":
            parts.append(f"site={self.site}")
        if self.query != "*":
            parts.append(f"query={self.query}")
        parts.append(f"action={self.action}")
        if self.action == "delay":
            parts.append(f"s={self.seconds}")
        for k in ("nth", "every", "p", "times"):
            v = getattr(self, k)
            if v is not None:
                parts.append(f"{k}={v}")
        if self.p is not None:
            parts.append(f"seed={self.seed}")
        return ",".join(parts)


def _parse_rule(text: str) -> FaultRule:
    kw: dict = {}
    for field in text.split(","):
        field = field.strip()
        if not field:
            continue
        if "=" not in field:
            raise ValueError(f"fault rule field {field!r} is not key=value "
                             f"(in rule {text!r})")
        k, v = field.split("=", 1)
        k, v = k.strip(), v.strip()
        if k in ("point", "site", "query", "action"):
            kw[k] = v
        elif k in ("nth", "every", "times", "seed"):
            kw[k] = int(v)
        elif k == "p":
            kw[k] = float(v)
        elif k == "s":
            kw["seconds"] = float(v)
        else:
            raise ValueError(f"unknown fault rule key {k!r} in {text!r}")
    if "point" not in kw:
        raise ValueError(f"fault rule {text!r} has no point=")
    return FaultRule(**kw)


class FaultPlan:
    """An armed set of rules.  ``fire`` is the one entry the chokepoints
    call; per-rule match counters live under one lock so concurrent worker
    threads see one deterministic global match order per rule (entry order is
    scheduler-dependent under true concurrency — single-driver chaos runs,
    the test suite's shape, are fully deterministic)."""

    def __init__(self, rules):
        self.rules = list(rules)
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        rules = [_parse_rule(r) for r in spec.split(";") if r.strip()]
        if not rules:
            raise ValueError(f"fault spec {spec!r} contains no rules")
        return cls(rules)

    def fire(self, point: str, site: str, query: Optional[str],
             label: Optional[str] = None) -> Optional[str]:
        """Match + trigger every rule for this event.  ``site`` is the bare
        chokepoint tag; ``label`` the composed "<Op>#<k>/<site>" form when an
        operator scope is active — a rule's site glob may address either.
        Raises for error/fatal actions, sleeps for delay, returns
        "drop"/"deny"/"kill_worker"/"disk_full" for the chokepoint to enact
        (first such action wins), else None."""
        fired: list = []
        with self._lock:
            for r in self.rules:
                if r.point != point:
                    continue
                if r.site != "*" \
                        and not fnmatch.fnmatchcase(site, r.site) \
                        and not (label is not None
                                 and fnmatch.fnmatchcase(label, r.site)):
                    continue
                if r.query != "*" and not fnmatch.fnmatchcase(query or "",
                                                              r.query):
                    continue
                r.matches += 1
                if r.should_fire():
                    fired.append(r)
        if not fired:
            return None
        from . import tracing

        result = None
        for r in fired:
            # count the fire as the action is ENACTED, not at match time: if
            # an earlier rule's raise aborts this loop, the unenacted rules
            # keep their ``times`` budget (and their ``fires`` stays honest —
            # chaos "fires>=1" assertions must imply the action happened)
            with self._lock:
                if r.times is not None and r.fires >= r.times:
                    continue  # a concurrent event enacted the last fire
                r.fires += 1
            tracing.record_fault(site=f"fault.{point}.{r.action}")
            msg = (f"injected {r.action} at {point}/{label or site} "
                   f"({r.spec()})")
            if r.action == "fatal":
                raise FatalInjectedFaultError(msg)
            if r.action == "error":
                raise InjectedFaultError(msg)
            if r.action == "delay":
                time.sleep(r.seconds)
            elif result is None:
                result = r.action  # drop | deny | kill_worker | disk_full
        return result

    def stats(self) -> list:
        with self._lock:
            return [{"rule": r.spec(), "matches": r.matches, "fires": r.fires}
                    for r in self.rules]

    def total_fires(self) -> int:
        with self._lock:
            return sum(r.fires for r in self.rules)


# the process-global armed plan; None (the default) = injection disabled.
# Chokepoints read this through maybe_inject — one global load + None test.
ACTIVE: Optional[FaultPlan] = None


def arm(plan) -> FaultPlan:
    """Arm a FaultPlan (or parse and arm a spec string).  Returns the plan so
    tests can read its per-rule stats afterwards."""
    global ACTIVE
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan)
    ACTIVE = plan
    return plan


def disarm() -> None:
    global ACTIVE
    ACTIVE = None


def active() -> Optional[FaultPlan]:
    return ACTIVE


@contextlib.contextmanager
def injected(spec):
    """Arm ``spec`` (string or FaultPlan) for the duration of a with-block —
    the chaos suite's per-scenario arming, restoring whatever was armed
    before (normally nothing)."""
    global ACTIVE
    prev = ACTIVE
    plan = arm(spec)
    try:
        yield plan
    finally:
        ACTIVE = prev


def maybe_inject(point: str, site: Optional[str] = None) -> Optional[str]:
    """The chokepoint hook.  Disarmed: one global read, returns None.  Armed:
    evaluates the plan against (point, bare site tag, composed
    "<Op>#<k>/<site>" label, active query id); may raise a typed fault,
    sleep, or return an action string for the caller."""
    plan = ACTIVE
    if plan is None:
        return None
    from . import tracing

    tag = site or ""
    return plan.fire(point, tag, tracing.current_query_id(),
                     label=tracing.full_site_label(tag))


def _arm_from_env() -> None:
    """One-shot env arming (TRINO_TPU_FAULTS) at import: scripts/chaos.py and
    tpu_watch capture runs arm whole processes this way; tests use the
    arm()/injected() API instead."""
    import os

    spec = os.environ.get("TRINO_TPU_FAULTS")
    if spec:
        arm(FaultPlan.parse(spec))


_arm_from_env()
