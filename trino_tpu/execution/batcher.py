"""Continuous template batching (round 21): the per-template rendezvous that
fuses N in-flight executions of ONE plan template into a single batched
device program.

The shape is the LLM-serving continuous-batching loop re-planned for SQL
templates: requests for the same compiled program but different bindings
coalesce into one dispatch (the per-REQUEST analog of the round-6 per-split
``_coalesced_batches``).  Each template-cache key owns a LANE:

- the FIRST request on an idle lane is the LEADER — it runs the exact
  existing single-statement path immediately, so an empty window adds ZERO
  latency or extra work (the budget suite's single-statement ceilings are
  untouched by construction);
- requests arriving while the lane is busy QUEUE; when the leader finishes
  it hands the lane to the first queued member, which becomes the DRIVER:
  it sleeps the gather window (TRINO_TPU_BATCH_WINDOW_MS), drains up to
  TRINO_TPU_BATCH_MAX members, and runs ONE fused execution
  (LocalExecutor.execute_batched) whose per-lane results resolve every
  member;
- a whole-batch failure (BatchUnsupported, a device fault) re-runs EVERY
  member on its own serial path — no member ever inherits another's error,
  and a per-lane decode error fails only its own request.

The batcher is pure host-side thread choreography: zero _jit/_host traffic
of its own (the fused execution accounts its spend on the driver's
statement like any executed plan)."""

from __future__ import annotations

import os
import threading
import time

__all__ = ["TemplateBatcher"]


# test seam: when set, called with the lane key by a LEADER after its own
# serial execution completes and BEFORE it hands the lane to a queued
# driver — tests park the leader here to deterministically accumulate a
# multi-member window instead of racing the wall clock
LEADER_EXIT_HOOK = None


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class _Member:
    __slots__ = ("runtime", "event", "drive", "serial", "result", "error",
                 "batched_with")

    def __init__(self, runtime):
        self.runtime = runtime
        self.event = threading.Event()
        self.drive = False  # woken to DRIVE the next window
        self.serial = False  # woken to fall back to its own serial run
        self.result = None
        self.error = None
        self.batched_with = 0


class _Lane:
    __slots__ = ("busy", "queue")

    def __init__(self):
        self.busy = False
        self.queue: list = []


class TemplateBatcher:
    """Per-template-key execution lanes (see module docstring).

    ``execute`` is the only entry point; ``info()`` snapshots the metrics
    surface (/v1/metrics template-batch counters + size histogram)."""

    def __init__(self, window_ms=None, max_batch=None, enabled=None):
        self.window_s = (_env_float("TRINO_TPU_BATCH_WINDOW_MS", 2.0)
                         if window_ms is None else float(window_ms)) / 1000.0
        self.max_batch = max(_env_int("TRINO_TPU_BATCH_MAX", 16)
                             if max_batch is None else int(max_batch), 1)
        if enabled is None:
            enabled = os.environ.get("TRINO_TPU_TEMPLATE_BATCH", "1") \
                not in ("0", "false", "no")
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._lanes: dict = {}
        self.batches_total = 0
        self.batched_requests_total = 0
        self._size_hist: dict = {}  # fused batch size -> count

    def execute(self, key, runtime, serial_fn, batch_fn):
        """Run one template request through the lane for ``key``.

        ``serial_fn(runtime) -> result`` is the exact single-statement path;
        ``batch_fn(runtimes) -> [result|Exception, ...]`` the fused one.
        Returns ``(result, batched_with)``: ``batched_with == 0`` means the
        request executed serially (idle-lane leader, singleton window, or
        fallback); > 0 is the fused batch size that served it.  Raises the
        member's OWN error only."""
        if not self.enabled:
            return serial_fn(runtime), 0
        with self._lock:
            lane = self._lanes.setdefault(key, _Lane())
            member = None
            if lane.busy:
                member = _Member(runtime)
                lane.queue.append(member)
            else:
                lane.busy = True
        if member is None:
            # leader on an idle lane: the unmodified serial path, now
            try:
                return serial_fn(runtime), 0
            finally:
                hook = LEADER_EXIT_HOOK
                if hook is not None:
                    try:
                        hook(key)
                    except Exception:
                        pass
                self._handoff(lane)
        member.event.wait()
        if member.drive:
            return self._drive(lane, member, serial_fn, batch_fn)
        if member.serial:
            # the window's fused run failed as a whole: run our own serial
            return serial_fn(member.runtime), 0
        if member.error is not None:
            raise member.error
        return member.result, member.batched_with

    def _drive(self, lane, member, serial_fn, batch_fn):
        """First queued member after a handoff: gather a window, run the
        fused batch, resolve every member, hand the lane on."""
        if self.window_s > 0:
            time.sleep(self.window_s)
        with self._lock:
            take = lane.queue[:self.max_batch - 1]
            del lane.queue[:len(take)]
        group = [member] + take
        if len(group) == 1:
            # nobody joined the window: the serial path is strictly better
            # (already compiled, no lane padding)
            try:
                return serial_fn(member.runtime), 0
            finally:
                self._handoff(lane)
        try:
            results = batch_fn([m.runtime for m in group])
            if not isinstance(results, (list, tuple)) \
                    or len(results) != len(group):
                raise RuntimeError(
                    "batch executor returned %r results for %d members"
                    % (None if results is None else len(results),
                       len(group)))
        except BaseException as e:
            # whole-batch failure: every OTHER member re-runs serially on
            # its own thread; this thread does the same (after freeing
            # them), unless the interpreter itself is going down
            for m in group[1:]:
                m.serial = True
                m.event.set()
            self._handoff(lane)
            if isinstance(e, (KeyboardInterrupt, SystemExit, GeneratorExit)):
                raise
            return serial_fn(member.runtime), 0
        n = len(group)
        with self._lock:
            self.batches_total += 1
            self.batched_requests_total += n
            self._size_hist[n] = self._size_hist.get(n, 0) + 1
        for m, r in zip(group, results):
            m.batched_with = n
            if isinstance(r, BaseException):
                m.error = r
            else:
                m.result = r
        for m in group[1:]:
            m.event.set()
        self._handoff(lane)
        if member.error is not None:
            raise member.error
        return member.result, member.batched_with

    def _handoff(self, lane) -> None:
        """Release the lane: promote the first queued member to driver, or
        mark the lane idle.  Every exit path of a lane holder runs this —
        a queued member can never be stranded."""
        with self._lock:
            if lane.queue:
                nxt = lane.queue.pop(0)
                nxt.drive = True
                nxt.event.set()
            else:
                lane.busy = False

    def info(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled,
                    "window_ms": self.window_s * 1000.0,
                    "max_batch": self.max_batch,
                    "batches_total": self.batches_total,
                    "batched_requests_total": self.batched_requests_total,
                    "sizes": dict(self._size_hist)}
