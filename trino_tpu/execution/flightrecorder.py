"""Query flight recorder: durable per-statement execution records.

The round-7..15 observability stack (counters, spans, plan-actuals, stall
reports, pressure rungs) all dies with the process — and the process shares a
tunnel that wedges within ~30 minutes of answering (CLAUDE.md), so the
capture window's most valuable profiles have been lost three rounds running.
The recorder is the black box: one JSON record per COMPLETED or ERRORED
statement — normalized SQL, counters + sites, the finished span tree
(stitched worker spans included on a cluster coordinator), the wall-clock
decomposition, plan-actuals payload, faults/retries, admission wait, and
(round 17) the statement's compile census (``compiles``/``compile_s`` plus
the per-compilation ``compile_events`` list from the engine's CompileLog) —
plus event records for stall reports, appended off the hot path under the same
guard discipline as cache stores: a recorder failure never fails the query,
and the feed adds ZERO ``_jit`` dispatches / ``_host`` pulls (everything it
writes was already computed on the host — the PlanHistoryStore contract,
pinned by test_query_budgets running with the recorder enabled).

Two tiers:

- an in-memory ring (``TRINO_TPU_FLIGHT_RECORDS`` entries, default 256;
  0 disables the recorder entirely) serving ``GET /v1/flight/{id}``,
  ``system.runtime.query_log`` and the completed-statement trace lookup;
- an optional on-disk JSONL ring (``TRINO_TPU_FLIGHT_DIR`` + byte budget
  ``TRINO_TPU_FLIGHT_BYTES``, default 64MB; unset dir = in-memory only):
  append-only segment files, oldest segments deleted when the directory
  exceeds budget.  ``read_flight_dir`` reads a DEAD process's directory —
  truncated tails (the process died mid-write) are skipped, not fatal.

Reference: the reference engine's query history / event-listener JSONL sinks
(plugin/trino-http-event-listener et al.), reduced to a dependency-free ring
the tpu_watch capture window can archive.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Optional

__all__ = ["FlightRecorder", "read_flight_dir", "pressure_rung",
           "summarize_compiles", "summarize_skew"]

DEFAULT_MAX_RECORDS = 256
DEFAULT_DISK_BUDGET = 64 << 20
_SEGMENT_FRACTION = 8  # rotate the active segment at budget/8


def _env_int(name: str, default: int) -> int:
    try:
        v = os.environ.get(name, "")
        return int(v) if v != "" else default
    except ValueError:
        return default


def pressure_rung(counters: Optional[dict]) -> Optional[str]:
    """The deepest memory-pressure-ladder rung this query's own counters
    show it reached (round-11 ladder vocabulary): disk spill > host spill >
    HBM spill > admission queue; None when the query never felt pressure.
    Derived, never fabricated — kills surface as the query's typed error,
    not a rung label."""
    c = counters or {}
    if c.get("spill_tier_disk"):
        return "spill-disk"
    if c.get("spill_tier_host"):
        return "spill-host"
    if c.get("spill_tier_hbm"):
        return "spill-hbm"
    if c.get("admission_queued"):
        return "admission-queue"
    return None


def summarize_compiles(rec: Optional[dict]):
    """(count, seconds) of XLA compilations attributed to one statement
    record — the round-17 top-level fields when the engine stamped them,
    else the counters snapshot (older records: (0, 0.0), never None).
    Stdlib-pure like the rest of this module: scripts/flight.py renders
    compile columns on a dead process's ring through this."""
    r = rec or {}
    c = r.get("counters") or {}
    n = r.get("compiles")
    if n is None:
        n = c.get("compiles")
    s = r.get("compile_s")
    if s is None:
        s = c.get("compile_s")
    return int(n or 0), float(s or 0.0)


def summarize_skew(rec: Optional[dict]):
    """(worst_ratio, imbalance_s, n_records) of the per-shard attribution in
    one statement record (round 20) — the top-level ``shard_stats`` when the
    engine stamped it, else the counters snapshot; (None, 0.0, 0) when the
    statement never crossed a mesh/cluster exchange.  Stdlib-pure:
    scripts/flight.py --skew renders a dead process's ring through this."""
    r = rec or {}
    stats = r.get("shard_stats")
    if stats is None:
        stats = (r.get("counters") or {}).get("shard_stats")
    stats = stats or []
    worst = None
    imb = 0.0
    for s in stats:
        ratio = float(s.get("ratio") or 1.0)
        if worst is None or ratio > worst:
            worst = ratio
        imb += float(s.get("imbalance_s") or 0.0)
    return worst, imb, len(stats)


def read_flight_dir(path: str) -> list:
    """Records from a flight directory, oldest first — works on a dead
    process's directory (scripts/flight.py).  Unparseable lines (a record
    truncated by the process dying mid-write) are skipped."""
    out: list = []
    try:
        names = sorted(n for n in os.listdir(path)
                       if n.startswith("flight-") and n.endswith(".jsonl"))
    except OSError:
        return out
    for name in names:
        try:
            with open(os.path.join(path, name), "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue  # torn tail of a dead process
        except OSError:
            continue
    # several recorders may share one directory (bench_serve's two engines,
    # chaos's second engine): name order interleaves instances, recording
    # time is the one global order.  Stable sort keeps in-file append order
    # for ties.
    out.sort(key=lambda r: r.get("recorded_at") or 0.0)
    return out


class FlightRecorder:
    """Bounded ring of per-statement flight records (+ stall/pressure event
    records), in-memory always, mirrored to an on-disk JSONL ring when
    ``TRINO_TPU_FLIGHT_DIR`` is set.  Every mutation is guarded: ``record``
    never raises (failures count on ``failures`` and surface as a metrics
    counter, exactly like guarded cache stores)."""

    def __init__(self, flight_dir: Optional[str] = None,
                 disk_budget: Optional[int] = None,
                 max_records: Optional[int] = None):
        self.flight_dir = flight_dir if flight_dir is not None \
            else (os.environ.get("TRINO_TPU_FLIGHT_DIR") or None)
        self.disk_budget = disk_budget if disk_budget is not None \
            else _env_int("TRINO_TPU_FLIGHT_BYTES", DEFAULT_DISK_BUDGET)
        self.max_records = max_records if max_records is not None \
            else _env_int("TRINO_TPU_FLIGHT_RECORDS", DEFAULT_MAX_RECORDS)
        self._lock = threading.Lock()
        self._records: deque = deque(maxlen=max(self.max_records, 1))
        self._seq = 0
        # lifetime counters (the /v1/metrics recorder series)
        self.records_total = 0
        self.failures = 0
        self.disk_evictions = 0
        self.spans_total = 0
        self.worker_spans_total = 0
        self._segment: Optional[str] = None  # active segment file path
        self._segment_bytes = 0
        # per-instance segment namespace: several recorders legitimately
        # share one TRINO_TPU_FLIGHT_DIR (bench_serve builds two engines,
        # chaos a second one) — identical names would make one instance's
        # eviction delete another's ACTIVE segment and silently lose records
        self._instance = f"{os.getpid():08x}{uuid.uuid4().hex[:6]}"

    @property
    def enabled(self) -> bool:
        return self.max_records > 0

    # -- write path ------------------------------------------------------------
    def record_query(self, rec: dict) -> Optional[dict]:
        """Append one statement record (kind defaults to "query").  Returns
        the stamped record, or None when disabled/failed — the caller never
        sees an exception (guard discipline)."""
        return self._append(dict(rec, kind=rec.get("kind", "query")))

    def record_event(self, rec: dict) -> Optional[dict]:
        """Append a non-statement event (stall report, pressure rung)."""
        return self._append(dict(rec, kind=rec.get("kind", "event")))

    def _append(self, rec: dict) -> Optional[dict]:
        if not self.enabled:
            return None
        try:
            with self._lock:
                self._seq += 1
                rec["seq"] = self._seq
                rec.setdefault("recorded_at", time.time())
                self._records.append(rec)
                self.records_total += 1
                spans = ((rec.get("trace") or {}).get("spans")
                         if isinstance(rec.get("trace"), dict) else None)
                if spans:
                    self.spans_total += len(spans)
                # stitched worker-span count: the cluster coordinator stamps
                # it on the record (how many harvested spans joined the tree)
                self.worker_spans_total += int(rec.get("worker_spans") or 0)
                if self.flight_dir:
                    self._write_disk(rec)
            return rec
        except Exception:
            # a recorder failure (full disk, unserializable value) must never
            # fail the statement it records
            with self._lock:
                self.failures += 1
            return None

    def _write_disk(self, rec: dict) -> None:
        """One JSONL line into the active segment; rotate at budget/8 and
        drop oldest segments while the directory exceeds the budget.  Caller
        holds the lock."""
        os.makedirs(self.flight_dir, exist_ok=True)
        line = (json.dumps(rec, default=_json_default) + "\n").encode()
        seg_target = max(self.disk_budget // _SEGMENT_FRACTION, 1)
        if self._segment is None or self._segment_bytes >= seg_target:
            self._segment = os.path.join(
                self.flight_dir,
                f"flight-{self._instance}-{self._seq:08d}.jsonl")
            self._segment_bytes = 0
        with open(self._segment, "ab") as f:
            f.write(line)
        self._segment_bytes += len(line)
        self._evict_disk()

    def _evict_disk(self) -> None:
        names = [n for n in os.listdir(self.flight_dir)
                 if n.startswith("flight-") and n.endswith(".jsonl")]
        sizes, mtimes = {}, {}
        for n in names:
            p = os.path.join(self.flight_dir, n)
            try:
                st = os.stat(p)
                sizes[n], mtimes[n] = st.st_size, st.st_mtime
            except OSError:
                sizes[n], mtimes[n] = 0, 0.0
        # oldest-WRITTEN first: with several instances sharing the dir, name
        # order interleaves their sequences — mtime is the shared clock, and
        # another instance's active segment (just written) sorts newest
        segs = sorted(names, key=lambda n: (mtimes[n], n))
        total = sum(sizes.values())
        # never delete the active segment: the newest record must survive
        # even when one record alone exceeds a tiny budget
        active = os.path.basename(self._segment) if self._segment else None
        for n in segs:
            if total <= self.disk_budget or n == active:
                break
            try:
                os.remove(os.path.join(self.flight_dir, n))
                self.disk_evictions += 1
            except OSError:
                pass
            total -= sizes[n]

    # -- read surfaces ---------------------------------------------------------
    def get(self, query_id: str) -> Optional[dict]:
        """Most recent record for ``query_id`` (statement records only)."""
        with self._lock:
            for rec in reversed(self._records):
                if rec.get("query_id") == query_id \
                        and rec.get("kind") == "query":
                    return rec
        return None

    def snapshot(self, limit: Optional[int] = None, kind: Optional[str] = None
                 ) -> list:
        """Records oldest-first; ``kind`` filters ("query"/"stall"/...)."""
        with self._lock:
            recs = list(self._records)
        if kind is not None:
            recs = [r for r in recs if r.get("kind") == kind]
        return recs[-limit:] if limit else recs

    def disk_bytes(self) -> int:
        if not self.flight_dir:
            return 0
        total = 0
        try:
            for n in os.listdir(self.flight_dir):
                if n.startswith("flight-") and n.endswith(".jsonl"):
                    try:
                        total += os.path.getsize(
                            os.path.join(self.flight_dir, n))
                    except OSError:
                        pass
        except OSError:
            pass
        return total

    def info(self) -> dict:
        with self._lock:
            n = len(self._records)
        return {"enabled": self.enabled, "records": n,
                "records_total": self.records_total,
                "failures": self.failures,
                "disk_evictions": self.disk_evictions,
                "spans_total": self.spans_total,
                "worker_spans_total": self.worker_spans_total,
                "dir": self.flight_dir,
                "disk_budget": self.disk_budget if self.flight_dir else 0,
                "disk_bytes": self.disk_bytes()}

    def clear(self) -> None:
        with self._lock:
            self._records.clear()


def _json_default(v):
    """JSON fallback for numpy scalars / stray objects inside counters or
    span attributes — a record must serialize, not raise."""
    try:
        import numpy as np

        if isinstance(v, np.integer):
            return int(v)
        if isinstance(v, np.floating):
            return float(v)
        if isinstance(v, np.bool_):
            return bool(v)
    except Exception:
        pass
    return str(v)
