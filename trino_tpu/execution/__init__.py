"""Query lifecycle, admission control, and session configuration.

The coordinator control-plane layer of the reference (io/trino/execution,
io/trino/dispatcher, io/trino/execution/resourcegroups), re-hosted around the
single-process TPU engine: queries still move through the same state machine,
resource-group admission, and event/tracing hooks — the pieces a drop-in user
expects to observe and configure.
"""

from .query_state import QueryInfo, QueryState, QueryStateMachine, QueryTracker
from .resourcegroups import ResourceGroup, ResourceGroupManager
from .session_properties import SessionPropertyManager, SYSTEM_SESSION_PROPERTIES
from .statemachine import StateMachine

__all__ = [
    "QueryInfo", "QueryState", "QueryStateMachine", "QueryTracker",
    "ResourceGroup", "ResourceGroupManager",
    "SessionPropertyManager", "SYSTEM_SESSION_PROPERTIES",
    "StateMachine",
]
