"""HBM device buffer pool: page & join-build caching across queries.

Trino-class engines treat a columnar buffer pool as table stakes; on tunneled
TPUs the payoff is double — a cached scan skips host generation AND the
host->device transfer, and (because the cached entry is the WHOLE scan as one
device page) every downstream per-split consumer loop collapses to a single
dispatch per stage.  TQP (arxiv 2203.01877) and "Accelerating Presto with
GPUs" (arxiv 2606.24647) both report that keeping hot columnar data resident
in accelerator memory, not re-staging it per query, is where warm wall-clock
goes.

Two tiers, one LRU:

- **Page tier** — a completed scan's pages, concatenated into ONE
  device-resident page, keyed on (catalog, table, split list, column set,
  connector plan_version).  Raw pre-transform pages, so queries with
  different filters/projections over the same scan share the entry.
  Entries are only stored when the scan ran to completion (a LIMIT
  short-circuit or error unwind must never cache a partial scan).
- **Build tier** — finished join build state (the materialized build page,
  its dictionaries, and the built hash table when the single-match strategy
  applies), keyed on a structural fingerprint of the build fragment plus the
  plan_versions of the catalogs it reads.  Checked out tables thread through
  ``_Stream.aux`` as JIT ARGUMENTS (the no-closed-over-aux rule) exactly like
  freshly built ones.
- **Result tier (round 12)** — completed ``MaterializedResult``s keyed on
  (structural plan fingerprint, catalogs, plan-shaping session props): a
  repeated dashboard-style statement is answered with ZERO device
  dispatches, zero executor checkout, and zero host pulls.  Entries are
  host-resident (numpy result columns), but accounting still rides this
  pool's labeled MemoryPool (tag ``result-cache``) so /v1/status, the
  metrics gauges and the leak checks see them next to the device tiers.
  The tier has its OWN byte budget (``TRINO_TPU_RESULT_CACHE``; unset = 0
  everywhere — results are host memory, there is no HBM fraction to steal,
  and bench.py must keep measuring the execute path unless a capture
  explicitly opts in) and a per-entry size cap
  (``TRINO_TPU_RESULT_CACHE_MAX_ENTRY``, default budget/4).  Admission
  policy (deterministic plans only, no volatile functions, cacheable
  connectors) is the ENGINE's job — the pool stores what it is handed.

Reservations flow through a private labeled :class:`~..memory.MemoryPool`
(visible in ``/v1/status`` and ``/v1/metrics`` as pool "buffer-pool");
pressure LRU-evicts instead of raising, and ``clear()`` releases every
reservation (Engine._invalidate calls it, so DDL can never leak device
memory through the pool).

Gating: ``TRINO_TPU_PAGE_CACHE`` is the HBM byte budget (``0`` = off, the
CPU-backend default — regeneration is cheap there and host RAM is the
scarce resource); unset on an accelerator backend defaults to 25% of HBM.
The non-plan-shaping ``page_cache`` session property opts single queries in
or out of a configured pool.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional

from . import faults

__all__ = ["DeviceBufferPool", "page_cache_budget", "result_cache_budget"]


def page_cache_budget() -> int:
    """Resolve the pool byte budget: the TRINO_TPU_PAGE_CACHE env var when
    set (plain bytes; 0 disables), else 0 on the CPU backend and a quarter of
    the device memory budget on accelerators.  Resolved lazily (first use) so
    importing this module never forces jax backend initialization."""
    import os

    raw = os.environ.get("TRINO_TPU_PAGE_CACHE")
    if raw is not None:
        try:
            return max(int(raw), 0)
        except ValueError:
            return 0
    import jax

    if jax.default_backend() == "cpu":
        return 0
    from ..memory import device_memory_budget

    return device_memory_budget(0.25)


def result_cache_budget() -> int:
    """Result-tier byte budget: TRINO_TPU_RESULT_CACHE (plain bytes; 0
    disables), unset = 0 on EVERY backend.  Unlike the page tier there is no
    accelerator default: result entries live in host RAM (no HBM fraction to
    derive a default from) and an implicit default would silently turn
    bench.py's warm runs into cache hits — serving deployments opt in
    explicitly."""
    import os

    raw = os.environ.get("TRINO_TPU_RESULT_CACHE")
    if raw is None:
        return 0
    try:
        return max(int(raw), 0)
    except ValueError:
        return 0


def _result_nbytes(result) -> int:
    """Host bytes a cached MaterializedResult pins (decoded + raw columns,
    deduped by identity — non-decoded columns ALIAS their raw array, and
    double-counting them would halve the tier's effective capacity).
    Object (string) columns estimate per-value payload + pointer overhead —
    a conservative over-count, like _table_nbytes."""
    import numpy as np

    total = 0
    seen: set = set()
    for cols in (result.columns, result.raw_columns):
        for c in cols:
            if id(c) in seen:
                continue
            seen.add(id(c))
            a = np.asarray(c)
            if a.dtype == object:
                total += 8 * a.size + sum(
                    len(str(v)) for v in a.ravel() if v is not None)
            else:
                total += a.nbytes
    return total


def _page_nbytes(page) -> int:
    """Device bytes a cached page pins (columns + null masks + valid)."""
    import numpy as np

    total = 0
    n = page.capacity
    for c in page.columns:
        if getattr(c, "dtype", None) == object:
            continue
        total += n * np.dtype(c.dtype).itemsize
    total += sum(n for m in page.null_masks if m is not None)
    if page.valid is not None:
        total += n
    return total


def _table_nbytes(table) -> int:
    """Device bytes of a join table's array leaves (JoinTable /
    DirectJoinTable pytrees).  build_columns may alias the build page's
    buffers — the double count is a deliberate conservative over-estimate
    (earlier eviction, never silent overcommit)."""
    import dataclasses

    import numpy as np

    if table is None:
        return 0
    total = 0
    for f in dataclasses.fields(table):
        v = getattr(table, f.name)
        leaves = v if isinstance(v, (tuple, list)) else (v,)
        for leaf in leaves:
            shape = getattr(leaf, "shape", None)
            if shape is None or getattr(leaf, "dtype", None) == object:
                continue
            total += int(np.prod(shape, dtype=np.int64)) * \
                np.dtype(leaf.dtype).itemsize
    return total


class _Entry:
    __slots__ = ("kind", "catalog", "table", "payload", "nbytes")

    def __init__(self, kind, catalog, table, payload, nbytes):
        self.kind = kind  # "page" | "build" | "result"
        self.catalog = catalog
        self.table = table  # per-table breakdown / invalidation ("" for
        # multi-table build fragments — they invalidate via clear()/versions)
        self.payload = payload
        self.nbytes = nbytes


class DeviceBufferPool:
    """Engine-owned two-tier HBM cache (page tier + join-build tier) with LRU
    eviction accounted through a labeled MemoryPool.  One instance is shared
    by every pooled executor under this lock; a WorkerServer owns its own."""

    PAGE_TAG = "page-cache"
    BUILD_TAG = "build-cache"
    RESULT_TAG = "result-cache"
    SPILL_TAG = "spill"

    def __init__(self, budget_bytes: Optional[int] = None,
                 result_budget_bytes: Optional[int] = None):
        self._budget = budget_bytes  # None = resolve lazily from env/backend
        self._result_budget = result_budget_bytes  # None = lazy from env
        # per-tier-group resident bytes: the shared MemoryPool's max is the
        # SUM of both budgets, so each group enforces its own sub-budget —
        # device entries (page/build, plus spill reservations) may never
        # expand into the result budget's headroom (that would over-commit
        # HBM) and host-resident results may never displace device entries
        self._result_bytes = 0
        self._device_bytes = 0
        # invalidation epoch: clear()/invalidate_catalog bump it, and a
        # result store presents the epoch its statement STARTED under — a
        # DML that invalidated mid-execution makes the late store a no-op
        # (the entry would otherwise outlive the invalidation that should
        # have covered it; connectors without plan_version have no other
        # staleness defense)
        self.epoch = 0
        self._lock = threading.RLock()
        self._entries: OrderedDict = OrderedDict()  # key -> _Entry (LRU)
        self.memory_pool = None  # created when the budget resolves nonzero
        # lifetime stats (the /v1/metrics *_total series — independent of
        # per-query counters so worker-merged totals don't double-count)
        self.hits = 0
        self.misses = 0
        self.build_hits = 0
        self.build_misses = 0
        self.result_hits = 0
        self.result_misses = 0
        self.evictions = 0

    # -- gating ----------------------------------------------------------------
    def budget(self) -> int:
        with self._lock:
            if self._budget is None:
                self._budget = page_cache_budget()
            return self._budget

    @property
    def enabled(self) -> bool:
        return self.budget() > 0

    def result_budget(self) -> int:
        with self._lock:
            if self._result_budget is None:
                self._result_budget = result_cache_budget()
            return self._result_budget

    @property
    def result_enabled(self) -> bool:
        return self.result_budget() > 0

    def result_entry_cap(self) -> int:
        """Per-entry admission cap for the result tier: a single giant result
        (a full-table SELECT) must not monopolize — or thrash — the budget.
        TRINO_TPU_RESULT_CACHE_MAX_ENTRY overrides; default budget/4."""
        import os

        raw = os.environ.get("TRINO_TPU_RESULT_CACHE_MAX_ENTRY")
        if raw is not None:
            try:
                return max(int(raw), 0)
            except ValueError:
                pass
        return max(self.result_budget() // 4, 1)

    @staticmethod
    def cacheable(conn) -> bool:
        """Only connectors whose page generation is deterministic for a given
        plan_version may cache (the same assumption the engine's plan cache
        makes: immutable generators, DDL/DML invalidates).  Volatile sources
        (system runtime tables, external dbapi databases) never opt in."""
        return bool(getattr(conn, "CACHEABLE_SCANS", False))

    def _pool(self):
        if self.memory_pool is None:
            from ..memory import MemoryPool

            # one labeled pool spans the device tiers AND the host-resident
            # result tier: the result tier's own sub-budget (checked in
            # put_result) keeps host entries from displacing device entries,
            # while the shared pool keeps every tier visible/leak-checkable
            # under one reserved==resident invariant
            self.memory_pool = MemoryPool(
                max_bytes=self.budget() + self.result_budget())
        return self.memory_pool

    @classmethod
    def _tag_of(cls, kind: str) -> str:
        return {"page": cls.PAGE_TAG, "build": cls.BUILD_TAG,
                "result": cls.RESULT_TAG}[kind]

    # -- keys ------------------------------------------------------------------
    @staticmethod
    def page_key(catalog: str, conn, table: str, splits, columns) -> tuple:
        ver = conn.plan_version() if hasattr(conn, "plan_version") else 0
        return ("page", catalog, table,
                tuple((s.lo, s.hi) if hasattr(s, "lo") and hasattr(s, "hi")
                      else repr(s) for s in splits),
                tuple(columns), ver)

    # -- page tier -------------------------------------------------------------
    def get_page(self, key):
        """-> (page, nbytes) or None; a hit refreshes LRU recency.  Chaos:
        ``cache_checkout`` faults land here — ``deny`` serves a miss (the
        caller regenerates, the recoverable path), raises propagate."""
        if faults.maybe_inject("cache_checkout", f"page.{key[2]}") == "deny":
            with self._lock:
                self.misses += 1
            return None
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return e.payload, e.nbytes

    def has_page(self, key) -> bool:
        """Presence probe WITHOUT recency/stat side effects — the store path
        uses it to skip staging an entry another executor already built."""
        with self._lock:
            return key in self._entries

    def put_page(self, key, page) -> bool:
        """Store a COMPLETED scan already staged as one device-resident page
        (exec.local_executor._stage_scan_entry does the staging: host arrays
        through the sanctioned _page_to_device chokepoint, concatenation as
        one COUNTED _jit dispatch — device work here would be invisible to
        the budget counters).  Chaos: ``cache_store`` faults land here —
        ``deny`` skips the admission (next query regenerates), raises
        propagate to the scan source's store guard, which treats the scan as
        uncacheable; either way no partial entry can be admitted."""
        if not self.enabled or page is None:
            return False
        with self._lock:
            if key in self._entries:
                return True  # another executor stored it first
        # inject only past the early-exits (duplicate store included): a fire
        # must mean a real store was attempted, or chaos "fires>=1"
        # assertions pass vacuously
        if faults.maybe_inject("cache_store", f"page.{key[2]}") == "deny":
            return False
        nbytes = _page_nbytes(page)
        return self._store(key, _Entry("page", key[1], key[2], page, nbytes),
                           self.PAGE_TAG)

    # -- build tier ------------------------------------------------------------
    def get_build(self, key):
        """-> payload dict or None.  Payload holds {"page", "dicts", "table",
        "span", "null_stats"} — everything _compile_join derives from the
        build fragment; "table" is None when the fragment needs the
        multi-match strategy (duplicate keys / residual filter)."""
        if faults.maybe_inject("cache_checkout", "build") == "deny":
            with self._lock:
                self.build_misses += 1
            return None
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.build_misses += 1
                return None
            self._entries.move_to_end(key)
            self.build_hits += 1
            return e.payload

    def put_build(self, key, payload) -> bool:
        """``key`` is ("build", fingerprint, right_keys, catalogs-tuple) —
        the catalogs tuple (key[3]) is what invalidate_catalog matches."""
        if not self.enabled:
            return False
        with self._lock:
            if key in self._entries:
                return True
        if faults.maybe_inject("cache_store", "build") == "deny":
            return False
        nbytes = _page_nbytes(payload["page"]) \
            + _table_nbytes(payload.get("table"))
        return self._store(
            key, _Entry("build", ",".join(key[3]), "", payload, nbytes),
            self.BUILD_TAG)

    # -- result tier (round 12) ------------------------------------------------
    def get_result(self, key):
        """-> (MaterializedResult, nbytes) or None; a hit refreshes LRU
        recency.  Chaos: ``cache_checkout`` faults with site ``result`` land
        here — ``deny`` serves a miss (the caller executes the statement,
        the recoverable path), raises propagate.  Served results are SHARED
        numpy arrays: every engine surface treats results as immutable."""
        if faults.maybe_inject("cache_checkout", "result") == "deny":
            with self._lock:
                self.result_misses += 1
            return None
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.result_misses += 1
                return None
            self._entries.move_to_end(key)
            self.result_hits += 1
            return e.payload, e.nbytes

    def put_result(self, key, result, epoch: Optional[int] = None) -> bool:
        """Store a completed MaterializedResult.  ``key`` is ("result",
        plan fingerprint, catalogs tuple, ...) — the catalogs tuple (key[2])
        is what invalidate_catalog matches.  ``epoch`` is the pool epoch the
        statement STARTED under: a mismatch means an invalidation landed
        while the statement executed, and admitting its (possibly pre-DML)
        result would resurrect state the invalidation cleared.  The
        ADMISSION decision (deterministic plan, cacheable connectors, no
        volatile functions) already happened in the engine; here only
        sizing/staleness applies: entries over the per-entry cap are
        skipped, and the tier LRU-evicts its own entries to stay inside its
        sub-budget before reserving under the shared pool.  Chaos:
        ``cache_store`` faults with site ``result`` — ``deny`` skips the
        admission, raises propagate to the engine's store guard (the query
        stays successful, the entry stays absent)."""
        if not self.result_enabled or result is None:
            return False
        with self._lock:
            if epoch is not None and epoch != self.epoch:
                return False  # invalidated mid-statement: never store
            if key in self._entries:
                return True  # a concurrent statement stored it first
        # past the early-exits: a fire must mean a real store was attempted
        if faults.maybe_inject("cache_store", "result") == "deny":
            return False
        nbytes = _result_nbytes(result)
        if nbytes > self.result_entry_cap():
            return False
        with self._lock:
            # the tier's own sub-budget: evict RESULT entries (oldest first)
            # until this one fits — device tiers are never displaced by a
            # host-resident result, and vice versa (_store's symmetric
            # device check)
            while self._result_bytes + nbytes > self.result_budget():
                if not self._evict_oldest(("result",)):
                    return False
            cats = ",".join(key[2]) if key[2] else ""
            return self._store(key, _Entry("result", cats, "", result,
                                           nbytes), self.RESULT_TAG)

    # -- storage / eviction ----------------------------------------------------
    def _store(self, key, entry: _Entry, tag: str) -> bool:
        pool = self._pool()
        with self._lock:
            if key in self._entries:
                return True
            if entry.nbytes > pool.max_bytes:
                return False  # can never fit: don't flush everyone else first
            if entry.kind in ("page", "build"):
                # device sub-budget: HBM entries plus device-resident spill
                # reservations stay under budget() even while the (host)
                # result budget sits underfull
                while self._device_usage() + entry.nbytes > self.budget():
                    if not self._evict_oldest(("page", "build")):
                        return False
            while not pool.try_reserve(entry.nbytes, tag):
                if not self._entries:
                    return False
                self._evict_lru()
            self._entries[key] = entry
            if entry.kind == "result":
                self._result_bytes += entry.nbytes
            else:
                self._device_bytes += entry.nbytes
            return True

    def _device_usage(self) -> int:
        """Caller holds the lock: resident page/build bytes + live
        device-resident spill reservations (the SPILL_TAG share of the
        shared pool) — the quantity the device sub-budget bounds."""
        spill = 0
        if self.memory_pool is not None:
            spill = self.memory_pool.info()["by_tag"].get(self.SPILL_TAG, 0)
        return self._device_bytes + spill

    def _forget(self, e: _Entry) -> None:
        """Caller holds the lock: update tier bytes + pool reservation for a
        removed entry."""
        if e.kind == "result":
            self._result_bytes -= e.nbytes
        else:
            self._device_bytes -= e.nbytes
        if self.memory_pool is not None:
            self.memory_pool.free(e.nbytes, self._tag_of(e.kind))

    def _evict_oldest(self, kinds) -> bool:
        """Caller holds the lock: evict the least-recently-used entry whose
        kind is in ``kinds``.  False when no such entry remains."""
        oldest = next((k for k, e in self._entries.items()
                       if e.kind in kinds), None)
        if oldest is None:
            return False
        e = self._entries.pop(oldest)
        self.evictions += 1
        self._forget(e)
        return True

    def _evict_lru(self) -> None:
        """Caller holds the lock.  Frees the oldest entry's reservation; the
        device arrays free when the last stream/aux reference drops (jax
        arrays are refcounted — an in-flight query holding the page keeps it
        alive exactly as long as it needs it)."""
        key, e = self._entries.popitem(last=False)
        self.evictions += 1
        self._forget(e)

    # -- spill tier / pressure eviction (round 11) -----------------------------
    def reserve_spill(self, nbytes: int) -> bool:
        """Claim HBM for a device-resident spill chunk (exec/spill's first
        tier).  Cache entries LRU-evict to make room — the escalation
        ladder's first rung: cache gives way to live query state before
        anything overflows to host RAM, queues, or dies — but spill can
        never push the pool past its budget (overflow goes to the next
        tier instead).  Reservations land under the "spill" tag of the
        pool's labeled MemoryPool, so /v1/status and the leak checks see
        device-resident spill alongside the cache tiers."""
        if not self.enabled or nbytes <= 0:
            return False
        pool = self._pool()
        with self._lock:
            # bounded by the DEVICE budget, not the pool's page+result sum:
            # spill chunks are HBM-resident, so they evict device entries
            # and may never expand into the host result tier's headroom
            if nbytes > self.budget():
                return False
            while self._device_usage() + nbytes > self.budget():
                if not self._evict_oldest(("page", "build")):
                    return False
            while not pool.try_reserve(nbytes, self.SPILL_TAG):
                if not self._entries:
                    return False
                self._evict_lru()
            return True

    def release_spill(self, nbytes: int) -> None:
        """Return a spill reservation (partition consumed / spill closed)."""
        if nbytes and self.memory_pool is not None:
            self.memory_pool.free(nbytes, self.SPILL_TAG)

    def evict_bytes(self, nbytes: int) -> int:
        """LRU-evict cache entries until ``nbytes`` are freed or the cache is
        empty (pressure shedding: worker admission refusal and the cluster
        memory killer both try this rung before anything harsher).  Returns
        the bytes actually freed."""
        freed = 0
        with self._lock:
            while freed < nbytes and self._entries:
                oldest = next(iter(self._entries.values()))
                freed += oldest.nbytes
                self._evict_lru()
        return freed

    # -- invalidation ----------------------------------------------------------
    def invalidate_catalog(self, catalog: str) -> None:
        """Drop every entry that reads ``catalog`` (version-stale plan
        eviction path).  Build and result entries fingerprint their versions,
        so a stale one would never SERVE — this releases its memory too."""
        with self._lock:
            self.epoch += 1
            dead = [k for k, e in self._entries.items()
                    if e.catalog == catalog
                    or (e.kind == "build" and catalog in k[3])
                    or (e.kind == "result" and catalog in k[2])]
            for k in dead:
                self._forget(self._entries.pop(k))

    def clear(self) -> None:
        """Release everything (Engine._invalidate / DDL / register_catalog).
        Reservations return to the pool so no device memory leaks across
        DDL."""
        with self._lock:
            self.epoch += 1
            for e in self._entries.values():
                if self.memory_pool is not None:
                    self.memory_pool.free(e.nbytes, self._tag_of(e.kind))
            self._entries.clear()
            self._result_bytes = 0
            self._device_bytes = 0

    # -- observability ---------------------------------------------------------
    def info(self) -> dict:
        """Snapshot for /v1/status's buffer_pool section and the
        /v1/metrics page-cache gauges."""
        with self._lock:
            per_table: dict = {}
            total = 0
            pages = builds = results = 0
            for e in self._entries.values():
                total += e.nbytes
                if e.kind == "page":
                    pages += 1
                elif e.kind == "build":
                    builds += 1
                else:
                    results += 1
                kind_label = "<build>" if e.kind == "build" else "<result>"
                label = f"{e.catalog}.{e.table}" if e.table else \
                    (f"{e.catalog}.{kind_label}" if e.catalog else kind_label)
                t = per_table.setdefault(label, {"entries": 0, "bytes": 0})
                t["entries"] += 1
                t["bytes"] += e.nbytes
            return {"budget_bytes": self._budget if self._budget is not None
                    else None,
                    "enabled": bool(self._budget) if self._budget is not None
                    else None,
                    "entries": len(self._entries),
                    "page_entries": pages, "build_entries": builds,
                    "result_entries": results,
                    "result_bytes": self._result_bytes,
                    "result_budget_bytes": self._result_budget,
                    "bytes": total,
                    "hits": self.hits, "misses": self.misses,
                    "build_hits": self.build_hits,
                    "build_misses": self.build_misses,
                    "result_hits": self.result_hits,
                    "result_misses": self.result_misses,
                    "evictions": self.evictions,
                    "per_table": per_table}
