"""HBM device buffer pool: page & join-build caching across queries.

Trino-class engines treat a columnar buffer pool as table stakes; on tunneled
TPUs the payoff is double — a cached scan skips host generation AND the
host->device transfer, and (because the cached entry is the WHOLE scan as one
device page) every downstream per-split consumer loop collapses to a single
dispatch per stage.  TQP (arxiv 2203.01877) and "Accelerating Presto with
GPUs" (arxiv 2606.24647) both report that keeping hot columnar data resident
in accelerator memory, not re-staging it per query, is where warm wall-clock
goes.

Two tiers, one LRU:

- **Page tier** — a completed scan's pages, concatenated into ONE
  device-resident page, keyed on (catalog, table, split list, column set,
  connector plan_version).  Raw pre-transform pages, so queries with
  different filters/projections over the same scan share the entry.
  Entries are only stored when the scan ran to completion (a LIMIT
  short-circuit or error unwind must never cache a partial scan).
- **Build tier** — finished join build state (the materialized build page,
  its dictionaries, and the built hash table when the single-match strategy
  applies), keyed on a structural fingerprint of the build fragment plus the
  plan_versions of the catalogs it reads.  Checked out tables thread through
  ``_Stream.aux`` as JIT ARGUMENTS (the no-closed-over-aux rule) exactly like
  freshly built ones.

Reservations flow through a private labeled :class:`~..memory.MemoryPool`
(visible in ``/v1/status`` and ``/v1/metrics`` as pool "buffer-pool");
pressure LRU-evicts instead of raising, and ``clear()`` releases every
reservation (Engine._invalidate calls it, so DDL can never leak device
memory through the pool).

Gating: ``TRINO_TPU_PAGE_CACHE`` is the HBM byte budget (``0`` = off, the
CPU-backend default — regeneration is cheap there and host RAM is the
scarce resource); unset on an accelerator backend defaults to 25% of HBM.
The non-plan-shaping ``page_cache`` session property opts single queries in
or out of a configured pool.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional

from . import faults

__all__ = ["DeviceBufferPool", "page_cache_budget"]


def page_cache_budget() -> int:
    """Resolve the pool byte budget: the TRINO_TPU_PAGE_CACHE env var when
    set (plain bytes; 0 disables), else 0 on the CPU backend and a quarter of
    the device memory budget on accelerators.  Resolved lazily (first use) so
    importing this module never forces jax backend initialization."""
    import os

    raw = os.environ.get("TRINO_TPU_PAGE_CACHE")
    if raw is not None:
        try:
            return max(int(raw), 0)
        except ValueError:
            return 0
    import jax

    if jax.default_backend() == "cpu":
        return 0
    from ..memory import device_memory_budget

    return device_memory_budget(0.25)


def _page_nbytes(page) -> int:
    """Device bytes a cached page pins (columns + null masks + valid)."""
    import numpy as np

    total = 0
    n = page.capacity
    for c in page.columns:
        if getattr(c, "dtype", None) == object:
            continue
        total += n * np.dtype(c.dtype).itemsize
    total += sum(n for m in page.null_masks if m is not None)
    if page.valid is not None:
        total += n
    return total


def _table_nbytes(table) -> int:
    """Device bytes of a join table's array leaves (JoinTable /
    DirectJoinTable pytrees).  build_columns may alias the build page's
    buffers — the double count is a deliberate conservative over-estimate
    (earlier eviction, never silent overcommit)."""
    import dataclasses

    import numpy as np

    if table is None:
        return 0
    total = 0
    for f in dataclasses.fields(table):
        v = getattr(table, f.name)
        leaves = v if isinstance(v, (tuple, list)) else (v,)
        for leaf in leaves:
            shape = getattr(leaf, "shape", None)
            if shape is None or getattr(leaf, "dtype", None) == object:
                continue
            total += int(np.prod(shape, dtype=np.int64)) * \
                np.dtype(leaf.dtype).itemsize
    return total


class _Entry:
    __slots__ = ("kind", "catalog", "table", "payload", "nbytes")

    def __init__(self, kind, catalog, table, payload, nbytes):
        self.kind = kind  # "page" | "build"
        self.catalog = catalog
        self.table = table  # per-table breakdown / invalidation ("" for
        # multi-table build fragments — they invalidate via clear()/versions)
        self.payload = payload
        self.nbytes = nbytes


class DeviceBufferPool:
    """Engine-owned two-tier HBM cache (page tier + join-build tier) with LRU
    eviction accounted through a labeled MemoryPool.  One instance is shared
    by every pooled executor under this lock; a WorkerServer owns its own."""

    PAGE_TAG = "page-cache"
    BUILD_TAG = "build-cache"
    SPILL_TAG = "spill"

    def __init__(self, budget_bytes: Optional[int] = None):
        self._budget = budget_bytes  # None = resolve lazily from env/backend
        self._lock = threading.RLock()
        self._entries: OrderedDict = OrderedDict()  # key -> _Entry (LRU)
        self.memory_pool = None  # created when the budget resolves nonzero
        # lifetime stats (the /v1/metrics *_total series — independent of
        # per-query counters so worker-merged totals don't double-count)
        self.hits = 0
        self.misses = 0
        self.build_hits = 0
        self.build_misses = 0
        self.evictions = 0

    # -- gating ----------------------------------------------------------------
    def budget(self) -> int:
        with self._lock:
            if self._budget is None:
                self._budget = page_cache_budget()
            return self._budget

    @property
    def enabled(self) -> bool:
        return self.budget() > 0

    @staticmethod
    def cacheable(conn) -> bool:
        """Only connectors whose page generation is deterministic for a given
        plan_version may cache (the same assumption the engine's plan cache
        makes: immutable generators, DDL/DML invalidates).  Volatile sources
        (system runtime tables, external dbapi databases) never opt in."""
        return bool(getattr(conn, "CACHEABLE_SCANS", False))

    def _pool(self):
        if self.memory_pool is None:
            from ..memory import MemoryPool

            self.memory_pool = MemoryPool(max_bytes=self.budget())
        return self.memory_pool

    # -- keys ------------------------------------------------------------------
    @staticmethod
    def page_key(catalog: str, conn, table: str, splits, columns) -> tuple:
        ver = conn.plan_version() if hasattr(conn, "plan_version") else 0
        return ("page", catalog, table,
                tuple((s.lo, s.hi) if hasattr(s, "lo") and hasattr(s, "hi")
                      else repr(s) for s in splits),
                tuple(columns), ver)

    # -- page tier -------------------------------------------------------------
    def get_page(self, key):
        """-> (page, nbytes) or None; a hit refreshes LRU recency.  Chaos:
        ``cache_checkout`` faults land here — ``deny`` serves a miss (the
        caller regenerates, the recoverable path), raises propagate."""
        if faults.maybe_inject("cache_checkout", f"page.{key[2]}") == "deny":
            with self._lock:
                self.misses += 1
            return None
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return e.payload, e.nbytes

    def has_page(self, key) -> bool:
        """Presence probe WITHOUT recency/stat side effects — the store path
        uses it to skip staging an entry another executor already built."""
        with self._lock:
            return key in self._entries

    def put_page(self, key, page) -> bool:
        """Store a COMPLETED scan already staged as one device-resident page
        (exec.local_executor._stage_scan_entry does the staging: host arrays
        through the sanctioned _page_to_device chokepoint, concatenation as
        one COUNTED _jit dispatch — device work here would be invisible to
        the budget counters).  Chaos: ``cache_store`` faults land here —
        ``deny`` skips the admission (next query regenerates), raises
        propagate to the scan source's store guard, which treats the scan as
        uncacheable; either way no partial entry can be admitted."""
        if not self.enabled or page is None:
            return False
        with self._lock:
            if key in self._entries:
                return True  # another executor stored it first
        # inject only past the early-exits (duplicate store included): a fire
        # must mean a real store was attempted, or chaos "fires>=1"
        # assertions pass vacuously
        if faults.maybe_inject("cache_store", f"page.{key[2]}") == "deny":
            return False
        nbytes = _page_nbytes(page)
        return self._store(key, _Entry("page", key[1], key[2], page, nbytes),
                           self.PAGE_TAG)

    # -- build tier ------------------------------------------------------------
    def get_build(self, key):
        """-> payload dict or None.  Payload holds {"page", "dicts", "table",
        "span", "null_stats"} — everything _compile_join derives from the
        build fragment; "table" is None when the fragment needs the
        multi-match strategy (duplicate keys / residual filter)."""
        if faults.maybe_inject("cache_checkout", "build") == "deny":
            with self._lock:
                self.build_misses += 1
            return None
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.build_misses += 1
                return None
            self._entries.move_to_end(key)
            self.build_hits += 1
            return e.payload

    def put_build(self, key, payload) -> bool:
        """``key`` is ("build", fingerprint, right_keys, catalogs-tuple) —
        the catalogs tuple (key[3]) is what invalidate_catalog matches."""
        if not self.enabled:
            return False
        with self._lock:
            if key in self._entries:
                return True
        if faults.maybe_inject("cache_store", "build") == "deny":
            return False
        nbytes = _page_nbytes(payload["page"]) \
            + _table_nbytes(payload.get("table"))
        return self._store(
            key, _Entry("build", ",".join(key[3]), "", payload, nbytes),
            self.BUILD_TAG)

    # -- storage / eviction ----------------------------------------------------
    def _store(self, key, entry: _Entry, tag: str) -> bool:
        pool = self._pool()
        with self._lock:
            if key in self._entries:
                return True
            if entry.nbytes > pool.max_bytes:
                return False  # can never fit: don't flush everyone else first
            while not pool.try_reserve(entry.nbytes, tag):
                if not self._entries:
                    return False
                self._evict_lru()
            self._entries[key] = entry
            return True

    def _evict_lru(self) -> None:
        """Caller holds the lock.  Frees the oldest entry's reservation; the
        device arrays free when the last stream/aux reference drops (jax
        arrays are refcounted — an in-flight query holding the page keeps it
        alive exactly as long as it needs it)."""
        key, e = self._entries.popitem(last=False)
        self.evictions += 1
        self.memory_pool.free(
            e.nbytes, self.PAGE_TAG if e.kind == "page" else self.BUILD_TAG)

    # -- spill tier / pressure eviction (round 11) -----------------------------
    def reserve_spill(self, nbytes: int) -> bool:
        """Claim HBM for a device-resident spill chunk (exec/spill's first
        tier).  Cache entries LRU-evict to make room — the escalation
        ladder's first rung: cache gives way to live query state before
        anything overflows to host RAM, queues, or dies — but spill can
        never push the pool past its budget (overflow goes to the next
        tier instead).  Reservations land under the "spill" tag of the
        pool's labeled MemoryPool, so /v1/status and the leak checks see
        device-resident spill alongside the cache tiers."""
        if not self.enabled or nbytes <= 0:
            return False
        pool = self._pool()
        with self._lock:
            if nbytes > pool.max_bytes:
                return False
            while not pool.try_reserve(nbytes, self.SPILL_TAG):
                if not self._entries:
                    return False
                self._evict_lru()
            return True

    def release_spill(self, nbytes: int) -> None:
        """Return a spill reservation (partition consumed / spill closed)."""
        if nbytes and self.memory_pool is not None:
            self.memory_pool.free(nbytes, self.SPILL_TAG)

    def evict_bytes(self, nbytes: int) -> int:
        """LRU-evict cache entries until ``nbytes`` are freed or the cache is
        empty (pressure shedding: worker admission refusal and the cluster
        memory killer both try this rung before anything harsher).  Returns
        the bytes actually freed."""
        freed = 0
        with self._lock:
            while freed < nbytes and self._entries:
                oldest = next(iter(self._entries.values()))
                freed += oldest.nbytes
                self._evict_lru()
        return freed

    # -- invalidation ----------------------------------------------------------
    def invalidate_catalog(self, catalog: str) -> None:
        """Drop every entry that reads ``catalog`` (version-stale plan
        eviction path).  Build entries fingerprint their versions, so a stale
        one would never SERVE — this releases its device memory too."""
        with self._lock:
            dead = [k for k, e in self._entries.items()
                    if e.catalog == catalog
                    or (e.kind == "build" and catalog in k[3])]
            for k in dead:
                e = self._entries.pop(k)
                if self.memory_pool is not None:
                    self.memory_pool.free(
                        e.nbytes,
                        self.PAGE_TAG if e.kind == "page" else self.BUILD_TAG)

    def clear(self) -> None:
        """Release everything (Engine._invalidate / DDL / register_catalog).
        Reservations return to the pool so no device memory leaks across
        DDL."""
        with self._lock:
            for e in self._entries.values():
                if self.memory_pool is not None:
                    self.memory_pool.free(
                        e.nbytes,
                        self.PAGE_TAG if e.kind == "page" else self.BUILD_TAG)
            self._entries.clear()

    # -- observability ---------------------------------------------------------
    def info(self) -> dict:
        """Snapshot for /v1/status's buffer_pool section and the
        /v1/metrics page-cache gauges."""
        with self._lock:
            per_table: dict = {}
            total = 0
            pages = builds = 0
            for e in self._entries.values():
                total += e.nbytes
                if e.kind == "page":
                    pages += 1
                else:
                    builds += 1
                label = f"{e.catalog}.{e.table}" if e.table else \
                    (f"{e.catalog}.<build>" if e.catalog else "<build>")
                t = per_table.setdefault(label, {"entries": 0, "bytes": 0})
                t["entries"] += 1
                t["bytes"] += e.nbytes
            return {"budget_bytes": self._budget if self._budget is not None
                    else None,
                    "enabled": bool(self._budget) if self._budget is not None
                    else None,
                    "entries": len(self._entries),
                    "page_entries": pages, "build_entries": builds,
                    "bytes": total,
                    "hits": self.hits, "misses": self.misses,
                    "build_hits": self.build_hits,
                    "build_misses": self.build_misses,
                    "evictions": self.evictions,
                    "per_table": per_table}
