"""Generic listener-based state machine.

Reference: execution/StateMachine.java:43 — thread-safe state holder with
terminal-state sets and state-change listeners, used for query/stage/task
lifecycles throughout the coordinator.
"""

from __future__ import annotations

import threading
from typing import Callable, Generic, Iterable, Optional, TypeVar

T = TypeVar("T")

__all__ = ["StateMachine"]


class StateMachine(Generic[T]):
    def __init__(self, name: str, initial: T, terminal_states: Iterable[T] = ()):
        self.name = name
        self._state = initial
        self._terminal = frozenset(terminal_states)
        self._lock = threading.Lock()
        self._listeners: list[Callable[[T], None]] = []

    def get(self) -> T:
        return self._state

    @property
    def is_terminal(self) -> bool:
        return self._state in self._terminal

    def add_state_change_listener(self, fn: Callable[[T], None]) -> None:
        with self._lock:
            self._listeners.append(fn)
            current = self._state
        fn(current)  # reference semantics: listener fires immediately with current state

    def set(self, new_state: T) -> bool:
        """Unconditional transition (no-op when already terminal or unchanged)."""
        with self._lock:
            if self._state in self._terminal or self._state == new_state:
                return False
            self._state = new_state
            listeners = list(self._listeners)
        for fn in listeners:
            fn(new_state)
        return True

    def compare_and_set(self, expected: T, new_state: T) -> bool:
        with self._lock:
            if self._state != expected or self._state in self._terminal:
                return False
            self._state = new_state
            listeners = list(self._listeners)
        for fn in listeners:
            fn(new_state)
        return True

    def transition(self, allowed_from: Iterable[T], new_state: T) -> bool:
        with self._lock:
            if self._state not in allowed_from or self._state in self._terminal:
                return False
            self._state = new_state
            listeners = list(self._listeners)
        for fn in listeners:
            fn(new_state)
        return True
