"""Time-shared worker execution across concurrent queries.

Reference: the worker's TaskExecutor time-shares a fixed thread pool over all
queries' splits in ~1s quanta (executor/timesharing/PrioritizedSplitRunner.java:49,187),
and a five-level feedback queue keyed by each query's ACCUMULATED scheduled
time decides who runs next (executor/timesharing/MultilevelSplitQueue.java:41)
— so a short query overtakes a long one instead of queueing behind it.

TPU translation: a fragment task's natural quantum is the SPLIT step (one
page-batch through the jitted pipeline — the device program itself is not
preemptible, and per-split steps are the boundaries the task body already
has).  Tasks run in their own threads holding one of N concurrency SLOTS;
between splits they call ``tick()``, which charges the elapsed quantum to
their query and yields the slot whenever a lower-level (less-served) query is
waiting — or unconditionally after the quantum expires with anyone waiting
(round-robin within a level).  Yielding keeps the task's executor state (the
group table lives on); only the slot token moves, which is exactly the
reference's split-runner re-queue."""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import OrderedDict

__all__ = ["FairScheduler", "LEVEL_THRESHOLDS"]

# accumulated-scheduled-seconds boundaries of the five feedback levels
# (MultilevelSplitQueue.java:41 — LEVEL_THRESHOLD_SECONDS {0, 1, 10, 60, 300})
LEVEL_THRESHOLDS = (0.0, 1.0, 10.0, 60.0, 300.0)
MAX_TRACKED_QUERIES = 256  # sched_time LRU bound (a long-lived worker serves
# unbounded queries; the other worker registries are capped the same way)


class FairScheduler:
    """N-slot admission with multilevel-feedback priority per QUERY."""

    def __init__(self, slots: int, quantum: float = None):
        self.slots = max(1, int(slots))
        self.quantum = float(
            os.environ.get("TRINO_TPU_SCHED_QUANTUM", "1.0")
            if quantum is None else quantum)
        self._cv = threading.Condition()
        self._running: dict = {}  # token -> (query_key, mark, held_since)
        self._waiters: list = []  # [(query_key, seq, token, enqueued_at)]
        self._seq = 0
        self._tokens = itertools.count()  # unique slot tokens: duplicate
        # task ids (speculation / wedged-task re-dispatch landing on the same
        # worker) must never share accounting entries
        self.sched_time: OrderedDict = OrderedDict()  # query -> seconds (LRU)
        self.preemptions = 0  # observability: quanta yielded to a waiter

    # -- priority ------------------------------------------------------------
    def _level(self, qk) -> int:
        t = self.sched_time.get(qk, 0.0)
        lvl = 0
        for i, th in enumerate(LEVEL_THRESHOLDS):
            if t >= th:
                lvl = i
        return lvl

    def _charge(self, qk: str, seconds: float) -> None:
        """Accumulate scheduled time under the LRU bound (call under cv)."""
        self.sched_time[qk] = self.sched_time.get(qk, 0.0) + seconds
        self.sched_time.move_to_end(qk)
        while len(self.sched_time) > MAX_TRACKED_QUERIES:
            self.sched_time.popitem(last=False)

    def _effective_level(self, w) -> int:
        """Level with AGING: a waiter starving past 10 quanta drops one level
        per further 10-quanta wait, so a steady stream of fresh queries
        cannot starve a long one forever (the reference avoids starvation
        with level-time RATIOS, MultilevelSplitQueue.java:41 computeTargetScheduledTime;
        aging is the same guarantee in this cooperative design)."""
        qk, _seq, _tok, enq = w
        waited = time.monotonic() - enq
        boost = int(waited / max(10.0 * self.quantum, 0.5))
        return max(self._level(qk) - boost, 0)

    def _best_waiter(self):
        return min(self._waiters,
                   key=lambda w: (self._effective_level(w), w[1]),
                   default=None)

    # -- slot lifecycle ------------------------------------------------------
    def new_token(self, task_id: str) -> str:
        """Unique per-execution slot token: two live executions of the same
        task id (speculative duplicate, wedged-task re-dispatch) must hold
        two slots, like the semaphore this scheduler replaced."""
        return f"{task_id}#{next(self._tokens)}"

    def acquire(self, query_key: str, token: str) -> None:
        """Block until this task holds a slot; grants go to the waiter whose
        query sits at the lowest (aged) feedback level, FIFO within one.

        Condition-variable notification, not a poll interval: every state
        change that can grant a slot notifies (release(), tick()'s yield, and
        the grant below — taking one of several free slots changes who is
        best, so the NEXT waiter must re-evaluate), so a blocked acquire
        wakes in notify latency, not at a 50ms poll boundary.  One wrinkle:
        the grant ORDER also depends on wall-clock aging, which can flip
        which waiter is "best" with no accompanying notify (two waiters each
        conclude "not me" around an aging boundary and both sleep over a free
        slot).  A coarse backstop wait at the aging-boundary granularity
        (10 quanta — the rate at which _effective_level can change at all)
        self-heals that stranding without reintroducing per-grant polling."""
        backstop = max(10.0 * self.quantum, 0.5)
        with self._cv:
            self._seq += 1
            w = (query_key, self._seq, token, time.monotonic())
            self._waiters.append(w)
            while not (len(self._running) < self.slots
                       and self._best_waiter() is w):
                self._cv.wait(backstop)
            self._waiters.remove(w)
            now = time.monotonic()
            self._running[token] = (query_key, now, now)
            self._cv.notify_all()  # remaining free slots go to the next-best

    def release(self, token: str) -> None:
        with self._cv:
            ent = self._running.pop(token, None)
            if ent is not None:
                qk, mark, _held = ent
                self._charge(qk, time.monotonic() - mark)
            self._cv.notify_all()

    def tick(self, token: str) -> None:
        """Split-boundary preemption point: charge the elapsed quantum; yield
        the slot when a less-served query waits, or when this quantum expired
        with ANY waiter (round-robin within the level)."""
        qk = None
        with self._cv:
            ent = self._running.get(token)
            if ent is None:
                return
            qk, mark, held_since = ent
            now = time.monotonic()
            self._charge(qk, now - mark)
            self._running[token] = (qk, now, held_since)
            if not self._waiters:
                return
            best = self._best_waiter()
            expired = (now - held_since) >= self.quantum
            if not (self._effective_level(best) < self._level(qk) or expired):
                return
            del self._running[token]
            self.preemptions += 1
            self._cv.notify_all()
        self.acquire(qk, token)  # rejoin behind the woken waiter

    def info(self) -> dict:
        with self._cv:
            recent = list(self.sched_time.items())[-16:]  # bounded payload
            return {"slots": self.slots,
                    "running": len(self._running),
                    "waiting": len(self._waiters),
                    "preemptions": self.preemptions,
                    "scheduled_time": {k: round(v, 3) for k, v in recent}}
