"""Typed system session properties with validation.

Reference: SystemSessionProperties.java (2,069 LoC of property definitions) +
metadata/SessionPropertyManager.java — per-query overrides of engine behavior,
validated at SET time.  The catalog here covers the knobs this engine actually
reads; unknown names raise, values are parsed/validated against the declared
type, exactly like `SET SESSION x = y` in the reference.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

__all__ = ["PropertyMetadata", "SessionPropertyManager", "SYSTEM_SESSION_PROPERTIES"]


@dataclasses.dataclass(frozen=True)
class PropertyMetadata:
    name: str
    description: str
    type: str  # 'boolean' | 'integer' | 'double' | 'varchar'
    default: Any
    validate: Optional[Callable[[Any], Optional[str]]] = None  # returns error or None

    def parse(self, value):
        if self.type == "boolean":
            if isinstance(value, bool):
                v = value
            elif str(value).lower() in ("true", "false"):
                v = str(value).lower() == "true"
            else:
                raise ValueError(f"{self.name} must be a boolean, got {value!r}")
        elif self.type == "integer":
            try:
                v = int(value)
            except (TypeError, ValueError):
                raise ValueError(f"{self.name} must be an integer, got {value!r}")
        elif self.type == "double":
            try:
                v = float(value)
            except (TypeError, ValueError):
                raise ValueError(f"{self.name} must be a double, got {value!r}")
        else:
            v = str(value)
        if self.validate is not None:
            err = self.validate(v)
            if err:
                raise ValueError(f"{self.name}: {err}")
        return v


def _positive(v):
    return None if v > 0 else "must be positive"


SYSTEM_SESSION_PROPERTIES = {p.name: p for p in [
    PropertyMetadata("query_max_run_time", "Maximum query run time in seconds",
                     "double", 3600.0, _positive),
    PropertyMetadata("join_distribution_type",
                     "AUTOMATIC | PARTITIONED | BROADCAST (reference: "
                     "DetermineJoinDistributionType.java:51)", "varchar", "AUTOMATIC",
                     lambda v: None if str(v).upper() in
                     ("AUTOMATIC", "PARTITIONED", "BROADCAST")
                     else "must be AUTOMATIC, PARTITIONED or BROADCAST"),
    PropertyMetadata("task_concurrency", "Local parallelism hint", "integer", 8,
                     _positive),
    PropertyMetadata("hash_partition_count",
                     "Number of partitions for distributed hash exchanges "
                     "(reference: DeterminePartitionCount.java:88)", "integer", 8,
                     _positive),
    PropertyMetadata("group_by_capacity",
                     "Initial group-by hash table capacity (0 = stats-derived)",
                     "integer", 0, lambda v: None if v >= 0 else "must be >= 0"),
    PropertyMetadata("dynamic_filtering_enabled",
                     "Prune probe-side splits from join build domains "
                     "(reference: DynamicFilterService)", "boolean", True),
    PropertyMetadata("spill_enabled",
                     "Allow partitioned re-execution when state exceeds device "
                     "memory (reference: spiller/*)", "boolean", True),
    PropertyMetadata("query_priority", "Scheduling priority", "integer", 1, _positive),
    PropertyMetadata("dispatch_batch",
                     "Coalesce up to K shape-uniform scan splits into one "
                     "device dispatch (0 = engine default from "
                     "TRINO_TPU_DISPATCH_BATCH, 1 = exact per-split "
                     "execution).  Plan-shaping: rides the plan-cache key",
                     "integer", 0, lambda v: None if v >= 0 else "must be >= 0"),
    PropertyMetadata("page_cache",
                     "Serve scans / join builds from the device buffer pool "
                     "(execution/bufferpool; pool budget from "
                     "TRINO_TPU_PAGE_CACHE).  NON-plan-shaping: flipping it "
                     "never re-plans or re-compiles", "boolean", True),
    PropertyMetadata("result_cache",
                     "Serve repeated deterministic statements from the "
                     "buffer pool's result tier (execution/bufferpool; tier "
                     "budget from TRINO_TPU_RESULT_CACHE).  NON-plan-"
                     "shaping: flipping it never re-plans or re-compiles",
                     "boolean", True),
    PropertyMetadata("adaptive_execution",
                     "Let the adaptive advisor (execution/adaptive) divert "
                     "statements to history-corrected plans (env default "
                     "TRINO_TPU_ADAPTIVE).  Plan-shaping: rides the "
                     "plan-cache key, so flipping it escapes (or re-enters) "
                     "the corrected plan", "boolean", True),
    PropertyMetadata("query_max_memory",
                     "Per-query device memory limit in bytes (0 = node limit "
                     "only; reference: query.max-memory + "
                     "ExceededMemoryLimitException)", "integer", 0,
                     lambda v: None if v >= 0 else "must be >= 0"),
]}


class SessionPropertyManager:
    def __init__(self, catalog: Optional[dict] = None):
        self.catalog = dict(catalog or SYSTEM_SESSION_PROPERTIES)

    def set_property(self, session, name: str, value) -> None:
        meta = self.catalog.get(name)
        if meta is None:
            raise ValueError(f"Session property '{name}' does not exist")
        session.properties[name] = meta.parse(value)

    def reset_property(self, session, name: str) -> None:
        if name not in self.catalog:
            raise ValueError(f"Session property '{name}' does not exist")
        session.properties.pop(name, None)

    def get(self, session, name: str):
        meta = self.catalog.get(name)
        if meta is None:
            raise ValueError(f"Session property '{name}' does not exist")
        return session.properties.get(name, meta.default)

    def rows(self, session) -> list[tuple]:
        """(name, value, default, type, description) — SHOW SESSION."""
        out = []
        for name in sorted(self.catalog):
            m = self.catalog[name]
            v = session.properties.get(name, m.default)
            out.append((name, str(v), str(m.default), m.type, m.description))
        return out
