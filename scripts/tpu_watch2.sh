#!/bin/bash
# Round-5 re-arm after the 2026-08-02 15:33Z contact wedged mid-capture.
# Lessons applied: (1) joins-first — q3/q18/q9 are the contested numbers and
# must land before the tunnel wedges; (2) bench.py now persists compiled
# executables in .jax_cache, so a later contact skips the ~110s cold
# compiles; (3) every bench leg writes its own artifact the moment it
# finishes, so a wedge loses only the in-flight leg.  Single-instance via
# the same flock as tpu_watch.sh.
cd /root/repo
LOG=scripts/tpu_watch.log
exec 9> scripts/tpu_watch.lock
if ! flock -n 9; then
  echo "$(date -Is) watch2: another watcher holds the lock; exiting" >> "$LOG"
  exit 2
fi
echo "$(date -Is) watch2 start (joins-first, compile cache armed)" >> "$LOG"
for i in $(seq 1 250); do
  if timeout 150 python -c "import jax; d=jax.devices()[0]; assert d.platform != 'cpu', d" >> "$LOG" 2>&1; then
    echo "$(date -Is) watch2: TPU UP on probe $i" >> "$LOG"
    for cfg in "sf1_joins:1:q3,q18,q9:420:540" \
               "sf1_rest:1:q1,q4:240:330" \
               "sf10_joins:10:q3,q18,q9:700:820" \
               "sf10_rest:10:q1,q4:400:500"; do
      IFS=: read -r name sf queries budget tmo <<< "$cfg"
      BENCH_BUDGET=$budget BENCH_SF=$sf BENCH_QUERIES=$queries \
        TRINO_TPU_SCAN_FUSED=0 \
        timeout -k 60 "$tmo" python bench.py \
        > "scripts/bench_${name}_w2.json" 2> "scripts/bench_${name}_w2.log"
      rc=$?
      echo "$(date -Is) watch2 $name rc=$rc : $(cat scripts/bench_${name}_w2.json)" >> "$LOG"
    done
    rm -f scripts/tpu_cluster_probe.json
    timeout -k 30 700 python scripts/tpu_cluster_probe.py \
      > scripts/tpu_cluster_probe.out 2>&1
    echo "$(date -Is) watch2 cluster probe rc=$?" >> "$LOG"
    python - <<'PY'
import json, re, subprocess, time
out = {"captured_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
       "note": "watch2 joins-first capture, post device-finalize/device-TopN"}
try:
    out["device"] = subprocess.run(
        ["python", "-c", "import jax; print(jax.devices()[0])"],
        capture_output=True, text=True, timeout=180).stdout.strip()
except Exception as e:
    out["device"] = f"probe-error: {e}"
for name in ("sf1_joins", "sf1_rest", "sf10_joins", "sf10_rest"):
    try:
        out[name] = json.load(open(f"scripts/bench_{name}_w2.json"))
    except Exception as e:
        out[name] = {"error": str(e)}
    # per-query engine timings survive in the stderr log even if the JSON
    # leg was killed mid-run
    try:
        lines = open(f"scripts/bench_{name}_w2.log").read()
        out[f"{name}_perq"] = re.findall(
            r"bench: (q\d+) engine cold=([\d.]+)s warm=([\d.]+)s", lines)
    except Exception:
        pass
try:
    out["cluster_tpu_probe"] = json.load(open("scripts/tpu_cluster_probe.json"))
except Exception as e:
    out["cluster_tpu_probe"] = {"error": str(e)}
json.dump(out, open("BENCH_local_r05b.json", "w"), indent=1)
PY
    echo "$(date -Is) watch2 wrote BENCH_local_r05b.json" >> "$LOG"
    exit 0
  fi
  echo "$(date -Is) watch2 probe $i: tunnel down" >> "$LOG"
  sleep 150
done
exit 1
