#!/usr/bin/env python
"""Per-query device-boundary counter trace: the tool that derives (and
re-derives) the budget numbers pinned in tests/test_query_budgets.py.

Runs the TPC-H north-star queries (bench.py's QUERIES) through the engine
twice — cold (plan + XLA compile) and warm (cached plan, compiled pipelines)
— and prints one JSON line per query with the QueryCounters snapshot of each
run: device_dispatches, host_transfers, host_bytes_pulled.

The WARM numbers are the budget: a warm query's dispatch count is its tunnel
round-trip bill and its pulled bytes are its transfer bill (CLAUDE.md round-5
facts).  To re-derive the test ceilings after an executor change:

    JAX_PLATFORMS=cpu python scripts/query_counters.py

and copy the warm numbers (with the headroom noted in the test) into
tests/test_query_budgets.py.  TRACE_SF / TRACE_QUERIES / TRACE_SPLIT_ROWS
override the scale factor (default 1, matching the tests), query subset, and
split size (default 1<<21, matching bench.py).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_force_cpu = os.environ.get("JAX_PLATFORMS") == "cpu"
if _force_cpu:
    os.environ.pop("JAX_PLATFORMS")
if "--distributed" in sys.argv and "host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    # the mesh trace needs the virtual 8-device CPU mesh, and the flag must
    # land BEFORE jax import (same dance as tests/conftest.py)
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

import jax  # noqa: E402

if _force_cpu:
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


def main():
    import argparse

    from bench import QUERIES
    from trino_tpu import Engine
    from trino_tpu.connectors.tpch import TpchConnector

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=None, metavar="N",
                    help="also trace with dispatch_batch=N and print batch=1 "
                         "vs batch=N side by side (coalescing A/B; default: "
                         "trace only the session default)")
    ap.add_argument("--page-cache", type=int, default=None, metavar="BYTES",
                    help="device buffer-pool budget for this trace "
                         "(TRINO_TPU_PAGE_CACHE; 0 = off).  The round-9 "
                         "budget ceilings derive with the cache ON — run "
                         "once with the budget the test fixture sets and "
                         "once with 0 for the A/B the docstring records")
    ap.add_argument("--result-cache", type=int, default=0, metavar="BYTES",
                    help="result-cache tier budget (TRINO_TPU_RESULT_CACHE) "
                         "for this trace.  DEFAULT 0 — the budget ceilings "
                         "in tests/test_query_budgets.py pin the EXECUTE "
                         "path and their fixture forces the tier off; a "
                         "warm run with the tier on costs 0 dispatches "
                         "(that's bench_serve.py's measurement, not this "
                         "one's)")
    ap.add_argument("--prepared", action="store_true",
                    help="trace the PREPARE/EXECUTE point-lookup class "
                         "instead of the TPC-H set: cold (template "
                         "creation) then warm EXECUTEs with fresh bindings, "
                         "against the substitution baseline (plan templates "
                         "disabled).  The warm template numbers are the "
                         "point-class ceilings — re-derive them here after "
                         "any template-path change")
    ap.add_argument("--serve-batch", action="store_true",
                    help="trace the round-21 template batcher: fused "
                         "windows of {1,4,16} concurrent EXECUTEs of one "
                         "point-lookup template, printing total and "
                         "PER-REQUEST warm dispatch counts per batch size "
                         "(the fused window must land within 2x of ONE "
                         "request's serial bill — the acceptance ratio).  "
                         "Fusion is manufactured deterministically (the "
                         "lane is held busy while the window enqueues), "
                         "not raced against the wall-clock gather window")
    ap.add_argument("--distributed", action="store_true",
                    help="trace the WORKER-MESH path instead of the local "
                         "executor: each query runs on the 8-device CPU "
                         "mesh (virtual workers; the flag forces the device "
                         "count before jax imports) cold+warm in BOTH "
                         "exchange modes — device-resident receive buffers "
                         "vs the host spool (TRINO_TPU_DEVICE_EXCHANGE "
                         "A/B).  The warm device-mode numbers are the "
                         "tests/test_distributed_budgets.py ceilings; the "
                         "spool/device exchange-site byte ratio is the "
                         "round-18 acceptance number")
    ap.add_argument("--sites", action="store_true",
                    help="print each warm query's per-site attribution table "
                         "(operator/call-site -> dispatches, transfers, "
                         "bytes) — the breakdown the budget-test docstrings "
                         "cite when a ceiling regresses")
    ap.add_argument("--breakdown", action="store_true",
                    help="print each warm query's wall-clock decomposition "
                         "(execution/tracing.wall_breakdown over the span "
                         "tree: plan / split generation / h2d / device "
                         "dispatch / host pull / unattributed) — the same "
                         "re-derivation contract as --sites/--history: the "
                         "breakdown is computed from spans the run already "
                         "emitted, zero extra dispatches/pulls")
    ap.add_argument("--compiles", action="store_true",
                    help="print each query's compile census (cold-vs-warm "
                         "compile counts/seconds plus the per-site compile "
                         "table from the attribution) — the re-derivation "
                         "contract matches --sites/--breakdown: detection "
                         "is a host-side set lookup, zero extra dispatches/"
                         "pulls, and the WARM row must show 0 compiles "
                         "(the recompile-regression guard "
                         "tests/test_query_budgets.py pins)")
    ap.add_argument("--adaptive", action="store_true",
                    help="print the adaptive advisor's per-statement "
                         "decision trace after the runs (state, frozen "
                         "corrections, win-vs-price reasons) — the warm run "
                         "is execution 2, so a material misestimate recorded "
                         "cold is exactly what the advisor judges here.  "
                         "Consult/observe are host-only: the counters "
                         "printed alongside are unchanged by the advisor "
                         "(the budget suite pins that)")
    ap.add_argument("--skew", action="store_true",
                    help="print each warm query's per-shard attribution "
                         "(site -> per-worker rows, max/mean ratio, argmax "
                         "worker, imbalance wall) from the ShardStats the "
                         "run already recorded — meaningful with "
                         "--distributed (local statements carry no shard "
                         "records).  Same re-derivation contract as "
                         "--sites: the skew derivation consumes host ints "
                         "already pulled at the existing dist.* sites, "
                         "zero new pulls, counters unchanged")
    ap.add_argument("--history", action="store_true",
                    help="print each warm query's est-vs-actual table from "
                         "the plan-actuals history (node path -> CBO "
                         "estimate, actual rows, over/under factor) — the "
                         "same re-derivation contract as --sites: the "
                         "history feed adds ZERO dispatches/pulls, so the "
                         "counters printed alongside are unchanged by it")
    args = ap.parse_args()

    if args.page_cache is not None:
        os.environ["TRINO_TPU_PAGE_CACHE"] = str(args.page_cache)
    os.environ["TRINO_TPU_RESULT_CACHE"] = str(args.result_cache)
    sf = float(os.environ.get("TRACE_SF", "1"))
    split_rows = int(os.environ.get("TRACE_SPLIT_ROWS", str(1 << 21)))
    names = [q.strip() for q in
             os.environ.get("TRACE_QUERIES", ",".join(QUERIES)).split(",")
             if q.strip() in QUERIES]

    engine = Engine()
    engine.register_catalog("tpch", TpchConnector(sf=sf, split_rows=split_rows))

    if args.prepared:
        _trace_prepared(engine, sf, split_rows)
        return
    if args.serve_batch:
        _trace_serve_batch(engine, sf, split_rows)
        return
    if args.distributed:
        _trace_distributed(engine, sf, split_rows, names, QUERIES,
                           args.sites, args.skew)
        return

    def trace(session, name):
        out = {}
        for phase in ("cold", "warm"):
            t0 = time.perf_counter()
            engine.execute_sql(QUERIES[name], session)
            counters = engine.last_query_counters.as_dict()
            sites = counters.pop("sites", {})
            counters.pop("dispatch_latency", None)  # histogram: JSON noise here
            out[phase] = {"wall_s": round(time.perf_counter() - t0, 3),
                          **counters}
            if args.sites and phase == "warm":
                print(f"# {name} warm per-site attribution "
                      "(dispatches/transfers/bytes):", flush=True)
                for key in sorted(sites, key=lambda k: (
                        -sites[k]["dispatches"], -sites[k]["bytes"], k)):
                    s = sites[key]
                    print(f"#   {key:<44} {s['dispatches']:>4} "
                          f"{s['transfers']:>4} {s['bytes']:>8}", flush=True)
            if args.compiles:
                n = out[phase].get("compiles", 0)
                cs = out[phase].get("compile_s", 0.0)
                print(f"# {name} {phase} compiles: {n} "
                      f"({cs * 1000:.1f} ms)", flush=True)
                comp = {k: v for k, v in sites.items() if v.get("compiles")}
                for key in sorted(comp, key=lambda k: (
                        -comp[k].get("compile_s", 0.0), k)):
                    s = comp[key]
                    print(f"#   {key:<44} {s.get('compiles', 0):>4} "
                          f"{s.get('compile_s', 0.0) * 1000:>9.1f} ms",
                          flush=True)
            if args.breakdown and phase == "warm":
                from trino_tpu.execution.tracing import WALL_BUCKETS
                bd = (engine.last_query_trace or {}).get("wall_breakdown") \
                    or {}
                print(f"# {name} warm wall breakdown "
                      f"(total {bd.get('wall_s', 0.0) * 1000:.1f} ms):",
                      flush=True)
                for b in WALL_BUCKETS:
                    v = bd.get(b) or 0.0
                    if v <= 0:
                        continue
                    wall = bd.get("wall_s") or 1.0
                    print(f"#   {b:<18} {v * 1000:>9.2f} ms "
                          f"{v / wall * 100:>5.1f}%", flush=True)
            if args.history and phase == "warm":
                actuals = engine.last_plan_actuals or {}
                print(f"# {name} warm est-vs-actual "
                      f"(plan {actuals.get('fingerprint', '?')}):",
                      flush=True)
                from trino_tpu.execution.history import misestimate
                for path, r in sorted((actuals.get("nodes") or {}).items()):
                    est = r.get("est_rows")
                    actual = r.get("actual_rows", 0)
                    if est is None:
                        drift = "no estimate"
                    else:
                        ratio, direction = misestimate(est, actual)
                        drift = "on estimate" if direction == "exact" \
                            else f"{ratio:.1f}x {direction}"
                    print(f"#   {path:<32} est "
                          f"{'-' if est is None else format(int(est), ',')}"
                          f"{'':<2} actual {actual:,}  {drift}", flush=True)
        return out

    if args.batch is None:
        session = engine.create_session("tpch")
        for name in names:
            print(json.dumps({"query": name, "sf": sf,
                              "split_rows": split_rows, **trace(session, name)}),
                  flush=True)
        if args.adaptive:
            _print_adaptive(engine)
        return

    # side-by-side: batch=1 (exact per-split) vs --batch N.  Separate sessions:
    # dispatch_batch is plan-shaping, so each mode keys (and compiles) its own
    # plan; the warm dispatch delta is the coalescing win the budget test pins.
    s1 = engine.create_session("tpch")
    engine.session_properties.set_property(s1, "dispatch_batch", 1)
    sn = engine.create_session("tpch")
    engine.session_properties.set_property(sn, "dispatch_batch", args.batch)
    for name in names:
        r1 = trace(s1, name)
        rn = trace(sn, name)
        print(json.dumps({"query": name, "sf": sf, "split_rows": split_rows,
                          "batch1": r1, f"batch{args.batch}": rn}), flush=True)
        w1, wn = r1["warm"], rn["warm"]
        print(f"# {name}: warm dispatches {w1['device_dispatches']} -> "
              f"{wn['device_dispatches']} "
              f"({wn['coalesced_splits']} splits coalesced), "
              f"bytes {w1['host_bytes_pulled']} -> {wn['host_bytes_pulled']}",
              flush=True)


def _print_adaptive(engine):
    """Decision trace (--adaptive): one block per statement the advisor has
    state for — what it decided and the win-vs-price arithmetic behind it."""
    adv = getattr(engine, "adaptive_advisor", None)
    info = adv.info() if adv is not None else {}
    print(f"# adaptive decisions ({info.get('replans_total', 0)} replans, "
          f"{info.get('holds_total', 0)} holds, "
          f"{info.get('demotions_total', 0)} demotions, "
          f"{info.get('confirms_total', 0)} confirms):", flush=True)
    for row in (adv.decision_trace() if adv is not None else []):
        sql = " ".join((row.get("sql") or "?").split())
        if len(sql) > 72:
            sql = sql[:69] + "..."
        verdict = row.get("last_verdict") or "no verdict yet"
        print(f"#   [{row['state']:<9}] {verdict:<7} {sql}", flush=True)
        for r in (row.get("reasons") or []):
            print(f"#       {r}", flush=True)


def _trace_distributed(engine, sf, split_rows, names, QUERIES, show_sites,
                       show_skew=False):
    """Worker-mesh trace: cold+warm counters per query in both exchange
    modes (device-resident vs host spool).  The warm device rows — total
    dist.* site bytes and the per-site table — are what
    tests/test_distributed_budgets.py pins; the spool:device byte ratio is
    the exchange-elimination factor bench.py --distributed reports."""
    from trino_tpu.exec.distributed import DistributedExecutor
    from trino_tpu.parallel.mesh import worker_mesh
    from trino_tpu.sql.frontend import compile_sql

    mesh = worker_mesh(min(jax.device_count(), 8))
    session = engine.create_session("tpch")
    for name in names:
        plan = compile_sql(QUERIES[name], engine, session)
        rec = {"query": name, "sf": sf, "split_rows": split_rows,
               "workers": int(mesh.devices.size)}
        for mode, dev in (("device", True), ("spool", False)):
            ex = DistributedExecutor(engine.catalogs, mesh=mesh,
                                     device_exchange=dev)
            out = {}
            for phase in ("cold", "warm"):
                t0 = time.perf_counter()
                ex.execute(plan)
                counters = ex.counters.as_dict()
                sites = counters.pop("sites", {})
                counters.pop("dispatch_latency", None)
                shard = counters.pop("shard_stats", [])
                dist = {k: v for k, v in sites.items() if "dist." in k}
                out[phase] = {
                    "wall_s": round(time.perf_counter() - t0, 3),
                    "dist_site_bytes": sum(v["bytes"] for v in dist.values()),
                    **{k: v for k, v in counters.items() if v}}
                if show_sites and phase == "warm":
                    print(f"# {name} warm {mode} dist sites "
                          "(dispatches/transfers/bytes):", flush=True)
                    for key in sorted(dist, key=lambda k: (
                            -dist[k]["bytes"], k)):
                        s = dist[key]
                        print(f"#   {key:<44} {s['dispatches']:>4} "
                              f"{s['transfers']:>4} {s['bytes']:>9}",
                              flush=True)
                if show_skew and phase == "warm":
                    print(f"# {name} warm {mode} shard skew "
                          "(site/kind -> per-worker rows, ratio):",
                          flush=True)
                    for s in shard:
                        rows = ",".join(str(int(v))
                                        for v in (s.get("rows") or [])[:16])
                        print(f"#   {s.get('site', '?'):<28} "
                              f"{s.get('kind', '?'):<10} "
                              f"{s.get('op') or '-':<12} "
                              f"{s.get('ratio', 1.0):>5.1f}x "
                              f"worker {s.get('worker', 0):<3} "
                              f"{s.get('imbalance_s', 0.0) * 1000:>7.1f} ms "
                              f"[{rows}]", flush=True)
            rec[mode] = out
        print(json.dumps(rec), flush=True)
        db = rec["device"]["warm"]["dist_site_bytes"]
        sb = rec["spool"]["warm"]["dist_site_bytes"]
        ratio = (sb / db) if db else float("inf")
        print(f"# {name}: warm exchange-site bytes spool {sb} -> "
              f"device {db} ({ratio:.1f}x)", flush=True)


def _trace_serve_batch(engine, sf, split_rows):
    """--serve-batch: dispatches-per-request through the template batcher at
    fused window sizes {1, 4, 16}.  Each window runs twice; the SECOND
    (warm — serial path and bindings-jit both compiled) run's counter delta
    is the number that matters: the fused window of N must bill within 2x
    of ONE serial request, not N times it.

    Fusion is deterministic, not raced: the template's lane is marked busy
    by hand, the N requests enqueue as members, and a manual handoff
    promotes the first to driver — the same state the real gather window
    produces, minus the wall clock."""
    import threading

    bt = engine.template_batcher
    bt.enabled = True
    bt.window_s = 0.2  # generous: members are already enqueued at handoff
    point = ("select c_name, c_acctbal, c_mktsegment from customer "
             "where c_custkey = ?")
    ncust = max(int(150000 * sf) - 1, 100)
    session = engine.create_session("tpch")
    # create + CONFIRM the template through the real protocol path (the
    # batcher only fuses confirmed templates), and warm the serial jits
    engine.execute_sql(point, session, parameters=[42])
    engine.execute_sql(point, session, parameters=[97])

    def run_window(n):
        keys = [1 + (i * 61) % ncust for i in range(n)]
        errs: list = []

        def fire(k):
            s = engine.create_session("tpch")
            try:
                engine.execute_sql(point, s, parameters=[int(k)])
            except Exception as e:  # surfaced after join
                errs.append(e)

        before = engine.counters_total.as_dict()
        t0 = time.perf_counter()
        if n == 1:
            fire(keys[0])
        else:
            lane = next(iter(bt._lanes.values()))
            with bt._lock:
                lane.busy = True
            threads = [threading.Thread(target=fire, args=(k,))
                       for k in keys]
            for t in threads:
                t.start()
            t_wait = time.monotonic()
            while time.monotonic() - t_wait < 30:
                with bt._lock:
                    if len(lane.queue) >= n:
                        break
                time.sleep(0.001)
            bt._handoff(lane)
            for t in threads:
                t.join()
        wall = time.perf_counter() - t0
        if errs:
            raise errs[0]
        after = engine.counters_total.as_dict()
        return {
            "wall_s": round(wall, 4),
            "device_dispatches": after["device_dispatches"]
            - before["device_dispatches"],
            "host_bytes_pulled": after["host_bytes_pulled"]
            - before["host_bytes_pulled"],
            "batched_requests": after.get("batched_requests", 0)
            - before.get("batched_requests", 0)}

    serial_d = None
    for n in (1, 4, 16):
        cold = run_window(n)   # first fused run compiles the rung's jit
        warm = run_window(n)
        rec = {"batch": n, "sf": sf, "split_rows": split_rows,
               "cold": cold, "warm": warm,
               "per_request_dispatches": round(
                   warm["device_dispatches"] / n, 2)}
        print(json.dumps(rec), flush=True)
        if n == 1:
            serial_d = warm["device_dispatches"]
        ratio = (warm["device_dispatches"] / serial_d) if serial_d else None
        print(f"# batch={n}: warm {warm['device_dispatches']} dispatches "
              f"({rec['per_request_dispatches']}/request, "
              f"{'-' if ratio is None else format(ratio, '.2f')}x one "
              f"request's bill), {warm['batched_requests']} "
              f"batched_requests", flush=True)


def _trace_prepared(engine, sf, split_rows):
    """PREPARE/EXECUTE point-class trace: per phase, wall + counters (the
    warm rows are the template-path budget; the baseline engine shows what
    the substitution path pays for the same statements)."""
    from trino_tpu import Engine
    from trino_tpu.connectors.tpch import TpchConnector

    baseline = Engine()
    baseline.plan_templates_enabled = False
    baseline.register_catalog(
        "tpch", TpchConnector(sf=sf, split_rows=split_rows))

    point = ("select c_name, c_acctbal, c_mktsegment from customer "
             "where c_custkey = ?")
    for label, eng in (("template", engine), ("substitution", baseline)):
        session = eng.create_session("tpch")
        eng.execute_sql(f"prepare point from {point}", session)
        out = {}
        for phase, key in (("cold", 42), ("warm", 4242), ("warm2", 97)):
            t0 = time.perf_counter()
            eng.execute_sql(f"execute point using {key}", session)
            counters = eng.last_query_counters.as_dict()
            counters.pop("sites", None)
            counters.pop("dispatch_latency", None)
            out[phase] = {"wall_s": round(time.perf_counter() - t0, 4),
                          **{k: v for k, v in counters.items() if v}}
        print(json.dumps({"mode": label, "sf": sf,
                          "split_rows": split_rows, **out}), flush=True)
        w = out["warm2"]
        print(f"# {label}: warm wall {w['wall_s'] * 1000:.1f} ms, "
              f"{w.get('device_dispatches', 0)} dispatches, "
              f"{w.get('plan_template_hits', 0)} template hits", flush=True)


if __name__ == "__main__":
    main()
