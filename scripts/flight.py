#!/usr/bin/env python
"""Flight-recorder reader: post-mortem on a DEAD process's record directory.

The recorder (trino_tpu/execution/flightrecorder.py) mirrors every statement
record into an on-disk JSONL ring when TRINO_TPU_FLIGHT_DIR is set; this
reader needs only that directory — no engine, no jax, no live process — so a
wedged-tunnel capture window leaves an artifact this script can decompose
hours later (the gap scripts/tpu_watch.sh has papered over with hand-rolled
/v1/status tailing for three rounds).

    python scripts/flight.py DIR                 # one summary line per record
    python scripts/flight.py DIR --id query_7    # one record, full JSON
    python scripts/flight.py DIR --json          # every record, JSON lines
    python scripts/flight.py DIR --stalls        # stall events only
    python scripts/flight.py DIR --compiles      # per-statement compile events
    python scripts/flight.py DIR --adaptive      # per-statement plan decisions
    python scripts/flight.py DIR --skew          # per-shard load / stragglers

Summary columns: query id, state, wall, dispatch/byte counters, the compile
census (count + seconds — round 17), and the top wall-breakdown bucket —
"where did the time go" per statement, from disk.
"""

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_reader():
    """Load flightrecorder.py DIRECTLY (not through the trino_tpu package,
    whose __init__ imports jax): the module is stdlib-pure, so this reader
    runs on boxes — and in moments — where jax cannot even initialize
    (exactly when a post-mortem is wanted)."""
    import importlib.util

    path = os.path.join(_REPO, "trino_tpu", "execution", "flightrecorder.py")
    spec = importlib.util.spec_from_file_location("_flightrecorder", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.read_flight_dir, mod.summarize_compiles, mod.summarize_skew


read_flight_dir, summarize_compiles, summarize_skew = _load_reader()

WALL_BUCKETS = ("plan", "compile", "admission_queue", "split_generation",
                "h2d", "device_dispatch", "host_pull", "exchange_wait",
                "retry_backoff", "unattributed")


def _top_bucket(bd):
    if not bd:
        return "-"
    best = max((b for b in WALL_BUCKETS), key=lambda b: bd.get(b) or 0.0)
    v = bd.get(best) or 0.0
    if v <= 0:
        return "-"
    wall = bd.get("wall_s") or 0.0
    pct = f" ({v / wall * 100:.0f}%)" if wall else ""
    return f"{best} {v * 1000:.1f}ms{pct}"


def _summary_line(rec) -> str:
    if rec.get("kind") == "stall":
        stuck = ", ".join(e.get("label", "?")
                          for e in rec.get("stalled") or [])[:60]
        return (f"{'<stall>':<14} {'-':<9} {'-':>9} {'-':>6} {'-':>10} "
                f"{'-':>12}  stuck: {stuck}")
    c = rec.get("counters") or {}
    wall = rec.get("wall_s")
    nc, cs = summarize_compiles(rec)
    comp = f"{nc}/{cs:.2f}s" if nc else "-"
    return (f"{rec.get('query_id') or '?':<14} "
            f"{rec.get('state') or '?':<9} "
            f"{('%.3fs' % wall) if wall is not None else '-':>9} "
            f"{c.get('device_dispatches') or 0:>6} "
            f"{c.get('host_bytes_pulled') or 0:>10} "
            f"{comp:>12}  "
            f"{_top_bucket(rec.get('wall_breakdown'))}"
            + (f"  ERROR: {rec['error'][:60]}" if rec.get("error") else ""))


def _print_compiles(recs) -> None:
    """--compiles detail: every statement record's compile events (site, op
    label, signature, duration) from the census the engine embedded.  The
    count is the CLUSTER truth (merged worker counters); the event lines
    are coordinator-local — a distributed statement legitimately shows
    fewer events than compilations (worker-side compiles live in the
    workers' own census rings)."""
    for rec in recs:
        if rec.get("kind") != "query":
            continue
        nc, cs = summarize_compiles(rec)
        events = rec.get("compile_events") or []
        if not nc and not events:
            continue
        note = "" if len(events) >= nc else \
            f" ({len(events)} local events; rest worker-side)"
        print(f"{rec.get('query_id') or '?'}: {nc} compilations, "
              f"{cs:.3f}s{note}")
        for ev in events:
            exe = f", exe {ev['exe_bytes']}B" if ev.get("exe_bytes") else ""
            print(f"  {ev.get('label') or ev.get('site'):<44} "
                  f"{(ev.get('duration_s') or 0.0) * 1000:>9.1f} ms{exe}  "
                  f"sig: {(ev.get('signature') or '')[:70]}")


def _print_adaptive(recs) -> None:
    """--adaptive detail: the advisor decision each statement ran under
    (round 19), from the record's embedded decision dict — verdict,
    win-vs-price reasons, frozen corrections.  Statements the advisor had
    no opinion on carry no field and are skipped."""
    for rec in recs:
        if rec.get("kind") != "query" or not rec.get("adaptive"):
            continue
        dec = rec["adaptive"]
        win, price = dec.get("predicted_win_s"), dec.get("compile_price_s")
        arith = "" if win is None else (
            f"  win {win:.4f}s x {dec.get('horizon', 0):g} vs "
            + (f"price {price:.4f}s" if price is not None else "unknown price"))
        print(f"{rec.get('query_id') or '?'}: {dec.get('verdict', '?')}"
              f"{arith}")
        for r in (dec.get("reasons") or []):
            print(f"  {r}")


def _print_skew(recs) -> None:
    """--skew detail: every statement record's per-shard attribution
    (round 20) — one line per statement with the worst max/mean ratio and
    summed recoverable imbalance wall, then one line per ShardStats record
    (site, kind, per-worker rows, argmax worker).  Statements that never
    crossed a mesh/cluster exchange carry no field and are skipped."""
    for rec in recs:
        if rec.get("kind") != "query":
            continue
        worst, imb, n = summarize_skew(rec)
        if not n:
            continue
        stats = rec.get("shard_stats") \
            or (rec.get("counters") or {}).get("shard_stats") or []
        print(f"{rec.get('query_id') or '?'}: {n} shard records, "
              f"worst {worst:.1f}x, {imb * 1000:.1f} ms imbalance")
        for s in stats:
            rows = s.get("rows") or []
            rows_str = ",".join(str(int(v)) for v in rows[:16])
            if len(rows) > 16:
                rows_str += ",..."
            lbl = s.get("op") or "-"
            print(f"  {s.get('site', '?'):<28} {s.get('kind', '?'):<10} "
                  f"{lbl:<12} {s.get('ratio', 1.0):>6.1f}x "
                  f"worker {s.get('worker', 0):<3} "
                  f"{s.get('imbalance_s', 0.0) * 1000:>8.1f} ms  "
                  f"rows [{rows_str}]")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dir", help="flight directory (TRINO_TPU_FLIGHT_DIR)")
    ap.add_argument("--id", default=None,
                    help="print ONE record (full JSON) by query id")
    ap.add_argument("--json", action="store_true",
                    help="dump every record as JSON lines")
    ap.add_argument("--stalls", action="store_true",
                    help="stall events only")
    ap.add_argument("--compiles", action="store_true",
                    help="per-statement compile events (site, signature, "
                         "duration) from the embedded census")
    ap.add_argument("--adaptive", action="store_true",
                    help="per-statement adaptive decisions (verdict, "
                         "win-vs-price reasons, corrections) from the "
                         "embedded advisor decision")
    ap.add_argument("--skew", action="store_true",
                    help="per-shard attribution (worker load per exchange, "
                         "max/mean skew, imbalance wall, cluster straggler "
                         "records) from the embedded shard stats")
    args = ap.parse_args(argv)
    recs = read_flight_dir(args.dir)
    if not recs:
        print(f"no flight records under {args.dir}", file=sys.stderr)
        return 1
    if args.id is not None:
        hits = [r for r in recs if r.get("query_id") == args.id]
        if not hits:
            print(f"no record for {args.id}", file=sys.stderr)
            return 1
        print(json.dumps(hits[-1], indent=1))
        return 0
    if args.compiles:
        _print_compiles(recs)
        return 0
    if args.adaptive:
        _print_adaptive(recs)
        return 0
    if args.skew:
        _print_skew(recs)
        return 0
    if args.stalls:
        recs = [r for r in recs if r.get("kind") == "stall"]
    if args.json:
        for r in recs:
            print(json.dumps(r))
        return 0
    print(f"{'query':<14} {'state':<9} {'wall':>9} {'disp':>6} "
          f"{'bytes':>10} {'compiles':>12}  top bucket")
    for r in recs:
        print(_summary_line(r))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # | head closed the pipe: not an error
        sys.exit(0)
