#!/bin/bash
# Probe the axon TPU tunnel; the moment it answers, capture the round-5
# A/B bench matrix (SF1/SF10 x scan-fused on/off) into BENCH_local_r05.json,
# then drive the real chip through the cluster plane once
# (scripts/tpu_cluster_probe.py).  Exits 0 after capture, 1 if the tunnel
# never recovered within the probe window (250 probes, ~150-190s each:
# ~11h when probes fail fast, up to ~21h if every probe eats its timeout).
# Single-instance: flock on scripts/tpu_watch.lock — a second watcher
# touching the device can wedge the tunnel (CLAUDE.md).
cd /root/repo
LOG=scripts/tpu_watch.log
exec 9> scripts/tpu_watch.lock
if ! flock -n 9; then
  echo "$(date -Is) another watcher holds the lock; exiting" >> "$LOG"
  exit 2
fi
echo "$(date -Is) watcher start (r05)" >> "$LOG"
for i in $(seq 1 250); do
  if timeout 150 python -c "import jax; d=jax.devices()[0]; assert d.platform != 'cpu', d" >> "$LOG" 2>&1; then
    echo "$(date -Is) TPU UP on probe $i — starting r05 A/B capture" >> "$LOG"
    # tunnel diagnosis FIRST (fast): per-dispatch overhead + traced Q3/Q18
    # sync sites — the data that decides the round-trip-reduction work
    timeout -k 60 1500 python scripts/tpu_diag.py \
      > scripts/tpu_diag.out 2>&1
    echo "$(date -Is) tpu_diag rc=$? : $(tail -c 300 scripts/tpu_diag.json 2>/dev/null)" >> "$LOG"
    for cfg in "sf1_fused:1:1:900:1200" "sf1_unfused:1:0:900:1200" \
               "sf10_fused:10:1:1500:1800" "sf10_unfused:10:0:1500:1800"; do
      IFS=: read -r name sf fused budget tmo <<< "$cfg"
      # -k: a wedged axon call absorbs SIGTERM indefinitely (bench.py notes);
      # SIGKILL after 60s keeps the watcher itself from hanging.
      BENCH_BUDGET=$budget BENCH_SF=$sf TRINO_TPU_SCAN_FUSED=$fused \
        timeout -k 60 "$tmo" python bench.py \
        > "scripts/bench_${name}.json" 2> "scripts/bench_${name}.log"
      rc=$?
      echo "$(date -Is) $name done rc=$rc : $(cat scripts/bench_${name}.json)" >> "$LOG"
    done
    rm -f scripts/tpu_cluster_probe.json  # never embed a stale probe artifact
    timeout -k 30 900 python scripts/tpu_cluster_probe.py \
      > scripts/tpu_cluster_probe.out 2>&1
    rc=$?
    echo "$(date -Is) cluster probe rc=$rc" >> "$LOG"
    python - <<'PY'
import json, subprocess, time
out = {"captured_at": time.strftime("%Y-%m-%dT%H:%M:%S%z")}
try:
    out["device"] = subprocess.run(
        ["python", "-c", "import jax; print(jax.devices()[0])"],
        capture_output=True, text=True, timeout=180).stdout.strip()
except Exception as e:
    out["device"] = f"probe-error: {e}"
for name in ("sf1_fused", "sf1_unfused", "sf10_fused", "sf10_unfused"):
    try:
        out[name] = json.load(open(f"scripts/bench_{name}.json"))
    except Exception as e:
        out[name] = {"error": str(e)}
try:
    out["cluster_tpu_probe"] = json.load(open("scripts/tpu_cluster_probe.json"))
except Exception as e:
    out["cluster_tpu_probe"] = {"error": str(e)}
json.dump(out, open("BENCH_local_r05.json", "w"), indent=1)
PY
    echo "$(date -Is) wrote BENCH_local_r05.json" >> "$LOG"
    exit 0
  fi
  echo "$(date -Is) probe $i: tunnel down" >> "$LOG"
  sleep 150
done
exit 1
