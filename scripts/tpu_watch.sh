#!/bin/bash
# Probe the axon TPU tunnel; the moment it answers, capture the round-6
# matrix into BENCH_local_r06.json: tunnel diagnosis, the dispatch-coalescing
# microbench curve (batch K in {1,2,4,8,16} — the per-dispatch overhead this
# round's whole design bets on), then SF1/SF10 bench A/B at dispatch batch
# 4 vs 1 (scan-fused stays OFF everywhere: the r05 capture proved on-device
# regeneration loses on the tunnel; coalescing batches HOST-generated pages
# instead).  Capture order is priority order — the tunnel historically wedges
# within ~30 min of first contact, so the cheap, decision-driving runs go
# first.  Exits 0 after capture, 1 if the tunnel never recovered within the
# probe window.  Single-instance: flock on scripts/tpu_watch.lock — a second
# watcher touching the device can wedge the tunnel (CLAUDE.md).
cd /root/repo
LOG=scripts/tpu_watch.log
exec 9> scripts/tpu_watch.lock
if ! flock -n 9; then
  echo "$(date -Is) another watcher holds the lock; exiting" >> "$LOG"
  exit 2
fi
echo "$(date -Is) watcher start (r06)" >> "$LOG"

# Round 8: stall post-mortems.  Every bench run arms the engine's stall
# watchdog (TRINO_TPU_STALL_S; 240s — cold Q1 compile alone is ~110s on the
# tunnel, the threshold must clear any legit compile) and serves
# GET /v1/status (BENCH_STATUS_PORT).  status_tail polls it in the
# background and archives any "stalled" verdict — a wedge mid-capture
# leaves scripts/stall_reports.jsonl (stuck site + thread stack) next to
# the diag output instead of only an rc=124 null.
STATUS_PORT=18923
export TRINO_TPU_STALL_S="${TRINO_TPU_STALL_S:-240}"
export BENCH_STATUS_PORT=$STATUS_PORT
status_tail() {
  while :; do
    s=$(timeout 8 python -c "import urllib.request as u;print(u.urlopen('http://127.0.0.1:${STATUS_PORT}/v1/status',timeout=5).read().decode())" 2>/dev/null)
    if [ -n "$s" ]; then
      printf '%s\n' "$s" > scripts/stall_status_last.json
      if printf '%s' "$s" | grep -q '"status": *"stalled"'; then
        printf '%s\n' "$s" >> scripts/stall_reports.jsonl
        echo "$(date -Is) STALL detected via /v1/status (archived to scripts/stall_reports.jsonl)" >> "$LOG"
      fi
    fi
    sleep 20
  done
}
status_tail &
STATUS_TAIL_PID=$!
trap 'kill $STATUS_TAIL_PID 2>/dev/null' EXIT
for i in $(seq 1 250); do
  if timeout 150 python -c "import jax; d=jax.devices()[0]; assert d.platform != 'cpu', d" >> "$LOG" 2>&1; then
    echo "$(date -Is) TPU UP on probe $i — starting r06 capture" >> "$LOG"
    # tunnel diagnosis FIRST (fast): per-dispatch overhead + traced Q3/Q18
    # sync sites — the data that decides the round-trip-reduction work
    timeout -k 60 1500 python scripts/tpu_diag.py \
      > scripts/tpu_diag.out 2>&1
    echo "$(date -Is) tpu_diag rc=$? : $(tail -c 300 scripts/tpu_diag.json 2>/dev/null)" >> "$LOG"
    # dispatch-coalescing overhead curve (NEW in r06): fixed rows, batch K
    # sweep — on the tunnel each saved dispatch is a full round-trip, so this
    # is the direct measurement of the win the budget tests pin on CPU
    timeout -k 60 1200 python bench_micro.py --rows 4000000 \
      --kernels dispatch_coalesce \
      > scripts/bench_micro_coalesce.json 2> scripts/bench_micro_coalesce.log
    echo "$(date -Is) micro coalesce rc=$? : $(tail -c 300 scripts/bench_micro_coalesce.json)" >> "$LOG"
    for cfg in "sf1_batch4:1:4:900:1200" "sf1_batch1:1:1:900:1200" \
               "sf10_batch4:10:4:1500:1800" "sf10_batch1:10:1:1500:1800"; do
      IFS=: read -r name sf batch budget tmo <<< "$cfg"
      # -k: a wedged axon call absorbs SIGTERM indefinitely (bench.py notes);
      # SIGKILL after 60s keeps the watcher itself from hanging.
      BENCH_BUDGET=$budget BENCH_SF=$sf TRINO_TPU_SCAN_FUSED=0 \
        TRINO_TPU_DISPATCH_BATCH=$batch \
        timeout -k 60 "$tmo" python bench.py \
        > "scripts/bench_${name}.json" 2> "scripts/bench_${name}.log"
      rc=$?
      echo "$(date -Is) $name done rc=$rc : $(cat scripts/bench_${name}.json)" >> "$LOG"
    done
    rm -f scripts/tpu_cluster_probe.json  # never embed a stale probe artifact
    timeout -k 30 900 python scripts/tpu_cluster_probe.py \
      > scripts/tpu_cluster_probe.out 2>&1
    rc=$?
    echo "$(date -Is) cluster probe rc=$rc" >> "$LOG"
    python - <<'PY'
import json, subprocess, time
out = {"captured_at": time.strftime("%Y-%m-%dT%H:%M:%S%z")}
try:
    out["device"] = subprocess.run(
        ["python", "-c", "import jax; print(jax.devices()[0])"],
        capture_output=True, text=True, timeout=180).stdout.strip()
except Exception as e:
    out["device"] = f"probe-error: {e}"
try:
    out["dispatch_coalesce_curve"] = json.load(
        open("scripts/bench_micro_coalesce.json"))
except Exception as e:
    out["dispatch_coalesce_curve"] = {"error": str(e)}
for name in ("sf1_batch4", "sf1_batch1", "sf10_batch4", "sf10_batch1"):
    try:
        out[name] = json.load(open(f"scripts/bench_{name}.json"))
    except Exception as e:
        out[name] = {"error": str(e)}
try:
    out["cluster_tpu_probe"] = json.load(open("scripts/tpu_cluster_probe.json"))
except Exception as e:
    out["cluster_tpu_probe"] = {"error": str(e)}
json.dump(out, open("BENCH_local_r06.json", "w"), indent=1)
PY
    echo "$(date -Is) wrote BENCH_local_r06.json" >> "$LOG"
    exit 0
  fi
  echo "$(date -Is) probe $i: tunnel down" >> "$LOG"
  sleep 150
done
exit 1
