#!/bin/bash
# Probe the axon TPU tunnel; the moment it answers, capture bench numbers
# (SF1 then SF10) into BENCH_local_r04.json artifacts.  Exits 0 after capture,
# 1 if the tunnel never recovered within ~11.5h.
cd /root/repo
LOG=scripts/tpu_watch.log
echo "$(date -Is) watcher start (r04)" >> "$LOG"
for i in $(seq 1 220); do
  if timeout 150 python -c "import jax; d=jax.devices()[0]; assert d.platform != 'cpu', d" >> "$LOG" 2>&1; then
    echo "$(date -Is) TPU UP on probe $i — starting capture" >> "$LOG"
    BENCH_BUDGET=1800 BENCH_SF=1 timeout 2100 python bench.py \
      > scripts/bench_sf1.json 2> scripts/bench_sf1.log
    echo "$(date -Is) SF1 done rc=$? : $(cat scripts/bench_sf1.json)" >> "$LOG"
    BENCH_BUDGET=2400 BENCH_SF=10 timeout 2700 python bench.py \
      > scripts/bench_sf10.json 2> scripts/bench_sf10.log
    echo "$(date -Is) SF10 done rc=$? : $(cat scripts/bench_sf10.json)" >> "$LOG"
    python - <<'PY'
import json, subprocess, time
out = {"captured_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
       "device": subprocess.run(["python","-c","import jax; print(jax.devices()[0])"],
                                capture_output=True, text=True, timeout=180).stdout.strip()}
for sf in ("sf1", "sf10"):
    try:
        out[sf] = json.load(open(f"scripts/bench_{sf}.json"))
    except Exception as e:
        out[sf] = {"error": str(e)}
json.dump(out, open("BENCH_local_r04.json", "w"), indent=1)
PY
    echo "$(date -Is) wrote BENCH_local_r04.json" >> "$LOG"
    exit 0
  fi
  echo "$(date -Is) probe $i: tunnel down" >> "$LOG"
  sleep 180
done
exit 1
