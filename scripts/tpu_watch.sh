#!/bin/bash
# Probe the axon TPU tunnel; the moment it answers, capture the round-9
# matrix into BENCH_local_r09.json: tunnel diagnosis, the H2D-transfer and
# dispatch-coalescing microbench curves, then the BUFFER-POOL A/B — SF1 with
# the device page cache on vs off (TRINO_TPU_PAGE_CACHE), then SF10 the same
# (scan-fused stays OFF everywhere: the r05 capture proved on-device
# regeneration loses on the tunnel; the pool keeps HOST-generated pages
# RESIDENT instead, which should zero the per-split generation round-trips
# warm).  Each bench JSON embeds per_query page_cache hits/misses/bytes_saved
# — the hit-rate archive the round-9 issue asks for.  Capture order is
# priority order — the tunnel historically wedges within ~30 min of first
# contact, so the cheap, decision-driving runs go first.  Exits 0 after
# capture, 1 if the tunnel never recovered within the probe window.
# Single-instance: flock on scripts/tpu_watch.lock — a second watcher
# touching the device can wedge the tunnel (CLAUDE.md).
cd /root/repo
LOG=scripts/tpu_watch.log
exec 9> scripts/tpu_watch.lock
if ! flock -n 9; then
  echo "$(date -Is) another watcher holds the lock; exiting" >> "$LOG"
  exit 2
fi
echo "$(date -Is) watcher start (r09)" >> "$LOG"

# Round 8: stall post-mortems.  Every bench run arms the engine's stall
# watchdog and serves GET /v1/status (BENCH_STATUS_PORT).  status_tail
# polls it in the background and archives any "stalled" verdict — a wedge
# mid-capture leaves scripts/stall_reports.jsonl (stuck site + thread
# stack) next to the diag output instead of only an rc=124 null.
# Round 17: the watchdog is COMPILE-AWARE — a first-seen-signature dispatch
# is judged against TRINO_TPU_STALL_COMPILE_S and verdicts "compiling", so
# STALL_S finally sits at tight WEDGE scale (30s; a tunnel round-trip is
# milliseconds) instead of the old 240s that had to clear the ~110s cold
# Q1 compile.  COMPILE_S=600 clears any legit on-device compile; past it a
# "compile" really is a wedge and reports stalled.
STATUS_PORT=18923
export TRINO_TPU_STALL_S="${TRINO_TPU_STALL_S:-30}"
export TRINO_TPU_STALL_COMPILE_S="${TRINO_TPU_STALL_COMPILE_S:-600}"
export BENCH_STATUS_PORT=$STATUS_PORT
# Round 16: every capture run's FLIGHT RECORDER mirrors to disk — one JSONL
# record per statement (counters, span tree, wall breakdown) plus stall
# events, surviving the process.  scripts/flight.py reads the directory even
# after a wedge kills the run; the status_tail below stays as a live
# in-addition signal, but the recorder directory is the durable artifact.
export TRINO_TPU_FLIGHT_DIR=scripts/flight_r16
export TRINO_TPU_FLIGHT_BYTES=$((256 * 1024 * 1024))
# NEVER delete a previous ring — it may be the only record of a wedged
# session nobody has read yet.  Archive it timestamped, keep the last 3.
if [ -d scripts/flight_r16 ]; then
  mv scripts/flight_r16 "scripts/flight_r16.prev.$(date +%s)"
fi
ls -dt scripts/flight_r16.prev.* 2>/dev/null | tail -n +4 | xargs -r rm -rf
status_tail() {
  while :; do
    s=$(timeout 8 python -c "import urllib.request as u;print(u.urlopen('http://127.0.0.1:${STATUS_PORT}/v1/status',timeout=5).read().decode())" 2>/dev/null)
    if [ -n "$s" ]; then
      printf '%s\n' "$s" > scripts/stall_status_last.json
      if printf '%s' "$s" | grep -q '"status": *"stalled"'; then
        printf '%s\n' "$s" >> scripts/stall_reports.jsonl
        echo "$(date -Is) STALL detected via /v1/status (archived to scripts/stall_reports.jsonl)" >> "$LOG"
      fi
    fi
    sleep 20
  done
}
status_tail &
STATUS_TAIL_PID=$!
trap 'kill $STATUS_TAIL_PID 2>/dev/null' EXIT
for i in $(seq 1 250); do
  if timeout 150 python -c "import jax; d=jax.devices()[0]; assert d.platform != 'cpu', d" >> "$LOG" 2>&1; then
    echo "$(date -Is) TPU UP on probe $i — starting r09 capture" >> "$LOG"
    # tunnel diagnosis FIRST (fast): per-dispatch overhead + traced Q3/Q18
    # sync sites — the data that decides the round-trip-reduction work
    timeout -k 60 1500 python scripts/tpu_diag.py \
      > scripts/tpu_diag.out 2>&1
    echo "$(date -Is) tpu_diag rc=$? : $(tail -c 300 scripts/tpu_diag.json 2>/dev/null)" >> "$LOG"
    # H2D staging bandwidth + dispatch-coalescing curves (cheap, run first):
    # bytes_saved/bandwidth prices the cache's savings in wall-clock, and
    # each saved dispatch is a full tunnel round-trip
    timeout -k 60 1200 python bench_micro.py --rows 16000000 \
      --kernels h2d_transfer,dispatch_coalesce \
      > scripts/bench_micro_r09.json 2> scripts/bench_micro_r09.log
    echo "$(date -Is) micro h2d+coalesce rc=$? : $(tail -c 300 scripts/bench_micro_r09.json)" >> "$LOG"
    # round-13 Pallas A/B: per-kernel XLA-vs-Mosaic throughput + result
    # equality for probe / build / agg-insert / compact, COMPILED for the
    # first time (CPU runs only prove parity through the interpreter).  This
    # is the go/no-go datum for keeping TRINO_TPU_PALLAS default-on for TPU —
    # cheap, so it runs long before the SF100 tail (capture beats feature
    # work inside the ~30-min wedge window).
    timeout -k 60 1200 python bench_micro.py --rows 4000000 \
      --kernels join_probe_ab,join_build_ab,hashagg_insert_ab,compact_ab \
      > scripts/bench_micro_pallas.json 2> scripts/bench_micro_pallas.log
    echo "$(date -Is) micro pallas A/B rc=$? : $(tail -c 300 scripts/bench_micro_pallas.json)" >> "$LOG"
    # round-18 mesh-exchange A/B: the distributed executor on the real chips,
    # device-resident exchange (default) vs the host-spool path
    # (TRINO_TPU_DEVICE_EXCHANGE=0).  Each half embeds per-query
    # dist_site_bytes — the first on-device datum for whether the carried
    # receive buffers pay off when a host pull costs a real tunnel
    # round-trip, not CPU-mesh microseconds.  Cheap (SF1), so it runs before
    # the SF10/SF100 tail; the route+append micro kernels price the
    # all_to_all step itself.
    timeout -k 60 900 python bench_micro.py --rows 4000000 \
      --kernels exchange_route,exchange_append \
      > scripts/bench_micro_exchange.json 2> scripts/bench_micro_exchange.log
    echo "$(date -Is) micro exchange rc=$? : $(tail -c 300 scripts/bench_micro_exchange.json)" >> "$LOG"
    for cfg in "dist_device: " "dist_spool:TRINO_TPU_DEVICE_EXCHANGE=0"; do
      IFS=: read -r name envset <<< "$cfg"
      env $envset BENCH_BUDGET=900 BENCH_SF=1 BENCH_QUERIES=q1,q3,q9,q18 \
        TRINO_TPU_SCAN_FUSED=0 \
        timeout -k 60 1200 python bench.py --distributed \
        > "scripts/bench_${name}.json" 2> "scripts/bench_${name}.log"
      echo "$(date -Is) $name rc=$? : $(tail -c 300 scripts/bench_${name}.json)" >> "$LOG"
    done
    # round-20 skewed-key capture: a hot-key sort (low-cardinality
    # o_orderstatus — range partitioning piles ~half the table on boundary
    # workers) vs a uniform control through the mesh at SF1, each warm run's
    # ShardStats embedded — the first on-device skew/straggler datum.
    # Cheap, so it rides right after the exchange A/B it decomposes.
    SKEW_SF=1 timeout -k 60 900 python scripts/skew_capture.py \
      > scripts/bench_dist_skew.json 2> scripts/bench_dist_skew.log
    echo "$(date -Is) dist skew rc=$? : $(tail -c 300 scripts/bench_dist_skew.json)" >> "$LOG"
    # buffer-pool A/B (the round-9 capture): cache on (2GB budget) vs off,
    # SF1 first — hit rates + bytes_saved embed in each bench JSON
    for cfg in "sf1_cache:1:2147483648:900:1200" "sf1_nocache:1:0:900:1200" \
               "sf10_cache:10:8589934592:1500:1800" "sf10_nocache:10:0:1500:1800"; do
      IFS=: read -r name sf budget_b budget tmo <<< "$cfg"
      # -k: a wedged axon call absorbs SIGTERM indefinitely (bench.py notes);
      # SIGKILL after 60s keeps the watcher itself from hanging.
      BENCH_BUDGET=$budget BENCH_SF=$sf TRINO_TPU_SCAN_FUSED=0 \
        TRINO_TPU_PAGE_CACHE=$budget_b \
        timeout -k 60 "$tmo" python bench.py \
        > "scripts/bench_${name}.json" 2> "scripts/bench_${name}.log"
      rc=$?
      echo "$(date -Is) $name done rc=$rc : $(cat scripts/bench_${name}.json)" >> "$LOG"
    done
    # round-10 chaos pass on the REAL device: the fault paths (wedges, lost
    # round-trips, denied reservations) are exactly what the tunnel exercises
    # for free — one JSON line, same contract as bench.py.  q18 in the list
    # also drives the round-11 PRESSURE matrix (tiered-spill ladder) against
    # the real q18 on device.
    CHAOS_SF=1 CHAOS_QUERIES=q1,q3,q18 CHAOS_BUDGET=900 \
      TRINO_TPU_PAGE_CACHE=1073741824 \
      timeout -k 60 1200 python scripts/chaos.py \
      > scripts/chaos_r10.json 2> scripts/chaos_r10.log
    rc=$?
    echo "$(date -Is) chaos rc=$rc : $(tail -c 300 scripts/chaos_r10.json)" >> "$LOG"
    # round-12 serving A/B: concurrent mixed load against the coordinator
    # HTTP protocol, result cache off vs on (bench_serve runs BOTH halves
    # in one invocation and embeds per-class p50/p99 + hit rates + the
    # zero-dispatch verification) — the first on-device datum for ROADMAP
    # item 4's "serve traffic" goal.  Cheap relative to the SF100 tail, so
    # it runs before the spill/SF100 captures.
    SERVE_SF=1 SERVE_DURATION=60 SERVE_CLIENTS=4 SERVE_QPS=8 \
      SERVE_BUDGET=900 TRINO_TPU_SCAN_FUSED=0 \
      timeout -k 60 1200 python bench_serve.py \
      > scripts/bench_serve_r12.json 2> scripts/bench_serve_r12.log
    echo "$(date -Is) serve A/B rc=$? : $(tail -c 300 scripts/bench_serve_r12.json 2>/dev/null)" >> "$LOG"
    # round-11 forced-spill A/B: q18 SF1 unconstrained vs TINY pool budgets
    # (page cache shrunk to force the spill ladder's HBM tier, host watermark
    # down to overflow into disk) — prices each tier's round-trip/wall cost
    # on the real tunnel, the SF100 go/no-go datum
    BENCH_BUDGET=900 BENCH_SF=1 BENCH_QUERIES=q18 TRINO_TPU_SCAN_FUSED=0 \
      TRINO_TPU_PAGE_CACHE=33554432 TRINO_TPU_SPILL_HOST_BYTES=33554432 \
      timeout -k 60 1200 python bench.py \
      > scripts/bench_sf1_spill.json 2> scripts/bench_sf1_spill.log
    echo "$(date -Is) spill A/B rc=$? : $(cat scripts/bench_sf1_spill.json 2>/dev/null | tail -c 300)" >> "$LOG"
    # SF100 q18 (the capture the tiered spill exists for): hours-long on a
    # good day, so it runs LAST — everything decision-driving is already on
    # disk if the tunnel wedges mid-run
    BENCH_BUDGET=14400 BENCH_SF=100 BENCH_QUERIES=q18 TRINO_TPU_SCAN_FUSED=0 \
      timeout -k 60 18000 python bench.py \
      > scripts/bench_sf100_q18.json 2> scripts/bench_sf100_q18.log
    echo "$(date -Is) SF100 q18 rc=$? : $(cat scripts/bench_sf100_q18.json 2>/dev/null | tail -c 300)" >> "$LOG"
    rm -f scripts/tpu_cluster_probe.json  # never embed a stale probe artifact
    timeout -k 30 900 python scripts/tpu_cluster_probe.py \
      > scripts/tpu_cluster_probe.out 2>&1
    rc=$?
    echo "$(date -Is) cluster probe rc=$rc" >> "$LOG"
    python - <<'PY'
import json, subprocess, time
out = {"captured_at": time.strftime("%Y-%m-%dT%H:%M:%S%z")}
try:
    out["device"] = subprocess.run(
        ["python", "-c", "import jax; print(jax.devices()[0])"],
        capture_output=True, text=True, timeout=180).stdout.strip()
except Exception as e:
    out["device"] = f"probe-error: {e}"
try:
    out["micro_curves"] = [json.loads(l) for l in
                           open("scripts/bench_micro_r09.json")
                           if l.strip()]
except Exception as e:
    out["micro_curves"] = {"error": str(e)}
try:
    out["pallas_micro"] = [json.loads(l) for l in
                           open("scripts/bench_micro_pallas.json")
                           if l.strip()]
except Exception as e:
    out["pallas_micro"] = {"error": str(e)}
# round 18: the mesh-exchange A/B (device receive buffers vs host spool)
# + the route/append micro kernels that price the all_to_all step
try:
    out["exchange_micro"] = [json.loads(l) for l in
                             open("scripts/bench_micro_exchange.json")
                             if l.strip()]
except Exception as e:
    out["exchange_micro"] = {"error": str(e)}
for name in ("dist_device", "dist_spool", "dist_skew"):
    try:
        out[name] = json.load(open(f"scripts/bench_{name}.json"))
    except Exception as e:
        out[name] = {"error": str(e)}
for name in ("sf1_cache", "sf1_nocache", "sf10_cache", "sf10_nocache"):
    try:
        out[name] = json.load(open(f"scripts/bench_{name}.json"))
    except Exception as e:
        out[name] = {"error": str(e)}
try:
    out["cluster_tpu_probe"] = json.load(open("scripts/tpu_cluster_probe.json"))
except Exception as e:
    out["cluster_tpu_probe"] = {"error": str(e)}
try:
    out["chaos"] = json.load(open("scripts/chaos_r10.json"))
except Exception as e:
    out["chaos"] = {"error": str(e)}
try:
    out["serve"] = json.load(open("scripts/bench_serve_r12.json"))
except Exception as e:
    out["serve"] = {"error": str(e)}
for name in ("sf1_spill", "sf100_q18"):
    try:
        out[name] = json.load(open(f"scripts/bench_{name}.json"))
    except Exception as e:
        out[name] = {"error": str(e)}
# round 16: flight-recorder summary — per-statement wall breakdowns + stall
# events captured across every bench above, read straight from the disk ring
# (the full directory scripts/flight_r16 stays on disk for scripts/flight.py)
try:
    import subprocess as _sp
    flight = _sp.run(["python", "scripts/flight.py", "scripts/flight_r16",
                      "--json"], capture_output=True, text=True, timeout=120)
    recs = [json.loads(l) for l in flight.stdout.splitlines() if l.strip()]
    out["flight"] = {"records": len(recs),
                     "stalls": [r for r in recs if r.get("kind") == "stall"],
                     "breakdowns": [
                         {"query_id": r.get("query_id"),
                          "state": r.get("state"),
                          "wall_breakdown": r.get("wall_breakdown")}
                         for r in recs if r.get("kind") == "query"][-40:]}
except Exception as e:
    recs = []
    out["flight"] = {"error": str(e)}
# round 17: the ON-DEVICE compile census — per-statement compile
# counts/seconds plus every retained compile event (site, signature,
# XLA duration).  This is exactly the datum the capture matrix lacked:
# what cold compilation actually costs on the tunnel, per operator.
# Its OWN try: a torn/legacy record must not clobber the flight summary
# above (and vice versa) — the two artifacts stay independent.
try:
    qrecs = [r for r in recs if r.get("kind") == "query"]
    out["compile_census"] = {
        "statements_with_compiles": sum(
            1 for r in qrecs if (r.get("compiles") or 0) > 0),
        "compiles_total": sum(r.get("compiles") or 0 for r in qrecs),
        "compile_s_total": round(
            sum(float(r.get("compile_s") or 0.0) for r in qrecs), 3),
        "events": [e for r in qrecs
                   for e in (r.get("compile_events") or [])][-200:]}
except Exception as e:
    out["compile_census"] = {"error": str(e)}
json.dump(out, open("BENCH_local_r09.json", "w"), indent=1)
PY
    echo "$(date -Is) wrote BENCH_local_r09.json (flight ring: scripts/flight_r16)" >> "$LOG"
    exit 0
  fi
  echo "$(date -Is) probe $i: tunnel down" >> "$LOG"
  sleep 150
done
exit 1
