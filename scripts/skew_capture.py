#!/usr/bin/env python
"""Skewed-key mesh capture (round 20): one hot-key statement and a uniform
control through DistributedExecutor, with each warm run's ShardStats records
— the on-device skew/straggler datum scripts/tpu_watch.sh archives next to
the round-18 exchange A/B.

TPC-H data is uniform per key, so the hot-key half sorts on the
low-cardinality o_orderstatus column (3 distinct values, one ~2% of rows):
the sort's range partitioning lands nearly half the table on single boundary
workers, which is exactly the load shape the per-shard attribution exists to
expose.  The control sorts the dense unique key and spreads evenly.

One JSON line always (bench.py contract).  SKEW_SF overrides the scale
factor (default 1).  JAX_PLATFORMS=cpu runs the virtual 8-device mesh
(same env dance as scripts/query_counters.py --distributed).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_force_cpu = os.environ.get("JAX_PLATFORMS") == "cpu"
if _force_cpu:
    os.environ.pop("JAX_PLATFORMS")
    if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if _force_cpu:
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


def main():
    from trino_tpu import Engine
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.exec.distributed import DistributedExecutor
    from trino_tpu.parallel.mesh import worker_mesh
    from trino_tpu.sql.frontend import compile_sql

    sf = float(os.environ.get("SKEW_SF", "1"))
    out = {"sf": sf, "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S%z")}
    try:
        engine = Engine()
        engine.register_catalog("tpch", TpchConnector(sf=sf))
        session = engine.create_session("tpch")
        mesh = worker_mesh(min(jax.device_count(), 8))
        out["workers"] = int(mesh.devices.size)
        stmts = {
            "hot": "select o_orderstatus, o_totalprice from orders "
                   "order by o_orderstatus",
            "uniform": "select o_orderkey, o_totalprice from orders "
                       "order by o_orderkey",
        }
        for name, sql in stmts.items():
            plan = compile_sql(sql, engine, session)
            ex = DistributedExecutor(engine.catalogs, mesh=mesh)
            ex.execute(plan)  # cold: compile + first routing
            t0 = time.perf_counter()
            ex.execute(plan)
            wall = time.perf_counter() - t0
            stats = [dict(r) for r in ex.counters.shard_stats]
            worst = max((float(r.get("ratio") or 1.0) for r in stats),
                        default=1.0)
            out[name] = {
                "warm_s": round(wall, 3),
                "worst_ratio": round(worst, 2),
                "imbalance_s": round(
                    sum(float(r.get("imbalance_s") or 0.0)
                        for r in stats), 4),
                "shard_stats": stats,
            }
    except Exception as e:  # one JSON line always, even on a wedged tunnel
        out["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
