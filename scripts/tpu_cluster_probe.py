"""Drive the real TPU through the cluster plane once (round-4 verdict item 9).

The coordinator process pins itself to the CPU backend (the tunnel wedges when
two processes touch the device, CLAUDE.md), spawns ONE worker process WITHOUT
TRINO_TPU_WORKER_CPU so the worker initialises the default (axon TPU) platform,
and runs one aggregate query through fragment dispatch + spooled exchange.
Writes scripts/tpu_cluster_probe.json {ok, rows_match, worker_saw_axon, ...}.

On SIGTERM (the watcher's `timeout`) the handler raises so the finally block
still reaps the worker — an orphaned worker would keep holding the device and
wedge the tunnel for the next probe.
"""

import json
import os
import pathlib
import signal
import subprocess
import sys
import tempfile
import time

os.environ.pop("JAX_PLATFORMS", None)
os.environ.pop("TRINO_TPU_WORKER_CPU", None)
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # coordinator stays off the device
jax.config.update("jax_enable_x64", True)

REPO = str(pathlib.Path(__file__).resolve().parents[1])
sys.path.insert(0, REPO)

from trino_tpu import Engine  # noqa: E402
from trino_tpu.connectors.tpch import TpchConnector  # noqa: E402
from trino_tpu.server.cluster import ClusterCoordinator  # noqa: E402

CATALOGS = {"tpch": {"connector": "tpch", "sf": 0.05, "split_rows": 1 << 13}}
Q = """select l_returnflag, l_linestatus, sum(l_quantity) qty, count(*) c
       from lineitem where l_shipdate <= date '1998-09-02'
       group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus"""


def _sigterm(signum, frame):  # noqa: ARG001
    raise SystemExit(143)


signal.signal(signal.SIGTERM, _sigterm)

out = {"ok": False, "started_at": time.strftime("%Y-%m-%dT%H:%M:%S%z")}
worker = None
coord = None
tmp = tempfile.mkdtemp(prefix="tpu_cluster_probe_")
wlog_path = os.path.join(REPO, "scripts", "tpu_cluster_worker.log")
try:
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.05, split_rows=1 << 13))
    coord = ClusterCoordinator(e, os.path.join(tmp, "spool"),
                               heartbeat_interval=0.5)
    url = coord.start()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # NO TRINO_TPU_WORKER_CPU: the worker takes the default (axon) platform.
    # Logs go to a file, not a pipe — an unread pipe fills and deadlocks the
    # worker mid-query.  start_new_session lets us kill the whole group.
    with open(wlog_path, "w") as wlog:
        worker = subprocess.Popen(
            [sys.executable, "-m", "trino_tpu.server.cluster",
             "--coordinator", url, "--catalogs", json.dumps(CATALOGS),
             "--spool", os.path.join(tmp, "spool"), "--node-id", "tpu-w1"],
            env=env, stdout=wlog, stderr=subprocess.STDOUT,
            start_new_session=True)
    coord.wait_for_workers(1, timeout=300)  # first TPU init is slow
    t0 = time.time()
    expected = e.execute_sql(Q).rows()
    got = coord.execute_sql(Q).rows()
    out["query_seconds"] = round(time.time() - t0, 3)
    out["rows_match"] = got == expected
    out["n_rows"] = len(got)
    out["ok"] = bool(out["rows_match"])
except BaseException as exc:  # noqa: BLE001 — artifact must always be written
    out["error"] = f"{type(exc).__name__}: {exc}"
finally:
    try:
        if coord is not None:
            coord.stop()
    except Exception:
        pass
    if worker is not None:
        try:
            os.killpg(worker.pid, signal.SIGTERM)
        except OSError:
            pass
        try:
            worker.wait(timeout=20)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(worker.pid, signal.SIGKILL)
            except OSError:
                pass
            worker.wait(timeout=20)
        try:
            wtext = open(wlog_path, "rb").read().decode("utf-8", "replace")
        except OSError:
            wtext = ""
        out["worker_saw_axon"] = "axon" in wtext  # full log, not the tail
        out["worker_log_tail"] = wtext[-1500:]
    with open(os.path.join(REPO, "scripts", "tpu_cluster_probe.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out)[:2000])
