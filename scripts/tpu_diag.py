"""Tunnel diagnosis: decompose where join-query wall-clock goes on the real
TPU.  Run on first tunnel contact, BEFORE the bench matrix (fast: ~3 min).

Measures
  1. per-dispatch overhead: tiny jitted call, chained async calls, scalar
     device_put, bool() sync, small/large device->host transfers
  2. a warm TPC-H Q3/Q18 at SF1 with every _host()/__bool__ call site traced
     and timed, so the per-site tunnel cost is attributable line-by-line.

Writes one JSON blob to scripts/tpu_diag.json (and a readable log to stdout).
"""

import collections
import json
import os
import sys
import time
import traceback

os.environ.pop("JAX_PLATFORMS", None)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

out: dict = {"started_at": time.strftime("%Y-%m-%dT%H:%M:%S%z")}


def timed(fn, reps=20, warm=2):
    for _ in range(warm):
        fn()
    t0 = time.perf_counter()
    for _ in range(reps):
        fn()
    return (time.perf_counter() - t0) / reps


def main():
    dev = jax.devices()[0]
    out["device"] = str(dev)
    print("device:", dev, flush=True)

    # --- 1. primitive costs -------------------------------------------------
    @jax.jit
    def tiny(x):
        return x + 1

    x = jnp.zeros((8,), jnp.int64)
    tiny(x).block_until_ready()
    out["tiny_call_sync_s"] = timed(lambda: tiny(x).block_until_ready())

    def chain10():
        y = x
        for _ in range(10):
            y = tiny(y)
        y.block_until_ready()

    out["chain10_sync_s"] = timed(chain10, reps=10)

    out["device_put_scalar_s"] = timed(
        lambda: jax.device_put(np.int64(7)).block_until_ready())
    big = np.zeros((1 << 20,), np.int64)  # 8 MB
    out["device_put_8mb_s"] = timed(
        lambda: jax.device_put(big).block_until_ready(), reps=5)

    db = jax.device_put(big)
    db.block_until_ready()
    out["host_pull_8mb_s"] = timed(lambda: np.asarray(db), reps=5)
    small = jax.device_put(np.zeros((16,), np.int64))
    small.block_until_ready()
    out["host_pull_small_s"] = timed(lambda: np.asarray(small))
    flag = jax.device_put(np.bool_(True))
    flag.block_until_ready()
    out["bool_sync_s"] = timed(lambda: bool(flag))

    # async pipelining: N launches then one sync — if per-launch RPC is
    # pipelined this approaches one RTT, if serial it is N RTTs
    def launches(n):
        ys = [tiny(x + i) for i in range(n)]
        for y in ys:
            y.block_until_ready()

    out["launch20_pipelined_s"] = timed(lambda: launches(20), reps=5)

    print(json.dumps({k: v for k, v in out.items() if k != "sites"},
                     indent=1), flush=True)

    # --- 2. traced Q3/Q18 ---------------------------------------------------
    import trino_tpu.exec.local_executor as LE

    site_time = collections.Counter()
    site_calls = collections.Counter()
    site_bytes = collections.Counter()
    _orig_host = LE._host

    def host_traced(arrs):
        st = traceback.extract_stack(limit=7)
        site = " <- ".join(f"{f.name}:{f.lineno}" for f in st[-4:-1])
        t0 = time.perf_counter()
        got = _orig_host(arrs)
        site_time[site] += time.perf_counter() - t0
        site_calls[site] += 1
        site_bytes[site] += sum(a.nbytes for a in got if a is not None)
        return got

    LE._host = host_traced

    import jax._src.array as jarr

    _ob = jarr.ArrayImpl.__bool__

    def bool_traced(self):
        st = traceback.extract_stack(limit=7)
        site = "BOOL " + " <- ".join(f"{f.name}:{f.lineno}" for f in st[-4:-1])
        t0 = time.perf_counter()
        r = _ob(self)
        site_time[site] += time.perf_counter() - t0
        site_calls[site] += 1
        return r

    jarr.ArrayImpl.__bool__ = bool_traced

    from trino_tpu import Engine
    from trino_tpu.connectors.tpch import TpchConnector

    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=1, split_rows=1 << 21))
    s = e.create_session("tpch")
    queries = {
        "q3": """select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
            o_orderdate, o_shippriority from customer, orders, lineitem
            where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
            and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
            and l_shipdate > date '1995-03-15'
            group by l_orderkey, o_orderdate, o_shippriority
            order by revenue desc, o_orderdate limit 10""",
        "q18": """select c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
            sum(l_quantity) from customer, orders, lineitem
            where o_orderkey in (select l_orderkey from lineitem group by l_orderkey
                                 having sum(l_quantity) > 300)
            and c_custkey = o_custkey and o_orderkey = l_orderkey
            group by 1,2,3,4,5 order by o_totalprice desc, o_orderdate limit 100""",
    }
    out["queries"] = {}
    for name, sql in queries.items():
        t0 = time.perf_counter()
        e.execute_sql(sql, s)
        cold = time.perf_counter() - t0
        site_time.clear(); site_calls.clear(); site_bytes.clear()
        t0 = time.perf_counter()
        e.execute_sql(sql, s)
        warm = time.perf_counter() - t0
        traced = sum(site_time.values())
        sites = [
            {"site": k, "calls": site_calls[k],
             "s": round(site_time[k], 4), "bytes": site_bytes.get(k, 0)}
            for k, _ in site_time.most_common(12)]
        out["queries"][name] = {
            "cold_s": round(cold, 2), "warm_s": round(warm, 3),
            "traced_sync_s": round(traced, 3),
            "untraced_s": round(warm - traced, 3), "sites": sites}
        print(f"{name}: cold {cold:.1f}s warm {warm:.3f}s "
              f"traced-sync {traced:.3f}s untraced {warm - traced:.3f}s",
              flush=True)
        for rec in sites:
            print(f"   {rec['s']:8.4f}s {rec['calls']:3d}x "
                  f"{rec['bytes']:>10d}B  {rec['site']}", flush=True)


try:
    main()
except Exception as ex:  # always leave a record
    out["error"] = f"{type(ex).__name__}: {ex}"
    traceback.print_exc()
finally:
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "tpu_diag.json"), "w") as f:
        json.dump(out, f, indent=1)
    print("wrote scripts/tpu_diag.json", flush=True)
