"""SF100 north-star run on the CPU backend (round-4 verdict item 2).

Engine-only (no pandas baseline: a 600M-row lineitem frame is buildable in
125GB RAM but the point here is exercising the ENGINE's Grace/spill tier at
real size — BASELINE ladder step 3). Runs Q1/Q3/Q18/Q9 at BENCH_SF (default
100) one at a time and rewrites SF100_cpu_r05.json after EVERY query so a
partial run still leaves an artifact with failure analysis.

Run: nice -n 19 python scripts/sf100_run.py  (hours are expected on 1 core).
"""

import json
import os
import pathlib
import time
import traceback

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
_force = os.environ.pop("JAX_PLATFORMS", None)
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import sys  # noqa: E402

REPO = str(pathlib.Path(__file__).resolve().parents[1])
sys.path.insert(0, REPO)

from bench import QUERIES  # noqa: E402  (single source of query text)
from trino_tpu import Engine  # noqa: E402
from trino_tpu.connectors.tpch import TpchConnector  # noqa: E402

SF = float(os.environ.get("BENCH_SF", "100"))
# SF100_QUERIES=q18,q9 resumes a partial run without repeating finished ones
ORDER = [q.strip() for q in os.environ.get(
    "SF100_QUERIES", "q1,q3,q18,q9").split(",") if q.strip() in QUERIES]
OUT = os.path.join(REPO, f"SF100_cpu_r05.json")

out = {
    "sf": SF,
    "backend": "cpu-1core",
    "started_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
    "queries": {},
}


def _flush():
    with open(OUT, "w") as f:
        json.dump(out, f, indent=1)


_flush()
e = Engine()
e.register_catalog("tpch", TpchConnector(sf=SF))
for q in ORDER:
    rec = {"status": "running", "t0": time.strftime("%H:%M:%S")}
    out["queries"][q] = rec
    _flush()
    t0 = time.time()
    try:
        r = e.execute_sql(QUERIES[q])
        rows = r.rows()
        rec["status"] = "ok"
        rec["n_rows"] = len(rows)
        rec["first_row"] = repr(rows[0]) if rows else None
    except BaseException as exc:  # noqa: BLE001 — artifact must record failures
        rec["status"] = "failed"
        rec["error"] = f"{type(exc).__name__}: {exc}"
        rec["traceback"] = traceback.format_exc()[-4000:]
        if isinstance(exc, KeyboardInterrupt):
            rec["wall_seconds"] = round(time.time() - t0, 1)
            _flush()
            raise
    rec["wall_seconds"] = round(time.time() - t0, 1)
    _flush()
    print(json.dumps({q: rec})[:500], flush=True)
out["finished_at"] = time.strftime("%Y-%m-%dT%H:%M:%S%z")
_flush()
