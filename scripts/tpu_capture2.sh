#!/bin/bash
# Second round-5 capture: tunnel answered 2026-08-02T15:33Z.  The Aug-1
# capture predates the device-resident finalize (850a1b7) and device TopN
# (_topn_page_device) work, and scan-fused is already proven slower on
# device — so this run measures ONLY unfused SF1/SF10 plus the cluster
# probe, in priority order, inside the ~30-min tunnel-life window.
cd /root/repo
LOG=scripts/tpu_watch.log
exec 9> scripts/tpu_watch.lock
if ! flock -n 9; then
  echo "$(date -Is) capture2: another watcher holds the lock; exiting" >> "$LOG"
  exit 2
fi
echo "$(date -Is) capture2 start (tunnel known up)" >> "$LOG"
for cfg in "sf1_unfused:1:0:540:720" "sf10_unfused:10:0:1200:1500"; do
  IFS=: read -r name sf fused budget tmo <<< "$cfg"
  BENCH_BUDGET=$budget BENCH_SF=$sf TRINO_TPU_SCAN_FUSED=$fused \
    timeout -k 60 "$tmo" python bench.py \
    > "scripts/bench_${name}_c2.json" 2> "scripts/bench_${name}_c2.log"
  rc=$?
  echo "$(date -Is) capture2 $name rc=$rc : $(cat scripts/bench_${name}_c2.json)" >> "$LOG"
done
rm -f scripts/tpu_cluster_probe.json
timeout -k 30 700 python scripts/tpu_cluster_probe.py \
  > scripts/tpu_cluster_probe.out 2>&1
echo "$(date -Is) capture2 cluster probe rc=$?" >> "$LOG"
python - <<'PY'
import json, subprocess, time
out = {"captured_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
       "note": "second r05 capture: unfused-only, post device-finalize/device-TopN"}
try:
    out["device"] = subprocess.run(
        ["python", "-c", "import jax; print(jax.devices()[0])"],
        capture_output=True, text=True, timeout=180).stdout.strip()
except Exception as e:
    out["device"] = f"probe-error: {e}"
for name in ("sf1_unfused", "sf10_unfused"):
    try:
        out[name] = json.load(open(f"scripts/bench_{name}_c2.json"))
    except Exception as e:
        out[name] = {"error": str(e)}
try:
    out["cluster_tpu_probe"] = json.load(open("scripts/tpu_cluster_probe.json"))
except Exception as e:
    out["cluster_tpu_probe"] = {"error": str(e)}
prev = {}
try:
    prev = json.load(open("BENCH_local_r05.json"))
except Exception:
    pass
out["aug1_capture"] = {k: prev.get(k) for k in ("captured_at", "sf1_unfused", "sf1_fused")}
json.dump(out, open("BENCH_local_r05b.json", "w"), indent=1)
PY
echo "$(date -Is) capture2 wrote BENCH_local_r05b.json" >> "$LOG"
