#!/usr/bin/env python
"""Standalone chaos matrix: the tests/test_chaos.py scenarios as a capture
artifact.  Prints ONE JSON line — always, even on crash (finally block) —
with per-scenario outcomes and the leak-check verdicts, same contract as
bench.py, so scripts/tpu_watch.sh can capture a chaos pass on real hardware
at the next tunnel contact (the fault paths most worth proving on device are
exactly the ones the tunnel exercises for free: wedges, lost round-trips).

Env knobs:
    CHAOS_SF       TPC-H scale factor (default 0.1 — CPU-box friendly)
    CHAOS_QUERIES  comma-separated subset of q1,q3,q9,q18 (default q1,q3)
    CHAOS_BUDGET   wall-clock budget in seconds (default 600): remaining
                   scenarios are skipped, not overrun
    TRINO_TPU_PAGE_CACHE  honored as usual; defaulted to 1GB here so the
                   cache fault classes have a cache to fault
"""

import json
import os
import sys
import time

_force_cpu = os.environ.get("JAX_PLATFORMS") == "cpu"
if _force_cpu:
    os.environ.pop("JAX_PLATFORMS")
os.environ.setdefault("TRINO_TPU_PAGE_CACHE", str(1 << 30))

import jax  # noqa: E402

if _force_cpu:
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    t_start = time.time()
    budget = float(os.environ.get("CHAOS_BUDGET", "600"))
    sf = float(os.environ.get("CHAOS_SF", "0.1"))
    names = [q.strip() for q in
             os.environ.get("CHAOS_QUERIES", "q1,q3").split(",") if q.strip()]
    payload = {"metric": "chaos_pass_fraction", "value": 0.0,
               "unit": "fraction", "sf": sf, "scenarios": []}
    rc = 1
    try:
        from benchenv import env_info

        payload["env"] = env_info()
    except Exception:
        pass
    try:
        from trino_tpu import Engine
        from trino_tpu.connectors.tpch import TpchConnector
        from trino_tpu.execution import faults
        # the scenario table + signature/leak helpers are SHARED with
        # tests/test_chaos.py: one matrix, pinned by the suite, captured here
        from trino_tpu.execution.chaos_matrix import (QUERIES, SCENARIOS,
                                                      leak_report)
        from trino_tpu.execution.chaos_matrix import result_signature as _sig
        from trino_tpu.execution.faults import InjectedFaultError

        engine = Engine()
        # multi-split geometry at every scale: the generate/h2d classes fire
        # on the 2nd+ split and the prefetch producer only exists for
        # multi-split scans
        split_rows = 1 << 21 if sf >= 1 else 1 << 16
        engine.register_catalog("tpch",
                                TpchConnector(sf=sf, split_rows=split_rows))
        payload["split_rows"] = split_rows
        session = engine.create_session("tpch")
        nocache = engine.create_session("tpch")
        engine.session_properties.set_property(nocache, "page_cache", False)
        baselines = {}
        for q in names:
            engine.execute_sql(QUERIES[q], session)  # cold
            baselines[q] = _sig(engine.execute_sql(QUERIES[q], session))
        done = skipped = 0
        for q in names:
            for (name, spec, kind, clear_pool, cache_on) in SCENARIOS:
                if time.time() - t_start > budget:
                    skipped += 1
                    continue
                rec = {"query": q, "scenario": name, "kind": kind}
                try:
                    if clear_pool:
                        engine.buffer_pool.clear()
                    sess = session if cache_on else nocache
                    with faults.injected(spec) as plan:
                        if kind == "fail":
                            try:
                                engine.execute_sql(QUERIES[q], sess)
                                rec["ok"] = False
                                rec["detail"] = "no error raised"
                            except InjectedFaultError:
                                rec["ok"] = True
                        else:
                            got = _sig(engine.execute_sql(QUERIES[q], sess))
                            rec["ok"] = got == baselines[q]
                            if not rec["ok"]:
                                rec["detail"] = "result diverged"
                    rec["fires"] = plan.total_fires()
                    if rec["fires"] < 1:
                        rec["ok"] = False
                        rec["detail"] = "scenario never fired"
                    leftovers = leak_report(engine)
                    if leftovers:
                        rec["ok"] = False
                        rec["leaks"] = leftovers
                    if rec.get("ok"):
                        # clean-rerun probe: no partial state survived
                        again = _sig(engine.execute_sql(QUERIES[q], session))
                        if again != baselines[q]:
                            rec["ok"] = False
                            rec["detail"] = "post-fault rerun diverged"
                except Exception as e:  # scenario harness failure
                    rec["ok"] = False
                    rec["detail"] = f"{type(e).__name__}: {e}"
                payload["scenarios"].append(rec)
                done += 1
        # round 11: the memory-pressure matrix (tiered spill ladder) — same
        # shared table the test suite pins (chaos_matrix.PRESSURE), run
        # against the REAL q18 at this scale plus the distilled pressure
        # query, inside the same wall-clock budget
        import tempfile

        from trino_tpu.execution.chaos_matrix import (PRESSURE,
                                                      PRESSURE_QUERY,
                                                      run_pressure_scenario)
        from trino_tpu.exec.local_executor import LocalExecutor
        from trino_tpu.sql.frontend import compile_sql

        pressure_queries = {"pressure-agg": PRESSURE_QUERY}
        if "q18" in names:
            pressure_queries["q18"] = QUERIES["q18"]
        for qname, sql in pressure_queries.items():
            plan = compile_sql(sql, engine, session)
            base = _sig(LocalExecutor(engine.catalogs).execute(plan))
            for (name, cfg, spec, kind) in PRESSURE:
                if time.time() - t_start > budget:
                    skipped += 1
                    continue
                scratch = tempfile.mkdtemp(prefix="trino_tpu_chaos_spill_")
                rec = run_pressure_scenario(engine, plan, base, name, cfg,
                                            spec, kind, scratch)
                rec["query"] = qname
                payload["scenarios"].append(rec)
                done += 1
                import shutil

                shutil.rmtree(scratch, ignore_errors=True)
        # round 18: the distributed-exchange matrix — the mesh exchange's
        # fault points (exchange_write/exchange_read at the dist.* sites),
        # run on the worker mesh (virtual CPU workers locally, the real
        # mesh on device)
        from trino_tpu.execution.chaos_matrix import (DIST_QUERIES,
                                                      DIST_SCENARIOS,
                                                      run_dist_scenario)
        from trino_tpu.parallel.mesh import worker_mesh

        n_dev = jax.device_count()
        if n_dev < 2:
            payload["dist_skipped"] = f"single-device backend ({n_dev})"
        else:
            mesh = worker_mesh(min(n_dev, 8))
            dist_baselines = {k: _sig(engine.execute_sql(sql, session))
                              for k, sql in DIST_QUERIES.items()}
            for (name, qkey, spec, kind) in DIST_SCENARIOS:
                if time.time() - t_start > budget:
                    skipped += 1
                    continue
                rec = run_dist_scenario(engine, DIST_QUERIES[qkey], session,
                                        mesh, dist_baselines[qkey], name,
                                        spec, kind)
                rec["query"] = f"dist-{qkey}"
                payload["scenarios"].append(rec)
                done += 1
        # round 12: the result-cache matrix — needs its OWN result-enabled
        # engine (enabling the tier on the main engine would serve the warm
        # statements from cache and the dispatch/generate fault classes
        # above would never fire)
        from trino_tpu.execution.bufferpool import DeviceBufferPool
        from trino_tpu.execution.chaos_matrix import (RESULT_SCENARIOS,
                                                      run_result_scenario)

        if time.time() - t_start > budget:
            skipped += len(RESULT_SCENARIOS)
        else:
            reng = Engine()
            reng.buffer_pool = DeviceBufferPool(budget_bytes=1 << 30,
                                                result_budget_bytes=256 << 20)
            reng.register_catalog("tpch",
                                  TpchConnector(sf=sf, split_rows=split_rows))
            rsess = reng.create_session("tpch")
            rsql = QUERIES[names[0]]
            reng.execute_sql(rsql, rsess)  # cold
            rbase = _sig(reng.execute_sql(rsql, rsess))
            for (name, spec, kind) in RESULT_SCENARIOS:
                if time.time() - t_start > budget:
                    skipped += 1
                    continue
                rec = run_result_scenario(reng, rsql, rsess, rbase, name,
                                          spec, kind)
                rec["query"] = names[0]
                payload["scenarios"].append(rec)
                done += 1
        total = len(payload["scenarios"])
        passed = sum(1 for r in payload["scenarios"] if r.get("ok"))
        payload["value"] = (passed / total) if total else 0.0
        payload["passed"], payload["total"] = passed, total
        payload["skipped_over_budget"] = skipped
        rc = 0 if total and passed == total else 1
    except BaseException as e:
        payload["error"] = f"{type(e).__name__}: {e}"
        raise
    finally:
        payload["wall_s"] = round(time.time() - t_start, 1)
        print(json.dumps(payload), flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
