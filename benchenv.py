"""Shared provenance stamp for benchmark records (bench.py / bench_micro.py).

Round-5 found a 4x unexplained pandas-baseline drift between captures that
could not be attributed after the fact — host identity, core count, and
library versions make captures comparable (and incomparable ones visible).
Kept stdlib-only and jax-free so importing it never races the callers'
jax platform/x64 configuration dance.
"""

import os


def env_info() -> dict:
    import platform
    import socket

    info = {"hostname": socket.gethostname(),
            "cpu_count": os.cpu_count(),
            "platform": platform.platform(),
            "python": platform.python_version()}
    for mod in ("numpy", "pandas", "jax"):
        try:
            info[mod] = __import__(mod).__version__
        except Exception:
            info[mod] = None
    return info
