"""Kernel-level microbenchmarks (the JMH-suite analog).

Reference: core/trino-main/src/test/java/io/trino/operator/Benchmark*.java
(BenchmarkHashAndStreamingAggregationOperators, BenchmarkHashJoinOperators,
BenchmarkGroupByHash, ...) — per-operator throughput isolated from SQL.

Runs on whatever backend is available (CPU by default; the real TPU when
JAX_PLATFORMS is left at its axon default).  Prints one JSON line per kernel:
  {"kernel": ..., "rows": N, "ms": median_ms, "rows_per_sec": r}

Usage:  python bench_micro.py [--rows 4000000] [--kernels a,b,...]
"""

import argparse
import json
import os
import sys
import time

_force_cpu = os.environ.get("JAX_PLATFORMS") == "cpu"
if _force_cpu:
    os.environ.pop("JAX_PLATFORMS")

import jax

from benchenv import env_info

if _force_cpu:
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def _timeit(fn, *args, runs=5):
    out = fn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(runs):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2]


def bench_hashagg_insert(n):
    """Group-by insert: n rows into ~n/4 distinct int64 keys."""
    from trino_tpu.ops import hashagg
    from trino_tpu.types import BIGINT

    rng = np.random.default_rng(0)
    keys = jnp.asarray(rng.integers(0, n // 4, n))
    vals = jnp.asarray(rng.random(n))
    state = hashagg.groupby_init(n, (np.int64,), ((np.float64, 0.0),))

    @jax.jit
    def step(state, keys, vals):
        return hashagg.groupby_insert(
            state, (keys,), (BIGINT,), jnp.ones((n,), bool),
            [(vals, None)], ["sum"])

    return _timeit(step, state, keys, vals)


def bench_join_build(n):
    from trino_tpu.ops.hashjoin import build_insert, build_table_init
    from trino_tpu.page import Field, Page, Schema
    from trino_tpu.types import BIGINT

    key = jnp.asarray((np.arange(n, dtype=np.int64) * 7919) % (1 << 40))
    page = Page(Schema((Field("k", BIGINT),)), (key,), (None,), None)

    @jax.jit
    def build(key):
        jt = build_table_init(4 * n, page)
        return build_insert(jt, (key,), (BIGINT,), jnp.ones((n,), bool))

    return _timeit(build, key)


def bench_join_probe(n):
    from trino_tpu.ops.hashjoin import build_insert, build_table_init, probe
    from trino_tpu.page import Field, Page, Schema
    from trino_tpu.types import BIGINT

    nb = max(n // 8, 1)
    rng = np.random.default_rng(0)
    bkey = np.unique((np.arange(nb, dtype=np.int64) * 7919) % (1 << 40))
    page = Page(Schema((Field("k", BIGINT),)), (jnp.asarray(bkey),), (None,),
                None)
    jt = jax.jit(lambda k: build_insert(
        build_table_init(4 * len(bkey), page), (k,), (BIGINT,),
        jnp.ones((len(bkey),), bool)))(jnp.asarray(bkey))
    pkeys = jnp.asarray(rng.choice(bkey, n))

    @jax.jit
    def run(jt, pkeys):
        return probe(jt, (pkeys,), (BIGINT,), jnp.ones((n,), bool))

    return _timeit(run, jt, pkeys)


# ------------------------------------------------------- XLA-vs-Pallas A/B
# Round-13 kernels (ops/pallas_kernels.py) benchmarked against the XLA paths
# they shadow, with result equality asserted per the parity contract (probe/
# compact byte-identical; build/insert observable-identical — slot layouts
# are backend-private).  Each _ab kernel prints its own one-JSON-line payload
# with both throughputs.  On CPU the pallas half runs INTERPRETED (correctness
# signal only — the wall time is the interpreter's, not Mosaic's); the row
# counts are capped so that stays tractable.  On TPU both halves are compiled
# and the speedup column is the capture tpu_watch.sh archives.

_AB_ROWS_CAP = 1 << 13


def _ab_line(name, n, t_xla, t_pallas, extra=None):
    import jax as _jax
    rec = {"kernel": name, "rows": n,
           "xla_ms": round(t_xla * 1000, 3),
           "pallas_ms": round(t_pallas * 1000, 3),
           "xla_rows_per_sec": round(n / t_xla),
           "pallas_rows_per_sec": round(n / t_pallas),
           "pallas_speedup": round(t_xla / t_pallas, 3),
           "equal": True,
           "interpret": _jax.default_backend() != "tpu",
           "env": env_info()}
    rec.update(extra or {})
    print(json.dumps(rec), flush=True)


def _per_backend(fn_builder):
    """Build + run one timed closure per backend.  pallas_kernels.force is a
    TRACE-time switch, so each backend gets its own freshly-traced jit."""
    from trino_tpu.ops import pallas_kernels as pk

    out = {}
    for mode in (False, True):
        pk.force(mode)
        try:
            out[mode] = fn_builder()
        finally:
            pk.force(None)
    return out[False], out[True]


def bench_join_probe_ab(n):
    """hashjoin.probe: XLA while_loop gathers vs the Pallas inversion probe —
    byte-identical (row_ids, matched) over the SAME table."""
    import numpy as np

    from trino_tpu.ops.hashjoin import build_insert, build_table_init, probe
    from trino_tpu.page import Field, Page, Schema
    from trino_tpu.types import BIGINT

    n = min(n, _AB_ROWS_CAP)
    nb = max(n // 8, 1)
    rng = np.random.default_rng(0)
    bkey = np.unique((np.arange(nb, dtype=np.int64) * 7919) % (1 << 40))
    page = Page(Schema((Field("k", BIGINT),)), (jnp.asarray(bkey),), (None,),
                None)
    jt = jax.jit(lambda k: build_insert(
        build_table_init(4 * len(bkey), page), (k,), (BIGINT,),
        jnp.ones((len(bkey),), bool)))(jnp.asarray(bkey))
    pkeys = jnp.asarray(rng.choice(bkey, n))

    def build():
        # all-ones masks build INSIDE the trace: a closed-over device
        # constant degrades every dispatch on tunneled TPUs (CLAUDE.md,
        # ~70ms/call) and would tax exactly the capture this A/B exists for
        run = jax.jit(lambda jt, pkeys: probe(jt, (pkeys,), (BIGINT,),
                                              jnp.ones((n,), bool)))
        t = _timeit(run, jt, pkeys)
        return t, run(jt, pkeys)

    (t_x, (r_x, m_x)), (t_p, (r_p, m_p)) = _per_backend(build)
    assert np.array_equal(np.asarray(r_x), np.asarray(r_p))
    assert np.array_equal(np.asarray(m_x), np.asarray(m_p))
    _ab_line("join_probe_ab", n, t_x, t_p,
             {"capacity": int(jt.capacity), "hits": int(np.asarray(m_x).sum())})
    return None


def bench_join_build_ab(n):
    """hashjoin build insertion: XLA scatter-min claims vs the Pallas in-kernel
    claim loop — observable-identical (word sets, dup/overflow counters, probe
    results over either table)."""
    import numpy as np

    from trino_tpu.ops.hashjoin import build_insert, build_table_init, probe
    from trino_tpu.page import Field, Page, Schema
    from trino_tpu.types import BIGINT

    n = min(n, _AB_ROWS_CAP)
    key = jnp.asarray((np.arange(n, dtype=np.int64) * 7919) % (1 << 40))
    schema = Schema((Field("k", BIGINT),))

    def build():
        # the page is (re)built from the traced argument INSIDE the jit: a
        # closed-over device page would bake its columns in as constants and
        # tax every dispatch on tunneled TPUs (CLAUDE.md ~70ms/call) — the
        # capture this A/B feeds must time the kernel, not constant uploads
        run = jax.jit(lambda key: build_insert(
            build_table_init(4 * n, Page(schema, (key,), (None,), None)),
            (key,), (BIGINT,), jnp.ones((n,), bool)))
        t = _timeit(run, key)
        return t, run(key)

    (t_x, jt_x), (t_p, jt_p) = _per_backend(build)
    assert np.array_equal(np.sort(np.asarray(jt_x.table)),
                          np.sort(np.asarray(jt_p.table)))
    assert int(jt_x.dup_count) == int(jt_p.dup_count)
    assert bool(jt_x.overflow) == bool(jt_p.overflow)
    from trino_tpu.ops import pallas_kernels as pk
    pk.force(False)
    try:
        px = jax.jit(lambda jt, key: probe(jt, (key,), (BIGINT,),
                                           jnp.ones((n,), bool)))
        r1, m1 = px(jt_x, key)
        r2, m2 = px(jt_p, key)
    finally:
        pk.force(None)
    assert np.array_equal(np.asarray(r1), np.asarray(r2))
    assert np.array_equal(np.asarray(m1), np.asarray(m2))
    _ab_line("join_build_ab", n, t_x, t_p, {"capacity": int(jt_x.capacity)})
    return None


def bench_hashagg_insert_ab(n):
    """Group-by slot insertion: XLA rounds of gather + scatter-min vs the
    Pallas claim kernel — identical key -> accumulator maps."""
    import numpy as np

    from trino_tpu.ops import hashagg
    from trino_tpu.types import BIGINT

    n = min(n, _AB_ROWS_CAP)
    rng = np.random.default_rng(0)
    keys = jnp.asarray(rng.integers(0, n // 4, n))
    vals = jnp.asarray(rng.random(n))

    def build():
        def step_fn(state, keys, vals):
            # mask built in-trace: no closed-over device constants (CLAUDE.md)
            return hashagg.groupby_insert(state, (keys,), (BIGINT,),
                                          jnp.ones((n,), bool),
                                          [(vals, None)], ["sum"])
        run = jax.jit(step_fn)
        state = hashagg.groupby_init(n, (np.int64,), ((np.float64, 0.0),))
        t = _timeit(run, state, keys, vals)
        out = run(state, keys, vals)
        occ, (k,), (acc,) = hashagg.agg_finalize(out)
        occ = np.asarray(occ)
        return t, dict(zip(np.asarray(k)[occ].tolist(),
                           np.round(np.asarray(acc)[occ], 9).tolist()))

    (t_x, g_x), (t_p, g_p) = _per_backend(build)
    assert g_x == g_p
    _ab_line("hashagg_insert_ab", n, t_x, t_p, {"groups": len(g_x)})
    return None


def bench_compact_ab(n):
    """The pipeline-boundary masked-lane pack at 1/16 selectivity: XLA
    cumsum-scatter vs the Pallas prefix-sum + one-hot matmul — byte-identical."""
    import numpy as np

    from trino_tpu.ops.arrays import compact_rows

    n = min(n, 1 << 16)
    rng = np.random.default_rng(0)
    valid = jnp.asarray(rng.random(n) < 1 / 16)
    cols = (jnp.asarray(rng.integers(0, 1 << 40, n)),
            jnp.asarray(rng.random(n)),
            jnp.asarray(rng.random(n) < 0.5))
    bucket = n // 8

    def build():
        run = jax.jit(lambda cols, valid: compact_rows(cols, valid, bucket))
        t = _timeit(run, cols, valid)
        packed, total = run(cols, valid)
        return t, ([np.asarray(p) for p in packed], int(total))

    (t_x, (p_x, c_x)), (t_p, (p_p, c_p)) = _per_backend(build)
    assert c_x == c_p
    for a, b in zip(p_x, p_p):
        assert np.array_equal(a, b)
    _ab_line("compact_ab", n, t_x, t_p, {"bucket": bucket, "live": c_x})
    return None


def bench_exchange_route(n):
    """bucketize + all_to_all over an 8-worker mesh (or fewer devices)."""
    from functools import partial

    from jax.sharding import NamedSharding, PartitionSpec as PS

    # version-shimmed import (top-level jax.shard_map only exists on jax>=0.6)
    from trino_tpu.exec.distributed import shard_map
    from trino_tpu.ops.exchange import bucketize, exchange_all_to_all
    from trino_tpu.parallel.mesh import WORKER_AXIS, worker_mesh

    W = min(8, len(jax.devices()))
    if W < 2:
        return None
    mesh = worker_mesh(W)
    per = n // W
    rng = np.random.default_rng(0)
    cols = jnp.asarray(rng.integers(0, 1 << 40, (W, per)))
    sharded = NamedSharding(mesh, PS(WORKER_AXIS))
    cols = jax.device_put(cols, sharded)

    @partial(shard_map, mesh=mesh, in_specs=PS(WORKER_AXIS),
             out_specs=PS(WORKER_AXIS))
    def route(c):
        c = c[0]
        pid = (c % W).astype(jnp.int32)
        packed, pvalid, _ = bucketize((c,), jnp.ones_like(c, bool), pid, W,
                                      per)
        recv, rvalid = exchange_all_to_all(packed, pvalid, WORKER_AXIS, W)
        return recv[0][None], rvalid[None]

    return _timeit(jax.jit(route), cols)


def bench_exchange_append(n):
    """The round-18 device-resident exchange batch step: bucketize +
    all_to_all + append_rows into the carried [cap+1] receive buffer — the
    per-batch device cost that replaced a per-batch host materialize.  Pair
    with exchange_route to price the append itself."""
    from functools import partial

    from jax.sharding import NamedSharding, PartitionSpec as PS

    from trino_tpu.exec.distributed import shard_map
    from trino_tpu.ops.arrays import append_rows
    from trino_tpu.ops.exchange import bucketize, exchange_all_to_all
    from trino_tpu.parallel.mesh import WORKER_AXIS, worker_mesh

    W = min(8, len(jax.devices()))
    if W < 2:
        return None
    mesh = worker_mesh(W)
    per = n // W
    cap = 2 * per  # headroom for skewed receives, like the capacity ladder
    rng = np.random.default_rng(0)
    sharded = NamedSharding(mesh, PS(WORKER_AXIS))
    cols = jax.device_put(jnp.asarray(rng.integers(0, 1 << 40, (W, per))),
                          sharded)
    bufs = jax.device_put(jnp.zeros((W, cap + 1), cols.dtype), sharded)
    cursor = jax.device_put(jnp.zeros((W,), jnp.int64), sharded)

    @partial(shard_map, mesh=mesh,
             in_specs=(PS(WORKER_AXIS),) * 3,
             out_specs=(PS(WORKER_AXIS),) * 3)
    def step(c, bufs, cursor):
        c, bufs, cursor = c[0], bufs[0], cursor[0]
        pid = (c % W).astype(jnp.int32)
        packed, pvalid, _ = bucketize((c,), jnp.ones_like(c, bool), pid, W,
                                      per)
        recv, rvalid = exchange_all_to_all(packed, pvalid, WORKER_AXIS, W)
        nb, ncur, of = append_rows((bufs,), cursor,
                                   (recv[0].reshape(-1),), rvalid.reshape(-1))
        return nb[0][None], ncur[None], of[None]

    return _timeit(jax.jit(step), cols, bufs, cursor)


def bench_sort(n):
    rng = np.random.default_rng(0)
    keys = jnp.asarray(rng.integers(0, 1 << 40, n))
    return _timeit(jax.jit(jnp.sort), keys)


def bench_window_scan(n):
    """Segmented prefix sums over ~n/64 partitions (the window-frame core)."""
    from trino_tpu.ops import window as W

    rng = np.random.default_rng(0)
    part = np.sort(rng.integers(0, n // 64, n))
    starts = jnp.asarray(np.concatenate([[True], part[1:] != part[:-1]]))
    vals = jnp.asarray(rng.random(n))

    @jax.jit
    def run(vals, starts):
        return W.segmented_scan_sum(vals, starts, starts)

    return _timeit(run, vals, starts)


def bench_compact(n):
    """The pipeline-boundary scatter-pack at 1/16 selectivity."""
    rng = np.random.default_rng(0)
    valid = jnp.asarray(rng.random(n) < 1 / 16)
    col = jnp.asarray(rng.integers(0, 1 << 40, n))
    bucket = n // 8

    @jax.jit
    def run(col, valid):
        pos = jnp.cumsum(valid) - 1
        dst = jnp.where(valid & (pos < bucket), pos, bucket).astype(jnp.int32)
        return jnp.zeros((bucket + 1,), col.dtype).at[dst].set(col)[:bucket]

    return _timeit(run, col, valid)


def bench_exchange_stream_vs_spool(n):
    """Inter-process exchange latency: one fragment-output envelope handed
    producer->consumer through the STREAMING buffer endpoint (in-memory,
    long-poll + token ack) vs the spooled filesystem exchange.  Prints its own
    line with both numbers; returns None (not a rows/sec kernel)."""
    import tempfile

    from trino_tpu.exec.fte import (SpoolingExchange,
                                    deserialize_fragment_output,
                                    serialize_fragment_output)
    from trino_tpu.server.cluster import _OutputBuffer

    rng = np.random.default_rng(0)
    nrows = min(n, 1 << 20)
    cols = [rng.integers(0, 1 << 40, nrows), rng.random(nrows)]
    env = serialize_fragment_output(cols, [None, None], (None, None))

    def via_spool():
        with tempfile.TemporaryDirectory() as d:
            ex = SpoolingExchange(d)
            ex.commit("t0", 0, env)
            return deserialize_fragment_output(ex.read("t0"))

    def via_stream():
        buf = _OutputBuffer()
        buf.add(env)
        buf.finish()
        out, _, _ = buf.get(0, max_wait=0.1)
        assert buf.get(1, max_wait=0.01)[1]  # ack + complete
        return deserialize_fragment_output(out)

    def med(fn, runs=7):
        fn()
        ts = []
        for _ in range(runs):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        return sorted(ts)[len(ts) // 2]

    t_spool, t_stream = med(via_spool), med(via_stream)
    print(json.dumps({
        "kernel": "exchange_stream_vs_spool", "rows": nrows,
        "spool_ms": round(t_spool * 1000, 3),
        "stream_ms": round(t_stream * 1000, 3),
        "stream_speedup": round(t_spool / t_stream, 2),
        "env": env_info(),
    }), flush=True)
    return None


def bench_dispatch_coalesce(nrows):
    """Dispatch-coalescing overhead curve: a fixed-size grouped aggregation
    over 16 uniform splits, executed at batch K in {1,2,4,8,16} — the
    per-dispatch overhead is (warm wall at K=1 - warm wall at K=16)/Δdispatch.
    On the CPU mesh the deltas are python+dispatch overhead (~ms); on a
    tunneled TPU each saved dispatch is a full round-trip, which is the curve
    this benchmark exists to capture on the next tunnel window."""
    from trino_tpu import Engine
    from trino_tpu.connectors.tpch import TpchConnector

    n_splits = 16
    sf = max(nrows / 1_500_000, 16 / 1_500_000)  # orders rows = 1.5M * sf
    engine = Engine()
    engine.register_catalog(
        "tpch", TpchConnector(sf=sf, split_rows=max(nrows // n_splits, 1)))
    sql = ("select o_orderstatus, count(*) c, sum(o_totalprice) s "
           "from orders group by o_orderstatus order by o_orderstatus")
    curve = []
    for k in (1, 2, 4, 8, 16):
        s = engine.create_session("tpch")
        engine.session_properties.set_property(s, "dispatch_batch", k)
        engine.execute_sql(sql, s)  # cold: plan + XLA compile
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            engine.execute_sql(sql, s)
            ts.append(time.perf_counter() - t0)
        c = engine.last_query_counters
        curve.append({"batch": k, "warm_ms": round(sorted(ts)[1] * 1000, 3),
                      **c.as_dict()})
    print(json.dumps({"kernel": "dispatch_coalesce", "rows": nrows,
                      "splits": n_splits, "curve": curve, "env": env_info()}),
          flush=True)
    return None


def bench_h2d_transfer(nrows):
    """Host->device staging bandwidth curve over page sizes — the transfer
    the device buffer pool's page tier saves on every warm scan.  For each
    page size: median wall of jax.device_put(numpy int64 column) +
    block_until_ready, reported as bytes/s.  On the CPU backend this is a
    memcpy (upper bound); on a tunneled TPU it is the real H2D bill, and
    (bytes_saved from bench.py per_query) / (bytes/s here) estimates the
    wall-clock the cache bought — capture both on the next tunnel window."""
    import jax

    import numpy as np

    curve = []
    size = 1 << 16
    while size <= max(nrows, 1 << 16):
        arr = np.arange(size, dtype=np.int64)
        def put(arr=arr):
            jax.device_put(arr).block_until_ready()
        put()  # warm: allocator + executable paths
        ts = []
        for _ in range(7):
            t0 = time.perf_counter()
            put()
            ts.append(time.perf_counter() - t0)
        med = sorted(ts)[len(ts) // 2]
        curve.append({"rows": size, "bytes": size * 8,
                      "ms": round(med * 1000, 4),
                      "bytes_per_sec": round(size * 8 / med)})
        size <<= 2
    print(json.dumps({"kernel": "h2d_transfer", "rows": nrows,
                      "curve": curve, "env": env_info()}), flush=True)
    return None


KERNELS = {
    "hashagg_insert": bench_hashagg_insert,
    "join_build": bench_join_build,
    "join_probe": bench_join_probe,
    "exchange_route": bench_exchange_route,
    "exchange_append": bench_exchange_append,
    "sort": bench_sort,
    "window_scan": bench_window_scan,
    "compact": bench_compact,
    "exchange_stream_vs_spool": bench_exchange_stream_vs_spool,
    "dispatch_coalesce": bench_dispatch_coalesce,
    "h2d_transfer": bench_h2d_transfer,
    # round-13 XLA-vs-Pallas A/B variants (result equality asserted)
    "join_probe_ab": bench_join_probe_ab,
    "join_build_ab": bench_join_build_ab,
    "hashagg_insert_ab": bench_hashagg_insert_ab,
    "compact_ab": bench_compact_ab,
}


def _filter_stderr():
    """XLA:CPU's AOT cache floods fd 2 with 'cpu_aot_loader' warnings
    (CLAUDE.md: harmless).  They come from C++ logging, so a python-level
    sys.stderr wrapper never sees them — pump the real fd through a filter
    thread so captured A/B output (tpu_watch.sh redirects 2> to a .log)
    stays readable.  An atexit hook restores fd 2 and JOINS the pump: a
    daemon thread alone dies at interpreter exit before forwarding whatever
    is still in the pipe — which is exactly where a crashing run's traceback
    sits, and an empty .log from the one-shot tunnel capture window is an
    undiagnosable failure."""
    import atexit
    import threading

    r, w = os.pipe()
    orig = os.dup(2)
    os.dup2(w, 2)
    os.close(w)

    def pump():
        buf = b""
        while True:
            try:
                chunk = os.read(r, 65536)
            except OSError:
                break
            if not chunk:
                break
            buf += chunk
            *lines, buf = buf.split(b"\n")
            for ln in lines:
                if b"cpu_aot_loader" not in ln:
                    os.write(orig, ln + b"\n")
        if buf and b"cpu_aot_loader" not in buf:
            os.write(orig, buf + b"\n")

    t = threading.Thread(target=pump, daemon=True, name="stderr-filter")
    t.start()

    def restore():
        try:
            sys.stderr.flush()
        except Exception:
            pass
        # putting orig back on fd 2 closes the pipe's only write end: the
        # pump sees EOF, forwards the tail (e.g. an uncaught traceback
        # printed during shutdown) to the real stderr, and exits
        os.dup2(orig, 2)
        t.join(timeout=10)

    atexit.register(restore)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=4_000_000)
    ap.add_argument("--kernels", type=str, default=",".join(KERNELS),
                    help="comma list from KERNELS; *_ab variants run the "
                         "XLA-vs-Pallas comparison (row counts capped; "
                         "interpret mode off-TPU)")
    args = ap.parse_args()
    _filter_stderr()
    env = env_info()
    for name in args.kernels.split(","):
        fn = KERNELS.get(name.strip())
        if fn is None:
            continue
        try:
            t = fn(args.rows)
        except Exception as e:  # one kernel must not kill the suite
            print(json.dumps({"kernel": name, "error": f"{type(e).__name__}: {e}",
                              "env": env}),
                  flush=True)
            continue
        if t is None:
            continue
        print(json.dumps({
            "kernel": name, "rows": args.rows, "ms": round(t * 1000, 3),
            "rows_per_sec": round(args.rows / t), "env": env,
        }), flush=True)


if __name__ == "__main__":
    main()
