"""Serving benchmark: the engine under CONCURRENT statement load.

The north star is "heavy traffic from millions of users" — many concurrent
small/medium statements against the coordinator HTTP protocol, not one big
scan — and this is the harness that measures it (ROADMAP item 4;
"Accelerating Presto with GPUs", arxiv 2606.24647: accelerator engines win
or lose on concurrent utilization, not single-query wall).

Two load modes against a live CoordinatorServer (the /v1/statement
protocol, nextUri paging, real HTTP):

- **closed loop** — SERVE_CLIENTS threads, each issuing its next statement
  the moment the previous one completes (throughput under a fixed
  concurrency; the classic dashboard-fleet shape);
- **open loop** — a Poisson-free fixed-rate arrival schedule at SERVE_QPS,
  each request timed from its SCHEDULED arrival (so queueing delay counts,
  the latency a user actually sees when the engine falls behind).

The mixed workload has five classes (warm TPC-H + point lookups with
per-request DISTINCT constants + protocol-parameterized EXECUTE + short
aggregations + one repeated dashboard statement).  The point/param classes
share one statement shape (_POINT_SQL, a customer point lookup): ``point``
inlines a fresh constant per request (stride 97 over the customer keys —
exercises AUTO-parameterization) and ``param`` binds one per request via
protocol parameters (stride 61) — every request a distinct binding,
identical up to constants, which is exactly the shape plan templates (and
the round-21 template batcher) serve.  The matrix runs THREE times — plan
templates OFF (substitution baseline), templates ON with result cache OFF
(isolates the round-13 template win), then result cache ON — so the JSON
line prices exactly what each tier buys:
per-class p50/p99, achieved qps, buffer-pool/result-cache hit rates,
admission/resource-group queueing, and (SERVE_WORKERS > 0) worker
fair-scheduler preemption counts.  The cache-on half also verifies the
acceptance contract in-process: the repeated statement's warm hit must show
``device_dispatches == 0`` on its counters and byte-identical results vs
the cache-off engine.

After the three-phase matrix, a round-21 template-batch A/B runs the
point+param classes OPEN-LOOP at SERVE_BATCH_QPS (well above
single-statement throughput, so the gather window actually fills) with the
template batcher OFF then ON — latency still measured from SCHEDULED
arrival, so gather-window queueing counts against p50/p99 — and the
payload carries the per-class and total open-loop qps speedups, the
``batched_requests`` counter delta, and batched-vs-serial byte identity.
The A/B drives the ENGINE in-process (``open_loop_inproc``), not the HTTP
protocol: on a small box the polling HTTP harness saturates near ~55 qps
with ZERO dispatches (the cache-on phase measures exactly that ceiling),
which would mask the fused path entirely — and both halves differ only in
the batcher flag, so the protocol layer cancels out of the ratio anyway.

Prints ONE JSON line — always, even on timeout/failure (finally block;
SIGTERM/SIGALRM raise through it) — env-stamped, same contract as bench.py.

Env knobs:
    SERVE_SF            TPC-H scale factor (default 0.1)
    SERVE_DURATION      seconds per load phase (default 20)
    SERVE_CLIENTS       closed-loop concurrency (default 4)
    SERVE_QPS           open-loop arrival rate (default 8; 0 skips open loop)
    SERVE_BATCH_QPS     in-process open-loop arrival rate for the
                        template-batch A/B phases (default 256; 0 skips
                        them — pick it well above the serial engine's
                        point-lookup throughput or neither half saturates)
    SERVE_BATCH_MAX     window cap for the A/B's ON half (default 16 —
                        deeper windows LOSE on CPU where the vmapped
                        program pays real per-lane compute; raise it on a
                        device where a dispatch is a round-trip)
    SERVE_BATCH_WINDOW_MS  gather-window for the ON half (default 0 =
                        pure continuous batching: fuse whatever queued
                        behind the running window, no artificial delay —
                        measured fastest on CPU; the engine-wide
                        TRINO_TPU_BATCH_WINDOW_MS default stays 2)
    SERVE_BUDGET        global wall-clock budget seconds (default 900)
    SERVE_RESULT_CACHE  result-tier bytes for the ON half (default 256MB)
    SERVE_PAGE_CACHE    page-tier bytes for BOTH halves (default 1GB)
    SERVE_CLASSES       comma list restricting the schedule to named classes
                        (e.g. "point,param" isolates the template A/B from
                        cross-class contention; default: all)
    SERVE_WORKERS       in-process cluster workers (default 0 = single node;
                        >0 routes statements through a ClusterCoordinator so
                        worker fair-scheduler preemption becomes measurable)
"""

import json
import os
import signal
import sys
import threading
import time

# same guard as bench.py: JAX_PLATFORMS=cpu as an ENV VAR hangs the axon
# plugin's discovery; pop it and select cpu via jax.config
_force_cpu = os.environ.get("JAX_PLATFORMS") == "cpu"
if _force_cpu:
    os.environ.pop("JAX_PLATFORMS")

import jax

if _force_cpu:
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

SF = float(os.environ.get("SERVE_SF", "0.1"))
DURATION = float(os.environ.get("SERVE_DURATION", "20"))
CLIENTS = int(os.environ.get("SERVE_CLIENTS", "4"))
QPS = float(os.environ.get("SERVE_QPS", "8"))
BATCH_QPS = float(os.environ.get("SERVE_BATCH_QPS", "256"))
BATCH_MAX = int(os.environ.get("SERVE_BATCH_MAX", "16"))
BATCH_WINDOW_MS = float(os.environ.get("SERVE_BATCH_WINDOW_MS", "0"))
BUDGET = float(os.environ.get("SERVE_BUDGET", "900"))
RESULT_CACHE = int(os.environ.get("SERVE_RESULT_CACHE", str(256 << 20)))
PAGE_CACHE = int(os.environ.get("SERVE_PAGE_CACHE", str(1 << 30)))
WORKERS = int(os.environ.get("SERVE_WORKERS", "0"))
# optional class filter ("point,param"): isolates one workload class for the
# template A/B — under the mixed cycle on a small box, per-class latency is
# dominated by cross-class contention, not the path under measurement
CLASSES = [c.strip() for c in os.environ.get("SERVE_CLASSES", "").split(",")
           if c.strip()]

# TPC-H q1/q3 inlined (importing bench.py re-points the process-wide XLA
# compile cache — the same reason test_query_budgets inlines them)
_Q1 = """
select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
       sum(l_extendedprice) as sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
       avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price,
       avg(l_discount) as avg_disc, count(*) as count_order
from lineitem where l_shipdate <= date '1998-12-01' - interval '90' day
group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus"""
_Q3 = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate limit 10"""


_POINT_SQL = "select c_name, c_acctbal, c_mktsegment from customer " \
             "where c_custkey = "
_CUSTOMERS = max(int(150000 * SF) - 1, 100)


def workload():
    """-> (classes: {name: [gen...]}, schedule: [(class, gen)...]) where each
    ``gen(i) -> (sql, params|None)`` produces the i-th request.  The schedule
    is a deterministic weighted cycle — repeat-heavy (the dashboard shape the
    result cache exists for), with per-request DISTINCT constants on the
    point/param classes (the millions-of-users shape plan templates exist
    for: every request is a fresh SQL text, identical up to constants).

    - ``point``: ad-hoc SELECT with an inline per-request constant —
      exercises AUTO-parameterization (template hit without client opt-in);
    - ``param``: the same statement with a ``?`` marker and the constant
      bound via protocol parameters (X-Trino-Execute-Parameters)."""

    def fixed(sql):
        return lambda i, sql=sql: (sql, None)

    def point(i):
        return (_POINT_SQL + str(1 + (i * 97) % _CUSTOMERS), None)

    def param(i):
        return (_POINT_SQL + "?", [1 + (i * 61) % _CUSTOMERS])

    classes = {
        # THE repeated statement: identical text every time — result-tier bait
        "repeat": [fixed(_Q3)],
        "point": [point],
        "param": [param],
        "agg": [
            fixed("select l_returnflag, count(*) c, sum(l_quantity) q "
                  "from lineitem group by l_returnflag order by l_returnflag"),
            fixed("select o_orderpriority, count(*) c from orders "
                  "group by o_orderpriority order by o_orderpriority"),
        ],
        "tpch": [fixed(_Q1)],
    }
    schedule = []
    # 12-slot cycle: 4x repeat, 3x point, 2x param, 2x agg, 1x tpch
    weights = (("repeat", 4), ("point", 3), ("param", 2), ("agg", 2),
               ("tpch", 1))
    idx = {c: 0 for c in classes}
    for name, w in weights:
        if CLASSES and name not in CLASSES:
            continue
        for _ in range(w):
            gens = classes[name]
            schedule.append((name, gens[idx[name] % len(gens)]))
            idx[name] += 1
    return classes, schedule


def _quantile(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[i]


def _class_stats(samples):
    """samples: {class: [latency_s...]} -> per-class p50/p99/mean/count."""
    out = {}
    for cls, vals in sorted(samples.items()):
        v = sorted(vals)
        out[cls] = {
            "count": len(v),
            "p50_ms": None if not v else round(_quantile(v, 0.50) * 1e3, 2),
            "p99_ms": None if not v else round(_quantile(v, 0.99) * 1e3, 2),
            "mean_ms": None if not v else round(sum(v) / len(v) * 1e3, 2),
        }
    return out


class _Sampler(threading.Thread):
    """Polls the engine's admission surfaces during a load phase: peak
    resource-group queue depth / running count and peak in-flight registry
    depth — the queueing behavior the payload reports."""

    def __init__(self, engine, interval=0.05):
        super().__init__(daemon=True, name="serve-sampler")
        self.engine = engine
        self.interval = interval
        self.max_queued = 0
        self.max_running = 0
        self.max_inflight = 0
        # NOT named _stop: threading.Thread has a private _stop METHOD that
        # join() calls — shadowing it with an Event breaks join()
        self._halt = threading.Event()

    def run(self):
        while not self._halt.is_set():
            try:
                for g in self.engine.resource_groups.info():
                    self.max_queued = max(self.max_queued, g["queued"])
                    self.max_running = max(self.max_running, g["running"])
                self.max_inflight = max(self.max_inflight,
                                        self.engine.inflight.depth())
            except Exception:
                pass
            self._halt.wait(self.interval)

    def stop(self):
        self._halt.set()
        self.join(timeout=2)
        return {"max_group_queued": self.max_queued,
                "max_group_running": self.max_running,
                "max_inflight": self.max_inflight}


_COUNTER_KEYS = ("device_dispatches", "host_transfers", "host_bytes_pulled",
                 "result_cache_hits", "result_cache_misses",
                 "result_cache_bytes_saved", "page_cache_hits",
                 "page_cache_misses", "admission_queued", "task_retries",
                 "plan_template_hits", "plan_template_misses",
                 "batched_requests")


def _counters_snapshot(engine):
    d = engine.counters_total.as_dict()
    return {k: d.get(k, 0) for k in _COUNTER_KEYS}


def _counters_delta(before, after):
    return {k: after[k] - before[k] for k in _COUNTER_KEYS}


def closed_loop(url, schedule, duration, clients, deadline):
    """Fixed-concurrency load: each client issues its next statement as soon
    as the previous completes; returns (per-class latencies, errors, wall)."""
    from trino_tpu.server.client import Client

    samples = {cls: [] for cls, _ in schedule}
    errors = [0]
    lock = threading.Lock()
    stop_at = min(time.monotonic() + duration, deadline)

    def run(offset):
        client = Client(url, catalog="tpch", poll_interval=0.002)
        i = offset  # stagger clients through the cycle so classes interleave
        while time.monotonic() < stop_at:
            cls, gen = schedule[i % len(schedule)]
            sql, params = gen(i)
            i += 1
            t0 = time.perf_counter()
            try:
                client.execute(sql, timeout=120, params=params)
            except Exception:
                with lock:
                    errors[0] += 1
                continue
            dt = time.perf_counter() - t0
            with lock:
                samples[cls].append(dt)

    t_start = time.monotonic()
    threads = [threading.Thread(target=run, args=(k * 3,), daemon=True)
               for k in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t_start
    total = sum(len(v) for v in samples.values())
    return {"wall_s": round(wall, 2),
            "total": {"count": total, "errors": errors[0],
                      "qps": round(total / wall, 2) if wall else None},
            "classes": _class_stats(samples)}


def open_loop(url, schedule, duration, qps, deadline):
    """Fixed-rate arrivals: latency counts from the SCHEDULED arrival time,
    so a backed-up engine shows its queueing delay instead of hiding it
    (the coordinated-omission correction)."""
    from concurrent.futures import ThreadPoolExecutor

    from trino_tpu.server.client import Client

    samples = {cls: [] for cls, _ in schedule}
    errors = [0]
    lock = threading.Lock()
    n = max(int(min(duration, max(deadline - time.monotonic(), 0)) * qps), 1)
    t0 = time.monotonic()

    def fire(i, cls, sql, params, scheduled):
        client = Client(url, catalog="tpch", poll_interval=0.002)
        try:
            client.execute(sql, timeout=120, params=params)
        except Exception:
            with lock:
                errors[0] += 1
            return
        dt = time.monotonic() - scheduled
        with lock:
            samples[cls].append(dt)

    with ThreadPoolExecutor(max_workers=32,
                            thread_name_prefix="serve-open") as pool:
        futures = []
        for i in range(n):
            scheduled = t0 + i / qps
            delay = scheduled - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            if time.monotonic() > deadline:
                break
            cls, gen = schedule[i % len(schedule)]
            sql, params = gen(i)
            futures.append(pool.submit(fire, i, cls, sql, params, scheduled))
        for f in futures:
            f.result()
    wall = time.monotonic() - t0
    total = sum(len(v) for v in samples.values())
    return {"wall_s": round(wall, 2), "target_qps": qps,
            "total": {"count": total, "errors": errors[0],
                      "achieved_qps": round(total / wall, 2) if wall else None},
            "classes": _class_stats(samples)}


def open_loop_inproc(engine, schedule, duration, qps, deadline):
    """open_loop minus the HTTP harness: fixed-rate arrivals fired straight
    at ``engine.execute_sql`` with protocol parameters, latency from the
    SCHEDULED arrival.  The template-batch A/B uses this so the measured
    ratio is the fused serving path, not the polling client's ceiling."""
    from concurrent.futures import ThreadPoolExecutor

    samples = {cls: [] for cls, _ in schedule}
    errors = [0]
    lock = threading.Lock()
    n = max(int(min(duration, max(deadline - time.monotonic(), 0)) * qps), 1)
    t0 = time.monotonic()

    def fire(i, cls, sql, params, scheduled):
        sess = engine.create_session("tpch")
        try:
            engine.execute_sql(sql, sess, parameters=params)
        except Exception:
            with lock:
                errors[0] += 1
            return
        dt = time.monotonic() - scheduled
        with lock:
            samples[cls].append(dt)

    with ThreadPoolExecutor(max_workers=32,
                            thread_name_prefix="serve-inproc") as pool:
        futures = []
        for i in range(n):
            scheduled = t0 + i / qps
            delay = scheduled - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            if time.monotonic() > deadline:
                break
            cls, gen = schedule[i % len(schedule)]
            sql, params = gen(i)
            futures.append(pool.submit(fire, i, cls, sql, params, scheduled))
        for f in futures:
            f.result()
    wall = time.monotonic() - t0
    total = sum(len(v) for v in samples.values())
    return {"wall_s": round(wall, 2), "target_qps": qps,
            "total": {"count": total, "errors": errors[0],
                      "achieved_qps": round(total / wall, 2) if wall else None},
            "classes": _class_stats(samples)}


def build_node(conn, result_cache_bytes, spool_root, templates=True):
    """One engine + coordinator server (+ optional in-process cluster).
    Returns (engine, server, cluster_parts | None).  ``templates=False``
    disables the plan-template path (the substitution-baseline half of the
    round-13 A/B)."""
    from trino_tpu import Engine
    from trino_tpu.execution.bufferpool import DeviceBufferPool
    from trino_tpu.server.server import CoordinatorServer

    engine = Engine()
    # explicit pool budgets (never via env: three phases in one process)
    engine.buffer_pool = DeviceBufferPool(
        budget_bytes=PAGE_CACHE, result_budget_bytes=result_cache_bytes)
    engine.plan_templates_enabled = templates
    engine.register_catalog("tpch", conn)
    cluster = None
    facade = engine
    if WORKERS > 0:
        from trino_tpu.server.cluster import ClusterCoordinator, WorkerServer

        coord = ClusterCoordinator(engine, spool_root)
        coord_url = coord.start()
        workers = []
        for i in range(WORKERS):
            w = WorkerServer({"tpch": {"connector": "tpch", "sf": SF}},
                             spool_root, coordinator_url=coord_url,
                             node_id=f"serve-w{i}")
            w.start()
            workers.append(w)
        coord.wait_for_workers(WORKERS)
        cluster = {"coordinator": coord, "workers": workers}

        class _ClusterFacade:
            """Statement routing through the cluster coordinator; every
            other engine surface (metrics, sessions, pools) passes through."""

            def __init__(self, coordinator, eng):
                self._coord = coordinator
                self._engine = eng

            def execute_sql(self, sql, session=None, parameters=None, **_kw):
                if parameters is not None:
                    # parameterized statements run on the coordinator's own
                    # engine (the template path is local; the cluster task
                    # protocol does not ship bindings)
                    return self._engine.execute_sql(sql, session,
                                                    parameters=parameters)
                return self._coord.execute_sql(sql, session)

            def __getattr__(self, name):
                return getattr(self._engine, name)

        facade = _ClusterFacade(coord, engine)
    server = CoordinatorServer(facade, port=0,
                               dispatch_threads=max(8, CLIENTS + 2))
    server.start()
    return engine, server, cluster


def run_phase(engine, server, schedule, deadline):
    """Warmup + closed loop + open loop + counter/admission deltas."""
    from trino_tpu.server.client import Client

    client = Client(server.url, catalog="tpch", poll_interval=0.002)
    seen = set()
    for _cls, gen in schedule:  # warmup: one pass compiles + populates
        sql, params = gen(0)
        k = (sql, None if params is None else tuple(params))
        if k not in seen:
            seen.add(k)
            client.execute(sql, timeout=600, params=params)
    before = _counters_snapshot(engine)
    sampler = _Sampler(engine)
    sampler.start()
    closed = closed_loop(server.url, schedule, DURATION, CLIENTS, deadline)
    open_ = None
    if QPS > 0 and time.monotonic() < deadline:
        open_ = open_loop(server.url, schedule, DURATION, QPS, deadline)
    admission = sampler.stop()
    bp = engine.buffer_pool.info()
    bp.pop("per_table", None)
    return {"closed": closed, "open": open_,
            "counters": _counters_delta(before, _counters_snapshot(engine)),
            "admission": admission, "buffer_pool": bp}


def main():
    # two Engines live in this process (the off/on halves) — an armed
    # TRINO_TPU_STALL_S (tpu_watch exports it for bench.py) would start TWO
    # watchdogs over the shared process-global in-flight registry and
    # cross-report (CLAUDE.md round-8: one armed Engine per process)
    os.environ.pop("TRINO_TPU_STALL_S", None)
    deadline = time.monotonic() + BUDGET

    def _bail(signum, frame):
        raise SystemExit(f"signal {signum}")

    signal.signal(signal.SIGTERM, _bail)
    signal.signal(signal.SIGALRM, _bail)
    signal.alarm(int(BUDGET + 60))

    payload = {"metric": f"serve_sf{SF:g}_bench_failed", "value": 0,
               "unit": "qps", "vs_baseline": 0}
    servers = []
    try:
        from trino_tpu.connectors.tpch import TpchConnector
        from trino_tpu.execution.chaos_matrix import result_signature as _sig

        conn = TpchConnector(sf=SF, split_rows=1 << 16)
        classes, schedule = workload()
        import tempfile

        spool_root = tempfile.mkdtemp(prefix="trino_tpu_serve_")
        phases = {}
        engines = {}
        # three phases: templates_off (result cache off, plan templates off —
        # the substitution baseline), cache_off (templates on, result cache
        # off — isolates the round-13 template win), cache_on (everything)
        matrix = (("templates_off", 0, False), ("cache_off", 0, True),
                  ("cache_on", RESULT_CACHE, True))
        for label, budget, templates in matrix:
            if time.monotonic() > deadline - 10:
                print(f"bench_serve: budget exhausted before {label}",
                      file=sys.stderr)
                break
            engine, server, cluster = build_node(conn, budget, spool_root,
                                                 templates=templates)
            servers.append(server)
            engines[label] = engine
            phases[label] = run_phase(engine, server, schedule, deadline)
            if cluster is not None:
                phases[label]["scheduler"] = {
                    "preemptions": sum(w.scheduler.preemptions
                                       for w in cluster["workers"]),
                    "workers": WORKERS}
            print(f"bench_serve: {label} done "
                  f"({phases[label]['closed']['total']})", file=sys.stderr)
        # -- round-21 template-batch A/B: point+param open-loop at a rate ---
        # well above single-statement throughput, batcher off vs on.  A
        # fresh engine pair (templates on, result cache off) so the only
        # difference is the fused path; latency still counts from SCHEDULED
        # arrival, so the gather window's queueing is in the percentiles.
        batch_sched = [("point", classes["point"][0]),
                       ("param", classes["param"][0])]
        batch_engines = {}
        for label, batching in (("batch_off", False), ("batch_on", True)):
            if BATCH_QPS <= 0 or time.monotonic() > deadline - 10:
                if BATCH_QPS > 0:
                    print(f"bench_serve: budget exhausted before {label}",
                          file=sys.stderr)
                break
            engine, server, _cluster = build_node(conn, 0, spool_root,
                                                  templates=True)
            servers.append(server)
            engine.template_batcher.enabled = batching
            engine.template_batcher.max_batch = BATCH_MAX
            engine.template_batcher.window_s = BATCH_WINDOW_MS / 1000.0
            batch_engines[label] = engine
            for k in (0, 1):  # two distinct bindings confirm the template
                for _cls, gen in batch_sched:
                    sql, params = gen(k)
                    sess = engine.create_session("tpch")
                    engine.execute_sql(sql, sess, parameters=params)
            # unmeasured pre-storm: compiles the pow2 rung ladder (the ON
            # half's analog of the serial half's already-warm plan — both
            # phases measure warm execution, not compilation)
            open_loop_inproc(engine, batch_sched, min(2.0, DURATION / 4),
                            BATCH_QPS, deadline)
            before = _counters_snapshot(engine)
            res = open_loop_inproc(engine, batch_sched, DURATION, BATCH_QPS,
                                   deadline)
            phases[label] = {
                "open": res,
                "counters": _counters_delta(before,
                                            _counters_snapshot(engine)),
                "batcher": engine.template_batcher.info()}
            print(f"bench_serve: {label} done ({res['total']})",
                  file=sys.stderr)
        if "batch_off" in phases and "batch_on" in phases:
            def _open_qps(label, cls_):
                ph = phases[label]["open"]
                n = ph["classes"].get(cls_, {}).get("count") or 0
                w = ph["wall_s"]
                return (n / w) if (n and w) else None

            for cls_ in ("point", "param"):
                off_q, on_q = _open_qps("batch_off", cls_), \
                    _open_qps("batch_on", cls_)
                if off_q and on_q:
                    payload[f"{cls_}_batch_qps_speedup"] = round(
                        on_q / off_q, 2)
            off_t = phases["batch_off"]["open"]["total"]["achieved_qps"]
            on_t = phases["batch_on"]["open"]["total"]["achieved_qps"]
            if off_t and on_t:
                payload["batch_open_qps_speedup"] = round(on_t / off_t, 2)
            payload["batched_requests"] = phases["batch_on"]["counters"] \
                .get("batched_requests", 0)
            # byte identity: the batched engine's answers vs the serial
            # engine's, same requests (the load phase already counter-
            # verified that fused batches actually served traffic)
            identical = True
            for i in range(4):
                for _cls, gen in batch_sched:
                    sql, params = gen(i)
                    s_on = batch_engines["batch_on"].create_session("tpch")
                    s_off = batch_engines["batch_off"].create_session("tpch")
                    if _sig(batch_engines["batch_on"].execute_sql(
                            sql, s_on, parameters=params)) != \
                            _sig(batch_engines["batch_off"].execute_sql(
                                sql, s_off, parameters=params)):
                        identical = False
                        print(f"bench_serve: MISMATCH batch on/off: "
                              f"{sql[:60]}", file=sys.stderr)
            payload["batch_identical"] = identical

        payload["phases"] = phases
        payload["sf"], payload["clients"] = SF, CLIENTS
        payload["duration_s"], payload["qps_target"] = DURATION, QPS
        payload["batch_qps_target"] = BATCH_QPS
        payload["batch_max"] = BATCH_MAX
        payload["batch_window_ms"] = BATCH_WINDOW_MS
        payload["workers"] = WORKERS

        # -- round-13 template A/B: substitution baseline vs templates ------
        if "templates_off" in phases and "cache_off" in phases:
            def _cls_stat(label, cls_, stat):
                return (phases[label]["closed"]["classes"]
                        .get(cls_, {}).get(stat))

            for cls_ in ("point", "param"):
                coff = _cls_stat("templates_off", cls_, "count")
                con = _cls_stat("cache_off", cls_, "count")
                woff = phases["templates_off"]["closed"]["wall_s"]
                won = phases["cache_off"]["closed"]["wall_s"]
                if coff and con and woff and won:
                    payload[f"{cls_}_template_qps_speedup"] = round(
                        (con / won) / (coff / woff), 2)
                p_off = _cls_stat("templates_off", cls_, "p50_ms")
                p_on = _cls_stat("cache_off", cls_, "p50_ms")
                if p_off and p_on:
                    payload[f"{cls_}_template_p50_speedup"] = round(
                        p_off / p_on, 2)
            ctr = phases["cache_off"]["counters"]
            served = sum(_cls_stat("cache_off", c_, "count") or 0
                         for c_ in ("point", "param"))
            if served:
                payload["template_hit_rate"] = round(
                    ctr.get("plan_template_hits", 0) / served, 3)

        # -- acceptance verification (in-process, both engines live) --------
        if "cache_on" in engines and "cache_off" in engines:
            eng_on, eng_off = engines["cache_on"], engines["cache_off"]
            repeat_sql = classes["repeat"][0](0)[0]
            # byte identity: every distinct statement, cache-on vs cache-off
            identical = True
            for _cls, gen in schedule:
                sql, params = gen(0)
                s_on = eng_on.create_session("tpch")
                s_off = eng_off.create_session("tpch")
                if _sig(eng_on.execute_sql(sql, s_on, parameters=params)) != \
                        _sig(eng_off.execute_sql(sql, s_off,
                                                 parameters=params)):
                    identical = False
                    print(f"bench_serve: MISMATCH cache on/off: {sql[:60]}",
                          file=sys.stderr)
            payload["cache_identical"] = identical
            # counter-verified zero-dispatch warm hit
            s = eng_on.create_session("tpch")
            eng_on.execute_sql(repeat_sql, s)
            eng_on.execute_sql(repeat_sql, s)
            c = eng_on.last_query_counters
            payload["warm_hit_zero_dispatches"] = bool(
                c.result_cache_hits >= 1 and c.device_dispatches == 0
                and c.host_transfers == 0)
            # the headline ratio: repeated-statement p50, off vs on
            off_p50 = phases["cache_off"]["closed"]["classes"] \
                .get("repeat", {}).get("p50_ms")
            on_p50 = phases["cache_on"]["closed"]["classes"] \
                .get("repeat", {}).get("p50_ms")
            if off_p50 and on_p50:
                payload["repeat_p50_speedup"] = round(off_p50 / on_p50, 2)
            on = phases["cache_on"]["closed"]["total"]
            payload["metric"] = f"serve_sf{SF:g}_mixed_closed_qps"
            payload["value"] = on.get("qps") or 0
            payload["vs_baseline"] = payload.get("repeat_p50_speedup", 0)
    except BaseException as e:
        import traceback

        print(f"bench_serve: fatal: {type(e).__name__}: {e}", file=sys.stderr)
        traceback.print_exc(file=sys.stderr)
    finally:
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        signal.signal(signal.SIGALRM, signal.SIG_IGN)
        signal.alarm(0)
        for srv in servers:
            try:
                srv.stop()
            except Exception:
                pass
        try:
            from benchenv import env_info

            payload["env"] = env_info()
        except Exception:
            pass
        print(json.dumps(payload), flush=True)


if __name__ == "__main__":
    main()
