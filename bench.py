"""Benchmark: the TPC-H north-star suite (Q1/Q3/Q9/Q18) on the local accelerator
vs a vectorized CPU (numpy/pandas) evaluation of the same queries on the same data.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} — ALWAYS, even on
timeout/failure (from a finally: block; SIGTERM/SIGALRM raise through it).

Protocol mirrors the reference's benchto macro setup (prewarm + timed runs,
SURVEY.md §6: testing/trino-benchto-benchmarks/.../tpch.yaml), adapted to survive a
cold XLA-compile cache: a global wall-clock budget (env BENCH_BUDGET seconds,
default 900) degrades the suite — fewer timed runs, then fewer queries — instead of
overrunning.  Each query completes engine+baseline as a unit, so whatever finished
when the budget ran out still yields a coherent metric.

value = summed TPC-H input rows / summed median wall-clock (rows/sec on one chip);
vs_baseline = geometric-mean per-query speedup over the CPU baseline.
BENCH_SF overrides the scale factor (default 1); BENCH_QUERIES picks a subset
(comma-separated, e.g. "q1,q3").

``--distributed`` benches the worker-mesh executor instead (rows/sec/chip
across the mesh; forces the virtual 8-device mesh on CPU) and embeds the
round-18 device-vs-spool exchange-byte A/B per query.

``--baseline BENCH_xxx.json`` diffs this run's per_query wall/dispatch/bytes
against a prior capture and prints a regression verdict line to stderr
(>20% wall growth or any budget-counter growth flags); the diff also embeds
in the JSON payload under "baseline".  BENCH_STATUS_PORT starts an HTTP
status server on the engine (GET /v1/status: in-flight registry, stall
report, running queries) so an external watcher — scripts/tpu_watch.sh — can
capture a post-mortem artifact if the tunnel wedges mid-bench; pair it with
TRINO_TPU_STALL_S to arm the engine's stall watchdog.
"""

import json
import os
import signal
import sys
import time

# Same guard as __graft_entry__: JAX_PLATFORMS=cpu as an ENV VAR hangs the axon
# plugin's discovery at first device use; the config route works.  The driver's
# real-TPU bench run leaves the env at its axon default, so this only affects
# CPU smoke runs.
_force_cpu = os.environ.get("JAX_PLATFORMS") == "cpu"
if _force_cpu:
    os.environ.pop("JAX_PLATFORMS")

# --distributed benches the worker-mesh executor: it needs >1 device, which
# on the CPU backend means forcing the virtual 8-device mesh BEFORE jax
# imports (same dance as tests/conftest.py; a no-op on a real multi-chip
# backend, where jax.devices() reports the hardware)
if "--distributed" in sys.argv and "host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

import jax

if _force_cpu:
    jax.config.update("jax_platforms", "cpu")
else:
    # Real-device runs: persist compiled executables across processes.  The
    # axon tunnel stays up ~30 min per contact (CLAUDE.md) and a cold Q1
    # compile alone eats ~110s of it; with this cache the next contact's
    # bench spends its window executing, not compiling.  CPU smoke runs skip
    # it (thousands of tiny programs would bloat the cache on the 1-core box).
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

SF = float(os.environ.get("BENCH_SF", "1"))
RUNS = int(os.environ.get("BENCH_RUNS", "3"))
BUDGET = float(os.environ.get("BENCH_BUDGET", "900"))

QUERIES = {
    "q1": """
    select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
           sum(l_extendedprice) as sum_base_price,
           sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
           sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
           avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price,
           avg(l_discount) as avg_disc, count(*) as count_order
    from lineitem where l_shipdate <= date '1998-12-01' - interval '90' day
    group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus""",
    "q3": """
    select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
           o_orderdate, o_shippriority
    from customer, orders, lineitem
    where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
      and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
      and l_shipdate > date '1995-03-15'
    group by l_orderkey, o_orderdate, o_shippriority
    order by revenue desc, o_orderdate limit 10""",
    "q4": """
    select o_orderpriority, count(*) as order_count from orders
    where o_orderdate >= date '1993-07-01'
      and o_orderdate < date '1993-07-01' + interval '3' month
      and exists (select 1 from lineitem where l_orderkey = o_orderkey
                  and l_commitdate < l_receiptdate)
    group by o_orderpriority order by o_orderpriority""",
    "q9": """
    select nation, o_year, sum(amount) as sum_profit from (
      select n_name as nation, extract(year from o_orderdate) as o_year,
        l_extendedprice * (1 - l_discount) - ps_supplycost * l_quantity as amount
      from part, supplier, lineitem, partsupp, orders, nation
      where s_suppkey = l_suppkey and ps_suppkey = l_suppkey and ps_partkey = l_partkey
        and p_partkey = l_partkey and o_orderkey = l_orderkey
        and s_nationkey = n_nationkey and p_name like '%green%') as profit
    group by nation, o_year order by nation, o_year desc""",
    "q18": """
    select c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice, sum(l_quantity)
    from customer, orders, lineitem
    where o_orderkey in (select l_orderkey from lineitem group by l_orderkey
                         having sum(l_quantity) > 300)
      and c_custkey = o_custkey and o_orderkey = l_orderkey
    group by c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
    order by o_totalprice desc, o_orderdate limit 100""",
}

# TPC-H input rows touched per query (the tables each query scans)
QUERY_TABLES = {
    "q1": ["lineitem"],
    "q3": ["customer", "orders", "lineitem"],
    "q4": ["orders", "lineitem"],
    "q9": ["part", "supplier", "lineitem", "partsupp", "orders", "nation"],
    "q18": ["customer", "orders", "lineitem"],
}

# columns the CPU baseline actually reads, per table — pulling full tables to
# host (16 lineitem columns, string decode via to_pylist) dominated the round-1
# bench wall-clock; the baseline only needs these
BASELINE_COLUMNS = {
    "lineitem": ["l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
                 "l_discount", "l_tax", "l_shipdate", "l_orderkey", "l_partkey",
                 "l_suppkey", "l_commitdate", "l_receiptdate"],
    "customer": ["c_custkey", "c_mktsegment", "c_name"],
    "orders": ["o_orderkey", "o_custkey", "o_orderdate", "o_shippriority",
               "o_totalprice", "o_orderpriority"],
    "part": ["p_partkey", "p_name"],
    "supplier": ["s_suppkey", "s_nationkey"],
    "partsupp": ["ps_partkey", "ps_suppkey", "ps_supplycost"],
    "nation": ["n_nationkey", "n_name"],
}


class _HostTables:
    """Lazy, cached host-side copies of the baseline's input columns (transfer
    time is NOT part of either measurement)."""

    def __init__(self, conn):
        self.conn = conn
        self._cache: dict = {}

    def __getitem__(self, t):
        import pandas as pd

        if t in self._cache:
            return self._cache[t]
        conn = self.conn
        schema = conn.schema(t)
        dicts = conn.dictionaries(t)
        cols = {}
        for name in BASELINE_COLUMNS[t]:
            f = schema.field(name)
            parts = []
            for sp in conn.splits(t):
                page = conn.generate(sp, [f.name])
                valid = np.asarray(page.valid_mask())
                arr = np.asarray(page.column(f.name))[valid]
                parts.append(arr)
            arr = np.concatenate(parts)
            d = dicts.get(f.name)
            if d is not None:
                arr = d.decode(arr)
            cols[name] = arr
        df = pd.DataFrame(cols)
        self._cache[t] = df
        return df


def cpu_q1(T):
    df = T["lineitem"]
    cutoff = (np.datetime64("1998-12-01") - np.timedelta64(90, "D")
              - np.datetime64("1970-01-01")).astype(np.int64)
    m = df[df["l_shipdate"].to_numpy() <= cutoff]
    disc = m["l_discount"].to_numpy() / 100.0
    tax = m["l_tax"].to_numpy() / 100.0
    price = m["l_extendedprice"].to_numpy() / 100.0
    g = m.assign(dp=price * (1 - disc), ch=price * (1 - disc) * (1 + tax),
                 qty=m["l_quantity"].to_numpy() / 100.0, pr=price, dc=disc)
    r = g.groupby(["l_returnflag", "l_linestatus"]).agg(
        sum_qty=("qty", "sum"), sum_base=("pr", "sum"), sum_dp=("dp", "sum"),
        sum_ch=("ch", "sum"), avg_qty=("qty", "mean"), avg_pr=("pr", "mean"),
        avg_dc=("dc", "mean"), cnt=("dp", "size")).reset_index()
    return r.sort_values(["l_returnflag", "l_linestatus"])


def cpu_q3(T):
    c = T["customer"]; o = T["orders"]; l = T["lineitem"]
    cutoff = (np.datetime64("1995-03-15") - np.datetime64("1970-01-01")).astype(np.int64)
    c2 = c[c["c_mktsegment"] == "BUILDING"][["c_custkey"]]
    o2 = o[o["o_orderdate"].to_numpy() < cutoff][
        ["o_orderkey", "o_custkey", "o_orderdate", "o_shippriority"]]
    l2 = l[l["l_shipdate"].to_numpy() > cutoff][
        ["l_orderkey", "l_extendedprice", "l_discount"]]
    j = o2.merge(c2, left_on="o_custkey", right_on="c_custkey")
    j = l2.merge(j, left_on="l_orderkey", right_on="o_orderkey")
    rev = (j["l_extendedprice"].to_numpy() / 100.0) * (1 - j["l_discount"].to_numpy() / 100.0)
    j = j.assign(revenue=rev)
    r = j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"])["revenue"].sum().reset_index()
    return r.sort_values(["revenue", "o_orderdate"], ascending=[False, True]).head(10)


def cpu_q4(T):
    o = T["orders"]; l = T["lineitem"]
    lo = (np.datetime64("1993-07-01") - np.datetime64("1970-01-01")).astype(np.int64)
    hi = (np.datetime64("1993-10-01") - np.datetime64("1970-01-01")).astype(np.int64)
    od = o["o_orderdate"].to_numpy()
    o2 = o[(od >= lo) & (od < hi)]
    late = l[l["l_commitdate"].to_numpy() < l["l_receiptdate"].to_numpy()]
    keys = np.unique(late["l_orderkey"].to_numpy())
    m = o2[np.isin(o2["o_orderkey"].to_numpy(), keys)]
    r = m.groupby("o_orderpriority").size().reset_index(name="order_count")
    return r.sort_values("o_orderpriority")


def cpu_q9(T):
    p = T["part"]; s = T["supplier"]; l = T["lineitem"]
    ps = T["partsupp"]; o = T["orders"]; n = T["nation"]
    p2 = p[p["p_name"].astype(str).str.contains("green")][["p_partkey"]]
    j = l.merge(p2, left_on="l_partkey", right_on="p_partkey")
    j = j.merge(s[["s_suppkey", "s_nationkey"]], left_on="l_suppkey", right_on="s_suppkey")
    j = j.merge(ps[["ps_partkey", "ps_suppkey", "ps_supplycost"]],
                left_on=["l_partkey", "l_suppkey"], right_on=["ps_partkey", "ps_suppkey"])
    j = j.merge(o[["o_orderkey", "o_orderdate"]], left_on="l_orderkey", right_on="o_orderkey")
    j = j.merge(n[["n_nationkey", "n_name"]], left_on="s_nationkey", right_on="n_nationkey")
    amount = (j["l_extendedprice"].to_numpy() / 100.0) * (1 - j["l_discount"].to_numpy() / 100.0) \
        - (j["ps_supplycost"].to_numpy() / 100.0) * (j["l_quantity"].to_numpy() / 100.0)
    year = (j["o_orderdate"].to_numpy().astype("datetime64[D]")).astype("datetime64[Y]").astype(int) + 1970
    j = j.assign(amount=amount, o_year=year)
    r = j.groupby(["n_name", "o_year"])["amount"].sum().reset_index()
    return r.sort_values(["n_name", "o_year"], ascending=[True, False])


def cpu_q18(T):
    c = T["customer"]; o = T["orders"]; l = T["lineitem"]
    qty = l.groupby("l_orderkey")["l_quantity"].sum()
    big = qty[qty > 30000].index  # l_quantity is a scaled decimal (x100)
    o2 = o[o["o_orderkey"].isin(big)]
    j = o2.merge(c[["c_custkey", "c_name"]], left_on="o_custkey", right_on="c_custkey")
    j = j.merge(l[["l_orderkey", "l_quantity"]], left_on="o_orderkey", right_on="l_orderkey")
    r = j.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate", "o_totalprice"])[
        "l_quantity"].sum().reset_index()
    return r.sort_values(["o_totalprice", "o_orderdate"],
                         ascending=[False, True]).head(100)


CPU_QUERIES = {"q1": cpu_q1, "q3": cpu_q3, "q4": cpu_q4, "q9": cpu_q9,
               "q18": cpu_q18}


class _BudgetExceeded(Exception):
    pass


# regression thresholds for --baseline: wall growth beyond the ratio flags;
# any growth in these per-query budget counters flags (they are supposed to
# be DETERMINISTIC warm-path quantities — growth means a real code change)
WALL_REGRESSION_RATIO = 1.2
BUDGET_COUNTERS = ("device_dispatches", "host_transfers", "host_bytes_pulled")
# cache-effectiveness counters diffed for VISIBILITY, never flagged: hit
# deltas between captures are configuration (budgets, order), not
# regressions — but a result-cache hit appearing here at all means the tier
# leaked into an execute-path measurement (see the RESULT-CACHE pin in main)
CACHE_COUNTERS = ("page_cache_hits", "page_cache_misses",
                  "result_cache_hits", "result_cache_misses")
# round 17: compile census, diffed for VISIBILITY, never flagged — cold
# compile counts/seconds move with XLA versions and cache state, but a WARM
# compile appearing at all is the recompile-regression signature the budget
# suite pins (warm compiles == 0), so the diff shows it without verdicting
COMPILE_COUNTERS = ("compiles", "compile_s",
                    "cold_compiles", "cold_compile_s")
# round 19: adaptive decisions, diffed for VISIBILITY, never flagged — a
# replan appearing between captures is the advisor doing its job (history
# accumulated), not a regression; the warm-path cost of a BAD correction
# shows up in the flagged budget counters above, which is where it belongs
ADAPTIVE_COUNTERS = ("adaptive_replans", "adaptive_holds")


def _baseline_diff(base_pq: dict, now_pq: dict) -> dict:
    """Per-query diff of this run vs a prior capture's per_query payload.
    Returns {"queries": {q: {...}}, "missing": [...], "regressions":
    [summary...]} — a query regresses on >20% wall growth, ANY budget-counter
    growth, or by DISAPPEARING from this run (a query that no longer finishes
    is the worst regression of all)."""
    queries, regressions = {}, []
    missing = sorted(set(base_pq) - set(now_pq))
    for q in missing:
        regressions.append(f"{q}: missing from this run "
                           "(present in baseline — crashed or timed out?)")
    for q in sorted(set(base_pq) & set(now_pq)):
        b, n = base_pq[q], now_pq[q]
        d: dict = {}
        flags = []
        bw, nw = b.get("engine_warm_s"), n.get("engine_warm_s")
        if bw and nw:
            d["wall_s"] = {"base": bw, "now": nw,
                           "ratio": round(nw / bw, 3)}
            if nw > WALL_REGRESSION_RATIO * bw:
                flags.append(f"wall +{(nw / bw - 1) * 100:.0f}% "
                             f"({bw:.3f}s -> {nw:.3f}s)")
        for k in BUDGET_COUNTERS:
            bv, nv = b.get(k), n.get(k)
            if bv is None or nv is None:
                continue
            d[k] = {"base": bv, "now": nv}
            if nv > bv:
                flags.append(f"{k} {bv} -> {nv}")
        for k in CACHE_COUNTERS + COMPILE_COUNTERS + ADAPTIVE_COUNTERS:
            bv, nv = b.get(k), n.get(k)
            if bv is None and nv is None:
                continue
            d[k] = {"base": bv, "now": nv}
        # wall-breakdown buckets (round 16): diffed for VISIBILITY, never
        # flagged — a regressed capture should show WHICH bucket moved
        # (dispatch vs host_pull vs unattributed), but bucket drift between
        # captures is timing, not by itself a verdict
        bbd, nbd = b.get("wall_breakdown") or {}, n.get("wall_breakdown") or {}
        if bbd or nbd:
            d["wall_breakdown"] = {
                k: {"base": bbd.get(k), "now": nbd.get(k)}
                for k in sorted(set(bbd) | set(nbd))
                if (bbd.get(k) or 0) > 0.0005 or (nbd.get(k) or 0) > 0.0005}
        d["flags"] = flags
        queries[q] = d
        if flags:
            regressions.append(f"{q}: " + "; ".join(flags))
    return {"queries": queries, "missing": missing,
            "regressions": regressions}


def _bench_distributed(engine, conn, session, names, remaining, payload):
    """The --distributed bench: Q1/Q3/Q9/Q18 through DistributedExecutor on
    the worker mesh (virtual 8-device CPU mesh locally, the real chips on
    device).  value = rows/sec/CHIP (total input rows / summed warm median /
    mesh size).  Each query also runs one cold+warm pair with the host-spool
    exchange (TRINO_TPU_DEVICE_EXCHANGE=0 equivalent) so the capture carries
    the round-18 A/B: per_query dist_site_bytes (device) vs
    spool_site_bytes."""
    from trino_tpu.exec.distributed import DistributedExecutor
    from trino_tpu.parallel.mesh import worker_mesh
    from trino_tpu.sql.frontend import compile_sql

    n_dev = jax.device_count()
    if n_dev < 2:
        payload["metric"] = f"tpch_sf{SF:g}_distributed_skipped"
        payload["detail"] = f"single-device backend ({n_dev})"
        return
    workers = min(n_dev, 8)
    mesh = worker_mesh(workers)
    payload["workers"] = workers

    def _dist_bytes(c):
        return sum(v["bytes"] for k, v in c.sites.items() if "dist." in k)

    engine_times: dict = {}
    row_counts: dict = {}
    per_query: dict = {}
    for name in names:
        if remaining() < 30:
            print(f"bench: budget exhausted before {name}", file=sys.stderr)
            break
        try:
            plan = compile_sql(QUERIES[name], engine, session)
            ex = DistributedExecutor(engine.catalogs, mesh=mesh)
            t0 = time.perf_counter()
            ex.execute(plan)  # prewarm = cold compile
            cold_s = time.perf_counter() - t0
            times = []
            for _ in range(RUNS):
                if times and remaining() < 3 * times[0]:
                    break
                t0 = time.perf_counter()
                ex.execute(plan)
                times.append(time.perf_counter() - t0)
            med = sorted(times)[len(times) // 2]
            c = ex.counters  # the last WARM run's counters
            pq = {"engine_warm_s": round(med, 3),
                  "engine_cold_s": round(cold_s, 3),
                  "dist_site_bytes": _dist_bytes(c), **c.as_dict()}
            # round 20: shard-skew summary — worst max/mean load ratio and
            # summed imbalance wall over the warm run's ShardStats (the raw
            # records ride along in as_dict's shard_stats)
            if c.shard_stats:
                worst = max(c.shard_stats,
                            key=lambda r: float(r.get("ratio") or 1.0))
                pq["skew"] = {
                    "worst_ratio": round(
                        float(worst.get("ratio") or 1.0), 2),
                    "worst_site": worst.get("site"),
                    "worst_worker": int(worst.get("worker") or 0),
                    "imbalance_s": round(
                        sum(float(r.get("imbalance_s") or 0.0)
                            for r in c.shard_stats), 4),
                    "records": len(c.shard_stats)}
            # spool half of the A/B (one cold + one warm, budget permitting):
            # the host-materializing exchange this round replaced
            if remaining() > 30 + 2 * cold_s:
                sp = DistributedExecutor(engine.catalogs, mesh=mesh,
                                         device_exchange=False)
                sp.execute(plan)
                t0 = time.perf_counter()
                sp.execute(plan)
                pq["spool_warm_s"] = round(time.perf_counter() - t0, 3)
                pq["spool_site_bytes"] = _dist_bytes(sp.counters)
            engine_times[name] = med
            per_query[name] = pq
            for t in QUERY_TABLES[name]:
                row_counts.setdefault(t, conn.row_count(t))
            print(f"bench: {name} mesh({workers}) cold={cold_s:.2f}s "
                  f"warm={med:.3f}s dist_bytes={pq['dist_site_bytes']}"
                  + (f" spool_bytes={pq['spool_site_bytes']}"
                     if "spool_site_bytes" in pq else "")
                  + f" ({remaining():.0f}s left)", file=sys.stderr)
        except _BudgetExceeded:
            raise
        except Exception as e:
            print(f"bench: {name} failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
    done = sorted(engine_times)
    if done:
        total_rows = sum(sum(row_counts[t] for t in QUERY_TABLES[q])
                         for q in done)
        total_t = sum(engine_times.values())
        payload.update({
            "metric": (f"tpch_sf{SF:g}_dist{workers}w_{'_'.join(done)}"
                       "_rows_per_sec_per_chip"),
            "value": round(total_rows / total_t / workers),
            "unit": "rows/s",
            "per_query": per_query,
        })


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", default=None, metavar="BENCH_JSON",
                    help="prior bench JSON to diff per_query wall/dispatch/"
                         "bytes against (prints a regression verdict line)")
    ap.add_argument("--no-page-cache", action="store_true",
                    help="force the device buffer pool OFF for this run "
                         "(TRINO_TPU_PAGE_CACHE=0) — the cache-off half of "
                         "an A/B pair; per_query embeds page_cache_hits/"
                         "misses/bytes_saved either way, so diffing two runs "
                         "quantifies exactly what the pool saved")
    ap.add_argument("--distributed", action="store_true",
                    help="bench the worker-mesh DistributedExecutor instead "
                         "of the local engine: rows/sec/CHIP across the mesh "
                         "plus the device-vs-spool exchange-byte A/B "
                         "(round 18); on CPU this forces the virtual "
                         "8-device mesh")
    args = ap.parse_args(argv)
    if args.no_page_cache:
        os.environ["TRINO_TPU_PAGE_CACHE"] = "0"
    # the RESULT cache (round 12) stays off unless a capture explicitly sets
    # the env: this benchmark measures the EXECUTE path, and with the tier
    # on every warm timed run would be answered from the cache in ~0 time
    # (bench_serve.py is where that is measured on purpose)
    os.environ.setdefault("TRINO_TPU_RESULT_CACHE", "0")

    deadline = time.monotonic() + BUDGET
    remaining = lambda: deadline - time.monotonic()

    # a terminated process prints nothing — round 1's rc=124 scored null.  Turn
    # SIGTERM (driver timeout) and SIGALRM (own hard stop, slightly past the
    # budget to catch a single hung compile) into an exception that unwinds to
    # the finally: below.  A signal arriving inside one long C-level XLA call
    # is only delivered when the interpreter resumes — hence the deadline
    # checks between runs, which keep any single call's overrun small.
    def _bail(signum, frame):
        raise _BudgetExceeded(f"signal {signum}")

    signal.signal(signal.SIGTERM, _bail)
    signal.signal(signal.SIGALRM, _bail)
    signal.alarm(int(BUDGET + 60))

    engine_times: dict = {}
    cpu_times: dict = {}
    row_counts: dict = {}
    query_counters: dict = {}
    payload = {"metric": f"tpch_sf{SF:g}_bench_failed", "value": 0,
               "unit": "rows/s", "vs_baseline": 0}

    try:
        # a wedged accelerator tunnel hangs INSIDE backend init, where the
        # plugin's retry loop swallows our signal-raised exceptions (observed:
        # axon init absorbing SIGTERM/SIGALRM indefinitely).  Probe device init
        # in a SUBPROCESS first: if it cannot come up within the probe budget,
        # emit the failure JSON instead of hanging into an rc=124 null.  Inside
        # this try: so a driver SIGTERM during the probe still reaches the
        # JSON-emitting finally below.
        if not _force_cpu:
            import subprocess

            probe_s = float(os.environ.get("BENCH_DEVICE_PROBE_TIMEOUT", "240"))
            try:
                probe = subprocess.run(
                    [sys.executable, "-c",
                     "import jax; print(jax.devices()[0].platform)"],
                    capture_output=True, timeout=probe_s)
                ok = probe.returncode == 0
                if not ok:
                    print(f"bench: device probe failed: "
                          f"{probe.stderr.decode()[-300:]}", file=sys.stderr)
            except subprocess.TimeoutExpired:
                ok = False
                print(f"bench: device init did not finish in {probe_s:.0f}s "
                      f"(wedged tunnel?)", file=sys.stderr)
            if not ok:
                payload["metric"] = f"tpch_sf{SF:g}_bench_failed_no_device"
                return  # the finally below prints the payload

        from trino_tpu import Engine
        from trino_tpu.connectors.tpch import TpchConnector

        conn = TpchConnector(sf=SF, split_rows=1 << 21)
        engine = Engine()
        engine.register_catalog("tpch", conn)
        session = engine.create_session("tpch")
        T = _HostTables(conn)

        # optional status sidecar (BENCH_STATUS_PORT): /v1/status serves the
        # live in-flight registry + engine.last_stall_report so tpu_watch.sh
        # can archive a post-mortem if the tunnel wedges mid-capture (the
        # engine's stall watchdog arms via TRINO_TPU_STALL_S)
        status_port = os.environ.get("BENCH_STATUS_PORT")
        if status_port:
            try:
                from trino_tpu.server.server import CoordinatorServer

                srv = CoordinatorServer(engine, port=int(status_port))
                srv.start()
                print(f"bench: status server at {srv.url}/v1/status",
                      file=sys.stderr)
            except Exception as se:
                print(f"bench: status server failed: {se}", file=sys.stderr)

        names = [q.strip() for q in
                 os.environ.get("BENCH_QUERIES", "q1,q3,q4,q9,q18").split(",")
                 if q.strip() in QUERIES]
        if args.distributed:
            # mesh bench: its own loop + payload (no pandas baseline — the
            # comparison that matters there is device-vs-spool exchange A/B)
            _bench_distributed(engine, conn, session,
                               [n for n in names if n != "q4"],
                               remaining, payload)
            return  # the finally below prints the payload
        for name in names:
            if remaining() < 30:
                print(f"bench: budget exhausted before {name}", file=sys.stderr)
                break
            sql = QUERIES[name]
            try:
                t0 = time.perf_counter()
                engine.execute_sql(sql, session)  # prewarm = the cold compile run
                cold_s = time.perf_counter() - t0
                # cold-run compile census (round 17): how many XLA
                # compilations the cold run paid and what they cost — the
                # cold-vs-warm split per_query carries (warm compiles ride
                # the counters snapshot below and must be ZERO)
                try:
                    cc = engine.last_query_counters
                    cold_compiles = cc.compiles
                    cold_compile_s = round(cc.compile_s, 4)
                except Exception:
                    cold_compiles = cold_compile_s = None
                # timed engine runs: as many of RUNS as the budget allows, min 1
                times = []
                for i in range(RUNS):
                    if times and remaining() < 3 * times[0]:
                        break
                    t0 = time.perf_counter()
                    engine.execute_sql(sql, session)
                    times.append(time.perf_counter() - t0)
                med = sorted(times)[len(times) // 2]
                # device-boundary counters of the LAST warm run: the
                # dispatch/transfer budget this query actually spent
                # (engine.last_query_counters — execution/tracing), including
                # the per-site attribution + dispatch-latency histogram, plus
                # a span-tree summary (engine.last_query_trace) — enough to
                # tell "wedging tunnel" (p99 blown, counts stalled) from
                # "slow plan" straight from the bench record
                try:
                    qc = engine.last_query_counters
                    query_counters[name] = qc.as_dict()
                    # the cold/warm compile split: as_dict already carries
                    # the WARM run's compiles/compile_s (expected 0/0.0)
                    query_counters[name]["cold_compiles"] = cold_compiles
                    query_counters[name]["cold_compile_s"] = cold_compile_s
                    tr = engine.last_query_trace or {}
                    query_counters[name]["trace"] = {
                        "spans": len(tr.get("spans", ())),
                        "root_span_s": tr.get("root_span_s"),
                        "dispatch_p50_s": qc.dispatch_latency.quantile(0.5),
                        "dispatch_p99_s": qc.dispatch_latency.quantile(0.99),
                    }
                    # round 16: the warm run's wall decomposed into named
                    # buckets (device dispatch vs host pull vs generation vs
                    # unattributed) — "where did the time go" rides every
                    # capture, and --baseline diffs WHICH bucket moved
                    bd = tr.get("wall_breakdown")
                    if bd:
                        query_counters[name]["wall_breakdown"] = bd
                except Exception:
                    pass
                print(f"bench: {name} engine cold={cold_s:.2f}s warm={med:.3f}s "
                      f"({len(times)} runs, {remaining():.0f}s left)", file=sys.stderr)

                # CPU baseline for the same query (host pull cached per table)
                fn = CPU_QUERIES[name]
                fn(T)  # warm (also triggers the host pull)
                ctimes = []
                for i in range(RUNS):
                    if ctimes and remaining() < 3 * ctimes[0]:
                        break
                    t0 = time.perf_counter()
                    fn(T)
                    ctimes.append(time.perf_counter() - t0)
                cmed = sorted(ctimes)[len(ctimes) // 2]
                print(f"bench: {name} cpu warm={cmed:.3f}s ({len(ctimes)} runs, "
                      f"{remaining():.0f}s left)", file=sys.stderr)

                engine_times[name] = med
                cpu_times[name] = cmed
                for t in QUERY_TABLES[name]:
                    row_counts.setdefault(t, conn.row_count(t))
            except _BudgetExceeded:
                raise
            except Exception as e:  # one pathological query must not zero the bench
                print(f"bench: {name} failed: {type(e).__name__}: {e}",
                      file=sys.stderr)
    except _BudgetExceeded as e:
        import traceback

        print(f"bench: stopped by {e} at:", file=sys.stderr)
        traceback.print_exc(file=sys.stderr)
    except Exception as e:
        import traceback

        print(f"bench: fatal: {type(e).__name__}: {e}", file=sys.stderr)
        traceback.print_exc(file=sys.stderr)
    finally:
        # the JSON emission itself must be uninterruptible: a driver SIGTERM
        # landing inside this block would otherwise raise mid-print and void
        # the "always prints one line" guarantee
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        signal.signal(signal.SIGALRM, signal.SIG_IGN)
        signal.alarm(0)
        done = sorted(engine_times)
        if done:
            total_rows = sum(sum(row_counts[t] for t in QUERY_TABLES[q]) for q in done)
            total_t = sum(engine_times.values())
            speedups = [cpu_times[q] / engine_times[q] for q in done]
            geomean = float(np.exp(np.mean(np.log(speedups))))
            payload = {
                "metric": f"tpch_sf{SF:g}_{'_'.join(done)}_rows_per_sec_per_chip",
                "value": round(total_rows / total_t),
                "unit": "rows/s",
                "vs_baseline": round(geomean, 3),
            }
            # per-query breakdown: both sides timed in THIS process (the
            # pandas baseline is recomputed alongside the engine run, never
            # copied from an earlier capture) plus each query's warm
            # device-boundary counters
            payload["per_query"] = {
                q: {"engine_warm_s": round(engine_times[q], 3),
                    "cpu_warm_s": round(cpu_times[q], 3),
                    **query_counters.get(q, {})} for q in done}
        if args.baseline:
            # BENCH trajectory comparison: diff against a prior capture and
            # print a one-line verdict (stderr; stdout stays one JSON line)
            try:
                with open(args.baseline) as f:
                    base = json.load(f)
                diff = _baseline_diff(base.get("per_query") or {},
                                      payload.get("per_query") or {})
                payload["baseline"] = {"path": args.baseline, **diff}
                if diff["regressions"]:
                    print(f"bench: baseline REGRESSION vs {args.baseline} — "
                          + " | ".join(diff["regressions"]), file=sys.stderr)
                else:
                    print(f"bench: baseline OK vs {args.baseline} "
                          f"({len(diff['queries'])} queries compared)",
                          file=sys.stderr)
            except Exception as be:
                print(f"bench: baseline diff failed: {type(be).__name__}: "
                      f"{be}", file=sys.stderr)
        try:
            from benchenv import env_info

            payload["env"] = env_info()
        except Exception:
            pass
        try:
            # buffer-pool end-state: entries/bytes/hit totals (per_query
            # already carries each query's page_cache_* counters via as_dict)
            bp = getattr(engine, "buffer_pool", None)
            if bp is not None:
                bi = bp.info()
                bi.pop("per_table", None)  # one JSON line: keep it flat-ish
                payload["page_cache"] = bi
        except Exception:
            pass
        print(json.dumps(payload), flush=True)


if __name__ == "__main__":
    main()
