"""Benchmark: the TPC-H north-star suite (Q1/Q3/Q9/Q18) on the local accelerator
vs a vectorized CPU (numpy/pandas) evaluation of the same queries on the same data.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Protocol mirrors the reference's benchto macro setup (2 prewarm + timed runs,
SURVEY.md §6: testing/trino-benchto-benchmarks/.../tpch.yaml): per query, 2 prewarm
+ 3 timed runs, median taken.  value = summed TPC-H input rows / summed median
wall-clock (rows/sec on one chip); vs_baseline = geometric-mean per-query speedup
over the CPU baseline.  BENCH_SF overrides the scale factor (default 1).
"""

import json
import os
import time

import jax

jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

SF = float(os.environ.get("BENCH_SF", "1"))
RUNS = int(os.environ.get("BENCH_RUNS", "3"))

QUERIES = {
    "q1": """
    select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
           sum(l_extendedprice) as sum_base_price,
           sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
           sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
           avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price,
           avg(l_discount) as avg_disc, count(*) as count_order
    from lineitem where l_shipdate <= date '1998-12-01' - interval '90' day
    group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus""",
    "q3": """
    select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
           o_orderdate, o_shippriority
    from customer, orders, lineitem
    where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
      and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
      and l_shipdate > date '1995-03-15'
    group by l_orderkey, o_orderdate, o_shippriority
    order by revenue desc, o_orderdate limit 10""",
    "q9": """
    select nation, o_year, sum(amount) as sum_profit from (
      select n_name as nation, extract(year from o_orderdate) as o_year,
        l_extendedprice * (1 - l_discount) - ps_supplycost * l_quantity as amount
      from part, supplier, lineitem, partsupp, orders, nation
      where s_suppkey = l_suppkey and ps_suppkey = l_suppkey and ps_partkey = l_partkey
        and p_partkey = l_partkey and o_orderkey = l_orderkey
        and s_nationkey = n_nationkey and p_name like '%green%') as profit
    group by nation, o_year order by nation, o_year desc""",
    "q18": """
    select c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice, sum(l_quantity)
    from customer, orders, lineitem
    where o_orderkey in (select l_orderkey from lineitem group by l_orderkey
                         having sum(l_quantity) > 300)
      and c_custkey = o_custkey and o_orderkey = l_orderkey
    group by c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
    order by o_totalprice desc, o_orderdate limit 100""",
}

# TPC-H input rows touched per query (the tables each query scans)
QUERY_TABLES = {
    "q1": ["lineitem"],
    "q3": ["customer", "orders", "lineitem"],
    "q9": ["part", "supplier", "lineitem", "partsupp", "orders", "nation"],
    "q18": ["customer", "orders", "lineitem"],
}


def _host_tables(conn, tables):
    """Pull the generated TPC-H columns to host numpy (baseline input; transfer
    time is NOT part of either measurement)."""
    import pandas as pd

    out = {}
    for t in set(tables):
        schema = conn.schema(t)
        dicts = conn.dictionaries(t)
        cols = {}
        for f in schema.fields:
            parts = []
            for sp in conn.splits(t):
                page = conn.generate(sp, [f.name])
                valid = np.asarray(page.valid_mask())
                arr = np.asarray(page.column(f.name))[valid]
                parts.append(arr)
            arr = np.concatenate(parts)
            d = dicts.get(f.name)
            if d is not None:
                arr = d.decode(arr)
            cols[f.name] = arr
        out[t] = pd.DataFrame(cols)
    return out


def cpu_q1(T):
    df = T["lineitem"]
    cutoff = (np.datetime64("1998-12-01") - np.timedelta64(90, "D")
              - np.datetime64("1970-01-01")).astype(np.int64)
    m = df[df["l_shipdate"].to_numpy() <= cutoff]
    disc = m["l_discount"].to_numpy() / 100.0
    tax = m["l_tax"].to_numpy() / 100.0
    price = m["l_extendedprice"].to_numpy() / 100.0
    g = m.assign(dp=price * (1 - disc), ch=price * (1 - disc) * (1 + tax),
                 qty=m["l_quantity"].to_numpy() / 100.0, pr=price, dc=disc)
    r = g.groupby(["l_returnflag", "l_linestatus"]).agg(
        sum_qty=("qty", "sum"), sum_base=("pr", "sum"), sum_dp=("dp", "sum"),
        sum_ch=("ch", "sum"), avg_qty=("qty", "mean"), avg_pr=("pr", "mean"),
        avg_dc=("dc", "mean"), cnt=("dp", "size")).reset_index()
    return r.sort_values(["l_returnflag", "l_linestatus"])


def cpu_q3(T):
    c = T["customer"]; o = T["orders"]; l = T["lineitem"]
    cutoff = (np.datetime64("1995-03-15") - np.datetime64("1970-01-01")).astype(np.int64)
    c2 = c[c["c_mktsegment"] == "BUILDING"][["c_custkey"]]
    o2 = o[o["o_orderdate"].to_numpy() < cutoff][
        ["o_orderkey", "o_custkey", "o_orderdate", "o_shippriority"]]
    l2 = l[l["l_shipdate"].to_numpy() > cutoff][
        ["l_orderkey", "l_extendedprice", "l_discount"]]
    j = o2.merge(c2, left_on="o_custkey", right_on="c_custkey")
    j = l2.merge(j, left_on="l_orderkey", right_on="o_orderkey")
    rev = (j["l_extendedprice"].to_numpy() / 100.0) * (1 - j["l_discount"].to_numpy() / 100.0)
    j = j.assign(revenue=rev)
    r = j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"])["revenue"].sum().reset_index()
    return r.sort_values(["revenue", "o_orderdate"], ascending=[False, True]).head(10)


def cpu_q9(T):
    p = T["part"]; s = T["supplier"]; l = T["lineitem"]
    ps = T["partsupp"]; o = T["orders"]; n = T["nation"]
    p2 = p[p["p_name"].astype(str).str.contains("green")][["p_partkey"]]
    j = l.merge(p2, left_on="l_partkey", right_on="p_partkey")
    j = j.merge(s[["s_suppkey", "s_nationkey"]], left_on="l_suppkey", right_on="s_suppkey")
    j = j.merge(ps[["ps_partkey", "ps_suppkey", "ps_supplycost"]],
                left_on=["l_partkey", "l_suppkey"], right_on=["ps_partkey", "ps_suppkey"])
    j = j.merge(o[["o_orderkey", "o_orderdate"]], left_on="l_orderkey", right_on="o_orderkey")
    j = j.merge(n[["n_nationkey", "n_name"]], left_on="s_nationkey", right_on="n_nationkey")
    amount = (j["l_extendedprice"].to_numpy() / 100.0) * (1 - j["l_discount"].to_numpy() / 100.0) \
        - (j["ps_supplycost"].to_numpy() / 100.0) * (j["l_quantity"].to_numpy() / 100.0)
    year = (j["o_orderdate"].to_numpy().astype("datetime64[D]")).astype("datetime64[Y]").astype(int) + 1970
    j = j.assign(amount=amount, o_year=year)
    r = j.groupby(["n_name", "o_year"])["amount"].sum().reset_index()
    return r.sort_values(["n_name", "o_year"], ascending=[True, False])


def cpu_q18(T):
    c = T["customer"]; o = T["orders"]; l = T["lineitem"]
    qty = l.groupby("l_orderkey")["l_quantity"].sum()
    big = qty[qty > 30000].index  # l_quantity is a scaled decimal (x100)
    o2 = o[o["o_orderkey"].isin(big)]
    j = o2.merge(c[["c_custkey", "c_name"]], left_on="o_custkey", right_on="c_custkey")
    j = j.merge(l[["l_orderkey", "l_quantity"]], left_on="o_orderkey", right_on="l_orderkey")
    r = j.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate", "o_totalprice"])[
        "l_quantity"].sum().reset_index()
    return r.sort_values(["o_totalprice", "o_orderdate"],
                         ascending=[False, True]).head(100)


CPU_QUERIES = {"q1": cpu_q1, "q3": cpu_q3, "q9": cpu_q9, "q18": cpu_q18}


def main():
    from trino_tpu import Engine
    from trino_tpu.connectors.tpch import TpchConnector

    conn = TpchConnector(sf=SF, split_rows=1 << 21)
    engine = Engine()
    engine.register_catalog("tpch", conn)
    session = engine.create_session("tpch")

    row_counts = {t: conn.row_count(t) for t in
                  {t for ts in QUERY_TABLES.values() for t in ts}}

    engine_times = {}
    for name, sql in QUERIES.items():
        try:
            for _ in range(2):
                engine.execute_sql(sql, session)
            times = []
            for _ in range(RUNS):
                t0 = time.perf_counter()
                engine.execute_sql(sql, session)
                times.append(time.perf_counter() - t0)
            engine_times[name] = sorted(times)[len(times) // 2]
        except Exception as e:  # one pathological query must not zero the bench
            import sys

            print(f"bench: {name} failed: {type(e).__name__}: {e}", file=sys.stderr)
    if not engine_times:
        print(json.dumps({"metric": "tpch_bench_failed", "value": 0,
                          "unit": "rows/s", "vs_baseline": 0}))
        return

    T = _host_tables(conn, [t for ts in QUERY_TABLES.values() for t in ts])
    cpu_times = {}
    for name, fn in CPU_QUERIES.items():
        if name not in engine_times:
            continue
        fn(T)  # warm
        times = []
        for _ in range(RUNS):
            t0 = time.perf_counter()
            fn(T)
            times.append(time.perf_counter() - t0)
        cpu_times[name] = sorted(times)[len(times) // 2]

    done = sorted(engine_times)
    total_rows = sum(sum(row_counts[t] for t in QUERY_TABLES[q]) for q in done)
    total_t = sum(engine_times.values())
    speedups = [cpu_times[q] / engine_times[q] for q in done]
    geomean = float(np.exp(np.mean(np.log(speedups))))
    print(json.dumps({
        "metric": f"tpch_sf{SF:g}_q1_q3_q9_q18_rows_per_sec_per_chip",
        "value": round(total_rows / total_t),
        "unit": "rows/s",
        "vs_baseline": round(geomean, 3),
    }))


if __name__ == "__main__":
    main()
