"""Benchmark: TPC-H Q1 at SF1 on the local accelerator vs a CPU columnar baseline.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Protocol mirrors the reference's benchto macro setup (2 prewarm + timed runs, SURVEY.md §6:
testing/trino-benchto-benchmarks/.../tpch.yaml): value = Q1 input rows/sec on one chip,
vs_baseline = speedup over a numpy/pandas vectorized CPU evaluation of the same query on
the same generated data.
"""

import json
import time

import jax

jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

SF = float(__import__("os").environ.get("BENCH_SF", "1"))
Q1 = """
    select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
           sum(l_extendedprice) as sum_base_price,
           sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
           sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
           avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price,
           avg(l_discount) as avg_disc, count(*) as count_order
    from lineitem where l_shipdate <= date '1998-12-01' - interval '90' day
    group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus"""


def main():
    from trino_tpu import Engine
    from trino_tpu.connectors.tpch import TpchConnector

    conn = TpchConnector(sf=SF, split_rows=1 << 21)
    engine = Engine()
    engine.register_catalog("tpch", conn)
    session = engine.create_session("tpch")

    # input cardinality (generated lineitem rows)
    n_rows = 0
    for s in conn.splits("lineitem"):
        page = conn.generate(s, ["l_orderkey"])
        n_rows += int(np.asarray(page.num_rows()))

    # engine timing: 2 prewarm + 3 timed (median)
    for _ in range(2):
        engine.execute_sql(Q1, session)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        engine.execute_sql(Q1, session)
        times.append(time.perf_counter() - t0)
    engine_t = sorted(times)[1]

    # CPU baseline: vectorized numpy over the same columns (host-side)
    cols = ["l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
            "l_discount", "l_tax", "l_shipdate"]
    host = {c: [] for c in cols}
    for s in conn.splits("lineitem"):
        page = conn.generate(s, cols)
        valid = np.asarray(page.valid_mask())
        for c in cols:
            host[c].append(np.asarray(page.column(c))[valid])
    host = {c: np.concatenate(v) for c, v in host.items()}

    def cpu_q1():
        cutoff = (np.datetime64("1998-12-01") - np.timedelta64(90, "D")
                  - np.datetime64("1970-01-01")).astype(np.int64)
        m = host["l_shipdate"] <= cutoff
        rf, ls = host["l_returnflag"][m], host["l_linestatus"][m]
        qty, price = host["l_quantity"][m], host["l_extendedprice"][m]
        disc, tax = host["l_discount"][m], host["l_tax"][m]
        gid = rf * 2 + ls
        dp = price * (100 - disc)
        ch = dp * (100 + tax)
        out = []
        for g in np.unique(gid):
            mm = gid == g
            out.append((qty[mm].sum(), price[mm].sum(), dp[mm].sum(), ch[mm].sum(),
                        mm.sum()))
        return out

    cpu_q1()  # warm caches
    t0 = time.perf_counter()
    cpu_q1()
    cpu_t = time.perf_counter() - t0

    value = n_rows / engine_t
    print(json.dumps({
        "metric": f"tpch_sf{SF:g}_q1_rows_per_sec_per_chip",
        "value": round(value),
        "unit": "rows/s",
        "vs_baseline": round(cpu_t / engine_t, 3),
    }))


if __name__ == "__main__":
    main()
