"""RANGE offset frames + IGNORE NULLS navigation (round-3 VERDICT #9).

Reference test models: the frame tests of TestWindowOperator /
AbstractTestWindowFunction, incl. value-based RANGE bounds
(operator/window/FramedWindowFunction) and nullTreatment
(LagFunction/LeadFunction/NthValueFunction ignoreNulls)."""

import numpy as np
import pandas as pd
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.memory import MemoryConnector


@pytest.fixture(scope="module")
def weng():
    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table m (g bigint, d date, x bigint, v bigint)", s)
    import datetime

    rng = np.random.default_rng(11)
    rows = []
    day = 9000
    for g in range(3):
        for i in range(40):
            day += int(rng.integers(1, 4))
            dt = datetime.date(1970, 1, 1) + datetime.timedelta(days=day)
            x = int(rng.integers(0, 50))
            v = "null" if (i % 7 == 3) else int(rng.integers(-30, 100))
            rows.append(f"({g}, date '{dt.isoformat()}', {x}, {v})")
    e.execute_sql("insert into m values " + ", ".join(rows), s)
    df = e.execute_sql("select g, d, x, v from m", s).to_pandas()
    return e, s, df


def _oracle_range_sum(df, key, lo_k, hi_k):
    """Per-row sum of v over rows in the same g whose key value is within
    [key_i - lo_k, key_i + hi_k] (ascending order)."""
    out = []
    for _, r in df.iterrows():
        grp = df[df.g == r.g]
        kv = grp[key].to_numpy()
        sel = (kv >= getattr(r, key) - lo_k) & (kv <= getattr(r, key) + hi_k)
        vs = grp.v.to_numpy()[sel]
        vs = vs[~pd.isna(vs)]
        out.append(vs.sum() if len(vs) else None)
    return out


def test_range_offset_frame_bigint(weng):
    e, s, df = weng
    got = e.execute_sql(
        "select g, x, sum(v) over (partition by g order by x "
        "range between 5 preceding and 5 following) s "
        "from m order by g, x", s).to_pandas()
    ref = df.sort_values(["g", "x"], kind="stable").reset_index(drop=True)
    expect = _oracle_range_sum(ref, "x", 5, 5)
    assert len(got) == len(expect)
    for a, b in zip(got.s.tolist(), expect):
        if b is None:
            assert a is None or (isinstance(a, float) and np.isnan(a))
        else:
            assert a == b


def test_range_offset_frame_dates(weng):
    """Date ORDER BY key: offsets count days."""
    e, s, df = weng
    got = e.execute_sql(
        "select g, d, count(v) over (partition by g order by d "
        "range between 3 preceding and current row) c "
        "from m order by g, d", s).to_pandas()
    ref = df.sort_values(["g", "d"], kind="stable").reset_index(drop=True)
    if np.issubdtype(np.asarray(ref.d).dtype, np.integer):
        days = ref.d.astype(np.int64)  # raw epoch-day representation
    else:
        days = pd.to_datetime(ref.d).map(lambda t: t.toordinal())
    out = []
    for i, r in ref.iterrows():
        grp = (ref.g == r.g)
        sel = grp & (days >= days[i] - 3) & (days <= days[i])
        out.append(int((~pd.isna(ref.v[sel])).sum()))
    assert got.c.tolist() == out


def test_range_offset_frame_descending(weng):
    """DESC order: PRECEDING looks toward larger values."""
    e, s, df = weng
    got = e.execute_sql(
        "select g, x, min(x) over (partition by g order by x desc "
        "range 10 preceding) lo from m order by g, x desc", s).to_pandas()
    ref = df.sort_values(["g", "x"], ascending=[True, False],
                         kind="stable").reset_index(drop=True)
    out = []
    for i, r in ref.iterrows():
        sel = (ref.g == r.g) & (ref.x <= r.x + 10) & (ref.x >= r.x)
        out.append(int(ref.x[sel].min()))
    assert got.lo.tolist() == out


def test_lag_lead_ignore_nulls(weng):
    e, s, df = weng
    got = e.execute_sql(
        "select g, x, v, "
        "lag(v) ignore nulls over (partition by g order by x, d) l1, "
        "lag(v, 2) ignore nulls over (partition by g order by x, d) l2, "
        "lead(v) ignore nulls over (partition by g order by x, d) f1 "
        "from m order by g, x, d", s).to_pandas()
    ref = df.sort_values(["g", "x", "d"], kind="stable").reset_index(drop=True)
    for col, off, direction in (("l1", 1, -1), ("l2", 2, -1), ("f1", 1, 1)):
        for i, r in ref.iterrows():
            vals = []
            j = i
            grp = ref.g[i]
            while True:
                j += direction
                if j < 0 or j >= len(ref) or ref.g[j] != grp:
                    break
                if not pd.isna(ref.v[j]):
                    vals.append(ref.v[j])
                if len(vals) == off:
                    break
            want = vals[off - 1] if len(vals) >= off else None
            a = got[col][i]
            if want is None:
                assert pd.isna(a), (col, i)
            else:
                assert a == want, (col, i)


def test_first_last_nth_ignore_nulls(weng):
    e, s, df = weng
    got = e.execute_sql(
        "select g, x, v, "
        "first_value(v) ignore nulls over (partition by g order by x, d) fv, "
        "last_value(v) ignore nulls over (partition by g order by x, d "
        " rows between unbounded preceding and unbounded following) lv, "
        "nth_value(v, 2) ignore nulls over (partition by g order by x, d "
        " rows between unbounded preceding and current row) nv "
        "from m order by g, x, d", s).to_pandas()
    ref = df.sort_values(["g", "x", "d"], kind="stable").reset_index(drop=True)
    for g in sorted(ref.g.unique()):
        grp = ref[ref.g == g].reset_index()
        nn = grp.v.dropna()
        last_nn = nn.iloc[-1] if len(nn) else None
        rows = got[got.g == g].reset_index()
        # lv uses an explicit unbounded frame: last non-null of the partition
        assert all(a == last_nn for a in rows.lv)
        # fv/nv use the DEFAULT running frame: first/2nd non-null SO FAR
        seen = []
        for i in range(len(grp)):
            if not pd.isna(grp.v[i]):
                seen.append(grp.v[i])
            want_fv = seen[0] if seen else None
            want_nv = seen[1] if len(seen) >= 2 else None
            a, b = rows.fv[i], rows.nv[i]
            assert (pd.isna(a) if want_fv is None else a == want_fv), (g, i)
            assert (pd.isna(b) if want_nv is None else b == want_nv), (g, i)


def test_ignore_nulls_rejected_for_rankings(weng):
    e, s, _ = weng
    from trino_tpu.sql.frontend import SemanticError

    with pytest.raises(SemanticError, match="navigation"):
        e.execute_sql(
            "select row_number() ignore nulls over (order by x) from m", s)


def test_window_over_partially_filled_page():
    """A scan split that doesn't divide the row count leaves the materialized
    page with trailing INVALID rows; the window kernel must isolate them from
    real partitions (regression: pads joined whichever partition matched their
    fill values, inflating row_number by hundreds)."""
    from trino_tpu.connectors.tpch import TpchConnector

    e = Engine()
    conn = TpchConnector(sf=0.01, split_rows=2048)  # 15000 orders -> 8 ragged pages
    e.register_catalog("tpch", conn)
    s = e.create_session("tpch")
    got = e.execute_sql(
        """select o_custkey, o_orderkey,
                  row_number() over (partition by o_custkey
                    order by o_totalprice desc, o_orderkey) rn,
                  count(*) over (partition by o_custkey) cnt
           from orders order by o_custkey, o_orderkey""", s).to_pandas()
    # oracle from a full-page engine read (single split -> no pad rows)
    e2 = Engine()
    e2.register_catalog("tpch", TpchConnector(sf=0.01))
    s2 = e2.create_session("tpch")
    df = e2.execute_sql("select o_custkey, o_orderkey, o_totalprice from orders",
                        s2).to_pandas()
    df = df.sort_values(["o_totalprice", "o_orderkey"],
                        ascending=[False, True])
    df["rn"] = df.groupby("o_custkey").cumcount() + 1
    df["cnt"] = df.groupby("o_custkey")["o_orderkey"].transform("size")
    df = df.sort_values(["o_custkey", "o_orderkey"])
    np.testing.assert_array_equal(got["rn"].to_numpy(), df["rn"].to_numpy())
    np.testing.assert_array_equal(got["cnt"].to_numpy(), df["cnt"].to_numpy())
