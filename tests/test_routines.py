"""SQL routines (CREATE FUNCTION) + table functions (round-3 VERDICT missing
item; reference: sql/routine/SqlRoutineCompiler.java:108,
spi/function/table/ConnectorTableFunction.java)."""

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.sql.frontend import SemanticError


@pytest.fixture()
def eng():
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 12))
    return e, e.create_session("tpch")


def test_create_function_inline(eng):
    e, s = eng
    e.execute_sql("create function taxed(p double, r double) "
                  "returns double return p * (1 + r)", s)
    rows = e.execute_sql("select taxed(100.0, 0.1) t", s).rows()
    assert rows == [(pytest.approx(110.0),)]
    # routines work over columns and inside aggregations
    got = e.execute_sql(
        "select sum(taxed(o_totalprice, 0.05)) st from orders "
        "where o_orderkey < 100", s).rows()
    base = e.execute_sql(
        "select sum(o_totalprice * 1.05) st from orders "
        "where o_orderkey < 100", s).rows()
    assert got[0][0] == pytest.approx(base[0][0])


def test_function_composition_and_show(eng):
    e, s = eng
    e.execute_sql("create function twice(x bigint) returns bigint "
                  "return x * 2", s)
    e.execute_sql("create function quad(x bigint) returns bigint "
                  "return twice(twice(x))", s)
    assert e.execute_sql("select quad(3) q", s).rows() == [(12,)]
    fns = e.execute_sql("show functions", s).rows()
    routines = {r[0]: r for r in fns if r[1] == "routine"}
    assert set(routines) == {"twice", "quad"}
    # replace + drop
    e.execute_sql("create or replace function twice(x bigint) "
                  "returns bigint return x * 3", s)
    assert e.execute_sql("select twice(2) t", s).rows() == [(6,)]
    e.execute_sql("drop function quad", s)
    with pytest.raises(SemanticError, match="not supported"):
        e.execute_sql("select quad(1)", s)
    with pytest.raises(ValueError, match="does not exist"):
        e.execute_sql("drop function quad", s)
    e.execute_sql("drop function if exists quad", s)  # no-op


def test_function_errors(eng):
    e, s = eng
    e.execute_sql("create function f1(x bigint) returns bigint return x", s)
    with pytest.raises(ValueError, match="already exists"):
        e.execute_sql("create function f1(x bigint) returns bigint "
                      "return x", s)
    with pytest.raises(SemanticError, match="expects 1 arguments"):
        e.execute_sql("select f1(1, 2)", s)
    # recursion guard: a self-referential routine can't loop the planner
    e.execute_sql("create or replace function f1(x bigint) returns bigint "
                  "return f1(x)", s)
    with pytest.raises(SemanticError, match="recursion"):
        e.execute_sql("select f1(1)", s)


def test_table_function_sequence(eng):
    e, s = eng
    rows = e.execute_sql(
        "select * from table(sequence(1, 5))", s).rows()
    assert rows == [(1,), (2,), (3,), (4,), (5,)]
    rows = e.execute_sql(
        "select sum(n) sn from table(sequence(0, 100, 10)) as t (n)",
        s).rows()
    assert rows == [(550,)]
    # join against a real table
    rows = e.execute_sql(
        "select count(*) c from table(sequence(0, 4)) t(k), nation "
        "where t.k = n_regionkey", s).rows()
    assert rows == [(25,)]
    with pytest.raises(SemanticError, match="step must not be zero"):
        e.execute_sql("select * from table(sequence(1, 5, 0))", s)


def test_routine_param_coercion_and_builtin_conflict(eng):
    e, s = eng
    e.execute_sql("create function half(x double) returns double "
                  "return x / 2", s)
    # the bigint literal coerces to the declared double param: 2.5, not 2
    assert e.execute_sql("select half(5) h", s).rows() == [(2.5,)]
    with pytest.raises(ValueError, match="conflicts with a built-in"):
        e.execute_sql("create function abs(x bigint) returns bigint "
                      "return x + 1", s)
