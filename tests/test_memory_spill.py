"""Memory accounting and Grace-partitioned (spill-analog) fallbacks.

Reference test models: TestMemoryPools, the spilling join/aggregation tests
(io.trino.operator join/spilling, SpillableHashAggregationBuilder tests).
"""

import jax
import numpy as np
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.exec.local_executor import LocalExecutor
from trino_tpu.memory import (AggregatedMemoryContext, MemoryPool,
                              MemoryPoolExhaustedError)
from trino_tpu.sql.frontend import compile_sql


def test_memory_pool_reserve_free():
    pool = MemoryPool(max_bytes=1000)
    assert pool.try_reserve(600, "a")
    assert not pool.try_reserve(600, "b")
    pool.free(600, "a")
    assert pool.try_reserve(600, "b")
    with pytest.raises(MemoryPoolExhaustedError):
        pool.reserve(600, "c")
    info = pool.info()
    assert info["reserved"] == 600 and info["by_tag"]["b"] == 600


def test_memory_contexts_hierarchy():
    pool = MemoryPool(max_bytes=1000)
    root = AggregatedMemoryContext(pool=pool, tag="query")
    op1 = root.new_child("op1").new_local()
    op2 = root.new_child("op2").new_local()
    op1.set_bytes(300)
    op2.set_bytes(400)
    assert root.bytes == 700 and pool.reserved == 700
    assert not op2.try_set_bytes(800)  # would exceed the pool
    assert op2.bytes == 400
    op1.close()
    assert root.bytes == 400 and pool.reserved == 400


def _q(sql, pool_bytes=None):
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 12))
    s = e.create_session("tpch")
    plan = compile_sql(sql, e, s)
    pool = None if pool_bytes is None else MemoryPool(max_bytes=pool_bytes)
    ex = LocalExecutor(e.catalogs, memory_pool=pool)
    res = ex.execute(plan)
    return res.rows(), ex


def test_tiny_pool_join_matches_unlimited():
    sql = """select o_orderpriority, count(*) c from orders, lineitem
             where o_orderkey = l_orderkey and l_quantity < 2500
             group by o_orderpriority order by o_orderpriority"""
    full, _ = _q(sql)
    small, ex = _q(sql, pool_bytes=200_000)  # forces partitioned join + agg
    assert small == full


def test_tiny_pool_left_join_matches_unlimited():
    sql = """select count(*), count(o_orderkey) from orders
             left join customer on o_custkey = c_custkey and c_acctbal > 5000"""
    # left join keeps unmatched probe rows once across partitions
    full, _ = _q(sql)
    small, _ = _q(sql, pool_bytes=150_000)
    assert small == full


def test_tiny_pool_semi_join_matches_unlimited():
    sql = """select count(*) from lineitem
             where l_orderkey in (select o_orderkey from orders
                                  where o_totalprice > 20000000)"""
    full, _ = _q(sql)
    small, _ = _q(sql, pool_bytes=150_000)
    assert small == full


def test_parquet_join_spills_without_redecoding(tmp_path):
    """The round-3 done-criterion for the host-RAM spill tier: a join whose
    build exceeds an artificially small pool completes on PARQUET input, its
    EXPLAIN ANALYZE shows spill stats, and the file decodes exactly ONCE
    (the spill pass buffers transformed pages in host RAM instead of
    re-reading the source per partition)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from trino_tpu.connectors.parquet import ParquetConnector

    n = 20_000
    rng = np.random.default_rng(3)
    pq.write_table(pa.table({
        "k": pa.array(rng.integers(0, 5000, n), pa.int64()),
        "v": pa.array(rng.normal(size=n)),
    }), tmp_path / "facts.parquet", row_group_size=2048)
    pq.write_table(pa.table({
        "k": pa.array(np.arange(5000, dtype=np.int64)),
        "w": pa.array(np.arange(5000, dtype=np.int64) * 2),
    }), tmp_path / "dims.parquet", row_group_size=2048)

    def engine(pool):
        e = Engine()
        conn = ParquetConnector(str(tmp_path))
        e.register_catalog("pq", conn)
        s = e.create_session("pq")
        ex = LocalExecutor(e.catalogs, memory_pool=pool)
        return e, conn, s, ex

    sql = ("select count(*) c, sum(w) sw from facts, dims "
           "where facts.k = dims.k and v < 10")
    e, _, s, ex_full = engine(None)
    full = ex_full.execute(compile_sql(sql, e, s)).rows()

    e, conn, s, ex = engine(MemoryPool(max_bytes=60_000))
    generated = []
    orig = conn.generate
    conn.generate = lambda split, cols: (generated.append(split),
                                         orig(split, cols))[1]
    try:
        plan = compile_sql(sql, e, s)
        small = ex.execute(plan).rows()
    finally:
        del conn.generate
    assert small == full
    # exactly one decode per split: the spill pass never re-reads the file
    keys = [repr(sp) for sp in generated]
    assert len(keys) == len(set(keys)), "a parquet split was decoded twice"
    # the join node carries spill stats, and EXPLAIN ANALYZE would render them
    from trino_tpu.sql import plan as P
    from trino_tpu.sql.planprinter import format_plan

    joins = []

    def walk(nd):
        if isinstance(nd, P.Join):
            joins.append(nd)
        for c in nd.children:
            walk(c)

    walk(plan)
    spill_stats = [ex.stats.get(id(j)) for j in joins]
    assert any(st and st.get("spilled_bytes") for st in spill_stats)
    text = format_plan(plan, ex.stats)
    assert "spilled:" in text and "partitions]" in text


def test_group_by_spills_to_partitioned():
    # many groups + a pool too small for the hash table: partitioned passes
    sql = """select l_orderkey, count(*) c from lineitem
             group by l_orderkey order by c desc, l_orderkey limit 5"""
    full, _ = _q(sql)
    small, _ = _q(sql, pool_bytes=100_000)
    assert small == full


def test_query_max_memory_kills_query():
    """Per-query memory kill policy (reference: query.max-memory ->
    ExceededMemoryLimitException): exceeding the per-query limit fails the
    query hard, while the node pool merely triggers the Grace fallback."""
    import pytest

    from trino_tpu import Engine
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.memory import QueryMemoryLimitError

    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01))
    s = e.create_session("tpch")
    e.execute_sql("set session query_max_memory = 1024", s)  # 1KB: join must die
    with pytest.raises(QueryMemoryLimitError, match="query_max_memory"):
        e.execute_sql(
            "select count(*) c from lineitem, orders "
            "where l_orderkey = o_orderkey", s)
    # reset: the same query runs fine
    e.execute_sql("reset session query_max_memory", s)
    r = e.execute_sql(
        "select count(*) c from lineitem, orders "
        "where l_orderkey = o_orderkey", s).to_pandas()
    assert int(r.iloc[0, 0]) > 0


@pytest.mark.slow
def test_grace_aggregation_at_50m_groups():
    """Grace-partitioned aggregation at REAL size (round-4 verdict item 2:
    the spill tier was toy-verified): SF34 orders = 51M distinct o_orderkey
    groups, 1.5x the 2^25 on-device group-table ceiling, forcing the
    host-RAM partition router (reference: SpillableHashAggregationBuilder at
    spill scale).  Asserts group count exactness and that the partitioned
    strategy (not the in-core table) executed."""
    sf = 34
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=sf, split_rows=1 << 21))
    s = e.create_session("tpch")
    # count over the distinct-key aggregation: the inner GROUP BY carries
    # 51,000,000 groups through the Grace router; the outer count collapses
    # the result so the assertion never materializes 51M python rows
    plan = compile_sql(
        "select count(*) c, sum(n) rows_total from "
        "(select o_orderkey, count(*) n from orders group by o_orderkey)",
        e, s)
    ex = LocalExecutor(e.catalogs)
    rows = ex.execute(plan).rows()
    n_groups, n_rows = rows[0]
    assert n_groups == int(sf * 1_500_000), rows
    assert n_rows == int(sf * 1_500_000), rows  # o_orderkey is unique in orders
    spilled = [st for st in ex.stats.values()
               if st.get("spill_partitions")]
    assert spilled, "expected the Grace-partitioned aggregation to engage"
    assert spilled[0]["spill_partitions"] >= 4
    assert spilled[0].get("spilled_bytes", 0) > 0
