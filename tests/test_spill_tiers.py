"""The memory-pressure escalation ladder (round 11): tiered spill
(HBM -> host RAM -> disk), accounted, observable, fault-injectable and
leak-checked end to end.

Reference models: the spilling operators + MemoryRevokingScheduler +
FileSingleStreamSpiller (byte-identity of spilled vs in-memory execution),
ClusterMemoryManager's rung ordering (evict before kill), and the resource
groups' admission deferral.  The pressure scenario table lives in
execution/chaos_matrix.py (PRESSURE), shared with scripts/chaos.py so the
pinned contract and the on-device capture artifact cannot drift.
"""

import os
import threading

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.exec.local_executor import LocalExecutor
from trino_tpu.execution import faults
from trino_tpu.execution.bufferpool import DeviceBufferPool
from trino_tpu.execution.chaos_matrix import (PRESSURE, PRESSURE_QUERY,
                                              QUERIES, run_pressure_scenario)
from trino_tpu.execution.chaos_matrix import result_signature as _sig
from trino_tpu.memory import MemoryPool
from trino_tpu.sql.frontend import compile_sql

SF = 0.02
SPLIT_ROWS = 1 << 12


@pytest.fixture(scope="module")
def env():
    engine = Engine()
    engine.register_catalog("tpch",
                            TpchConnector(sf=SF, split_rows=SPLIT_ROWS))
    session = engine.create_session("tpch")
    plan = compile_sql(PRESSURE_QUERY, engine, session)
    # unconstrained baseline: a default-budget executor, same plan object
    base_ex = LocalExecutor(engine.catalogs)
    baseline = _sig(base_ex.execute(plan))
    yield engine, session, plan, baseline
    engine._invalidate()


# ----------------------------------------------------------- pressure matrix
@pytest.mark.parametrize("name", [s[0] for s in PRESSURE])
def test_pressure_scenario(env, name, tmp_path):
    """The chaos pressure matrix (acceptance): every forced tier is
    byte-identical to the unconstrained run, injected spill faults yield
    typed errors, and the extended leak check (spill files, "spill"-tag
    reservations, executor-held spills) passes after every scenario."""
    engine, _session, plan, baseline = env
    cfg, spec, kind = next((c, sp, k) for n, c, sp, k in PRESSURE
                           if n == name)
    scratch = tmp_path / "spill"
    scratch.mkdir()
    rec = run_pressure_scenario(engine, plan, baseline, name, cfg, spec,
                                kind, str(scratch))
    assert rec["ok"], rec


def test_forced_tiers_report_on_counters(env, tmp_path):
    """Tier forcing is visible, not just correct: the disk-forced run's
    per-query counters carry spilled_bytes attributed to the disk tier and
    zero to the others."""
    engine, _session, plan, _baseline = env
    scratch = tmp_path / "spill"
    scratch.mkdir()
    prev = os.environ.get("TRINO_TPU_SPILL_HOST_BYTES")
    os.environ["TRINO_TPU_SPILL_HOST_BYTES"] = "0"
    os.environ["TRINO_TPU_SPILL_DIR"] = str(scratch)
    try:
        ex = LocalExecutor(engine.catalogs,
                           memory_pool=MemoryPool(max_bytes=1 << 19),
                           buffer_pool=DeviceBufferPool(budget_bytes=0))
        ex.execute(plan)
        c = ex.counters
        assert c.spill_tier_disk > 0
        assert c.spill_tier_hbm == 0 and c.spill_tier_host == 0
        assert c.spilled_bytes == c.spill_tier_disk
        # site attribution: the spill landed under a named site
        assert any("spill.disk" in k for k in c.sites), sorted(c.sites)
        assert not os.listdir(scratch), "spill files survived the query"
    finally:
        os.environ.pop("TRINO_TPU_SPILL_DIR", None)
        if prev is None:
            os.environ.pop("TRINO_TPU_SPILL_HOST_BYTES", None)
        else:
            os.environ["TRINO_TPU_SPILL_HOST_BYTES"] = prev


def test_partitioned_join_spill_tiers_identity(env, tmp_path):
    """The Grace join's build+probe spill walks the same ladder: a tiny pool
    forces the partitioned join, results match the unconstrained run, tier
    stats land on the plan stats, and per-query host-tier reservations
    release (the persistent build side keeps its own "spill-build" tag)."""
    engine, session, _plan, _baseline = env
    os.environ["TRINO_TPU_SPILL_DIR"] = str(tmp_path)
    try:
        sql = """select o_orderpriority, count(*) c from orders, lineitem
                 where o_orderkey = l_orderkey group by o_orderpriority
                 order by o_orderpriority"""
        plan = compile_sql(sql, engine, session)
        full = _sig(LocalExecutor(engine.catalogs).execute(plan))
        ex = LocalExecutor(engine.catalogs,
                           memory_pool=MemoryPool(max_bytes=200_000))
        got = _sig(ex.execute(plan))
        assert got == full
        spilled = [st for st in ex.stats.values()
                   if st.get("spill_partitions")]
        assert spilled and any(st.get("spill_tiers") for st in spilled)
        ex.close_producers()
        tags = ex.memory_pool.info()["by_tag"]
        assert tags.get("spill", 0) == 0, tags
        # the PERSISTENT build spill may keep its disk partitions (it lives
        # with the cached stream, like the build cache; deliberately
        # unaccounted in the pool — plan-lifetime reservations would pin
        # blocked() true forever); evicting the plan's compiled artifacts —
        # the designed eviction path, since jax's global jit caches pin the
        # closure graph past any del/gc — must reclaim its files with it
        ex.forget_plan(plan)
        assert not ex._spills
        assert not [f for f in os.listdir(tmp_path)], \
            "build spill files survived forget_plan"
    finally:
        os.environ.pop("TRINO_TPU_SPILL_DIR", None)


def test_spill_error_mid_partition_cleans_up(env, tmp_path):
    """An error raised MID-SPILL (second disk chunk) unwinds clean: typed
    error, no orphaned file, no stranded reservation — the executor's
    exit-path sweep, not the consumer's finally, is what guarantees it when
    the traceback pins the generator frames."""
    engine, _session, plan, _baseline = env
    os.environ["TRINO_TPU_SPILL_DIR"] = str(tmp_path)
    os.environ["TRINO_TPU_SPILL_HOST_BYTES"] = "0"
    try:
        ex = LocalExecutor(engine.catalogs,
                           memory_pool=MemoryPool(max_bytes=1 << 19))
        with faults.injected(
                "point=spill_write,site=spill.disk,action=error,nth=3"
        ) as fplan:
            with pytest.raises(faults.InjectedFaultError):
                ex.execute(plan)
        assert fplan.total_fires() == 1
        ex.close_producers()
        assert not ex._spills
        assert ex.memory_pool.info()["by_tag"].get("spill", 0) == 0
        assert not os.listdir(tmp_path), "orphaned spill file"
    finally:
        os.environ.pop("TRINO_TPU_SPILL_DIR", None)
        os.environ.pop("TRINO_TPU_SPILL_HOST_BYTES", None)


# ------------------------------------------------------ observability surface
def test_explain_and_metrics_carry_spill_line(tmp_path):
    """Observability satellite: the EXPLAIN ANALYZE rendering grows a Spill
    line (+ per-node tier breakdown) when and only when the query spilled,
    and /v1/metrics exports the per-tier counters + the admission-queue
    counter once a spilling query ran through the engine."""
    import re

    from trino_tpu.server.server import CoordinatorServer
    from trino_tpu.sql.planprinter import format_plan

    os.environ["TRINO_TPU_SPILL_DIR"] = str(tmp_path)
    try:
        engine = Engine()
        engine.register_catalog(
            "tpch", TpchConnector(sf=SF, split_rows=SPLIT_ROWS))
        session = engine.create_session("tpch")
        plan = compile_sql(PRESSURE_QUERY, engine, session)
        # unconstrained: no Spill line
        ex = LocalExecutor(engine.catalogs)
        ex.execute(plan)
        text = format_plan(plan, ex.stats, counters=ex.counters,
                           boundary=ex.boundary)
        assert "Spill:" not in text and "[tiers:" not in text
        # spilled: the line + the per-node tier breakdown render
        ex = LocalExecutor(engine.catalogs,
                           memory_pool=MemoryPool(max_bytes=1 << 19))
        ex.execute(plan)
        text = format_plan(plan, ex.stats, counters=ex.counters,
                           boundary=ex.boundary)
        assert "Spill:" in text and "bytes" in text, text
        assert "[spilled:" in text and "[tiers:" in text, text
        # engine path: shrink the POOLED executors so a plain statement
        # spills, then scrape the metrics endpoint
        engine.execute_sql("select count(*) from nation", session)
        for pooled in engine._all_executors:
            pooled.memory_pool.max_bytes = 1 << 19
        engine.execute_sql(PRESSURE_QUERY, session)
        c = engine.last_query_counters
        assert c.spilled_bytes > 0
        mtext = CoordinatorServer(engine)._metrics_text()
        assert "# TYPE trino_tpu_spilled_bytes_total counter" in mtext
        m = {t: int(v) for t, v in re.findall(
            r'^trino_tpu_spilled_bytes_total\{tier="(\w+)"\} (\d+)$',
            mtext, re.M)}
        assert set(m) == {"hbm", "host", "disk"}
        assert sum(m.values()) >= c.spilled_bytes
        assert re.search(r"^trino_tpu_admission_queued_total \d+$", mtext,
                         re.M)
        engine._invalidate()
    finally:
        os.environ.pop("TRINO_TPU_SPILL_DIR", None)


# ---------------------------------------------------- admission (queue rung)
def test_admission_gate_queues_then_drains():
    """ResourceGroupManager's memory gate: with work running and the gate
    blocked, new submissions QUEUE (and the memory-queued callback fires);
    finish() re-drains once the gate clears; an idle tree always admits
    (no deadlock)."""
    from trino_tpu.execution.resourcegroups import (ResourceGroup,
                                                    ResourceGroupManager)

    blocked = {"v": False}
    mgr = ResourceGroupManager(admission_gate=lambda: not blocked["v"])
    g = mgr.get_or_create("global.alice")
    started, mem_queued = [], []
    # idle tree + blocked gate: still admits (nothing running to drain it)
    blocked["v"] = True
    mgr.submit(g, lambda: started.append("q1"),
               queued_on_memory=lambda: mem_queued.append("q1"))
    assert started == ["q1"] and not mem_queued
    # running + blocked: defer, and attribute the deferral to memory
    mgr.submit(g, lambda: started.append("q2"),
               queued_on_memory=lambda: mem_queued.append("q2"))
    assert started == ["q1"] and mem_queued == ["q2"]
    assert mgr.memory_queued_total == 1
    # finish with the gate still blocked: q1 was the last runner, so the
    # tree is idle and the drain admits q2 anyway (progress guarantee)
    mgr.finish(g)
    assert started == ["q1", "q2"]
    mgr.finish(g)


def test_engine_defers_admission_under_pool_pressure():
    """Engine-level rung: with an executor pool blocked and a query running,
    a second statement queues (admission_queued lands on its counters and
    the engine totals) and completes once the pressure clears."""
    import time

    from trino_tpu.execution.memory_killer import BLOCKED_FRACTION

    engine = Engine()
    engine.register_catalog("tpch",
                            TpchConnector(sf=0.01, split_rows=1 << 11))
    session = engine.create_session("tpch")
    engine.execute_sql("select count(*) from nation", session)  # warm pool
    before = engine.counters_total.admission_queued
    ex = engine._all_executors[0]
    hog = int(ex.memory_pool.max_bytes * (BLOCKED_FRACTION + 0.05))
    assert ex.memory_pool.try_reserve(hog, "test-hog")
    group = engine.resource_groups.get_or_create("global.holder")
    engine.resource_groups.submit(group, lambda: None)  # a "running" query
    try:
        done = {}

        def run():
            done["r"] = engine.execute_sql(
                "select count(*) from nation", session)

        t = threading.Thread(target=run)
        t.start()
        # the statement must be QUEUED, not running: give it a beat
        deadline = time.time() + 5
        while time.time() < deadline \
                and engine.resource_groups.memory_queued_total == 0:
            time.sleep(0.01)
        assert engine.resource_groups.memory_queued_total == 1
        assert "r" not in done
        # pressure clears -> the holder finishes -> the queue drains
        ex.memory_pool.free(hog, "test-hog")
        engine.resource_groups.finish(group)
        t.join(timeout=30)
        assert not t.is_alive() and len(done["r"]) == 1
        assert engine.counters_total.admission_queued == before + 1
        assert engine.last_query_counters.admission_queued == 1
    finally:
        engine._invalidate()


# ------------------------------------------------- cluster rungs (pre-kill)
def test_coordinator_walks_evict_rung_before_kill(tmp_path):
    """The cluster killer's ladder order: a blocked node gets one debounce
    beat, then a cache-evict request, and only on the THIRD consecutive
    blocked pass does the policy pick a victim — with both rungs recorded
    (pressure_events order, per-query rung for the victim)."""
    from trino_tpu.server.cluster import ClusterCoordinator

    coord = ClusterCoordinator(Engine(), str(tmp_path / "spool"))
    coord._announce("w0", "http://127.0.0.1:1")  # unreachable: posts no-op
    w = coord.workers["w0"]
    w.mem_reserved, w.mem_max = 95, 100
    w.mem_by_query = {"hog": 90}
    coord._run_memory_killer()  # streak 1: debounce
    assert coord.oom_kills == 0 and not coord.pressure_events
    coord._run_memory_killer()  # streak 2: evict rung
    assert coord.oom_kills == 0
    assert [e["rung"] for e in coord.pressure_events] == ["evict-cache"]
    coord._run_memory_killer()  # streak 3: kill rung
    assert coord.oom_kills == 1 and coord.last_oom_victim == "hog"
    assert [e["rung"] for e in coord.pressure_events] == \
        ["evict-cache", "kill"]
    assert coord.query_pressure_rung["hog"] == "kill"
    # recovery resets the ladder
    w.mem_reserved = 10
    coord._run_memory_killer()
    assert coord._blocked_streak == 0


def test_worker_sheds_cache_then_refuses(tmp_path):
    """Worker admission rung: a memory-blocked worker evicts its buffer
    pool, counts the denial, and refuses the task (the coordinator
    re-offers elsewhere)."""
    from trino_tpu.server.cluster import WorkerServer, _WorkerBusy

    w = WorkerServer({"tpch": {"connector": "tpch", "sf": 0.01}},
                     str(tmp_path / "spool"))
    w.fragments["f0"] = object()  # never executed: admission refuses first
    w.memory_pool.reserved = int(w.memory_pool.max_bytes * 0.95)
    with pytest.raises(_WorkerBusy):
        w._start_task({"task_id": "t0", "fragment_id": "f0"})
    assert w.admission_denials == 1
    w.memory_pool.reserved = 0


# ----------------------------------------------------------- counters plumb
def test_spill_counters_merge_and_roundtrip():
    """The new fields ride every counter flow: merge, dict round-trip (the
    worker->coordinator wire shape), and snapshot."""
    from trino_tpu.execution.tracing import QueryCounters, record_spill, \
        track_counters

    c = QueryCounters()
    with track_counters(c):
        record_spill("host", 100)
        record_spill("disk", 50)
    assert (c.spilled_bytes, c.spill_tier_host, c.spill_tier_disk) == \
        (150, 100, 50)
    assert any(v.get("spilled_bytes") for v in c.sites.values())
    d = QueryCounters.from_dict(c.as_dict())
    assert d.spilled_bytes == 150 and d.spill_tier_disk == 50
    m = QueryCounters()
    m.merge(c)
    m.merge(d)
    assert m.spilled_bytes == 300 and m.spill_tier_host == 200
    m.admission_queued += 1
    assert QueryCounters.from_dict(m.as_dict()).admission_queued == 1


# ------------------------------------------------------------------ at scale
@pytest.mark.slow
def test_q18_crosses_all_tiers_byte_identical(tmp_path):
    """Acceptance at real shape: TPC-H q18 (SF0.1) with the pool forced down
    and tiny tier budgets crosses hbm AND host AND disk in one query, and
    the result is byte-identical to the unconstrained run."""
    engine = Engine()
    engine.register_catalog("tpch",
                            TpchConnector(sf=0.1, split_rows=1 << 16))
    session = engine.create_session("tpch")
    plan = compile_sql(QUERIES["q18"], engine, session)
    baseline = _sig(LocalExecutor(engine.catalogs).execute(plan))
    os.environ["TRINO_TPU_SPILL_DIR"] = str(tmp_path)
    os.environ["TRINO_TPU_SPILL_HOST_BYTES"] = str(96 << 10)
    try:
        ex = LocalExecutor(engine.catalogs,
                           memory_pool=MemoryPool(max_bytes=1 << 20),
                           buffer_pool=DeviceBufferPool(
                               budget_bytes=128 << 10))
        got = _sig(ex.execute(plan))
        assert got == baseline
        c = ex.counters
        assert c.spill_tier_hbm > 0, c.as_dict()
        assert c.spill_tier_host > 0, c.as_dict()
        assert c.spill_tier_disk > 0, c.as_dict()
        ex.close_producers()
        assert ex.memory_pool.info()["by_tag"].get("spill", 0) == 0
        # the partitioned join's persistent build spill lives with the
        # compiled stream; evicting the plan reclaims its files too
        ex.forget_plan(plan)
        assert not os.listdir(tmp_path)
    finally:
        os.environ.pop("TRINO_TPU_SPILL_DIR", None)
        os.environ.pop("TRINO_TPU_SPILL_HOST_BYTES", None)
        engine._invalidate()
