"""Plan templates (round 13): compile once, bind constants per request.

Covers the acceptance surface of the parameterized-template path:

- warm EXECUTE parity vs the cold substitution path across every bindable
  literal type (ints, decimals incl. negatives, doubles, dictionary strings,
  dates, timestamps, IN-lists of fixed arity, NULL bindings);
- the zero-replanning claim, counter/span-verified: a warm EXECUTE records a
  plan_template_hit, opens NO planner span, and spends exactly the same warm
  dispatch count as the equivalent inline statement (templates change what
  happens BEFORE dispatch, not how many dispatches);
- bindability fallbacks: a LIMIT parameter (plan-shaping) stays on the
  substitution path byte-identically; binding-specific impossibilities
  (type-width overflow) fall back per execution while the template survives;
- auto-parameterization: ad-hoc point SELECTs identical up to constants
  share one template without opting in;
- the result-cache interplay: template executions key on (template,
  bound values) — two bindings never share an entry — and volatility is
  tested on the TEMPLATE text, so a bound string containing "random(" still
  caches;
- plan-cache key normalization: comment/whitespace-reformatted repeats of
  one statement stop re-planning;
- concurrent EXECUTE of one template from multiple sessions;
- typed errors for unsupported EXECUTE parameter AST kinds, DDL
  invalidation, and the observability wiring (EXPLAIN ANALYZE / EXPLAIN
  EXECUTE lines, /v1/metrics series, protocol-level parameters).
"""

import threading

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.execution.chaos_matrix import result_signature as _sig

SF, SPLIT_ROWS = 0.01, 1 << 14


@pytest.fixture(scope="module")
def tpch_conn():
    return TpchConnector(sf=SF, split_rows=SPLIT_ROWS)


@pytest.fixture()
def eng(tpch_conn, monkeypatch):
    """Template-enabled engine, result/page tiers off (the template win must
    be measured on the execute path, not the result tier)."""
    from trino_tpu.connectors.memory import MemoryConnector

    monkeypatch.setenv("TRINO_TPU_RESULT_CACHE", "0")
    monkeypatch.setenv("TRINO_TPU_PAGE_CACHE", "0")
    e = Engine()
    e.register_catalog("tpch", tpch_conn)
    e.register_catalog("mem", MemoryConnector())
    return e


@pytest.fixture()
def baseline(tpch_conn, monkeypatch):
    """Substitution-only engine: the parity oracle for every template run."""
    from trino_tpu.connectors.memory import MemoryConnector

    monkeypatch.setenv("TRINO_TPU_RESULT_CACHE", "0")
    monkeypatch.setenv("TRINO_TPU_PAGE_CACHE", "0")
    e = Engine()
    e.plan_templates_enabled = False
    e.register_catalog("tpch", tpch_conn)
    e.register_catalog("mem", MemoryConnector())
    return e


def _span_names(engine):
    trace = engine._thread_accounting.trace or {}
    return [s.get("name") for s in trace.get("spans", ())]


def _prepared_pair(eng, baseline, text):
    s1, s2 = eng.create_session("tpch"), baseline.create_session("tpch")
    eng.execute_sql(f"prepare p from {text}", s1)
    baseline.execute_sql(f"prepare p from {text}", s2)
    return s1, s2


def _assert_parity(eng, baseline, s1, s2, stmt):
    a = eng.execute_sql(stmt, s1)
    b = baseline.execute_sql(stmt, s2)
    assert _sig(a) == _sig(b), f"template/substitution mismatch for {stmt}"
    return a


# ---------------------------------------------------------------- parity
@pytest.mark.parametrize("text,bindings", [
    # integer key (the point-lookup shape)
    ("select c_name, c_acctbal from customer where c_custkey = ?",
     ["42", "97", "1", "null"]),
    # decimal comparisons, incl. negative decimals (round-13 satellite).
    # The FIRST binding types the template's decimal scale; later bindings
    # share it (a scale-mismatched binding would fall back, also correct,
    # but this case pins the template path)
    ("select c_custkey from customer where c_acctbal < ? "
     "order by c_custkey limit 5",
     ["0.00", "-123.45", "9999.99"]),
    # double arithmetic in a projection
    ("select c_custkey, c_acctbal * ? from customer "
     "where c_custkey < ? order by c_custkey limit 4",
     ["1.5e0, 10", "0.25e1, 7"]),
    # dictionary string equality (id resolved at BIND time)
    ("select c_custkey from customer where c_mktsegment = ? "
     "order by c_custkey limit 6",
     ["'BUILDING'", "'AUTOMOBILE'", "'no-such-segment'", "null"]),
    # dictionary string inequality
    ("select c_custkey from customer where c_mktsegment <> ? "
     "order by c_custkey limit 3",
     ["'BUILDING'", "'MACHINERY'"]),
    # date comparison
    ("select o_orderkey from orders where o_orderdate < ? "
     "order by o_orderkey limit 5",
     ["date '1995-03-15'", "date '1992-06-01'"]),
    # IN-list of fixed arity (ints and strings)
    ("select c_custkey from customer where c_custkey in (?, ?, ?) "
     "order by c_custkey",
     ["3, 5, 7", "10, 11, 12"]),
    ("select c_custkey from customer where c_mktsegment in (?, ?) "
     "order by c_custkey limit 4",
     ["'BUILDING', 'MACHINERY'", "'AUTOMOBILE', 'HOUSEHOLD'"]),
    # BETWEEN bounds (decimal-typed first binding so later ones share it)
    ("select c_custkey from customer where c_acctbal between ? and ? "
     "order by c_custkey limit 5",
     ["100.0, 500.0", "-100.5, 50"]),
])
def test_warm_execute_parity(eng, baseline, text, bindings):
    s1, s2 = _prepared_pair(eng, baseline, text)
    for i, b in enumerate(bindings):
        _assert_parity(eng, baseline, s1, s2, f"execute p using {b}")
        if i >= 1:
            # past creation, every EXECUTE must ride the template
            assert eng.last_query_counters.plan_template_hits == 1, \
                f"binding {b} did not hit the template"


def test_timestamp_parameter(eng, baseline):
    sessions = {}
    for e in (eng, baseline):
        s = e.create_session("mem")
        e.execute_sql("create table ts_t (id bigint, ts timestamp(3))", s)
        e.execute_sql(
            "insert into ts_t values (1, timestamp '2020-01-01 00:00:00'), "
            "(2, timestamp '2020-06-01 12:30:00'), "
            "(3, timestamp '2021-01-01 00:00:00')", s)
        e.execute_sql(
            "prepare p from select id from ts_t where ts < ? order by id", s)
        sessions[id(e)] = s
    s1, s2 = sessions[id(eng)], sessions[id(baseline)]
    for b in ["timestamp '2020-06-01 12:30:00'",
              "timestamp '2022-01-01 00:00:00'"]:
        _assert_parity(eng, baseline, s1, s2, f"execute p using {b}")
    assert eng.last_query_counters.plan_template_hits == 1


# ------------------------------------------------- zero-replanning claims
def test_warm_execute_no_planner_span_and_dispatch_parity(eng, baseline):
    text = "select c_name, c_acctbal from customer where c_custkey = ?"
    s1 = eng.create_session("tpch")
    eng.execute_sql(f"prepare p from {text}", s1)
    eng.execute_sql("execute p using 42", s1)  # creation
    eng.execute_sql("execute p using 97", s1)  # warm
    c = eng.last_query_counters
    assert c.plan_template_hits == 1
    assert c.plan_template_misses == 0
    assert "planner" not in _span_names(eng), \
        "warm EXECUTE must perform zero plan work"

    # dispatch parity: the warm template EXECUTE spends exactly what the
    # equivalent warm inline statement spends (templates change what happens
    # BEFORE dispatch, not how many dispatches) — same binding on both sides
    # so data-dependent steps (compaction) match too
    s2 = baseline.create_session("tpch")
    inline = "select c_name, c_acctbal from customer where c_custkey = 97"
    baseline.execute_sql(inline, s2)
    baseline.execute_sql(inline, s2)  # warm inline run
    warm_inline = baseline.last_query_counters.device_dispatches
    eng.execute_sql("execute p using 97", s1)
    assert eng.last_query_counters.device_dispatches == warm_inline


def test_warm_auto_param_no_planner_span(eng, baseline):
    tmpl = "select c_name from customer where c_custkey = {}"
    s1 = eng.create_session("tpch")
    eng.execute_sql(tmpl.format(10), s1)  # creates the template
    for k in (20, 30):
        a = eng.execute_sql(tmpl.format(k), s1)
        s2 = baseline.create_session("tpch")
        b = baseline.execute_sql(tmpl.format(k), s2)
        assert _sig(a) == _sig(b)
        assert eng.last_query_counters.plan_template_hits == 1
        assert "planner" not in _span_names(eng)


def test_identical_repeat_spends_zero_plan_work(eng):
    """An EXACT repeat of an auto-parameterized statement serves through the
    template with zero parse/analyze/plan work (the first execution created
    the template, so the plain plan cache never saw the text)."""
    sql = "select c_name from customer where c_custkey = 77"
    s = eng.create_session("tpch")
    eng.execute_sql(sql, s)
    eng.execute_sql(sql, s)
    c = eng.last_query_counters
    assert c.plan_template_hits == 1
    assert "planner" not in _span_names(eng)


# ------------------------------------------------------------- fallbacks
def test_limit_parameter_falls_back_byte_identical(eng, baseline):
    text = "select c_custkey from customer order by c_custkey limit ?"
    s1, s2 = _prepared_pair(eng, baseline, text)
    for b in ("3", "7"):
        a = _assert_parity(eng, baseline, s1, s2, f"execute p using {b}")
        assert len(a) == int(b)
        # plan-shaping parameter: never a template hit
        assert eng.last_query_counters.plan_template_hits == 0


def test_typewidth_overflow_falls_back_then_template_survives(eng, baseline):
    text = ("select c_custkey from customer where c_custkey = ? "
            "or c_custkey + ? < 0")
    s1, s2 = _prepared_pair(eng, baseline, text)
    _assert_parity(eng, baseline, s1, s2, "execute p using 5, 1")
    # 2^40 exceeds the template's INTEGER slot: this binding substitutes...
    _assert_parity(eng, baseline, s1, s2,
                   "execute p using 5, 1099511627776")
    assert eng.last_query_counters.plan_template_hits == 0
    # ...but the template still serves in-range bindings afterwards
    _assert_parity(eng, baseline, s1, s2, "execute p using 9, 2")
    assert eng.last_query_counters.plan_template_hits == 1


def test_aggregate_statement_falls_back(eng, baseline):
    text = ("select count(*) c from customer where c_mktsegment = ?")
    s1, s2 = _prepared_pair(eng, baseline, text)
    for b in ("'BUILDING'", "'MACHINERY'"):
        _assert_parity(eng, baseline, s1, s2, f"execute p using {b}")
        assert eng.last_query_counters.plan_template_hits == 0


def test_unsupported_parameter_kind_typed_error(eng):
    s = eng.create_session("tpch")
    eng.execute_sql(
        "prepare p from select c_custkey from customer where c_custkey = ?",
        s)
    with pytest.raises(ValueError, match="parameter"):
        eng.execute_sql("execute p using c_custkey + 1", s)


def test_arity_mismatch_raises(eng):
    s = eng.create_session("tpch")
    eng.execute_sql(
        "prepare p from select c_custkey from customer where c_custkey = ?",
        s)
    with pytest.raises(Exception, match="parameter"):
        eng.execute_sql("execute p using 1, 2", s)
    with pytest.raises(Exception, match="parameter"):
        eng.execute_sql("execute p", s)


# ----------------------------------------------------------- concurrency
def test_concurrent_execute_two_sessions(eng, baseline):
    text = ("select c_name, c_acctbal from customer where c_custkey = ?")
    s0 = eng.create_session("tpch")
    eng.execute_sql(f"prepare p from {text}", s0)
    eng.execute_sql("execute p using 1", s0)  # create + confirm

    keys = list(range(1, 41))
    sref = baseline.create_session("tpch")
    expected = {}
    for k in keys:
        expected[k] = _sig(baseline.execute_sql(
            text.replace("?", str(k)), sref))

    errors: list = []

    def worker(offset):
        sess = eng.create_session("tpch")
        eng.execute_sql(f"prepare p from {text}", sess)
        for k in keys[offset::2]:
            try:
                got = eng.execute_sql(f"execute p using {k}", sess)
                if _sig(got) != expected[k]:
                    errors.append(f"mismatch at {k}")
            except Exception as e:  # noqa: BLE001
                errors.append(f"{k}: {e!r}")

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors


# ------------------------------------------------- result-cache interplay
def _result_engine(tpch_conn, monkeypatch):
    monkeypatch.setenv("TRINO_TPU_RESULT_CACHE", str(64 << 20))
    monkeypatch.setenv("TRINO_TPU_PAGE_CACHE", "0")
    e = Engine()
    e.register_catalog("tpch", tpch_conn)
    return e


def test_result_cache_entries_are_binding_specific(tpch_conn, monkeypatch):
    e = _result_engine(tpch_conn, monkeypatch)
    s = e.create_session("tpch")
    text = "select c_name, c_acctbal from customer where c_custkey = ?"
    e.execute_sql(f"prepare p from {text}", s)
    a1 = e.execute_sql("execute p using 42", s)
    b1 = e.execute_sql("execute p using 97", s)
    assert _sig(a1) != _sig(b1), "distinct bindings must differ (test data)"
    # repeats serve from the result tier, each from ITS OWN entry
    a2 = e.execute_sql("execute p using 42", s)
    assert e.last_query_counters.result_cache_hits == 1
    assert e.last_query_counters.device_dispatches == 0
    b2 = e.execute_sql("execute p using 97", s)
    assert e.last_query_counters.result_cache_hits == 1
    assert _sig(a2) == _sig(a1)
    assert _sig(b2) == _sig(b1)


def test_volatile_check_on_template_text_not_binding(tpch_conn, monkeypatch):
    """A bound string containing 'random(' must not disqualify caching —
    volatility is tested on the TEMPLATE text, where values are markers."""
    e = _result_engine(tpch_conn, monkeypatch)
    s = e.create_session("tpch")
    text = ("select c_custkey from customer where c_mktsegment = ? "
            "order by c_custkey limit 3")
    e.execute_sql(f"prepare p from {text}", s)
    stmt = "execute p using 'random() now() uuid()'"
    e.execute_sql(stmt, s)
    e.execute_sql(stmt, s)
    assert e.last_query_counters.result_cache_hits == 1
    # while a template whose TEXT is volatile never caches (now() folds at
    # plan time, so only the text can reveal it)
    e.execute_sql("prepare pv from select c_custkey from customer "
                  "where c_custkey = ? and now() is not null", s)
    e.execute_sql("execute pv using 5", s)
    e.execute_sql("execute pv using 5", s)
    assert e.last_query_counters.result_cache_hits == 0


# ------------------------------------------- plan-cache key normalization
def test_plan_cache_key_normalization(eng):
    s = eng.create_session("tpch")
    a = eng.execute_sql(
        "select c_name from customer where c_custkey = 123454321", s)
    # same statement, reformatted + commented: must reuse the cached plan
    b = eng.execute_sql(
        "select  c_name\n  from customer   -- trailing comment\n"
        " where /* block\n comment */ c_custkey =     123454321", s)
    assert _sig(a) == _sig(b)
    assert "planner" not in _span_names(eng), \
        "reformatted repeat of a cached statement must not re-plan"


# ------------------------------------------------------------ lifecycle
def test_ddl_invalidates_templates(eng, baseline):
    sessions = {}
    for e in (eng, baseline):
        s = e.create_session("mem")
        e.execute_sql("create table inv_t (k bigint, v double)", s)
        e.execute_sql("insert into inv_t values (1, 1.5), (2, 2.5)", s)
        e.execute_sql("prepare p from select v from inv_t where k = ?", s)
        sessions[id(e)] = s
    s1, s2 = sessions[id(eng)], sessions[id(baseline)]
    _assert_parity(eng, baseline, s1, s2, "execute p using 1")
    for e, sess in ((eng, s1), (baseline, s2)):
        e.execute_sql("insert into inv_t values (3, 9.5)", sess)
    # the INSERT invalidated the template cache: a stale template would miss
    # row 3; the re-created one must see it
    got = _assert_parity(eng, baseline, s1, s2, "execute p using 3")
    assert len(got) == 1


def test_null_first_binding_does_not_poison_template(eng, baseline):
    text = "select c_name from customer where c_custkey = ?"
    s1, s2 = _prepared_pair(eng, baseline, text)
    # NULL first: typed UNKNOWN — substitution fallback, no negative cache
    _assert_parity(eng, baseline, s1, s2, "execute p using null")
    # a later non-NULL binding still creates the template
    _assert_parity(eng, baseline, s1, s2, "execute p using 7")
    _assert_parity(eng, baseline, s1, s2, "execute p using 8")
    assert eng.last_query_counters.plan_template_hits == 1
    # and NULL now binds against the typed template at runtime
    got = _assert_parity(eng, baseline, s1, s2, "execute p using null")
    assert len(got) == 0


def test_bind_time_split_pruning(monkeypatch):
    """A parameterized point predicate prunes splits per EXECUTION from the
    bound values — without it, the template path would scan every split on
    exactly the shape templates exist to serve (review finding)."""
    monkeypatch.setenv("TRINO_TPU_RESULT_CACHE", "0")
    monkeypatch.setenv("TRINO_TPU_PAGE_CACHE", "0")
    conn = TpchConnector(sf=0.05, split_rows=1 << 10)  # ~8 customer splits
    e = Engine()
    e.register_catalog("tpch", conn)
    b = Engine()
    b.plan_templates_enabled = False
    b.register_catalog("tpch", conn)
    s1, s2 = e.create_session("tpch"), b.create_session("tpch")
    text = "select c_name, c_acctbal from customer where c_custkey = ?"
    e.execute_sql(f"prepare p from {text}", s1)
    b.execute_sql(f"prepare p from {text}", s2)
    e.execute_sql("execute p using 42", s1)  # creation
    got = e.execute_sql("execute p using 7000", s1)
    want = b.execute_sql("execute p using 7000", s2)
    assert _sig(got) == _sig(want) and len(got) == 1
    assert e.last_query_counters.plan_template_hits == 1
    # warm substitution run prunes statically; the template's bind-time
    # pruning must land on the SAME dispatch count for the same binding
    b.execute_sql("execute p using 7000", s2)
    assert e.last_query_counters.device_dispatches == \
        b.last_query_counters.device_dispatches


def test_question_mark_inside_comment_substitutes(eng, baseline):
    """The substitution fallback must not treat a '?' inside a comment as a
    marker (the parser lexes comments away, so marker counts must agree)."""
    text = ("select count(*) c from customer "
            "where c_custkey > ? -- really?")
    s1, s2 = _prepared_pair(eng, baseline, text)
    # aggregate shape: both engines take the substitution path
    a = eng.execute_sql("execute p using 1400", s1)
    b = baseline.execute_sql("execute p using 1400", s2)
    assert _sig(a) == _sig(b)


def test_volatile_statement_never_templates(eng):
    """now()/current_date fold to plan-time constants: a template would
    serve the FIRST execution's fold frozen to every later binding, so
    volatile texts must reject at creation (each distinct statement
    re-plans and re-folds)."""
    s = eng.create_session("tpch")
    for k in (1, 2, 3):
        eng.execute_sql(
            f"select now(), c_name from customer where c_custkey = {k}", s)
        c = eng.last_query_counters
        assert c.plan_template_hits == 0, \
            "volatile statement must never serve from a template"
    # the prepared form rejects too
    eng.execute_sql("prepare pv from "
                    "select now(), c_name from customer "
                    "where c_custkey = ?", s)
    eng.execute_sql("execute pv using 5", s)
    eng.execute_sql("execute pv using 6", s)
    assert eng.last_query_counters.plan_template_hits == 0


def test_illtyped_binding_does_not_poison_other_kinds(eng, baseline):
    """The negative cache is scoped to the literal KINDS that failed: an
    ill-typed numeric comparison against a string column must not demote the
    well-typed string form that shares the same template text."""
    s1 = eng.create_session("tpch")
    # ill-typed ad-hoc statement (auto-parameterizes to c_mktsegment = ?)
    with pytest.raises(Exception):
        eng.execute_sql(
            "select c_custkey from customer where c_mktsegment = 5 "
            "order by c_custkey limit 3", s1)
    # the well-typed string form of the SAME template text still templates
    tmpl = ("select c_custkey from customer where c_mktsegment = '{}' "
            "order by c_custkey limit 3")
    s2 = baseline.create_session("tpch")
    eng.execute_sql(tmpl.format("BUILDING"), s1)
    a = eng.execute_sql(tmpl.format("MACHINERY"), s1)
    b = baseline.execute_sql(tmpl.format("MACHINERY"), s2)
    assert _sig(a) == _sig(b)
    assert eng.last_query_counters.plan_template_hits == 1


def test_protocol_float_parameter_stays_double(eng, baseline):
    """A python float protocol parameter must type DOUBLE on both the
    template and substitution paths (a bare '2.5' literal would re-parse as
    decimal(2,1) and compute in exact scaled-int, diverging by an ulp)."""
    sql = ("select c_custkey, c_acctbal * ? from customer "
           "where c_custkey < ? order by c_custkey limit 3")
    s1, s2 = eng.create_session("tpch"), baseline.create_session("tpch")
    a = eng.execute_sql(sql, s1, parameters=[2.5, 10])
    b = baseline.execute_sql(sql, s2, parameters=[2.5, 10])
    assert a.types[1].name == "double"
    assert b.types[1].name == "double"
    assert _sig(a) == _sig(b)


# --------------------------------------------------------- observability
def test_explain_surfaces(eng):
    s = eng.create_session("tpch")
    text = "select c_name from customer where c_custkey = ?"
    eng.execute_sql(f"prepare p from {text}", s)
    plan0 = "\n".join(r[0] for r in
                      eng.execute_sql("explain execute p", s).rows())
    assert "not yet created" in plan0
    eng.execute_sql("execute p using 3", s)
    plan1 = "\n".join(r[0] for r in
                      eng.execute_sql("explain execute p", s).rows())
    assert "Plan template: cached" in plan1
    assert "TableScan" in plan1
    analyzed = "\n".join(r[0] for r in eng.execute_sql(
        "explain analyze execute p using 5", s).rows())
    assert "Plan template: 1 hits" in analyzed


def test_protocol_parameters_and_metrics(eng):
    from trino_tpu.server.client import Client
    from trino_tpu.server.server import CoordinatorServer

    server = CoordinatorServer(eng, port=0)
    server.start()
    try:
        client = Client(server.url, catalog="tpch", poll_interval=0.002)
        sql = "select c_name, c_acctbal from customer where c_custkey = ?"
        r1 = client.execute(sql, params=[42])
        r2 = client.execute(sql, params=[97])
        assert r1.rows and r2.rows and r1.rows != r2.rows
        assert r1.rows[0][0] == "Customer#000000042"
        r3 = client.execute(sql, params=[42])
        assert r3.rows == r1.rows
        import urllib.request

        with urllib.request.urlopen(server.url + "/v1/metrics") as resp:
            body = resp.read().decode()
        assert "trino_tpu_plan_template_hits_total" in body
        hits = [line for line in body.splitlines()
                if line.startswith("trino_tpu_plan_template_hits_total")]
        assert hits and int(hits[0].split()[-1]) >= 1
    finally:
        server.stop()
