"""TPC-DS query breadth, round 5 (VERDICT r4 item 5): the correlated-subquery,
CASE-pivot, window-rank, and channel-overlap shapes of the remaining corpus,
each against a pandas oracle over the same generated data.  Reference corpus:
testing/trino-benchmark-queries/ + plugin/trino-tpcds query suite."""

import numpy as np
import pandas as pd
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpcds import TpcdsConnector

from test_tpcds2 import _table  # shared host-side oracle loader

SF = 0.01


@pytest.fixture(scope="module")
def eng():
    e = Engine()
    e.register_catalog("tpcds", TpcdsConnector(sf=SF, split_rows=1 << 14))
    return e, e.create_session("tpcds")


@pytest.fixture(scope="module")
def host(eng):
    e, _ = eng
    conn = e.catalogs["tpcds"]
    return {
        "store_sales": _table(conn, "store_sales", [
            "ss_sold_date_sk", "ss_sold_time_sk", "ss_item_sk", "ss_store_sk",
            "ss_customer_sk", "ss_hdemo_sk", "ss_cdemo_sk", "ss_addr_sk",
            "ss_ticket_number", "ss_quantity", "ss_sales_price",
            "ss_ext_sales_price", "ss_ext_discount_amt", "ss_net_profit",
            "ss_net_paid", "ss_ext_wholesale_cost", "ss_list_price",
            "ss_coupon_amt", "ss_promo_sk"]),
        "store_returns": _table(conn, "store_returns", [
            "sr_returned_date_sk", "sr_item_sk", "sr_customer_sk",
            "sr_store_sk", "sr_ticket_number", "sr_return_amt",
            "sr_return_quantity", "sr_reason_sk", "sr_net_loss"]),
        "web_sales": _table(conn, "web_sales", [
            "ws_sold_date_sk", "ws_sold_time_sk", "ws_ship_date_sk",
            "ws_item_sk", "ws_bill_customer_sk", "ws_web_site_sk",
            "ws_warehouse_sk", "ws_ship_mode_sk", "ws_order_number",
            "ws_quantity", "ws_ext_sales_price", "ws_ext_discount_amt",
            "ws_sales_price", "ws_net_profit", "ws_net_paid",
            "ws_ext_ship_cost"]),
        "web_returns": _table(conn, "web_returns", [
            "wr_returned_date_sk", "wr_item_sk", "wr_returning_customer_sk",
            "wr_returning_addr_sk", "wr_return_amt", "wr_order_number"]),
        "catalog_sales": _table(conn, "catalog_sales", [
            "cs_sold_date_sk", "cs_ship_date_sk", "cs_item_sk",
            "cs_bill_customer_sk", "cs_bill_addr_sk", "cs_call_center_sk",
            "cs_warehouse_sk", "cs_ship_mode_sk", "cs_order_number",
            "cs_quantity", "cs_ext_sales_price", "cs_sales_price",
            "cs_net_profit"]),
        "date_dim": _table(conn, "date_dim", [
            "d_date_sk", "d_year", "d_moy", "d_dom", "d_qoy", "d_dow",
            "d_week_seq", "d_day_name"]),
        "item": _table(conn, "item", [
            "i_item_sk", "i_item_id", "i_item_desc", "i_brand_id", "i_brand",
            "i_category", "i_class", "i_manufact_id", "i_manager_id",
            "i_current_price"]),
        "store": _table(conn, "store", [
            "s_store_sk", "s_store_name", "s_store_id", "s_city", "s_state",
            "s_number_employees"]),
        "customer": _table(conn, "customer", [
            "c_customer_sk", "c_customer_id", "c_current_addr_sk",
            "c_first_name", "c_last_name", "c_preferred_cust_flag",
            "c_birth_year"]),
        "customer_address": _table(conn, "customer_address", [
            "ca_address_sk", "ca_city", "ca_state", "ca_zip", "ca_county"]),
        "household_demographics": _table(conn, "household_demographics", [
            "hd_demo_sk", "hd_dep_count", "hd_vehicle_count",
            "hd_buy_potential"]),
        "time_dim": _table(conn, "time_dim", [
            "t_time_sk", "t_hour", "t_minute", "t_am_pm"]),
        "warehouse": _table(conn, "warehouse", [
            "w_warehouse_sk", "w_warehouse_name"]),
        "ship_mode": _table(conn, "ship_mode", [
            "sm_ship_mode_sk", "sm_type"]),
        "web_site": _table(conn, "web_site", [
            "web_site_sk", "web_name"]),
        "reason": _table(conn, "reason", ["r_reason_sk", "r_reason_desc"]),
        "promotion": _table(conn, "promotion", [
            "p_promo_sk", "p_channel_dmail", "p_channel_email",
            "p_channel_tv"]),
    }


def _check(got, ref, float_cols, rtol=1e-9):
    assert len(got) == len(ref), (len(got), len(ref))
    for c in got.columns:
        a, b = got[c].to_numpy(), ref[c].to_numpy()
        if c in float_cols:
            np.testing.assert_allclose(a.astype(float), b.astype(float),
                                       rtol=rtol, err_msg=c)
        else:
            assert list(a) == list(b), c


# ---------------------------------------------------------------- correlated
def test_q01_returns_above_store_average(eng, host):
    """Q1: customers whose total store returns exceed 1.2x the average for
    their store (CTE + correlated scalar subquery)."""
    e, s = eng
    got = e.execute_sql("""
        with customer_total_return as (
          select sr_customer_sk ctr_customer_sk, sr_store_sk ctr_store_sk,
                 sum(sr_return_amt) ctr_total_return
          from store_returns, date_dim
          where sr_returned_date_sk = d_date_sk and d_year = 2000
          group by sr_customer_sk, sr_store_sk)
        select c_customer_id
        from customer_total_return ctr1, store, customer
        where ctr1.ctr_total_return >
              (select avg(ctr_total_return) * 1.2 from customer_total_return ctr2
               where ctr1.ctr_store_sk = ctr2.ctr_store_sk)
          and s_store_sk = ctr1.ctr_store_sk and s_state = 'TN'
          and ctr1.ctr_customer_sk = c_customer_sk
        order by c_customer_id limit 100""", s).to_pandas()
    sr, dd, st, cu = (host["store_returns"], host["date_dim"], host["store"],
                      host["customer"])
    j = sr.merge(dd, left_on="sr_returned_date_sk", right_on="d_date_sk")
    j = j[j.d_year == 2000]
    ctr = j.groupby(["sr_customer_sk", "sr_store_sk"], as_index=False) \
        .sr_return_amt.sum().rename(columns={
            "sr_customer_sk": "ctr_customer_sk", "sr_store_sk": "ctr_store_sk",
            "sr_return_amt": "ctr_total_return"})
    avg = ctr.groupby("ctr_store_sk").ctr_total_return.mean() * 1.2
    ctr = ctr.merge(avg.rename("thresh"), left_on="ctr_store_sk",
                    right_index=True)
    ctr = ctr[ctr.ctr_total_return > ctr.thresh]
    ref = ctr.merge(st[st.s_state == "TN"], left_on="ctr_store_sk",
                    right_on="s_store_sk") \
        .merge(cu, left_on="ctr_customer_sk", right_on="c_customer_sk")
    ref = ref[["c_customer_id"]].sort_values("c_customer_id").head(100)
    _check(got, ref, set())


def test_q30_web_returns_above_state_average(eng, host):
    """Q30 shape: web returners above 1.2x their state's average return."""
    e, s = eng
    got = e.execute_sql("""
        with ctr as (
          select wr_returning_customer_sk ctr_cust, ca_state ctr_state,
                 sum(wr_return_amt) ctr_ret
          from web_returns, date_dim, customer_address
          where wr_returned_date_sk = d_date_sk and d_year = 2000
            and wr_returning_addr_sk = ca_address_sk
          group by wr_returning_customer_sk, ca_state)
        select c_customer_id, ctr_ret
        from ctr, customer
        where ctr_ret > (select avg(ctr_ret) * 1.2 from ctr c2
                         where ctr.ctr_state = c2.ctr_state)
          and ctr_cust = c_customer_sk
        order by c_customer_id limit 50""", s).to_pandas()
    wr, dd, ca, cu = (host["web_returns"], host["date_dim"],
                      host["customer_address"], host["customer"])
    j = wr.merge(dd, left_on="wr_returned_date_sk", right_on="d_date_sk")
    j = j[j.d_year == 2000].merge(
        ca, left_on="wr_returning_addr_sk", right_on="ca_address_sk")
    ctr = j.groupby(["wr_returning_customer_sk", "ca_state"], as_index=False) \
        .wr_return_amt.sum().rename(columns={
            "wr_returning_customer_sk": "cust", "ca_state": "state",
            "wr_return_amt": "ret"})
    avg = ctr.groupby("state").ret.mean() * 1.2
    ctr = ctr.merge(avg.rename("thresh"), left_on="state", right_index=True)
    ctr = ctr[ctr.ret > ctr.thresh]
    ref = ctr.merge(cu, left_on="cust", right_on="c_customer_sk")
    ref = ref[["c_customer_id", "ret"]].rename(columns={"ret": "ctr_ret"}) \
        .sort_values("c_customer_id").head(50)
    _check(got, ref, {"ctr_ret"})


def test_q92_excess_web_discount(eng, host):
    """Q92: web discount amounts above 1.3x the per-item average (correlated
    aggregate in a comparison)."""
    e, s = eng
    got = e.execute_sql("""
        select sum(ws_ext_discount_amt) excess
        from web_sales ws1, item, date_dim
        where i_item_sk = ws1.ws_item_sk and i_manufact_id = 3
          and d_date_sk = ws1.ws_sold_date_sk and d_year = 2000
          and ws1.ws_ext_discount_amt >
              (select 1.3 * avg(ws_ext_discount_amt)
               from web_sales ws2, date_dim dd2
               where ws2.ws_item_sk = ws1.ws_item_sk
                 and dd2.d_date_sk = ws2.ws_sold_date_sk
                 and dd2.d_year = 2000)""", s).to_pandas()
    ws, it, dd = host["web_sales"], host["item"], host["date_dim"]
    j = ws.merge(dd, left_on="ws_sold_date_sk", right_on="d_date_sk")
    j = j[j.d_year == 2000]
    per_item = j.groupby("ws_item_sk").ws_ext_discount_amt.mean() * 1.3
    j2 = j.merge(it[it.i_manufact_id == 3], left_on="ws_item_sk",
                 right_on="i_item_sk")
    j2 = j2.merge(per_item.rename("thresh"), left_on="ws_item_sk",
                  right_index=True)
    want = j2[j2.ws_ext_discount_amt > j2.thresh].ws_ext_discount_amt.sum()
    got_v = got.iloc[0, 0]
    if len(j2[j2.ws_ext_discount_amt > j2.thresh]) == 0:
        assert got_v is None or (isinstance(got_v, float) and np.isnan(got_v))
    else:
        np.testing.assert_allclose(float(got_v), float(want), rtol=1e-9)


# ----------------------------------------------------------- CASE / buckets
def test_q09_bucket_report_scalar_subqueries(eng, host):
    """Q9: CASE over scalar-subquery counts picks avg columns per bucket."""
    e, s = eng
    got = e.execute_sql("""
        select case when (select count(*) from store_sales
                          where ss_quantity between 1 and 20) > 20000
                    then (select avg(ss_ext_discount_amt) from store_sales
                          where ss_quantity between 1 and 20)
                    else (select avg(ss_net_paid) from store_sales
                          where ss_quantity between 1 and 20) end bucket1,
               case when (select count(*) from store_sales
                          where ss_quantity between 21 and 40) > 15000
                    then (select avg(ss_ext_discount_amt) from store_sales
                          where ss_quantity between 21 and 40)
                    else (select avg(ss_net_paid) from store_sales
                          where ss_quantity between 21 and 40) end bucket2
        """, s).to_pandas()
    ss = host["store_sales"]
    out = []
    for lo, hi, cap in ((1, 20, 20000), (21, 40, 15000)):
        b = ss[(ss.ss_quantity >= lo) & (ss.ss_quantity <= hi)]
        out.append(b.ss_ext_discount_amt.mean() if len(b) > cap
                   else b.ss_net_paid.mean())
    # avg over decimal(7,2) is decimal(7,2) (reference typing): the engine's
    # result rounds to scale 2, so compare at that granularity
    np.testing.assert_allclose(got.iloc[0].astype(float).to_numpy(),
                               np.array(out), atol=0.0051)


def test_q48_disjunctive_quantity_price_sum(eng, host):
    """Q48 shape: sum of quantities under an OR of (price-band AND
    quantity-band) arms."""
    e, s = eng
    got = e.execute_sql("""
        select sum(ss_quantity) q from store_sales, store, date_dim
        where s_store_sk = ss_store_sk and ss_sold_date_sk = d_date_sk
          and d_year = 2001
          and ((ss_sales_price between 50.00 and 100.00 and ss_net_profit >= 0)
            or (ss_sales_price between 100.00 and 150.00 and ss_net_profit >= 50)
            or (ss_sales_price between 150.00 and 200.00 and ss_net_profit >= 100))
        """, s).to_pandas()
    ss, st, dd = host["store_sales"], host["store"], host["date_dim"]
    j = ss.merge(st, left_on="ss_store_sk", right_on="s_store_sk") \
        .merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j[j.d_year == 2001]
    m = (((j.ss_sales_price >= 50) & (j.ss_sales_price <= 100)
          & (j.ss_net_profit >= 0))
         | ((j.ss_sales_price >= 100) & (j.ss_sales_price <= 150)
            & (j.ss_net_profit >= 50))
         | ((j.ss_sales_price >= 150) & (j.ss_sales_price <= 200)
            & (j.ss_net_profit >= 100)))
    assert int(got.iloc[0, 0]) == int(j[m].ss_quantity.sum())


def test_q88_time_bucket_cross_counts(eng, host):
    """Q88 shape: cross join of independent scalar-count subqueries over
    half-hour buckets."""
    e, s = eng
    got = e.execute_sql("""
        select * from
          (select count(*) h8 from store_sales, household_demographics, time_dim
           where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
             and t_hour = 8 and t_minute >= 30 and hd_dep_count = 2),
          (select count(*) h9 from store_sales, household_demographics, time_dim
           where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
             and t_hour = 9 and t_minute < 30 and hd_dep_count = 2)""",
                        s).to_pandas()
    ss, hd, td = (host["store_sales"], host["household_demographics"],
                  host["time_dim"])
    j = ss.merge(td, left_on="ss_sold_time_sk", right_on="t_time_sk") \
        .merge(hd[hd.hd_dep_count == 2], left_on="ss_hdemo_sk",
               right_on="hd_demo_sk")
    h8 = len(j[(j.t_hour == 8) & (j.t_minute >= 30)])
    h9 = len(j[(j.t_hour == 9) & (j.t_minute < 30)])
    assert (int(got.h8[0]), int(got.h9[0])) == (h8, h9)


def test_q34_ticket_dep_count_buckets(eng, host):
    """Q34 shape: per-ticket item counts in a band, grouped via a derived
    table + HAVING."""
    e, s = eng
    got = e.execute_sql("""
        select c_last_name, c_first_name, ticket, cnt from
          (select ss_ticket_number ticket, ss_customer_sk cust, count(*) cnt
           from store_sales, household_demographics
           where ss_hdemo_sk = hd_demo_sk and hd_vehicle_count > 2
           group by ss_ticket_number, ss_customer_sk
           having count(*) between 2 and 5) dn, customer
        where cust = c_customer_sk
        order by c_last_name, c_first_name, ticket limit 50""", s).to_pandas()
    ss, hd, cu = (host["store_sales"], host["household_demographics"],
                  host["customer"])
    j = ss.merge(hd[hd.hd_vehicle_count > 2], left_on="ss_hdemo_sk",
                 right_on="hd_demo_sk")
    g = j.groupby(["ss_ticket_number", "ss_customer_sk"], as_index=False) \
        .size().rename(columns={"size": "cnt", "ss_ticket_number": "ticket",
                                "ss_customer_sk": "cust"})
    g = g[(g.cnt >= 2) & (g.cnt <= 5)]
    ref = g.merge(cu, left_on="cust", right_on="c_customer_sk")
    ref = ref[["c_last_name", "c_first_name", "ticket", "cnt"]] \
        .sort_values(["c_last_name", "c_first_name", "ticket"]).head(50)
    _check(got, ref, set())


# ------------------------------------------------------------------ windows
def test_q44_best_worst_items_by_rank(eng, host):
    """Q44 shape: rank items by average net profit ascending and descending,
    pair rank n with rank n from each direction."""
    e, s = eng
    got = e.execute_sql("""
        with perf as (
          select ss_item_sk item_sk, avg(ss_net_profit) rank_col
          from store_sales where ss_store_sk = 1 group by ss_item_sk)
        select a.rnk, i1.i_item_id best, i2.i_item_id worst from
          (select item_sk, row_number() over (order by rank_col desc, item_sk) rnk
           from perf) a,
          (select item_sk, row_number() over (order by rank_col asc, item_sk) rnk
           from perf) b, item i1, item i2
        where a.rnk = b.rnk and a.rnk <= 10
          and i1.i_item_sk = a.item_sk and i2.i_item_sk = b.item_sk
        order by a.rnk""", s).to_pandas()
    ss, it = host["store_sales"], host["item"]
    perf = ss[ss.ss_store_sk == 1].groupby("ss_item_sk", as_index=False) \
        .ss_net_profit.mean().rename(columns={"ss_net_profit": "rank_col"})
    best = perf.sort_values(["rank_col", "ss_item_sk"],
                            ascending=[False, True]).head(10).reset_index()
    worst = perf.sort_values(["rank_col", "ss_item_sk"],
                             ascending=[True, True]).head(10).reset_index()
    names = it.set_index("i_item_sk").i_item_id
    ref = pd.DataFrame({
        "rnk": np.arange(1, len(best) + 1),
        "best": best.ss_item_sk.map(names).to_numpy(),
        "worst": worst.ss_item_sk.map(names).to_numpy()})
    _check(got, ref, set())


def test_q51_cumulative_channel_windows(eng, host):
    """Q51 shape: cumulative window sums per item over weeks, two channels
    joined on (item, week)."""
    e, s = eng
    got = e.execute_sql("""
        with web as (
          select ws_item_sk item_sk, d_week_seq wk, sum(ws_ext_sales_price) rev
          from web_sales, date_dim
          where ws_sold_date_sk = d_date_sk and d_year = 2000
          group by ws_item_sk, d_week_seq),
        store as (
          select ss_item_sk item_sk, d_week_seq wk, sum(ss_ext_sales_price) rev
          from store_sales, date_dim
          where ss_sold_date_sk = d_date_sk and d_year = 2000
          group by ss_item_sk, d_week_seq)
        select w.item_sk, w.wk,
               sum(w.rev) over (partition by w.item_sk order by w.wk) cume_web,
               sum(st.rev) over (partition by st.item_sk order by st.wk) cume_store
        from web w, store st
        where w.item_sk = st.item_sk and w.wk = st.wk
        order by w.item_sk, w.wk limit 100""", s).to_pandas()
    ws, ss, dd = host["web_sales"], host["store_sales"], host["date_dim"]
    ddy = dd[dd.d_year == 2000]
    web = ws.merge(ddy, left_on="ws_sold_date_sk", right_on="d_date_sk") \
        .groupby(["ws_item_sk", "d_week_seq"], as_index=False) \
        .ws_ext_sales_price.sum() \
        .rename(columns={"ws_item_sk": "item_sk", "d_week_seq": "wk",
                         "ws_ext_sales_price": "wrev"})
    sto = ss.merge(ddy, left_on="ss_sold_date_sk", right_on="d_date_sk") \
        .groupby(["ss_item_sk", "d_week_seq"], as_index=False) \
        .ss_ext_sales_price.sum() \
        .rename(columns={"ss_item_sk": "item_sk", "d_week_seq": "wk",
                         "ss_ext_sales_price": "srev"})
    j = web.merge(sto, on=["item_sk", "wk"]).sort_values(["item_sk", "wk"])
    j["cume_web"] = j.groupby("item_sk").wrev.cumsum()
    j["cume_store"] = j.groupby("item_sk").srev.cumsum()
    ref = j[["item_sk", "wk", "cume_web", "cume_store"]].head(100)
    _check(got, ref, {"cume_web", "cume_store"})


# ------------------------------------------------------- lag / ship buckets
def test_q50_return_lag_buckets(eng, host):
    """Q50 shape: sale-to-return day lag bucketed per store."""
    e, s = eng
    got = e.execute_sql("""
        select s_store_name,
               sum(case when sr_returned_date_sk - ss_sold_date_sk <= 30
                        then 1 else 0 end) d30,
               sum(case when sr_returned_date_sk - ss_sold_date_sk > 30
                         and sr_returned_date_sk - ss_sold_date_sk <= 90
                        then 1 else 0 end) d90,
               sum(case when sr_returned_date_sk - ss_sold_date_sk > 90
                        then 1 else 0 end) dmore
        from store_sales, store_returns, store
        where ss_ticket_number = sr_ticket_number and ss_item_sk = sr_item_sk
          and ss_store_sk = s_store_sk
        group by s_store_name order by s_store_name""", s).to_pandas()
    ss, sr, st = host["store_sales"], host["store_returns"], host["store"]
    j = ss.merge(sr, left_on=["ss_ticket_number", "ss_item_sk"],
                 right_on=["sr_ticket_number", "sr_item_sk"]) \
        .merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    lag = j.sr_returned_date_sk - j.ss_sold_date_sk
    ref = pd.DataFrame({
        "s_store_name": j.s_store_name,
        "d30": (lag <= 30).astype(int),
        "d90": ((lag > 30) & (lag <= 90)).astype(int),
        "dmore": (lag > 90).astype(int)})
    ref = ref.groupby("s_store_name", as_index=False).sum() \
        .sort_values("s_store_name")
    _check(got, ref, set())


def test_q62_web_ship_lag_by_site(eng, host):
    """Q62: web ship lag buckets by warehouse/ship-mode/site."""
    e, s = eng
    got = e.execute_sql("""
        select w_warehouse_name, sm_type, web_name,
               sum(case when ws_ship_date_sk - ws_sold_date_sk <= 30
                        then 1 else 0 end) d30,
               sum(case when ws_ship_date_sk - ws_sold_date_sk > 30
                        then 1 else 0 end) dmore
        from web_sales, warehouse, ship_mode, web_site
        where ws_warehouse_sk = w_warehouse_sk
          and ws_ship_mode_sk = sm_ship_mode_sk
          and ws_web_site_sk = web_site_sk
        group by w_warehouse_name, sm_type, web_name
        order by w_warehouse_name, sm_type, web_name limit 100""",
                        s).to_pandas()
    ws, wh, sm, wsit = (host["web_sales"], host["warehouse"],
                        host["ship_mode"], host["web_site"])
    j = ws.merge(wh, left_on="ws_warehouse_sk", right_on="w_warehouse_sk") \
        .merge(sm, left_on="ws_ship_mode_sk", right_on="sm_ship_mode_sk") \
        .merge(wsit, left_on="ws_web_site_sk", right_on="web_site_sk")
    lag = j.ws_ship_date_sk - j.ws_sold_date_sk
    ref = pd.DataFrame({"w_warehouse_name": j.w_warehouse_name,
                        "sm_type": j.sm_type, "web_name": j.web_name,
                        "d30": (lag <= 30).astype(int),
                        "dmore": (lag > 30).astype(int)})
    ref = ref.groupby(["w_warehouse_name", "sm_type", "web_name"],
                      as_index=False).sum() \
        .sort_values(["w_warehouse_name", "sm_type", "web_name"]).head(100)
    _check(got, ref, set())


# ------------------------------------------------------------ ratio reports
def test_q61_promotional_revenue_ratio(eng, host):
    """Q61 shape: promotional vs total revenue as a cross join of two
    single-row aggregates."""
    e, s = eng
    got = e.execute_sql("""
        select promo, total, promo / total * 100 pct from
          (select sum(ss_ext_sales_price) promo
           from store_sales, promotion where ss_promo_sk = p_promo_sk
             and (p_channel_dmail = 'Y' or p_channel_email = 'Y'
                  or p_channel_tv = 'Y')),
          (select sum(ss_ext_sales_price) total from store_sales)""",
                        s).to_pandas()
    ss, pr = host["store_sales"], host["promotion"]
    j = ss.merge(pr, left_on="ss_promo_sk", right_on="p_promo_sk")
    j = j[(j.p_channel_dmail == "Y") | (j.p_channel_email == "Y")
          | (j.p_channel_tv == "Y")]
    promo, total = j.ss_ext_sales_price.sum(), ss.ss_ext_sales_price.sum()
    np.testing.assert_allclose(
        got.iloc[0].astype(float).to_numpy(),
        np.array([promo, total, promo / total * 100]), rtol=1e-9)


def test_q90_am_pm_ratio(eng, host):
    """Q90: am/pm web sales count ratio of two derived aggregates."""
    e, s = eng
    got = e.execute_sql("""
        select cast(amc as double) / pmc ratio from
          (select count(*) amc from web_sales, time_dim
           where ws_sold_time_sk = t_time_sk and t_hour between 7 and 8),
          (select count(*) pmc from web_sales, time_dim
           where ws_sold_time_sk = t_time_sk and t_hour between 19 and 20)""",
                        s).to_pandas()
    ws, td = host["web_sales"], host["time_dim"]
    j = ws.merge(td, left_on="ws_sold_time_sk", right_on="t_time_sk")
    amc = len(j[(j.t_hour >= 7) & (j.t_hour <= 8)])
    pmc = len(j[(j.t_hour >= 19) & (j.t_hour <= 20)])
    np.testing.assert_allclose(float(got.iloc[0, 0]), amc / pmc, rtol=1e-9)


def test_q59_weekly_sales_year_over_year(eng, host):
    """Q59 shape: store weekly sums self-joined a year (52 weeks) apart."""
    e, s = eng
    got = e.execute_sql("""
        with wss as (
          select d_week_seq wk, ss_store_sk store_sk,
                 sum(ss_ext_sales_price) rev
          from store_sales, date_dim where ss_sold_date_sk = d_date_sk
          group by d_week_seq, ss_store_sk)
        select y.store_sk, y.wk, y.rev this_year, z.rev next_year
        from wss y, wss z
        where y.store_sk = z.store_sk and z.wk = y.wk + 52
        order by y.store_sk, y.wk limit 100""", s).to_pandas()
    ss, dd = host["store_sales"], host["date_dim"]
    wss = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk") \
        .groupby(["d_week_seq", "ss_store_sk"], as_index=False) \
        .ss_ext_sales_price.sum().rename(columns={
            "d_week_seq": "wk", "ss_store_sk": "store_sk",
            "ss_ext_sales_price": "rev"})
    z = wss.copy()
    z["wk"] = z.wk - 52
    j = wss.merge(z, on=["store_sk", "wk"], suffixes=("_y", "_z"))
    ref = j.rename(columns={"rev_y": "this_year", "rev_z": "next_year"}) \
        [["store_sk", "wk", "this_year", "next_year"]] \
        .sort_values(["store_sk", "wk"]).head(100)
    _check(got, ref, {"this_year", "next_year"})


# ----------------------------------------------------------- star + filters
def test_q15_catalog_zip_report(eng, host):
    """Q15: catalog revenue by customer zip under a disjunctive
    zip/state/price filter."""
    e, s = eng
    got = e.execute_sql("""
        select ca_zip, sum(cs_sales_price) rev
        from catalog_sales, customer, customer_address, date_dim
        where cs_bill_customer_sk = c_customer_sk
          and c_current_addr_sk = ca_address_sk
          and (ca_state in ('CA', 'WA', 'GA') or cs_sales_price > 160)
          and cs_sold_date_sk = d_date_sk and d_qoy = 2 and d_year = 2001
        group by ca_zip order by ca_zip limit 100""", s).to_pandas()
    cs, cu, ca, dd = (host["catalog_sales"], host["customer"],
                      host["customer_address"], host["date_dim"])
    j = cs.merge(cu, left_on="cs_bill_customer_sk", right_on="c_customer_sk") \
        .merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk") \
        .merge(dd, left_on="cs_sold_date_sk", right_on="d_date_sk")
    j = j[(j.d_qoy == 2) & (j.d_year == 2001)
          & (j.ca_state.isin(["CA", "WA", "GA"]) | (j.cs_sales_price > 160))]
    ref = j.groupby("ca_zip", as_index=False).cs_sales_price.sum() \
        .rename(columns={"cs_sales_price": "rev"}) \
        .sort_values("ca_zip").head(100)
    _check(got, ref, {"rev"})


def test_q25_sale_return_catalog_flow(eng, host):
    """Q25 shape: customers who bought in store, returned, then bought the
    same item by catalog (3 fact tables chained on customer+item)."""
    e, s = eng
    got = e.execute_sql("""
        select i_item_id, sum(ss_net_profit) store_profit,
               sum(sr_net_loss) return_loss, sum(cs_net_profit) catalog_profit
        from store_sales, store_returns, catalog_sales, item
        where ss_customer_sk = sr_customer_sk and ss_item_sk = sr_item_sk
          and ss_ticket_number = sr_ticket_number
          and sr_customer_sk = cs_bill_customer_sk and sr_item_sk = cs_item_sk
          and ss_item_sk = i_item_sk
        group by i_item_id order by i_item_id limit 50""", s).to_pandas()
    ss, sr, cs, it = (host["store_sales"], host["store_returns"],
                      host["catalog_sales"], host["item"])
    j = ss.merge(sr, left_on=["ss_customer_sk", "ss_item_sk",
                              "ss_ticket_number"],
                 right_on=["sr_customer_sk", "sr_item_sk",
                           "sr_ticket_number"]) \
        .merge(cs, left_on=["sr_customer_sk", "sr_item_sk"],
               right_on=["cs_bill_customer_sk", "cs_item_sk"]) \
        .merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    ref = j.groupby("i_item_id", as_index=False).agg(
        store_profit=("ss_net_profit", "sum"),
        return_loss=("sr_net_loss", "sum"),
        catalog_profit=("cs_net_profit", "sum")) \
        .sort_values("i_item_id").head(50)
    _check(got, ref, {"store_profit", "return_loss", "catalog_profit"})


def test_q45_zip_list_or_item_subquery(eng, host):
    """Q45: web revenue by zip where the zip is in a literal list OR the item
    is in a subquery's id set."""
    e, s = eng
    got = e.execute_sql("""
        select ca_zip, sum(ws_sales_price) rev
        from web_sales, customer, customer_address, item
        where ws_bill_customer_sk = c_customer_sk
          and c_current_addr_sk = ca_address_sk and ws_item_sk = i_item_sk
          and (ca_zip in (85669, 86197, 88274)
               or i_item_sk in (select i_item_sk from item
                                where i_manufact_id = 5))
        group by ca_zip order by ca_zip limit 50""", s).to_pandas()
    ws, cu, ca, it = (host["web_sales"], host["customer"],
                      host["customer_address"], host["item"])
    j = ws.merge(cu, left_on="ws_bill_customer_sk", right_on="c_customer_sk") \
        .merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk") \
        .merge(it, left_on="ws_item_sk", right_on="i_item_sk")
    m5 = set(it[it.i_manufact_id == 5].i_item_sk)
    j = j[j.ca_zip.isin([85669, 86197, 88274])
          | j.ws_item_sk.isin(m5)]
    ref = j.groupby("ca_zip", as_index=False).ws_sales_price.sum() \
        .rename(columns={"ws_sales_price": "rev"}) \
        .sort_values("ca_zip").head(50)
    _check(got, ref, {"rev"})


def test_q46_city_ticket_amounts(eng, host):
    """Q46 shape: per-ticket aggregation over a demographic filter joined to
    the customer's current city."""
    e, s = eng
    got = e.execute_sql("""
        select c_last_name, ticket, amt from
          (select ss_ticket_number ticket, ss_customer_sk cust,
                  sum(ss_coupon_amt) amt
           from store_sales, household_demographics
           where ss_hdemo_sk = hd_demo_sk
             and (hd_dep_count = 4 or hd_vehicle_count = 3)
           group by ss_ticket_number, ss_customer_sk) dn, customer
        where cust = c_customer_sk
        order by c_last_name, ticket limit 50""", s).to_pandas()
    ss, hd, cu = (host["store_sales"], host["household_demographics"],
                  host["customer"])
    j = ss.merge(hd[(hd.hd_dep_count == 4) | (hd.hd_vehicle_count == 3)],
                 left_on="ss_hdemo_sk", right_on="hd_demo_sk")
    g = j.groupby(["ss_ticket_number", "ss_customer_sk"], as_index=False) \
        .ss_coupon_amt.sum().rename(columns={
            "ss_ticket_number": "ticket", "ss_customer_sk": "cust",
            "ss_coupon_amt": "amt"})
    ref = g.merge(cu, left_on="cust", right_on="c_customer_sk")
    ref = ref[["c_last_name", "ticket", "amt"]] \
        .sort_values(["c_last_name", "ticket"]).head(50)
    _check(got, ref, {"amt"})


def test_q79_ticket_profit_by_city(eng, host):
    """Q79 shape: per-ticket profit with store city, demographic-filtered."""
    e, s = eng
    got = e.execute_sql("""
        select c_last_name, s_city, profit from
          (select ss_ticket_number tick, ss_customer_sk cust, s_city,
                  sum(ss_net_profit) profit
           from store_sales, household_demographics, store
           where ss_hdemo_sk = hd_demo_sk and ss_store_sk = s_store_sk
             and hd_dep_count = 6
           group by ss_ticket_number, ss_customer_sk, s_city) ms, customer
        where cust = c_customer_sk
        order by c_last_name, s_city, profit limit 50""", s).to_pandas()
    ss, hd, st, cu = (host["store_sales"], host["household_demographics"],
                      host["store"], host["customer"])
    j = ss.merge(hd[hd.hd_dep_count == 6], left_on="ss_hdemo_sk",
                 right_on="hd_demo_sk") \
        .merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    g = j.groupby(["ss_ticket_number", "ss_customer_sk", "s_city"],
                  as_index=False).ss_net_profit.sum() \
        .rename(columns={"ss_net_profit": "profit",
                         "ss_customer_sk": "cust"})
    ref = g.merge(cu, left_on="cust", right_on="c_customer_sk")
    ref = ref[["c_last_name", "s_city", "profit"]] \
        .sort_values(["c_last_name", "s_city", "profit"]).head(50)
    _check(got, ref, {"profit"})


# --------------------------------------------------------------- exists family
def test_q16_catalog_ship_not_exists_returns(eng, host):
    """Q16 shape: catalog orders shipped from a warehouse with NO return
    recorded (not exists) and a same-order different-warehouse sibling
    (exists)."""
    e, s = eng
    got = e.execute_sql("""
        select count(distinct cs_order_number) cnt,
               sum(cs_ext_ship_cost) ship, sum(cs_net_profit) profit
        from catalog_sales cs1, date_dim
        where cs_sold_date_sk = d_date_sk and d_year = 2000
          and exists (select 1 from catalog_sales cs2
                      where cs1.cs_order_number = cs2.cs_order_number
                        and cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk)
          and not exists (select 1 from catalog_returns cr
                          where cs1.cs_order_number = cr.cr_order_number)""",
                        s).to_pandas()
    cs, dd = host["catalog_sales"], host["date_dim"]
    conn = e.catalogs["tpcds"]
    cr = _table(conn, "catalog_returns", ["cr_order_number"])
    per_order = cs.groupby("cs_order_number").cs_warehouse_sk.nunique()
    multi = set(per_order[per_order > 1].index)
    returned = set(cr.cr_order_number)
    j = cs.merge(dd, left_on="cs_sold_date_sk", right_on="d_date_sk")
    j = j[(j.d_year == 2000) & j.cs_order_number.isin(multi)
          & ~j.cs_order_number.isin(returned)]
    # exists() semantics require the sibling to be a DIFFERENT-warehouse row
    # of the same order; rows whose own warehouse is the only one don't count
    cnt = j.cs_order_number.nunique()
    assert int(got.cnt[0]) == cnt
    if cnt == 0:  # SQL sum over zero rows is NULL (pandas gives 0.0)
        assert got.ship[0] is None or np.isnan(got.ship[0])
        assert got.profit[0] is None or np.isnan(got.profit[0])
    else:
        np.testing.assert_allclose(float(got.ship[0]),
                                   j.cs_ext_ship_cost.sum(), rtol=1e-9)
        np.testing.assert_allclose(float(got.profit[0]),
                                   j.cs_net_profit.sum(), rtol=1e-9)


def test_q69_demographics_store_only_shoppers(eng, host):
    """Q69 shape: customers with store purchases in a window and NO web
    purchases (exists + not exists), reported by demographics."""
    e, s = eng
    got = e.execute_sql("""
        select cd_gender, cd_education_status, count(*) cnt
        from customer c, customer_demographics
        where c_current_cdemo_sk = cd_demo_sk
          and exists (select 1 from store_sales, date_dim
                      where c.c_customer_sk = ss_customer_sk
                        and ss_sold_date_sk = d_date_sk and d_year = 2002)
          and not exists (select 1 from web_sales, date_dim
                          where c.c_customer_sk = ws_bill_customer_sk
                            and ws_sold_date_sk = d_date_sk and d_year = 2002)
        group by cd_gender, cd_education_status
        order by cd_gender, cd_education_status limit 50""", s).to_pandas()
    conn = e.catalogs["tpcds"]
    cd = _table(conn, "customer_demographics",
                ["cd_demo_sk", "cd_gender", "cd_education_status"])
    cu, ss, ws, dd = (host["customer"], host["store_sales"],
                      host["web_sales"], host["date_dim"])
    cu2 = _table(conn, "customer", ["c_customer_sk", "c_current_cdemo_sk"])
    d02 = set(dd[dd.d_year == 2002].d_date_sk)
    st_cust = set(ss[ss.ss_sold_date_sk.isin(d02)].ss_customer_sk)
    web_cust = set(ws[ws.ws_sold_date_sk.isin(d02)].ws_bill_customer_sk)
    j = cu2[cu2.c_customer_sk.isin(st_cust)
            & ~cu2.c_customer_sk.isin(web_cust)]
    j = j.merge(cd, left_on="c_current_cdemo_sk", right_on="cd_demo_sk")
    ref = j.groupby(["cd_gender", "cd_education_status"], as_index=False) \
        .size().rename(columns={"size": "cnt"}) \
        .sort_values(["cd_gender", "cd_education_status"]).head(50)
    _check(got, ref, set())


# ---------------------------------------------------------- channel overlap
def test_q97_channel_overlap_counts(eng, host):
    """Q97: store-only / catalog-only / both customer-item overlap via FULL
    OUTER JOIN of two grouped channels."""
    e, s = eng
    got = e.execute_sql("""
        with ssci as (
          select ss_customer_sk cust, ss_item_sk item from store_sales
          where ss_customer_sk is not null
          group by ss_customer_sk, ss_item_sk),
        csci as (
          select cs_bill_customer_sk cust, cs_item_sk item from catalog_sales
          where cs_bill_customer_sk is not null
          group by cs_bill_customer_sk, cs_item_sk)
        select sum(case when ssci.cust is not null and csci.cust is null
                        then 1 else 0 end) store_only,
               sum(case when ssci.cust is null and csci.cust is not null
                        then 1 else 0 end) catalog_only,
               sum(case when ssci.cust is not null and csci.cust is not null
                        then 1 else 0 end) both_channels
        from ssci full outer join csci
          on ssci.cust = csci.cust and ssci.item = csci.item""",
                        s).to_pandas()
    ss, cs = host["store_sales"], host["catalog_sales"]
    a = set(map(tuple, ss[["ss_customer_sk", "ss_item_sk"]]
                .drop_duplicates().to_numpy()))
    b = set(map(tuple, cs[["cs_bill_customer_sk", "cs_item_sk"]]
                .drop_duplicates().to_numpy()))
    want = (len(a - b), len(b - a), len(a & b))
    assert (int(got.store_only[0]), int(got.catalog_only[0]),
            int(got.both_channels[0])) == want


def test_q60_three_channel_category_union(eng, host):
    """Q60 shape: per-item revenue summed across all three channels via
    UNION ALL, restricted to one category."""
    e, s = eng
    got = e.execute_sql("""
        with sales as (
          select i_item_id item_id, ss_ext_sales_price price
          from store_sales, item
          where ss_item_sk = i_item_sk and i_category = 'Music'
          union all
          select i_item_id, cs_ext_sales_price from catalog_sales, item
          where cs_item_sk = i_item_sk and i_category = 'Music'
          union all
          select i_item_id, ws_ext_sales_price from web_sales, item
          where ws_item_sk = i_item_sk and i_category = 'Music')
        select item_id, sum(price) total from sales
        group by item_id order by item_id, total limit 50""", s).to_pandas()
    ss, cs, ws, it = (host["store_sales"], host["catalog_sales"],
                      host["web_sales"], host["item"])
    itm = it[it.i_category == "Music"]
    parts = []
    for df, k, v in ((ss, "ss_item_sk", "ss_ext_sales_price"),
                     (cs, "cs_item_sk", "cs_ext_sales_price"),
                     (ws, "ws_item_sk", "ws_ext_sales_price")):
        m = df.merge(itm, left_on=k, right_on="i_item_sk")
        parts.append(m[["i_item_id", v]].rename(
            columns={"i_item_id": "item_id", v: "price"}))
    allp = pd.concat(parts)
    ref = allp.groupby("item_id", as_index=False).price.sum() \
        .rename(columns={"price": "total"}) \
        .sort_values(["item_id", "total"]).head(50)
    _check(got, ref, {"total"})


def test_q71_brand_revenue_by_hour_channels(eng, host):
    """Q71 shape: three-channel union joined to time_dim, brand revenue at
    breakfast/dinner hours."""
    e, s = eng
    got = e.execute_sql("""
        with sales as (
          select ws_ext_sales_price price, ws_item_sk item_sk,
                 ws_sold_time_sk time_sk from web_sales
          union all
          select ss_ext_sales_price, ss_item_sk, ss_sold_time_sk
          from store_sales)
        select i_brand_id, t_hour, sum(price) rev
        from sales, item, time_dim
        where item_sk = i_item_sk and i_manager_id = 1
          and time_sk = t_time_sk and (t_hour = 8 or t_hour = 19)
        group by i_brand_id, t_hour order by i_brand_id, t_hour limit 50""",
                        s).to_pandas()
    ws, ss, it, td = (host["web_sales"], host["store_sales"], host["item"],
                      host["time_dim"])
    parts = [
        ws[["ws_ext_sales_price", "ws_item_sk", "ws_sold_time_sk"]].rename(
            columns={"ws_ext_sales_price": "price", "ws_item_sk": "item_sk",
                     "ws_sold_time_sk": "time_sk"}),
        ss[["ss_ext_sales_price", "ss_item_sk", "ss_sold_time_sk"]].rename(
            columns={"ss_ext_sales_price": "price", "ss_item_sk": "item_sk",
                     "ss_sold_time_sk": "time_sk"})]
    allp = pd.concat(parts)
    j = allp.merge(it[it.i_manager_id == 1], left_on="item_sk",
                   right_on="i_item_sk") \
        .merge(td, left_on="time_sk", right_on="t_time_sk")
    j = j[(j.t_hour == 8) | (j.t_hour == 19)]
    ref = j.groupby(["i_brand_id", "t_hour"], as_index=False).price.sum() \
        .rename(columns={"price": "rev"}) \
        .sort_values(["i_brand_id", "t_hour"]).head(50)
    _check(got, ref, {"rev"})


def test_q93_reason_adjusted_sales(eng, host):
    """Q93 shape: net paid recomputed against returns for one reason."""
    e, s = eng
    got = e.execute_sql("""
        select cust, sum(act) total from
          (select ss_customer_sk cust,
                  case when sr_return_quantity is not null
                       then (ss_quantity - sr_return_quantity) * ss_sales_price
                       else ss_quantity * ss_sales_price end act
           from store_sales left join store_returns
             on ss_item_sk = sr_item_sk
            and ss_ticket_number = sr_ticket_number
           where sr_reason_sk = 1 or sr_reason_sk is null) t
        group by cust order by total desc, cust limit 20""", s).to_pandas()
    ss, sr = host["store_sales"], host["store_returns"]
    j = ss.merge(sr, left_on=["ss_item_sk", "ss_ticket_number"],
                 right_on=["sr_item_sk", "sr_ticket_number"], how="left")
    j = j[(j.sr_reason_sk == 1) | j.sr_reason_sk.isna()]
    act = np.where(j.sr_return_quantity.notna(),
                   (j.ss_quantity - j.sr_return_quantity.fillna(0))
                   * j.ss_sales_price,
                   j.ss_quantity * j.ss_sales_price)
    ref = pd.DataFrame({"cust": j.ss_customer_sk, "total": act}) \
        .groupby("cust", as_index=False).total.sum() \
        .sort_values(["total", "cust"], ascending=[False, True]).head(20)
    _check(got, ref, {"total"})


def test_q47_monthly_brand_vs_yearly_average(eng, host):
    """Q47 shape: monthly brand sums compared against the brand-year window
    average (window avg + deviation filter)."""
    e, s = eng
    got = e.execute_sql("""
        with v1 as (
          select i_brand_id brand, d_year yr, d_moy moy,
                 sum(ss_ext_sales_price) msum
          from store_sales, item, date_dim
          where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
            and d_year = 2000
          group by i_brand_id, d_year, d_moy)
        select brand, moy, msum,
               avg(msum) over (partition by brand, yr) avg_monthly
        from v1 order by brand, moy limit 100""", s).to_pandas()
    ss, it, dd = host["store_sales"], host["item"], host["date_dim"]
    j = ss.merge(it, left_on="ss_item_sk", right_on="i_item_sk") \
        .merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j[j.d_year == 2000]
    v1 = j.groupby(["i_brand_id", "d_year", "d_moy"], as_index=False) \
        .ss_ext_sales_price.sum().rename(columns={
            "i_brand_id": "brand", "d_year": "yr", "d_moy": "moy",
            "ss_ext_sales_price": "msum"})
    v1["avg_monthly"] = v1.groupby(["brand", "yr"]).msum.transform("mean")
    ref = v1[["brand", "moy", "msum", "avg_monthly"]] \
        .sort_values(["brand", "moy"]).head(100)
    for c in ("brand", "moy"):
        assert list(got[c]) == list(ref[c]), c
    np.testing.assert_allclose(got.msum.astype(float), ref.msum.astype(float),
                               rtol=1e-9)
    # avg over decimal keeps the input scale (reference typing): the engine's
    # avg_monthly rounds to 2 decimals
    np.testing.assert_allclose(got.avg_monthly.astype(float),
                               ref.avg_monthly.astype(float), atol=0.0051)


def test_q39_inventory_mean_stdev(eng, host):
    """Q39 shape: warehouse-item monthly inventory mean + stdev/mean ratio
    filter."""
    e, s = eng
    got = e.execute_sql("""
        select w_warehouse_sk wh, inv_item_sk item, d_moy moy,
               avg(inv_quantity_on_hand) mean_q,
               stddev_samp(inv_quantity_on_hand) sd_q
        from inventory, date_dim, warehouse
        where inv_date_sk = d_date_sk and inv_warehouse_sk = w_warehouse_sk
          and d_year = 2000 and d_moy = 1
        group by w_warehouse_sk, inv_item_sk, d_moy
        order by wh, item limit 100""", s).to_pandas()
    conn = e.catalogs["tpcds"]
    inv = _table(conn, "inventory", ["inv_date_sk", "inv_item_sk",
                                     "inv_warehouse_sk",
                                     "inv_quantity_on_hand"])
    dd, wh = host["date_dim"], host["warehouse"]
    j = inv.merge(dd, left_on="inv_date_sk", right_on="d_date_sk") \
        .merge(wh, left_on="inv_warehouse_sk", right_on="w_warehouse_sk")
    j = j[(j.d_year == 2000) & (j.d_moy == 1)]
    ref = j.groupby(["w_warehouse_sk", "inv_item_sk", "d_moy"],
                    as_index=False).agg(
        mean_q=("inv_quantity_on_hand", "mean"),
        sd_q=("inv_quantity_on_hand", lambda x: x.std(ddof=1))) \
        .rename(columns={"w_warehouse_sk": "wh", "inv_item_sk": "item",
                         "d_moy": "moy"}) \
        .sort_values(["wh", "item"]).head(100)
    _check(got, ref, {"mean_q", "sd_q"})
