"""Fault-tolerant execution: page serde, spooled exchange, task retries,
failure injection, dedup.

Reference test models: BaseFailureRecoveryTest (testing/trino-testing/.../
BaseFailureRecoveryTest.java:84) — inject TASK_FAILURE /
TASK_GET_RESULTS_FAILURE via the production FailureInjector hook and assert
queries still succeed; serde tests mirror TestPagesSerde.
"""

import numpy as np
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.exec.fte import (FailureInjector, FaultTolerantExecutor,
                                InjectedFailure, SpoolingExchange,
                                deserialize_page, serialize_page)
from trino_tpu.sql.frontend import compile_sql

Q1 = """select l_returnflag, l_linestatus, sum(l_quantity) qty, count(*) c,
               avg(l_discount) d
        from lineitem where l_shipdate <= date '1998-09-02'
        group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus"""


def test_page_serde_roundtrip():
    cols = [np.arange(10, dtype=np.int64), np.linspace(0, 1, 10)]
    nulls = [None, np.arange(10) % 3 == 0]
    data = serialize_page(cols, nulls)
    rc, rn = deserialize_page(data)
    np.testing.assert_array_equal(rc[0], cols[0])
    np.testing.assert_array_equal(rc[1], cols[1])
    assert rn[0] is None
    np.testing.assert_array_equal(rn[1], nulls[1])
    # corruption is detected
    bad = data[:20] + bytes([data[20] ^ 0xFF]) + data[21:]
    with pytest.raises(ValueError):
        deserialize_page(bad)


def test_page_serde_codecs(monkeypatch):
    """NONE/ZLIB/ZSTD codecs round-trip (reference: CompressionCodec.java:23)."""
    import trino_tpu.exec.fte as F

    cols = [np.arange(1000, dtype=np.int64), np.linspace(0, 1, 1000)]
    nulls = [None, np.arange(1000) % 3 == 0]
    codecs = ["none", "zlib"]
    try:  # stdlib-only container: zstd binding is optional
        import zstandard  # noqa: F401

        codecs.append("zstd")
    except ImportError:
        pass
    for codec in codecs:
        monkeypatch.setattr(F, "PAGE_CODEC", codec)
        rc, rn = deserialize_page(serialize_page(cols, nulls))
        np.testing.assert_array_equal(rc[0], cols[0])
        np.testing.assert_array_equal(rn[1], nulls[1])


def test_page_serde_encryption(monkeypatch):
    """AES-GCM exchange encryption: round-trips with the key, refuses without
    it, and authenticated tampering fails (reference:
    CompressingEncryptingPageSerializer.java:58)."""
    pytest.importorskip("cryptography")  # optional dep (stdlib-only container)
    cols = [np.arange(100, dtype=np.int64)]
    nulls = [None]
    monkeypatch.setenv("TRINO_TPU_EXCHANGE_KEY", "00" * 16)
    data = serialize_page(cols, nulls)
    assert data[4] & 0x80  # encrypted flag
    rc, _ = deserialize_page(data)
    np.testing.assert_array_equal(rc[0], cols[0])
    # tamper INSIDE the ciphertext and fix up the CRC: GCM must still refuse
    import zlib as _z

    body = bytearray(data)
    body[30] ^= 0xFF
    crc = _z.crc32(bytes(body[17:]))
    body[5:9] = crc.to_bytes(4, "little")
    with pytest.raises(Exception):
        deserialize_page(bytes(body))
    monkeypatch.delenv("TRINO_TPU_EXCHANGE_KEY")
    with pytest.raises(ValueError, match="encrypted"):
        deserialize_page(data)


def test_spool_first_commit_wins(tmp_path):
    ex = SpoolingExchange(str(tmp_path / "x"))
    assert ex.commit(0, 0, b"attempt0")
    assert not ex.commit(0, 1, b"attempt1")  # dedup: first commit wins
    assert ex.read(0) == b"attempt0"


def _setup(tmp_path, **kw):
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 11))
    s = e.create_session("tpch")
    plan = compile_sql(Q1, e, s)
    inj = FailureInjector()
    ex = FaultTolerantExecutor(e.catalogs, str(tmp_path / "spool"), injector=inj, **kw)
    expected = e.execute_sql(Q1, s).rows()
    return plan, inj, ex, expected


def test_fte_no_failures_matches_local(tmp_path):
    plan, inj, ex, expected = _setup(tmp_path)
    assert ex.execute(plan).rows() == expected


def test_fte_recovers_from_task_failures(tmp_path):
    plan, inj, ex, expected = _setup(tmp_path)
    inj.inject(0, "TASK_FAILURE", times=2)
    inj.inject(1, "TASK_GET_RESULTS_FAILURE", times=1)
    assert ex.execute(plan).rows() == expected
    assert ex.task_attempts[0] == 3  # two failed attempts + success
    assert ex.task_attempts[1] == 2


def test_fte_post_commit_failure_does_not_duplicate(tmp_path):
    plan, inj, ex, expected = _setup(tmp_path)
    inj.inject(2, "POST_COMMIT_FAILURE", times=1)
    assert ex.execute(plan).rows() == expected  # dedup: sums not doubled


def test_fte_exhausted_retries_fail_query(tmp_path):
    plan, inj, ex, _ = _setup(tmp_path, max_attempts=2)
    inj.inject(0, "TASK_FAILURE", times=5)
    with pytest.raises(RuntimeError, match="failed after 2 attempts"):
        ex.execute(plan)


def test_fte_join_query_via_engine(tmp_path):
    """Join above the scan-fed aggregate: FTE handles the aggregation stage and
    the remaining plan runs locally; engine entry point routes it."""
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 11))
    s = e.create_session("tpch")
    q = """select o_orderpriority, count(*) from orders
           group by o_orderpriority order by 1"""
    expected = e.execute_sql(q, s).rows()
    got = e.execute_sql(q, s, fault_tolerant=True).rows()
    assert got == expected


# ------------------------------------------------------------------- fragments
# round-2 generalization: the retryable unit is any blocking plan fragment
# (joins, windows, sorts included), not just scan-fed aggregations
# (reference: EventDrivenFaultTolerantQueryScheduler schedules arbitrary
# fragments whose inputs are replayable TaskDescriptors / spooled exchanges)

QJOIN = """select o_orderpriority, count(*) c
           from lineitem, orders
           where l_orderkey = o_orderkey and o_totalprice > 100000
           group by o_orderpriority order by o_orderpriority"""

QWINDOW = """select o_custkey, o_orderkey,
                    row_number() over (partition by o_custkey
                                       order by o_orderkey) rn
             from orders where o_custkey < 100
             order by o_custkey, o_orderkey limit 50"""


def _setup_q(tmp_path, sql, **kw):
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 11))
    s = e.create_session("tpch")
    plan = compile_sql(sql, e, s)
    inj = FailureInjector()
    ex = FaultTolerantExecutor(e.catalogs, str(tmp_path / "spool"),
                               injector=inj, **kw)
    expected = e.execute_sql(sql, s).rows()
    return plan, inj, ex, expected


def test_fte_mid_join_task_kill(tmp_path):
    """A join fragment task dies twice mid-execution and recovers — its inputs
    (scan splits) replay, its committed output dedups."""
    plan, inj, ex, expected = _setup_q(tmp_path, QJOIN)
    inj.inject("frag0", "TASK_FAILURE", times=2)  # frag0 = the join fragment
    assert ex.execute(plan).rows() == expected
    assert ex.task_attempts["frag0"] == 3


def test_fte_join_post_commit_failure_no_duplicates(tmp_path):
    plan, inj, ex, expected = _setup_q(tmp_path, QJOIN)
    inj.inject("frag0", "POST_COMMIT_FAILURE", times=1)
    inj.inject("frag1", "TASK_GET_RESULTS_FAILURE", times=1)
    assert ex.execute(plan).rows() == expected


def test_fte_window_fragment_retries(tmp_path):
    plan, inj, ex, expected = _setup_q(tmp_path, QWINDOW)
    inj.inject("frag0", "TASK_FAILURE", times=1)  # the window fragment
    assert ex.execute(plan).rows() == expected
    assert ex.task_attempts["frag0"] == 2


def test_fte_join_exhausted_retries(tmp_path):
    plan, inj, ex, _ = _setup_q(tmp_path, QJOIN, max_attempts=2)
    inj.inject("frag0", "TASK_FAILURE", times=5)
    with pytest.raises(RuntimeError, match="failed after 2 attempts"):
        ex.execute(plan)


class _FlakyGenerate:
    """Connector shim whose generate raises a REAL exception for the first
    ``fail_times`` calls — the reference's flaky-connector recovery shape
    (BaseFailureRecoveryTest exercises real task failures, not only injected
    ones)."""

    def __init__(self, conn, exc_factory, fail_times):
        self._orig = conn.generate
        self._exc = exc_factory
        self.left = fail_times

    def __call__(self, *a, **k):
        if self.left > 0:
            self.left -= 1
            raise self._exc()
        return self._orig(*a, **k)


def test_fte_retries_real_connector_failures(tmp_path):
    """A connector raising a genuine OSError mid-scan recovers under FTE (the
    retry loop classifies it retryable) but fails the plain executor."""
    from trino_tpu.exec.local_executor import LocalExecutor

    plan, inj, ex, expected = _setup(tmp_path)
    conn = ex.catalogs["tpch"]
    conn.generate = _FlakyGenerate(conn, lambda: OSError("simulated io loss"), 2)
    try:
        assert ex.execute(plan).rows() == expected
    finally:
        del conn.generate
    # without fault tolerance the same flake kills the query (the scan-fused
    # path regenerates on device without touching conn.generate — disable it
    # so the plain executor actually walks the flaky page source)
    conn.generate = _FlakyGenerate(conn, lambda: OSError("simulated io loss"), 2)
    plain = LocalExecutor(ex.catalogs)
    plain._run_aggregate_scan_fused = lambda *a, **k: None
    plain._run_global_scan_fused = lambda *a, **k: None
    try:
        with pytest.raises(OSError):
            plain.execute(plan)
    finally:
        del conn.generate


def test_fte_deterministic_errors_do_not_retry(tmp_path):
    """SemanticError-class failures would fail identically every attempt:
    they surface immediately instead of burning the retry budget."""
    plan, inj, ex, _ = _setup(tmp_path)
    conn = ex.catalogs["tpch"]
    conn.generate = _FlakyGenerate(
        conn, lambda: NotImplementedError("unsupported encoding"), 99)
    try:
        with pytest.raises(NotImplementedError):
            ex.execute(plan)
    finally:
        del conn.generate
    assert max(ex.task_attempts.values()) == 1  # no retries burned


def test_fte_consumes_spooled_join_output(tmp_path):
    """The aggregate above a join fragment must read the join's SPOOLED page,
    not re-execute the join from its cached stream (the join would silently run
    twice): under FTE every scan split generates exactly as many pages as one
    local execution pulls."""
    from trino_tpu.exec.local_executor import LocalExecutor

    plan, inj, ex, expected = _setup_q(tmp_path, QJOIN)
    conn = ex.catalogs["tpch"]
    calls = []
    orig = conn.generate
    conn.generate = lambda *a, **k: (calls.append(1), orig(*a, **k))[1]
    try:
        assert ex.execute(plan).rows() == expected
        fte_calls = len(calls)
        calls.clear()
        LocalExecutor(ex.catalogs).execute(plan)
        local_calls = len(calls)
    finally:
        del conn.generate
    assert fte_calls == local_calls


def test_fte_engine_join_fault_tolerant(tmp_path):
    """Engine-level fault_tolerant execution of a join+window plan matches the
    plain path."""
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 11))
    s = e.create_session("tpch")
    q = QJOIN
    expected = e.execute_sql(q, s).rows()
    got = e.execute_sql(q, s, fault_tolerant=True).rows()
    assert got == expected


def test_adaptive_join_side_swap(tmp_path):
    """Adaptive replanning (reference: AdaptivePlanner.java:121): once both
    join children materialize, actual row counts replace estimates — a build
    side that materialized clearly larger than the probe swaps sides, with a
    projection restoring column order; results are identical."""
    from trino_tpu import Engine
    from trino_tpu.connectors.tpch import TpchConnector

    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01))
    s = e.create_session("tpch")
    sql = """
        select a.k, a.ca, b.cb from
         (select s_suppkey k, count(*) ca from supplier
          where s_suppkey <= 3 group by s_suppkey) a
         join (select o_custkey k, count(*) cb from orders
               group by o_custkey) b
         on a.k = b.k
        order by a.k"""
    plain = e.execute_sql(sql, s).to_pandas()
    fte = e.execute_sql(sql, s, fault_tolerant=True).to_pandas()
    assert plain.values.tolist() == fte.values.tolist()
    # the 3-row build vs 1500-group probe inversion must have triggered a swap
    assert getattr(e._fte_executor, "adaptive_swaps", 0) >= 1
