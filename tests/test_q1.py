"""TPC-H Q1 end-to-end over a hand-built plan, validated against a pandas oracle
(SURVEY.md §4: the reference cross-checks DistributedQueryRunner results against H2)."""

import numpy as np
import pytest

from trino_tpu.page import Schema
from trino_tpu.sql import plan as P
from trino_tpu.sql.ir import Call, Constant, FieldRef
from trino_tpu.types import BIGINT, DecimalType, parse_date_literal
from trino_tpu.connectors.tpch import TPCH_SCHEMAS

DEC2 = DecimalType.of(15, 2)
DEC4 = DecimalType.of(18, 4)
DEC6 = DecimalType.of(18, 6)


def build_q1_plan():
    cols = ("l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
            "l_discount", "l_tax", "l_shipdate")
    lineitem = TPCH_SCHEMAS["lineitem"]
    scan_schema = Schema(tuple(lineitem.field(c) for c in cols))
    scan = P.TableScan("tpch", "lineitem", cols, scan_schema)

    ship = FieldRef(6, scan_schema.fields[6].type, "l_shipdate")
    cutoff = parse_date_literal("1998-12-01") - 90
    filt = P.Filter(scan, Call("lte", (ship, Constant(cutoff, ship.type)), __import__(
        "trino_tpu.types", fromlist=["BOOLEAN"]).BOOLEAN))

    rf = FieldRef(0, scan_schema.fields[0].type, "l_returnflag")
    ls = FieldRef(1, scan_schema.fields[1].type, "l_linestatus")
    qty = FieldRef(2, DEC2, "l_quantity")
    price = FieldRef(3, DEC2, "l_extendedprice")
    disc = FieldRef(4, DEC2, "l_discount")
    tax = FieldRef(5, DEC2, "l_tax")
    one2 = Constant(100, DEC2)  # literal 1 at scale 2
    disc_price = Call("multiply", (price, Call("subtract", (one2, disc), DEC2)), DEC4)
    charge = Call("multiply", (disc_price, Call("add", (one2, tax), DEC2)), DEC6)

    proj_schema = Schema.of(
        ("l_returnflag", rf.type), ("l_linestatus", ls.type), ("qty", DEC2),
        ("price", DEC2), ("disc_price", DEC4), ("charge", DEC6), ("disc", DEC2),
    )
    proj = P.Project(filt, (rf, ls, qty, price, disc_price, charge, disc), proj_schema)

    aggs = (
        P.AggSpec("sum", FieldRef(2, DEC2), "sum_qty", DEC2),
        P.AggSpec("sum", FieldRef(3, DEC2), "sum_base_price", DEC2),
        P.AggSpec("sum", FieldRef(4, DEC4), "sum_disc_price", DEC4),
        P.AggSpec("sum", FieldRef(5, DEC6), "sum_charge", DEC6),
        P.AggSpec("avg", FieldRef(2, DEC2), "avg_qty", DEC2),
        P.AggSpec("avg", FieldRef(3, DEC2), "avg_price", DEC2),
        P.AggSpec("avg", FieldRef(6, DEC2), "avg_disc", DEC2),
        P.AggSpec("count_star", None, "count_order", BIGINT),
    )
    agg_schema = Schema(
        (proj_schema.fields[0], proj_schema.fields[1])
        + tuple(__import__("trino_tpu.page", fromlist=["Field"]).Field(a.name, a.type) for a in aggs)
    )
    agg = P.Aggregate(proj, (0, 1), aggs, agg_schema, capacity=64)
    sort = P.Sort(agg, (P.SortKey(0), P.SortKey(1)))
    return P.Output(sort, tuple(f.name for f in agg_schema.fields))


def oracle_q1(tpch_pandas):
    li = tpch_pandas["lineitem"]
    cutoff = np.datetime64("1998-12-01") - np.timedelta64(90, "D")
    df = li[li["l_shipdate"].to_numpy().astype("datetime64[D]") <= cutoff].copy()
    df["disc_price"] = df.l_extendedprice * (1 - df.l_discount)
    df["charge"] = df.disc_price * (1 + df.l_tax)
    g = df.groupby(["l_returnflag", "l_linestatus"], as_index=False).agg(
        sum_qty=("l_quantity", "sum"),
        sum_base_price=("l_extendedprice", "sum"),
        sum_disc_price=("disc_price", "sum"),
        sum_charge=("charge", "sum"),
        avg_qty=("l_quantity", "mean"),
        avg_price=("l_extendedprice", "mean"),
        avg_disc=("l_discount", "mean"),
        count_order=("l_quantity", "size"),
    )
    return g.sort_values(["l_returnflag", "l_linestatus"]).reset_index(drop=True)


def test_q1(engine, tpch_pandas):
    result = engine.execute_plan(build_q1_plan())
    expected = oracle_q1(tpch_pandas)
    got = result.to_pandas()
    assert len(got) == len(expected) > 0
    assert list(got["l_returnflag"]) == list(expected["l_returnflag"])
    assert list(got["l_linestatus"]) == list(expected["l_linestatus"])
    for col in ("sum_qty", "sum_base_price", "sum_disc_price", "sum_charge", "count_order"):
        np.testing.assert_allclose(
            got[col].to_numpy(np.float64), expected[col].to_numpy(np.float64),
            rtol=1e-9, err_msg=col)
    for col in ("avg_qty", "avg_price", "avg_disc"):
        np.testing.assert_allclose(
            got[col].to_numpy(np.float64), expected[col].to_numpy(np.float64),
            atol=0.01, err_msg=col)  # engine rounds decimal avg to column scale


def test_lineitem_rowcount_plausible(tpch_pandas):
    n = len(tpch_pandas["lineitem"])
    orders = len(tpch_pandas["orders"])
    assert orders * 1 <= n <= orders * 7
    assert abs(n / orders - 4.0) < 0.1  # mean lines/order ≈ 4


def test_referential_integrity(tpch_pandas):
    li = tpch_pandas["lineitem"]
    assert li["l_orderkey"].isin(tpch_pandas["orders"]["o_orderkey"]).all()
    assert li["l_partkey"].between(1, len(tpch_pandas["part"])).all()
    assert li["l_suppkey"].between(1, len(tpch_pandas["supplier"])).all()
    assert tpch_pandas["orders"]["o_custkey"].isin(tpch_pandas["customer"]["c_custkey"]).all()
