"""Pallas kernel parity vs the XLA paths (interpret mode on CPU; compiled
Mosaic on TPU).

Round-13 contract (ops/pallas_kernels.py docstring):
- hash_probe is BIT-identical to the XLA while_loop probe given the same
  table (same hash family, same probe order, same MAX_PROBES/EMPTY
  semantics).
- hash_insert resolves slot contention by min row index instead of
  scatter-min over packed words, so the slot LAYOUT may differ from the XLA
  table; both protocols keep the open-addressing chain invariant, so parity
  is pinned on OBSERVABLES: placed sets, table word sets, table[slot] ==
  packed, and probe results against either table.  Never assert raw slot
  order across backends.
- compact_rows / bucketize are byte-identical.
- engine results are byte-identical between TRINO_TPU_PALLAS=0 and =1
  (pallas_kernels.force + jax.clear_caches between modes: the choice is
  baked into cached executables at trace time).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from trino_tpu.ops import hashagg, hashjoin, pallas_kernels as pk
from trino_tpu.ops.arrays import compact_rows
from trino_tpu.ops.exchange import bucketize
from trino_tpu.ops.hashing import (EMPTY_KEY, ceil_pow2, pack_keys, probe_step,
                                   splitmix64)
from trino_tpu.ops.pallas_kernels import fused_segment_agg
from trino_tpu.types import BIGINT, INTEGER

INTERPRET = jax.default_backend() != "tpu"


@pytest.fixture
def forced(request):
    """Run a test body under both backends cleanly: force(mode) +
    jax.clear_caches() per switch, always restored."""
    def run(fn):
        out = {}
        for mode in (False, True):
            pk.force(mode)
            jax.clear_caches()
            try:
                out[mode] = fn()
            finally:
                pk.force(None)
        jax.clear_caches()
        return out[False], out[True]
    return run


def _xla_probe(table, rows, packed, valid):
    """The hashjoin.probe while_loop body, pinned here so the parity baseline
    cannot silently change backends."""
    C = table.shape[0] - 1
    h0 = splitmix64(packed)
    stp = probe_step(h0)
    row_ids = jnp.zeros(packed.shape, jnp.int32)
    matched = jnp.zeros(packed.shape, bool)
    done = ~valid

    def cond(c):
        return (c[0] < hashjoin.MAX_PROBES) & ~jnp.all(c[3])

    def body(c):
        p, r, m, d = c
        idx = ((h0 + p * stp) & (C - 1)).astype(jnp.int32)
        cur = table[idx]
        hit = (cur == packed) & ~d
        r = jnp.where(hit, rows[idx], r)
        m = m | hit
        d = d | hit | (cur == EMPTY_KEY)
        return p + 1, r, m, d

    _, r, m, _ = jax.lax.while_loop(
        cond, body, (jnp.zeros((), jnp.int32), row_ids, matched, done))
    return r, m


def _build_xla(keys, C, valid=None):
    n = keys.shape[0]
    if valid is None:
        valid = jnp.ones((n,), bool)
    packed, _ = pack_keys((keys,), (BIGINT,))
    packed = jnp.where(valid, packed, EMPTY_KEY - 1)
    table0 = jnp.full((C + 1,), EMPTY_KEY, jnp.int64)
    pk.force(False)
    try:
        table, slot, placed = hashagg._probe_insert(table0, packed, valid)
    finally:
        pk.force(None)
    rows = jnp.full((C + 1,), 2**31 - 1, jnp.int32).at[
        jnp.where(placed & valid, slot, C)].min(
        jnp.arange(n, dtype=jnp.int32)).at[C].set(0)
    return table, rows, packed


@pytest.mark.parametrize("nb,C_req,npr,seed", [
    (100, 256, 1000, 0),
    (1000, 1024, 4096, 1),
    (256, 256, 512, 2),   # table at 100% load: wraparound + MAX_PROBES paths
    (5, 8, 64, 3),        # capacity < MAX_PROBES: chain revisits slots
])
def test_hash_probe_bit_parity(nb, C_req, npr, seed):
    """Same table -> pallas probe must be BIT-identical to the XLA loop,
    across present keys, absent keys (EMPTY termination and probe
    exhaustion) and invalid lanes."""
    rng = np.random.default_rng(seed)
    C = ceil_pow2(C_req)
    bkeys = jnp.asarray(rng.choice(np.arange(1, 20 * nb), nb,
                                   replace=False).astype(np.int64))
    table, rows, _ = _build_xla(bkeys, C)
    pool = np.concatenate([np.asarray(bkeys), np.asarray(bkeys).max() + 1
                           + np.arange(nb)])
    probe_keys = jnp.asarray(rng.choice(pool, npr))
    valid = jnp.asarray(rng.random(npr) < 0.9)
    packed, _ = pack_keys((probe_keys,), (BIGINT,))
    r_x, m_x = _xla_probe(table, rows, packed, valid)
    h0 = splitmix64(packed)
    r_p, m_p = pk.hash_probe(table[:C], rows[:C], packed, h0, probe_step(h0),
                             valid, interpret=INTERPRET)
    assert np.array_equal(np.asarray(m_x), np.asarray(m_p))
    assert np.array_equal(np.asarray(r_x), np.asarray(r_p))


def test_hash_probe_all_invalid_and_empty_table():
    C = 64
    table = jnp.full((C + 1,), EMPTY_KEY, jnp.int64)
    rows = jnp.zeros((C + 1,), jnp.int32)
    keys = jnp.arange(32, dtype=jnp.int64)
    packed, _ = pack_keys((keys,), (BIGINT,))
    h0 = splitmix64(packed)
    # empty table: every probe terminates at round 0 EMPTY
    r, m = pk.hash_probe(table[:C], rows[:C], packed, h0, probe_step(h0),
                         jnp.ones((32,), bool), interpret=INTERPRET)
    assert not bool(m.any()) and not bool((r != 0).any())
    # all-invalid lanes: nothing matches regardless of table contents
    full_table, frows, _ = _build_xla(keys, C)
    r, m = pk.hash_probe(full_table[:C], frows[:C], packed, h0, probe_step(h0),
                         jnp.zeros((32,), bool), interpret=INTERPRET)
    assert not bool(m.any()) and not bool((r != 0).any())


def test_hash_probe_dictionary_id_key_mix():
    """Multi-column key: int64 + int32 dictionary ids through pack_keys —
    the packed-word compare in-kernel must agree with the XLA loop."""
    rng = np.random.default_rng(4)
    n, C = 512, 1024
    k64 = rng.integers(0, 1 << 20, n).astype(np.int64)
    k32 = rng.integers(0, 500, n).astype(np.int32)  # dictionary-id shaped
    # stats-derived ranges keep the two-column pack injective (the planner's
    # TupleDomain path): 21 + 9 bits << 62
    packed, exact = pack_keys((jnp.asarray(k64), jnp.asarray(k32)),
                              (BIGINT, INTEGER),
                              ranges=((0, 1 << 20), (0, 499)))
    assert exact
    table0 = jnp.full((C + 1,), EMPTY_KEY, jnp.int64)
    table, slot, placed = hashagg._probe_insert(table0, packed,
                                                jnp.ones((n,), bool))
    rows = jnp.arange(C + 1, dtype=jnp.int32)
    valid = jnp.asarray(rng.random(n) < 0.8)
    r_x, m_x = _xla_probe(table, rows, packed, valid)
    h0 = splitmix64(packed)
    r_p, m_p = pk.hash_probe(table[:C], rows[:C], packed, h0, probe_step(h0),
                             valid, interpret=INTERPRET)
    assert np.array_equal(np.asarray(m_x), np.asarray(m_p))
    assert np.array_equal(np.asarray(r_x), np.asarray(r_p))


@pytest.mark.parametrize("n,C_req,dup,seed", [
    (1000, 4096, False, 0),
    (1000, 1024, True, 1),
    (512, 512, False, 2),   # table ends at 100% load
    (30, 32, True, 3),
])
def test_hash_insert_observable_parity(n, C_req, dup, seed):
    """hash_insert vs the XLA claim protocol on the layout-independent
    observables: identical placed lanes, identical table word sets, slot ->
    packed consistency, and identical probe results over either table."""
    rng = np.random.default_rng(seed)
    C = ceil_pow2(C_req)
    keys = (rng.integers(1, n, n) if dup
            else rng.choice(np.arange(1, 20 * n), n, replace=False)).astype(np.int64)
    valid = jnp.asarray(rng.random(n) < 0.85)
    packed, _ = pack_keys((jnp.asarray(keys),), (BIGINT,))
    packed = jnp.where(valid, packed, EMPTY_KEY - 1)
    t0 = jnp.full((C + 1,), EMPTY_KEY, jnp.int64)
    pk.force(False)
    try:
        tx, sx, px = hashagg._probe_insert(t0, packed, valid)
    finally:
        pk.force(None)
    tp, sp, pp = pk.hash_insert(t0, packed, valid, interpret=INTERPRET)
    assert np.array_equal(np.asarray(px), np.asarray(pp))
    assert np.array_equal(np.sort(np.asarray(tx[:C])), np.sort(np.asarray(tp[:C])))
    assert int(tp[C]) == EMPTY_KEY
    live = np.asarray(valid & pp)
    assert np.array_equal(np.asarray(tp)[np.asarray(sp)[live]],
                          np.asarray(packed)[live])
    rows = jnp.arange(C + 1, dtype=jnp.int32)
    pv = jnp.ones((n,), bool)
    _, m1 = _xla_probe(tx, rows, packed, pv)
    s2, m2 = _xla_probe(tp, rows, packed, pv)
    assert np.array_equal(np.asarray(m1), np.asarray(m2))
    # the slot a probe finds in the pallas table holds the probed key
    mm = np.asarray(m2)
    assert np.array_equal(np.asarray(tp)[np.asarray(s2)[mm]],
                          np.asarray(packed)[mm])


def test_hash_insert_multi_page_state_threading():
    """A table built page-by-page (the groupby state threading shape) stays
    chain-consistent: page 2's duplicate keys must find page 1's slots."""
    rng = np.random.default_rng(5)
    C = 1024
    k1 = rng.choice(np.arange(1, 5000), 400, replace=False).astype(np.int64)
    k2 = np.concatenate([k1[:200], 5000 + np.arange(200)]).astype(np.int64)
    p1, _ = pack_keys((jnp.asarray(k1),), (BIGINT,))
    p2, _ = pack_keys((jnp.asarray(k2),), (BIGINT,))
    t = jnp.full((C + 1,), EMPTY_KEY, jnp.int64)
    t, s1, pl1 = pk.hash_insert(t, p1, jnp.ones((400,), bool), interpret=INTERPRET)
    t, s2, pl2 = pk.hash_insert(t, p2, jnp.ones((400,), bool), interpret=INTERPRET)
    assert bool(pl1.all()) and bool(pl2.all())
    # repeated keys landed on their page-1 slots
    assert np.array_equal(np.asarray(s2[:200]), np.asarray(s1[:200]))
    assert int(jnp.sum(t[:C] != EMPTY_KEY)) == 600


def test_groupby_insert_backend_equivalence(forced):
    """End-to-end hashagg: same groups/accumulators from either backend
    (compared as key -> value maps; slot order is backend-private)."""
    rng = np.random.default_rng(6)
    n = 2000
    keys = jnp.asarray(rng.integers(0, 300, n))
    vals = jnp.asarray(rng.random(n))
    valid = jnp.asarray(rng.random(n) < 0.9)

    def run():
        state = hashagg.groupby_init(1024, (np.int64,), ((np.float64, 0.0),))
        state = hashagg.groupby_insert(state, (keys,), (BIGINT,), valid,
                                       [(vals, None)], ["sum"])
        occ, (k,), (acc,) = hashagg.agg_finalize(state)
        occ = np.asarray(occ)
        return dict(zip(np.asarray(k)[occ].tolist(),
                        np.round(np.asarray(acc)[occ], 9).tolist()))

    ref, got = forced(run)
    assert ref == got


@pytest.mark.parametrize("n,sel,bucket", [
    (1000, 0.1, 256), (4096, 0.5, 4096), (512, 0.0, 64),
    (300, 1.0, 100),  # live rows overflow the bucket: clamp/drop path
    (100, 0.5, 200),  # out_len > n: invalid rows must still DROP, not leak
])
def test_compact_rows_byte_parity(n, sel, bucket, forced):
    rng = np.random.default_rng(int(n + bucket))
    valid = jnp.asarray(rng.random(n) < sel)
    cols = (jnp.asarray(rng.integers(-2**62, 2**62, n)),
            jnp.asarray(rng.random(n)),
            jnp.asarray(rng.integers(0, 2**31, n).astype(np.int32)),
            jnp.asarray(rng.random(n).astype(np.float32)),
            jnp.asarray(rng.random(n) < 0.5),
            None)

    def run():
        packed, total = compact_rows(cols, valid, bucket)
        return ([None if p is None else np.asarray(p) for p in packed],
                int(total))

    (ref, rt), (got, gt) = forced(run)
    assert rt == gt == int(valid.sum())
    for r, g in zip(ref, got):
        if r is None:
            assert g is None
        else:
            assert r.dtype == g.dtype and np.array_equal(r, g)
    # the documented contract, independent of backend agreement: zeros
    # beyond the live count (an out_len > n leak once survived review)
    live = min(int(valid.sum()), bucket)
    assert not np.any(ref[0][live:])


def test_bucketize_byte_parity(forced):
    rng = np.random.default_rng(8)
    n, P, bucket = 2048, 8, 320
    cols = (jnp.asarray(rng.integers(0, 1 << 40, n)),
            jnp.asarray(rng.random(n)),
            jnp.asarray(rng.random(n) < 0.5))
    valid = jnp.asarray(rng.random(n) < 0.9)
    pid = jnp.asarray(rng.integers(0, P, n).astype(np.int32))

    def run():
        packed, pvalid, oflow = bucketize(cols, valid, pid, P, bucket)
        return ([np.asarray(c) for c in packed], np.asarray(pvalid),
                bool(oflow))

    (rc, rv, ro), (gc, gv, go) = forced(run)
    assert ro == go
    assert np.array_equal(rv, gv)
    for r, g in zip(rc, gc):
        assert np.array_equal(r, g)


def test_shard_map_pallas_parity(forced):
    """The kernels as the DISTRIBUTED path runs them — inside shard_map over
    the 8-device CPU mesh: bucketize + all_to_all routing, and per-worker
    insert + probe_slots with a REPLICATED build side against varying probe
    keys (the round-5 varying-axis shape).  use_pallas() is OFF by default on
    this mesh, so without this test the shard_map Pallas traces would first
    execute on the real TPU inside the one-shot tunnel window."""
    from functools import partial

    from jax.sharding import NamedSharding, PartitionSpec as PS

    from trino_tpu.exec.distributed import shard_map
    from trino_tpu.parallel.mesh import WORKER_AXIS, worker_mesh

    W = min(8, len(jax.devices()))
    if W < 2:
        pytest.skip("needs a multi-device mesh")
    per, C = 256, 1024
    rng = np.random.default_rng(9)
    mesh = worker_mesh(W)
    pkeys = jax.device_put(jnp.asarray(rng.integers(1, 4000, (W, per))),
                           NamedSharding(mesh, PS(WORKER_AXIS)))
    bkeys = jnp.asarray(rng.choice(np.arange(1, 4000), 500,
                                   replace=False).astype(np.int64))

    def frag(pk_keys, bkeys):
        from trino_tpu.ops.exchange import bucketize, exchange_all_to_all

        k = pk_keys[0]
        pid = (k % W).astype(jnp.int32)
        packed, pvalid, _ = bucketize((k,), jnp.ones_like(k, bool), pid, W,
                                      per)
        recv, rvalid = exchange_all_to_all(packed, pvalid, WORKER_AXIS, W)
        bpacked, _ = pack_keys((bkeys,), (BIGINT,))
        t0 = jnp.full((C + 1,), EMPTY_KEY, jnp.int64)
        table, _, _ = hashagg._probe_insert(t0, bpacked,
                                            jnp.ones(bkeys.shape, bool))
        slot, matched = hashjoin.probe_slots(table, (recv[0],), (BIGINT,),
                                             rvalid)
        # slot layout is backend-private: reduce to the layout-independent
        # observable (the probed key word where matched)
        found = jnp.where(matched, table[slot], 0)
        return found[None], matched[None]

    def run():
        f = partial(shard_map, mesh=mesh, in_specs=(PS(WORKER_AXIS), PS()),
                    out_specs=(PS(WORKER_AXIS), PS(WORKER_AXIS)))(frag)
        found, matched = jax.jit(f)(pkeys, bkeys)
        return np.asarray(found), np.asarray(matched)

    (f_x, m_x), (f_p, m_p) = forced(run)
    assert np.array_equal(m_x, m_p)
    assert np.array_equal(f_x, f_p)
    assert m_x.any()  # the probe actually matched something


# ------------------------------------------------------------ engine tier-1
# Byte-identity of full statements between TRINO_TPU_PALLAS=0 and =1.  q1/q3
# are the ISSUE's pinned pair; the planner's direct-index paths bypass the
# hash kernels for TPC-H's dense keys, so two hash-shaped statements ride
# along (multi-column join key -> JoinTable probe; expression group-by key ->
# unknown ranges -> _probe_insert) and the test asserts the pallas branch
# actually fired for them.
_ENGINE_STMTS = {
    "q1": None,  # filled from chaos_matrix below
    "q3": None,
    "join2": ("select count(*) c, sum(ps_availqty) s from lineitem l "
              "join partsupp ps on l.l_partkey = ps.ps_partkey "
              "and l.l_suppkey = ps.ps_suppkey"),
    "aggexpr": ("select l_orderkey % 97 as k, count(*) c, sum(l_quantity) q "
                "from lineitem group by l_orderkey % 97 order by k"),
}


def test_engine_results_byte_identical_across_backends(monkeypatch):
    from trino_tpu import Engine
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.execution.chaos_matrix import QUERIES, result_signature

    stmts = dict(_ENGINE_STMTS)
    stmts["q1"] = QUERIES["q1"]
    stmts["q3"] = QUERIES["q3"]

    picks = {"probe": 0, "insert": 0}
    real_probe, real_insert = pk.hash_probe, pk.hash_insert

    def count_probe(*a, **k):
        picks["probe"] += 1
        return real_probe(*a, **k)

    def count_insert(*a, **k):
        picks["insert"] += 1
        return real_insert(*a, **k)

    monkeypatch.setattr(pk, "hash_probe", count_probe)
    monkeypatch.setattr(pk, "hash_insert", count_insert)

    sigs = {}
    for mode in (False, True):
        pk.force(mode)
        jax.clear_caches()
        try:
            e = Engine()
            e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=4096))
            s = e.create_session("tpch")
            sigs[mode] = {n: result_signature(e.execute_sql(q, s))
                          for n, q in stmts.items()}
        finally:
            pk.force(None)
    jax.clear_caches()
    for name in stmts:
        assert sigs[False][name] == sigs[True][name], name
    # the hash-shaped statements must have taken the pallas branch
    assert picks["probe"] >= 1 and picks["insert"] >= 1, picks


# ----------------------------------------------------- fused segment agg (r3)
def test_fused_segment_agg_matches_numpy():
    rng = np.random.default_rng(7)
    n, C = 10_000, 8
    slot = rng.integers(0, C, n).astype(np.int32)
    valid = rng.random(n) < 0.8
    v1 = rng.random(n)
    v2 = rng.random(n) * 10
    counts, (s1, s2) = fused_segment_agg(
        jax.numpy.asarray(slot), jax.numpy.asarray(valid),
        (jax.numpy.asarray(v1), jax.numpy.asarray(v2)), n_slots=C,
        interpret=INTERPRET)
    for c in range(C):
        m = valid & (slot == c)
        assert int(counts[c]) == int(m.sum())
        assert np.isclose(float(s1[c]), v1[m].sum(), rtol=1e-5)
        assert np.isclose(float(s2[c]), v2[m].sum(), rtol=1e-5)


def test_fused_segment_agg_no_values():
    slot = jax.numpy.asarray(np.array([0, 1, 1, 2, 2, 2], np.int32))
    valid = jax.numpy.asarray(np.array([True] * 5 + [False]))
    counts, sums = fused_segment_agg(slot, valid, (), n_slots=4,
                                     interpret=INTERPRET)
    assert list(np.asarray(counts)) == [1, 2, 2, 0]
    assert sums == ()
