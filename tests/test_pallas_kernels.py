"""Pallas fused segment aggregation vs numpy (interpret mode on CPU;
compiled Mosaic on TPU)."""

import jax
import numpy as np

from trino_tpu.ops.pallas_kernels import fused_segment_agg

INTERPRET = jax.default_backend() != "tpu"


def test_fused_segment_agg_matches_numpy():
    rng = np.random.default_rng(7)
    n, C = 10_000, 8
    slot = rng.integers(0, C, n).astype(np.int32)
    valid = rng.random(n) < 0.8
    v1 = rng.random(n)
    v2 = rng.random(n) * 10
    counts, (s1, s2) = fused_segment_agg(
        jax.numpy.asarray(slot), jax.numpy.asarray(valid),
        (jax.numpy.asarray(v1), jax.numpy.asarray(v2)), n_slots=C,
        interpret=INTERPRET)
    for c in range(C):
        m = valid & (slot == c)
        assert int(counts[c]) == int(m.sum())
        assert np.isclose(float(s1[c]), v1[m].sum(), rtol=1e-5)
        assert np.isclose(float(s2[c]), v2[m].sum(), rtol=1e-5)


def test_fused_segment_agg_no_values():
    slot = jax.numpy.asarray(np.array([0, 1, 1, 2, 2, 2], np.int32))
    valid = jax.numpy.asarray(np.array([True] * 5 + [False]))
    counts, sums = fused_segment_agg(slot, valid, (), n_slots=4,
                                     interpret=INTERPRET)
    assert list(np.asarray(counts)) == [1, 2, 2, 0]
    assert sums == ()
