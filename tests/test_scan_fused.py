"""Scan-fused aggregation: the whole scan (generate -> filter/project/join
probes -> group insert) runs inside one ``lax.scan`` over split offsets — O(1)
host dispatches instead of O(splits) (reference analog: the zero-per-page
scheduler cost of operator/Driver.java:372-481, re-designed for tunneled TPUs
where every dispatch pays a host round-trip)."""

import numpy as np
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector


@pytest.fixture()
def feng(monkeypatch):
    """Engine with a counter on the fused path: calls['n'] counts fused-path
    executions that actually took the query (returned a result).  The fused
    paths gate off on the CPU backend by default; force them on here."""
    import trino_tpu.exec.local_executor as LE

    monkeypatch.setenv("TRINO_TPU_SCAN_FUSED", "1")

    calls = {"n": 0, "global": 0}
    orig = LE.LocalExecutor._run_aggregate_scan_fused
    orig_g = LE.LocalExecutor._run_global_scan_fused

    def counting(self, *a, **k):
        out = orig(self, *a, **k)
        if out is not None:
            calls["n"] += 1
        return out

    def counting_g(self, *a, **k):
        out = orig_g(self, *a, **k)
        if out is not None:
            calls["global"] += 1
        return out

    monkeypatch.setattr(LE.LocalExecutor, "_run_aggregate_scan_fused", counting)
    monkeypatch.setattr(LE.LocalExecutor, "_run_global_scan_fused", counting_g)
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.02, split_rows=1 << 13))
    return e, e.create_session("tpch"), calls


def _oracle(sql):
    """Same query with the fused paths disabled (page-loop execution)."""
    import trino_tpu.exec.local_executor as LE

    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.02, split_rows=1 << 13))
    s = e.create_session("tpch")
    orig = LE.LocalExecutor._run_aggregate_scan_fused
    orig_g = LE.LocalExecutor._run_global_scan_fused
    LE.LocalExecutor._run_aggregate_scan_fused = lambda self, *a, **k: None
    LE.LocalExecutor._run_global_scan_fused = lambda self, *a, **k: None
    try:
        return e.execute_sql(sql, s).to_pandas()
    finally:
        LE.LocalExecutor._run_aggregate_scan_fused = orig
        LE.LocalExecutor._run_global_scan_fused = orig_g


def test_fused_direct_groupby(feng):
    e, s, calls = feng
    sql = ("select l_returnflag, l_linestatus, sum(l_quantity) q, count(*) c "
           "from lineitem where l_shipdate <= date '1998-09-02' "
           "group by l_returnflag, l_linestatus "
           "order by l_returnflag, l_linestatus")
    got = e.execute_sql(sql, s).to_pandas()
    assert calls["n"] == 1, "fused path did not take the grouped aggregation"
    exp = _oracle(sql)
    assert got.values.tolist() == exp.values.tolist()


def test_fused_hash_groupby_after_join(feng):
    e, s, calls = feng
    sql = ("select l_orderkey, sum(l_extendedprice * (1 - l_discount)) rev "
           "from orders, lineitem "
           "where l_orderkey = o_orderkey and o_orderdate < date '1995-03-15' "
           "and l_shipdate > date '1995-03-15' "
           "group by l_orderkey order by rev desc, l_orderkey limit 10")
    got = e.execute_sql(sql, s).to_pandas()
    assert calls["n"] >= 1, "fused path did not take the join+agg pipeline"
    exp = _oracle(sql)
    assert np.allclose(got["rev"].values, exp["rev"].values)
    assert got["l_orderkey"].values.tolist() == exp["l_orderkey"].values.tolist()


def test_fused_global_agg(feng):
    e, s, calls = feng
    sql = ("select count(*) c, sum(l_extendedprice) se, min(l_discount) mn, "
           "max(l_tax) mx from lineitem where l_discount > 0.03")
    got = e.execute_sql(sql, s).to_pandas()
    assert calls["global"] == 1, "fused path did not take the global aggregation"
    exp = _oracle(sql)
    assert got.values.tolist() == exp.values.tolist()


def test_fused_growth_on_undersized_capacity(feng):
    """A tiny session capacity forces in-fused-path overflow: the table grows
    4x and the scan re-runs; results stay exact."""
    e, s, calls = feng
    e.execute_sql("set session group_by_capacity = 64", s)
    sql = ("select l_suppkey, count(*) c from lineitem "
           "group by l_suppkey order by l_suppkey limit 20")
    got = e.execute_sql(sql, s).to_pandas()
    assert calls["n"] >= 1
    exp = _oracle(sql)
    assert got.values.tolist() == exp.values.tolist()


def test_fused_semi_join_agg(feng):
    """EXISTS semi join (dynamic-filter pruned splits) feeding an aggregation:
    the kept-split list must flow into the fused scan."""
    e, s, calls = feng
    sql = ("select o_orderpriority, count(*) c from orders "
           "where o_orderdate >= date '1993-07-01' "
           "and o_orderdate < date '1993-10-01' "
           "and exists (select 1 from lineitem where l_orderkey = o_orderkey "
           "and l_commitdate < l_receiptdate) "
           "group by o_orderpriority order by o_orderpriority")
    got = e.execute_sql(sql, s).to_pandas()
    exp = _oracle(sql)
    assert got.values.tolist() == exp.values.tolist()
