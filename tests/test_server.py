"""Client protocol tests: coordinator HTTP server + paging client + CLI formatting
(reference pattern: DistributedQueryRunner boots real servers on ephemeral ports in one
process, testing/trino-testing/DistributedQueryRunner.java:108)."""

import pytest


@pytest.fixture(scope="module")
def coordinator(tpch_sf001):
    from trino_tpu import Engine
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.server import CoordinatorServer

    e = Engine()
    e.register_catalog("tpch", tpch_sf001)
    e.register_catalog("memory", MemoryConnector())
    srv = CoordinatorServer(e, port=0)  # ephemeral port
    srv.start()
    yield srv
    srv.stop()


def test_protocol_roundtrip(coordinator):
    from trino_tpu.server import Client

    c = Client(coordinator.url, catalog="tpch")
    r = c.execute("select n_name, n_regionkey from nation "
                  "where n_regionkey = 3 order by n_name")
    assert r.column_names == ["n_name", "n_regionkey"]
    assert [row[0] for row in r.rows] == ["FRANCE", "GERMANY", "ROMANIA",
                                         "RUSSIA", "UNITED KINGDOM"]


def test_protocol_paging(coordinator):
    from trino_tpu.server import Client

    c = Client(coordinator.url, catalog="tpch")
    r = c.execute("select o_orderkey from orders order by o_orderkey limit 10000")
    assert len(r.rows) == 10000  # > DATA_ROWS_PER_FETCH -> multiple nextUri pages
    assert r.rows[0][0] == 1


def test_protocol_error(coordinator):
    from trino_tpu.server import Client, client as _client

    c = Client(coordinator.url, catalog="tpch")
    with pytest.raises(_client.QueryError, match="no_such_table"):
        c.execute("select * from no_such_table")


def test_protocol_ddl(coordinator):
    from trino_tpu.server import Client

    c = Client(coordinator.url, catalog="memory")
    c.execute("create table srv_t (a bigint)")
    c.execute("insert into srv_t values (41), (42)")
    r = c.execute("select max(a) m from srv_t")
    assert r.rows == [[42]]
    c.execute("drop table srv_t")


def test_query_info(coordinator):
    import json
    import urllib.request

    from trino_tpu.server import Client

    c = Client(coordinator.url, catalog="tpch")
    c.execute("select 1 as one from region limit 1")
    # newest by creation time, NOT string order: query ids are a process-wide
    # sequence ("q9" > "q10" lexically), so the string sort picks a stale —
    # possibly FAILED — query once the module's ids cross a digit boundary
    qid = max(coordinator.queries.values(),
              key=lambda q: q.created_at).query_id
    with urllib.request.urlopen(f"{coordinator.url}/v1/query/{qid}") as resp:
        info = json.loads(resp.read())
    assert info["state"] == "FINISHED"
    assert "elapsedMs" in info


def test_cli_formatting():
    from trino_tpu.server.cli import format_aligned

    out = format_aligned(["a", "bb"], [[1, None], [22, "x"]])
    lines = out.split("\n")
    assert lines[0].split(" | ")[0].strip() == "a"
    assert "NULL" in out and "(2 rows)" in out


def test_cancel_terminal(coordinator):
    import json
    import urllib.request

    # submit, cancel immediately, then poll: state must be terminal (no infinite poll)
    req = urllib.request.Request(f"{coordinator.url}/v1/statement",
                                 data=b"select count(*) from lineitem, orders "
                                      b"where l_orderkey = o_orderkey",
                                 method="POST")
    with urllib.request.urlopen(req) as resp:
        out = json.loads(resp.read())
    qid = out["id"]
    req = urllib.request.Request(f"{coordinator.url}/v1/statement/{qid}",
                                 method="DELETE")
    urllib.request.urlopen(req)
    q = coordinator.queries[qid]
    # canceled-while-queued queries never execute; canceled-after-finish stays FINISHED
    import time
    for _ in range(100):
        if q.state in ("CANCELED", "FINISHED", "FAILED"):
            break
        time.sleep(0.05)
    assert q.state in ("CANCELED", "FINISHED")
    if q.state == "CANCELED":
        resp = urllib.request.urlopen(
            f"{coordinator.url}/v1/statement/executing/{qid}/0")
        body = json.loads(resp.read())
        assert "nextUri" not in body  # terminal: client stops polling


def test_spooled_result_protocol(tmp_path, tpch_sf001):
    """Results at/above the spool threshold return segment descriptors instead
    of inline pages; the client fetches and decompresses segment payloads by
    URI (reference: server/protocol/spooling + spi/spool/SpoolingManager,
    client OkHttpSegmentLoader)."""
    import json as _json
    import urllib.request
    import zlib

    from trino_tpu import Engine
    from trino_tpu.server.client import Client
    from trino_tpu.server.server import CoordinatorServer

    e = Engine()
    e.register_catalog("tpch", tpch_sf001)
    srv = CoordinatorServer(e, spool_dir=str(tmp_path / "spool"),
                            spool_threshold_rows=100)
    srv.start()
    try:
        c = Client(srv.url, catalog="tpch")
        res = c.execute("select c_custkey from customer order by c_custkey")
        n = len(res.rows)
        assert n == 1500
        assert [r[0] for r in res.rows[:3]] == [1, 2, 3]
        # raw protocol surface: the executing response carries segments
        out = _json.loads(urllib.request.urlopen(
            urllib.request.Request(f"{srv.url}/v1/statement", method="POST",
                                   data=b"select c_custkey from customer",
                                   headers={"X-Trino-Catalog": "tpch"}),
            timeout=30).read())
        import time as _t

        while out.get("nextUri") and "segments" not in out:
            _t.sleep(0.05)
            out = _json.loads(urllib.request.urlopen(out["nextUri"],
                                                     timeout=10).read())
        assert out["segments"] and out["segments"][0]["encoding"] == "json+zlib"
        seg = out["segments"][0]
        payload = urllib.request.urlopen(seg["uri"], timeout=10).read()
        assert len(_json.loads(zlib.decompress(payload))) == seg["rowCount"]
        # small results stay inline
        res2 = c.execute("select count(*) c from region")
        assert res2.rows == [[5]]
    finally:
        srv.stop()


def test_ui_spa_and_json_api(tpch_sf001):
    """The web UI is a single-page app over JSON endpoints (reference:
    core/trino-web-ui's React SPA, reduced to one dependency-free page):
    /ui serves the shell, /ui/api/overview the live query list, and
    /ui/api/query/<id> the drill-down with SQL/state/plan; the legacy
    server-rendered /ui/query/<id> deep link still works."""
    import json as _json
    import urllib.request

    from trino_tpu import Engine
    from trino_tpu.server.server import CoordinatorServer

    e = Engine()
    e.register_catalog("tpch", tpch_sf001)
    srv = CoordinatorServer(e)
    srv.start()
    try:
        from trino_tpu.server.client import Client

        c = Client(srv.url, catalog="tpch")
        c.execute("select count(*) c from region")
        shell = urllib.request.urlopen(f"{srv.url}/ui", timeout=10
                                       ).read().decode()
        assert "/ui/api/overview" in shell  # the SPA polls the JSON api
        assert "/v1/statement" in shell  # the console speaks the protocol
        over = _json.loads(urllib.request.urlopen(
            f"{srv.url}/ui/api/overview", timeout=10).read())
        assert "tpch" in over["catalogs"]
        assert over["queries"] and over["queries"][0]["state"] == "FINISHED"
        qid = over["queries"][0]["query_id"]
        det = _json.loads(urllib.request.urlopen(
            f"{srv.url}/ui/api/query/{qid}", timeout=30).read())
        assert det["sql"] == "select count(*) c from region"
        assert det["state"] == "FINISHED" and det["rows"] == 1
        assert "Aggregate" in det.get("plan", "") \
            or "Values" in det.get("plan", "")
        # legacy server-rendered deep link stays alive
        page = urllib.request.urlopen(f"{srv.url}/ui/query/{qid}",
                                      timeout=30).read().decode()
        assert "select count(*) c from region" in page
        import pytest
        import urllib.error

        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{srv.url}/ui/api/query/nope", timeout=10)
    finally:
        srv.stop()


def test_concurrent_queries_share_the_engine_safely(coordinator):
    """Concurrent queries check out SEPARATE executors from the engine's pool
    (one query's host gaps overlap another's device work; a shared executor's
    per-query state would race).  Results must match serial execution."""
    import threading

    from trino_tpu.server import Client

    queries = [
        "select count(*) c from lineitem",
        "select l_returnflag, sum(l_quantity) q from lineitem "
        "group by l_returnflag order by l_returnflag",
        "select max(l_extendedprice) m from lineitem",
        "select count(*) c from orders where o_custkey < 100",
    ]
    c = Client(coordinator.url, catalog="tpch")
    expected = [c.execute(q).rows for q in queries]

    results = [None] * len(queries) * 3
    errors = []

    def run(i, q):
        try:
            results[i] = Client(coordinator.url, catalog="tpch").execute(q).rows
        except Exception as e:  # pragma: no cover - the assertion reports it
            errors.append(e)

    threads = []
    for k in range(3):
        for j, q in enumerate(queries):
            threads.append(threading.Thread(
                target=run, args=(k * len(queries) + j, q)))
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    for k in range(3):
        for j in range(len(queries)):
            assert results[k * len(queries) + j] == expected[j]


def test_metrics_exposes_device_boundary_counters(coordinator):
    """/v1/metrics exports the engine's lifetime device-boundary totals
    (dispatches / host transfers / bytes pulled) alongside the query gauges."""
    import urllib.request

    from trino_tpu.server import Client

    c = Client(coordinator.url, catalog="tpch")
    c.execute("select count(*) from nation")
    body = urllib.request.urlopen(
        coordinator.url + "/v1/metrics").read().decode()
    for metric in ("trino_tpu_device_dispatches_total",
                   "trino_tpu_host_transfers_total",
                   "trino_tpu_host_bytes_pulled_total"):
        lines = [l for l in body.splitlines()
                 if l.startswith(metric) and not l.startswith("# ")]
        assert lines, f"{metric} missing from /v1/metrics"
        assert float(lines[0].split()[-1]) > 0, lines
