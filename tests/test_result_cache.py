"""Result-cache tier (execution/bufferpool third tier) — round 12.

Covers the acceptance surface of the RESULT cache: a repeated deterministic
statement is answered with ZERO device dispatches / executor checkouts /
host pulls (counter-verified), byte-identical to the executed run; the full
invalidation matrix (INSERT/DDL clear, catalog-version bump, plan-shaping
SET SESSION, volatile functions/connectors, LRU under a tiny budget,
per-entry cap); concurrent pooled executors racing the same statement; the
shared chaos scenarios (store/checkout deny recoverable, errored queries
never cache); and the observability wiring (EXPLAIN ANALYZE line,
/v1/metrics series, system.runtime.queries column).

The tier budget comes from TRINO_TPU_RESULT_CACHE, resolved lazily at first
use — every test sets it via monkeypatch BEFORE building its Engine (the
same pattern as test_page_cache).
"""

import threading

import numpy as np
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.execution import faults
from trino_tpu.execution.chaos_matrix import (RESULT_SCENARIOS, leak_report,
                                              run_result_scenario)
from trino_tpu.execution.chaos_matrix import result_signature as _sig

SF, SPLIT_ROWS = 0.01, 1 << 14

Q_AGG = """
select l_returnflag, l_linestatus, sum(l_quantity) s, count(*) c
from lineitem where l_shipdate <= date '1998-09-02'
group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus"""

Q_JOIN = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate limit 10"""

Q_POINT = "select c_name, c_acctbal from customer where c_custkey = 7"


def _engine(monkeypatch, budget=64 << 20, page_budget=0):
    monkeypatch.setenv("TRINO_TPU_RESULT_CACHE", str(budget))
    monkeypatch.setenv("TRINO_TPU_PAGE_CACHE", str(page_budget))
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=SF, split_rows=SPLIT_ROWS))
    return e


def _assert_same(a, b):
    assert _sig(a) == _sig(b)
    for x, y in zip(a.raw_columns, b.raw_columns):
        xa, ya = np.asarray(x), np.asarray(y)
        assert xa.dtype == ya.dtype
        assert np.array_equal(xa, ya, equal_nan=xa.dtype.kind == "f")


@pytest.mark.parametrize("sql", [Q_AGG, Q_JOIN, Q_POINT],
                         ids=["agg", "join", "point"])
def test_warm_hit_zero_boundary_and_byte_identical(monkeypatch, sql):
    e = _engine(monkeypatch)
    s = e.create_session("tpch")
    off = e.create_session("tpch")
    e.session_properties.set_property(off, "result_cache", False)
    r_off = e.execute_sql(sql, off)
    c = e.last_query_counters
    assert c.result_cache_hits == 0 and c.result_cache_misses == 0
    r1 = e.execute_sql(sql, s)  # admissible miss: executes + stores
    c = e.last_query_counters
    assert c.result_cache_misses == 1 and c.result_cache_hits == 0
    r2 = e.execute_sql(sql, s)  # warm: served whole from the tier
    c = e.last_query_counters
    # the zero-dispatch contract, counter-verified: no device work, no host
    # pulls, no splits — the statement never reached the executor path
    assert c.result_cache_hits == 1
    assert c.device_dispatches == 0 and c.host_transfers == 0 \
        and c.host_bytes_pulled == 0, c.as_dict()
    assert c.result_cache_bytes_saved > 0
    # attribution: the hit landed on the result.cache site
    assert c.sites.get("result.cache", {}).get("result_cache_hits") == 1
    _assert_same(r_off, r1)
    _assert_same(r_off, r2)
    e._invalidate()


def test_hit_skips_executor_checkout(monkeypatch):
    e = _engine(monkeypatch)
    s = e.create_session("tpch")
    e.execute_sql(Q_POINT, s)
    n_executors = len(e._all_executors)
    calls = []
    orig = e._checkout_executor

    def counting():
        calls.append(1)
        return orig()

    monkeypatch.setattr(e, "_checkout_executor", counting)
    e.execute_sql(Q_POINT, s)
    assert e.last_query_counters.result_cache_hits == 1
    assert not calls, "a served statement checked out an executor"
    assert len(e._all_executors) == n_executors
    e._invalidate()


def test_insert_and_ddl_invalidate(monkeypatch):
    from trino_tpu.connectors.memory import MemoryConnector

    monkeypatch.setenv("TRINO_TPU_RESULT_CACHE", str(64 << 20))
    monkeypatch.setenv("TRINO_TPU_PAGE_CACHE", "0")
    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table t (k bigint, v bigint)", s)
    e.execute_sql("insert into t values (1, 10), (2, 20)", s)
    e.execute_sql("select sum(v) s from t", s)
    e.execute_sql("select sum(v) s from t", s)
    assert e.last_query_counters.result_cache_hits == 1
    assert e.buffer_pool.info()["result_entries"] == 1
    e.execute_sql("insert into t values (3, 70)", s)  # DML clears the pool
    assert e.buffer_pool.info()["result_entries"] == 0
    r = e.execute_sql("select sum(v) s from t", s)
    assert int(r.columns[0][0]) == 100, "stale result served after INSERT"
    e.execute_sql("create table u (x bigint)", s)  # DDL clears too
    assert e.buffer_pool.info()["result_entries"] == 0
    # pool accounting: reservations always equal resident bytes
    bp = e.buffer_pool
    assert bp.memory_pool is None or \
        bp.memory_pool.reserved == bp.info()["bytes"]
    e._invalidate()


class _VersionedTpch(TpchConnector):
    """Cacheable connector with a bumpable plan_version — the growable-
    catalog shape (parquet DML, system dictionaries) without the weight."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.version = 0

    def plan_version(self) -> int:
        return self.version


def test_catalog_version_bump_invalidates(monkeypatch):
    monkeypatch.setenv("TRINO_TPU_RESULT_CACHE", str(64 << 20))
    monkeypatch.setenv("TRINO_TPU_PAGE_CACHE", "0")
    e = Engine()
    conn = _VersionedTpch(sf=SF, split_rows=SPLIT_ROWS)
    e.register_catalog("tpch", conn)
    s = e.create_session("tpch")
    e.execute_sql(Q_POINT, s)
    e.execute_sql(Q_POINT, s)
    assert e.last_query_counters.result_cache_hits == 1
    conn.version += 1
    # the version-stale plan path replans AND drops the catalog's entries:
    # the old entry can neither serve (fingerprint embeds v0) nor pin bytes
    e.execute_sql(Q_POINT, s)
    c = e.last_query_counters
    assert c.result_cache_hits == 0 and c.result_cache_misses == 1
    info = e.buffer_pool.info()
    assert info["result_entries"] == 1  # only the fresh v1 entry
    e.execute_sql(Q_POINT, s)
    assert e.last_query_counters.result_cache_hits == 1
    e._invalidate()


def test_plan_shaping_property_change_misses(monkeypatch):
    e = _engine(monkeypatch)
    s = e.create_session("tpch")
    e.execute_sql(Q_AGG, s)
    e.execute_sql(Q_AGG, s)
    assert e.last_query_counters.result_cache_hits == 1
    # dispatch_batch rides _plan_shape_props, which rides the result key: a
    # SET SESSION that re-plans must also re-execute, never serve the old
    # shape's cached result
    e.session_properties.set_property(s, "dispatch_batch", 1)
    r = e.execute_sql(Q_AGG, s)
    c = e.last_query_counters
    assert c.result_cache_hits == 0 and c.result_cache_misses == 1
    assert len(r) > 0
    e._invalidate()


def test_volatile_functions_and_connectors_excluded(monkeypatch):
    e = _engine(monkeypatch)
    s = e.create_session("tpch")
    vol = "select n_name, now() t from nation"
    e.execute_sql(vol, s)
    e.execute_sql(vol, s)
    c = e.last_query_counters
    assert c.result_cache_hits == 0 and c.result_cache_misses == 0
    # the system catalog is a volatile connector (no CACHEABLE_SCANS):
    # repeated runs execute every time
    q = "select count(*) c from system.queries"
    e.execute_sql(q, s)
    e.execute_sql(q, s)
    c = e.last_query_counters
    assert c.result_cache_hits == 0 and c.result_cache_misses == 0
    assert e.buffer_pool.info()["result_entries"] <= 1  # only the tpch entry
    e._invalidate()


def test_lru_eviction_and_entry_cap_under_tiny_budget(monkeypatch):
    # ~2KB budget: the region/nation singles fit one at a time, so
    # alternating statements must LRU-evict, never raise, and stay inside
    # the labeled pool's ceiling
    monkeypatch.setenv("TRINO_TPU_RESULT_CACHE", "2048")
    monkeypatch.setenv("TRINO_TPU_PAGE_CACHE", "0")
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=SF, split_rows=SPLIT_ROWS))
    s = e.create_session("tpch")
    for sql in ("select count(*) c from region group by r_regionkey",
                "select count(*) c from nation group by n_nationkey",
                "select count(*) c from region group by r_regionkey"):
        e.execute_sql(sql, s)
    info = e.buffer_pool.info()
    assert info["result_bytes"] <= 2048
    assert e.buffer_pool.memory_pool.reserved == info["bytes"]
    # an entry past the per-entry cap (budget/4 = 512B) is skipped, not an
    # error — the wide customer scan result is far bigger than that
    r = e.execute_sql("select c_custkey, c_name, c_acctbal from customer", s)
    assert len(r) > 0
    info = e.buffer_pool.info()
    assert info["result_bytes"] <= 2048
    e._invalidate()


def test_concurrent_same_statement_byte_identical_one_store(monkeypatch):
    e = _engine(monkeypatch)
    s0 = e.create_session("tpch")
    ref = e.execute_sql(Q_JOIN, s0)  # plan + first store
    results, errors = [None] * 6, []

    def run(i):
        try:
            results[i] = e.execute_sql(Q_JOIN, e.create_session("tpch"))
        except Exception as ex:  # surface in the main thread
            errors.append(ex)

    ts = [threading.Thread(target=run, args=(i,)) for i in range(6)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors, errors
    for r in results:
        _assert_same(ref, r)
    info = e.buffer_pool.info()
    # at most one store: every racer either hit or found the entry already
    # present at store time (put_result's in-lock duplicate check)
    assert info["result_entries"] == 1, info
    assert info["result_hits"] >= 1
    assert not leak_report(e)
    e._invalidate()
    assert e.buffer_pool.info()["entries"] == 0
    assert e.buffer_pool.memory_pool.reserved == 0


@pytest.mark.parametrize("scenario", [n for n, _s, _k in RESULT_SCENARIOS])
def test_chaos_result_scenarios(monkeypatch, scenario):
    """The shared chaos matrix rows: store/checkout faults are recoverable
    and byte-identical, no entry is admitted under a store fault, and the
    leak check passes after every scenario."""
    spec, kind = next((s, k) for n, s, k in RESULT_SCENARIOS
                      if n == scenario)
    e = _engine(monkeypatch)
    s = e.create_session("tpch")
    e.execute_sql(Q_AGG, s)  # cold
    base = _sig(e.execute_sql(Q_AGG, s))
    rec = run_result_scenario(e, Q_AGG, s, base, scenario, spec, kind)
    assert rec.get("ok"), rec
    e._invalidate()


def test_store_refused_after_mid_statement_invalidation(monkeypatch):
    """A DML's invalidation landing WHILE a select executes must refuse the
    select's late store: the result may predate the DML, and connectors
    without plan_version have no other staleness defense.  The engine
    captures the pool epoch before executing and presents it at store."""
    e = _engine(monkeypatch)
    s = e.create_session("tpch")
    r = e.execute_sql(Q_POINT, s)
    bp = e.buffer_pool
    key = ("result", "fp-under-test", (), False, False, ())
    epoch = bp.epoch
    bp.clear()  # the concurrent invalidation
    assert bp.put_result(key, r, epoch=epoch) is False
    assert bp.info()["result_entries"] == 0
    # the CURRENT epoch stores fine (and with no epoch = unguarded callers)
    assert bp.put_result(key, r, epoch=bp.epoch) is True
    e._invalidate()


def test_errored_queries_never_cache(monkeypatch):
    e = _engine(monkeypatch)
    s = e.create_session("tpch")
    e.execute_sql(Q_AGG, s)  # plan + compile + store
    e.buffer_pool.clear()
    with faults.injected("point=dispatch,action=error,nth=1"):
        with pytest.raises(faults.InjectedFaultError):
            e.execute_sql(Q_AGG, s)
    assert e.buffer_pool.info()["result_entries"] == 0, \
        "an errored query stored a result"
    assert not leak_report(e)
    # the clean rerun re-executes, stores, and the next run serves it
    e.execute_sql(Q_AGG, s)
    e.execute_sql(Q_AGG, s)
    assert e.last_query_counters.result_cache_hits == 1
    e._invalidate()


def test_cluster_coordinator_serves_from_result_cache(monkeypatch, tmp_path):
    """Coordinator-side gating: ClusterCoordinator.execute_sql consults the
    engine's result tier before scheduling any fragment (no live workers
    here, so the cold run degrades to local — the LOOKUP path is identical
    either way)."""
    from trino_tpu.server.cluster import ClusterCoordinator

    e = _engine(monkeypatch)
    coord = ClusterCoordinator(e, str(tmp_path))
    s = e.create_session("tpch")
    r1 = coord.execute_sql(Q_AGG, s)
    r2 = coord.execute_sql(Q_AGG, s)
    _assert_same(r1, r2)
    assert e.buffer_pool.result_hits >= 1
    assert e.last_query_counters.result_cache_hits == 1
    assert coord.last_query_counters.result_cache_hits == 1
    e._invalidate()


def test_explain_analyze_and_metrics_surfaces(monkeypatch):
    from trino_tpu.server.server import CoordinatorServer
    from trino_tpu.sql.planprinter import format_plan
    from trino_tpu.sql import parser as A
    from trino_tpu.sql.frontend import Planner

    e = _engine(monkeypatch)
    s = e.create_session("tpch")
    e.execute_sql(Q_AGG, s)
    e.execute_sql(Q_AGG, s)
    c = e.last_query_counters
    assert c.result_cache_hits == 1
    plan = Planner(e, s).plan_query(A.parse(Q_AGG))
    text = format_plan(plan, counters=c)
    assert "Result cache: 1 hits" in text, text
    # /v1/metrics result series read straight off the pool (no HTTP needed)
    srv = CoordinatorServer(e)
    body = srv._metrics_text()
    assert "trino_tpu_result_cache_hits_total 1" in body
    assert "trino_tpu_result_cache_entries 1" in body
    # system.runtime.queries marks cache-served statements
    rows = e.execute_sql(
        "select query_id, result_cache_hits from system.queries "
        "where result_cache_hits > 0", s).rows()
    assert rows, "no cache-served statement visible in system.queries"
    e._invalidate()


def test_off_by_default_without_env(monkeypatch):
    monkeypatch.delenv("TRINO_TPU_RESULT_CACHE", raising=False)
    monkeypatch.setenv("TRINO_TPU_PAGE_CACHE", "0")
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=SF, split_rows=SPLIT_ROWS))
    s = e.create_session("tpch")
    e.execute_sql(Q_POINT, s)
    e.execute_sql(Q_POINT, s)
    c = e.last_query_counters
    # unset env = tier off on EVERY backend: no lookups, no stores — the
    # warm path keeps executing (bench.py and the budget suite depend on it)
    assert c.result_cache_hits == 0 and c.result_cache_misses == 0
    assert c.device_dispatches > 0
    assert e.buffer_pool.info()["result_entries"] == 0
    e._invalidate()
