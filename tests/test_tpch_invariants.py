"""TPC-H published-invariant checks, independent of the pandas self-oracle
(VERDICT r3 weak #5: a generator bug changes both engine and oracle
identically and is invisible).  These assert facts fixed by the TPC-H
specification (section 4.2.3 table scaling, column value domains, Q1's known
answer structure), so generator drift surfaces even though dbgen's exact text
and seed streams are not replicated (connectors/tpch.py:12-14)."""

import numpy as np
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector

SF = 0.1


@pytest.fixture(scope="module")
def eng():
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=SF, split_rows=1 << 16))
    return e, e.create_session("tpch")


def test_spec_row_counts_scale():
    """Spec 4.2.3: cardinalities scale linearly with SF except nation(25) and
    region(5); partsupp = 4x part, lineitem averages ~4 rows per order."""
    conn = TpchConnector(sf=1.0)
    assert conn.row_count("nation") == 25
    assert conn.row_count("region") == 5
    assert conn.row_count("customer") == 150_000
    assert conn.row_count("orders") == 1_500_000
    assert conn.row_count("part") == 200_000
    assert conn.row_count("supplier") == 10_000
    assert conn.row_count("partsupp") == 800_000
    small = TpchConnector(sf=0.01)
    assert small.row_count("orders") == 15_000
    assert small.row_count("customer") == 1_500


def test_lineitem_per_order_distribution(eng):
    """Spec: each order has 1..7 lineitems; the average is ~4 and the total
    lineitem count at SF1 is ~6.001M (within 2% here)."""
    e, s = eng
    r = e.execute_sql(
        "select count(*) n, min(l_linenumber) mn, max(l_linenumber) mx "
        "from lineitem", s).rows()[0]
    n, mn, mx = (int(x) for x in r)
    o = int(e.execute_sql("select count(distinct l_orderkey) from lineitem",
                          s).rows()[0][0])
    n_orders = int(1_500_000 * SF)
    assert o == n_orders  # every order has at least one lineitem
    assert mn == 1 and 1 <= mx <= 7
    assert abs(n / n_orders - 4.0) < 0.1  # ~6.001M/1.5M at SF1
    assert abs(n - 6_001_215 * SF) / (6_001_215 * SF) < 0.02


def test_column_value_domains(eng):
    """Spec value domains: l_discount in [0, .10], l_tax in [0, .08],
    l_quantity in [1, 50], o_totalprice positive, dates inside the spec
    calendar (1992-01-01 .. 1998-12-31 shifted windows)."""
    e, s = eng
    r = e.execute_sql(
        "select min(l_discount), max(l_discount), min(l_tax), max(l_tax), "
        "min(l_quantity), max(l_quantity) from lineitem", s).rows()[0]
    dmn, dmx, tmn, tmx, qmn, qmx = (float(x) for x in r)
    assert 0.0 <= dmn and dmx <= 0.10001
    assert 0.0 <= tmn and tmx <= 0.08001
    assert qmn >= 1 and qmx <= 50
    r = e.execute_sql(
        "select min(o_orderdate), max(o_orderdate), min(o_totalprice) "
        "from orders", s).rows()[0]
    lo, hi, tp = r
    assert np.datetime64("1992-01-01") <= np.datetime64(lo)
    assert np.datetime64(hi) <= np.datetime64("1998-08-02")  # ENDDATE - 151
    assert float(tp) > 0


def test_ship_commit_receipt_ordering(eng):
    """Spec: l_shipdate = o_orderdate + [1..121] days, l_receiptdate =
    l_shipdate + [1..30] days — receipt strictly after ship, ship after
    order."""
    e, s = eng
    r = e.execute_sql(
        "select count(*) from lineitem, orders where l_orderkey = o_orderkey "
        "and (l_shipdate <= o_orderdate or l_receiptdate <= l_shipdate)",
        s).rows()[0]
    assert int(r[0]) == 0


def test_referential_integrity(eng):
    """Every lineitem joins exactly one order/part/supplier; partsupp keys are
    unique pairs with 4 suppliers per part."""
    e, s = eng
    r = e.execute_sql(
        "select count(*) from lineitem where l_orderkey not in "
        "(select o_orderkey from orders)", s).rows()[0]
    assert int(r[0]) == 0
    r = e.execute_sql(
        "select max(c) from (select ps_partkey, count(*) c from partsupp "
        "group by ps_partkey) t", s).rows()[0]
    assert int(r[0]) == 4
    n = int(e.execute_sql("select count(*) from part", s).rows()[0][0])
    d = int(e.execute_sql("select count(distinct p_partkey) from part",
                          s).rows()[0][0])
    assert n == d


def test_q1_answer_structure(eng):
    """Q1's published SF1 answer: exactly 4 (returnflag, linestatus) groups —
    A/F, N/F, N/O, R/F — with N/F a ~1.5% sliver, avg qty ~25.5, avg disc
    ~0.05, and the date filter keeping ~98.5% of rows."""
    e, s = eng
    rows = e.execute_sql(
        "select l_returnflag, l_linestatus, count(*) c, avg(l_quantity) q, "
        "avg(l_discount) d from lineitem "
        "where l_shipdate <= date '1998-12-01' - interval '90' day "
        "group by l_returnflag, l_linestatus "
        "order by l_returnflag, l_linestatus", s).rows()
    keys = [(str(r[0]), str(r[1])) for r in rows]
    assert keys == [("A", "F"), ("N", "F"), ("N", "O"), ("R", "F")]
    counts = {k: int(r[2]) for k, r in zip(keys, rows)}
    total = sum(counts.values())
    # N/F is the small group (orders shipped in the last window only)
    assert counts[("N", "F")] / total < 0.05
    # A/F and R/F are near-equal halves of returned-era rows
    assert abs(counts[("A", "F")] - counts[("R", "F")]) \
        / max(counts[("A", "F")], 1) < 0.1
    for r in rows:
        assert 24.0 < float(r[3]) < 27.0  # avg qty ~25.5
        assert 0.045 < float(r[4]) < 0.055  # avg discount ~0.05
    full = int(e.execute_sql("select count(*) from lineitem", s).rows()[0][0])
    assert 0.97 < total / full < 1.0  # filter keeps ~98.5%
