"""Array/map/row types + UNNEST (reference: spi/block/ArrayBlock.java,
MapBlock.java, RowBlock.java, operator/unnest/UnnestOperator.java,
operator/scalar array/map functions).

The TPU layout under test: span-packed int64 columns over element heaps
(ops/arrays.py), expansion via the searchsorted map — results checked against
plain python evaluation of the same data."""

import numpy as np
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.memory import MemoryConnector


@pytest.fixture()
def mem_engine(tpch_sf001):
    e = Engine()
    e.register_catalog("tpch", tpch_sf001)
    e.register_catalog("mem", MemoryConnector())
    return e


def test_array_literal_ops(mem_engine):
    e = mem_engine
    s = e.create_session("mem")
    r = e.execute_sql(
        "select cardinality(array[1,2,3]) c, array[5,6,7][2] x, "
        "contains(array[1,2,3], 2) a, contains(array[1,2,3], 9) b", s).rows()
    assert r == [(3, 6, True, False)]
    # out-of-bounds subscript -> NULL (reference: element_at semantics here)
    r = e.execute_sql("select element_at(array[1,2], 5) v", s).rows()
    assert r == [(None,)]
    # string arrays decode through the element dictionary
    r = e.execute_sql("select element_at(array['a','b','c'], 3) v", s).rows()
    assert r == [("c",)]


def test_unnest_literal_and_sequence(mem_engine):
    e = mem_engine
    s = e.create_session("mem")
    r = e.execute_sql(
        "select n, o from unnest(array[10,20,30]) with ordinality as t(n, o)",
        s).rows()
    assert r == [(10, 1), (20, 2), (30, 3)]
    r = e.execute_sql("select n from unnest(sequence(1,5)) t(n) where n > 2",
                      s).rows()
    assert [v for (v,) in r] == [3, 4, 5]
    # parallel unnest zips positionally, shorter array pads with NULL
    r = e.execute_sql(
        "select a, b from unnest(array[1,2,3], array[7,8]) t(a, b)", s).rows()
    assert r == [(1, 7), (2, 8), (3, None)]


def test_map_ops(mem_engine):
    e = mem_engine
    s = e.create_session("mem")
    r = e.execute_sql(
        "select map(array['x','y'], array[7,8])['y'] v, "
        "cardinality(map(array[1,2], array[3,4])) c", s).rows()
    assert r == [(8, 2)]
    # missing key -> NULL
    r = e.execute_sql("select element_at(map(array[1], array[9]), 5) v", s).rows()
    assert r == [(None,)]
    r = e.execute_sql(
        "select cardinality(map_keys(map(array[1,2], array[3,4]))) k, "
        "map_values(map(array['a'], array[42]))[1] v", s).rows()
    assert r == [(2, 42)]


def test_row_field_access(mem_engine):
    """row() flattens to struct-of-columns: field access folds at plan time."""
    e = mem_engine
    s = e.create_session("mem")
    r = e.execute_sql("select row(1, 'two', 3.5)[3] a, row(4, 5)[1] b", s).rows()
    assert r == [(3.5, 4)]


def test_storage_arrays_and_lateral_unnest(mem_engine):
    """Memory-connector array columns: heap storage, scans, CROSS JOIN UNNEST
    (lateral — the unnest argument references the sibling relation)."""
    e = mem_engine
    s = e.create_session("mem")
    e.execute_sql(
        "create table ar (id bigint, tags array(varchar), nums array(bigint))", s)
    conn = e.catalogs["mem"]
    conn.append("ar", [[1, 2, 3],
                       [["red", "blue"], ["blue"], None],
                       [[10, 20], [30], []]])
    e._invalidate()
    rows = e.execute_sql("select id, tags, nums from ar order by id", s).rows()
    assert rows == [(1, ["red", "blue"], [10, 20]), (2, ["blue"], [30]),
                    (3, None, [])]
    rows = e.execute_sql(
        "select t.id, u.tag from ar t cross join unnest(t.tags) u(tag) "
        "order by id, tag", s).rows()
    assert rows == [(1, "blue"), (1, "red"), (2, "blue")]
    # aggregate over expanded elements; NULL/empty arrays contribute nothing
    rows = e.execute_sql("select sum(n) sn, count(*) c from ar "
                         "cross join unnest(nums) u(n)", s).rows()
    assert rows == [(60, 3)]
    rows = e.execute_sql("select id, cardinality(nums) c from ar order by id",
                         s).rows()
    assert rows == [(1, 2), (2, 1), (3, 0)]


def test_unnest_with_filter_and_join(mem_engine):
    """Unnested elements behave as first-class columns: filters, joins,
    group-by over them."""
    e = mem_engine
    s = e.create_session("tpch")
    rows = e.execute_sql(
        "select r_name, n from region cross join unnest(sequence(1,3)) u(n) "
        "where n <= 2 order by r_name, n", s).rows()
    assert len(rows) == 10  # 5 regions x 2 elements
    assert rows[0][1] == 1 and rows[1][1] == 2
    rows = e.execute_sql(
        "select n % 2 k, count(*) c from unnest(sequence(1,10)) t(n) "
        "group by n % 2 order by k", s).rows()
    assert rows == [(0, 5), (1, 5)]


def test_insert_array_literals(mem_engine):
    """INSERT ... VALUES with array literals reaches the connector's heap
    storage (regression: the VALUES evaluator rejected ArrayLiteral)."""
    e = mem_engine
    s = e.create_session("mem")
    e.execute_sql("create table ia (id bigint, xs array(bigint), "
                  "ss array(varchar))", s)
    e.execute_sql("insert into ia values (1, array[1,2], array['a','b']), "
                  "(2, array[], null)", s)
    rows = e.execute_sql("select id, xs, ss from ia order by id", s).rows()
    assert rows == [(1, [1, 2], ["a", "b"]), (2, [], None)]


def test_sequence_step_zero_rejected(mem_engine):
    from trino_tpu.sql.frontend import SemanticError

    s = mem_engine.create_session("tpch")
    with pytest.raises(SemanticError, match="step"):
        mem_engine.execute_sql("select n from unnest(sequence(1,5,0)) t(n)", s)


def test_array_type_ddl_roundtrip(mem_engine):
    """array(T)/map(K,V) type names parse in DDL and SHOW COLUMNS."""
    e = mem_engine
    s = e.create_session("mem")
    e.execute_sql("create table tt (a array(bigint), m bigint)", s)
    cols = e.execute_sql("show columns from tt", s).rows()
    assert cols[0] == ("a", "array(bigint)")


def test_array_reductions_and_position():
    """array_min/max/sum/average + array_position (reference:
    operator/scalar/ArrayMinFunction family, ArrayPositionFunction)."""
    from trino_tpu import Engine
    from trino_tpu.connectors.memory import MemoryConnector

    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table t (id bigint, a array(bigint))", s)
    e.execute_sql("insert into t values (1, array[3,1,2]), (2, array[10]), "
                  "(3, array[]), (4, null)", s)
    r = e.execute_sql(
        "select id, array_min(a) mn, array_max(a) mx, array_sum(a) sm, "
        "array_average(a) av, array_position(a, 2) p from t order by id",
        s).to_pandas()
    assert r["mn"].tolist()[:2] == [1, 10]
    assert r["mx"].tolist()[:2] == [3, 10]
    assert r["sm"].tolist()[:2] == [6, 10]
    assert r["av"].tolist()[:2] == [2.0, 10.0]
    # 1-based position; 0 = absent; empty arrays -> NULL reductions
    assert r["p"].tolist()[:3] == [3, 0, 0]
    assert r["mn"].isna().tolist() == [False, False, True, True]
    # filters over reductions
    r = e.execute_sql("select id from t where array_sum(a) > 7", s).to_pandas()
    assert r["id"].tolist() == [2]
