"""TPC-H queries with subqueries / multi-aliases / OR-factored predicates vs pandas
oracles (second batch: Q4, Q7, Q8, Q11, Q18, Q19)."""

import numpy as np
import pandas as pd

from tests.test_sql_tpch import assert_frames_close, dcol, run, D


def test_q4(engine, tpch_pandas):
    got = run(engine, """
        select o_orderpriority, count(*) as order_count
        from orders
        where o_orderdate >= date '1993-07-01'
          and o_orderdate < date '1993-07-01' + interval '3' month
          and exists (select * from lineitem
                      where l_orderkey = o_orderkey and l_commitdate < l_receiptdate)
        group by o_orderpriority order by o_orderpriority""")
    t = tpch_pandas
    o = t["orders"]
    o2 = o[(dcol(o, "o_orderdate") >= D("1993-07-01"))
           & (dcol(o, "o_orderdate") < D("1993-10-01"))]
    li = t["lineitem"]
    ok = li[dcol(li, "l_commitdate") < dcol(li, "l_receiptdate")]["l_orderkey"].unique()
    o3 = o2[o2.o_orderkey.isin(ok)]
    exp = (o3.groupby("o_orderpriority", as_index=False).size()
           .rename(columns={"size": "order_count"})
           .sort_values("o_orderpriority").reset_index(drop=True))
    assert_frames_close(got, exp)


def test_q7(engine, tpch_pandas):
    got = run(engine, """
        select supp_nation, cust_nation, l_year, sum(volume) as revenue
        from (select n1.n_name as supp_nation, n2.n_name as cust_nation,
                     extract(year from l_shipdate) as l_year,
                     l_extendedprice * (1 - l_discount) as volume
              from supplier, lineitem, orders, customer, nation n1, nation n2
              where s_suppkey = l_suppkey and o_orderkey = l_orderkey
                and c_custkey = o_custkey and s_nationkey = n1.n_nationkey
                and c_nationkey = n2.n_nationkey
                and ((n1.n_name = 'FRANCE' and n2.n_name = 'GERMANY')
                     or (n1.n_name = 'GERMANY' and n2.n_name = 'FRANCE'))
                and l_shipdate between date '1995-01-01' and date '1996-12-31'
             ) as shipping
        group by supp_nation, cust_nation, l_year
        order by supp_nation, cust_nation, l_year""")
    t = tpch_pandas
    li = t["lineitem"]
    li2 = li[(dcol(li, "l_shipdate") >= D("1995-01-01"))
             & (dcol(li, "l_shipdate") <= D("1996-12-31"))]
    j = (li2.merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
         .merge(t["orders"], left_on="l_orderkey", right_on="o_orderkey")
         .merge(t["customer"], left_on="o_custkey", right_on="c_custkey")
         .merge(t["nation"].rename(columns={"n_name": "supp_nation"}),
                left_on="s_nationkey", right_on="n_nationkey")
         .merge(t["nation"].rename(columns={"n_name": "cust_nation"}),
                left_on="c_nationkey", right_on="n_nationkey"))
    j = j[((j.supp_nation == "FRANCE") & (j.cust_nation == "GERMANY"))
          | ((j.supp_nation == "GERMANY") & (j.cust_nation == "FRANCE"))]
    j = j.copy()
    j["l_year"] = dcol(j, "l_shipdate").astype("datetime64[Y]").astype(int) + 1970
    j["volume"] = j.l_extendedprice * (1 - j.l_discount)
    exp = (j.groupby(["supp_nation", "cust_nation", "l_year"], as_index=False)
           .agg(revenue=("volume", "sum"))
           .sort_values(["supp_nation", "cust_nation", "l_year"]).reset_index(drop=True))
    assert_frames_close(got, exp, rtol=1e-9)


def test_q8(engine, tpch_pandas):
    got = run(engine, """
        select o_year,
               sum(case when nation = 'BRAZIL' then volume else 0 end) / sum(volume)
                   as mkt_share
        from (select extract(year from o_orderdate) as o_year,
                     l_extendedprice * (1 - l_discount) as volume, n2.n_name as nation
              from part, supplier, lineitem, orders, customer, nation n1, nation n2, region
              where p_partkey = l_partkey and s_suppkey = l_suppkey
                and l_orderkey = o_orderkey and o_custkey = c_custkey
                and c_nationkey = n1.n_nationkey and n1.n_regionkey = r_regionkey
                and r_name = 'AMERICA' and s_nationkey = n2.n_nationkey
                and o_orderdate between date '1995-01-01' and date '1996-12-31'
                and p_type = 'ECONOMY ANODIZED STEEL'
             ) as all_nations
        group by o_year order by o_year""")
    t = tpch_pandas
    o = t["orders"]
    o2 = o[(dcol(o, "o_orderdate") >= D("1995-01-01"))
           & (dcol(o, "o_orderdate") <= D("1996-12-31"))]
    p2 = t["part"][t["part"].p_type == "ECONOMY ANODIZED STEEL"]
    j = (t["lineitem"].merge(p2, left_on="l_partkey", right_on="p_partkey")
         .merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
         .merge(o2, left_on="l_orderkey", right_on="o_orderkey")
         .merge(t["customer"], left_on="o_custkey", right_on="c_custkey")
         .merge(t["nation"].add_suffix("_1"), left_on="c_nationkey",
                right_on="n_nationkey_1")
         .merge(t["region"], left_on="n_regionkey_1", right_on="r_regionkey")
         .merge(t["nation"].add_suffix("_2"), left_on="s_nationkey",
                right_on="n_nationkey_2"))
    j = j[j.r_name == "AMERICA"].copy()
    j["o_year"] = dcol(j, "o_orderdate").astype("datetime64[Y]").astype(int) + 1970
    j["volume"] = j.l_extendedprice * (1 - j.l_discount)
    j["bra"] = j.volume.where(j.n_name_2 == "BRAZIL", 0.0)
    g = j.groupby("o_year", as_index=False).agg(bra=("bra", "sum"), vol=("volume", "sum"))
    g["mkt_share"] = g.bra / g.vol
    exp = g[["o_year", "mkt_share"]].sort_values("o_year").reset_index(drop=True)
    assert_frames_close(got, exp, rtol=1e-6)


def test_q11(engine, tpch_pandas):
    got = run(engine, """
        select ps_partkey, sum(ps_supplycost * ps_availqty) as value
        from partsupp, supplier, nation
        where ps_suppkey = s_suppkey and s_nationkey = n_nationkey
          and n_name = 'GERMANY'
        group by ps_partkey
        having sum(ps_supplycost * ps_availqty) >
               (select sum(ps_supplycost * ps_availqty) * 0.0001
                from partsupp, supplier, nation
                where ps_suppkey = s_suppkey and s_nationkey = n_nationkey
                  and n_name = 'GERMANY')
        order by value desc limit 100""")
    t = tpch_pandas
    j = (t["partsupp"].merge(t["supplier"], left_on="ps_suppkey", right_on="s_suppkey")
         .merge(t["nation"], left_on="s_nationkey", right_on="n_nationkey"))
    j = j[j.n_name == "GERMANY"].copy()
    j["v"] = j.ps_supplycost * j.ps_availqty
    g = j.groupby("ps_partkey", as_index=False).agg(value=("v", "sum"))
    thresh = j.v.sum() * 0.0001
    exp = (g[g.value > thresh].sort_values("value", ascending=False)
           .head(100).reset_index(drop=True))
    assert_frames_close(got, exp, rtol=1e-9)


def test_q18(engine, tpch_pandas):
    got = run(engine, """
        select c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
               sum(l_quantity) as total_qty
        from customer, orders, lineitem
        where o_orderkey in (select l_orderkey from lineitem
                             group by l_orderkey having sum(l_quantity) > 212)
          and c_custkey = o_custkey and o_orderkey = l_orderkey
        group by c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
        order by o_totalprice desc, o_orderdate limit 100""")
    t = tpch_pandas
    li = t["lineitem"]
    big = li.groupby("l_orderkey").agg(q=("l_quantity", "sum"))
    big_keys = big[big.q > 212].index
    j = (li[li.l_orderkey.isin(big_keys)]
         .merge(t["orders"], left_on="l_orderkey", right_on="o_orderkey")
         .merge(t["customer"], left_on="o_custkey", right_on="c_custkey"))
    exp = (j.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate", "o_totalprice"],
                     as_index=False).agg(total_qty=("l_quantity", "sum"))
           .sort_values(["o_totalprice", "o_orderdate"], ascending=[False, True])
           .head(100).reset_index(drop=True))
    exp = exp[["c_name", "c_custkey", "o_orderkey", "o_orderdate", "o_totalprice",
               "total_qty"]]
    got2 = got.drop(columns=["o_orderdate"])
    exp2 = exp.drop(columns=["o_orderdate"])
    assert_frames_close(got2, exp2, rtol=1e-9)


def test_q19(engine, tpch_pandas):
    got = run(engine, """
        select sum(l_extendedprice * (1 - l_discount)) as revenue
        from lineitem, part
        where (p_partkey = l_partkey and p_brand = 'Brand#12'
               and p_container in ('SM CASE', 'SM BOX', 'SM PACK', 'SM PKG')
               and l_quantity >= 1 and l_quantity <= 11 and p_size between 1 and 5
               and l_shipmode in ('AIR', 'AIR REG')
               and l_shipinstruct = 'DELIVER IN PERSON')
           or (p_partkey = l_partkey and p_brand = 'Brand#23'
               and p_container in ('MED BAG', 'MED BOX', 'MED PKG', 'MED PACK')
               and l_quantity >= 10 and l_quantity <= 20 and p_size between 1 and 10
               and l_shipmode in ('AIR', 'AIR REG')
               and l_shipinstruct = 'DELIVER IN PERSON')
           or (p_partkey = l_partkey and p_brand = 'Brand#34'
               and p_container in ('LG CASE', 'LG BOX', 'LG PACK', 'LG PKG')
               and l_quantity >= 20 and l_quantity <= 30 and p_size between 1 and 15
               and l_shipmode in ('AIR', 'AIR REG')
               and l_shipinstruct = 'DELIVER IN PERSON')""")
    t = tpch_pandas
    j = t["lineitem"].merge(t["part"], left_on="l_partkey", right_on="p_partkey")
    j = j[(j.l_shipmode.isin(["AIR", "AIR REG"]))
          & (j.l_shipinstruct == "DELIVER IN PERSON")]
    m1 = ((j.p_brand == "Brand#12")
          & j.p_container.isin(["SM CASE", "SM BOX", "SM PACK", "SM PKG"])
          & (j.l_quantity >= 1) & (j.l_quantity <= 11)
          & (j.p_size >= 1) & (j.p_size <= 5))
    m2 = ((j.p_brand == "Brand#23")
          & j.p_container.isin(["MED BAG", "MED BOX", "MED PKG", "MED PACK"])
          & (j.l_quantity >= 10) & (j.l_quantity <= 20)
          & (j.p_size >= 1) & (j.p_size <= 10))
    m3 = ((j.p_brand == "Brand#34")
          & j.p_container.isin(["LG CASE", "LG BOX", "LG PACK", "LG PKG"])
          & (j.l_quantity >= 20) & (j.l_quantity <= 30)
          & (j.p_size >= 1) & (j.p_size <= 15))
    sel = j[m1 | m2 | m3]
    exp = (sel.l_extendedprice * (1 - sel.l_discount)).sum()
    np.testing.assert_allclose(got["revenue"][0], exp, rtol=1e-9)
