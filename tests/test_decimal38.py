"""Declared decimal(p<=38) columns end-to-end (VERDICT r3 item 8): a
decimal(38,x) column flows through aggregation + join + sort with exact
results.  Storage is scaled int64 (value domain |v| < 2^63, checked at
ingest); sums beyond 2^63 stay exact through the two-limb accumulators
(reference: spi/type/DecimalType Int128 long decimals,
DecimalSumAggregation's Int128 state)."""

import numpy as np
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.memory import MemoryConnector


@pytest.fixture()
def eng():
    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    return e, e.create_session("mem")


def test_decimal38_column_declaration_agg_join_sort(eng):
    e, s = eng
    e.execute_sql("create table w (k bigint, v decimal(38, 4))", s)
    e.execute_sql("create table d (k bigint, name varchar)", s)
    # large-but-fitting raw values: |v*10^4| < 2^63
    e.execute_sql(
        "insert into w values (1, 123456789012345.6789), "
        "(1, 876543210987654.3211), (2, 500000000000000.5000), "
        "(2, 0.0001), (3, 899999999999999.9999)", s)
    e.execute_sql("insert into d values (1, 'one'), (2, 'two'), (3, 'three')",
                  s)
    got = e.execute_sql(
        "select name, sum(v) sv, min(v) mn, max(v) mx, count(*) c "
        "from w, d where w.k = d.k group by name order by sv desc",
        s).to_pandas()
    assert got["name"].tolist() == ["one", "three", "two"]
    np.testing.assert_allclose(
        got["sv"].astype(float).to_numpy(),
        [1e15, 899999999999999.9999, 500000000000000.5001], rtol=1e-15)
    assert int(got["c"].sum()) == 5


def test_decimal38_sum_beyond_int64_exact(eng):
    """Sums past 2^63 finalize exactly (two-limb accumulators -> exact Decimal
    at the surface)."""
    from decimal import Decimal

    e, s = eng
    e.execute_sql("create table big (v decimal(38, 2))", s)
    n = 40
    val = "92233720368547758.07"  # raw = int64 max
    e.execute_sql("insert into big values " +
                  ", ".join([f"({val})"] * n), s)
    r = e.execute_sql("select sum(v) from big", s).rows()[0][0]
    assert Decimal(str(r)) == Decimal(val) * n  # > 2^63 in raw units


def test_decimal38_arithmetic_precision(eng):
    e, s = eng
    e.execute_sql("create table p (a decimal(20, 2), b decimal(20, 2))", s)
    e.execute_sql("insert into p values (100000.25, 3.50)", s)
    got = e.execute_sql("select a + b, a * b, a - b from p", s).rows()[0]
    assert float(got[0]) == 100003.75
    assert abs(float(got[1]) - 350000.875) < 1e-9
    assert float(got[2]) == 99996.75


def test_decimal38_ingest_overflow_rejected(eng):
    e, s = eng
    e.execute_sql("create table o (v decimal(38, 10))", s)
    with pytest.raises(Exception, match="2\\^63|beyond"):
        e.execute_sql(
            "insert into o values (99999999999999999999999999.0)", s)


def test_parquet_decimal38_roundtrip(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    from trino_tpu.connectors.parquet import ParquetConnector
    from decimal import Decimal

    vals = [Decimal("123456789012345.6789"), Decimal("-99999.0001"),
            Decimal("0.5")]
    tbl = pa.table({"v": pa.array(vals, type=pa.decimal128(38, 4)),
                    "k": pa.array([1, 2, 3], type=pa.int64())})
    pq.write_table(tbl, tmp_path / "t.parquet")
    e = Engine()
    e.register_catalog("pq", ParquetConnector(str(tmp_path)))
    s = e.create_session("pq")
    got = e.execute_sql("select k, v from t order by k", s).to_pandas()
    np.testing.assert_allclose(got["v"].astype(float).to_numpy(),
                               [float(v) for v in vals], rtol=1e-12)
    # a genuinely Int128-wide value is rejected with a clear error
    wide = pa.table({"v": pa.array([Decimal("9" * 25)],
                                   type=pa.decimal128(38, 0))})
    pq.write_table(wide, tmp_path / "w.parquet")
    e2 = Engine()
    e2.register_catalog("pq", ParquetConnector(str(tmp_path)))
    s2 = e2.create_session("pq")
    with pytest.raises(Exception, match="2\\^63|Int128"):
        e2.execute_sql("select sum(v) from w", s2).rows()
