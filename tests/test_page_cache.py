"""Device buffer pool (execution/bufferpool.DeviceBufferPool) — round 9.

Covers the acceptance surface of the HBM page/build cache: byte-identical
results cache on vs off, warm hits that actually collapse the dispatch bill,
per-query counter attribution, concurrent pooled executors sharing one pool,
LRU eviction under a tiny budget, full release on Engine._invalidate, and
INSERT/DDL invalidation (a stale page is never served).

The pool budget comes from TRINO_TPU_PAGE_CACHE, resolved lazily at first
use — every test sets it via monkeypatch BEFORE building its Engine.
"""

import threading

import numpy as np
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector

# small but multi-split: sf=0.01 lineitem ~60k rows over ~7 splits
SF, SPLIT_ROWS = 0.01, 1 << 14

Q_JOIN = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate limit 10"""

Q_AGG = """
select l_returnflag, l_linestatus, sum(l_quantity) s, count(*) c
from lineitem where l_shipdate <= date '1998-09-02'
group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus"""

# q18-shaped: semi join over a grouped subquery + string/date/decimal output
# surfaces — the dtype-decode paths a cached (concatenated) scan page must
# reproduce exactly
Q_SEMI = """
select c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice, sum(l_quantity)
from customer, orders, lineitem
where o_orderkey in (select l_orderkey from lineitem group by l_orderkey
                     having sum(l_quantity) > 100)
  and c_custkey = o_custkey and o_orderkey = l_orderkey
group by c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
order by o_totalprice desc, o_orderdate limit 50"""


def _engine(monkeypatch, budget=1 << 30):
    monkeypatch.setenv("TRINO_TPU_PAGE_CACHE", str(budget))
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=SF, split_rows=SPLIT_ROWS))
    return e


def _cols(res):
    return [np.asarray(c) for c in res.columns] + \
        [np.asarray(c) for c in res.raw_columns]


def _assert_same(a, b):
    for x, y in zip(_cols(a), _cols(b)):
        assert x.dtype == y.dtype
        assert np.array_equal(x, y, equal_nan=x.dtype.kind == "f")


@pytest.mark.parametrize("sql", [Q_JOIN, Q_SEMI], ids=["join", "semi"])
def test_results_byte_identical_cache_on_off(monkeypatch, sql):
    e = _engine(monkeypatch)
    on = e.create_session("tpch")
    off = e.create_session("tpch")
    e.session_properties.set_property(off, "page_cache", False)
    r_off = e.execute_sql(sql, off)
    assert e.last_query_counters.page_cache_misses == 0  # property respected
    r1 = e.execute_sql(sql, on)   # populates the pool
    r2 = e.execute_sql(sql, on)   # warm: whole-scan hit
    assert e.last_query_counters.page_cache_hits >= 1
    _assert_same(r_off, r1)
    _assert_same(r_off, r2)
    e._invalidate()


def test_warm_hit_collapses_dispatches(monkeypatch):
    e = _engine(monkeypatch)
    s = e.create_session("tpch")
    off = e.create_session("tpch")
    e.session_properties.set_property(off, "page_cache", False)
    e.execute_sql(Q_JOIN, s)          # cold: plan + compile + store
    e.execute_sql(Q_JOIN, off)        # warm baseline without the pool
    base = e.last_query_counters.snapshot()
    e.execute_sql(Q_JOIN, s)          # warm WITH the pool
    c = e.last_query_counters
    assert c.page_cache_hits >= 1
    assert c.page_cache_bytes_saved > 0
    # the whole probe scan arrives as ONE page: per-split consumer loops
    # collapse, so the warm dispatch bill must strictly beat cache-off
    assert c.device_dispatches < base.device_dispatches, \
        (c.device_dispatches, base.device_dispatches)
    # attribution: the hit landed on a "<Op>/scan.<table>.cache" site
    assert any(k.endswith(".cache") and v.get("page_cache_hits")
               for k, v in c.sites.items()), c.sites
    e._invalidate()


def test_hits_attributed_to_the_querys_own_counters(monkeypatch):
    e = _engine(monkeypatch)
    s = e.create_session("tpch")
    e.execute_sql(Q_AGG, s)                      # populate lineitem entry
    e.execute_sql("select count(*) from nation", s)
    c = e.last_query_counters
    assert c.page_cache_hits == 0, "nation query charged a lineitem hit"
    e.execute_sql(Q_AGG, s)
    assert e.last_query_counters.page_cache_hits >= 1
    e._invalidate()


def test_concurrent_pooled_executors_share_the_pool(monkeypatch):
    e = _engine(monkeypatch)
    s = e.create_session("tpch")
    ref = e.execute_sql(Q_JOIN, s)  # plan + first store
    results, errors = [None] * 4, []

    def run(i):
        try:
            results[i] = e.execute_sql(Q_JOIN, e.create_session("tpch"))
        except Exception as ex:  # surface in the main thread
            errors.append(ex)

    ts = [threading.Thread(target=run, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors, errors
    for r in results:
        _assert_same(ref, r)
    info = e.buffer_pool.info()
    # concurrent checkouts compile on FRESH executors: their builds/scans
    # must come from the shared pool, not be rebuilt per executor
    assert info["hits"] >= 1
    assert info["build_hits"] >= 1, info
    # full release on invalidation: no device-memory leak across DDL
    e._invalidate()
    assert e.buffer_pool.info()["entries"] == 0
    assert e.buffer_pool.memory_pool.reserved == 0


def test_build_cache_checkout_across_executors(monkeypatch):
    from trino_tpu.exec.local_executor import LocalExecutor
    from trino_tpu.sql import parser as A
    from trino_tpu.sql.frontend import Planner

    e = _engine(monkeypatch)
    sess = e.create_session("tpch")
    plan = Planner(e, sess).plan_query(A.parse(Q_JOIN))
    bp = e.buffer_pool
    ex1 = LocalExecutor(e.catalogs, buffer_pool=bp)
    ex2 = LocalExecutor(e.catalogs, buffer_pool=bp)
    r1 = ex1.execute(plan)
    h0 = bp.build_hits
    r2 = ex2.execute(plan)
    assert bp.build_hits > h0, "second executor rebuilt the cached build"
    _assert_same(r1, r2)
    e._invalidate()


def test_lru_eviction_under_tiny_budget(monkeypatch):
    # budget fits roughly one small scan: alternating tables must evict,
    # never raise, and stay within the labeled pool's ceiling
    e = _engine(monkeypatch, budget=64 << 10)
    s = e.create_session("tpch")
    for sql in ("select count(*) c from region group by r_regionkey",
                "select count(*) c from nation group by n_nationkey",
                "select count(*) c from region group by r_regionkey"):
        e.execute_sql(sql, s)
    info = e.buffer_pool.info()
    assert info["evictions"] >= 1 or info["bytes"] <= 64 << 10
    assert e.buffer_pool.memory_pool.reserved <= 64 << 10
    # an entry larger than the whole budget is skipped, not an error
    r = e.execute_sql(Q_AGG, s)
    assert len(r) > 0
    assert e.buffer_pool.memory_pool.reserved <= 64 << 10
    e._invalidate()


def test_insert_invalidates_stale_pages(monkeypatch):
    from trino_tpu.connectors.memory import MemoryConnector

    monkeypatch.setenv("TRINO_TPU_PAGE_CACHE", str(1 << 30))
    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table t (k bigint, v bigint)", s)
    e.execute_sql("insert into t values (1, 10), (2, 20)", s)
    r1 = e.execute_sql("select sum(v) s from t", s)
    assert int(r1.columns[0][0]) == 30
    e.execute_sql("select sum(v) s from t", s)  # cached read
    e.execute_sql("insert into t values (3, 70)", s)  # invalidates the pool
    assert e.buffer_pool.info()["entries"] == 0
    assert e.buffer_pool.memory_pool is None \
        or e.buffer_pool.memory_pool.reserved == 0
    r2 = e.execute_sql("select sum(v) s from t", s)
    assert int(r2.columns[0][0]) == 100, "stale cached page served after INSERT"
    e._invalidate()


def test_cache_off_by_default_without_env(monkeypatch):
    monkeypatch.delenv("TRINO_TPU_PAGE_CACHE", raising=False)
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=SF, split_rows=SPLIT_ROWS))
    s = e.create_session("tpch")
    e.execute_sql(Q_AGG, s)
    e.execute_sql(Q_AGG, s)
    c = e.last_query_counters
    # CPU backend default: pool disabled — no lookups, no stores
    assert c.page_cache_hits == 0 and c.page_cache_misses == 0
    assert e.buffer_pool.info()["entries"] == 0
    e._invalidate()


def test_worker_owns_its_pool(monkeypatch, tmp_path):
    """A WorkerServer caches what IT scans: its executors share the worker's
    own DeviceBufferPool, never the coordinator engine's."""
    from trino_tpu.server.cluster import WorkerServer

    monkeypatch.setenv("TRINO_TPU_PAGE_CACHE", str(1 << 20))
    w = WorkerServer({"tpch": {"connector": "tpch", "sf": 0.01}},
                     str(tmp_path))
    assert w.local.buffer_pool is w.buffer_pool
    ex = w._checkout_executor(query_key="q", token="t0")
    try:
        assert ex.buffer_pool is w.buffer_pool
    finally:
        w._release_executor(ex, token="t0")
    e = Engine()
    assert e.buffer_pool is not w.buffer_pool


def test_explain_analyze_shows_buffer_pool_line(monkeypatch):
    e = _engine(monkeypatch)
    s = e.create_session("tpch")
    e.execute_sql(Q_AGG, s)  # populate
    r = e.execute_sql(f"explain analyze {Q_AGG}", s)
    text = "\n".join(str(row[0]) for row in r.rows())
    assert "Buffer pool:" in text, text
    e._invalidate()
