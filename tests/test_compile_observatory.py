"""Compile observatory (round 17): per-compilation attribution at the _jit
chokepoint, compile-aware stall verdicts, and the executable cost census.

What this pins:
- cold/warm detection — a first-seen ABSTRACT arg signature per _jit wrapper
  records one compile (counters.compiles / compile_s, site-attributed); a
  warm re-execution records ZERO (the recompile-regression guard — the SF1
  version lives in tests/test_query_budgets.py);
- wall attribution — the "compile" bucket outranks device_dispatch, so a
  cold statement's wall names compilation instead of inflating the dispatch
  bucket, and buckets still sum to wall by construction;
- compile-aware stall verdicts — a compiling in-flight entry past STALL_S
  but under TRINO_TPU_STALL_COMPILE_S verdicts "compiling" (no stall
  report, no worker degradation); past the compile threshold it is a
  genuine wedge and reports stalled;
- the census — CompileLog ring + recompile-storm detection, surfaced via
  system.runtime.compilations, GET /v1/compiles, /v1/metrics (strict
  Prometheus parse), EXPLAIN ANALYZE's "Compile:" line, and flight records.
"""

import json
import time
import urllib.request

import pytest

from trino_tpu.execution import tracing
from trino_tpu.execution.tracing import (COMPILE_LOG, CompileLog,
                                         QueryCounters, StallWatchdog,
                                         arg_signature, signature_summary)

QUERY = """select l_returnflag, sum(l_quantity) q, count(*) c
           from lineitem where l_shipdate <= date '1998-09-02'
           group by l_returnflag order by l_returnflag"""


# ---------------------------------------------------------------- unit layer
def test_arg_signature_distinguishes_shapes_dtypes_and_statics():
    import numpy as np

    k1 = arg_signature((np.zeros((4,), np.int64),))
    k2 = arg_signature((np.zeros((8,), np.int64),))   # shape differs
    k3 = arg_signature((np.zeros((4,), np.float64),))  # dtype differs
    k4 = arg_signature((np.zeros((4,), np.int64), 7))  # static differs
    k5 = arg_signature((np.zeros((4,), np.int64), 8))
    assert len({k1, k2, k3, k4, k5}) == 5
    k1b = arg_signature((np.ones((4,), np.int64),))  # values don't matter
    assert k1 == k1b
    # the printable form renders lazily FROM the key (cold path only)
    assert "int64[4]" in signature_summary(k1)
    assert "7" in signature_summary(k4)
    # pytree STRUCTURE is part of the key (same leaves, different nesting)
    ka = arg_signature(((np.zeros((2,)), np.zeros((2,))),))
    kb = arg_signature((np.zeros((2,)), np.zeros((2,))))
    assert ka != kb


def test_counters_carry_compiles_and_roundtrip():
    a = QueryCounters()
    a.compiles = 2
    a.compile_s = 1.25
    a.sites["Agg#0/step"] = {"dispatches": 1, "transfers": 0, "bytes": 0,
                             "compiles": 2, "compile_s": 1.25}
    b = QueryCounters.from_dict(a.as_dict())
    assert b.compiles == 2 and b.compile_s == pytest.approx(1.25)
    assert b.sites["Agg#0/step"]["compile_s"] == pytest.approx(1.25)
    b.merge(a)
    assert b.compiles == 4 and b.compile_s == pytest.approx(2.5)


def test_jit_wrapper_detects_first_seen_signatures():
    """Two distinct shapes through ONE wrapper = two compiles; repeats of a
    seen shape = zero more.  Detection is a host-side set lookup — the
    dispatch count keeps counting every invocation."""
    import jax.numpy as jnp

    from trino_tpu.exec.local_executor import _jit

    f = _jit(lambda x: x * 2 + 1, site="obs.test")
    c = QueryCounters()
    with tracing.track_counters(c):
        f(jnp.arange(8))
        f(jnp.arange(8))   # warm
        f(jnp.arange(16))  # new shape -> compile
        f(jnp.arange(16))  # warm
    assert c.compiles == 2, c.as_dict()
    assert c.device_dispatches == 4
    assert c.compile_s > 0
    assert c.sites["obs.test"]["compiles"] == 2


def test_failed_first_seen_dispatch_does_not_poison_seen():
    """A first-seen dispatch that RAISES (injected fault, transient device
    error) records no compile and leaves the signature unseen — the retry
    is the run that really compiles, and it must still be flagged
    `compiling` or a tight STALL_S would read the legit compile as a wedge
    (the footgun this round retires)."""
    import jax.numpy as jnp

    from trino_tpu.exec.local_executor import _jit

    f = _jit(lambda x: x + 1, site="obs.fail")
    c = QueryCounters()
    fired = {"n": 0}

    def hook(label):
        if label == "obs.fail" and fired["n"] == 0:
            fired["n"] += 1
            raise RuntimeError("injected")

    tracing.DISPATCH_TEST_HOOK = hook
    try:
        with tracing.track_counters(c):
            with pytest.raises(RuntimeError):
                f(jnp.arange(4))
            assert c.compiles == 0  # failure: nothing recorded, not seen
            f(jnp.arange(4))  # the retry pays (and records) THE compile
            assert c.compiles == 1
            f(jnp.arange(4))  # now genuinely warm
            assert c.compiles == 1
    finally:
        tracing.DISPATCH_TEST_HOOK = None


def test_compile_log_storm_detection(caplog):
    import logging

    cl = CompileLog(max_records=16, storm_sigs=3)
    with caplog.at_level(logging.WARNING, logger="trino_tpu.stall"):
        for i in range(5):
            cl.record(site="probe.step", label="HashJoin#2/probe.step",
                      query_id="q1", signature=f"int64[{i}]",
                      sig_key=f"s{i}", duration_s=0.01)
        # a second site under threshold never storms
        cl.record(site="other", label="Agg#0/other", query_id="q1",
                  signature="int64[1]", sig_key="t0", duration_s=0.01)
    info = cl.info()
    assert info["compiles_total"] == 6
    assert info["storms_total"] == 1
    assert info["stormed_labels"] == ["HashJoin#2/probe.step"]
    storms = [r for r in caplog.records if "recompile storm" in r.message]
    assert len(storms) == 1  # warned ONCE per storm, not per compile
    assert "HashJoin#2/probe.step" in storms[0].getMessage()
    # a DIFFERENT statement's compiles at the same site count in their own
    # key (storms are per execution — cross-query shape diversity through
    # module-level wrappers is legitimate, not churn)
    cl.record(site="probe.step", label="HashJoin#2/probe.step",
              query_id="q2", signature="int64[0]", sig_key="s0",
              duration_s=0.01)
    assert cl.info()["storms_total"] == 1
    assert len(cl.for_query("q2")) == 1
    # the histogram rides the compile bucket scale
    assert cl.latency.total == 7


def test_watchdog_compile_aware_verdicts():
    """Fake clock: a compiling entry past stall_s but under compile_stall_s
    verdicts "compiling" with NO stall report; past compile_stall_s it is a
    genuine wedge; a non-compiling entry stalls at stall_s as before."""
    reg = tracing.InflightRegistry()
    got = []
    wd = StallWatchdog(registry=reg, stall_s=5.0, compile_stall_s=200.0,
                       kill_s=0, on_stall=got.append)
    with tracing.track_inflight(reg), tracing.query_scope("q7"):
        tok = reg.enter("dispatch", "agg.step", compiling=True)
        try:
            now = time.monotonic() + 100.0  # 100s old: over stall, under compile
            assert wd.verdict(now=now) == ("compiling", 1)
            assert wd.check(now=now) is None and got == []
            assert wd.compiling_now == 1 and wd.stalled_now == 0
            now = time.monotonic() + 300.0  # past compile threshold: wedged
            assert wd.verdict(now=now) == ("stalled", 1)
            report = wd.check(now=now)
            assert report is not None and got == [report]
            assert report["stalled"][0]["compiling"] is True
        finally:
            reg.exit(tok)
        # non-compiling entry: stalls at stall_s exactly as before round 17
        tok = reg.enter("dispatch", "probe.step")
        try:
            now = time.monotonic() + 10.0
            assert wd.verdict(now=now) == ("stalled", 1)
        finally:
            reg.exit(tok)
    assert wd.verdict()[0] == "ok"


def test_watchdog_compile_threshold_defaults_to_10x():
    wd = StallWatchdog(registry=tracing.InflightRegistry(), stall_s=3.0)
    assert wd.compile_stall_s == pytest.approx(30.0)


def test_coordinator_does_not_degrade_compiling_worker(tmp_path):
    """The acceptance bit the round-8 footgun was about: a worker whose
    health verdict is "compiling" keeps receiving work (not degraded, stays
    in live_workers); "stalled" still gates it out."""
    from trino_tpu import Engine
    from trino_tpu.server.cluster import ClusterCoordinator

    coord = ClusterCoordinator(Engine(), spool_dir=str(tmp_path))
    # no coord.start(): _announce + live_workers are plain methods
    coord._announce("w1", "http://127.0.0.1:1", health="compiling")
    coord._announce("w2", "http://127.0.0.1:2", health="stalled")
    coord._announce("w3", "http://127.0.0.1:3", health="ok")
    by_id = {w.node_id: w for w in coord.workers.values()}
    assert not by_id["w1"].degraded
    assert by_id["w2"].degraded
    assert {w.node_id for w in coord.live_workers()} == {"w1", "w3"}


# -------------------------------------------------------------- engine layer
@pytest.fixture(scope="module")
def obs_engine(tpch_sf001):
    """A FRESH engine: the module needs genuinely cold executions (the
    shared session `engine` fixture is warm from other modules)."""
    from trino_tpu import Engine

    e = Engine()
    e.register_catalog("tpch", tpch_sf001)
    yield e
    e._invalidate()


def test_cold_then_warm_compile_split_and_wall_attribution(obs_engine):
    """Acceptance (test scale; SF1 lives in test_query_budgets): the cold
    run records compiles and its wall_breakdown charges more to `compile`
    than to `device_dispatch`; the warm run records ZERO compiles and no
    compile bucket; buckets sum to wall within the structural 5%."""
    from trino_tpu.execution.tracing import WALL_BUCKETS

    s = obs_engine.create_session("tpch")
    obs_engine.execute_sql(QUERY, s)
    cold = obs_engine.last_query_counters
    cold_bd = obs_engine.last_query_trace.get("wall_breakdown")
    assert cold.compiles > 0 and cold.compile_s > 0
    assert cold_bd and cold_bd["compile"] > 0
    # compilation, not execution, is the named cost of a cold statement
    assert cold_bd["compile"] > cold_bd["device_dispatch"]
    total = sum(cold_bd[b] for b in WALL_BUCKETS)
    assert abs(total - cold_bd["wall_s"]) <= 0.05 * cold_bd["wall_s"]
    # per-site sums equal the totals (the attribution invariant extends)
    assert sum(v.get("compiles", 0) for v in cold.sites.values()) \
        == cold.compiles
    obs_engine.execute_sql(QUERY, s)
    warm = obs_engine.last_query_counters
    warm_bd = obs_engine.last_query_trace.get("wall_breakdown")
    assert warm.compiles == 0 and warm.compile_s == 0.0
    assert warm_bd["compile"] == 0.0
    total = sum(warm_bd[b] for b in WALL_BUCKETS)
    assert abs(total - warm_bd["wall_s"]) <= 0.05 * warm_bd["wall_s"]


def test_flight_record_carries_compile_census(obs_engine):
    s = obs_engine.create_session("tpch")
    sql = "select count(*) from orders where o_orderkey > 7"
    obs_engine.execute_sql(sql, s)
    qid = obs_engine.last_query_trace["query_id"]
    n = obs_engine.last_query_counters.compiles
    assert n > 0
    rec = obs_engine.flight_recorder.get(qid)
    assert rec is not None
    assert rec["compiles"] == n
    assert rec["compile_s"] > 0
    events = rec["compile_events"]
    assert events and all(e["query_id"] == qid for e in events)
    assert sum(1 for _ in events) == n
    assert all(e.get("signature") for e in events)


def test_explain_analyze_compile_line(obs_engine):
    """EXPLAIN ANALYZE runs a throwaway executor (fresh _jit wrappers), so
    its counters always include the run's compiles — the "Compile:" line is
    deterministic there."""
    import re

    s = obs_engine.create_session("tpch")
    r = obs_engine.execute_sql(
        "explain analyze select count(*) from nation", s)
    text = "\n".join(str(row[0]) for row in r.rows())
    m = re.search(r"Compile: (\d+) compilations, ([0-9.]+)s", text)
    assert m, text
    assert int(m.group(1)) > 0


def test_system_runtime_compilations_table(obs_engine):
    s = obs_engine.create_session("tpch")
    obs_engine.execute_sql(QUERY, s)  # ensure census rows exist
    r = obs_engine.execute_sql(
        "select site, label, query_id, signature, duration_s "
        "from system.compilations", s)
    rows = r.rows()
    assert rows
    sites = {row[0] for row in rows}
    assert any(site for site in sites)
    # rows mirror the engine's census ring (the scan itself may compile and
    # append, so subset — every retained record has a positive duration)
    assert all(row[4] is None or row[4] >= 0 for row in rows)
    labels = {row[1] for row in rows}
    assert any("/" in (l or "") for l in labels)  # "<Op>#<k>/<site>" form


# ---------------------------------------------------------------- HTTP layer
@pytest.fixture()
def obs_server(obs_engine):
    from trino_tpu.server.server import CoordinatorServer

    srv = CoordinatorServer(obs_engine, port=0)
    srv.start()
    yield srv
    srv.stop()


def test_v1_compiles_endpoint(obs_server, obs_engine):
    s = obs_engine.create_session("tpch")
    obs_engine.execute_sql(QUERY, s)
    payload = json.loads(urllib.request.urlopen(
        obs_server.url + "/v1/compiles", timeout=10).read().decode())
    assert payload["info"]["compiles_total"] > 0
    assert payload["info"]["storm_threshold_sigs"] > 0
    recs = payload["records"]
    assert recs
    for r in recs[:5]:
        assert {"site", "label", "query_id", "signature", "duration_s",
                "exe_bytes", "at"} <= set(r)


def test_metrics_compile_series_strict_parse(obs_server, obs_engine):
    from test_profiling import _parse_prometheus

    s = obs_engine.create_session("tpch")
    obs_engine.execute_sql("select count(*) from region", s)
    body = urllib.request.urlopen(
        obs_server.url + "/v1/metrics", timeout=10).read().decode()
    parsed = _parse_prometheus(body)
    assert parsed["types"]["trino_tpu_compiles_total"] == "counter"
    assert parsed["samples"]["trino_tpu_compiles_total"][0][1] > 0
    assert parsed["types"]["trino_tpu_recompile_storms_total"] == "counter"
    assert parsed["types"]["trino_tpu_compiling_dispatches"] == "gauge"
    assert parsed["samples"]["trino_tpu_compiling_dispatches"][0][1] == 0
    assert parsed["types"]["trino_tpu_compile_seconds"] == "histogram"
    buckets = parsed["samples"]["trino_tpu_compile_seconds_bucket"]
    assert buckets[-1][0].get("le") == "+Inf"
    counts = [v for _, v in buckets]
    assert counts == sorted(counts)  # cumulative
    assert counts[-1] == parsed["samples"][
        "trino_tpu_compile_seconds_count"][0][1]
    assert parsed["samples"]["trino_tpu_compile_seconds_sum"][0][1] > 0


def test_status_health_reports_compiling(obs_server, obs_engine):
    """/v1/status health flips to "compiling" (NOT "stalled", no stall
    report) while a compiling in-flight entry ages past STALL_S but under
    the compile threshold — live, via the registry, no watchdog thread."""
    wd = obs_engine.stall_watchdog
    saved = (wd.stall_s, wd.compile_stall_s)
    wd.stall_s, wd.compile_stall_s = 0.05, 60.0
    tok = obs_engine.inflight.enter("dispatch", "obs.compile",
                                    compiling=True)
    try:
        time.sleep(0.1)
        st = json.loads(urllib.request.urlopen(
            obs_server.url + "/v1/status", timeout=10).read().decode())
        assert st["health"]["status"] == "compiling"
        assert st["health"]["compiling"] >= 1
        assert st["health"]["stalled"] == 0
        entries = [e for e in st["inflight"] if e["site"] == "obs.compile"]
        assert entries and entries[0]["compiling"] is True
    finally:
        obs_engine.inflight.exit(tok)
        wd.stall_s, wd.compile_stall_s = saved
    assert obs_engine.health()["status"] == "ok"


def test_query_log_compile_columns(obs_engine):
    s = obs_engine.create_session("tpch")
    obs_engine.execute_sql(QUERY, s)
    qid = obs_engine.last_query_trace["query_id"]
    r = obs_engine.execute_sql(
        "select query_id, compiles, compile_s from system.query_log", s)
    rows = {row[0]: row for row in r.rows()}
    assert qid in rows
    # the module's first QUERY execution was cold: its record carries the
    # compiles it paid; this (warm) re-execution's record will carry 0
    assert rows[qid][1] is not None
