"""Higher-order array functions (reference: operator/scalar/
ArrayTransformFunction, ArrayFilterFunction, ArrayAnyMatchFunction + the
grammar's lambda expressions).  TPU re-design: the element heap is a
plan-time constant, so lambdas evaluate once over the whole heap (the string
LUT trick) and the device-side work stays span-only — filter remaps spans
through an exclusive cumsum of kept elements, never touching elements."""

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.memory import MemoryConnector


@pytest.fixture(scope="module")
def eng():
    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table t (id bigint)", s)
    e.execute_sql("insert into t values (1), (2)", s)
    return e, s


def _one(eng, expr):
    e, s = eng
    return e.execute_sql(f"select {expr} v from t where id = 1", s).rows()[0][0]


def test_transform(eng):
    assert _one(eng, "transform(array[1,2,3], x -> x * 2 + 1)") == [3, 5, 7]
    assert _one(eng, "transform(array[1.5, 2.5], x -> x * 2)") == [3.0, 5.0]


def test_filter(eng):
    assert _one(eng, "filter(array[5,-2,7,0], x -> x > 0)") == [5, 7]
    assert _one(eng, "filter(array[1,2,3], x -> x > 9)") == []
    assert _one(eng, "cardinality(filter(array[5,-2,7,0], x -> x >= 5))") == 2


def test_matches(eng):
    assert bool(_one(eng, "any_match(array[1,2,3], x -> x > 2)"))
    assert not bool(_one(eng, "any_match(array[1,2,3], x -> x > 9)"))
    assert bool(_one(eng, "all_match(array[1,2,3], x -> x > 0)"))
    assert not bool(_one(eng, "all_match(array[1,2,3], x -> x > 1)"))
    assert bool(_one(eng, "none_match(array[1,2,3], x -> x > 9)"))
    assert not bool(_one(eng, "none_match(array[1,2,3], x -> x = 2)"))


def test_compose(eng):
    assert _one(eng, "transform(filter(array[1,2,3,4], x -> x % 2 = 0), "
                     "y -> y * 10)") == [20, 40]
    assert _one(eng, "array_sum(transform(array[1,2,3], x -> x * x))") == 14


def test_two_param_lambda_rejected_cleanly(eng):
    e, s = eng
    with pytest.raises(Exception, match="one-parameter"):
        e.execute_sql("select transform(array[1], (a, b) -> a + b) v "
                      "from t where id = 1", s)
