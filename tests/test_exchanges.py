"""AddExchanges distribution planning (reference test model:
TestAddExchanges / TestDetermineJoinDistributionType over
sql/planner/optimizations/AddExchanges.java:145): cost-compared
broadcast-vs-partitioned on plan trees + the EXPLAIN Exchange surface."""

import numpy as np

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.page import Field, Schema
from trino_tpu.sql import plan as P
from trino_tpu.sql.exchanges import (BROADCAST_ABS_CAP, estimate_rows,
                                     physical_plan, resolve_distributions)
from trino_tpu.sql.frontend import compile_sql
from trino_tpu.types import BIGINT


class _StatsConn:
    """Minimal connector exposing row counts for the estimator."""

    def __init__(self, tables):  # {name: rows}
        self._tables = tables

    def row_count(self, table):
        return self._tables[table]


def _scan(table, rows_field="a"):
    schema = Schema((Field(rows_field, BIGINT), Field("k", BIGINT)))
    return P.TableScan("cat", table, (rows_field, "k"), schema)


def _join(left, right, dist="replicated"):
    schema = Schema((Field("l0", BIGINT), Field("l1", BIGINT),
                     Field("r0", BIGINT), Field("r1", BIGINT)))
    return P.Join("inner", left, right, (1,), (1,), schema,
                  distribution=dist)


CATALOGS = {"cat": _StatsConn({"big": 50_000_000, "mid": 400_000,
                               "small": 1_000})}


def test_estimate_rows_basics():
    assert estimate_rows(_scan("big"), CATALOGS) == 50_000_000
    assert estimate_rows(P.Limit(_scan("big"), 10), CATALOGS) == 10
    f = P.Filter(_scan("big"), None)  # predicate unused by the estimator
    est = estimate_rows(f, CATALOGS)
    assert est is not None and 0 < est < 50_000_000


def test_small_build_huge_probe_forces_broadcast():
    """Replicating 400k x 8 devices beats routing 50M probe rows: the global
    pass sees the probe side the frontend's per-join estimate did not."""
    j = _join(_scan("big"), _scan("mid"), dist="partitioned")
    out = resolve_distributions(j, CATALOGS)
    assert out.distribution == "broadcast", out.distribution


def test_large_build_partitions():
    j = _join(_scan("big"), _scan("big"))
    out = resolve_distributions(j, CATALOGS)
    assert out.distribution == "partitioned"


def test_broadcast_cap_defers_to_executor():
    """A build past the absolute cap must NOT be force-broadcast even when
    the traffic model prefers it — the executor's actual-size threshold is
    the estimate-risk safety net."""
    huge_build = int(BROADCAST_ABS_CAP * 1.5)
    cat = {"cat": _StatsConn({"probe": 10_000_000_000, "build": huge_build})}
    j = _join(_scan("probe"), _scan("build"))
    out = resolve_distributions(j, cat)
    assert out.distribution == "partitioned"  # >= threshold, not broadcast


def test_session_forcing_wins():
    j = _join(_scan("big"), _scan("big"))
    out = resolve_distributions(j, CATALOGS,
                                {"join_distribution_type": "BROADCAST"})
    assert out.distribution == "broadcast"


def test_tiny_build_stays_automatic():
    j = _join(_scan("big"), _scan("small"))
    out = resolve_distributions(j, CATALOGS)
    assert out.distribution == "broadcast"  # 1k x 8 << 50M: clear winner


def test_physical_plan_marks_exchanges():
    j = _join(_scan("big"), _scan("big"))
    phys = physical_plan(j, CATALOGS)
    exs = []

    def walk(n):
        if isinstance(n, P.Exchange):
            exs.append(n)
        for c in n.children:
            walk(c)

    walk(phys)
    kinds = sorted(e.kind for e in exs)
    assert kinds == ["hash", "hash"], kinds
    assert all(e.keys == (1,) for e in exs)


def test_explain_shows_exchange_placement():
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 11))
    r = e.execute_sql("""explain select c_name, o_orderkey from customer, orders
                         where c_custkey = o_custkey
                         order by o_orderkey limit 5""")
    text = "\n".join(str(row[0]) for row in r.rows())
    assert "Exchange[" in text, text
    assert "gather" in text or "broadcast" in text or "hash" in text, text


def test_resolved_distribution_correctness():
    """The pass's decisions must not change results: force each mode through
    session properties and compare."""
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 11))
    sql = """select o_orderpriority, count(*) c from orders, lineitem
             where o_orderkey = l_orderkey group by o_orderpriority
             order by o_orderpriority"""
    base = e.execute_sql(sql).rows()
    for mode in ("BROADCAST", "PARTITIONED"):
        s = e.create_session("tpch")
        s.properties["join_distribution_type"] = mode
        assert e.execute_sql(sql, s).rows() == base, mode


def test_unconfident_estimate_never_forces_broadcast():
    """A coefficient-derived build estimate (aggregate x0.1 guess) must not
    force 'broadcast' — the executor's actual-size threshold stays the
    safety net (post-review hardening: a wrong guess would replicate a huge
    build in-core with no fallback)."""
    agg_schema = Schema((Field("k", BIGINT), Field("n", BIGINT)))
    build = P.Aggregate(_scan("big"), (1,),
                        (P.AggSpec("count_star", None, "n", BIGINT),),
                        agg_schema)  # est: 50M * 0.1 = 5M... still a GUESS
    cat = {"cat": _StatsConn({"big": 50_000_000})}
    j = P.Join("inner", _scan("big"), build, (1,), (0,),
               Schema((Field("l0", BIGINT), Field("l1", BIGINT),
                       Field("r0", BIGINT), Field("r1", BIGINT))))
    out = resolve_distributions(j, cat)
    assert out.distribution != "broadcast", out.distribution
