"""Static device-boundary lint over ``trino_tpu/exec/*.py``.

CLAUDE.md's rule — executor code MUST go through ``_jit`` (not bare
``jax.jit``) and ``_host`` (never a loose ``np.asarray`` of device values) or
the dispatch/transfer is invisible to the per-query budget counters — was a
doc note until round 6.  This test makes it an enforced invariant:

- ``jax.jit`` may be REFERENCED only inside the ``_jit`` helper itself (the
  one place the accounting wrapper is built).  Round 11 tightened this from
  call-sites to attribute references: ``partial(jax.jit, ...)`` smuggled an
  uncounted/uninjectable dispatch past the call-only check for four rounds
  (exec/spill's old ``_route_sorted`` was the escapee).
- ``jax.device_get(`` is an unbatched, uncounted device->host pull — it may
  appear only inside ``_host`` or on a line annotated ``# host-ok[: reason]``
  asserting the value is already host-resident.
- ``np.asarray(`` may appear only
  (a) inside a small set of allowlisted HOST-SIDE helpers (below, each with
      the reason it is exempt), or
  (b) on a line annotated ``# host-ok[: reason]`` asserting the value is
      already host-resident (python lists, dictionary values, arrays
      previously pulled through ``_host``/``jax.device_get``).

A new un-annotated np.asarray is treated as an unaccounted device pull until
proven otherwise — the failure mode this PR's sweep fixed dozens of times
over (per-column pulls in exchange/serialize/merge paths that never showed on
the budget).  If your np.asarray really is host-side, say so with the marker;
if it isn't, batch it through ``_host``.

Round 7 adds the ATTRIBUTION rule over the same files (local_executor.py,
distributed.py, fte.py, ...): every ``_host(...)`` call must pass a
``site=`` tag (or carry ``# site-ok: <reason>`` on the call line), and every
``_jit(...)`` call whose function argument is anonymous (a lambda/closure
expression) must too — a named function self-labels through ``__name__``.
Without this, per-site boundary attribution (EXPLAIN ANALYZE's site table,
the budget-failure dump, /v1/metrics site series) silently rots to
"untagged" as new call sites land.
"""

import ast
import pathlib

import pytest

EXEC_DIR = pathlib.Path(__file__).resolve().parent.parent / "trino_tpu" / "exec"
OPS_DIR = pathlib.Path(__file__).resolve().parent.parent / "trino_tpu" / "ops"

# functions whose BODY may use np.asarray freely, with why:
ASARRAY_ALLOWED_FUNCS = {
    "_host",              # the accounting chokepoint itself
    "_host_page",         # batched page pull built on _host
    "_page_to_device",    # host->device direction (no pull)
    "_finalize_aggs",     # host finalize over accumulators its callers pulled
    "_combine_limbs_vec",  # host two-limb recombine (input already pulled)
}

MARKER = "# host-ok"

# functions whose BODY may call jax.device_get freely, with why:
DEVICE_GET_ALLOWED_FUNCS = {
    "_host",              # the accounting chokepoint itself
}

# functions whose BODY may call jax.device_put freely, with why:
DEVICE_PUT_ALLOWED_FUNCS = {
    "_page_to_device",    # THE sanctioned H2D chokepoint: prefetch staging
    # and buffer-pool stores funnel through it (execution/bufferpool has its
    # own _to_device twin outside exec/)
}

DEVICE_MARKER = "# device-ok"


def _exec_files():
    files = sorted(EXEC_DIR.glob("*.py"))
    assert files, EXEC_DIR
    return files


SITE_MARKER = "# site-ok"

# functions whose BODY may call _host/_jit without a site tag (the helpers
# that thread their caller's site through):
SITE_ALLOWED_FUNCS = {
    "_host_page",  # passes its own ``site`` parameter through to _host
}

STATS_MARKER = "# stats-ok"

# functions whose BODY may touch ``.stats.setdefault`` directly, with why:
STATS_ALLOWED_FUNCS = {
    "_node_stats",  # THE registration chokepoint: captures the structural
    # node path + CBO estimate the plan-history feed needs (round 15)
}


class _Scan(ast.NodeVisitor):
    def __init__(self, lines):
        self.lines = lines
        self.func_stack = []
        self.jit_hits = []      # (lineno, enclosing function)
        self.asarray_hits = []  # (lineno, enclosing function)
        self.device_put_hits = []  # (lineno, enclosing function)
        self.device_get_hits = []  # (lineno, enclosing function)
        self.site_hits = []     # (lineno, enclosing function, callee)
        self.stats_hits = []    # (lineno, enclosing function)

    def visit_FunctionDef(self, node):
        self.func_stack.append(node.name)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _check_site(self, node, callee):
        """_host calls always need site=/marker; _jit calls need one unless
        the wrapped function is a NAME (self-labeling via __name__)."""
        if set(self.func_stack) & SITE_ALLOWED_FUNCS:
            return
        if any(kw.arg == "site" for kw in node.keywords):
            return
        if SITE_MARKER in self.lines[node.lineno - 1]:
            return
        if callee == "_jit" and node.args \
                and isinstance(node.args[0], (ast.Name, ast.Attribute)):
            return  # named step fn: _jit derives the site from __name__
        where = self.func_stack[-1] if self.func_stack else "<module>"
        self.site_hits.append((node.lineno, where, callee))

    def visit_Attribute(self, node):
        # ATTRIBUTE references, not just calls: `partial(jax.jit, ...)` and
        # `f = jax.device_get` alias the boundary away from the call-site
        # checks, so the raw reference is what the lint must flag
        if isinstance(node.value, ast.Name) and node.value.id == "jax":
            where = self.func_stack[-1] if self.func_stack else "<module>"
            if node.attr == "jit" and "_jit" not in self.func_stack:
                self.jit_hits.append((node.lineno, where))
            if node.attr == "device_get":
                if not (set(self.func_stack) & DEVICE_GET_ALLOWED_FUNCS) \
                        and MARKER not in self.lines[node.lineno - 1]:
                    self.device_get_hits.append((node.lineno, where))
        self.generic_visit(node)

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Name) and f.id in ("_jit", "_host"):
            self._check_site(node, f.id)
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            where = self.func_stack[-1] if self.func_stack else "<module>"
            if f.value.id == "np" and f.attr == "asarray":
                if not (set(self.func_stack) & ASARRAY_ALLOWED_FUNCS) \
                        and MARKER not in self.lines[node.lineno - 1]:
                    self.asarray_hits.append((node.lineno, where))
            if f.value.id == "jax" and f.attr == "device_put":
                if not (set(self.func_stack) & DEVICE_PUT_ALLOWED_FUNCS) \
                        and DEVICE_MARKER not in self.lines[node.lineno - 1]:
                    self.device_put_hits.append((node.lineno, where))
        # round-15 rule: `<anything>.stats.setdefault(` outside _node_stats —
        # a raw registration skips the structural-path/estimate capture the
        # plan-history feed relies on
        if isinstance(f, ast.Attribute) and f.attr == "setdefault" \
                and isinstance(f.value, ast.Attribute) \
                and f.value.attr == "stats":
            where = self.func_stack[-1] if self.func_stack else "<module>"
            if not (set(self.func_stack) & STATS_ALLOWED_FUNCS) \
                    and STATS_MARKER not in self.lines[node.lineno - 1]:
                self.stats_hits.append((node.lineno, where))
        self.generic_visit(node)


def _scan(path):
    src = path.read_text()
    s = _Scan(src.splitlines())
    s.visit(ast.parse(src))
    return s


@pytest.mark.parametrize("path", _exec_files(), ids=lambda p: p.name)
def test_no_bare_jax_jit(path):
    s = _scan(path)
    assert not s.jit_hits, (
        f"{path.name}: bare jax.jit reference at "
        + ", ".join(f"line {ln} (in {fn})" for ln, fn in s.jit_hits)
        + " — use exec.local_executor._jit so the dispatch is counted "
          "against the query budget (partial(jax.jit, ...) counts too)")


@pytest.mark.parametrize("path", _exec_files(), ids=lambda p: p.name)
def test_no_bare_device_get(path):
    """Round-11 rule: jax.device_get is an unbatched, uncounted D2H pull —
    invisible to the budget counters, the in-flight registry and the chaos
    injector.  Pull through _host (batched, counted) or annotate
    '# host-ok: <reason>' when the value is already host-resident."""
    s = _scan(path)
    assert not s.device_get_hits, (
        f"{path.name}: bare jax.device_get at "
        + ", ".join(f"line {ln} (in {fn})" for ln, fn in s.device_get_hits)
        + " — batch the pull through _host, or annotate "
          "'# host-ok: <reason>'")


@pytest.mark.parametrize("path", _exec_files(), ids=lambda p: p.name)
def test_no_loose_np_asarray(path):
    s = _scan(path)
    assert not s.asarray_hits, (
        f"{path.name}: loose np.asarray at "
        + ", ".join(f"line {ln} (in {fn})" for ln, fn in s.asarray_hits)
        + " — a device value must pull through _host (batched, counted); "
          "a host value needs a '# host-ok: <reason>' annotation")


@pytest.mark.parametrize("path", _exec_files(), ids=lambda p: p.name)
def test_no_bare_device_put(path):
    """Round-9 rule: H2D staging goes through the sanctioned chokepoints
    (_page_to_device / the buffer pool's store path) or carries a
    '# device-ok: <reason>' annotation — a loose jax.device_put is H2D
    traffic the page cache can neither serve nor account."""
    s = _scan(path)
    assert not s.device_put_hits, (
        f"{path.name}: bare jax.device_put at "
        + ", ".join(f"line {ln} (in {fn})" for ln, fn in s.device_put_hits)
        + " — stage through _page_to_device (or the buffer pool) so cached "
          "scans can serve it, or annotate '# device-ok: <reason>'")


@pytest.mark.parametrize("path", _exec_files(), ids=lambda p: p.name)
def test_every_boundary_call_is_attributed(path):
    """Every _jit/_host call site carries a site tag (or is self-labeling /
    explicitly marked), so per-site boundary attribution cannot silently rot
    back to 'untagged' as new executor code lands."""
    s = _scan(path)
    assert not s.site_hits, (
        f"{path.name}: unattributed boundary call at "
        + ", ".join(f"line {ln} ({callee} in {fn})"
                    for ln, fn, callee in s.site_hits)
        + " — pass site=\"<op.tag>\" (or '# site-ok: <reason>' if the call "
          "is intentionally untagged); named functions self-label for _jit")


@pytest.mark.parametrize("path", _exec_files(), ids=lambda p: p.name)
def test_stats_register_via_node_stats(path):
    """Round-15 rule: blocking operators register per-node stats through
    LocalExecutor._node_stats, never a bare ``self.stats.setdefault(`` —
    the helper captures the structural node path and CBO row estimate at
    registration, which is what lets clean-completion plan-history
    collection merge records across executors and the cluster.  Annotate
    '# stats-ok: <reason>' for a deliberate bypass."""
    s = _scan(path)
    assert not s.stats_hits, (
        f"{path.name}: bare self.stats.setdefault at "
        + ", ".join(f"line {ln} (in {fn})" for ln, fn in s.stats_hits)
        + " — register through _node_stats(node) so the plan-history feed "
          "sees the node, or annotate '# stats-ok: <reason>'")


PKG_DIR = EXEC_DIR.parent
COMPILE_MARKER = "# compile-ok"


def _pkg_files_outside_exec():
    """Every trino_tpu module OUTSIDE exec/ (exec/ has the stricter rule:
    jax.jit is banned there outright — only _jit may build one)."""
    files = sorted(p for p in PKG_DIR.rglob("*.py")
                   if EXEC_DIR not in p.parents
                   and "__pycache__" not in p.parts)
    assert files, PKG_DIR
    return files


def _untracked_jit_refs(path):
    """jax.jit attribute references outside exec/ missing a
    ``# compile-ok: <reason>`` annotation — each is an XLA compilation the
    round-17 compile observatory cannot see (no seen-signature detection,
    no compile span, no census record, no compile-aware stall verdict)."""
    src = path.read_text()
    lines = src.splitlines()
    hits = []
    for node in ast.walk(ast.parse(src)):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "jax" and node.attr in ("jit", "pjit"):
            if COMPILE_MARKER not in lines[node.lineno - 1]:
                hits.append(node.lineno)
    return hits


@pytest.mark.parametrize("path", _pkg_files_outside_exec(),
                         ids=lambda p: str(p.relative_to(PKG_DIR)))
def test_jit_outside_exec_is_annotated(path):
    """Round-17 rule: a ``jax.jit`` reference outside exec/ is an XLA
    compile the observatory at the ``_jit`` chokepoint never sees — the new
    loose np.asarray.  Route it through the tracked wrapper, or annotate
    ``# compile-ok: <reason>`` stating why it is exempt (module-level
    kernels dispatched inside exec's _jit steps, host-side generation)."""
    hits = _untracked_jit_refs(path)
    assert not hits, (
        f"{path.relative_to(PKG_DIR)}: untracked jax.jit reference at "
        f"line(s) {', '.join(map(str, hits))} — route through "
        "exec.local_executor._jit so the compile is observed (counted, "
        "span'd, census'd, compile-aware-stall-judged), or annotate "
        "'# compile-ok: <reason>'")


def _pallas_call_hits(path):
    """pallas_call(...) invocations missing an ``interpret=`` keyword —
    both attribute form (pl.pallas_call) and a direct-imported name."""
    src = path.read_text()
    hits = []
    for node in ast.walk(ast.parse(src)):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        named = (isinstance(f, ast.Attribute) and f.attr == "pallas_call") \
            or (isinstance(f, ast.Name) and f.id == "pallas_call")
        if named and not any(kw.arg == "interpret" for kw in node.keywords):
            hits.append(node.lineno)
    return hits


def _ops_files():
    files = sorted(OPS_DIR.glob("*.py"))
    assert files, OPS_DIR
    return files


@pytest.mark.parametrize("path", _ops_files(), ids=lambda p: p.name)
def test_pallas_call_plumbs_interpret(path):
    """Round-13 rule: every pl.pallas_call in trino_tpu/ops/ must plumb an
    ``interpret=`` parameter.  A hard-coded device-only kernel can never run
    on the CPU mesh, which silently exempts it from the tier-1 parity tests —
    the interpret knob is what makes a Mosaic kernel testable off-device
    (pallas_kernels.pallas_interpret() is the standard source).  Kernel
    DISPATCH accounting needs no extra rule: ops kernels only run inside
    exec's _jit-wrapped step functions, which the exec-side lints above
    already police, so counters/faults/in-flight coverage is automatic."""
    hits = _pallas_call_hits(path)
    assert not hits, (
        f"{path.name}: pl.pallas_call without interpret= at line(s) "
        + ", ".join(map(str, hits))
        + " — plumb interpret (default pallas_kernels.pallas_interpret()) so "
          "the kernel body runs under the CPU-mesh parity tests")


SQL_DIR = PKG_DIR / "sql"
ADAPTIVE_MARKER = "# adaptive-ok"


def _adaptive_read_hits(path):
    """``.plan_history`` / ``.compile_log`` attribute reads in exec/ or sql/
    missing a ``# adaptive-ok: <reason>`` annotation.  Round-19 rule: the
    AdaptiveAdvisor (execution/adaptive.py) is THE chokepoint where recorded
    history and compile costs turn into plan decisions — an executor or
    planner module reading the stores directly grows a second, unaccounted
    decision path (no win-vs-price gate, no probation/demotion, no
    counters/EXPLAIN/flight visibility)."""
    src = path.read_text()
    lines = src.splitlines()
    hits = []
    for node in ast.walk(ast.parse(src)):
        if isinstance(node, ast.Attribute) \
                and node.attr in ("plan_history", "compile_log"):
            if ADAPTIVE_MARKER not in lines[node.lineno - 1]:
                hits.append((node.lineno, node.attr))
    return hits


def _decision_input_files():
    files = sorted(list(EXEC_DIR.glob("*.py")) + list(SQL_DIR.rglob("*.py")))
    assert files, (EXEC_DIR, SQL_DIR)
    return files


@pytest.mark.parametrize("path", _decision_input_files(),
                         ids=lambda p: str(p.relative_to(PKG_DIR)))
def test_history_reads_route_through_advisor(path):
    """Round-19 rule: nothing under trino_tpu/exec/ or trino_tpu/sql/ reads
    ``plan_history``/``compile_log`` directly — decision logic lives in
    execution/adaptive.py (the engine consults it at admission; the planner
    consumes only the emitted correction facts).  Annotate
    '# adaptive-ok: <reason>' for a deliberate, non-decision read."""
    hits = _adaptive_read_hits(path)
    assert not hits, (
        f"{path.relative_to(PKG_DIR)}: direct decision-input read at "
        + ", ".join(f"line {ln} (.{attr})" for ln, attr in hits)
        + " — route the decision through execution.adaptive.AdaptiveAdvisor,"
          " or annotate '# adaptive-ok: <reason>'")


PULL_MARKER = "# pull-ok"

# The FROZEN set of device->host pull sites in exec/distributed.py (round
# 20).  The device-resident exchange's whole point is that the warm path
# pulls at exactly these sites — the distributed-budget suite pins the warm
# subset dynamically, and this rule pins the SITE NAMESPACE statically: a
# new `_host(..., site="dist...")` call is a new pull site until proven
# otherwise, the same failure mode the round-6 loose-np.asarray rule
# closed for the local executor.  The round-20 skew derivation consumes
# ints already pulled at these existing sites and must never need a new
# one.  Adding a site here is a deliberate act that should come with a
# budget-suite re-derivation (scripts/query_counters.py --distributed).
DIST_PULL_SITES = {
    "dist.build.dupcheck",
    "dist.hostfed.pull",
    "dist.shards.concat",
    "dist.shards.pull",
    "dist.join.buildsize",
    "dist.join.build_exchange",
    "dist.join.overflow",
    "dist.sort.sample",
    "dist.exchange.collect",
    "dist.exchange.route",
    "dist.exchange.flags",
    "dist.topn.states",
    "dist.agg.overflow",
    "dist.agg.compact",
    "dist.agg.groups",
    "dist.agg.states",
    "dist.stream.collect",
    "dist.stream.route",
    "dist.stream.flags",
}


def _dist_pull_hits(path, allowed=None):
    """``_host(...)`` calls in exec/distributed.py whose ``site=`` literal is
    NOT in the frozen pull-site set and whose line lacks a
    ``# pull-ok: <reason>`` annotation.  A site= that is not a string
    literal cannot be verified statically and needs the marker too."""
    allowed = DIST_PULL_SITES if allowed is None else allowed
    src = path.read_text()
    lines = src.splitlines()
    hits = []
    for node in ast.walk(ast.parse(src)):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "_host"):
            continue
        site = None
        for kw in node.keywords:
            if kw.arg == "site":
                if isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    site = kw.value.value
                break
        if site is not None and site in allowed:
            continue
        if PULL_MARKER in lines[node.lineno - 1]:
            continue
        hits.append((node.lineno, site))
    return hits


def test_distributed_pull_sites_frozen():
    """Round-20 rule: the warm distributed path's host-pull bill is a
    handful of known sites (one batched flags pull per exchange run, the
    occupancy-sized agg pulls, ...).  Any NEW ``_host`` call in
    exec/distributed.py must either reuse a frozen site name or carry
    ``# pull-ok: <reason>`` — the per-shard skew derivation in particular
    is required to consume ints already pulled at existing sites, never to
    add a pull of its own."""
    path = EXEC_DIR / "distributed.py"
    hits = _dist_pull_hits(path)
    assert not hits, (
        f"distributed.py: _host call outside the frozen pull-site set at "
        + ", ".join(f"line {ln} (site={site!r})" for ln, site in hits)
        + " — reuse an existing dist.* site, or annotate "
          "'# pull-ok: <reason>' and re-derive the distributed budget "
          "ceilings (scripts/query_counters.py --distributed --sites)")


def test_pull_site_lint_catches_violations(tmp_path):
    """The pull-site rule must actually flag what it claims to."""
    bad = tmp_path / "dist.py"
    bad.write_text(
        "def f(x, _host, s):\n"
        "    a = _host([x], site='dist.exchange.flags')\n"   # frozen -> ok
        "    b = _host([x], site='dist.skew.extra')\n"       # line 3: flagged
        "    c = _host([x], site='dist.skew.extra')  # pull-ok: test\n"
        "    d = _host([x], site=s)\n"                       # line 5: flagged
        "    e = _host([x], site=s)  # pull-ok: test\n"
        "    return a, b, c, d, e\n")
    assert [(ln, site) for ln, site in _dist_pull_hits(bad)] == \
        [(3, "dist.skew.extra"), (5, None)]


def test_lint_catches_violations(tmp_path):
    """The lint must actually flag what it claims to (guards against the
    visitor silently matching nothing after a refactor)."""
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax, numpy as np\n"
        "from functools import partial\n"
        "def f(x):\n"
        "    g = jax.jit(lambda a: a)\n"               # line 4: flagged
        "    g2 = partial(jax.jit, static_argnames=('n',))\n"  # 5: flagged
        "    return np.asarray(x)\n"                   # line 6: flagged
        "def _jit(fn):\n"
        "    return jax.jit(fn)\n"
        "def _host(arrays):\n"
        "    return [np.asarray(a) for a in arrays]\n"
        "ok = np.asarray([1, 2])  # host-ok: literal\n"
        "def h(x):\n"
        "    y = jax.device_put(x)\n"                  # line 13: flagged
        "    z = jax.device_put(x)  # device-ok: test\n"
        "    w = jax.device_get(x)\n"                  # line 15: flagged
        "    w2 = jax.device_get(x)  # host-ok: test\n"
        "    return y, z, w, w2\n"
        "def _page_to_device(p):\n"
        "    return jax.device_put(p)\n"
        "def g(x, step):\n"
        "    a = _host([x])\n"                  # line 21: missing site
        "    b = _host([x], site='g.pull')\n"        # tagged -> ok
        "    c = _host([x])  # site-ok: test\n"      # marked -> ok
        "    d = _jit(lambda v: v)\n"            # line 24: anonymous
        "    e = _jit(step)\n"                       # named -> self-labels
        "    f2 = _jit(lambda v: v, site='g.step')\n"  # tagged -> ok
        "    return a, b, c, d, e, f2\n"
        "class X:\n"
        "    def reg(self, node):\n"
        "        s = self.stats.setdefault(id(node), {})\n"  # line 30: flagged
        "        s2 = self.stats.setdefault(id(node), {})  # stats-ok: test\n"
        "        return s, s2\n"
        "    def _node_stats(self, node):\n"
        "        return self.stats.setdefault(id(node), {})\n")  # chokepoint
    s = _scan(bad)
    assert [ln for ln, _ in s.jit_hits] == [4, 5]
    assert [ln for ln, _ in s.asarray_hits] == [6]
    assert [ln for ln, _ in s.device_put_hits] == [13]
    assert [ln for ln, _ in s.device_get_hits] == [15]
    assert [(ln, callee) for ln, _, callee in s.site_hits] == \
        [(21, "_host"), (24, "_jit")]
    assert [ln for ln, _ in s.stats_hits] == [30]
    # the round-17 outside-exec rule flags un-annotated jax.jit refs and
    # accepts the compile-ok marker
    jitmod = tmp_path / "jitmod.py"
    jitmod.write_text(
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnums=(0,))\n"       # line 3: flagged
        "def f(n, x):\n"
        "    return x\n"
        "@partial(jax.jit, static_argnums=(0,))  # compile-ok: test\n"
        "def g(n, x):\n"
        "    return x\n"
        "h = jax.jit(lambda x: x)\n")                    # line 9: flagged
    assert _untracked_jit_refs(jitmod) == [3, 9]
    kern = tmp_path / "kern.py"
    kern.write_text(
        "from jax.experimental import pallas as pl\n"
        "from jax.experimental.pallas import pallas_call\n"
        "def f(x):\n"
        "    return pl.pallas_call(lambda r, o: None, out_shape=x)(x)\n"  # 4: flagged
        "def g(x, interp):\n"
        "    return pl.pallas_call(lambda r, o: None, out_shape=x,\n"
        "                          interpret=interp)(x)\n"
        "def h(x):\n"
        "    return pallas_call(lambda r, o: None, out_shape=x)(x)\n"  # 9: flagged
        "def k(x, interp):\n"
        "    return pallas_call(lambda r, o: None, out_shape=x,\n"
        "                       interpret=interp)(x)\n")
    assert _pallas_call_hits(kern) == [4, 9]
    # the round-19 rule flags un-annotated plan_history/compile_log reads
    # and accepts the adaptive-ok marker
    adap = tmp_path / "adap.py"
    adap.write_text(
        "def f(engine):\n"
        "    h = engine.plan_history\n"                  # line 2: flagged
        "    c = engine.compile_log.snapshot()\n"        # line 3: flagged
        "    h2 = engine.plan_history  # adaptive-ok: test\n"
        "    return h, c, h2\n")
    assert _adaptive_read_hits(adap) == \
        [(2, "plan_history"), (3, "compile_log")]
