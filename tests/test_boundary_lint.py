"""Static device-boundary lint over ``trino_tpu/exec/*.py``.

CLAUDE.md's rule — executor code MUST go through ``_jit`` (not bare
``jax.jit``) and ``_host`` (never a loose ``np.asarray`` of device values) or
the dispatch/transfer is invisible to the per-query budget counters — was a
doc note until round 6.  This test makes it an enforced invariant:

- ``jax.jit(`` may appear only inside the ``_jit`` helper itself (the one
  place the accounting wrapper is built).
- ``np.asarray(`` may appear only
  (a) inside a small set of allowlisted HOST-SIDE helpers (below, each with
      the reason it is exempt), or
  (b) on a line annotated ``# host-ok[: reason]`` asserting the value is
      already host-resident (python lists, dictionary values, arrays
      previously pulled through ``_host``/``jax.device_get``).

A new un-annotated np.asarray is treated as an unaccounted device pull until
proven otherwise — the failure mode this PR's sweep fixed dozens of times
over (per-column pulls in exchange/serialize/merge paths that never showed on
the budget).  If your np.asarray really is host-side, say so with the marker;
if it isn't, batch it through ``_host``.
"""

import ast
import pathlib

import pytest

EXEC_DIR = pathlib.Path(__file__).resolve().parent.parent / "trino_tpu" / "exec"

# functions whose BODY may use np.asarray freely, with why:
ASARRAY_ALLOWED_FUNCS = {
    "_host",              # the accounting chokepoint itself
    "_host_page",         # batched page pull built on _host
    "_page_to_device",    # host->device direction (no pull)
    "_finalize_aggs",     # host finalize over accumulators its callers pulled
    "_combine_limbs_vec",  # host two-limb recombine (input already pulled)
}

MARKER = "# host-ok"


def _exec_files():
    files = sorted(EXEC_DIR.glob("*.py"))
    assert files, EXEC_DIR
    return files


class _Scan(ast.NodeVisitor):
    def __init__(self, lines):
        self.lines = lines
        self.func_stack = []
        self.jit_hits = []      # (lineno, enclosing function)
        self.asarray_hits = []  # (lineno, enclosing function)

    def visit_FunctionDef(self, node):
        self.func_stack.append(node.name)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            where = self.func_stack[-1] if self.func_stack else "<module>"
            if f.value.id == "jax" and f.attr == "jit":
                if "_jit" not in self.func_stack:
                    self.jit_hits.append((node.lineno, where))
            if f.value.id == "np" and f.attr == "asarray":
                if not (set(self.func_stack) & ASARRAY_ALLOWED_FUNCS) \
                        and MARKER not in self.lines[node.lineno - 1]:
                    self.asarray_hits.append((node.lineno, where))
        self.generic_visit(node)


def _scan(path):
    src = path.read_text()
    s = _Scan(src.splitlines())
    s.visit(ast.parse(src))
    return s


@pytest.mark.parametrize("path", _exec_files(), ids=lambda p: p.name)
def test_no_bare_jax_jit(path):
    s = _scan(path)
    assert not s.jit_hits, (
        f"{path.name}: bare jax.jit at "
        + ", ".join(f"line {ln} (in {fn})" for ln, fn in s.jit_hits)
        + " — use exec.local_executor._jit so the dispatch is counted "
          "against the query budget")


@pytest.mark.parametrize("path", _exec_files(), ids=lambda p: p.name)
def test_no_loose_np_asarray(path):
    s = _scan(path)
    assert not s.asarray_hits, (
        f"{path.name}: loose np.asarray at "
        + ", ".join(f"line {ln} (in {fn})" for ln, fn in s.asarray_hits)
        + " — a device value must pull through _host (batched, counted); "
          "a host value needs a '# host-ok: <reason>' annotation")


def test_lint_catches_violations(tmp_path):
    """The lint must actually flag what it claims to (guards against the
    visitor silently matching nothing after a refactor)."""
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax, numpy as np\n"
        "def f(x):\n"
        "    g = jax.jit(lambda a: a)\n"
        "    return np.asarray(x)\n"
        "def _jit(fn):\n"
        "    return jax.jit(fn)\n"
        "def _host(arrays):\n"
        "    return [np.asarray(a) for a in arrays]\n"
        "ok = np.asarray([1, 2])  # host-ok: literal\n")
    s = _scan(bad)
    assert [ln for ln, _ in s.jit_hits] == [3]
    assert [ln for ln, _ in s.asarray_hits] == [4]
