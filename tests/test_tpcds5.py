"""TPC-DS query breadth, round 5 batch 3: revenue-ratio reports, window
averages over case pivots, quarter-over-quarter growth, multi-channel
EXISTS demographics, ranked return ratios, city-pair customer reports.
Reference corpus: testing/trino-benchmark-queries/ + plugin/trino-tpcds."""

import numpy as np
import pandas as pd
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpcds import TpcdsConnector

from test_tpcds2 import _table
from test_tpcds3 import _check

SF = 0.01


@pytest.fixture(scope="module")
def eng():
    e = Engine()
    e.register_catalog("tpcds", TpcdsConnector(sf=SF, split_rows=1 << 14))
    return e, e.create_session("tpcds")


@pytest.fixture(scope="module")
def host(eng):
    e, _ = eng
    conn = e.catalogs["tpcds"]
    return {
        "store_sales": _table(conn, "store_sales", [
            "ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_customer_sk",
            "ss_hdemo_sk", "ss_addr_sk", "ss_ticket_number", "ss_quantity",
            "ss_ext_sales_price", "ss_sales_price", "ss_ext_list_price",
            "ss_coupon_amt", "ss_net_profit"]),
        "web_sales": _table(conn, "web_sales", [
            "ws_sold_date_sk", "ws_item_sk", "ws_bill_customer_sk",
            "ws_ext_sales_price", "ws_net_paid"]),
        "catalog_sales": _table(conn, "catalog_sales", [
            "cs_sold_date_sk", "cs_item_sk", "cs_bill_customer_sk",
            "cs_ext_sales_price"]),
        "store_returns": _table(conn, "store_returns", [
            "sr_returned_date_sk", "sr_item_sk", "sr_return_quantity",
            "sr_return_amt", "sr_ticket_number", "sr_customer_sk"]),
        "item": _table(conn, "item", [
            "i_item_sk", "i_item_id", "i_item_desc", "i_category", "i_class",
            "i_current_price", "i_manufact_id", "i_brand"]),
        "date_dim": _table(conn, "date_dim", [
            "d_date_sk", "d_year", "d_moy", "d_qoy", "d_month_seq",
            "d_week_seq"]),
        "customer": _table(conn, "customer", [
            "c_customer_sk", "c_customer_id", "c_current_addr_sk",
            "c_current_hdemo_sk", "c_first_name", "c_last_name"]),
        "customer_address": _table(conn, "customer_address", [
            "ca_address_sk", "ca_city", "ca_county"]),
        "household_demographics": _table(conn, "household_demographics", [
            "hd_demo_sk", "hd_dep_count", "hd_vehicle_count"]),
    }


def test_q12_category_revenue_ratio(eng, host):
    """Q12 shape: per-item revenue share of its class via a window sum."""
    e, s = eng
    got = e.execute_sql("""
        select i_item_id, i_class,
          sum(ws_ext_sales_price) itemrevenue,
          sum(ws_ext_sales_price) * 100.0 /
            sum(sum(ws_ext_sales_price)) over (partition by i_class) ratio
        from web_sales, item, date_dim
        where ws_item_sk = i_item_sk and i_category = 'Books'
          and ws_sold_date_sk = d_date_sk and d_year = 2000
        group by i_item_id, i_class
        order by i_class, i_item_id limit 40""", s).to_pandas()
    ws, it, dd = host["web_sales"], host["item"], host["date_dim"]
    j = ws.merge(it[it.i_category == "Books"], left_on="ws_item_sk",
                 right_on="i_item_sk") \
        .merge(dd[dd.d_year == 2000], left_on="ws_sold_date_sk",
               right_on="d_date_sk")
    g = j.groupby(["i_item_id", "i_class"], as_index=False) \
        .ws_ext_sales_price.sum() \
        .rename(columns={"ws_ext_sales_price": "itemrevenue"})
    g["ratio"] = g.itemrevenue * 100.0 / \
        g.groupby("i_class").itemrevenue.transform("sum")
    ref = g.sort_values(["i_class", "i_item_id"]).head(40) \
        .reset_index(drop=True)[["i_item_id", "i_class", "itemrevenue",
                                 "ratio"]]
    _check(got, ref, {"itemrevenue", "ratio"})


def test_q17_sales_returns_stats(eng, host):
    """Q17 shape: quantity statistics joining sales to their returns."""
    e, s = eng
    got = e.execute_sql("""
        select i_item_id, count(ss_quantity) cnt, avg(ss_quantity) a,
               stddev_samp(ss_quantity) sd
        from store_sales, store_returns, item
        where ss_ticket_number = sr_ticket_number
          and ss_item_sk = sr_item_sk and ss_item_sk = i_item_sk
        group by i_item_id order by i_item_id limit 25""", s).to_pandas()
    ss, sr, it = host["store_sales"], host["store_returns"], host["item"]
    j = ss.merge(sr, left_on=["ss_ticket_number", "ss_item_sk"],
                 right_on=["sr_ticket_number", "sr_item_sk"]) \
        .merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    ref = j.groupby("i_item_id", as_index=False).agg(
        cnt=("ss_quantity", "count"), a=("ss_quantity", "mean"),
        sd=("ss_quantity", lambda x: x.std(ddof=1)))
    ref["sd"] = ref["sd"].fillna(0)
    ref = ref.sort_values("i_item_id").head(25).reset_index(drop=True)
    got["sd"] = got["sd"].fillna(0)
    _check(got, ref, {"a", "sd"})


def test_q31_county_quarter_growth(eng, host):
    """Q31 shape: store-sales by county and quarter via CTE self-joins."""
    e, s = eng
    got = e.execute_sql("""
        with ss as (
          select ca_county, d_qoy, sum(ss_ext_sales_price) sales
          from store_sales, date_dim, customer_address
          where ss_sold_date_sk = d_date_sk and ss_addr_sk = ca_address_sk
            and d_year = 2000 group by ca_county, d_qoy)
        select s1.ca_county, s1.sales q1_sales, s2.sales q2_sales
        from ss s1, ss s2
        where s1.ca_county = s2.ca_county and s1.d_qoy = 1 and s2.d_qoy = 2
          and s2.sales > s1.sales
        order by s1.ca_county limit 25""", s).to_pandas()
    ss, dd, ca = (host["store_sales"], host["date_dim"],
                  host["customer_address"])
    j = ss.merge(dd[dd.d_year == 2000], left_on="ss_sold_date_sk",
                 right_on="d_date_sk") \
        .merge(ca, left_on="ss_addr_sk", right_on="ca_address_sk")
    g = j.groupby(["ca_county", "d_qoy"], as_index=False) \
        .ss_ext_sales_price.sum() \
        .rename(columns={"ss_ext_sales_price": "sales"})
    q1 = g[g.d_qoy == 1][["ca_county", "sales"]] \
        .rename(columns={"sales": "q1_sales"})
    q2 = g[g.d_qoy == 2][["ca_county", "sales"]] \
        .rename(columns={"sales": "q2_sales"})
    ref = q1.merge(q2, on="ca_county")
    ref = ref[ref.q2_sales > ref.q1_sales].sort_values("ca_county") \
        .head(25).reset_index(drop=True)
    _check(got, ref, {"q1_sales", "q2_sales"})


def test_q35_multi_channel_exists(eng, host):
    """Q35 shape: customers active in store AND (web OR catalog)."""
    e, s = eng
    got = e.execute_sql("""
        select count(*) n from customer c
        where exists (select 1 from store_sales
                      where ss_customer_sk = c.c_customer_sk)
          and (exists (select 1 from web_sales
                       where ws_bill_customer_sk = c.c_customer_sk)
            or exists (select 1 from catalog_sales
                       where cs_bill_customer_sk = c.c_customer_sk))""",
        s).to_pandas()
    c, ss, ws, cs = (host["customer"], host["store_sales"],
                     host["web_sales"], host["catalog_sales"])
    in_ss = c.c_customer_sk.isin(set(ss.ss_customer_sk))
    in_ws = c.c_customer_sk.isin(set(ws.ws_bill_customer_sk))
    in_cs = c.c_customer_sk.isin(set(cs.cs_bill_customer_sk))
    assert got["n"].iloc[0] == int((in_ss & (in_ws | in_cs)).sum())


def test_q49_ranked_return_ratios(eng, host):
    """Q49 shape: items ranked by return-quantity ratio."""
    e, s = eng
    got = e.execute_sql("""
        select item_sk, rnk from (
          select ss_item_sk item_sk,
            row_number() over (order by sum(sr_return_quantity) * 1.0 /
                               sum(ss_quantity), ss_item_sk) rnk
          from store_sales, store_returns
          where ss_ticket_number = sr_ticket_number
            and ss_item_sk = sr_item_sk
          group by ss_item_sk)
        where rnk <= 10 order by rnk""", s).to_pandas()
    ss, sr = host["store_sales"], host["store_returns"]
    j = ss.merge(sr, left_on=["ss_ticket_number", "ss_item_sk"],
                 right_on=["sr_ticket_number", "sr_item_sk"])
    g = j.groupby("ss_item_sk", as_index=False).agg(
        rq=("sr_return_quantity", "sum"), sq=("ss_quantity", "sum"))
    g["ratio"] = g.rq * 1.0 / g.sq
    g = g.sort_values(["ratio", "ss_item_sk"]).reset_index(drop=True)
    ref = pd.DataFrame({"item_sk": g.ss_item_sk.head(10).to_numpy(),
                        "rnk": np.arange(1, min(len(g), 10) + 1)})
    _check(got, ref, set())


def test_q53_manufact_window_avg(eng, host):
    """Q53 shape: quarterly manufacturer sales vs their yearly average
    (window avg over the aggregate)."""
    e, s = eng
    got = e.execute_sql("""
        select i_manufact_id, d_qoy, sum_sales, avg_quarterly
        from (select i_manufact_id, d_qoy,
                sum(ss_ext_sales_price) sum_sales,
                avg(sum(ss_ext_sales_price))
                  over (partition by i_manufact_id) avg_quarterly
              from store_sales, item, date_dim
              where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
                and d_year = 2000 and i_manufact_id between 1 and 20
              group by i_manufact_id, d_qoy)
        order by i_manufact_id, d_qoy limit 40""", s).to_pandas()
    ss, it, dd = host["store_sales"], host["item"], host["date_dim"]
    j = ss.merge(it[(it.i_manufact_id >= 1) & (it.i_manufact_id <= 20)],
                 left_on="ss_item_sk", right_on="i_item_sk") \
        .merge(dd[dd.d_year == 2000], left_on="ss_sold_date_sk",
               right_on="d_date_sk")
    g = j.groupby(["i_manufact_id", "d_qoy"], as_index=False) \
        .ss_ext_sales_price.sum() \
        .rename(columns={"ss_ext_sales_price": "sum_sales"})
    # engine decimal avg rounds HALF_UP to scale 2
    g["avg_quarterly"] = np.floor(g.groupby("i_manufact_id")
                                  .sum_sales.transform("mean") * 100
                                  + 0.5) / 100
    ref = g.sort_values(["i_manufact_id", "d_qoy"]).head(40) \
        .reset_index(drop=True)
    _check(got, ref, {"sum_sales", "avg_quarterly"})


def test_q68_city_pair_tickets(eng, host):
    """Q68 shape: per-ticket extended summaries joined back to customers."""
    e, s = eng
    got = e.execute_sql("""
        select c_last_name, c_first_name, ca_city, bought_city,
               ss_ticket_number, extended_price
        from (select ss_ticket_number, ss_customer_sk, ca_city bought_city,
                sum(ss_ext_sales_price) extended_price
              from store_sales, date_dim, customer_address,
                   household_demographics
              where ss_sold_date_sk = d_date_sk
                and ss_addr_sk = ca_address_sk
                and ss_hdemo_sk = hd_demo_sk
                and hd_dep_count = 5 and d_year = 2000
              group by ss_ticket_number, ss_customer_sk, ca_city) dn,
             customer, customer_address current_addr
        where ss_customer_sk = c_customer_sk
          and c_current_addr_sk = current_addr.ca_address_sk
        order by ss_ticket_number, extended_price, c_last_name
        limit 20""", s).to_pandas()
    ss, dd, ca, hd, c = (host["store_sales"], host["date_dim"],
                         host["customer_address"],
                         host["household_demographics"], host["customer"])
    j = ss.merge(dd[dd.d_year == 2000], left_on="ss_sold_date_sk",
                 right_on="d_date_sk") \
        .merge(ca, left_on="ss_addr_sk", right_on="ca_address_sk") \
        .merge(hd[hd.hd_dep_count == 5], left_on="ss_hdemo_sk",
               right_on="hd_demo_sk")
    dn = j.groupby(["ss_ticket_number", "ss_customer_sk", "ca_city"],
                   as_index=False).ss_ext_sales_price.sum() \
        .rename(columns={"ca_city": "bought_city",
                         "ss_ext_sales_price": "extended_price"})
    m = dn.merge(c, left_on="ss_customer_sk", right_on="c_customer_sk") \
        .merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
    ref = m.sort_values(["ss_ticket_number", "extended_price",
                         "c_last_name"]).head(20).reset_index(drop=True)[
        ["c_last_name", "c_first_name", "ca_city", "bought_city",
         "ss_ticket_number", "extended_price"]]
    _check(got, ref, {"extended_price"})


def test_q20_catalog_revenue_ratio(eng, host):
    """Q20 shape: catalog revenue share within class (window over agg)."""
    e, s = eng
    got = e.execute_sql("""
        select i_item_id,
          sum(cs_ext_sales_price) rev,
          sum(sum(cs_ext_sales_price)) over (partition by i_class) class_rev
        from catalog_sales, item, date_dim
        where cs_item_sk = i_item_sk and i_category = 'Music'
          and cs_sold_date_sk = d_date_sk and d_year = 2001
        group by i_item_id, i_class
        order by i_item_id limit 30""", s).to_pandas()
    cs, it, dd = host["catalog_sales"], host["item"], host["date_dim"]
    j = cs.merge(it[it.i_category == "Music"], left_on="cs_item_sk",
                 right_on="i_item_sk") \
        .merge(dd[dd.d_year == 2001], left_on="cs_sold_date_sk",
               right_on="d_date_sk")
    g = j.groupby(["i_item_id", "i_class"], as_index=False) \
        .cs_ext_sales_price.sum().rename(
            columns={"cs_ext_sales_price": "rev"})
    g["class_rev"] = g.groupby("i_class").rev.transform("sum")
    ref = g.sort_values("i_item_id").head(30).reset_index(drop=True)[
        ["i_item_id", "rev", "class_rev"]]
    _check(got, ref, {"rev", "class_rev"})
