"""MERGE INTO statement (reference: sql/tree/Merge.java planned through
MergeWriterOperator's RowChangeOperations; test model: the MERGE cases of
testing/trino-testing/.../AbstractTestEngineOnlyQueries)."""

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.memory import MemoryConnector


@pytest.fixture()
def meng():
    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table tgt (id bigint, name varchar, qty bigint)", s)
    e.execute_sql(
        "insert into tgt values (1, 'a', 10), (2, 'b', 20), (3, 'c', 30)", s)
    e.execute_sql("create table src (id bigint, name varchar, qty bigint)", s)
    e.execute_sql(
        "insert into src values (2, 'B', 200), (3, 'c', -1), (4, 'd', 40)", s)
    return e, s


def test_merge_update_delete_insert(meng):
    e, s = meng
    e.execute_sql("""
        merge into tgt t using src s on t.id = s.id
        when matched and s.qty < 0 then delete
        when matched then update set name = s.name, qty = t.qty + s.qty
        when not matched then insert (id, name, qty) values (s.id, s.name, s.qty)
    """, s)
    r = e.execute_sql("select id, name, qty from tgt order by id", s).to_pandas()
    assert r.values.tolist() == [[1, "a", 10], [2, "B", 220], [4, "d", 40]]


def test_merge_clause_priority_first_match_wins(meng):
    e, s = meng
    # both clauses' conditions hold for id=2; the FIRST must win
    e.execute_sql("""
        merge into tgt t using src s on t.id = s.id
        when matched and s.qty > 100 then update set qty = 111
        when matched and s.qty > 0 then update set qty = 222
    """, s)
    r = e.execute_sql("select qty from tgt where id = 2", s).to_pandas()
    assert r.iloc[0, 0] == 111


def test_merge_duplicate_source_match_errors(meng):
    e, s = meng
    e.execute_sql("insert into src values (2, 'x', 1)", s)
    with pytest.raises(ValueError, match="more than one source row"):
        e.execute_sql(
            "merge into tgt using src on tgt.id = src.id "
            "when matched then delete", s)


def test_merge_subquery_source_and_missing_insert_columns(meng):
    e, s = meng
    e.execute_sql("""
        merge into tgt using (select id + 100 as sid, qty from src) s
          on tgt.id = s.sid
        when not matched and s.qty > 30 then insert (id, qty)
          values (s.sid, s.qty)
    """, s)
    r = e.execute_sql("select id, name, qty from tgt order by id", s).to_pandas()
    assert r["id"].tolist() == [1, 2, 3, 102, 104]
    # unspecified insert columns are NULL
    assert r["name"].isna().tolist() == [False, False, False, True, True]
    assert r["qty"].tolist() == [10, 20, 30, 200, 40]


def test_merge_null_keys_never_match(meng):
    e, s = meng
    e.execute_sql("insert into tgt values (null, 'n', 0)", s)
    e.execute_sql("insert into src values (null, 'N', 99)", s)
    e.execute_sql("""
        merge into tgt t using src s on t.id = s.id
        when matched then update set qty = 1
        when not matched then insert (id, name) values (s.id, s.name)
    """, s)
    r = e.execute_sql("select name, qty from tgt order by qty, name", s).to_pandas()
    # NULL target keeps qty 0; NULL source row INSERTS (not matched)
    assert ("n", 0) in set(map(tuple, r.values.tolist()))
    assert ("N", None) in set((a, None if b != b else b)
                              for a, b in r.values.tolist())


def test_merge_multiple_when_not_matched(meng):
    e, s = meng
    e.execute_sql("""
        merge into tgt using src on tgt.id = src.id
        when not matched and src.qty > 100 then insert (id, qty) values (src.id, 0)
        when not matched then insert (id, qty) values (src.id, src.qty)
    """, s)
    # only id=4 is unmatched; qty 40 <= 100 -> second clause
    r = e.execute_sql("select qty from tgt where id = 4", s).to_pandas()
    assert r.iloc[0, 0] == 40


def test_merge_cross_scale_decimal_keys_match(meng):
    e, s = meng
    e.execute_sql("create table dt (k decimal(10,2), v bigint)", s)
    e.execute_sql("insert into dt values (1.00, 1)", s)
    e.execute_sql("create table ds (k decimal(4,1), v bigint)", s)
    e.execute_sql("insert into ds values (1.0, 99)", s)
    # raw storage differs (100 vs 10); ON keys compare post-decode
    e.execute_sql(
        "merge into dt using ds on dt.k = ds.k "
        "when matched then update set v = ds.v", s)
    assert e.execute_sql("select v from dt", s).to_pandas().iloc[0, 0] == 99


def test_merge_set_rejects_source_qualifier(meng):
    e, s = meng
    with pytest.raises(ValueError, match="not the target alias"):
        e.execute_sql(
            "merge into tgt t using src s on t.id = s.id "
            "when matched then update set s.qty = 1", s)


def test_merge_insert_arity_error_precedes_mutation(meng):
    e, s = meng
    with pytest.raises(ValueError, match="columns but"):
        e.execute_sql("""
            merge into tgt t using src s on t.id = s.id
            when matched then update set qty = 0
            when not matched then insert (id) values (s.id, s.qty)
        """, s)
    # the matched update must NOT have been applied (no partial MERGE)
    r = e.execute_sql("select qty from tgt order by id", s).to_pandas()
    assert r["qty"].tolist() == [10, 20, 30]


def test_merge_int64_keys_past_2_53(meng):
    e, s = meng
    big = (1 << 53) + 1
    e.execute_sql(f"insert into tgt values ({1 << 53}, 'p', 1)", s)
    e.execute_sql(f"insert into src values ({big}, 'q', 2)", s)
    # 2^53 and 2^53+1 are distinct keys (float flattening would collide them)
    e.execute_sql(
        "merge into tgt t using src s on t.id = s.id "
        "when matched then update set qty = 999", s)
    r = e.execute_sql(f"select qty from tgt where id = {1 << 53}", s).to_pandas()
    assert r["qty"].tolist() == [1]
