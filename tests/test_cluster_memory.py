"""Cluster-wide memory management (reference:
memory/ClusterMemoryManager.java:92): workers report their node pool through
announces/heartbeats, the coordinator aggregates a cluster view, and a
nearly-full pool refuses task admission (429) so the coordinator re-offers
elsewhere instead of OOMing the node."""

import json
import pickle
import time

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.server.cluster import ClusterCoordinator, WorkerServer, _http

CATALOGS = {"tpch": {"connector": "tpch", "sf": 0.01, "split_rows": 1 << 11}}


def test_worker_reports_pool_and_refuses_when_full(tmp_path):
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 11))
    w = WorkerServer(CATALOGS, str(tmp_path / "spool"))
    url = w.start()
    try:
        info = json.loads(_http(f"{url}/v1/info"))
        assert info["mem_max"] > 0 and info["mem_reserved"] >= 0

        from trino_tpu.sql.frontend import compile_sql

        plan = compile_sql("select count(*) from lineitem", e,
                           e.create_session("tpch"))
        _http(f"{url}/v1/fragment",
              pickle.dumps({"fragment_id": "f1", "plan": plan}))
        # fill the pool past the admission threshold: new tasks refuse 429
        w.memory_pool.try_reserve(
            int(w.memory_pool.max_bytes * 0.95), "test-fill")
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as exc:
            _http(f"{url}/v1/task",
                  pickle.dumps({"task_id": "t1", "fragment_id": "f1",
                                "kind": "fragment",
                                "exchange_dir": str(tmp_path / "x")}))
        assert exc.value.code == 429
        w.memory_pool.free(int(w.memory_pool.max_bytes * 0.95), "test-fill")
        # with the pool freed the same task admits and completes
        _http(f"{url}/v1/task",
              pickle.dumps({"task_id": "t1", "fragment_id": "f1",
                            "kind": "fragment",
                            "exchange_dir": str(tmp_path / "x")}))
        deadline = time.time() + 60
        while time.time() < deadline:
            st = json.loads(_http(f"{url}/v1/task/t1"))
            if st["state"] == "done":
                break
            assert st["state"] != "failed", st
            time.sleep(0.1)
        else:
            raise AssertionError("task did not finish")
    finally:
        w.stop()


def test_coordinator_aggregates_cluster_memory(tmp_path):
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 11))
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.2)
    url = coord.start()
    w = None
    try:
        w = WorkerServer(CATALOGS, str(tmp_path / "spool"),
                         coordinator_url=url, node_id="wmem",
                         announce_interval=0.2)
        w.start()
        coord.wait_for_workers(1, timeout=30)
        w.memory_pool.try_reserve(12345, "test")
        deadline = time.time() + 10
        while time.time() < deadline:
            mem = coord.cluster_memory()
            byid = {x["node_id"]: x for x in mem["workers"]}
            if byid.get("wmem", {}).get("mem_reserved", 0) >= 12345:
                break
            time.sleep(0.2)
        else:
            raise AssertionError(f"memory never aggregated: {mem}")
        assert mem["total_max"] > 0
        assert mem["total_reserved"] >= 12345
        # the HTTP surface serves the same view
        via_http = json.loads(_http(f"{url}/v1/memory"))
        assert via_http["total_max"] == mem["total_max"]
    finally:
        coord.stop()
        if w is not None:
            w.stop()
