"""Distributed (8-virtual-worker SPMD) execution vs local single-device results.

Mirrors the reference's DistributedQueryRunner-vs-H2 pattern (SURVEY.md §4): the same query
runs on the worker mesh and on one device; results must match exactly.
"""

import numpy as np
import pandas as pd
import pytest

import jax

from trino_tpu.parallel.mesh import worker_mesh


QUERIES = {
    "q1": """
        select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
               sum(l_extendedprice) as sum_base_price,
               sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
               sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
               avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price,
               avg(l_discount) as avg_disc, count(*) as count_order
        from lineitem where l_shipdate <= date '1998-12-01' - interval '90' day
        group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus""",
    "q3": """
        select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
               o_orderdate, o_shippriority
        from customer, orders, lineitem
        where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
          and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
          and l_shipdate > date '1995-03-15'
        group by l_orderkey, o_orderdate, o_shippriority
        order by revenue desc, o_orderdate limit 10""",
    "q5": """
        select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue
        from customer, orders, lineitem, supplier, nation, region
        where c_custkey = o_custkey and l_orderkey = o_orderkey
          and l_suppkey = s_suppkey and c_nationkey = s_nationkey
          and s_nationkey = n_nationkey and n_regionkey = r_regionkey
          and r_name = 'ASIA' and o_orderdate >= date '1994-01-01'
          and o_orderdate < date '1994-01-01' + interval '1' year
        group by n_name order by revenue desc""",
    "q6": """
        select sum(l_extendedprice * l_discount) as revenue
        from lineitem
        where l_shipdate >= date '1994-01-01'
          and l_shipdate < date '1994-01-01' + interval '1' year
          and l_discount between 0.05 and 0.07 and l_quantity < 24""",
    "scan_filter": """
        select o_orderkey, o_totalprice from orders
        where o_orderdate >= date '1998-01-01' and o_custkey < 50
        order by o_orderkey limit 50""",
    # north-star suite completion (round-1 VERDICT weak #3: Q9/Q18 shapes fell
    # back to local because of Project-above-Aggregate and null-aware semi)
    "q9": """
        select nation, o_year, sum(amount) as sum_profit from (
          select n_name as nation, extract(year from o_orderdate) as o_year,
            l_extendedprice * (1 - l_discount) - ps_supplycost * l_quantity as amount
          from part, supplier, lineitem, partsupp, orders, nation
          where s_suppkey = l_suppkey and ps_suppkey = l_suppkey and ps_partkey = l_partkey
            and p_partkey = l_partkey and o_orderkey = l_orderkey
            and s_nationkey = n_nationkey and p_name like '%green%') as profit
        group by nation, o_year order by nation, o_year desc""",
    "q18": """
        select c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice, sum(l_quantity)
        from customer, orders, lineitem
        where o_orderkey in (select l_orderkey from lineitem group by l_orderkey
                             having sum(l_quantity) > 100)
          and c_custkey = o_custkey and o_orderkey = l_orderkey
        group by c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
        order by o_totalprice desc, o_orderdate limit 100""",
    # streaming topN without an aggregate: per-worker device topN + host merge
    "topn_stream": """
        select l_orderkey, l_extendedprice from lineitem
        order by l_extendedprice desc, l_orderkey limit 7""",
    # residual join filter on a non-inner join (match condition, not post-filter)
    "left_filter": """
        select count(*) c, sum(o_totalprice) sp from orders
        left join customer on o_custkey = c_custkey and c_acctbal > 5000""",
    # NOT IN with an empty build set: every probe row survives
    "anti_empty": """
        select count(*) c from orders where o_custkey not in
        (select c_custkey from customer where c_acctbal > 99999999)""",
    # full ORDER BY without LIMIT: range-partitioned exchange + per-worker
    # device sort + host concat in rank order (round-2 VERDICT weak #9)
    "full_sort": """
        select o_orderkey, o_totalprice, o_orderdate from orders
        order by o_totalprice desc, o_orderkey""",
    # dictionary-encoded primary sort key: splitters live in collation-rank space
    "full_sort_dict": """
        select c_custkey, c_mktsegment from customer
        order by c_mktsegment, c_custkey desc""",
    # partitioned window: rows hash-routed so each worker owns whole partitions,
    # then the local window kernel runs per shard (round-2 VERDICT weak #9)
    "window_dist": """
        select o_custkey, o_orderkey, o_totalprice,
               row_number() over (partition by o_custkey order by o_totalprice desc,
                                  o_orderkey) rn,
               sum(o_totalprice) over (partition by o_custkey) tot,
               lag(o_orderkey) over (partition by o_custkey order by o_orderdate,
                                     o_orderkey) prev
        from orders order by o_custkey, o_orderkey""",
    # north-star Q4: EXISTS semi join distributed (bench suite member)
    "q4": """
        select o_orderpriority, count(*) as order_count from orders
        where o_orderdate >= date '1993-07-01'
          and o_orderdate < date '1993-07-01' + interval '3' month
          and exists (select 1 from lineitem where l_orderkey = o_orderkey
                      and l_commitdate < l_receiptdate)
        group by o_orderpriority order by o_orderpriority""",
    # global variance distributed (sum_sq accumulator through psum merge)
    "var_global": """
        select var_pop(l_discount) v, stddev_samp(l_quantity) s,
               sum(l_tax) t from lineitem where l_orderkey < 1000""",
    "window_dist_frame": """
        select o_custkey, o_orderkey,
               sum(o_totalprice) over (partition by o_custkey
                 order by o_orderdate, o_orderkey
                 rows between 1 preceding and current row) s
        from orders order by o_custkey, o_orderkey""",
}


def _frames_equal(a: pd.DataFrame, b: pd.DataFrame):
    assert len(a) == len(b)
    for ca, cb in zip(a.columns, b.columns):
        ga, gb = a[ca].to_numpy(), b[cb].to_numpy()
        if ga.dtype == object or gb.dtype == object:
            assert list(ga) == list(gb), ca
        else:
            np.testing.assert_allclose(ga.astype(np.float64), gb.astype(np.float64),
                                       rtol=1e-12, err_msg=ca)


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    return worker_mesh(8)


@pytest.mark.parametrize("name", list(QUERIES))
def test_distributed_matches_local(engine, mesh8, name):
    sql = QUERIES[name]
    session = engine.create_session("tpch")
    local = engine.execute_sql(sql, session).to_pandas()
    dist = engine.execute_sql(sql, session, distributed=True, mesh=mesh8).to_pandas()
    _frames_equal(dist, local)


def test_distributed_on_subset_mesh(engine):
    """Mesh smaller than the device count (2 workers)."""
    mesh = worker_mesh(2)
    session = engine.create_session("tpch")
    local = engine.execute_sql(QUERIES["q6"], session).to_pandas()
    dist = engine.execute_sql(QUERIES["q6"], session, distributed=True, mesh=mesh).to_pandas()
    _frames_equal(dist, local)


def test_distributed_not_in_empty_build_null_probe(engine, mesh8):
    """NOT IN against an EMPTY set is TRUE even for a NULL probe key (3VL:
    there is nothing to compare against) — NULL-keyed probe rows must survive,
    matching local (regression: distributed dropped them unconditionally)."""
    sql = ("select count(*) c from orders where "
           "(case when o_custkey < 5 then null else o_custkey end) not in "
           "(select c_custkey from customer where c_acctbal > 99999999)")
    session = engine.create_session("tpch")
    local = engine.execute_sql(sql, session).to_pandas()
    dist = engine.execute_sql(sql, session, distributed=True, mesh=mesh8).to_pandas()
    _frames_equal(dist, local)
    # every orders row survives, including the NULL-keyed ones
    n_orders = engine.execute_sql("select count(*) c from orders",
                                  session).to_pandas().iloc[0, 0]
    assert int(local.iloc[0, 0]) == int(n_orders)


def test_distributed_null_aware_anti_with_null_build(engine, mesh8):
    """NOT IN whose subquery yields a NULL: 3VL makes every membership test
    unknown, so zero rows survive — distributed must agree with local."""
    sql = ("select count(*) c from orders where o_custkey not in "
           "(select case when c_custkey < 5 then null else c_custkey end "
           " from customer)")
    session = engine.create_session("tpch")
    local = engine.execute_sql(sql, session).to_pandas()
    dist = engine.execute_sql(sql, session, distributed=True, mesh=mesh8).to_pandas()
    _frames_equal(dist, local)
    assert int(local.iloc[0, 0]) == 0


# lineitem ⋈ partsupp on partkey alone: BOTH sides carry duplicate keys, so
# whichever side builds needs the multi-match (position-links analog) strategy
DUP_KEY_Q = ("select l_partkey, count(*) n, sum(ps_supplycost) sc "
             "from lineitem, partsupp where l_partkey = ps_partkey "
             "group by l_partkey order by l_partkey limit 30")


@pytest.mark.parametrize("threshold", [8, 1 << 30],
                         ids=["partitioned", "broadcast"])
def test_multi_match_join_matches_local(engine, mesh8, threshold):
    """Duplicate-build-key joins run DISTRIBUTED (no silent local fallback) in
    both distribution modes: slot-grouped expansion per shard, overflow
    side-channel retries (VERDICT r2 #3)."""
    from trino_tpu.exec.distributed import DistributedExecutor
    from trino_tpu.sql.frontend import compile_sql

    s = engine.create_session("tpch")
    local = engine.execute_sql(DUP_KEY_Q, s).to_pandas()
    ex = DistributedExecutor(engine.catalogs, mesh=mesh8,
                             partition_threshold=threshold)
    dist = ex.execute(compile_sql(DUP_KEY_Q, engine, s)).to_pandas()
    _frames_equal(dist, local)


def test_multi_match_left_join_matches_local(engine, mesh8):
    """LEFT joins against a duplicate-key build: unmatched probe rows survive
    with NULL build columns through the distributed expansion."""
    sql = ("select count(*) c, sum(ps_availqty) q from part "
           "left join partsupp on p_partkey = ps_partkey "
           "and ps_supplycost > 500")
    s = engine.create_session("tpch")
    local = engine.execute_sql(sql, s).to_pandas()
    from trino_tpu.exec.distributed import DistributedExecutor
    from trino_tpu.sql.frontend import compile_sql

    ex = DistributedExecutor(engine.catalogs, mesh=mesh8,
                             partition_threshold=8)
    dist = ex.execute(compile_sql(sql, engine, s)).to_pandas()
    _frames_equal(dist, local)


def test_probe_bucket_overflow_retries(engine, mesh8):
    """Force the first ladder rung to overflow (skewed partition ids) and
    assert the retry ladder still converges to the right answer: all rows of
    one key hash to ONE worker, so a ~2n/W probe bucket must overflow."""
    from trino_tpu.exec.distributed import DistributedExecutor
    from trino_tpu.sql.frontend import compile_sql

    # constant join key -> every probe row routes to the same partition
    sql = ("select count(*) c from "
           "(select 1 k, l_quantity from lineitem) l "
           "join (select 1 k, n_nationkey from nation) n on l.k = n.k")
    s = engine.create_session("tpch")
    local = engine.execute_sql(sql, s).to_pandas()
    ex = DistributedExecutor(engine.catalogs, mesh=mesh8,
                             partition_threshold=8)
    dist = ex.execute(compile_sql(sql, engine, s)).to_pandas()
    _frames_equal(dist, local)


def test_partitioned_join_matches_local(engine):
    """Hash-partitioned (all-to-all) join distribution vs broadcast/local results."""
    import numpy as np

    from trino_tpu.exec.distributed import DistributedExecutor
    from trino_tpu.sql.frontend import compile_sql

    s = engine.create_session("tpch")
    q = ("select l_orderkey, count(*) n, sum(l_quantity) q from lineitem, orders "
         "where l_orderkey = o_orderkey and o_orderdate < date '1994-01-01' "
         "group by l_orderkey order by l_orderkey limit 50")
    local = engine.execute_sql(q, s).to_pandas()
    ex = DistributedExecutor(engine.catalogs, partition_threshold=8)
    dist = ex.execute(compile_sql(q, engine, s)).to_pandas()
    assert len(dist) == len(local)
    for c in local.columns:
        np.testing.assert_allclose(dist[c].to_numpy().astype(float),
                                   local[c].to_numpy().astype(float), rtol=1e-9)
