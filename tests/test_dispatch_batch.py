"""Dispatch coalescing (TRINO_TPU_DISPATCH_BATCH / SET SESSION dispatch_batch):
batched multi-split execution must be a pure dispatch-count optimization —
byte-identical results, identical page generation (once per split; the failed
scan-fused path's on-device REGENERATION must never silently come back), and a
visible `coalesced_splits` counter.  batch=1 is the exact-old-behavior escape
hatch.

Scale here is tiny but split-RICH (sf=0.02, split_rows=1<<11 -> ~100 lineitem
splits): coalescing coverage comes from split count, not data volume.
"""

import numpy as np
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector

from test_query_budgets import QUERIES  # the tier-1 north-star queries

SF = 0.02
SPLIT_ROWS = 1 << 11


@pytest.fixture(scope="module")
def ab_engine():
    """One engine, two sessions: dispatch_batch is plan-shaping, so each
    session keys (and compiles) its own plan — the A/B runs share nothing but
    the connector."""
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=SF, split_rows=SPLIT_ROWS))
    s1 = e.create_session("tpch")
    e.session_properties.set_property(s1, "dispatch_batch", 1)
    s4 = e.create_session("tpch")
    e.session_properties.set_property(s4, "dispatch_batch", 4)
    yield e, s1, s4
    e._invalidate()


def _assert_results_identical(r1, r4, name):
    assert r1.names == r4.names
    assert r1.types == r4.types
    for decoded in (False, True):
        cols1 = r1.columns if decoded else r1.raw_columns
        cols4 = r4.columns if decoded else r4.raw_columns
        for cn, c1, c4 in zip(r1.names, cols1, cols4):
            a1, a4 = np.asarray(c1), np.asarray(c4)
            # byte-identical: same dtype (DATE/TIMESTAMP surfaces decode to
            # datetime64, dictionary columns decode to their values) and same
            # values in the same row order
            assert a1.dtype == a4.dtype, (name, cn, a1.dtype, a4.dtype)
            assert np.array_equal(a1, a4), (name, cn, decoded)


@pytest.mark.parametrize("name", sorted(QUERIES))
def test_batch1_vs_batch4_results_byte_identical(ab_engine, name):
    e, s1, s4 = ab_engine
    r1 = e.execute_sql(QUERIES[name], s1)
    r4 = e.execute_sql(QUERIES[name], s4)
    assert len(r1) == len(r4) and len(r1) > 0
    _assert_results_identical(r1, r4, name)


@pytest.mark.parametrize("name", sorted(QUERIES))
def test_warm_dispatch_reduction(ab_engine, name):
    """Batch=4 must dispatch strictly less than batch=1, with the
    coalesced-splits counter attributing the difference; batch=1 must not
    coalesce at all (the escape hatch is exact old behavior).  One execution
    per mode: the byte-identity tests above already compiled both plans, and
    the inequalities hold cold or warm (both modes pay the same one-time
    build-side work)."""
    e, s1, s4 = ab_engine
    e.execute_sql(QUERIES[name], s1)
    c1 = e.last_query_counters
    e.execute_sql(QUERIES[name], s4)
    c4 = e.last_query_counters
    assert c1.coalesced_splits == 0, c1.as_dict()
    assert c4.coalesced_splits > 0, c4.as_dict()
    assert c4.device_dispatches < c1.device_dispatches, \
        (name, c1.as_dict(), c4.as_dict())
    # bytes must not regress: coalescing only batches dispatches (per-batch
    # live-count scalars can only get fewer)
    assert c4.host_bytes_pulled <= c1.host_bytes_pulled, \
        (name, c1.as_dict(), c4.as_dict())


def test_pages_generated_once_per_split():
    """Coalescing stacks pages the connector already produced — the page
    generation count per split must not change with the batch width (guards
    against resurrecting scan-fused regeneration, and against a batcher that
    drops or duplicates splits)."""
    def run(batch):
        e = Engine()
        conn = TpchConnector(sf=0.01, split_rows=SPLIT_ROWS)
        calls = []
        orig = conn.generate
        conn.generate = lambda sp, cols=None: (calls.append(sp),
                                               orig(sp, cols))[1]
        e.register_catalog("tpch", conn)
        s = e.create_session("tpch")
        e.session_properties.set_property(s, "dispatch_batch", batch)
        r = e.execute_sql(QUERIES["q3"], s)
        e._invalidate()
        return calls, r

    calls1, r1 = run(1)
    calls4, r4 = run(4)
    assert sorted(repr(sp) for sp in calls1) == \
        sorted(repr(sp) for sp in calls4)
    _assert_results_identical(r1, r4, "q3")


def test_set_session_rides_plan_cache(ab_engine):
    """SET SESSION dispatch_batch must take effect on an already-cached
    statement: the property is plan-shaping (engine._plan_shape_props), so
    changing it re-keys the plan instead of silently reusing the old one."""
    e, _, _ = ab_engine
    s = e.create_session("tpch")
    sql = QUERIES["q1"]
    e.execute_sql(sql, s)
    e.execute_sql(sql, s)  # warm at the default batch (4)
    assert e.last_query_counters.coalesced_splits > 0
    warm_default = e.last_query_counters.device_dispatches
    e.execute_sql("set session dispatch_batch = 1", s)
    e.execute_sql(sql, s)
    e.execute_sql(sql, s)
    assert e.last_query_counters.coalesced_splits == 0
    assert e.last_query_counters.device_dispatches > warm_default
    e.execute_sql("reset session dispatch_batch", s)
    e.execute_sql(sql, s)
    assert e.last_query_counters.coalesced_splits > 0


def test_explain_analyze_shows_coalescing(ab_engine):
    e, _, s4 = ab_engine
    r = e.execute_sql(
        "explain analyze select count(*), sum(l_quantity) from lineitem", s4)
    text = "\n".join(str(row[0]) for row in r.rows())
    assert "splits coalesced" in text
