"""Spatial distance join (reference: operator/SpatialJoinOperator.java +
plugin/trino-geospatial ST_* scalars — round-4 verdict missing item 7).

TPU re-design: points never materialize (st_point is a planner macro);
ST_Distance lowers to one canonical ir op; a distance-radius predicate over a
cross join rewrites to a grid-bucketed EQUI-join (cells of size r, build side
expanded 9x into the 3x3 neighbor shifts via UNION ALL) with the exact
distance kept as the residual filter — the KDB-tree partitioning of the
reference, re-planned as one hash join the existing machinery runs."""

import numpy as np
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.sql import plan as P
from trino_tpu.sql.frontend import compile_sql


@pytest.fixture(scope="module")
def geo():
    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    rng = np.random.default_rng(7)
    n, m = 400, 300
    A = rng.uniform(0, 100, (n, 2))
    B = rng.uniform(0, 100, (m, 2))
    e.execute_sql("create table pts_a (aid bigint, ax double, ay double)")
    e.execute_sql("create table pts_b (bid bigint, qx double, qy double)")
    e.execute_sql("insert into pts_a values " + ", ".join(
        f"({i}, {A[i, 0]:.6f}, {A[i, 1]:.6f})" for i in range(n)))
    e.execute_sql("insert into pts_b values " + ", ".join(
        f"({i}, {B[i, 0]:.6f}, {B[i, 1]:.6f})" for i in range(m)))
    d = np.sqrt(((A[:, None, :] - B[None, :, :]) ** 2).sum(-1))
    return e, A, B, d


def test_distance_scalars(geo):
    e, A, B, d = geo
    r = e.execute_sql(
        "select st_distance(st_point(0.0, 0.0), st_point(3.0, 4.0)) v"
    ).rows()
    assert float(r[0][0]) == pytest.approx(5.0)
    r = e.execute_sql(
        "select st_x(st_point(ax, ay)) x, st_y(st_point(ax, ay)) y "
        "from pts_a where aid = 3").rows()
    assert float(r[0][0]) == pytest.approx(A[3, 0], abs=1e-6)
    assert float(r[0][1]) == pytest.approx(A[3, 1], abs=1e-6)


def test_spatial_join_matches_bruteforce(geo):
    e, A, B, d = geo
    for radius in (2.0, 5.0, 11.5):
        got = int(e.execute_sql(
            f"""select count(*) c from pts_a, pts_b
                where st_distance(st_point(ax, ay), st_point(qx, qy))
                      <= {radius}""").rows()[0][0])
        assert got == int((d <= radius).sum()), radius


def test_spatial_join_plan_uses_grid(geo):
    e, *_ = geo
    plan = compile_sql(
        """select aid, bid from pts_a, pts_b
           where st_distance(st_point(ax, ay), st_point(qx, qy)) <= 5.0""",
        e, e.create_session("mem"))

    unions, joins = [], []

    def walk(n):
        if isinstance(n, P.Union):
            unions.append(n)
        if isinstance(n, P.Join):
            joins.append(n)
        for c in n.children:
            walk(c)

    walk(plan)
    assert unions and len(unions[0].inputs) == 9, "3x3 cell expansion missing"
    assert joins and joins[0].filter is not None, \
        "exact distance residual must remain on the join"


def test_spatial_join_pairs_unique_and_exact(geo):
    """Pair-level correctness: no duplicates from the 9-cell expansion, and
    boundary distances stay exact through the residual filter."""
    e, A, B, d = geo
    rows = e.execute_sql(
        """select aid, bid from pts_a, pts_b
           where st_distance(st_point(ax, ay), st_point(qx, qy)) <= 3.0
           order by aid, bid""").rows()
    got = [(int(a), int(b)) for a, b in rows]
    assert len(got) == len(set(got)), "duplicate pairs from cell expansion"
    ai, bi = np.nonzero(d <= 3.0)
    assert got == sorted(zip(ai.tolist(), bi.tolist()))


def test_spatial_join_with_extra_conjuncts(geo):
    e, A, B, d = geo
    got = int(e.execute_sql(
        """select count(*) c from pts_a, pts_b
           where st_distance(st_point(ax, ay), st_point(qx, qy)) <= 5.0
             and aid < 200 and bid >= 10""").rows()[0][0])
    assert got == int((d[:200, 10:] <= 5.0).sum())


def test_st_point_standalone_rejected(geo):
    e, *_ = geo
    with pytest.raises(Exception, match="st_point"):
        e.execute_sql("select st_point(1.0, 2.0) p from pts_a limit 1")


def test_degenerate_constant_join_not_rewritten(geo):
    """ON 1 = 2 is an always-empty join, not a cross join: the grid rewrite
    must not invent rows (post-review hardening)."""
    e, *_ = geo
    got = e.execute_sql(
        """select count(*) c from pts_a a join pts_b b on 1 = 2
           where st_distance(st_point(ax, ay), st_point(qx, qy)) <= 50.0"""
    ).rows()
    assert int(got[0][0]) == 0


def test_large_coordinates_stay_exact(geo):
    """Cell packing runs in int64: coordinates ~4e6 with r=1 (cells ~2^22,
    past the double-packing precision cliff) must not duplicate pairs."""
    e, *_ = geo
    import numpy as np

    e.execute_sql("create table big_a (i bigint, x double, y double)")
    e.execute_sql("create table big_b (j bigint, x double, y double)")
    base = 4.0e6
    A = [(i, base + i * 0.4, base - i * 0.3) for i in range(60)]
    B = [(j, base + j * 0.4 + 0.05, base - j * 0.3 + 0.05) for j in range(60)]
    e.execute_sql("insert into big_a values " + ", ".join(
        f"({i}, {x:.6f}, {y:.6f})" for i, x, y in A))
    e.execute_sql("insert into big_b values " + ", ".join(
        f"({j}, {x:.6f}, {y:.6f})" for j, x, y in B))
    rows = e.execute_sql(
        """select i, j from big_a, big_b
           where st_distance(st_point(big_a.x, big_a.y),
                             st_point(big_b.x, big_b.y)) <= 1.0
           order by i, j""").rows()
    got = [(int(a), int(b)) for a, b in rows]
    assert len(got) == len(set(got)), "duplicate pairs at large coordinates"
    a = np.array([(x, y) for _, x, y in A])
    b = np.array([(x, y) for _, x, y in B])
    d = np.sqrt(((a[:, None, :] - b[None, :, :]) ** 2).sum(-1))
    ai, bi = np.nonzero(d <= 1.0)
    assert got == sorted(zip(ai.tolist(), bi.tolist()))
