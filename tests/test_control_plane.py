"""Query lifecycle, resource groups, session properties, events, tracing, and the
system connector.

Reference test models: TestQueryStateMachine, TestInternalResourceGroup,
TestSystemSessionProperties, connector/system tests.
"""

import threading
import time

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.execution.eventlistener import EventListener
from trino_tpu.execution.query_state import QueryState, QueryStateMachine
from trino_tpu.execution.resourcegroups import (QueryQueueFullError, ResourceGroup,
                                                ResourceGroupManager)
from trino_tpu.execution.statemachine import StateMachine


def _engine():
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.001, split_rows=1 << 12))
    return e


def test_state_machine_listeners_and_terminal():
    sm = StateMachine("t", "A", terminal_states=["C"])
    seen = []
    sm.add_state_change_listener(seen.append)
    assert seen == ["A"]  # fires with current state on registration
    assert sm.set("B")
    assert sm.compare_and_set("B", "C")
    assert not sm.set("A")  # terminal
    assert seen == ["A", "B", "C"]
    assert sm.is_terminal


def test_query_state_machine_flow():
    q = QueryStateMachine("q1", "select 1")
    for s in (QueryState.DISPATCHING, QueryState.PLANNING, QueryState.RUNNING,
              QueryState.FINISHING, QueryState.FINISHED):
        assert q.transition(s)
    assert q.is_done and q.state == QueryState.FINISHED
    assert q.info().wall_s is not None
    q2 = QueryStateMachine("q2", "select 1")
    q2.fail("boom")
    assert q2.state == QueryState.FAILED and q2.error == "boom"


def test_engine_tracks_queries_and_fires_events():
    e = _engine()
    s = e.create_session("tpch")
    events = []

    class L(EventListener):
        def query_created(self, ev):
            events.append(("created", ev.query_id))

        def query_completed(self, ev):
            events.append(("completed", ev.query_id, ev.state, ev.rows))

    e.event_listeners.add(L())
    r = e.execute_sql("select count(*) from nation", s)
    assert r.rows()[0][0] == 25
    infos = [q.info() for q in e.query_tracker.all_queries()]
    assert any(i.state == "FINISHED" and i.rows == 1 for i in infos)
    kinds = [ev[0] for ev in events]
    assert kinds == ["created", "completed"]
    assert events[1][2] == "FINISHED" and events[1][3] == 1
    # failures are tracked too
    with pytest.raises(Exception):
        e.execute_sql("select no_such_column from nation", s)
    infos = [q.info() for q in e.query_tracker.all_queries()]
    assert any(i.state == "FAILED" and i.error for i in infos)
    assert events[-1][2] == "FAILED"


def test_resource_group_queueing_and_fairness():
    mgr = ResourceGroupManager(ResourceGroup("global", hard_concurrency_limit=1))
    g = mgr.get_or_create("global.user")
    order = []
    started = [threading.Event() for _ in range(3)]

    def mk(i):
        def start():
            order.append(i)
            started[i].set()
        return start

    mgr.submit(g, mk(0))
    mgr.submit(g, mk(1))  # queued (limit 1)
    mgr.submit(g, mk(2))  # queued
    assert order == [0]
    mgr.finish(g)  # releases slot -> starts 1
    assert order == [0, 1]
    mgr.finish(g)
    assert order == [0, 1, 2]
    mgr.finish(g)
    info = {i["name"]: i for i in mgr.info()}
    assert info["global.user"]["running"] == 0 and info["global.user"]["queued"] == 0


def test_resource_group_queue_full():
    mgr = ResourceGroupManager(ResourceGroup("global", hard_concurrency_limit=1))
    g = mgr.get_or_create("global.u")
    g.max_queued = 1
    mgr.submit(g, lambda: None)
    mgr.submit(g, lambda: None)
    with pytest.raises(QueryQueueFullError):
        mgr.submit(g, lambda: None)


def test_engine_concurrent_queries_respect_admission():
    e = _engine()
    e.resource_groups.root.hard_concurrency_limit = 2
    s = e.create_session("tpch")
    e.execute_sql("select count(*) from region", s)  # compile once
    results = []

    def run():
        r = e.execute_sql("select count(*) from region", s)
        results.append(r.rows()[0][0])

    ts = [threading.Thread(target=run) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert results == [5, 5, 5, 5]


def test_session_properties_sql():
    e = _engine()
    s = e.create_session("tpch")
    e.execute_sql("set session task_concurrency = 4", s)
    assert e.session_properties.get(s, "task_concurrency") == 4
    e.execute_sql("set session join_distribution_type = 'BROADCAST'", s)
    assert e.session_properties.get(s, "join_distribution_type") == "BROADCAST"
    rows = e.execute_sql("show session", s).rows()
    d = {r[0]: r[1] for r in rows}
    assert d["task_concurrency"] == "4"
    e.execute_sql("reset session task_concurrency", s)
    assert e.session_properties.get(s, "task_concurrency") == 8
    with pytest.raises(ValueError):
        e.execute_sql("set session no_such_prop = 1", s)
    with pytest.raises(ValueError):
        e.execute_sql("set session task_concurrency = 'abc'", s)


def test_show_statements():
    e = _engine()
    s = e.create_session("tpch")
    cats = [r[0] for r in e.execute_sql("show catalogs", s).rows()]
    assert "tpch" in cats and "system" in cats
    tabs = [r[0] for r in e.execute_sql("show tables", s).rows()]
    assert "lineitem" in tabs
    cols = e.execute_sql("show columns from nation", s).rows()
    assert ("n_name", "varchar(25)") in [(c, t) for c, t in cols]
    fns = [r[0] for r in e.execute_sql("show functions", s).rows()]
    assert "sum" in fns and "substring" in fns


def test_system_tables():
    e = _engine()
    s = e.create_session("tpch")
    e.execute_sql("select count(*) from region", s)
    rows = e.execute_sql(
        "select state, count(*) c from system.queries group by state order by state",
        s).rows()
    states = {r[0] for r in rows}
    assert "FINISHED" in states
    cats = e.execute_sql("select catalog_name from system.catalogs order by 1", s).rows()
    assert ("system",) in cats and ("tpch",) in cats
    t = e.execute_sql(
        "select table_name from system.tables where table_catalog = 'tpch' order by 1",
        s).rows()
    assert ("lineitem",) in t
    rg = e.execute_sql("select name, running from system.resource_groups", s).rows()
    assert any(r[0] == "global" for r in rg)
    # re-execution sees NEW queries (dictionaries grow in place, plans stay valid)
    n1 = e.execute_sql("select count(*) from system.queries", s).rows()[0][0]
    e.execute_sql("select count(*) from nation", s)
    n2 = e.execute_sql("select count(*) from system.queries", s).rows()[0][0]
    assert n2 > n1


def test_tracing_spans():
    e = _engine()
    s = e.create_session("tpch")
    e.execute_sql("select count(*) from part", s)
    qid = [q.query_id for q in e.query_tracker.all_queries()][-1]
    spans = e.tracer.spans_for(qid)
    names = {sp.name for sp in spans}
    assert {"query", "planner", "execution"} <= names
    q = next(sp for sp in spans if sp.name == "query")
    pl = next(sp for sp in spans if sp.name == "planner")
    assert pl.parent_id == q.span_id
    assert q.duration_s is not None and q.status == "OK"


def test_prepared_statements():
    """PREPARE / EXECUTE [USING ...] / DEALLOCATE PREPARE (reference:
    QueryPreparer + session prepared statements)."""
    e = _engine()
    s = e.create_session("tpch")
    e.execute_sql("prepare q from select count(*) from orders where o_orderkey <= ?",
                  s)
    r = e.execute_sql("execute q using 50", s).rows()
    assert r[0][0] == 50
    r = e.execute_sql("execute q using 10", s).rows()
    assert r[0][0] == 10
    e.execute_sql("prepare seg from "
                  "select count(*) from customer where c_mktsegment = ?", s)
    n = e.execute_sql("execute seg using 'BUILDING'", s).rows()[0][0]
    direct = e.execute_sql(
        "select count(*) from customer where c_mktsegment = 'BUILDING'", s).rows()[0][0]
    assert n == direct
    e.execute_sql("deallocate prepare q", s)
    with pytest.raises(Exception):
        e.execute_sql("execute q using 5", s)
    with pytest.raises(Exception):
        e.execute_sql("deallocate prepare nope", s)


def test_show_stats():
    e = _engine()
    s = e.create_session("tpch")
    rows = e.execute_sql("show stats for orders", s).rows()
    by_col = {r[0]: r for r in rows}
    assert "o_orderkey" in by_col
    assert by_col[""][4] != ""  # summary row carries the row count
    lo, hi = by_col["o_orderkey"][2], by_col["o_orderkey"][3]
    assert lo in ("0", "1") and int(hi) > 0
