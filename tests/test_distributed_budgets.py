"""Device-boundary budgets for the WORKER-MESH path (round 18).

The round-6 budget discipline extended to the distributed executor: warm
Q3/Q9/Q18 on the 8-device CPU mesh must be byte-identical to local execution
AND stay under committed ceilings on the host bytes pulled at the dist.*
sites.  With the device-resident exchange, routed rows live in carried
[W, cap] device receive buffers inside the routing shard_map — the only
host traffic between scan and the blocking consumer is scalar
overflow/cursor flags, so a full-page pull appearing at an exchange site
(the round-17 host spool's signature) blows the ceiling immediately.

Re-derive after an INTENTIONAL executor change with:

    TRACE_SF=0.02 TRACE_SPLIT_ROWS=4096 TRACE_QUERIES=q3,q9,q18 \
        JAX_PLATFORMS=cpu python scripts/query_counters.py --distributed --sites

Measured trace the ceilings derive from (2026-08-06, jax 0.7 CPU mesh):

    q3  warm device: dist bytes 20586 (agg.groups 20480), pulled 20610
        warm spool:  dist bytes 25322984 (1230x)
    q9  warm device: dist bytes 9349, pulled 9403
        warm spool:  dist bytes 23522761 (2516x)
    q18 warm device: dist bytes 563, pulled 598
        warm spool:  dist bytes 33887208 (60190x)

Ceilings sit at ~2x measured for group-count headroom.  A failure means a
bulk pull crept back into the mesh path — fix the path, don't bump the
ceiling.
"""

import jax
import numpy as np
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.exec.distributed import DistributedExecutor
from trino_tpu.parallel.mesh import worker_mesh
from trino_tpu.sql.frontend import compile_sql

SF = 0.02
SPLIT_ROWS = 1 << 12

# inlined (budget-suite convention: the ceilings must not drift with a
# benchmark edit) — text matches bench.py's QUERIES
QUERIES = {
    "q3": """
    select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
           o_orderdate, o_shippriority
    from customer, orders, lineitem
    where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
      and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
      and l_shipdate > date '1995-03-15'
    group by l_orderkey, o_orderdate, o_shippriority
    order by revenue desc, o_orderdate limit 10""",
    "q9": """
    select nation, o_year, sum(amount) as sum_profit from (
      select n_name as nation, extract(year from o_orderdate) as o_year,
        l_extendedprice * (1 - l_discount) - ps_supplycost * l_quantity as amount
      from part, supplier, lineitem, partsupp, orders, nation
      where s_suppkey = l_suppkey and ps_suppkey = l_suppkey and ps_partkey = l_partkey
        and p_partkey = l_partkey and o_orderkey = l_orderkey
        and s_nationkey = n_nationkey and p_name like '%green%') as profit
    group by nation, o_year order by nation, o_year desc""",
    "q18": """
    select c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice, sum(l_quantity)
    from customer, orders, lineitem
    where o_orderkey in (select l_orderkey from lineitem group by l_orderkey
                         having sum(l_quantity) > 300)
      and c_custkey = o_custkey and o_orderkey = l_orderkey
    group by c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
    order by o_totalprice desc, o_orderdate limit 100""",
}

# warm, device-exchange mode: total bytes at dist.* sites / total host bytes
CEILINGS = {
    "q3": {"dist_bytes": 45_000, "host_bytes_pulled": 46_000},
    "q9": {"dist_bytes": 20_000, "host_bytes_pulled": 21_000},
    "q18": {"dist_bytes": 2_000, "host_bytes_pulled": 2_600},
}

# full-page exchange/stream spool sites: these existing warm at all means the
# device path silently degraded to the host spool
FORBIDDEN_WARM_SITES = ("dist.exchange.collect", "dist.stream.collect",
                        "dist.shards.pull")


def _frames_equal(a, b):
    assert len(a) == len(b)
    for ca, cb in zip(a.columns, b.columns):
        ga, gb = a[ca].to_numpy(), b[cb].to_numpy()
        if ga.dtype == object or gb.dtype == object:
            assert list(ga) == list(gb), ca
        else:
            np.testing.assert_array_equal(ga, gb, err_msg=ca)


@pytest.fixture(scope="module")
def dist_env():
    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    engine = Engine()
    engine.register_catalog("tpch",
                            TpchConnector(sf=SF, split_rows=SPLIT_ROWS))
    session = engine.create_session("tpch")
    mesh = worker_mesh(8)
    baselines = {}
    plans = {}
    for name, sql in QUERIES.items():
        baselines[name] = engine.execute_sql(sql, session).to_pandas()
        plans[name] = compile_sql(sql, engine, session)
    return engine, mesh, plans, baselines


def _warm_run(engine, mesh, plan, device_exchange):
    """Cold + warm run on one executor; returns (warm frame, warm counters)."""
    ex = DistributedExecutor(engine.catalogs, mesh=mesh,
                             device_exchange=device_exchange)
    ex.execute(plan)
    warm = ex.execute(plan).to_pandas()
    return warm, ex.counters


@pytest.mark.parametrize("name", list(QUERIES))
def test_mesh_warm_budget(dist_env, name):
    engine, mesh, plans, baselines = dist_env
    warm, c = _warm_run(engine, mesh, plans[name], device_exchange=True)
    # byte-identity vs the local executor (the acceptance contract)
    _frames_equal(warm, baselines[name])
    sites = c.sites
    for bad in FORBIDDEN_WARM_SITES:
        hits = [k for k in sites if bad in k]
        assert not hits, f"{name}: host-spool site live on the mesh: {hits}"
    dist_bytes = sum(v["bytes"] for k, v in sites.items() if "dist." in k)
    lim = CEILINGS[name]
    site_table = {k: v["bytes"] for k, v in sorted(sites.items())
                  if "dist." in k}
    assert dist_bytes <= lim["dist_bytes"], \
        f"{name}: dist-site bytes {dist_bytes} > {lim['dist_bytes']}: " \
        f"{site_table}"
    assert c.host_bytes_pulled <= lim["host_bytes_pulled"], \
        f"{name}: total pulled {c.host_bytes_pulled} > " \
        f"{lim['host_bytes_pulled']}: {site_table}"


def test_mesh_exchange_ab_ratio(dist_env):
    """The round-18 acceptance number: the device-resident exchange cuts
    warm Q3 exchange-site host bytes >= 10x vs the host spool (measured
    1230x at this scale — 10x is the never-regress floor)."""
    engine, mesh, plans, baselines = dist_env
    dev_f, dev_c = _warm_run(engine, mesh, plans["q3"], device_exchange=True)
    sp_f, sp_c = _warm_run(engine, mesh, plans["q3"], device_exchange=False)
    _frames_equal(dev_f, baselines["q3"])
    _frames_equal(sp_f, baselines["q3"])  # both modes byte-identical
    dev = sum(v["bytes"] for k, v in dev_c.sites.items() if "dist." in k)
    sp = sum(v["bytes"] for k, v in sp_c.sites.items() if "dist." in k)
    assert dev > 0  # scalar flag syncs still counted (the path stays honest)
    assert sp >= 10 * dev, f"spool {sp} vs device {dev}: ratio collapsed"


def test_device_exchange_defaults_on(monkeypatch):
    """TRINO_TPU_DEVICE_EXCHANGE unset = ON everywhere (the mesh path IS the
    round-18 contract); =0 restores the host spool for A/B captures."""
    monkeypatch.delenv("TRINO_TPU_DEVICE_EXCHANGE", raising=False)
    assert DistributedExecutor({}, mesh=worker_mesh(8)).device_exchange
    monkeypatch.setenv("TRINO_TPU_DEVICE_EXCHANGE", "0")
    assert not DistributedExecutor({}, mesh=worker_mesh(8)).device_exchange
