"""Regression tests for subquery/join planner edge cases found in review:
key-type coercion in semi joins, computed correlation keys, CTE scoping, scalar
subquery cardinality errors, distributed fallback for duplicate build keys."""

import pytest

from trino_tpu.sql.frontend import SemanticError


def test_in_subquery_key_type_coercion(engine):
    """decimal IN (select bigint ...): both sides must coerce to the common key type."""
    a = engine.execute_sql(
        "select count(*) c from lineitem where l_quantity in (select p_size from part)")
    lits = ",".join(str(i) for i in range(1, 51))
    b = engine.execute_sql(
        f"select count(*) c from lineitem where l_quantity in ({lits})")
    assert a.columns[0][0] == b.columns[0][0] > 0


def test_correlated_agg_computed_key(engine):
    """A computed/coerced correlation key appends a probe helper channel; the aggregate
    column must still resolve to the right channel."""
    plain = engine.execute_sql(
        "select count(*) c from orders where o_totalprice < "
        "(select sum(l_extendedprice) from lineitem where l_orderkey = o_orderkey)")
    computed = engine.execute_sql(
        "select count(*) c from orders where o_totalprice < "
        "(select sum(l_extendedprice) from lineitem where l_orderkey = o_orderkey + 0)")
    assert plain.columns[0][0] == computed.columns[0][0] > 0


def test_cte_shadowing(engine):
    r = engine.execute_sql("""
        with t as (select n_name from nation)
        select * from (with t as (select r_name from region)
                       select r_name from t) y limit 3""")
    assert r.names == ("r_name",) and len(r) == 3
    r = engine.execute_sql("with t as (select n_name from nation) select n_name from t")
    assert r.names == ("n_name",) and len(r) == 25


def test_scalar_subquery_cardinality_error(engine):
    with pytest.raises(SemanticError, match="exactly one value"):
        engine.execute_sql("select count(*) c from orders where o_totalprice > "
                           "(select o_totalprice from orders)")


def test_distributed_dup_key_join_falls_back(engine):
    r = engine.execute_sql(
        "select l_orderkey from lineitem, partsupp where ps_suppkey = l_suppkey limit 5",
        distributed=True)
    assert len(r) == 5


def test_empty_build_side_joins(engine):
    """Filters selecting zero build rows must not crash any join kind."""
    r = engine.execute_sql("""select count(*) c from nation left outer join customer
                              on n_nationkey = c_nationkey and c_acctbal < -99999999""")
    assert r.columns[0][0] == 25
    r = engine.execute_sql("""select count(*) c from nation, customer
                              where n_nationkey = c_nationkey and c_acctbal < -99999999""")
    assert r.columns[0][0] == 0


def test_correlated_count_empty_group(engine):
    """count() over an empty correlated group is 0, not a dropped row."""
    a = engine.execute_sql(
        "select count(*) c from customer where "
        "(select count(*) from orders where o_custkey = c_custkey) = 0")
    b = engine.execute_sql(
        "select count(*) c from customer where "
        "not exists (select * from orders where o_custkey = c_custkey)")
    assert a.columns[0][0] == b.columns[0][0] > 0


def test_exists_group_having_semantics(engine):
    with pytest.raises(SemanticError, match="HAVING"):
        engine.execute_sql(
            "select count(*) from customer where exists "
            "(select 1 from orders where o_custkey = c_custkey "
            " group by o_orderstatus having count(*) > 1000)")
    # ungrouped aggregate subquery always yields one row: EXISTS is constant-true
    r = engine.execute_sql("select count(*) c from nation where exists "
                           "(select max(o_orderkey) from orders where o_custkey = -1)")
    assert r.columns[0][0] == 25


def test_in_subquery_respects_limit(engine):
    a = engine.execute_sql(
        "select count(*) c from lineitem where l_partkey in "
        "(select p_partkey from part order by p_partkey limit 5)")
    b = engine.execute_sql(
        "select count(*) c from lineitem where l_partkey in (1, 2, 3, 4, 5)")
    assert a.columns[0][0] == b.columns[0][0] > 0


def test_exists_nested_explicit_joins(engine):
    r = engine.execute_sql("""
        select count(*) c from supplier s1 where exists (
            select 1 from lineitem l2
            join orders o2 on l2.l_orderkey = o2.o_orderkey
            join customer c2 on o2.o_custkey = c2.c_custkey
            where l2.l_suppkey = s1.s_suppkey and o2.o_orderstatus = 'F')""")
    assert r.columns[0][0] > 0


def test_not_in_null_semantics(engine):
    """x NOT IN (set containing NULL) is UNKNOWN -> no rows (SQL 3VL)."""
    r = engine.execute_sql(
        "select count(*) c from nation where n_nationkey not in "
        "(select case when r_regionkey > 0 then r_regionkey else null end from region)")
    assert r.columns[0][0] == 0
    r = engine.execute_sql(
        "select count(*) c from nation where n_nationkey in "
        "(select case when r_regionkey > 0 then r_regionkey else null end from region)")
    assert r.columns[0][0] == 4  # nationkeys 1..4


def test_constant_join_key(engine):
    r = engine.execute_sql(
        "select count(*) c from nation join region on r_regionkey = 0")
    assert r.columns[0][0] == 25


def test_dynamic_filter_split_pruning(tpch_sf001, monkeypatch):
    """Inner/semi joins prune probe splits outside the build-key domain.
    The scan-fused paths regenerate on device without calling conn.generate,
    so they are disabled here — this test observes pruning through the
    page-loop machinery's generate calls (the fused path consumes the same
    pruned split list; test_scan_fused covers it)."""
    from trino_tpu import Engine
    from trino_tpu.connectors.tpch import TpchConnector
    import trino_tpu.exec.local_executor as LE

    monkeypatch.setattr(LE.LocalExecutor, "_run_aggregate_scan_fused",
                        lambda self, *a, **k: None)
    monkeypatch.setattr(LE.LocalExecutor, "_run_global_scan_fused",
                        lambda self, *a, **k: None)
    monkeypatch.setattr(LE, "_concat_traced", lambda stream: None)

    conn = TpchConnector(sf=0.01, split_rows=1 << 12)
    e = Engine()
    e.register_catalog("tpch", conn)
    calls = {"n": 0}
    orig = conn.generate

    def counting(split, columns=None):
        if split.table == "lineitem":
            calls["n"] += 1
        return orig(split, columns)

    conn.generate = counting
    n_splits = len(conn.splits("lineitem"))
    assert n_splits > 10
    r = e.execute_sql("select count(*) c from lineitem where l_orderkey in "
                      "(select o_orderkey from orders where o_orderkey < 100)")
    assert calls["n"] <= 2
    r2 = e.execute_sql("select count(*) c from lineitem, orders "
                       "where l_orderkey = o_orderkey and o_orderkey < 100")
    assert r.columns[0][0] == r2.columns[0][0] > 0
    # outer/anti joins must NOT prune
    calls["n"] = 0
    r3 = e.execute_sql("select count(*) c from lineitem where l_orderkey not in "
                       "(select o_orderkey from orders where o_orderkey >= 100)")
    assert calls["n"] == n_splits
    assert r3.columns[0][0] == r.columns[0][0]
