"""MATCH_RECOGNIZE row-pattern matching (reference: SQL:2016 pattern
recognition — grammar patternRecognition, sql/planner/plan/
PatternRecognitionNode.java, operator/window/matcher/Matcher.java).

Subset under test: linear patterns with ?/*/+ quantifiers (greedy with
backtracking), DEFINE with PREV/NEXT navigation, MEASURES FIRST/LAST/var.col,
ONE ROW PER MATCH, AFTER MATCH SKIP PAST LAST ROW."""

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.memory import MemoryConnector


@pytest.fixture()
def px_engine():
    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table px (sym varchar, d bigint, price double)", s)
    e.execute_sql("""insert into px values
      ('a',1,10),('a',2,8),('a',3,7),('a',4,9),('a',5,12),('a',6,11),
      ('b',1,5),('b',2,6),('b',3,4),('b',4,3),('b',5,8)""", s)
    return e, s


def test_v_shape_pattern(px_engine):
    """The canonical V-shape (price falls then recovers) from the reference
    docs (docs/src/main/sphinx/sql/match-recognize.md)."""
    e, s = px_engine
    rows = e.execute_sql("""
        select * from px match_recognize (
          partition by sym order by d
          measures first(a.price) as start_price,
                   last(b.price) as bottom_price,
                   last(c.price) as end_price
          one row per match
          after match skip past last row
          pattern (a b+ c+)
          define b as price < prev(price), c as price > prev(price)
        ) as m order by sym""", s).rows()
    assert rows == [("a", 10.0, 7.0, 12.0), ("b", 6.0, 3.0, 8.0)]


def test_quantifiers_and_multiple_matches(px_engine):
    """* matches zero-or-more (greedy); non-overlapping matches advance past
    the last matched row."""
    e, s = px_engine
    # every maximal strictly-decreasing run of length >= 2 (s = the row the
    # run starts from, d+ = the strictly-lower continuation rows)
    rows = e.execute_sql("""
        select * from px match_recognize (
          partition by sym order by d
          measures first(s.price) as top, last(d.price) as low
          pattern (s d+)
          define d as price < prev(price)
        ) as m order by sym, top""", s).rows()
    assert ("a", 10.0, 7.0) in rows  # 10 > 8 > 7
    assert ("a", 12.0, 11.0) in rows  # 12 > 11
    assert ("b", 6.0, 3.0) in rows  # 6 > 4 > 3
    # optional tail: c? after the run (greedy, may be absent)
    rows = e.execute_sql("""
        select * from px match_recognize (
          order by sym, d
          measures first(r.price) as p0, last(r.price) as p1
          pattern (r r?)
          define r as true
        ) as m""", s).rows()
    # pairs consumed greedily over the whole (single) partition: 11 rows -> 6
    assert len(rows) == 6


def test_unmatched_optional_variable_is_null(px_engine):
    e, s = px_engine
    rows = e.execute_sql("""
        select * from px match_recognize (
          partition by sym order by d
          measures last(z.price) as spike
          pattern (s z?)
          define z as price > 100
        ) as m order by sym""", s).rows()
    # z never matches: one (s) match per row, spike NULL everywhere
    assert len(rows) == 11 and all(r[1] is None for r in rows)


def test_next_navigation(px_engine):
    e, s = px_engine
    rows = e.execute_sql("""
        select * from px match_recognize (
          partition by sym order by d
          measures first(t.d) as at_day
          pattern (t)
          define t as price < next(price)
        ) as m order by sym, at_day""", s).rows()
    # rows whose NEXT price is higher (one-row matches)
    a_days = [r[1] for r in rows if r[0] == "a"]
    assert a_days == [3, 4]  # 7<9, 9<12


# ---------------------------------------------------------------- round 3
def test_alternation_group(px_engine):
    """(U|D)+ — alternation inside a quantified group (reference: pattern
    alternation, leftmost-preferred): classify every move as up or down."""
    e, s = px_engine
    rows = e.execute_sql("""
        select * from px match_recognize (
          partition by sym order by d
          measures first(m.price) as st, last(u.price) as lastup,
                   last(dn.price) as lastdn
          pattern (m (u|dn)+)
          define u as price > prev(price), dn as price < prev(price)
        ) as x order by sym""", s).rows()
    # one maximal match per partition: every subsequent row is up or down
    assert len(rows) == 2
    a = [r for r in rows if r[0] == "a"][0]
    assert a[1] == 10.0 and a[2] == 12.0 and a[3] == 11.0  # d=6: 11 < 12
    b = [r for r in rows if r[0] == "b"][0]
    assert b[1] == 5.0 and b[2] == 8.0 and b[3] == 3.0


def test_all_rows_per_match(px_engine):
    """ALL ROWS PER MATCH: every matched input row survives with its input
    columns plus RUNNING-semantics measures (the reference's ALL ROWS
    default: each row sees the match only up to itself)."""
    e, s = px_engine
    rows = e.execute_sql("""
        select sym, d, price, low from px match_recognize (
          partition by sym order by d
          measures last(dn.price) as low
          all rows per match
          pattern (st dn+)
          define dn as price < prev(price)
        ) as x order by sym, d""", s).rows()
    # partition a: match rows d=1..3 (10 > 8 > 7); partition b: d=2..4 (6>4>3)
    a_rows = [r for r in rows if r[0] == "a"]
    assert a_rows == [("a", 1, 10.0, None), ("a", 2, 8.0, 8.0),
                      ("a", 3, 7.0, 7.0),
                      ("a", 5, 12.0, None), ("a", 6, 11.0, 11.0)]
    b_rows = [r for r in rows if r[0] == "b"]
    assert b_rows == [("b", 2, 6.0, None), ("b", 3, 4.0, 4.0),
                      ("b", 4, 3.0, 3.0)]


def test_alternation_all_rows_combined(px_engine):
    e, s = px_engine
    rows = e.execute_sql("""
        select sym, d, price from px match_recognize (
          partition by sym order by d
          measures first(m.price) as st
          all rows per match
          pattern (m (u|dn)+)
          define u as price > prev(price), dn as price < prev(price)
        ) as x order by sym, d""", s).rows()
    # the whole series matches in each partition (every step is up or down)
    assert len([r for r in rows if r[0] == "a"]) == 6
    assert len([r for r in rows if r[0] == "b"]) == 5


def test_vectorized_matcher_agrees_with_backtracker():
    """The run-length fast path (ops/matcher.py) must produce byte-identical
    results to the host backtracker on the canonical V-pattern over
    randomized data — and must actually ACTIVATE for it."""
    import numpy as np

    import trino_tpu.ops.matcher as M
    from trino_tpu import Engine
    from trino_tpu.connectors.memory import MemoryConnector

    rng = np.random.default_rng(7)
    rows = []
    for g in range(4):
        price = 100
        for i in range(200):
            price += int(rng.integers(-8, 9))
            rows.append(f"({g}, {i}, {price})")

    def build():
        e = Engine()
        e.register_catalog("mem", MemoryConnector())
        s = e.create_session("mem")
        e.execute_sql("create table ticks (g bigint, t bigint, price bigint)", s)
        e.execute_sql("insert into ticks values " + ", ".join(rows), s)
        return e, s

    sql = """
        select * from ticks match_recognize (
          partition by g order by t
          measures first(down.price) as top, last(down.price) as bottom,
                   last(up.price) as rebound
          pattern (down+ up+)
          define down as price < prev(price), up as price > prev(price)
        ) order by 1, 2
    """
    calls = {"n": 0}
    orig = M.vector_match

    def counting(*a, **kw):
        out = orig(*a, **kw)
        if out is not None:
            calls["n"] += 1
        return out

    M.vector_match = counting
    try:
        e, s = build()
        fast = e.execute_sql(sql, s).to_pandas()
    finally:
        M.vector_match = orig
    assert calls["n"] == 1, "vector path did not activate for DOWN+ UP+"

    M.vector_match = lambda *a, **kw: None  # force the host backtracker
    try:
        e, s = build()
        slow = e.execute_sql(sql, s).to_pandas()
    finally:
        M.vector_match = orig
    assert fast.values.tolist() == slow.values.tolist()
    assert len(fast) > 10  # the data actually contains matches


def test_vectorized_matcher_rejects_overlapping_conditions():
    """A quantified element whose condition overlaps a later element's must
    fall back (greedy backtracking is not run-length arithmetic there)."""
    import numpy as np

    from trino_tpu.ops.matcher import vector_match

    n = 8
    conds = {"a": np.ones(n, bool), "b": np.ones(n, bool)}
    new_part = np.zeros(n, bool)
    new_part[0] = True
    assert vector_match((("a", "+"), ("b", None)), conds, new_part,
                        set()) is None
    # disjoint conditions pass the gate
    conds2 = {"a": np.arange(n) % 2 == 0, "b": np.arange(n) % 2 == 1}
    assert vector_match((("a", "+"), ("b", None)), conds2, new_part,
                        set()) is not None


def test_vectorized_matcher_partition_boundary_clip():
    """A quantified element clipped at a partition boundary must NOT let a
    later element match in the next partition (review-found: the run-length
    chain gathered the next element's run at the next partition's first row)."""
    import numpy as np

    from trino_tpu.ops.matcher import vector_match

    # partitions {0,1,2} and {3,4,5}; A matches rows 1-2 (to partition end),
    # B matches row 3 (the NEXT partition's first row)
    ok_a = np.array([False, True, True, False, False, False])
    ok_b = np.array([False, False, False, True, False, False])
    new_part = np.array([True, False, False, True, False, False])
    vm = vector_match((("a", "+"), ("b", None)),
                      {"a": ok_a, "b": ok_b}, new_part, set())
    assert vm is not None
    assert not vm.usable[1], "match crossed the partition boundary"
    assert not vm.usable.any()
