"""timestamp(p) and char(n) semantics (reference: spi/type/TimestampType
short encoding, spi/type/CharType + Chars.padSpaces; test models:
TestTimestamp, TestCharType in core/trino-main)."""

import datetime

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.memory import MemoryConnector


def _us(v):
    """Decoded timestamp (pandas Timestamp / np.datetime64) -> epoch micros."""
    import pandas as pd

    return int(pd.Timestamp(v).value // 1000)


def _days(v):
    """Decoded date -> epoch days."""
    import pandas as pd

    return int(pd.Timestamp(v).value // (86_400 * 10**9))


@pytest.fixture(scope="module")
def teng():
    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql(
        "create table ev (id bigint, ts timestamp(6), ts3 timestamp(3), "
        "name varchar)", s)
    e.execute_sql("""insert into ev values
        (1, timestamp '2024-03-15 10:30:45.123456',
            timestamp '2024-03-15 10:30:45.123', 'alpha'),
        (2, timestamp '2021-01-01 00:00:00',
            timestamp '2021-01-01 00:00:00', 'beta  '),
        (3, timestamp '1969-12-31 23:59:59',
            timestamp '1969-12-31 23:59:59', 'gamma')""", s)
    return e, s


def _micros(y, mo, d, h=0, mi=0, se=0, us=0):
    dt = datetime.datetime(y, mo, d, h, mi, se, us)
    return round((dt - datetime.datetime(1970, 1, 1)).total_seconds()
                 * 1_000_000)


def test_timestamp_literal_storage_and_comparison(teng):
    e, s = teng
    r = e.execute_sql("select ts from ev where id = 1", s).to_pandas()
    assert _us(r.iloc[0, 0]) == _micros(2024, 3, 15, 10, 30, 45, 123456)
    r = e.execute_sql(
        "select id from ev where ts > timestamp '2023-01-01 00:00:00'",
        s).to_pandas()
    assert r["id"].tolist() == [1]
    # pre-epoch timestamps stay exact
    r = e.execute_sql("select ts from ev where id = 3", s).to_pandas()
    assert _us(r.iloc[0, 0]) == -1_000_000


def test_timestamp_extract_parts(teng):
    e, s = teng
    r = e.execute_sql(
        "select extract(year from ts) y, extract(month from ts) mo, "
        "extract(day from ts) d, extract(hour from ts) h, "
        "extract(minute from ts) mi, extract(second from ts) se, "
        "hour(ts) h2, minute(ts) mi2, second(ts) se2, millisecond(ts) ms "
        "from ev where id = 1", s).to_pandas()
    assert r.iloc[0].tolist() == [2024, 3, 15, 10, 30, 45, 10, 30, 45, 123]


def test_timestamp_precision_cast_rescales(teng):
    e, s = teng
    r = e.execute_sql(
        "select cast(ts as timestamp(3)) t3, cast(ts3 as timestamp(6)) t6, "
        "cast(ts as timestamp(0)) t0 from ev where id = 1", s).to_pandas()
    base = datetime.datetime(2024, 3, 15, 10, 30, 45)
    secs = round((base - datetime.datetime(1970, 1, 1)).total_seconds())
    assert _us(r["t3"].iloc[0]) == (secs * 1000 + 123) * 1000  # .123456 -> .123
    assert _us(r["t6"].iloc[0]) == (secs * 1000 + 123) * 1000
    assert _us(r["t0"].iloc[0]) == secs * 1_000_000  # rounds down at p=0


def test_timestamp_date_casts(teng):
    e, s = teng
    r = e.execute_sql(
        "select cast(ts as date) d, "
        "cast(date '2024-03-15' as timestamp(6)) t from ev where id = 1",
        s).to_pandas()
    days = (datetime.date(2024, 3, 15) - datetime.date(1970, 1, 1)).days
    assert _days(r["d"].iloc[0]) == days
    assert _us(r["t"].iloc[0]) == days * 86400 * 1_000_000
    # pre-epoch: floor to the CIVIL day, not toward zero
    r = e.execute_sql("select cast(ts as date) d from ev where id = 3",
                      s).to_pandas()
    assert _days(r["d"].iloc[0]) == -1


def test_timestamp_group_and_order(teng):
    e, s = teng
    r = e.execute_sql(
        "select id from ev order by ts desc", s).to_pandas()
    assert r["id"].tolist() == [1, 2, 3]


def test_char_cast_pads_and_compares_space_blind(teng):
    e, s = teng
    r = e.execute_sql(
        "select cast(name as char(8)) c from ev order by id", s).to_pandas()
    assert r["c"].tolist() == ["alpha   ", "beta    ", "gamma   "]
    # trailing spaces in the column value are insignificant for char equality
    r = e.execute_sql(
        "select id from ev where cast(name as char(8)) = 'beta'",
        s).to_pandas()
    assert r["id"].tolist() == [2]
    # truncation past the declared length
    r = e.execute_sql(
        "select cast(name as char(3)) c from ev where id = 1", s).to_pandas()
    assert r["c"].iloc[0] == "alp"


def test_current_timestamp_is_sane(teng):
    e, s = teng
    r = e.execute_sql("select current_timestamp() ct from ev where id = 1",
                      s).to_pandas()
    now_us = round((datetime.datetime.now(datetime.timezone.utc)
                    .replace(tzinfo=None)
                    - datetime.datetime(1970, 1, 1)).total_seconds() * 1e6)
    assert abs(_us(r.iloc[0, 0]) - now_us) < 3600 * 1_000_000


def test_pre_epoch_fractional_literal():
    """The fraction advances time FORWARD even before the epoch (review
    regression: 23:59:59.5 was parsed a full second early)."""
    from trino_tpu.types import parse_timestamp_literal

    v, ty = parse_timestamp_literal("1969-12-31 23:59:59.500")
    assert ty.precision == 3
    assert v == -500


def test_char_column_create_insert_compare():
    """char(n) columns created via DDL store space-padded values, so equality
    against unpadded literals works (review regression: stored unpadded)."""
    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table c (k char(3), v bigint)", s)
    e.execute_sql("insert into c values ('ab', 1), ('xyz', 2)", s)
    r = e.execute_sql("select v from c where k = 'ab'", s).to_pandas()
    assert r["v"].tolist() == [1]
    r = e.execute_sql("select k from c order by v", s).to_pandas()
    assert r["k"].tolist() == ["ab ", "xyz"]


def test_finer_literal_never_equals_coarser_column(teng):
    e, s = teng
    # ts3 has millis precision; a micros-precision literal between ticks
    # must NOT equal (comparison happens at the finer precision)
    r = e.execute_sql(
        "select id from ev where ts3 = '2021-01-01 00:00:00.000500'",
        s).to_pandas()
    assert r["id"].tolist() == []
    r = e.execute_sql(
        "select id from ev where ts3 > '2020-12-31 23:59:59.999999'",
        s).to_pandas()
    assert 2 in r["id"].tolist()


def test_timestamp_interval_arithmetic(teng):
    e, s = teng
    r = e.execute_sql(
        "select ts + interval '2' hour a, ts - interval '90' second b "
        "from ev where id = 2", s).to_pandas()
    base = _micros(2021, 1, 1)
    assert _us(r["a"].iloc[0]) == base + 2 * 3600 * 1_000_000
    assert _us(r["b"].iloc[0]) == base - 90 * 1_000_000
    # comparison with shifted bounds
    r = e.execute_sql(
        "select id from ev where ts > timestamp '2021-01-01 00:00:00' "
        "- interval '1' minute and ts < timestamp '2021-01-01 00:00:00' "
        "+ interval '1' minute", s).to_pandas()
    assert r["id"].tolist() == [2]
