"""Exact wide-decimal aggregation: sum(decimal) accumulates in two int64
limbs and recombines exactly past 2^63 (reference: Int128 accumulator state,
spi/type/Int128.java + DecimalSumAggregation.java — the round-3 VERDICT #6
done-criterion: a scaled sum exceeding 2^63 matches the exact oracle)."""

from decimal import Decimal

import numpy as np
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.tpch import TpchConnector


@pytest.fixture()
def big_engine():
    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table big (g bigint, v decimal(18,2))", s)
    # raw scaled values near int64's ceiling: 40 rows of ~8.6e17 raw sums to
    # ~3.5e19 >> 2^63 ~ 9.2e18 — a single int64 accumulator would wrap
    vals = [(i % 2, f"{8_600_000_000_000_000 + i * 7}.25") for i in range(40)]
    rows = ", ".join(f"({g}, {v})" for g, v in vals)
    e.execute_sql(f"insert into big values {rows}", s)
    exact = {}
    for g in (0, 1):
        exact[g] = sum(Decimal(v) for gg, v in vals if gg == g)
    return e, s, exact


def test_wide_sum_exact_global(big_engine):
    e, s, exact = big_engine
    (got,) = e.execute_sql("select sum(v) s from big", s).rows()[0],
    val = got[0]
    assert isinstance(val, Decimal)
    assert val == exact[0] + exact[1]  # EXACT, not a float approximation


def test_wide_sum_exact_group_by(big_engine):
    e, s, exact = big_engine
    rows = e.execute_sql("select g, sum(v) s from big group by g order by g",
                         s).rows()
    assert rows[0][1] == exact[0] and rows[1][1] == exact[1]
    assert isinstance(rows[0][1], Decimal)


def test_wide_avg_exact(big_engine):
    e, s, exact = big_engine
    rows = e.execute_sql("select g, avg(v) a, count(*) c from big "
                         "group by g order by g", s).rows()
    for g, a, c in rows:
        # avg fits the input type; rounding is half-up on the exact sum
        c = int(c)
        scaled = int(exact[g] * 100)
        q, r = divmod(abs(scaled), c)
        expect = (q + (2 * r >= c)) * (1 if scaled >= 0 else -1)
        assert a == pytest.approx(expect / 100)


def test_wide_sum_small_values_stay_int64():
    """Sums that fit int64 keep a plain device-safe column (no object dtype),
    and TPC-H Q1 still matches its oracle through the two-limb path."""
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 12))
    s = e.create_session("tpch")
    r = e.execute_sql(
        "select l_returnflag, sum(l_quantity) q, "
        "sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) ch "
        "from lineitem group by l_returnflag order by l_returnflag", s)
    assert len(r.rows()) == 3
    assert all(isinstance(v, float) for v in r.columns[1])  # decoded decimal


def test_wide_sum_fault_tolerant_and_distributed(big_engine):
    """The two-limb partial sums merge by plain addition across the FTE spool
    and the SPMD exchange."""
    e, s, exact = big_engine
    want = [(0, exact[0]), (1, exact[1])]
    q = "select g, sum(v) s from big group by g order by g"
    assert e.execute_sql(q, s, fault_tolerant=True).rows() == want
    got = e.execute_sql(q, s, distributed=True).rows()
    assert [(g, v) for g, v in got] == want


def test_wide_sum_rehash_growth():
    """Limb accumulators survive hash-table growth: rehash re-inserts them by
    plain addition (_REHASH_KIND covers sum_hi32/sum_lo32)."""
    import jax.numpy as jnp

    from trino_tpu.ops import hashagg
    from trino_tpu.types import BIGINT

    state = hashagg.groupby_init(8, (jnp.int64,),
                                 [(jnp.int64, 0), (jnp.int64, 0)])
    keys = (jnp.arange(6, dtype=jnp.int64),)
    v = 8_600_000_000_000_000_000  # near int64's ceiling
    vals = jnp.full((6,), v, jnp.int64)
    valid = jnp.ones((6,), bool)
    state = hashagg.groupby_insert(state, keys, (BIGINT,), valid,
                                   [(vals, None), (vals, None)],
                                   ["sum_hi32", "sum_lo32"])
    grown = hashagg.rehash(state, 32, ("sum_hi32", "sum_lo32"))
    assert int(hashagg.group_count(grown)) == 6
    _, _, accs = hashagg.compact_groups(grown, 8)
    hi, lo = np.asarray(accs[0])[:6], np.asarray(accs[1])[:6]
    total = sum(int(h) * (1 << 32) + int(l) for h, l in zip(hi, lo))
    assert total == 6 * v  # exact through the growth path


def test_wide_sum_expression_raises_cleanly(big_engine):
    """HAVING/expressions over a >2^63 sum surface a clear unsupported-feature
    error instead of a raw JAX TypeError."""
    e, s, _ = big_engine
    with pytest.raises(NotImplementedError, match="wide-decimal"):
        e.execute_sql("select sum(v) + 1 x from big", s)
