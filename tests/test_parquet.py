"""Parquet connector: scans, projections, nulls, strings, decimals, row-group splits."""

import numpy as np
import pytest


@pytest.fixture()
def pq_dir(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    n = 5000
    rng = np.random.default_rng(7)
    tbl = pa.table({
        "id": pa.array(np.arange(n, dtype=np.int64)),
        "grp": pa.array(rng.integers(0, 5, n).astype(np.int32)),
        "val": pa.array(np.where(np.arange(n) % 11 == 0, None,
                                 rng.normal(size=n).round(3)).tolist(),
                        type=pa.float64()),
        "name": pa.array([None if i % 13 == 0 else f"name-{i % 7}"
                          for i in range(n)]),
        "price": pa.array([round(float(i) / 100, 2) for i in range(n)],
                          type=pa.float64()).cast(pa.decimal128(12, 2)),
        "day": pa.array(np.arange(n, dtype=np.int32) % 1000, type=pa.int32()
                        ).cast(pa.date32()),
    })
    pq.write_table(tbl, tmp_path / "events.parquet", row_group_size=1024)
    return str(tmp_path)


@pytest.fixture()
def pq_engine(pq_dir, tpch_sf001):
    from trino_tpu import Engine
    from trino_tpu.connectors.parquet import ParquetConnector

    e = Engine()
    e.register_catalog("tpch", tpch_sf001)
    e.register_catalog("parquet", ParquetConnector(pq_dir))
    return e


def test_parquet_scan_and_agg(pq_engine):
    r = pq_engine.execute_sql("select count(*) c, sum(id) s from events")
    assert r.columns[0][0] == 5000
    assert r.columns[1][0] == 5000 * 4999 // 2
    r = pq_engine.execute_sql(
        "select grp, count(*) n, count(val) nv from events group by grp order by grp")
    assert len(r) == 5
    assert sum(r.columns[1].tolist()) == 5000
    assert sum(r.columns[2].tolist()) == 5000 - len(range(0, 5000, 11))


def test_parquet_strings_and_nulls(pq_engine):
    r = pq_engine.execute_sql(
        "select name, count(*) n from events group by name order by name nulls last")
    names = r.columns[0].tolist()
    assert names[-1] is None  # NULL group present
    assert set(n for n in names if n is not None) == {f"name-{i}" for i in range(7)}
    r = pq_engine.execute_sql(
        "select count(*) c from events where name = 'name-3'")
    assert r.columns[0][0] > 0
    r = pq_engine.execute_sql("select upper(name) u from events "
                              "where name is not null order by id limit 1")
    assert r.columns[0][0].startswith("NAME-")


def test_parquet_decimal_date(pq_engine):
    r = pq_engine.execute_sql(
        "select sum(price) s from events where day >= date '1970-01-11'")
    # days 10..999 repeated; oracle:
    total = sum(round(i / 100, 2) for i in range(5000) if (i % 1000) >= 10)
    assert abs(r.columns[0][0] - total) < 1e-6


def test_parquet_join_with_tpch(pq_engine):
    r = pq_engine.execute_sql(
        "select count(*) c from events, nation where grp = n_nationkey")
    assert r.columns[0][0] == 5000  # every grp in 0..4 matches one nation


def test_parquet_write_roundtrip(pq_engine, pq_dir):
    res = pq_engine.execute_sql(
        "select n_name, n_regionkey from nation where n_regionkey = 2")
    conn = pq_engine.catalogs["parquet"]
    conn.write_table("asia", res.names, res.types, [c.tolist() for c in res.columns])
    r = pq_engine.execute_sql("select count(*) c from asia")
    assert r.columns[0][0] == 5
    r = pq_engine.execute_sql("select n_name from asia order by n_name limit 1")
    assert r.columns[0][0] == "CHINA"


def test_dictionary_id_decode_path(tmp_path):
    """String columns decode through parquet dictionary INDICES (no per-row
    python): local ids remap to table-wide ids via a per-distinct LUT
    (reference: trino-parquet dictionary-aware readers -> DictionaryBlock)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from trino_tpu import Engine
    from trino_tpu.connectors.parquet import ParquetConnector

    n = 5000
    vals = ["x", "yy", "zzz", None]
    pq.write_table(
        pa.table({"s": pa.array([vals[i % 4] for i in range(n)]).dictionary_encode(),
                  "k": pa.array(np.arange(n) % 7)}),
        str(tmp_path / "t.parquet"), row_group_size=1000)
    e = Engine()
    e.register_catalog("pq", ParquetConnector(str(tmp_path)))
    s = e.create_session("pq")
    rows = e.execute_sql(
        "select s, count(*) c from t group by s order by s nulls last", s).rows()
    assert rows == [("x", 1250), ("yy", 1250), ("zzz", 1250), (None, 1250)]
    # ids survive into predicates (dictionary-domain comparison)
    rows = e.execute_sql("select count(*) c from t where s = 'yy'", s).rows()
    assert rows == [(1250,)]


def test_decimal_buffer_decode(tmp_path):
    """Short decimals decode from the raw 16-byte buffer (low-word int64),
    exact for >15-significant-digit values that a float64 path would corrupt."""
    import decimal

    import pyarrow as pa
    import pyarrow.parquet as pq

    from trino_tpu import Engine
    from trino_tpu.connectors.parquet import ParquetConnector

    vals = [decimal.Decimal("12345678901234.56"), decimal.Decimal("-0.01"),
            None, decimal.Decimal("99999999999999.99")]
    pq.write_table(pa.table({"d": pa.array(vals, pa.decimal128(16, 2))}),
                   str(tmp_path / "d.parquet"))
    e = Engine()
    e.register_catalog("pq", ParquetConnector(str(tmp_path)))
    s = e.create_session("pq")
    rows = e.execute_sql("select sum(d) s, min(d) mn, count(d) c from d", s).rows()
    assert abs(rows[0][0] - (12345678901234.56 - 0.01 + 99999999999999.99)) < 0.5
    assert rows[0][1] == -0.01 and rows[0][2] == 3


def test_parquet_ctas_target(tmp_path):
    """CREATE TABLE AS writes a parquet file through the connector's pending-
    schema + append surface; the new table reads back through the device path."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from trino_tpu import Engine
    from trino_tpu.connectors.parquet import ParquetConnector

    pq.write_table(pa.table({"g": pa.array(["a", "b"] * 50),
                             "v": pa.array(np.arange(100))}),
                   str(tmp_path / "src.parquet"))
    e = Engine()
    e.register_catalog("pq", ParquetConnector(str(tmp_path)))
    s = e.create_session("pq")
    e.execute_sql("create table agg as select g, sum(v) sv from src group by g", s)
    assert (tmp_path / "agg.parquet").exists()
    rows = e.execute_sql("select g, sv from agg order by g", s).rows()
    assert rows == [("a", sum(range(0, 100, 2))), ("b", sum(range(1, 100, 2)))]


def test_parquet_create_insert_decimal_roundtrip(tmp_path):
    """Plain CREATE TABLE writes a scannable empty file; INSERT appends with
    exact decimal rescale (regression: CTAS decimals persisted 100x; bare
    CREATE left an unscannable phantom table)."""
    import decimal

    import pyarrow as pa
    import pyarrow.parquet as pq

    from trino_tpu import Engine
    from trino_tpu.connectors.parquet import ParquetConnector

    pq.write_table(pa.table({"d": pa.array([decimal.Decimal("1234.56")],
                                           pa.decimal128(18, 2))}),
                   str(tmp_path / "src.parquet"))
    e = Engine()
    e.register_catalog("pq", ParquetConnector(str(tmp_path)))
    s = e.create_session("pq")
    e.execute_sql("create table out as select d from src", s)
    assert e.execute_sql("select d from out", s).rows() == [(1234.56,)]
    e.execute_sql("create table t2 (x bigint, d decimal(10,2), s varchar)", s)
    assert e.execute_sql("select count(*) c from t2", s).rows() == [(0,)]
    e.execute_sql("insert into t2 values (1, 9.75, 'hello'), (2, null, null)", s)
    e.execute_sql("insert into t2 values (3, 1.25, 'hello')", s)
    rows = e.execute_sql("select x, d, s from t2 order by x", s).rows()
    assert rows == [(1, 9.75, "hello"), (2, None, None), (3, 1.25, "hello")]
