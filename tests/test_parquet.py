"""Parquet connector: scans, projections, nulls, strings, decimals, row-group splits."""

import numpy as np
import pytest


@pytest.fixture()
def pq_dir(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    n = 5000
    rng = np.random.default_rng(7)
    tbl = pa.table({
        "id": pa.array(np.arange(n, dtype=np.int64)),
        "grp": pa.array(rng.integers(0, 5, n).astype(np.int32)),
        "val": pa.array(np.where(np.arange(n) % 11 == 0, None,
                                 rng.normal(size=n).round(3)).tolist(),
                        type=pa.float64()),
        "name": pa.array([None if i % 13 == 0 else f"name-{i % 7}"
                          for i in range(n)]),
        "price": pa.array([round(float(i) / 100, 2) for i in range(n)],
                          type=pa.float64()).cast(pa.decimal128(12, 2)),
        "day": pa.array(np.arange(n, dtype=np.int32) % 1000, type=pa.int32()
                        ).cast(pa.date32()),
    })
    pq.write_table(tbl, tmp_path / "events.parquet", row_group_size=1024)
    return str(tmp_path)


@pytest.fixture()
def pq_engine(pq_dir, tpch_sf001):
    from trino_tpu import Engine
    from trino_tpu.connectors.parquet import ParquetConnector

    e = Engine()
    e.register_catalog("tpch", tpch_sf001)
    e.register_catalog("parquet", ParquetConnector(pq_dir))
    return e


def test_parquet_scan_and_agg(pq_engine):
    r = pq_engine.execute_sql("select count(*) c, sum(id) s from events")
    assert r.columns[0][0] == 5000
    assert r.columns[1][0] == 5000 * 4999 // 2
    r = pq_engine.execute_sql(
        "select grp, count(*) n, count(val) nv from events group by grp order by grp")
    assert len(r) == 5
    assert sum(r.columns[1].tolist()) == 5000
    assert sum(r.columns[2].tolist()) == 5000 - len(range(0, 5000, 11))


def test_parquet_strings_and_nulls(pq_engine):
    r = pq_engine.execute_sql(
        "select name, count(*) n from events group by name order by name nulls last")
    names = r.columns[0].tolist()
    assert names[-1] is None  # NULL group present
    assert set(n for n in names if n is not None) == {f"name-{i}" for i in range(7)}
    r = pq_engine.execute_sql(
        "select count(*) c from events where name = 'name-3'")
    assert r.columns[0][0] > 0
    r = pq_engine.execute_sql("select upper(name) u from events "
                              "where name is not null order by id limit 1")
    assert r.columns[0][0].startswith("NAME-")


def test_parquet_decimal_date(pq_engine):
    r = pq_engine.execute_sql(
        "select sum(price) s from events where day >= date '1970-01-11'")
    # days 10..999 repeated; oracle:
    total = sum(round(i / 100, 2) for i in range(5000) if (i % 1000) >= 10)
    assert abs(r.columns[0][0] - total) < 1e-6


def test_parquet_join_with_tpch(pq_engine):
    r = pq_engine.execute_sql(
        "select count(*) c from events, nation where grp = n_nationkey")
    assert r.columns[0][0] == 5000  # every grp in 0..4 matches one nation


def test_parquet_write_roundtrip(pq_engine, pq_dir):
    res = pq_engine.execute_sql(
        "select n_name, n_regionkey from nation where n_regionkey = 2")
    conn = pq_engine.catalogs["parquet"]
    conn.write_table("asia", res.names, res.types, [c.tolist() for c in res.columns])
    r = pq_engine.execute_sql("select count(*) c from asia")
    assert r.columns[0][0] == 5
    r = pq_engine.execute_sql("select n_name from asia order by n_name limit 1")
    assert r.columns[0][0] == "CHINA"
