"""DB-API federation connector (the JDBC-family analog; reference:
plugin/trino-base-jdbc BaseJdbcClient) over sqlite3."""

import sqlite3

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.dbapi import DbapiConnector
from trino_tpu.connectors.tpch import TpchConnector


@pytest.fixture(scope="module")
def remote_db(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("db") / "remote.db")
    con = sqlite3.connect(path)
    con.execute("create table users (uid integer, region integer, "
                "name text, balance real)")
    rows = [(i, i % 5, None if i % 11 == 0 else f"user-{i % 7}",
             round(i * 1.5, 2)) for i in range(1000)]
    con.executemany("insert into users values (?,?,?,?)", rows)
    con.execute("create table tiny (k integer, v text)")
    con.executemany("insert into tiny values (?,?)",
                    [(1, "a"), (2, "b"), (3, None)])
    con.commit()
    con.close()
    return path


@pytest.fixture(scope="module")
def fed_engine(remote_db):
    e = Engine()
    e.register_catalog("db", DbapiConnector(
        lambda: sqlite3.connect(remote_db), split_rows=256))
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 12))
    return e, e.create_session("db")


def test_remote_scan_and_aggregate(fed_engine):
    e, s = fed_engine
    rows = e.execute_sql(
        "select region, count(*) c, sum(balance) sb from users "
        "group by region order by region", s).rows()
    assert len(rows) == 5
    assert sum(r[1] for r in rows) == 1000
    assert rows[0][2] == pytest.approx(sum(i * 1.5 for i in range(0, 1000, 5)))


def test_remote_strings_and_nulls(fed_engine):
    e, s = fed_engine
    rows = e.execute_sql(
        "select name, count(*) c from users group by name "
        "order by name nulls last", s).rows()
    names = [r[0] for r in rows]
    assert names[-1] is None  # the NULL group survives
    assert set(n for n in names if n is not None) == \
        {f"user-{i}" for i in range(7)}
    assert e.execute_sql("select v from tiny where k = 3", s).rows() == \
        [(None,)]


def test_remote_federated_join(fed_engine):
    """A remote table joins a generator-connector table — cross-catalog
    federation through the shared page machinery."""
    e, s = fed_engine
    rows = e.execute_sql(
        "select count(*) c from db.users, tpch.nation "
        "where users.region = nation.n_regionkey and users.uid < 100",
        s).rows()
    assert rows == [(100 * 5,)]


def test_remote_metadata_and_splits(fed_engine, remote_db):
    e, s = fed_engine
    conn = e.catalogs["db"]
    assert conn.tables() == ["tiny", "users"]
    assert conn.row_count("users") == 1000
    assert conn.column_range("users", "uid") == (0, 999)
    splits = conn.splits("users")
    assert sum(1 for _ in splits) >= 4  # rowid ranges cover the table
    # churn detection: a new string value after the snapshot errors clearly
    import sqlite3 as _sq
    con = _sq.connect(remote_db)
    con.execute("update tiny set v='brand-new' where k=1")
    con.commit(); con.close()
    with pytest.raises(RuntimeError, match="changed since"):
        for sp in conn.splits("tiny"):
            conn.generate(sp, ["v"])
    with pytest.raises(ValueError, match="unsupported remote identifier"):
        conn.column_range('users"; drop table users; --', "uid")


# ------------------------------------------- applyTopN / applyJoin pushdown
def test_topn_pushdown_ships_n_rows(fed_engine):
    """Limit(Sort(scan)) over the federation connector issues ORDER BY ...
    LIMIT remotely (ConnectorMetadata.applyTopN analog): results identical,
    the pushed handle visible, and the remote read bounded."""
    e, s = fed_engine
    conn = e.catalogs["db"]
    before = conn.pushed_queries
    rows = e.execute_sql(
        "select uid, balance from users order by balance desc, uid limit 7",
        s).rows()
    assert conn.pushed_queries > before, "topN did not push to the remote"
    assert len(rows) == 7
    assert [r[0] for r in rows] == list(range(999, 992, -1))
    # exactness is preserved by the local Sort+Limit above the pushed scan
    assert rows[0][1] == pytest.approx(999 * 1.5)


def test_topn_pushdown_respects_nulls_ordering(fed_engine):
    e, s = fed_engine
    rows = e.execute_sql(
        "select name from users order by name desc nulls last limit 3",
        s).rows()
    assert all(r[0] is not None for r in rows)
    assert rows[0][0] == "user-6"


def _undo_churn(fed_engine, remote_db):
    """test_metadata_surfaces mutates tiny.v past its dictionary snapshot on
    purpose; restore the value and refresh the snapshot for the join tests."""
    import sqlite3 as _sq

    e, _ = fed_engine
    con = _sq.connect(remote_db)
    con.execute("update tiny set v='a' where k=1")
    con.commit()
    con.close()
    e.catalogs["db"]._tables.pop("tiny", None)


def test_join_pushdown_runs_remotely(fed_engine, remote_db):
    """An inner equi-join of two tables in the SAME remote database executes
    there (ConnectorMetadata.applyJoin analog); the engine scans the joined
    handle, split-parallel over the left side."""
    _undo_churn(fed_engine, remote_db)
    e, s = fed_engine
    conn = e.catalogs["db"]
    sql = ("select u.uid, u.balance, t.v from users u "
           "join tiny t on u.region = t.k "
           "order by u.uid limit 10")
    before = conn.pushed_queries
    got = e.execute_sql(sql, s).rows()
    assert conn.pushed_queries > before, "join did not push to the remote"
    # oracle: region in (1,2,3) joins tiny's k; v maps 1->a, 2->b, 3->NULL
    import sqlite3

    vmap = {1: "a", 2: "b", 3: None}
    want = [(i, i * 1.5, vmap[i % 5]) for i in range(1000)
            if i % 5 in vmap][:10]
    assert [(r[0], round(r[1], 2), r[2]) for r in got] \
        == [(u, round(b, 2), v) for u, b, v in want]


def test_join_pushdown_access_checks_source_tables(fed_engine):
    """The virtual handle is not a grantable object: access control checks
    the SOURCE tables, so a denial on either side still blocks the query."""
    e, s = fed_engine
    from trino_tpu.spi.security import AccessDeniedError

    class DenyTiny:
        def check_can_select(self, user, catalog, table):
            if table == "tiny":
                raise AccessDeniedError("tiny is restricted")

        def __getattr__(self, name):  # every other check allows
            return lambda *a, **k: None

    saved = e.access_control
    e.access_control = DenyTiny()
    try:
        with pytest.raises(AccessDeniedError):
            e.execute_sql("select u.uid from users u "
                          "join tiny t on u.region = t.k limit 1", s)
    finally:
        e.access_control = saved


def test_filter_blocks_join_pushdown(fed_engine, remote_db):
    """A residual filter above a side keeps the join local (the applyJoin
    contract) — results still correct, no push recorded."""
    _undo_churn(fed_engine, remote_db)
    e, s = fed_engine
    conn = e.catalogs["db"]
    before = conn.pushed_queries
    got = e.execute_sql(
        "select count(*) c from users u join tiny t on u.region = t.k "
        "where u.balance > 100 and t.v = 'a'", s).rows()
    want = sum(1 for i in range(1000)
               if i % 5 == 1 and i * 1.5 > 100)
    assert int(got[0][0]) == want


def test_pushed_spec_travels_with_split(fed_engine, remote_db):
    """A WORKER process builds its own connector and never saw the planning
    pass: the virtual-handle spec rides the split (pickled), so the scan
    reconstructs remotely (review finding: handles lived only in the
    planner's registry)."""
    import pickle
    import sqlite3 as _sq

    _undo_churn(fed_engine, remote_db)
    e, s = fed_engine
    conn = e.catalogs["db"]
    handle = conn.apply_join("users", "tiny", [("region", "k")],
                             ["l0", "l1", "r0"], ["uid", "region"], ["v"])
    splits = conn.splits(handle)
    assert splits and splits[0].pushed_spec is not None
    # fresh instance = the worker's connector (no _pushed state)
    worker_conn = DbapiConnector(lambda: _sq.connect(remote_db),
                                 split_rows=256)
    sp = pickle.loads(pickle.dumps(splits[0]))
    page = worker_conn.generate(sp, ["l0", "r0"])
    assert page.columns[0].shape[0] > 0
    # deduped registration: same spec returns the same handle
    again = conn.apply_join("users", "tiny", [("region", "k")],
                            ["l0", "l1", "r0"], ["uid", "region"], ["v"])
    assert again == handle


@pytest.fixture()
def probe_catalog(fed_engine):
    e, _ = fed_engine
    if "m2" not in e.catalogs:
        from trino_tpu.connectors.memory import MemoryConnector

        e.register_catalog("m2", MemoryConnector())
        sm = e.create_session("m2")
        e.execute_sql("create table probe (uid bigint, tag bigint)", sm)
        e.execute_sql("insert into probe values (5, 1), (9, 2), (5, 3), "
                      "(700, 4)", sm)
    return e


def test_index_join_lookup(fed_engine, probe_catalog):
    """Index join (reference: operator/index/IndexLoader): a small local
    probe ships its distinct keys into a remote WHERE-IN lookup instead of
    scanning the whole remote table."""
    e, s = fed_engine
    conn = e.catalogs["db"]
    before = conn.pushed_queries
    r = e.execute_sql(
        "select p.uid, p.tag, u.balance from m2.default.probe p, "
        "db.default.users u where p.uid = u.uid order by p.tag", s).to_pandas()
    assert list(r["tag"]) == [1, 2, 3, 4]
    assert abs(r["balance"].iloc[0] - 7.5) < 1e-9
    assert abs(r["balance"].iloc[3] - 1050.0) < 1e-9
    # the build side went through a pushed index-lookup handle
    assert conn.pushed_queries > before
    spec = list(conn._pushed.values())[-1]
    assert spec["kind"] == "index"
    assert sorted(spec["keys"]) == [5, 9, 700]


def test_index_join_disabled_env(fed_engine, probe_catalog, monkeypatch):
    e, s = fed_engine
    conn = e.catalogs["db"]
    monkeypatch.setenv("TRINO_TPU_INDEX_JOIN", "0")
    before = conn.pushed_queries
    n_handles = len(conn._pushed)
    r = e.execute_sql(
        "select count(*) c from m2.default.probe p, db.default.users u "
        "where p.uid = u.uid", s).to_pandas()
    assert r["c"].iloc[0] == 4
    # the kill switch must actually suppress the pushdown, not just
    # coincidentally produce the right count
    assert conn.pushed_queries == before
    assert len(conn._pushed) == n_handles
