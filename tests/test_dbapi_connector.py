"""DB-API federation connector (the JDBC-family analog; reference:
plugin/trino-base-jdbc BaseJdbcClient) over sqlite3."""

import sqlite3

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.dbapi import DbapiConnector
from trino_tpu.connectors.tpch import TpchConnector


@pytest.fixture(scope="module")
def remote_db(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("db") / "remote.db")
    con = sqlite3.connect(path)
    con.execute("create table users (uid integer, region integer, "
                "name text, balance real)")
    rows = [(i, i % 5, None if i % 11 == 0 else f"user-{i % 7}",
             round(i * 1.5, 2)) for i in range(1000)]
    con.executemany("insert into users values (?,?,?,?)", rows)
    con.execute("create table tiny (k integer, v text)")
    con.executemany("insert into tiny values (?,?)",
                    [(1, "a"), (2, "b"), (3, None)])
    con.commit()
    con.close()
    return path


@pytest.fixture(scope="module")
def fed_engine(remote_db):
    e = Engine()
    e.register_catalog("db", DbapiConnector(
        lambda: sqlite3.connect(remote_db), split_rows=256))
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 12))
    return e, e.create_session("db")


def test_remote_scan_and_aggregate(fed_engine):
    e, s = fed_engine
    rows = e.execute_sql(
        "select region, count(*) c, sum(balance) sb from users "
        "group by region order by region", s).rows()
    assert len(rows) == 5
    assert sum(r[1] for r in rows) == 1000
    assert rows[0][2] == pytest.approx(sum(i * 1.5 for i in range(0, 1000, 5)))


def test_remote_strings_and_nulls(fed_engine):
    e, s = fed_engine
    rows = e.execute_sql(
        "select name, count(*) c from users group by name "
        "order by name nulls last", s).rows()
    names = [r[0] for r in rows]
    assert names[-1] is None  # the NULL group survives
    assert set(n for n in names if n is not None) == \
        {f"user-{i}" for i in range(7)}
    assert e.execute_sql("select v from tiny where k = 3", s).rows() == \
        [(None,)]


def test_remote_federated_join(fed_engine):
    """A remote table joins a generator-connector table — cross-catalog
    federation through the shared page machinery."""
    e, s = fed_engine
    rows = e.execute_sql(
        "select count(*) c from db.users, tpch.nation "
        "where users.region = nation.n_regionkey and users.uid < 100",
        s).rows()
    assert rows == [(100 * 5,)]


def test_remote_metadata_and_splits(fed_engine, remote_db):
    e, s = fed_engine
    conn = e.catalogs["db"]
    assert conn.tables() == ["tiny", "users"]
    assert conn.row_count("users") == 1000
    assert conn.column_range("users", "uid") == (0, 999)
    splits = conn.splits("users")
    assert sum(1 for _ in splits) >= 4  # rowid ranges cover the table
    # churn detection: a new string value after the snapshot errors clearly
    import sqlite3 as _sq
    con = _sq.connect(remote_db)
    con.execute("update tiny set v='brand-new' where k=1")
    con.commit(); con.close()
    with pytest.raises(RuntimeError, match="changed since"):
        for sp in conn.splits("tiny"):
            conn.generate(sp, ["v"])
    with pytest.raises(ValueError, match="unsupported remote identifier"):
        conn.column_range('users"; drop table users; --', "uid")
