"""Second aggregate batch: max_by/min_by, array_agg, histogram, map_agg,
checksum, bitwise_*_agg (reference: operator/aggregation/minmaxby/,
ArrayAggregation, MapHistogramAggregation, MapAggAggregation,
ChecksumAggregationFunction, BitwiseAndAggregation test models)."""

import numpy as np
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.memory import MemoryConnector


@pytest.fixture(scope="module")
def aeng():
    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table t (g bigint, n bigint, x double, s varchar)", s)
    e.execute_sql("""insert into t values
        (1, 10, 1.5, 'a'),
        (1, 30, 0.5, 'b'),
        (1, 20, 2.5, 'c'),
        (2, 7,  9.0, 'd'),
        (2, 5,  8.0, 'd'),
        (3, null, 1.0, null)""", s)
    return e, s


def _rows(aeng, sql):
    e, s = aeng
    return e.execute_sql(sql, s).to_pandas()


def test_max_by_min_by(aeng):
    r = _rows(aeng, "select g, max_by(s, n) mx, min_by(s, n) mn from t "
                    "group by g order by g")
    assert list(r["mx"])[:2] == ["b", "d"]
    assert list(r["mn"]) [:2]== ["a", "d"]
    # group 3: ranking value all NULL -> NULL payload
    assert r["mx"].iloc[2] is None or r["mx"].isna().iloc[2]


def test_max_by_numeric_payload(aeng):
    r = _rows(aeng, "select g, max_by(x, n) v from t group by g order by g")
    assert list(r["v"])[:2] == [0.5, 9.0]


def test_max_by_global(aeng):
    r = _rows(aeng, "select max_by(s, n) v from t")
    assert r["v"].iloc[0] == "b"


def test_array_agg(aeng):
    r = _rows(aeng, "select g, array_agg(n) a from t group by g order by g")
    assert sorted(r["a"].iloc[0]) == [10, 20, 30]
    assert sorted(r["a"].iloc[1]) == [5, 7]
    assert r["a"].iloc[2] is None or not isinstance(r["a"].iloc[2], list)


def test_array_agg_strings(aeng):
    r = _rows(aeng, "select g, array_agg(s) a from t group by g order by g")
    assert sorted(r["a"].iloc[0]) == ["a", "b", "c"]


def test_histogram(aeng):
    r = _rows(aeng, "select g, histogram(s) h from t group by g order by g")
    assert r["h"].iloc[0] == {"a": 1, "b": 1, "c": 1}
    assert r["h"].iloc[1] == {"d": 2}


def test_map_agg(aeng):
    r = _rows(aeng, "select g, map_agg(s, n) m from t group by g order by g")
    assert r["m"].iloc[0] == {"a": 10, "b": 30, "c": 20}
    # duplicate key 'd': first value kept (documented deviation)
    assert set(r["m"].iloc[1].keys()) == {"d"}


def test_checksum(aeng):
    r = _rows(aeng, "select g, checksum(n) c from t group by g order by g")
    # deterministic, order-insensitive, non-trivial
    r2 = _rows(aeng, "select g, checksum(n) c from (select * from t order by n desc) "
                     "group by g order by g")
    assert list(r["c"])[:2] == list(r2["c"])[:2]
    assert r["c"].iloc[0] != r["c"].iloc[1]
    # all-NULL group -> NULL
    assert r["c"].isna().iloc[2]


def test_checksum_global_mixes_with_others(aeng):
    r = _rows(aeng, "select checksum(n) c, count(*) k, sum(n) s from t")
    assert r["k"].iloc[0] == 6
    assert r["s"].iloc[0] == 72
    assert not r["c"].isna().iloc[0]


def test_bitwise_aggs(aeng):
    r = _rows(aeng, "select g, bitwise_and_agg(n) a, bitwise_or_agg(n) o, "
                    "bitwise_xor_agg(n) x from t group by g order by g")
    assert list(r["a"])[:2] == [10 & 30 & 20, 7 & 5]
    assert list(r["o"])[:2] == [10 | 30 | 20, 7 | 5]
    assert list(r["x"])[:2] == [10 ^ 30 ^ 20, 7 ^ 5]
    assert r["a"].isna().iloc[2]


def test_max_by_string_ranking_is_lexicographic(aeng):
    """Dictionary ids are insertion-ordered; the ranking must follow VALUES
    (code-review catch: 'zebra' inserted first must still rank highest)."""
    e, s = aeng
    e.execute_sql("create table rk (p varchar, s varchar)", s)
    e.execute_sql("insert into rk values ('pz', 'zebra'), ('pa', 'apple'), "
                  "('pm', 'mango')", s)
    r = e.execute_sql("select max_by(p, s) mx, min_by(p, s) mn from rk",
                      s).to_pandas()
    assert r["mx"].iloc[0] == "pz"
    assert r["mn"].iloc[0] == "pa"


def test_checksum_distributed_matches_local():
    """Distributed accumulators must hash checksum inputs exactly like the
    local path (code-review catch: raw-sum drift on the mesh)."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual mesh")
    from trino_tpu.connectors.tpch import TpchConnector

    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01))
    s = e.create_session("tpch")
    sql = ("select l_returnflag, checksum(l_quantity) c from lineitem "
           "group by l_returnflag order by l_returnflag")
    dist = e.execute_sql(sql, s, distributed=True).to_pandas()
    local = e.execute_sql(sql, s).to_pandas()
    assert list(dist["c"]) == list(local["c"])


def test_sorted_aggs_mix_with_hash_aggs(aeng):
    """max_by + count/sum in ONE query: planned as per-part aggregations
    joined on the group keys (the mixed-distinct composition)."""
    r = _rows(aeng, "select g, max_by(s, n) mx, count(*) k, sum(n) t "
                    "from t group by g order by g")
    assert list(r["mx"])[:2] == ["b", "d"]
    assert list(r["k"]) == [3, 2, 1]
    assert list(r["t"])[:2] == [60, 12]


def test_sorted_agg_mix_global(aeng):
    r = _rows(aeng, "select max_by(s, n) mx, count(*) k from t")
    assert r["mx"].iloc[0] == "b"
    assert r["k"].iloc[0] == 6


def test_sorted_agg_all_rows_filtered_out(aeng):
    """Filters mask lanes without shrinking pages; g==0 with GROUP BY keys
    must still emit an arity-correct (empty) result (code-review catch)."""
    r = _rows(aeng, "select g, max_by(s, n) mx from t where n > 100 group by g")
    assert len(r) == 0
    assert list(r.columns) == ["g", "mx"]
    r = _rows(aeng, "select g, approx_percentile(x, 0.5) p from t "
                    "where n > 100 group by g")
    assert len(r) == 0 and list(r.columns) == ["g", "p"]


def test_mixed_sorted_distinct_rejected(aeng):
    e, s = aeng
    with pytest.raises(Exception, match="DISTINCT"):
        e.execute_sql("select g, approx_distinct(n), max_by(s, n) from t "
                      "group by g", s)


def test_agg_arity_errors(aeng):
    e, s = aeng
    for bad in ("checksum()", "histogram()", "array_agg()"):
        with pytest.raises(Exception, match="argument"):
            e.execute_sql(f"select {bad} from t", s)


def test_wilson_z_zero(aeng):
    e, s = aeng
    r = e.execute_sql("select wilson_interval_lower(20, 100, 0) lo, "
                      "wilson_interval_upper(20, 100, 0) hi from t "
                      "where n = 5", s).to_pandas()
    assert abs(r["lo"].iloc[0] - 0.2) < 1e-12
    assert abs(r["hi"].iloc[0] - 0.2) < 1e-12


def test_device_topn_null_ties_break_on_secondary_key(aeng):
    """NULL primary-key rows must order by the secondary key, not by their
    arbitrary lane fill values (code-review catch on the device TopN)."""
    e, s = aeng
    e.execute_sql("create table nt (a bigint, b bigint)", s)
    e.execute_sql("insert into nt values (null, 3), (null, 1), (null, 2), "
                  "(5, 0), (6, 0)", s)
    r = e.execute_sql("select a, b from nt order by a nulls first, b limit 3",
                      s).to_pandas()
    assert list(r["b"]) == [1, 2, 3]
    assert r["a"].isna().all()


def test_show_functions_has_new_aggs(aeng):
    e, s = aeng
    r = e.execute_sql("show functions", s).to_pandas()
    names = set(r.iloc[:, 0])
    for n in ("max_by", "min_by", "array_agg", "histogram", "map_agg",
              "checksum", "bitwise_and_agg"):
        assert n in names, n
