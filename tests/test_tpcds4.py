"""TPC-DS query breadth, round 5 batch 2: demographic band predicates,
inventory pivots, channel set-ops (INTERSECT/EXCEPT), correlated
excess-discount, order-shipping semi/anti joins, income-band lookups.
Reference corpus: testing/trino-benchmark-queries/ + plugin/trino-tpcds."""

import numpy as np
import pandas as pd
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpcds import TpcdsConnector

from test_tpcds2 import _table
from test_tpcds3 import _check

SF = 0.01


def _dec2(x):
    """Engine avg over scale-2 decimals rounds HALF_UP to scale 2; mirror it
    so float means compare exactly."""
    return np.floor(np.asarray(x, dtype=float) * 100 + 0.5) / 100


@pytest.fixture(scope="module")
def eng():
    e = Engine()
    e.register_catalog("tpcds", TpcdsConnector(sf=SF, split_rows=1 << 14))
    return e, e.create_session("tpcds")


@pytest.fixture(scope="module")
def host(eng):
    e, _ = eng
    conn = e.catalogs["tpcds"]
    return {
        "store_sales": _table(conn, "store_sales", [
            "ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_customer_sk",
            "ss_cdemo_sk", "ss_hdemo_sk", "ss_addr_sk", "ss_ticket_number",
            "ss_quantity", "ss_list_price", "ss_sales_price",
            "ss_ext_sales_price", "ss_ext_wholesale_cost", "ss_coupon_amt",
            "ss_net_profit"]),
        "store_returns": _table(conn, "store_returns", [
            "sr_returned_date_sk", "sr_item_sk", "sr_customer_sk",
            "sr_ticket_number", "sr_return_quantity", "sr_reason_sk"]),
        "catalog_sales": _table(conn, "catalog_sales", [
            "cs_sold_date_sk", "cs_item_sk", "cs_bill_customer_sk",
            "cs_warehouse_sk", "cs_order_number", "cs_quantity",
            "cs_sales_price", "cs_ext_discount_amt", "cs_ext_sales_price",
            "cs_ext_ship_cost", "cs_net_profit"]),
        "catalog_returns": _table(conn, "catalog_returns", [
            "cr_returned_date_sk", "cr_item_sk", "cr_order_number",
            "cr_return_quantity", "cr_return_amount", "cr_call_center_sk",
            "cr_returning_customer_sk"]),
        "web_sales": _table(conn, "web_sales", [
            "ws_sold_date_sk", "ws_ship_date_sk", "ws_item_sk",
            "ws_bill_customer_sk", "ws_warehouse_sk", "ws_order_number",
            "ws_ext_ship_cost", "ws_net_profit", "ws_ext_sales_price",
            "ws_ship_addr_sk", "ws_web_site_sk"]),
        "web_returns": _table(conn, "web_returns", [
            "wr_order_number", "wr_item_sk", "wr_return_amt",
            "wr_returning_customer_sk", "wr_returned_date_sk",
            "wr_refunded_cdemo_sk", "wr_reason_sk", "wr_return_quantity"]),
        "inventory": _table(conn, "inventory", [
            "inv_date_sk", "inv_item_sk", "inv_warehouse_sk",
            "inv_quantity_on_hand"]),
        "item": _table(conn, "item", [
            "i_item_sk", "i_item_id", "i_item_desc", "i_current_price",
            "i_manufact_id", "i_category", "i_brand", "i_color",
            "i_product_name", "i_manager_id"]),
        "date_dim": _table(conn, "date_dim", [
            "d_date_sk", "d_year", "d_moy", "d_month_seq", "d_qoy",
            "d_dom"]),
        "customer": _table(conn, "customer", [
            "c_customer_sk", "c_customer_id", "c_current_cdemo_sk",
            "c_current_hdemo_sk", "c_current_addr_sk", "c_first_name",
            "c_last_name"]),
        "customer_address": _table(conn, "customer_address", [
            "ca_address_sk", "ca_city", "ca_state", "ca_country"]),
        "customer_demographics": _table(conn, "customer_demographics", [
            "cd_demo_sk", "cd_gender", "cd_marital_status",
            "cd_education_status", "cd_dep_count"]),
        "household_demographics": _table(conn, "household_demographics", [
            "hd_demo_sk", "hd_income_band_sk", "hd_dep_count",
            "hd_vehicle_count", "hd_buy_potential"]),
        "income_band": _table(conn, "income_band", [
            "ib_income_band_sk", "ib_lower_bound", "ib_upper_bound"]),
        "warehouse": _table(conn, "warehouse", [
            "w_warehouse_sk", "w_warehouse_name", "w_state"]),
        "call_center": _table(conn, "call_center", [
            "cc_call_center_sk", "cc_name", "cc_manager"]),
        "reason": _table(conn, "reason", ["r_reason_sk", "r_reason_desc"]),
    }


def test_q13_demographic_band_averages(eng, host):
    """Q13 shape: averages under OR'd demographic bands."""
    e, s = eng
    got = e.execute_sql("""
        select avg(ss_quantity) aq, avg(ss_ext_sales_price) ap,
               sum(ss_ext_wholesale_cost) sw
        from store_sales, customer_demographics, household_demographics,
             date_dim
        where ss_cdemo_sk = cd_demo_sk and ss_hdemo_sk = hd_demo_sk
          and ss_sold_date_sk = d_date_sk and d_year = 2001
          and ((cd_marital_status = 'M' and hd_dep_count = 3)
            or (cd_marital_status = 'S' and hd_dep_count = 1))""",
        s).to_pandas()
    ss, cd, hd, dd = (host["store_sales"], host["customer_demographics"],
                      host["household_demographics"], host["date_dim"])
    j = ss.merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk") \
          .merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk") \
          .merge(dd[dd.d_year == 2001], left_on="ss_sold_date_sk",
                 right_on="d_date_sk")
    j = j[((j.cd_marital_status == "M") & (j.hd_dep_count == 3))
          | ((j.cd_marital_status == "S") & (j.hd_dep_count == 1))]
    assert len(got) == 1
    if len(j):
        assert abs(got["aq"].iloc[0] - j.ss_quantity.mean()) < 1e-6
        assert abs(got["ap"].iloc[0] - _dec2(j.ss_ext_sales_price.mean())) \
            < 1e-9
        assert abs(got["sw"].iloc[0] - j.ss_ext_wholesale_cost.sum()) < 1e-4
    else:
        assert got["aq"].isna().iloc[0]


def test_q21_inventory_before_after(eng, host):
    """Q21 shape: inventory split before/after a pivot date per warehouse."""
    e, s = eng
    got = e.execute_sql("""
        select w_warehouse_name, i_item_id,
          sum(case when d_date_sk < 2451200 then inv_quantity_on_hand
              else 0 end) before_qty,
          sum(case when d_date_sk >= 2451200 then inv_quantity_on_hand
              else 0 end) after_qty
        from inventory, warehouse, item, date_dim
        where inv_item_sk = i_item_sk and inv_warehouse_sk = w_warehouse_sk
          and inv_date_sk = d_date_sk and i_current_price between 0.99 and 49.99
        group by w_warehouse_name, i_item_id
        order by w_warehouse_name, i_item_id limit 50""", s).to_pandas()
    inv, w, it, dd = (host["inventory"], host["warehouse"], host["item"],
                      host["date_dim"])
    j = inv.merge(w, left_on="inv_warehouse_sk", right_on="w_warehouse_sk") \
        .merge(it[(it.i_current_price >= 0.99)
                  & (it.i_current_price <= 49.99)],
               left_on="inv_item_sk", right_on="i_item_sk") \
        .merge(dd, left_on="inv_date_sk", right_on="d_date_sk")
    j["before_qty"] = np.where(j.d_date_sk < 2451200,
                               j.inv_quantity_on_hand, 0)
    j["after_qty"] = np.where(j.d_date_sk >= 2451200,
                              j.inv_quantity_on_hand, 0)
    ref = j.groupby(["w_warehouse_name", "i_item_id"], as_index=False)[
        ["before_qty", "after_qty"]].sum()
    ref = ref.sort_values(["w_warehouse_name", "i_item_id"]).head(50) \
        .reset_index(drop=True)
    _check(got, ref, set())


def test_q28_price_band_buckets(eng, host):
    """Q28 shape: per-band avg/count/count-distinct joined as one row."""
    e, s = eng
    got = e.execute_sql("""
        select b1.a a1, b1.c c1, b1.d d1, b2.a a2, b2.c c2, b2.d d2
        from (select avg(ss_list_price) a, count(ss_list_price) c,
                     count(distinct ss_list_price) d
              from store_sales where ss_quantity between 0 and 5) b1,
             (select avg(ss_list_price) a, count(ss_list_price) c,
                     count(distinct ss_list_price) d
              from store_sales where ss_quantity between 6 and 10) b2""",
        s).to_pandas()
    ss = host["store_sales"]
    b1 = ss[(ss.ss_quantity >= 0) & (ss.ss_quantity <= 5)].ss_list_price
    b2 = ss[(ss.ss_quantity >= 6) & (ss.ss_quantity <= 10)].ss_list_price
    assert got["c1"].iloc[0] == b1.count()
    assert got["d1"].iloc[0] == b1.nunique()
    assert abs(got["a1"].iloc[0] - _dec2(b1.mean())) < 1e-9
    assert got["c2"].iloc[0] == b2.count()
    assert got["d2"].iloc[0] == b2.nunique()
    assert abs(got["a2"].iloc[0] - _dec2(b2.mean())) < 1e-9


def test_q32_excess_discount(eng, host):
    """Q32 shape: correlated scalar subquery — discounts above 1.3x the
    item's average."""
    e, s = eng
    got = e.execute_sql("""
        select sum(cs_ext_discount_amt) excess
        from catalog_sales, item
        where i_item_sk = cs_item_sk and i_manufact_id = 77
          and cs_ext_discount_amt > (
            select 1.3 * avg(cs_ext_discount_amt) from catalog_sales
            where cs_item_sk = i_item_sk)""", s).to_pandas()
    cs, it = host["catalog_sales"], host["item"]
    sel = it[it.i_manufact_id == 77]
    j = cs.merge(sel[["i_item_sk"]], left_on="cs_item_sk",
                 right_on="i_item_sk")
    avg = cs.groupby("cs_item_sk").cs_ext_discount_amt.mean()
    j = j[j.cs_ext_discount_amt > 1.3 * j.cs_item_sk.map(avg)]
    want = j.cs_ext_discount_amt.sum()
    if len(j):
        assert abs(got["excess"].iloc[0] - want) < 1e-4
    else:
        assert got["excess"].isna().iloc[0]


def test_q37_inventory_price_band(eng, host):
    """Q37 shape: items in a price band currently in inventory and sold by
    catalog."""
    e, s = eng
    got = e.execute_sql("""
        select i_item_id, i_current_price
        from item, inventory, catalog_sales
        where i_current_price between 10 and 40
          and inv_item_sk = i_item_sk and cs_item_sk = i_item_sk
          and inv_quantity_on_hand between 100 and 500
        group by i_item_id, i_current_price
        order by i_item_id limit 30""", s).to_pandas()
    it, inv, cs = host["item"], host["inventory"], host["catalog_sales"]
    sel = it[(it.i_current_price >= 10) & (it.i_current_price <= 40)]
    has_inv = set(inv[(inv.inv_quantity_on_hand >= 100)
                      & (inv.inv_quantity_on_hand <= 500)].inv_item_sk)
    has_cs = set(cs.cs_item_sk)
    sel = sel[sel.i_item_sk.isin(has_inv) & sel.i_item_sk.isin(has_cs)]
    ref = sel[["i_item_id", "i_current_price"]].drop_duplicates() \
        .groupby("i_item_id", as_index=False).i_current_price.first()
    ref = sel.groupby(["i_item_id", "i_current_price"], as_index=False) \
        .size()[["i_item_id", "i_current_price"]]
    ref = ref.sort_values("i_item_id").head(30).reset_index(drop=True)
    _check(got, ref, {"i_current_price"})


def test_q38_channel_intersect(eng, host):
    """Q38 shape: customers present in all three channels (INTERSECT)."""
    e, s = eng
    got = e.execute_sql("""
        select count(*) n from (
          select distinct ss_customer_sk from store_sales, date_dim
          where ss_sold_date_sk = d_date_sk and d_year = 2000
          intersect
          select distinct cs_bill_customer_sk from catalog_sales, date_dim
          where cs_sold_date_sk = d_date_sk and d_year = 2000
          intersect
          select distinct ws_bill_customer_sk from web_sales, date_dim
          where ws_sold_date_sk = d_date_sk and d_year = 2000)""",
        s).to_pandas()
    dd = host["date_dim"]
    days = set(dd[dd.d_year == 2000].d_date_sk)
    ss = host["store_sales"]; cs = host["catalog_sales"]; ws = host["web_sales"]
    a = set(ss[ss.ss_sold_date_sk.isin(days)].ss_customer_sk)
    b = set(cs[cs.cs_sold_date_sk.isin(days)].cs_bill_customer_sk)
    c = set(ws[ws.ws_sold_date_sk.isin(days)].ws_bill_customer_sk)
    assert got["n"].iloc[0] == len(a & b & c)


def test_q40_returns_adjusted_pivot(eng, host):
    """Q40 shape: catalog sales net of returns, before/after a pivot date."""
    e, s = eng
    got = e.execute_sql("""
        select w_state, i_item_id,
          sum(case when d_date_sk < 2451200
              then cs_sales_price - coalesce(cr_return_amount, 0)
              else 0 end) before_amt,
          sum(case when d_date_sk >= 2451200
              then cs_sales_price - coalesce(cr_return_amount, 0)
              else 0 end) after_amt
        from catalog_sales
          left join catalog_returns on cs_order_number = cr_order_number
            and cs_item_sk = cr_item_sk,
          warehouse, item, date_dim
        where i_item_sk = cs_item_sk and cs_warehouse_sk = w_warehouse_sk
          and cs_sold_date_sk = d_date_sk
        group by w_state, i_item_id
        order by w_state, i_item_id limit 40""", s).to_pandas()
    cs, cr, w, it, dd = (host["catalog_sales"], host["catalog_returns"],
                         host["warehouse"], host["item"], host["date_dim"])
    j = cs.merge(cr[["cr_order_number", "cr_item_sk", "cr_return_amount"]],
                 left_on=["cs_order_number", "cs_item_sk"],
                 right_on=["cr_order_number", "cr_item_sk"], how="left")
    j = j.merge(w, left_on="cs_warehouse_sk", right_on="w_warehouse_sk") \
        .merge(it, left_on="cs_item_sk", right_on="i_item_sk") \
        .merge(dd, left_on="cs_sold_date_sk", right_on="d_date_sk")
    amt = j.cs_sales_price - j.cr_return_amount.fillna(0)
    j["before_amt"] = np.where(j.d_date_sk < 2451200, amt, 0)
    j["after_amt"] = np.where(j.d_date_sk >= 2451200, amt, 0)
    ref = j.groupby(["w_state", "i_item_id"], as_index=False)[
        ["before_amt", "after_amt"]].sum()
    ref = ref.sort_values(["w_state", "i_item_id"]).head(40) \
        .reset_index(drop=True)
    _check(got, ref, {"before_amt", "after_amt"})


def test_q41_manufact_exists(eng, host):
    """Q41 shape: distinct product names whose manufacturer also makes an
    item matching color conditions (EXISTS as semi-join)."""
    e, s = eng
    got = e.execute_sql("""
        select distinct i_product_name
        from item i1
        where i_manufact_id between 700 and 740
          and exists (select 1 from item i2
                      where i2.i_manufact = i1.i_manufact
                        and i2.i_color in ('red', 'blue'))
        order by i_product_name limit 25""", s).to_pandas()
    it = _table(eng[0].catalogs["tpcds"], "item",
                ["i_manufact", "i_manufact_id", "i_color", "i_product_name"])
    sel = it[(it.i_manufact_id >= 700) & (it.i_manufact_id <= 740)]
    good = set(it[it.i_color.isin(["red", "blue"])].i_manufact)
    names = sorted(set(sel[sel.i_manufact.isin(good)].i_product_name))[:25]
    assert list(got["i_product_name"]) == names


def test_q66_warehouse_monthly(eng, host):
    """Q66 shape: warehouse sales pivoted into months."""
    e, s = eng
    got = e.execute_sql("""
        select w_warehouse_name,
          sum(case when d_moy = 1 then ws_ext_sales_price else 0 end) jan,
          sum(case when d_moy = 2 then ws_ext_sales_price else 0 end) feb,
          sum(case when d_moy = 12 then ws_ext_sales_price else 0 end) dec
        from web_sales, warehouse, date_dim
        where ws_warehouse_sk = w_warehouse_sk and ws_sold_date_sk = d_date_sk
          and d_year = 2001
        group by w_warehouse_name order by w_warehouse_name""",
        s).to_pandas()
    ws, w, dd = host["web_sales"], host["warehouse"], host["date_dim"]
    j = ws.merge(w, left_on="ws_warehouse_sk", right_on="w_warehouse_sk") \
        .merge(dd[dd.d_year == 2001], left_on="ws_sold_date_sk",
               right_on="d_date_sk")
    for m, name in ((1, "jan"), (2, "feb"), (12, "dec")):
        j[name] = np.where(j.d_moy == m, j.ws_ext_sales_price, 0)
    ref = j.groupby("w_warehouse_name", as_index=False)[
        ["jan", "feb", "dec"]].sum().sort_values("w_warehouse_name") \
        .reset_index(drop=True)
    _check(got, ref, {"jan", "feb", "dec"})


def test_q84_income_band_customers(eng, host):
    """Q84 shape: customers in an income band via hd -> ib lookups."""
    e, s = eng
    got = e.execute_sql("""
        select c_customer_id, c_last_name, c_first_name
        from customer, customer_address, household_demographics, income_band
        where c_current_addr_sk = ca_address_sk
          and c_current_hdemo_sk = hd_demo_sk
          and hd_income_band_sk = ib_income_band_sk
          and ib_lower_bound >= 20000 and ib_upper_bound <= 60000
        order by c_customer_id limit 30""", s).to_pandas()
    c, ca, hd, ib = (host["customer"], host["customer_address"],
                     host["household_demographics"], host["income_band"])
    j = c.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk") \
        .merge(hd, left_on="c_current_hdemo_sk", right_on="hd_demo_sk") \
        .merge(ib[(ib.ib_lower_bound >= 20000)
                  & (ib.ib_upper_bound <= 60000)],
               left_on="hd_income_band_sk", right_on="ib_income_band_sk")
    ref = j.sort_values("c_customer_id").head(30)[
        ["c_customer_id", "c_last_name", "c_first_name"]] \
        .reset_index(drop=True)
    _check(got, ref, set())


def test_q85_web_returns_reasons(eng, host):
    """Q85 shape: web return reasons by refunding demographic bands."""
    e, s = eng
    got = e.execute_sql("""
        select r_reason_desc, avg(wr_return_quantity) q, avg(wr_return_amt) a
        from web_returns, reason, customer_demographics
        where wr_reason_sk = r_reason_sk
          and wr_refunded_cdemo_sk = cd_demo_sk
          and cd_education_status in ('College', 'Primary')
        group by r_reason_desc order by r_reason_desc limit 20""",
        s).to_pandas()
    wr, r, cd = host["web_returns"], host["reason"], \
        host["customer_demographics"]
    j = wr.merge(r, left_on="wr_reason_sk", right_on="r_reason_sk") \
        .merge(cd[cd.cd_education_status.isin(["College", "Primary"])],
               left_on="wr_refunded_cdemo_sk", right_on="cd_demo_sk")
    ref = j.groupby("r_reason_desc", as_index=False).agg(
        q=("wr_return_quantity", "mean"), a=("wr_return_amt", "mean"))
    ref["a"] = _dec2(ref["a"])  # engine decimal avg rounds HALF_UP to scale 2
    ref = ref.sort_values("r_reason_desc").head(20).reset_index(drop=True)
    _check(got, ref, {"q", "a"})


def test_q87_channel_except(eng, host):
    """Q87 shape: customers in store but NOT catalog channel (EXCEPT)."""
    e, s = eng
    got = e.execute_sql("""
        select count(*) n from (
          select distinct ss_customer_sk from store_sales, date_dim
          where ss_sold_date_sk = d_date_sk and d_year = 2000
          except
          select distinct cs_bill_customer_sk from catalog_sales, date_dim
          where cs_sold_date_sk = d_date_sk and d_year = 2000)""",
        s).to_pandas()
    dd = host["date_dim"]
    days = set(dd[dd.d_year == 2000].d_date_sk)
    ss, cs = host["store_sales"], host["catalog_sales"]
    a = set(ss[ss.ss_sold_date_sk.isin(days)].ss_customer_sk)
    b = set(cs[cs.cs_sold_date_sk.isin(days)].cs_bill_customer_sk)
    assert got["n"].iloc[0] == len(a - b)


def test_q91_call_center_losses(eng, host):
    """Q91 shape: call-center return losses by manager."""
    e, s = eng
    got = e.execute_sql("""
        select cc_name, cc_manager, sum(cr_return_amount) loss
        from catalog_returns, call_center, date_dim
        where cr_call_center_sk = cc_call_center_sk
          and cr_returned_date_sk = d_date_sk and d_year = 2000
        group by cc_name, cc_manager order by loss desc, cc_name limit 10""",
        s).to_pandas()
    cr, cc, dd = (host["catalog_returns"], host["call_center"],
                  host["date_dim"])
    j = cr.merge(cc, left_on="cr_call_center_sk",
                 right_on="cc_call_center_sk") \
        .merge(dd[dd.d_year == 2000], left_on="cr_returned_date_sk",
               right_on="d_date_sk")
    ref = j.groupby(["cc_name", "cc_manager"], as_index=False) \
        .cr_return_amount.sum().rename(columns={"cr_return_amount": "loss"})
    ref = ref.sort_values(["loss", "cc_name"],
                          ascending=[False, True]).head(10) \
        .reset_index(drop=True)[["cc_name", "cc_manager", "loss"]]
    _check(got, ref, {"loss"})


def test_q94_ship_anti_join(eng, host):
    """Q94 shape: web orders shipped from one site with no returns
    (NOT EXISTS as anti-join) and a shipping window."""
    e, s = eng
    got = e.execute_sql("""
        select count(distinct ws_order_number) orders,
               sum(ws_ext_ship_cost) ship, sum(ws_net_profit) profit
        from web_sales ws1
        where ws_ship_date_sk between 2450900 and 2451000
          and not exists (select 1 from web_returns
                          where wr_order_number = ws1.ws_order_number)""",
        s).to_pandas()
    ws, wr = host["web_sales"], host["web_returns"]
    sel = ws[(ws.ws_ship_date_sk >= 2450900) & (ws.ws_ship_date_sk <= 2451000)]
    sel = sel[~sel.ws_order_number.isin(set(wr.wr_order_number))]
    assert got["orders"].iloc[0] == sel.ws_order_number.nunique()
    if len(sel):
        assert abs(got["ship"].iloc[0] - sel.ws_ext_ship_cost.sum()) < 1e-4
        assert abs(got["profit"].iloc[0] - sel.ws_net_profit.sum()) < 1e-4


def test_q95_repeat_ship_sites(eng, host):
    """Q95 shape: orders that ship across multiple warehouses (EXISTS
    self-join on a different warehouse)."""
    e, s = eng
    got = e.execute_sql("""
        select count(distinct ws_order_number) n
        from web_sales ws1
        where exists (select 1 from web_sales ws2
                      where ws2.ws_order_number = ws1.ws_order_number
                        and ws2.ws_warehouse_sk <> ws1.ws_warehouse_sk)""",
        s).to_pandas()
    ws = host["web_sales"]
    g = ws.groupby("ws_order_number").ws_warehouse_sk.nunique()
    assert got["n"].iloc[0] == int((g > 1).sum())
