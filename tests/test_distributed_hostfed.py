"""Distribution beyond the TPC-H generator (VERDICT r3 missing #2): TPC-DS
traced scans and HOST-FED scans (memory/parquet connectors: coordinator-side
split queues decoded into stacked fixed-shape batches) shard across the mesh,
and the executor's fragment-mode trace makes every fallback visible
(reference: SourcePartitionedScheduler.java:55 scheduling any connector's
splits; sql/planner/planprinter fragment output)."""

import numpy as np
import pandas as pd
import pytest

import jax

from trino_tpu import Engine
from trino_tpu.parallel.mesh import worker_mesh


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    return worker_mesh(8)


def _frames_equal(a: pd.DataFrame, b: pd.DataFrame):
    assert len(a) == len(b)
    for ca, cb in zip(a.columns, b.columns):
        ga, gb = a[ca].to_numpy(), b[cb].to_numpy()
        if ga.dtype == object or gb.dtype == object:
            assert list(ga) == list(gb), ca
        else:
            np.testing.assert_allclose(ga.astype(np.float64),
                                       gb.astype(np.float64), rtol=1e-12,
                                       err_msg=ca)


@pytest.fixture(scope="module")
def ds_engine():
    from trino_tpu.connectors.tpcds import TpcdsConnector

    e = Engine()
    e.register_catalog("tpcds", TpcdsConnector(sf=0.01, split_rows=1 << 13))
    return e, e.create_session("tpcds")


def test_tpcds_star_distributed(ds_engine, mesh8):
    e, s = ds_engine
    sql = ("select i_category, sum(ss_ext_sales_price) rev, count(*) c "
           "from store_sales, date_dim, item "
           "where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk "
           "and d_year = 2000 group by i_category order by rev desc, i_category")
    local = e.execute_sql(sql, s).to_pandas()
    dist = e.execute_sql(sql, s, distributed=True, mesh=mesh8).to_pandas()
    _frames_equal(dist, local)


def test_tpcds_global_agg_distributed(ds_engine, mesh8):
    e, s = ds_engine
    sql = ("select count(*) c, sum(ss_quantity) q from store_sales "
           "where ss_quantity between 1 and 50")
    local = e.execute_sql(sql, s).to_pandas()
    dist = e.execute_sql(sql, s, distributed=True, mesh=mesh8).to_pandas()
    _frames_equal(dist, local)


@pytest.fixture(scope="module")
def mem_engine():
    from trino_tpu.connectors.memory import MemoryConnector

    e = Engine()
    mem = MemoryConnector()
    e.register_catalog("mem", mem)
    s = e.create_session("mem")
    e.execute_sql("create table t (k bigint, v double, tag varchar)", s)
    rng = np.random.default_rng(7)
    n = 30000
    ks = (rng.integers(0, 251, n)).tolist()
    vs = np.round(rng.uniform(0, 1000, n), 3).tolist()
    tags = [f"tag{int(x) % 7}" for x in ks]
    mem.append("t", [ks, vs, tags])
    return e, s


def test_memory_hostfed_groupby(mem_engine, mesh8):
    e, s = mem_engine
    sql = ("select k, sum(v) sv, count(*) c from t "
           "group by k order by k")
    local = e.execute_sql(sql, s).to_pandas()
    dist = e.execute_sql(sql, s, distributed=True, mesh=mesh8).to_pandas()
    _frames_equal(dist, local)


def test_memory_hostfed_filter_topn(mem_engine, mesh8):
    e, s = mem_engine
    sql = ("select k, v from t where v > 500 "
           "order by v desc, k limit 25")
    local = e.execute_sql(sql, s).to_pandas()
    dist = e.execute_sql(sql, s, distributed=True, mesh=mesh8).to_pandas()
    _frames_equal(dist, local)


def test_parquet_hostfed_distributed(tmp_path_factory, mesh8):
    from trino_tpu.connectors.parquet import ParquetConnector
    from trino_tpu.connectors.tpch import TpchConnector

    d = tmp_path_factory.mktemp("pq_dist")
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 13))
    e.register_catalog("pq", ParquetConnector(str(d)))
    s = e.create_session("pq")
    e.execute_sql("create table po as select o_custkey, o_totalprice, "
                  "o_orderkey from tpch.orders", s)
    sql = ("select o_custkey, sum(o_totalprice) sp, count(*) c from po "
           "group by o_custkey order by o_custkey limit 40")
    local = e.execute_sql(sql, s).to_pandas()
    dist = e.execute_sql(sql, s, distributed=True, mesh=mesh8).to_pandas()
    _frames_equal(dist, local)


def test_exec_trace_reports_modes(mem_engine, mesh8):
    """EXPLAIN ANALYZE on a distributed run prints each fragment's actual
    execution mode with fallback reasons (no silent fallback)."""
    e, s = mem_engine
    r = e.execute_sql("explain analyze select k, sum(v) sv from t "
                      "group by k order by k", s,
                      distributed=True, mesh=mesh8)
    text = "\n".join(r.columns[0].tolist())
    assert "Fragment execution (distributed run):" in text
    assert "[mesh] Aggregate" in text


def test_rollup_distributes_per_branch(mesh8):
    """Grouping sets plan to a Union of aggregate branches; each branch must
    run on the mesh with the union gathered on the coordinator."""
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.exec.distributed import DistributedExecutor
    from trino_tpu.sql.frontend import compile_sql

    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.005, split_rows=1 << 12))
    s = e.create_session("tpch")
    sql = ("select l_returnflag, l_linestatus, sum(l_quantity) q, count(*) c "
           "from lineitem group by rollup (l_returnflag, l_linestatus) "
           "order by l_returnflag, l_linestatus")
    local = e.execute_sql(sql, s).to_pandas()
    ex = DistributedExecutor(e.catalogs, mesh=mesh8)
    from trino_tpu.exec.local_executor import _sort_page  # noqa: F401 (plan shape doc)
    dist = e.execute_sql(sql, s, distributed=True, mesh=mesh8).to_pandas()
    assert local.shape == dist.shape
    for c in local.columns:
        a, b = local[c], dist[c]
        try:
            np.testing.assert_allclose(a.astype(float), b.astype(float))
        except (ValueError, TypeError):
            assert a.fillna("~").tolist() == b.fillna("~").tolist()
    # trace: every aggregate branch on the mesh, union gathered
    ex.execute(compile_sql(sql, e, s))
    agg_modes = [m for label, m, _ in ex.exec_trace if label == "Aggregate"]
    assert agg_modes and all(m == "mesh" for m in agg_modes)
    assert ("Union", "coordinator") in [(l, m) for l, m, _ in ex.exec_trace]


def test_north_star_no_unintended_fallback(mesh8):
    """The north-star TPC-H suite must distribute its aggregation fragments on
    the mesh — zero 'local' modes in the trace (VERDICT r3 item 4)."""
    from trino_tpu.exec.distributed import DistributedExecutor
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.sql.frontend import compile_sql
    import __graft_entry__ as G

    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.001, split_rows=1 << 12))
    s = e.create_session("tpch")
    for sql in (G.Q1, G.Q9, G.Q18):
        ex = DistributedExecutor(e.catalogs, mesh=mesh8)
        ex.execute(compile_sql(sql, e, s))
        local_modes = [t for t in ex.exec_trace if t[1] == "local"]
        assert not local_modes, (sql[:60], local_modes)
        assert any(t[1] == "mesh" for t in ex.exec_trace), sql[:60]
