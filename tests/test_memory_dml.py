"""Memory connector, DDL/DML statements, null-aware grouping and sorting."""

import numpy as np
import pytest


@pytest.fixture()
def mem_engine(tpch_sf001):
    from trino_tpu import Engine
    from trino_tpu.connectors.memory import MemoryConnector

    e = Engine()
    e.register_catalog("tpch", tpch_sf001)
    e.register_catalog("memory", MemoryConnector())
    return e


def test_create_insert_select(mem_engine):
    e = mem_engine
    e.execute_sql("create table t (a bigint, b varchar, c decimal(10,2), d date)")
    e.execute_sql("insert into t values (1, 'x', 1.50, date '2020-01-02'), "
                  "(2, 'y', 2.25, date '2021-03-04'), (3, null, null, null)")
    r = e.execute_sql("select * from t order by a")
    assert r.columns[0].tolist() == [1, 2, 3]
    assert r.columns[1].tolist() == ["x", "y", None]
    assert r.columns[2].tolist()[:2] == [1.5, 2.25]
    assert r.columns[2][2] is None


def test_null_group_and_sort(mem_engine):
    e = mem_engine
    e.execute_sql("create table t (a bigint, b varchar, c decimal(10,2))")
    e.execute_sql("insert into t values (1, 'x', 1.50), (2, 'y', 2.25), "
                  "(3, null, null), (4, null, 5.00)")
    r = e.execute_sql(
        "select b, sum(c) s, count(*) n from t group by b order by b nulls first")
    assert len(r) == 3
    assert r.columns[0][0] is None  # NULLs form one group, placed first
    assert r.columns[2][0] == 2
    assert abs(r.columns[1][0] - 5.0) < 1e-9
    r = e.execute_sql("select b from t group by b order by b")
    assert r.columns[0].tolist() == ["x", "y", None]  # default NULLS LAST


def test_ctas_and_cross_catalog_join(mem_engine):
    e = mem_engine
    e.execute_sql("create table amerika as "
                  "select n_name, n_regionkey from nation where n_regionkey = 1")
    r = e.execute_sql("select count(*) c from amerika")
    assert r.columns[0][0] == 5
    r = e.execute_sql("select a.n_name, r_name from amerika a, region "
                      "where a.n_regionkey = r_regionkey order by a.n_name")
    assert r.columns[1].tolist() == ["AMERICA"] * 5
    e.execute_sql("drop table amerika")


def test_insert_select_and_partial_columns(mem_engine):
    e = mem_engine
    e.execute_sql("create table t (a bigint, b varchar)")
    e.execute_sql("insert into t (a) values (7)")
    e.execute_sql("insert into t select n_nationkey, n_name from nation "
                  "where n_nationkey < 2")
    r = e.execute_sql("select a, b from t order by a")
    assert r.columns[0].tolist() == [0, 1, 7]
    assert r.columns[1].tolist() == ["ALGERIA", "ARGENTINA", None]


def test_drop_and_if_exists(mem_engine):
    e = mem_engine
    e.execute_sql("create table t (a bigint)")
    e.execute_sql("drop table t")
    with pytest.raises(Exception):
        e.execute_sql("select * from t")
    e.execute_sql("drop table if exists t")
    e.execute_sql("create table if not exists t2 (a bigint)")
    e.execute_sql("create table if not exists t2 (a bigint)")


def test_explain_analyze(mem_engine):
    r = mem_engine.execute_sql("explain analyze select count(*) from nation")
    text = "\n".join(r.columns[0].tolist())
    assert "executed in" in text and "1 output rows" in text


def test_ctas_if_not_exists_no_duplicate(mem_engine):
    e = mem_engine
    e.execute_sql("create table c1 as select n_nationkey from nation")
    e.execute_sql("create table if not exists c1 as select n_nationkey from nation")
    r = e.execute_sql("select count(*) c from c1")
    assert r.columns[0][0] == 25  # second CTAS skipped the insert entirely


def test_unknown_catalog_qualifier(mem_engine):
    from trino_tpu.sql.frontend import SemanticError

    with pytest.raises(SemanticError, match="memry"):
        mem_engine.execute_sql("select * from memry.t")


def test_drop_missing_table_message(mem_engine):
    with pytest.raises(ValueError, match="does not exist"):
        mem_engine.execute_sql("drop table never_created")


def test_delete_and_update():
    """Row-level DML (reference: ConnectorMergeSink delete/update surface)."""
    import numpy as np

    from trino_tpu import Engine
    from trino_tpu.connectors.memory import MemoryConnector

    e = Engine()
    e.register_catalog("memory", MemoryConnector())
    s = e.create_session("memory")
    e.execute_sql("create table emp (id bigint, name varchar, salary decimal(10,2))", s)
    e.execute_sql("""insert into emp values (1, 'ann', 100.00), (2, 'bob', 200.00),
                     (3, 'cat', 300.00), (4, 'dan', 400.00)""", s)
    e.execute_sql("update emp set salary = salary * 2 where id >= 3", s)
    r = e.execute_sql("select id, salary from emp order by id", s).rows()
    assert [(i, float(v)) for i, v in r] == [(1, 100.0), (2, 200.0), (3, 600.0),
                                            (4, 800.0)]
    e.execute_sql("update emp set name = 'zed', salary = 1.50 where id = 1", s)
    r = e.execute_sql("select name, salary from emp where id = 1", s).rows()
    assert r == [("zed", 1.5)]
    e.execute_sql("delete from emp where salary > 500", s)
    r = e.execute_sql("select id from emp order by id", s).rows()
    assert [x[0] for x in r] == [1, 2]
    e.execute_sql("delete from emp", s)
    assert e.execute_sql("select count(*) from emp", s).rows()[0][0] == 0


def test_dml_returns_affected_row_counts():
    """INSERT/UPDATE/DELETE surface their affected-row counts (reference:
    the client protocol's updateCount)."""
    from trino_tpu import Engine
    from trino_tpu.connectors.memory import MemoryConnector

    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table t (a bigint)", s)
    assert e.execute_sql("insert into t values (1), (2), (3)",
                         s).to_pandas().values.tolist() == [[3]]
    assert e.execute_sql("update t set a = a + 1 where a >= 2",
                         s).to_pandas().values.tolist() == [[2]]
    assert e.execute_sql("delete from t where a = 4",
                         s).to_pandas().values.tolist() == [[1]]
    assert e.execute_sql("delete from t where a = 999",
                         s).to_pandas().values.tolist() == [[0]]
