"""Cluster low-memory kill policy (reference test model:
TestTotalReservationOnBlockedNodesQueryLowMemoryKiller /
TestClusterMemoryManager over memory/ClusterMemoryManager.java:92 —
round-4 verdict item 7)."""

import json
import pickle
import time

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.execution.memory_killer import (
    NoneKiller, TotalReservationKiller, TotalReservationOnBlockedNodesKiller)
from trino_tpu.memory import MemoryPool, QueryKilledError
from trino_tpu.server.cluster import ClusterCoordinator, WorkerServer, _http

CATALOGS = {"tpch": {"connector": "tpch", "sf": 0.01, "split_rows": 1 << 11}}


# ----------------------------------------------------------------- policies
def _node(nid, reserved, cap, by_query):
    return {"node_id": nid, "url": f"http://x/{nid}", "mem_reserved": reserved,
            "mem_max": cap, "mem_by_query": by_query}


def test_blocked_nodes_policy_picks_top_query_on_blocked_only():
    nodes = [
        _node("blocked", 95, 100, {"qA": 60, "qB": 35}),
        _node("healthy", 10, 100, {"qC": 1000}),  # big but NOT on a blocked node
    ]
    assert TotalReservationOnBlockedNodesKiller().pick_victim(nodes) == "qA"


def test_blocked_nodes_policy_none_when_healthy():
    nodes = [_node("n1", 10, 100, {"qA": 10})]
    assert TotalReservationOnBlockedNodesKiller().pick_victim(nodes) is None


def test_total_reservation_policy_sums_all_nodes():
    nodes = [
        _node("blocked", 95, 100, {"qA": 60}),
        _node("healthy", 50, 100, {"qB": 45, "qA": 5}),
    ]
    # qA: 65 total, qB: 45 -> qA; engages because SOME node is blocked
    assert TotalReservationKiller().pick_victim(nodes) == "qA"
    assert NoneKiller().pick_victim(nodes) is None


# ------------------------------------------------------------- pool poisoning
def test_pool_kill_poisons_reservations_and_checkpoints():
    pool = MemoryPool(max_bytes=1000)
    with pool.query_scope("q1"):
        assert pool.try_reserve(100)
        assert pool.by_query() == {"q1": 100}
    pool.kill_query("q1")
    with pool.query_scope("q1"):
        with pytest.raises(QueryKilledError):
            pool.try_reserve(10)
        with pytest.raises(QueryKilledError):
            pool.check_killed()
    # other queries unaffected
    with pool.query_scope("q2"):
        assert pool.try_reserve(10)
        pool.check_killed()
    pool.clear_query("q1")
    assert "q1" not in pool.by_query()  # attribution cleared...
    with pool.query_scope("q1"):
        with pytest.raises(QueryKilledError):
            pool.try_reserve(10)  # ...but poison SURVIVES clear_query:
            # re-offered sibling tasks of the victim must still die here
            # (the bounded FIFO retires entries, not task completion)
    for i in range(pool._killed_cap + 1):  # FIFO bound retires old entries
        pool.kill_query(f"other{i}")
    with pool.query_scope("q1"):
        assert pool.try_reserve(10)


# --------------------------------------------------------------- cluster e2e
@pytest.mark.slow
def test_cluster_kills_top_reserving_query_on_blocked_node(tmp_path):
    """Two queries on one memory-starved worker: the policy victim (the hog)
    dies with a memory error while the other query completes (reference:
    TotalReservationOnBlockedNodesQueryLowMemoryKiller behavior)."""
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 11))
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.2)
    url = coord.start()
    w = WorkerServer(CATALOGS, str(tmp_path / "spool"), coordinator_url=url,
                     node_id="w1")
    w.start()
    try:
        coord.wait_for_workers(1, timeout=30)
        # simulate the hog: a query holding 95% of the worker pool (a real
        # running query's reservations, fabricated deterministically so the
        # test does not depend on landing group-by state inside the 90-100%
        # window)
        hog_bytes = int(w.memory_pool.max_bytes * 0.95)
        with w.memory_pool.query_scope("hog-query"):
            assert w.memory_pool.try_reserve(hog_bytes, "group-by")
        try:
            # the node now announces blocked; the policy must pick the hog
            deadline = time.time() + 15
            while time.time() < deadline and coord.oom_kills == 0:
                time.sleep(0.05)
            assert coord.oom_kills >= 1, "policy never fired on a blocked node"
            assert coord.last_oom_victim == "hog-query"
            # the victim dies at its next reservation/checkpoint
            with w.memory_pool.query_scope("hog-query"):
                with pytest.raises(QueryKilledError):
                    w.memory_pool.try_reserve(1, "group-by")
        finally:
            with w.memory_pool.query_scope("hog-query"):
                w.memory_pool.free(hog_bytes, "group-by")
        # ... and the OTHER query completes normally on the freed cluster
        got = coord.execute_sql(
            "select count(*) c from lineitem").rows()
        assert got == e.execute_sql("select count(*) c from lineitem").rows()
    finally:
        coord.stop()
        w.stop()


@pytest.mark.slow
def test_killed_query_task_fails_deterministically(tmp_path):
    """A running task of a killed query fails with QueryKilledError at its
    next preemption point, marked non-retryable (no attempt-budget burn), and
    the coordinator surfaces the kill instead of rerunning locally."""
    from trino_tpu.exec.fte import is_retryable_failure

    assert not is_retryable_failure(QueryKilledError("x"))
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 11))
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.2)
    url = coord.start()
    w = WorkerServer(CATALOGS, str(tmp_path / "spool"), coordinator_url=url,
                     node_id="w1")
    w.start()
    try:
        coord.wait_for_workers(1, timeout=30)
        import threading

        res = {}

        def run():
            try:
                res["rows"] = coord.execute_sql(
                    "select l_orderkey, sum(l_quantity) q from lineitem "
                    "group by l_orderkey").rows()
            except Exception as ex:
                res["error"] = ex

        t = threading.Thread(target=run)
        t.start()
        # kill the query's key the moment tasks register on the worker
        deadline = time.time() + 30
        killed = False
        while time.time() < deadline and not killed:
            with w._wlock:
                keys = list(w._running_queries)
            if keys:
                w.memory_pool.kill_query(keys[0])
                killed = True
            time.sleep(0.005)
        t.join(timeout=120)
        assert killed, "no query ever started on the worker"
        assert not t.is_alive()
        if "error" in res:
            assert isinstance(res["error"], QueryKilledError), res["error"]
            assert coord.local_fallbacks == 0, \
                "killed query must not rerun locally"
        else:
            # the kill raced query completion: acceptable, but the local
            # path must not have run
            assert coord.local_fallbacks == 0
    finally:
        coord.stop()
        w.stop()
