"""Chaos suite: deterministic fault injection at every chokepoint, and the
clean-failure contract it enforces.

Rounds 6-9 built retries, speculation, stream replay, Grace fallbacks, a
buffer pool and prefetch producer threads — none of which ever ran under an
injected failure.  This matrix (execution/faults.py is the injector; rules
arm through the ``faults.injected(...)`` context manager, never by
monkeypatching internals — the DISPATCH_TEST_HOOK precedent) pins the
contract the next arc (SPMD exchange, SF100) builds on:

- a RECOVERABLE fault (cache denial, reservation denial, guarded store
  failure, dispatch delay) yields results BYTE-IDENTICAL to the fault-free
  run;
- a NON-RECOVERABLE fault (dispatch/generate/pull/h2d errors on a local
  query) yields a clean TYPED error (InjectedFaultError /
  FatalInjectedFaultError), never a hang or a corrupt result;
- after EVERY scenario the engine is clean: zero residual in-flight registry
  entries, no surviving prefetch-producer thread, no executor holding a live
  producer registration, buffer-pool reservations exactly equal to its
  resident bytes (no orphaned reservation, no partial page), and a
  subsequent fault-free run still byte-identical (no truncated cache entry
  served).

Tier-1 (``-m 'not slow'``) runs the q1/q3 local matrix plus the injector,
backoff and regression tests; the q9/q18 matrix and the distributed matrix
(worker faults, worker crash, dropped exchange commits, retry-budget
exhaustion over an in-process cluster) are ``slow``.

The scenario table, result signature and leak-report semantics are shared
with the standalone capture harness (scripts/chaos.py) through
execution/chaos_matrix.py — edit the matrix THERE so the test contract and
the on-device artifact cannot drift apart.
"""

import time

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.execution import faults
from trino_tpu.execution.chaos_matrix import (DIST_SCENARIOS, FAILING,
                                              QUERIES, RECOVERABLE,
                                              leak_report)
from trino_tpu.execution.chaos_matrix import result_signature as _sig
from trino_tpu.execution.chaos_matrix import settle as _settle
from trino_tpu.execution.faults import (FatalInjectedFaultError, FaultPlan,
                                        InjectedFaultError)

FAST_QUERIES = ("q1", "q3")
SLOW_QUERIES = ("q9", "q18")


def _leak_check(engine):
    """The post-scenario contract: nothing survives the query."""
    leftovers = leak_report(engine)
    assert not leftovers, f"post-scenario leaks: {leftovers}"


@pytest.fixture(scope="module")
def sf1():
    import os

    prev = os.environ.get("TRINO_TPU_PAGE_CACHE")
    os.environ["TRINO_TPU_PAGE_CACHE"] = str(6 * 1024 * 1024 * 1024)
    engine = Engine()
    engine.register_catalog("tpch", TpchConnector(sf=1, split_rows=1 << 21))
    session = engine.create_session("tpch")
    nocache = engine.create_session("tpch")
    engine.session_properties.set_property(nocache, "page_cache", False)
    state = {"baselines": {}}
    yield engine, session, nocache, state
    engine._invalidate()
    if prev is None:
        os.environ.pop("TRINO_TPU_PAGE_CACHE", None)
    else:
        os.environ["TRINO_TPU_PAGE_CACHE"] = prev


def _baseline(sf1_tuple, name):
    engine, session, _nocache, state = sf1_tuple
    if name not in state["baselines"]:
        engine.execute_sql(QUERIES[name], session)  # cold: plan + compile
        state["baselines"][name] = \
            _sig(engine.execute_sql(QUERIES[name], session))
    return state["baselines"][name]


def _run_recoverable(sf1_tuple, name, scenario):
    engine, session, _nocache, _state = sf1_tuple
    spec, clear_pool = RECOVERABLE[scenario]
    base = _baseline(sf1_tuple, name)
    if clear_pool:
        engine.buffer_pool.clear()  # force the run to regenerate AND store
    with faults.injected(spec) as plan:
        got = _sig(engine.execute_sql(QUERIES[name], session))
    assert plan.total_fires() >= 1, f"scenario never fired: {plan.stats()}"
    assert got == base, f"{name} under {spec}: result diverged"
    _leak_check(engine)
    # and the engine is still healthy fault-free
    assert _sig(engine.execute_sql(QUERIES[name], session)) == base


def _run_failing(sf1_tuple, name, spec, cache_on):
    engine, session, nocache, _state = sf1_tuple
    base = _baseline(sf1_tuple, name)
    sess = session if cache_on else nocache
    with faults.injected(spec) as plan:
        with pytest.raises(InjectedFaultError):
            engine.execute_sql(QUERIES[name], sess)
    assert plan.total_fires() >= 1, f"scenario never fired: {plan.stats()}"
    _leak_check(engine)
    # no partial page was cached, no state corrupted: the fault-free rerun
    # regenerates and matches the baseline byte for byte
    assert _sig(engine.execute_sql(QUERIES[name], session)) == base
    _leak_check(engine)


# ------------------------------------------------------------ local matrix
@pytest.mark.parametrize("name", FAST_QUERIES)
@pytest.mark.parametrize("scenario", sorted(RECOVERABLE))
def test_recoverable_fault_is_invisible(sf1, name, scenario):
    _run_recoverable(sf1, name, scenario)


@pytest.mark.parametrize("name", FAST_QUERIES)
@pytest.mark.parametrize("scenario", sorted(FAILING))
def test_unrecoverable_fault_fails_clean(sf1, name, scenario):
    spec, cache_on = FAILING[scenario]
    _run_failing(sf1, name, spec, cache_on)


@pytest.mark.slow
@pytest.mark.parametrize("name", SLOW_QUERIES)
@pytest.mark.parametrize("scenario", sorted(RECOVERABLE))
def test_recoverable_fault_is_invisible_slow(sf1, name, scenario):
    _run_recoverable(sf1, name, scenario)


@pytest.mark.slow
@pytest.mark.parametrize("name", SLOW_QUERIES)
@pytest.mark.parametrize("scenario", sorted(FAILING))
def test_unrecoverable_fault_fails_clean_slow(sf1, name, scenario):
    spec, cache_on = FAILING[scenario]
    _run_failing(sf1, name, spec, cache_on)


def test_repeated_faulted_runs_hold_reservations_steady(sf1):
    """Leaked reservations compound: run the same faulted scenario twice and
    assert no labeled pool's reservation grew between the runs (compiled-
    artifact reservations from the FIRST run may legitimately persist for
    the plan-cache lifetime; growth across identical runs is the leak)."""
    engine, session, _nocache, _state = sf1
    _baseline(sf1, "q1")

    def faulted_run():
        with faults.injected("point=dispatch,action=error,nth=3"):
            with pytest.raises(InjectedFaultError):
                engine.execute_sql(QUERIES["q1"], session)
        _settle()

    faulted_run()
    first = {d["pool"]: d["reserved"] for d in engine.memory_info()}
    faulted_run()
    second = {d["pool"]: d["reserved"] for d in engine.memory_info()}
    assert second == first, (first, second)
    _leak_check(engine)


def test_faults_are_counted_and_explained(sf1):
    """Observability satellite: faults_injected reaches the per-query
    counters and EXPLAIN ANALYZE's Device boundary line names them, so a
    chaos run is self-describing."""
    engine, session, _nocache, _state = sf1
    _baseline(sf1, "q1")
    with faults.injected("point=dispatch,action=delay,s=0,every=1"):
        r = engine.execute_sql(f"explain analyze {QUERIES['q1']}", session)
    text = "\n".join(str(row[0]) for row in r.rows())
    c = engine.last_query_counters
    assert c.faults_injected > 0
    assert f"{c.faults_injected} faults injected" in text, text
    # disarmed queries keep the pristine line (budget-suite regexes etc.)
    r = engine.execute_sql(f"explain analyze {QUERIES['q1']}", session)
    text = "\n".join(str(row[0]) for row in r.rows())
    assert "faults injected" not in text
    assert engine.last_query_counters.faults_injected == 0


# -------------------------------------------- prefetch-producer regression
def test_mid_scan_fault_kills_prefetch_producer():
    """Satellite regression: a dispatch fault raised mid-scan (while the
    prefetch producer is pumping ahead of the consumer) must kill the
    producer thread and clear its in-flight state even though the exception
    traceback pins the consumer generators alive (pytest.raises holds it).
    Before close_producers() this thread survived, pumping against a full
    queue, until the traceback was released.  The query must take the
    GROUPED aggregation path: its page iterator is a NAMED local
    (page_iter/pages_once in _run_aggregate), which traceback frames pin —
    a plain ``for page in gen():`` iterator lives on the value stack, which
    CPython already clears during unwind (verified: the pre-fix leak
    reproduces with this query and not with a global aggregate)."""
    engine = Engine()
    # many small splits so the producer is genuinely ahead when the consumer
    # faults; page cache off so the scan actually streams
    engine.register_catalog("tpch",
                            TpchConnector(sf=0.1, split_rows=1 << 14))
    session = engine.create_session("tpch")
    engine.session_properties.set_property(session, "page_cache", False)
    q = ("select l_returnflag, sum(l_quantity) from lineitem "
         "group by l_returnflag")
    engine.execute_sql(q, session)  # warm: compile outside the scenario
    assert not _settle()
    with faults.injected("point=dispatch,action=error,nth=3") as plan:
        with pytest.raises(InjectedFaultError):
            engine.execute_sql(q, session)
    assert plan.total_fires() == 1
    leftovers = _settle(timeout=4.0)
    assert not leftovers, f"producer survived the faulted query: {leftovers}"
    for ex in engine._all_executors:
        assert not ex._producers
    engine._invalidate()


def test_generate_fault_on_producer_thread_fails_clean():
    """A generation fault raised ON the producer thread surfaces at the
    consume site as the typed error, the producer dies with it, and a
    subsequent clean run regenerates correctly (no partial page cached)."""
    import os

    prev = os.environ.get("TRINO_TPU_PAGE_CACHE")
    os.environ["TRINO_TPU_PAGE_CACHE"] = str(1 << 30)
    try:
        engine = Engine()
        engine.register_catalog(
            "tpch", TpchConnector(sf=0.05, split_rows=1 << 13))
        session = engine.create_session("tpch")
        q = "select count(*), sum(l_quantity) from lineitem"
        base = _sig(engine.execute_sql(q, session))
        engine._invalidate()  # drop the cached scan: force regeneration
        # nth=4 lands past the 2-page synchronous warmup — producer thread
        with faults.injected("point=generate,action=error,nth=4") as plan:
            with pytest.raises(InjectedFaultError):
                engine.execute_sql(q, session)
        assert plan.total_fires() == 1
        # the firing happened ON the producer thread: the counters handoff
        # must still charge it to the query, or chaos runs over the default
        # prefetch path would read 0 faults_injected
        assert engine.last_query_counters.faults_injected == 1
        assert not _settle()
        # the errored scan must NOT have been admitted: the rerun generates
        # and matches (a truncated cached page would change the aggregates)
        assert _sig(engine.execute_sql(q, session)) == base
        info = engine.buffer_pool.info()
        if engine.buffer_pool.memory_pool is not None:
            assert engine.buffer_pool.memory_pool.reserved == info["bytes"]
        engine._invalidate()
    finally:
        if prev is None:
            os.environ.pop("TRINO_TPU_PAGE_CACHE", None)
        else:
            os.environ["TRINO_TPU_PAGE_CACHE"] = prev


# ------------------------------------------------------------ injector unit
def test_fault_plan_triggers_are_deterministic():
    p = FaultPlan.parse("point=dispatch,nth=2,action=error")
    assert p.fire("dispatch", "x", None) is None
    with pytest.raises(InjectedFaultError):
        p.fire("dispatch", "x", None)
    assert p.fire("dispatch", "x", None) is None  # nth implies times=1

    p = FaultPlan.parse("point=reserve,action=deny,every=3")
    fires = [p.fire("reserve", "t", None) for _ in range(9)]
    assert fires == [None, None, "deny"] * 3

    a = FaultPlan.parse("point=task,action=drop,p=0.3,seed=11,times=1000")
    b = FaultPlan.parse("point=task,action=drop,p=0.3,seed=11,times=1000")
    seq = [a.fire("task", "s", None) for _ in range(50)]
    assert seq == [b.fire("task", "s", None) for _ in range(50)]
    assert 0 < seq.count("drop") < 50  # actually probabilistic, not const

    # site and query globs gate matching
    p = FaultPlan.parse("point=dispatch,site=Agg*,action=error,query=q7")
    assert p.fire("dispatch", "Join#0/probe", "q7") is None
    assert p.fire("dispatch", "Aggregate#1/step", "q8") is None
    with pytest.raises(InjectedFaultError):
        p.fire("dispatch", "Aggregate#1/step", "q7")


def test_fault_plan_parse_rejects_garbage():
    for bad in ("", "action=error", "point=nope", "point=dispatch,wat=1",
                "point=dispatch,action=explode"):
        with pytest.raises(ValueError):
            FaultPlan.parse(bad)


def test_fatal_fault_is_classified_deterministic():
    from trino_tpu.exec.fte import is_retryable_failure

    assert is_retryable_failure(InjectedFaultError("x"))
    assert not is_retryable_failure(FatalInjectedFaultError("x"))


def test_disarmed_injector_is_inert():
    assert faults.active() is None
    assert faults.maybe_inject("dispatch", "anything") is None


def test_fte_dropped_commit_is_retried(tmp_path):
    """A LOST exchange commit (chaos ``exchange_write`` drop) on the local
    FTE path must be detected by the retry loop — is_committed after commit —
    recomputed and recommitted, never returned as success for output that
    never became visible (the reader would hit a missing spool file)."""
    from trino_tpu.exec.fte import FailureInjector, FaultTolerantExecutor
    from trino_tpu.sql.frontend import compile_sql

    engine = Engine()
    engine.register_catalog("tpch",
                            TpchConnector(sf=0.01, split_rows=1 << 11))
    session = engine.create_session("tpch")
    q = ("select l_returnflag, sum(l_quantity) q from lineitem "
         "group by l_returnflag order by l_returnflag")
    plan = compile_sql(q, engine, session)
    expected = engine.execute_sql(q, session).rows()
    ex = FaultTolerantExecutor(engine.catalogs, str(tmp_path / "spool"),
                               injector=FailureInjector())
    with faults.injected("point=exchange_write,action=drop,nth=1") as plan_f:
        got = ex.execute(plan).rows()
    assert plan_f.total_fires() == 1, plan_f.stats()
    assert got == expected
    assert max(ex.task_attempts.values()) >= 2, ex.task_attempts
    _settle()


# ------------------------------------------------------------- backoff unit
def test_backoff_spacing_grows_and_is_deterministic():
    from trino_tpu.server.cluster import _backoff_s

    a = [_backoff_s("t42", k, base=0.1, cap=60.0) for k in range(1, 8)]
    assert a == sorted(a) and a[0] < a[-1]  # grows
    assert a == [_backoff_s("t42", k, base=0.1, cap=60.0)
                 for k in range(1, 8)]  # deterministic
    # jitter separates keys without breaking growth
    b = [_backoff_s("t43", k, base=0.1, cap=60.0) for k in range(1, 8)]
    assert a != b
    # cap holds
    assert _backoff_s("t42", 30, base=0.1, cap=2.5) == 2.5
    # unbounded attempts (heartbeat misses of a never-returning worker) must
    # not overflow float — pre-clamp this raised OverflowError at ~1025,
    # killing the heartbeat daemon thread
    assert _backoff_s("t42", 5000, base=0.25, cap=5.0) == 5.0


def test_operator_targeted_site_glob_fires():
    """The documented addressing contract: a rule's site glob matches the
    composed "<Op>#<k>/<site>" label (operator targeting, the module
    docstring's own example) AND the bare chokepoint tag.  Regression: the
    chokepoints used to pass only the bare tag, so ``site=Aggregate*``
    silently matched nothing and a chaos run passed vacuously."""
    engine = Engine()
    engine.register_catalog("tpch",
                            TpchConnector(sf=0.01, split_rows=1 << 11))
    session = engine.create_session("tpch")
    sql = "select l_returnflag, count(*) c from lineitem group by l_returnflag"
    expected = engine.execute_sql(sql, session).rows()
    for glob in ("Aggregate*",        # operator-composed label
                 "agg.*"):            # bare site tag
        with faults.injected(
                f"point=dispatch,site={glob},action=delay,s=0,every=1"
        ) as plan:
            got = engine.execute_sql(sql, session).rows()
        assert plan.total_fires() >= 1, \
            f"site={glob} matched no dispatch: {plan.stats()}"
        assert got == expected
    _leak_check(engine)
    engine._invalidate()


@pytest.fixture(scope="module")
def dist_chaos():
    """Throwaway small engine + 8-worker mesh + local baselines for the
    distributed-exchange matrix (round 18): the mesh path must fail typed on
    injected exchange faults and recover byte-identically from delays."""
    import jax

    from trino_tpu.execution.chaos_matrix import DIST_QUERIES
    from trino_tpu.parallel.mesh import worker_mesh

    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    engine = Engine()
    engine.register_catalog("tpch",
                            TpchConnector(sf=0.02, split_rows=1 << 12))
    session = engine.create_session("tpch")
    mesh = worker_mesh(8)
    baselines = {k: _sig(engine.execute_sql(sql, session))
                 for k, sql in DIST_QUERIES.items()}
    return engine, session, mesh, baselines


@pytest.mark.parametrize("name,query,spec,kind", DIST_SCENARIOS,
                         ids=[s[0] for s in DIST_SCENARIOS])
def test_distributed_exchange_fault_matrix(dist_chaos, name, query, spec,
                                           kind):
    from trino_tpu.execution.chaos_matrix import (DIST_QUERIES,
                                                  run_dist_scenario)

    engine, session, mesh, baselines = dist_chaos
    rec = run_dist_scenario(engine, DIST_QUERIES[query], session, mesh,
                            baselines[query], name, spec, kind)
    assert rec.get("ok"), rec


def test_reannounce_resets_heartbeat_probe_backoff(tmp_path):
    """A worker that re-announces after a probe-failure streak must be
    probe-able immediately: stale ``next_probe`` backoff otherwise blinds the
    failure detector to a second death for the rest of the window."""
    from trino_tpu.server.cluster import ClusterCoordinator

    coord = ClusterCoordinator(Engine(), str(tmp_path / "spool"))
    coord._announce("w0", "http://127.0.0.1:1")
    w = coord.workers["w0"]
    w.alive, w.misses, w.next_probe = False, 3, time.time() + 999.0
    coord._announce("w0", "http://127.0.0.1:1")
    assert w.alive and w.misses == 0
    assert w.next_probe == 0.0


def test_metrics_export_fault_and_retry_counters():
    from trino_tpu.server.server import CoordinatorServer

    engine = Engine()
    engine.register_catalog("tpch",
                            TpchConnector(sf=0.01, split_rows=1 << 11))
    session = engine.create_session("tpch")
    with faults.injected("point=dispatch,action=delay,s=0,every=1"):
        engine.execute_sql("select count(*) from nation", session)
    assert engine.counters_total.faults_injected > 0
    text = CoordinatorServer(engine)._metrics_text()
    assert "# TYPE trino_tpu_faults_injected_total counter" in text
    assert "# TYPE trino_tpu_task_retries_total counter" in text
    import re

    m = re.search(r"^trino_tpu_faults_injected_total (\d+)$", text, re.M)
    assert m and int(m.group(1)) > 0, text
    engine._invalidate()


# -------------------------------------------------------- distributed matrix
CATALOGS = {"tpch": {"connector": "tpch", "sf": 0.01, "split_rows": 1 << 11}}


def _cluster(tmp_path, n_workers=2, **coord_kw):
    from trino_tpu.server.cluster import ClusterCoordinator, WorkerServer

    engine = Engine()
    engine.register_catalog("tpch",
                            TpchConnector(sf=0.01, split_rows=1 << 11))
    kw = dict(heartbeat_interval=0.2, retry_backoff_s=0.05,
              retry_backoff_cap_s=1.0)
    kw.update(coord_kw)
    coord = ClusterCoordinator(engine, str(tmp_path / "spool"), **kw)
    url = coord.start()
    workers = []
    for i in range(n_workers):
        w = WorkerServer(CATALOGS, str(tmp_path / "spool"),
                         coordinator_url=url, node_id=f"w{i}")
        w.start()
        workers.append(w)
    coord.wait_for_workers(n_workers, timeout=60)
    return engine, coord, workers


def _stop_cluster(coord, workers):
    for w in workers:
        try:
            w.stop()
        except Exception:
            pass
    coord.stop()


@pytest.mark.slow
def test_distributed_q9_retries_injected_task_fault(tmp_path):
    """A retryable worker-task fault burns one attempt; the coordinator
    re-dispatches on the backoff curve and the distributed q9 still matches
    local execution byte for byte.  task_retries reaches the merged query
    counters and the retry schedule records the backoff."""
    engine, coord, workers = _cluster(tmp_path)
    try:
        expected = engine.execute_sql(QUERIES["q9"]).rows()
        with faults.injected("point=task,action=error,nth=1") as plan:
            got = coord.execute_sql(QUERIES["q9"]).rows()
        assert got == expected
        assert plan.total_fires() == 1
        assert coord.local_fallbacks == 0, coord.last_fallback_error
        assert coord.last_query_counters.task_retries >= 1
        assert coord.last_retry_schedule, "no backoff was scheduled"
        _leak_check(engine)
    finally:
        _stop_cluster(coord, workers)


@pytest.mark.slow
def test_distributed_worker_crash_mid_query_recovers(tmp_path):
    """kill_worker: one worker's HTTP plane dies mid-task (a crashed node,
    not a drained one).  The failure detector gates it out on its backoff
    schedule, the task re-dispatches to the survivor, and the query answer
    is unchanged."""
    engine, coord, workers = _cluster(tmp_path, task_timeout=8.0)
    try:
        expected = engine.execute_sql(QUERIES["q1"]).rows()
        with faults.injected("point=task,action=kill_worker,nth=1"):
            got = coord.execute_sql(QUERIES["q1"]).rows()
        assert got == expected
        # exactly one worker crashed; the detector notices within its window
        deadline = time.time() + 10
        while time.time() < deadline:
            if sum(1 for w in coord.workers.values() if not w.alive) >= 1:
                break
            time.sleep(0.1)
        assert sum(1 for w in coord.workers.values() if not w.alive) == 1
        _leak_check(engine)
    finally:
        _stop_cluster(coord, workers)


@pytest.mark.slow
def test_distributed_dropped_commit_redispatches(tmp_path):
    """exchange_write drop: a worker task completes but its spool commit is
    silently lost.  The coordinator's deadline expires, the task burns an
    attempt (with backoff) and the re-dispatch commits — the result is
    unchanged and the retry is visible in the counters.  task_timeout must
    clear the workers' cold fragment compiles (tasks REFUSED past the
    timeout also burn attempts), and the retry budget is opened up so
    compile-time refusals cannot exhaust it before the dropped commit's
    deadline fires."""
    engine, coord, workers = _cluster(tmp_path, task_timeout=25.0,
                                      max_query_retries=1000)
    try:
        expected = engine.execute_sql(QUERIES["q1"]).rows()
        with faults.injected(
                "point=exchange_write,action=drop,nth=1") as plan:
            got = coord.execute_sql(QUERIES["q1"]).rows()
        assert got == expected
        assert plan.total_fires() == 1
        assert coord.local_fallbacks == 0, coord.last_fallback_error
        assert coord.last_query_counters.task_retries >= 1
        _leak_check(engine)
    finally:
        _stop_cluster(coord, workers)


@pytest.mark.slow
def test_distributed_redispatch_spacing_grows(tmp_path):
    """Acceptance: re-dispatch attempt spacing GROWS.  Task t0 fails twice
    (site-targeted injection), succeeds on the third attempt; the recorded
    backoff schedule shows attempt 2's delay strictly above attempt 1's and
    the query result is unchanged."""
    engine, coord, workers = _cluster(tmp_path, max_attempts=10)
    try:
        expected = engine.execute_sql(QUERIES["q1"]).rows()
        with faults.injected(
                "point=task,site=*.t0,action=error,every=1,times=2") as plan:
            got = coord.execute_sql(QUERIES["q1"]).rows()
        assert got == expected
        assert plan.total_fires() == 2
        t0 = sorted((a, d) for tid, a, d in coord.last_retry_schedule
                    if tid == "t0")
        assert len(t0) >= 2, coord.last_retry_schedule
        assert t0[1][1] > t0[0][1], t0  # spacing grew
        _leak_check(engine)
    finally:
        _stop_cluster(coord, workers)


@pytest.mark.slow
def test_distributed_retry_budget_is_enforced(tmp_path):
    """Acceptance: the per-query retry budget is enforced — a permanently
    failing task set stops retrying at max_query_retries with the budget
    named in the error (the coordinator then degrades to local execution,
    its designed last resort, so the query still answers)."""
    engine, coord, workers = _cluster(tmp_path, max_attempts=10,
                                      max_query_retries=3)
    try:
        expected = engine.execute_sql(QUERIES["q1"]).rows()
        with faults.injected("point=task,action=error,every=1,times=1000"):
            got = coord.execute_sql(QUERIES["q1"]).rows()
        assert got == expected  # local degrade answered
        assert coord.local_fallbacks == 1
        assert "retry budget exhausted" in (coord.last_fallback_error or "")
        assert "max_query_retries=3" in coord.last_fallback_error
        assert len(coord.last_retry_schedule) <= 3
        _leak_check(engine)
    finally:
        _stop_cluster(coord, workers)
