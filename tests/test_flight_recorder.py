"""Flight recorder, stitched traces, and wall-clock decomposition (round 16).

The tentpole's three pieces and their contracts:

- ``execution/flightrecorder.FlightRecorder`` — one record per completed OR
  errored statement (counters, span tree, wall breakdown, plan-actuals),
  in-memory ring always, on-disk JSONL ring under TRINO_TPU_FLIGHT_DIR with
  byte-budget eviction, readable from a DEAD process's directory; appended
  under cache-store guard discipline (a recorder failure never fails the
  query; zero device work — test_query_budgets pins the ceilings with the
  recorder ENABLED).
- stitched distributed traces — the coordinator propagates the query's trace
  id + root-span id through /v1/task, worker task spans ship back and
  re-parent under the query root: ONE OTLP tree per distributed query.
- ``tracing.wall_breakdown`` — the span tree decomposed into named wall
  buckets (plan / split generation / h2d / device dispatch / host pull /
  exchange wait / admission queue / retry backoff / unattributed) that sum
  to the reported wall by construction.
"""

import json
import os
import time
import urllib.error
import urllib.request

import pytest

from trino_tpu.execution.flightrecorder import (FlightRecorder,
                                                pressure_rung,
                                                read_flight_dir)
from trino_tpu.execution.tracing import (WALL_BUCKETS, format_wall_breakdown,
                                         wall_breakdown)

QUERY = """select l_returnflag, sum(l_quantity) q, count(*) c
           from lineitem where l_shipdate <= date '1998-09-02'
           group by l_returnflag order by l_returnflag"""


# ---------------------------------------------------------------- unit layer
def _span(name, start, end, span_id=None, parent=None, trace="q"):
    return {"name": name, "trace_id": trace, "span_id": span_id or id(name),
            "parent_id": parent, "start_s": start, "end_s": end,
            "attributes": {}, "status": "OK"}


def test_wall_breakdown_buckets_and_sum():
    """Disjoint sweep attribution: overlapped background staging never
    double-counts against foreground dispatch, and every bucket plus the
    unattributed remainder sums to the wall exactly."""
    spans = [
        _span("query", 0.0, 10.0, span_id=1),
        _span("planner", 0.5, 1.5, span_id=2, parent=1),
        _span("dispatch", 2.0, 5.0, span_id=3, parent=1),
        # h2d prefetch fully overlapping the dispatch: the slice charges to
        # the dispatch (foreground), the non-overlapped tail to h2d
        _span("prefetch", 4.0, 6.0, span_id=4, parent=1),
        _span("host_pull", 7.0, 8.0, span_id=5, parent=1),
    ]
    bd = wall_breakdown(spans, queued_s=0.25)
    assert bd["plan"] == pytest.approx(1.0)
    assert bd["device_dispatch"] == pytest.approx(3.0)
    assert bd["h2d"] == pytest.approx(1.0)  # only the 5.0-6.0 tail
    assert bd["host_pull"] == pytest.approx(1.0)
    assert bd["admission_queue"] == pytest.approx(0.25)
    assert bd["unattributed"] == pytest.approx(4.0)
    assert bd["wall_s"] == pytest.approx(10.25)
    total = sum(bd[b] for b in WALL_BUCKETS)
    assert total == pytest.approx(bd["wall_s"], rel=1e-6)
    # explicit-window form (EXPLAIN ANALYZE): clipped + summed the same way
    bd2 = wall_breakdown(spans, window=(2.0, 6.0))
    assert bd2["device_dispatch"] == pytest.approx(3.0)
    assert bd2["plan"] == 0.0
    assert bd2["wall_s"] == pytest.approx(4.0)
    # no closed root span and no window -> no breakdown (never fabricated)
    assert wall_breakdown([_span("dispatch", 0, 1)]) is None
    line = format_wall_breakdown(bd)
    assert line.startswith("Wall breakdown:") and "device_dispatch" in line


def test_pressure_rung_derivation():
    assert pressure_rung(None) is None
    assert pressure_rung({"admission_queued": 1}) == "admission-queue"
    assert pressure_rung({"spill_tier_hbm": 10}) == "spill-hbm"
    assert pressure_rung({"spill_tier_hbm": 1, "spill_tier_disk": 2}) \
        == "spill-disk"


def test_recorder_ring_eviction_and_dead_process_readback(tmp_path):
    """Tiny byte budget: the disk ring stays bounded, oldest records evict,
    the newest survives even when one record alone exceeds the budget — and
    a FRESH reader (the dead-process post-mortem path) sees exactly what is
    on disk, skipping a torn tail."""
    d = str(tmp_path / "flight")
    fr = FlightRecorder(flight_dir=d, disk_budget=4000, max_records=16)
    pad = "x" * 300  # ~400B/record -> eviction after ~10
    for i in range(40):
        fr.record_query({"query_id": f"q{i}", "state": "FINISHED",
                         "sql": pad, "wall_s": 0.1})
    assert fr.disk_evictions > 0
    # bounded: budget + one active segment of slack
    assert fr.disk_bytes() <= 4000 + 4000 // 8 + 600
    recs = read_flight_dir(d)
    assert recs, "nothing readable from the ring"
    ids = [r["query_id"] for r in recs]
    assert "q39" in ids and "q0" not in ids  # newest kept, oldest evicted
    assert ids == sorted(ids, key=lambda q: int(q[1:]))  # oldest-first order
    # torn tail (process died mid-write): skipped, records before it survive
    segs = sorted(p for p in os.listdir(d) if p.endswith(".jsonl"))
    with open(os.path.join(d, segs[-1]), "ab") as f:
        f.write(b'{"query_id": "torn...')
    recs2 = read_flight_dir(d)
    assert [r["query_id"] for r in recs2] == ids
    # in-memory ring independently bounded
    assert len(fr.snapshot()) == 16


def test_record_shape_success_and_error(engine):
    """Completed AND errored statements both land, typed: the errored
    record carries the state machine's error and still has counters/trace."""
    s = engine.create_session("tpch")
    engine.execute_sql(QUERY, s)
    qid = engine.last_query_trace["query_id"]
    rec = engine.flight_recorder.get(qid)
    assert rec is not None and rec["kind"] == "query"
    assert rec["state"] == "FINISHED" and rec["error"] is None
    assert rec["counters"]["device_dispatches"] > 0
    assert rec["counters"]["sites"]
    assert rec["trace"]["spans"] and rec["trace"]["root_span_s"] > 0
    assert rec["sql"].startswith("select")  # normalized text
    bd = rec["wall_breakdown"]
    assert bd and abs(sum(bd[b] for b in WALL_BUCKETS) - bd["wall_s"]) \
        <= 0.05 * bd["wall_s"]
    # errored statement: recorded, typed, state FAILED
    before = engine.flight_recorder.records_total
    with pytest.raises(Exception):
        engine.execute_sql("select no_such_column from lineitem", s)
    recs = engine.flight_recorder.snapshot(kind="query")
    assert engine.flight_recorder.records_total == before + 1
    err = recs[-1]
    assert err["state"] == "FAILED"
    assert err["error"] and "no_such_column" in err["error"]


def test_recorder_failure_never_fails_query(engine):
    """Guard discipline: a recorder that raises (full disk, broken encoder)
    must leave the statement successful — same contract as cache stores."""
    fr = engine.flight_recorder
    orig = fr.record_query
    calls = []

    def boom(rec):
        calls.append(rec)
        raise RuntimeError("disk full")

    fr.record_query = boom
    try:
        res = engine.execute_sql("select count(*) from nation",
                                 engine.create_session("tpch"))
        assert res.rows()[0][0] == 25
        assert calls, "recorder was never consulted"
    finally:
        fr.record_query = orig
    # the recorder's own internal guard counts failures instead of raising
    bad = FlightRecorder(flight_dir="/nonexistent/\0bad", disk_budget=100,
                         max_records=4)
    assert bad.record_query({"query_id": "q", "state": "FINISHED"}) is None
    assert bad.failures == 1


def test_chaos_fatal_injection_record_and_leak_clean():
    """Acceptance: the flight record for an ERRORED (chaos ``fatal``) query
    is present, typed, and the engine passes the chaos leak check after."""
    from trino_tpu import Engine
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.execution import faults
    from trino_tpu.execution.chaos_matrix import leak_report
    from trino_tpu.execution.faults import FatalInjectedFaultError

    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 11))
    s = e.create_session("tpch")
    e.execute_sql(QUERY, s)  # warm: the fault hits a compiled dispatch
    with faults.injected("point=dispatch,action=fatal,nth=1"):
        with pytest.raises(FatalInjectedFaultError):
            e.execute_sql(QUERY, s)
    rec = e.flight_recorder.snapshot(kind="query")[-1]
    assert rec["state"] == "FAILED"
    # typed: the record names the injected fault's point/site/rule, the
    # same text the raised FatalInjectedFaultError carried
    assert "injected fatal at dispatch" in (rec["error"] or "")
    assert rec["counters"]["faults_injected"] == 1
    leaks = leak_report(e)
    assert not leaks, leaks
    e._invalidate()


def test_stall_reports_fold_into_recorder(engine):
    """Satellite: StallWatchdog reports append as flight EVENTS (kind=stall)
    through the engine's on_stall hook."""
    report = {"detected_at_s": time.time(), "threshold_s": 1.0,
              "stalled": [{"label": "HashJoin#2/probe.step",
                           "elapsed_s": 9.9}], "inflight_depth": 1}
    before = len(engine.flight_recorder.snapshot(kind="stall"))
    engine._on_stall(dict(report))
    stalls = engine.flight_recorder.snapshot(kind="stall")
    assert len(stalls) == before + 1
    assert stalls[-1]["stalled"][0]["label"] == "HashJoin#2/probe.step"
    assert engine.last_stall_report["threshold_s"] == 1.0


# ------------------------------------------------------------- HTTP surfaces
@pytest.fixture()
def flight_server(engine):
    from trino_tpu.server.server import CoordinatorServer

    srv = CoordinatorServer(engine, port=0)
    srv.start()
    yield srv
    srv.stop()


def test_trace_endpoint_serves_completed_statements_from_recorder(
        flight_server, engine):
    """Satellite: /v1/query/{id}/trace resolves AFTER later statements land
    — served from the flight recorder, not the live-tracer slot (proven by
    clearing the tracer's finished ring before the fetch)."""
    s = engine.create_session("tpch")
    engine.execute_sql(QUERY, s)
    qid = engine.last_query_trace["query_id"]
    engine.execute_sql("select count(*) from region", s)  # a later statement
    with engine.tracer._lock:
        engine.tracer.finished.clear()  # live tracer can no longer serve it
    payload = json.loads(urllib.request.urlopen(
        flight_server.url + f"/v1/query/{qid}/trace", timeout=10)
        .read().decode())
    spans = payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
    names = {sp["name"] for sp in spans}
    assert "query" in names and "dispatch" in names
    roots = [sp for sp in spans if sp["parentSpanId"] == ""]
    assert len(roots) == 1 and roots[0]["name"] == "query"


def test_flight_http_endpoints_and_query_log(flight_server, engine):
    s = engine.create_session("tpch")
    engine.execute_sql(QUERY, s)
    qid = engine.last_query_trace["query_id"]
    idx = json.loads(urllib.request.urlopen(
        flight_server.url + "/v1/flight", timeout=10).read().decode())
    assert idx["info"]["enabled"] and idx["info"]["records"] > 0
    assert any(r["query_id"] == qid for r in idx["records"])
    rec = json.loads(urllib.request.urlopen(
        flight_server.url + f"/v1/flight/{qid}", timeout=10).read().decode())
    assert rec["state"] == "FINISHED" and rec["wall_breakdown"]
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(flight_server.url + "/v1/flight/nope",
                               timeout=10)
    assert exc.value.code == 404
    # system.runtime.query_log: the SQL twin — per-statement counters and
    # flattened breakdown buckets
    r = engine.execute_sql(
        "select query_id, state, device_dispatches, device_dispatch_s, "
        "unattributed_s from system.query_log", s)
    rows = r.rows()
    mine = [row for row in rows if row[0] == qid]
    assert mine, rows[:5]
    assert mine[0][1] == "FINISHED" and mine[0][2] > 0
    assert mine[0][3] is not None and mine[0][4] is not None


def test_metrics_flight_series(flight_server, engine):
    """Satellite: recorder records/bytes gauges + stitched-span counters
    pass the strict Prometheus parse."""
    from test_profiling import _parse_prometheus

    engine.execute_sql("select count(*) from nation",
                       engine.create_session("tpch"))
    body = urllib.request.urlopen(
        flight_server.url + "/v1/metrics", timeout=10).read().decode()
    parsed = _parse_prometheus(body)
    assert parsed["types"]["trino_tpu_flight_records"] == "gauge"
    assert parsed["samples"]["trino_tpu_flight_records"][0][1] > 0
    assert parsed["types"]["trino_tpu_flight_disk_bytes"] == "gauge"
    assert parsed["types"]["trino_tpu_flight_records_total"] == "counter"
    assert parsed["samples"]["trino_tpu_flight_records_total"][0][1] > 0
    assert parsed["types"]["trino_tpu_flight_spans_total"] == "counter"
    assert parsed["samples"]["trino_tpu_flight_spans_total"][0][1] > 0
    assert parsed["types"]["trino_tpu_flight_worker_spans_total"] == "counter"
    assert parsed["types"]["trino_tpu_flight_record_failures_total"] \
        == "counter"


# ---------------------------------------------------------- stitched cluster
def test_in_process_cluster_one_stitched_trace(tmp_path):
    """Acceptance: a distributed query produces ONE stitched OTLP trace —
    every worker task span carries the query's trace id and parents under
    the coordinator's root span; the flight record carries the whole tree."""
    from trino_tpu import Engine
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.execution.tracing import spans_to_otlp
    from trino_tpu.server.cluster import ClusterCoordinator, WorkerServer

    CATALOGS = {"tpch": {"connector": "tpch", "sf": 0.01,
                         "split_rows": 1 << 11}}
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 11))
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.2)
    url = coord.start()
    w = WorkerServer(CATALOGS, str(tmp_path / "spool"), coordinator_url=url,
                     node_id="inproc")
    w.start()
    try:
        coord.wait_for_workers(1, timeout=60)
        expected = e.execute_sql(QUERY).rows()
        got = coord.execute_sql(QUERY).rows()
        assert got == expected
        assert coord.local_fallbacks == 0, coord.last_fallback_error
        t = coord.last_query_trace
        qid = t["query_id"]
        spans = t["spans"]
        # ONE trace id across coordinator and workers
        assert {sp["trace_id"] for sp in spans} == {qid}
        roots = [sp for sp in spans if sp["parent_id"] is None]
        assert len(roots) == 1 and roots[0]["name"] == "query"
        # worker task spans present and parented DIRECTLY under the root
        tasks = [sp for sp in spans if sp["name"] == "task"]
        assert tasks, "no worker task spans stitched"
        assert all(sp["parent_id"] == roots[0]["span_id"] for sp in tasks)
        # parent integrity: no orphans anywhere in the stitched tree
        ids = {sp["span_id"] for sp in spans}
        for sp in spans:
            if sp["parent_id"] is not None:
                assert sp["parent_id"] in ids, sp
        assert coord.stitched_spans_total >= len(tasks)
        # the OTLP rendering keeps it one tree under one traceId
        otlp = spans_to_otlp(spans)
        ospans = otlp["resourceSpans"][0]["scopeSpans"][0]["spans"]
        assert len({sp["traceId"] for sp in ospans}) == 1
        # flight record: distributed, stitched span count stamped
        rec = e.flight_recorder.get(qid)
        assert rec is not None and rec.get("distributed")
        assert rec["worker_spans"] >= len(tasks)
        assert rec["trace"]["spans"]
        bd = rec["wall_breakdown"]
        assert bd and abs(sum(bd[b] for b in WALL_BUCKETS) - bd["wall_s"]) \
            <= 0.05 * bd["wall_s"]
        # legacy surface still carries the worker half
        names = {sp["name"] for sp in coord.last_query_worker_spans}
        assert "task" in names and "dispatch" in names
    finally:
        w.stop()
        coord.stop()
        e._invalidate()
