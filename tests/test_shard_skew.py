"""Per-shard skew & straggler attribution over the distributed path (round
20).

The device-resident exchange already pulls per-worker receive cursors and
occupancy counts at its existing flag sites; round 20 folds those
already-host ints into ShardStats records on ``QueryCounters.shard_stats``
— per-worker load, max/mean skew ratio, argmax worker, imbalance wall —
with ZERO new pull sites (test_boundary_lint's frozen pull-site rule and
test_distributed_budgets' unchanged ceilings are the enforcement).

This module pins the detection contract on the 8-device CPU mesh: a
memory-connector table where >=80% of rows share one sort key must report a
routing-exchange skew ratio >= 4x (range partitioning lands the hot run on
one worker) while a uniform control stays <= 1.5x, byte-identical to local
execution in both cases; the same single run must surface the record in
EXPLAIN ANALYZE, the flight record, and /v1/metrics.  Plus the round-20
wall-breakdown satellite: the distributed q3's exchange.route/merge spans
land in the ``exchange_wait`` bucket and the buckets still sum to wall_s.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pandas as pd
import pytest

import jax

from trino_tpu import Engine
from trino_tpu.execution.tracing import (SHARD_STATS_MAX, QueryCounters,
                                         record_shard_stats, shard_skew,
                                         track_counters)
from trino_tpu.parallel.mesh import worker_mesh

N_ROWS = 20000
HOT_KEY = 7
HOT_FRACTION = 0.85  # >= the 80% the round-20 issue specifies


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) >= 8, "conftest must force 8 CPU devices"
    return worker_mesh(8)


@pytest.fixture(scope="module")
def skew_engine():
    """Memory-connector engine with a hot-key table (>=80% of rows share one
    sort key -> range partitioning piles them on one worker) and a uniform
    control of identical shape."""
    from trino_tpu.connectors.memory import MemoryConnector

    e = Engine()
    mem = MemoryConnector()
    e.register_catalog("mem", mem)
    s = e.create_session("mem")
    rng = np.random.default_rng(20)
    n_hot = int(N_ROWS * HOT_FRACTION)
    e.execute_sql("create table hot (k bigint, v double)", s)
    hot_k = np.concatenate([
        np.full(n_hot, HOT_KEY, np.int64),
        rng.integers(1000, 2000, N_ROWS - n_hot).astype(np.int64)])
    vs = np.round(rng.uniform(0, 1000, N_ROWS), 3)
    mem.append("hot", [hot_k.tolist(), vs.tolist()])
    e.execute_sql("create table uni (k bigint, v double)", s)
    uni_k = rng.permutation(N_ROWS).astype(np.int64)
    mem.append("uni", [uni_k.tolist(), vs.tolist()])
    return e, s


HOT_SQL = "select k, v from hot order by k, v"
UNI_SQL = "select k, v from uni order by k, v"


def _frames_equal(a: pd.DataFrame, b: pd.DataFrame):
    assert len(a) == len(b)
    for ca, cb in zip(a.columns, b.columns):
        np.testing.assert_array_equal(a[ca].to_numpy(), b[cb].to_numpy(),
                                      err_msg=ca)


def _routing_records(counters):
    """The ShardStats records of the statement's routing exchange(s) —
    either exchange mode (the device gate may decline a host-fed or
    seeded-sample collect and fall back to the spool; both record)."""
    return [r for r in counters.shard_stats if r.get("kind") == "exchange"]


# ---------------------------------------------------------------- unit layer


def test_shard_skew_arithmetic():
    rec = shard_skew([80, 10, 10, 0])
    assert rec["workers"] == 4 and rec["max"] == 80
    assert rec["mean"] == 25.0 and rec["worker"] == 0
    assert rec["ratio"] == pytest.approx(3.2)
    # degenerate: empty exchange -> neutral ratio, no div-by-zero
    z = shard_skew([0, 0, 0])
    assert z["ratio"] == 1.0 and z["max"] == 0
    assert shard_skew([])["workers"] == 0


def test_record_shard_stats_accumulates_and_caps():
    c = QueryCounters()
    with track_counters(c):
        rec = record_shard_stats("dist.exchange.flags", [30, 10],
                                 wall_s=2.0, kind="exchange", op="Sort",
                                 bytes_per_row=16)
        for _ in range(SHARD_STATS_MAX + 8):
            record_shard_stats("dist.agg.overflow", [5, 5],
                               kind="occupancy")
    assert rec["ratio"] == pytest.approx(1.5)
    # imbalance = (max - mean)/max * wall = (30-20)/30 * 2
    assert rec["imbalance_s"] == pytest.approx(2.0 / 3.0)
    assert rec["bytes"] == [480, 160]
    assert len(c.shard_stats) == SHARD_STATS_MAX  # bounded ring
    # snapshot/merge/as_dict carry the records; empty counters emit none
    snap = c.snapshot()
    assert len(snap.shard_stats) == SHARD_STATS_MAX
    other = QueryCounters()
    other.merge(snap)
    assert len(other.shard_stats) == SHARD_STATS_MAX
    assert "shard_stats" in c.as_dict()
    assert "shard_stats" not in QueryCounters().as_dict()


# ------------------------------------------------------- detection contract


def test_hot_key_skew_detected(skew_engine, mesh8):
    """The tentpole acceptance: >=80%-one-key table through the mesh reports
    a routing-exchange skew ratio >= 4x, byte-identical to local."""
    e, s = skew_engine
    local = e.execute_sql(HOT_SQL, s).to_pandas()
    dist = e.execute_sql(HOT_SQL, s, distributed=True,
                         mesh=mesh8).to_pandas()
    _frames_equal(dist, local)
    recs = _routing_records(e.last_query_counters)
    assert recs, "no routing-exchange ShardStats recorded"
    worst = max(r["ratio"] for r in recs)
    assert worst >= 4.0, recs
    hot = max(recs, key=lambda r: r["ratio"])
    # the hot worker holds the dominant share of the routed rows
    assert hot["rows"][hot["worker"]] >= 0.5 * sum(hot["rows"]), hot
    assert hot["imbalance_s"] >= 0.0 and hot["wall_s"] >= 0.0


def test_uniform_control_stays_balanced(skew_engine, mesh8):
    e, s = skew_engine
    local = e.execute_sql(UNI_SQL, s).to_pandas()
    dist = e.execute_sql(UNI_SQL, s, distributed=True,
                         mesh=mesh8).to_pandas()
    _frames_equal(dist, local)
    recs = _routing_records(e.last_query_counters)
    assert recs, "no routing-exchange ShardStats recorded"
    assert max(r["ratio"] for r in recs) <= 1.5, recs


def test_one_run_three_surfaces(skew_engine, mesh8):
    """The issue's acceptance criterion: ONE hot-key run surfaces its skew
    in EXPLAIN ANALYZE, the flight record, and /v1/metrics."""
    from trino_tpu.server.server import CoordinatorServer

    e, s = skew_engine
    r = e.execute_sql(f"explain analyze {HOT_SQL}", s,
                      distributed=True, mesh=mesh8)
    text = "\n".join(r.columns[0].tolist())
    assert "[skew: max/mean " in text, text
    assert "Skew: " in text, text
    # the plain (non-explain) run's flight record carries the raw records
    e.execute_sql(HOT_SQL, s, distributed=True, mesh=mesh8)
    qid = e.last_query_trace["query_id"]
    rec = e.flight_recorder.get(qid)
    assert rec is not None and rec.get("shard_stats"), rec
    assert max(float(x["ratio"]) for x in rec["shard_stats"]) >= 4.0
    # /v1/metrics: worst-ratio gauge + per-worker load of the last record
    body = CoordinatorServer(e)._metrics_text()
    assert "trino_tpu_exchange_skew_ratio " in body
    line = [ln for ln in body.splitlines()
            if ln.startswith("trino_tpu_exchange_skew_ratio")][0]
    assert float(line.split()[-1]) >= 4.0, line
    assert 'trino_tpu_shard_rows{worker="0"' in body


def test_quiet_surfaces_without_skew(skew_engine):
    """Zero-is-silent discipline: a LOCAL statement records no shard stats,
    prints no Skew: line, and its query_log columns are NULL (the budget
    suites' EXPLAIN regexes and zero-device-work pins depend on this)."""
    e, s = skew_engine
    r = e.execute_sql(f"explain analyze {HOT_SQL}", s)
    text = "\n".join(r.columns[0].tolist())
    assert "Skew:" not in text and "[skew:" not in text
    assert not e.last_query_counters.shard_stats
    rows = e.execute_sql(
        "select skew_ratio, skew_imbalance_s from system.runtime.query_log",
        s).to_pandas()
    assert len(rows)  # the statements above are on the ring


def test_plan_history_carries_skew(skew_engine, mesh8):
    """r15-precedent record-and-expose: the skew facts land in the
    plan-history store under structural node paths WITHOUT touching the
    cardinality EWMAs the adaptive advisor reads."""
    e, s = skew_engine
    e.execute_sql(HOT_SQL, s, distributed=True, mesh=mesh8)
    ents = [ent for ent in e.plan_history.snapshot()
            if any("skew" in r for r in ent["nodes"].values())]
    assert ents, "no plan-history entry carries a skew fact"
    for ent in ents:
        for path, r in ent["nodes"].items():
            sk = r.get("skew")
            if sk is None:
                continue
            assert sk["ratio"] >= 1.0 and 0 <= sk["worker"] < sk["workers"]
            assert "ratio_ewma" in sk
            # the skew-only merge never fabricated cardinality actuals
            if r.get("executions", 0) == 0:
                assert not r.get("actual_rows"), (path, r)


def test_system_query_log_skew_columns(skew_engine, mesh8):
    e, s = skew_engine
    e.execute_sql(HOT_SQL, s, distributed=True, mesh=mesh8)
    rows = e.execute_sql(
        "select skew_ratio, skew_imbalance_s from system.runtime.query_log "
        "order by skew_ratio desc", s).to_pandas()
    top = rows.iloc[0]
    assert float(top["skew_ratio"]) >= 4.0
    assert float(top["skew_imbalance_s"]) >= 0.0


# -------------------------------------------------- wall-breakdown satellite


def test_distributed_q3_breakdown_has_exchange_bucket(mesh8):
    """Round-20 satellite: the mesh run's exchange.route/exchange.merge
    spans attribute to the ``exchange_wait`` bucket and the buckets still
    sum to wall_s (the round-16 structural contract holds on the
    distributed path)."""
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.execution.tracing import WALL_BUCKETS

    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 12))
    s = e.create_session("tpch")
    q3 = ("select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as "
          "revenue, o_orderdate, o_shippriority "
          "from customer, orders, lineitem "
          "where c_mktsegment = 'BUILDING' and c_custkey = o_custkey "
          "and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15' "
          "and l_shipdate > date '1995-03-15' "
          "group by l_orderkey, o_orderdate, o_shippriority "
          "order by revenue desc, o_orderdate limit 10")
    e.execute_sql(q3, s, distributed=True, mesh=mesh8)  # cold
    e.execute_sql(q3, s, distributed=True, mesh=mesh8)  # warm: measured
    t = e.last_query_trace
    names = {sp.get("name") for sp in t.get("spans") or []}
    assert "exchange.route" in names or "exchange.merge" in names, names
    bd = t.get("wall_breakdown")
    assert bd, "no wall breakdown on the distributed trace"
    assert bd.get("exchange_wait", 0.0) > 0.0, bd
    total = sum(bd[b] for b in WALL_BUCKETS)
    wall = bd["wall_s"]
    assert wall > 0 and abs(total - wall) <= 0.05 * wall, (total, wall, bd)


# ------------------------------------------------------ flight.py --skew CLI


def test_flight_skew_reader_is_jax_free(skew_engine, mesh8, tmp_path):
    """scripts/flight.py --skew decodes a dead process's ring without jax
    (same contract as the round-16 reader): run a hot-key statement with an
    on-disk flight ring, then read it back in a subprocess whose jax import
    is poisoned."""
    from trino_tpu.execution.flightrecorder import FlightRecorder

    e, s = skew_engine
    fdir = str(tmp_path / "flight_skew")
    rec = FlightRecorder(flight_dir=fdir, max_records=16)
    old = e.flight_recorder
    e.flight_recorder = rec
    try:
        e.execute_sql(HOT_SQL, s, distributed=True, mesh=mesh8)
    finally:
        e.flight_recorder = old
    env = dict(os.environ)
    # poison jax: the reader must not import it (round-16 contract)
    env["PYTHONPATH"] = str(tmp_path / "poison")
    (tmp_path / "poison").mkdir()
    (tmp_path / "poison" / "jax.py").write_text(
        "raise ImportError('flight.py must stay jax-free')\n")
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.dirname(__file__)),
                      "scripts", "flight.py"), fdir, "--skew"],
        capture_output=True, text=True, env=env, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "shard records" in out.stdout, out.stdout
    assert "worst " in out.stdout and "x" in out.stdout
    # and the summarize helper agrees with the raw record
    out_json = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.dirname(__file__)),
                      "scripts", "flight.py"), fdir, "--json"],
        capture_output=True, text=True, env=env, timeout=120)
    recs = [json.loads(ln) for ln in out_json.stdout.splitlines()
            if ln.strip()]
    assert any((r.get("shard_stats") or []) for r in recs)
