"""Iterative rule-based optimizer: Memo mechanics + one plan assertion per
rule + fixpoint behavior (reference test model: the per-rule BaseRuleTest
subclasses under sql/planner/iterative/rule/, e.g. TestMergeFilters, each
asserting on the rewritten plan shape)."""

import dataclasses

import numpy as np
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.page import Field, Schema
from trino_tpu.sql import ir
from trino_tpu.sql import plan as P
from trino_tpu.sql.frontend import compile_sql
from trino_tpu.sql.rules import (DEFAULT_RULES, IterativeOptimizer, Memo,
                                 optimize_plan)
from trino_tpu.types import BIGINT, BOOLEAN


def _scan():
    schema = Schema((Field("a", BIGINT), Field("b", BIGINT)))
    return P.TableScan("cat", "t", ("a", "b"), schema)


def _pred(ch, op, v):
    return ir.Call(op, (ir.FieldRef(ch, BIGINT), ir.Constant(v, BIGINT)),
                   BOOLEAN)


def _find(node, kind):
    out = []

    def walk(n):
        if isinstance(n, kind):
            out.append(n)
        for c in n.children:
            walk(c)

    walk(node)
    return out


def _opt(plan):
    return IterativeOptimizer(DEFAULT_RULES).run(plan)


def test_memo_roundtrip():
    plan = P.Limit(P.Filter(_scan(), _pred(0, "lt", 5)), 3)
    m = Memo(plan)
    assert m.extract() == plan  # insert + extract is identity


def test_merge_filters():
    plan = P.Filter(P.Filter(P.Filter(_scan(), _pred(0, "lt", 5)),
                             _pred(1, "gt", 1)), _pred(0, "gt", 0))
    out = _opt(plan)
    filters = _find(out, P.Filter)
    assert len(filters) == 1  # fixpoint: the whole chain merged
    # all three conjuncts survive in one AND tree
    assert "lt" in repr(filters[0].predicate)
    assert "gt" in repr(filters[0].predicate)


def test_merge_limits():
    plan = P.Limit(P.Limit(_scan(), 10), 3)
    out = _opt(plan)
    limits = _find(out, P.Limit)
    assert len(limits) == 1 and limits[0].count == 3
    plan = P.Limit(P.Limit(_scan(), 2), 7)
    assert _find(_opt(plan), P.Limit)[0].count == 2


def test_eliminate_limit_zero():
    plan = P.Limit(P.Filter(_scan(), _pred(0, "lt", 5)), 0)
    out = _opt(plan)
    assert isinstance(out, P.Values) and out.rows == ()
    assert not _find(out, P.TableScan)  # the pipeline under it is gone


def test_remove_identity_project():
    scan = _scan()
    plan = P.Project(scan, (ir.FieldRef(0, BIGINT), ir.FieldRef(1, BIGINT)),
                     scan.schema, None)
    out = _opt(P.Limit(plan, 5))
    assert not _find(out, P.Project)
    # a renaming projection is NOT removed
    renamed = Schema((Field("x", BIGINT), Field("y", BIGINT)))
    plan = P.Project(scan, (ir.FieldRef(0, BIGINT), ir.FieldRef(1, BIGINT)),
                     renamed, None)
    assert _find(_opt(P.Limit(plan, 5)), P.Project)


def test_eliminate_sort_under_aggregate():
    agg = P.Aggregate(
        P.Sort(_scan(), (P.SortKey(0),)), (0,),
        (P.AggSpec("count_star", None, "c", BIGINT),),
        Schema((Field("a", BIGINT), Field("c", BIGINT))))
    out = _opt(agg)
    assert not _find(out, P.Sort)
    # Sort directly under Limit (the TopN shape) is preserved
    topn = P.Limit(P.Sort(_scan(), (P.SortKey(0),)), 5)
    assert _find(_opt(topn), P.Sort)


def test_infer_join_side_filters():
    left, right = _scan(), _scan()
    join = P.Join(
        "inner", P.Filter(left, _pred(0, "lt", 100)), right, (0,), (1,),
        Schema(tuple(left.schema.fields) + tuple(right.schema.fields)))
    out = _opt(join)
    j = _find(out, P.Join)[0]
    # the right side gained the mirrored comparison on ITS key channel
    rfilters = _find(j.right, P.Filter)
    assert rfilters, "expected inferred filter on the build side"
    pred = rfilters[0].predicate
    assert isinstance(pred, ir.Call) and pred.op == "lt"
    ref, const = pred.args
    assert isinstance(ref, ir.FieldRef) and ref.index == 1  # right key channel
    assert ref.type == right.schema.fields[1].type  # destination field's type
    assert const.value == 100
    # outer joins must NOT infer (unmatched rows survive)
    outer = P.Join(
        "left", P.Filter(left, _pred(0, "lt", 100)), right, (0,), (1,),
        Schema(tuple(left.schema.fields) + tuple(right.schema.fields)))
    j2 = _find(_opt(outer), P.Join)[0]
    assert not _find(j2.right, P.Filter)


def test_rules_fixpoint_terminates():
    """Stacked rewrites converge: filters + limits + identity projects in one
    tree all fire without looping."""
    scan = _scan()
    plan = P.Limit(
        P.Limit(
            P.Project(
                P.Filter(P.Filter(scan, _pred(0, "lt", 5)), _pred(1, "gt", 1)),
                (ir.FieldRef(0, BIGINT), ir.FieldRef(1, BIGINT)),
                scan.schema, None),
            10),
        3)
    out = _opt(plan)
    assert len(_find(out, P.Filter)) == 1
    assert len(_find(out, P.Limit)) == 1
    assert not _find(out, P.Project)


# ------------------------------------------------------------- end-to-end SQL
@pytest.fixture(scope="module")
def tpch_engine():
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 11))
    return e, e.create_session("tpch")


def test_sql_limit_zero_short_circuits(tpch_engine):
    e, s = tpch_engine
    assert e.execute_sql(
        "select l_orderkey from lineitem limit 0", s).rows() == []


def test_sql_infer_join_filter_correct(tpch_engine):
    """Inference keeps results identical while the plan gains the mirrored
    filter (checked via the compiled plan)."""
    e, s = tpch_engine
    q = ("select count(*) c from lineitem, orders "
         "where l_orderkey = o_orderkey and o_orderkey < 1000")
    plan = compile_sql(q, e, s)
    joins = _find(plan, P.Join)
    assert joins
    assert _find(joins[0].left, P.Filter), "expected inferred probe-side filter"
    got = e.execute_sql(q, s).rows()
    # oracle: the filter on the join key holds on both sides by transitivity
    expected = e.execute_sql(
        "select count(*) c from lineitem, orders "
        "where l_orderkey = o_orderkey and o_orderkey < 1000 "
        "and l_orderkey < 1000", s).rows()
    assert got == expected


def test_sql_subquery_sort_removed_under_group_by(tpch_engine):
    e, s = tpch_engine
    q = ("select l_returnflag, count(*) c from "
         "(select * from lineitem order by l_orderkey) "
         "group by l_returnflag order by l_returnflag")
    plan = compile_sql(q, e, s)
    aggs = _find(plan, P.Aggregate)
    assert aggs and not _find(aggs[0].child, P.Sort)
    rows = e.execute_sql(q, s).rows()
    expected = e.execute_sql(
        "select l_returnflag, count(*) c from lineitem "
        "group by l_returnflag order by l_returnflag", s).rows()
    assert rows == expected


def test_push_filter_through_project():
    proj = P.Project(_scan(), (ir.FieldRef(1, BIGINT, "b"),
                               ir.FieldRef(0, BIGINT, "a")),
                     Schema((Field("b", BIGINT), Field("a", BIGINT))))
    plan = P.Filter(proj, _pred(0, "lt", 5))  # filters on OUTPUT channel 0 = b
    out = _opt(plan)
    assert isinstance(out, P.Project)
    filt = _find(out, P.Filter)
    assert len(filt) == 1
    # the rewritten predicate references INPUT channel 1 (column b)
    assert filt[0].predicate.args[0].index == 1
    assert isinstance(filt[0].child, P.TableScan)


def test_push_limit_through_project_keeps_topn():
    proj = P.Project(_scan(), (ir.FieldRef(0, BIGINT, "a"),
                               ir.FieldRef(1, BIGINT, "b")),
                     Schema((Field("a", BIGINT), Field("b", BIGINT))))
    out = _opt(P.Limit(proj, 7))
    # identity project is ALSO removed; the limit must sit under any project
    lims = _find(out, P.Limit)
    assert len(lims) == 1 and isinstance(lims[0].child, P.TableScan)
    # Limit(Project(Sort)) stays a TopN shape: the limit must NOT split from
    # its sort
    srt = P.Sort(_scan(), (P.SortKey(0, True, False),))
    proj2 = P.Project(srt, (ir.FieldRef(0, BIGINT, "a"),
                            ir.FieldRef(1, BIGINT, "bb")),
                      Schema((Field("a", BIGINT), Field("bb", BIGINT))))
    out2 = _opt(P.Limit(proj2, 7))
    lims2 = _find(out2, P.Limit)
    assert len(lims2) == 1


def test_remove_trivial_filter():
    t = _opt(P.Filter(_scan(), ir.Constant(True, BOOLEAN)))
    assert isinstance(t, P.TableScan)
    f = _opt(P.Filter(_scan(), ir.Constant(False, BOOLEAN)))
    assert isinstance(f, P.Values) and len(f.rows) == 0


def test_merge_unions_flattens():
    s = _scan()
    inner = P.Union((s, _scan()), s.schema)
    outer = P.Union((inner, _scan()), s.schema)
    out = _opt(outer)
    assert isinstance(out, P.Union)
    assert len(out.inputs) == 3
    assert all(isinstance(c, P.TableScan) for c in out.inputs)


def test_push_limit_through_union():
    s = _scan()
    u = P.Union((s, _scan()), s.schema)
    out = _opt(P.Limit(u, 5))
    assert isinstance(out, P.Limit)
    inner = out.child
    assert isinstance(inner, P.Union)
    assert all(isinstance(c, P.Limit) and c.count == 5 for c in inner.inputs)


def test_remove_redundant_limit_over_global_agg():
    agg = P.Aggregate(_scan(), (), (P.AggSpec("count_star", None, "c",
                                              BIGINT),),
                      Schema((Field("c", BIGINT),)))
    out = _opt(P.Limit(agg, 10))
    assert isinstance(out, P.Aggregate)


# ---------------------------------------------------------------- round-5 rules
def _join(kind="inner"):
    l = _scan()
    r_schema = Schema((Field("c", BIGINT), Field("d", BIGINT)))
    r = P.TableScan("cat", "u", ("c", "d"), r_schema)
    schema = Schema((Field("l0", BIGINT), Field("l1", BIGINT),
                     Field("r0", BIGINT), Field("r1", BIGINT)))
    if kind in ("semi", "anti"):
        schema = Schema((Field("l0", BIGINT), Field("l1", BIGINT)))
    return P.Join(kind, l, r, (0,), (0,), schema)


def test_push_filter_through_join_splits_sides():
    pred = ir.Call("and", (_pred(1, "gt", 5), _pred(3, "lt", 9)), BOOLEAN)
    out = _opt(P.Filter(_join("inner"), pred))
    join = _find(out, P.Join)[0]
    assert isinstance(out, P.Join) or not isinstance(out, P.Filter)
    lf = _find(join.left, P.Filter)
    rf = _find(join.right, P.Filter)
    assert lf and rf, "both side-local conjuncts must push below the join"
    # the right conjunct's channel remapped into build-side coordinates
    assert "$1" in repr(rf[0].predicate)


def test_push_filter_through_outer_join_keeps_build_conjunct():
    pred = ir.Call("and", (_pred(1, "gt", 5), _pred(3, "lt", 9)), BOOLEAN)
    out = _opt(P.Filter(_join("left"), pred))
    join = _find(out, P.Join)[0]
    assert _find(join.left, P.Filter), "probe conjunct pushes"
    assert not _find(join.right, P.Filter), \
        "NULL-extended build conjunct must NOT push below a left join"
    assert isinstance(out, P.Filter), "build conjunct stays above"


def test_push_filter_through_aggregate_keys_only():
    agg_schema = Schema((Field("a", BIGINT), Field("n", BIGINT)))
    agg = P.Aggregate(_scan(), (0,),
                      (P.AggSpec("count_star", None, "n", BIGINT),),
                      agg_schema)
    # key-channel conjunct pushes; agg-output conjunct stays
    pred = ir.Call("and", (_pred(0, "gt", 3), _pred(1, "lt", 100)), BOOLEAN)
    out = _opt(P.Filter(agg, pred))
    assert isinstance(out, P.Filter), "agg-output conjunct stays above"
    agg2 = _find(out, P.Aggregate)[0]
    inner_f = _find(agg2.child, P.Filter) + (
        [agg2.child] if isinstance(agg2.child, P.Filter) else [])
    assert inner_f, "key conjunct must push below the aggregation"


def test_push_filter_through_window_partition_keys():
    w_schema = Schema((Field("a", BIGINT), Field("b", BIGINT),
                       Field("rn", BIGINT)))
    spec = P.WindowSpec("row_number", None, (0,), (P.SortKey(1),),
                        "rn", BIGINT)
    win = P.Window(_scan(), (spec,), w_schema)
    pred = ir.Call("and", (_pred(0, "eq", 7), _pred(1, "gt", 2)), BOOLEAN)
    out = _opt(P.Filter(win, pred))
    assert isinstance(out, P.Filter), "non-partition conjunct stays above"
    win2 = _find(out, P.Window)[0]
    assert isinstance(win2.child, P.Filter), \
        "partition-key conjunct pushes below the window"


def test_push_filter_through_union_and_sort():
    u_schema = Schema((Field("a", BIGINT), Field("b", BIGINT)))
    u = P.Union((_scan(), _scan()), u_schema)
    out = _opt(P.Filter(u, _pred(0, "gt", 1)))
    assert not isinstance(out, P.Filter)
    union = _find(out, P.Union)[0]
    for c in union.children:
        assert _find(c, P.Filter) or isinstance(c, P.Filter)
    out2 = _opt(P.Filter(P.Sort(_scan(), (P.SortKey(0),)), _pred(0, "gt", 1)))
    assert isinstance(out2, P.Sort), "filter moves below the sort"


def test_empty_propagation_collapses_pipeline():
    # LIMIT 0 seeds an empty Values; everything above collapses with it
    plan = P.Sort(P.Filter(P.Limit(_scan(), 0), _pred(0, "gt", 1)),
                  (P.SortKey(0),))
    out = _opt(plan)
    assert isinstance(out, P.Values) and not out.rows
    # inner join with an empty side collapses too
    j = _join("inner")
    j = dataclasses.replace(j, right=P.Values((), j.right.schema))
    out2 = _opt(j)
    assert isinstance(out2, P.Values) and not out2.rows


def test_merge_adjacent_projects():
    s = _scan()
    inner = P.Project(s, (ir.FieldRef(1, BIGINT), ir.FieldRef(0, BIGINT)),
                      Schema((Field("x", BIGINT), Field("y", BIGINT))))
    outer = P.Project(inner, (ir.Call("add", (ir.FieldRef(0, BIGINT),
                                              ir.FieldRef(1, BIGINT)),
                                      BIGINT),),
                      Schema((Field("z", BIGINT),)))
    out = _opt(outer)
    projs = _find(out, P.Project)
    assert len(projs) == 1, "adjacent projects must merge"
    assert "add" in repr(projs[0].exprs[0])


def test_simplify_constant_predicate():
    t = ir.Call("lt", (ir.Constant(1, BIGINT), ir.Constant(2, BIGINT)),
                BOOLEAN)
    out = _opt(P.Filter(_scan(), t))
    assert isinstance(out, P.TableScan), "1<2 folds to TRUE -> filter gone"
    f = ir.Call("gt", (ir.Constant(1, BIGINT), ir.Constant(2, BIGINT)),
                BOOLEAN)
    out2 = _opt(P.Filter(_scan(), f))
    assert isinstance(out2, P.Values) and not out2.rows


def test_values_folding_filter_and_limit():
    schema = Schema((Field("a", BIGINT),))
    vals = P.Values(((1,), (5,), (9,)), schema)
    out = _opt(P.Filter(vals, _pred(0, "gt", 4)))
    assert isinstance(out, P.Values) and out.rows == ((5,), (9,))
    out2 = _opt(P.Limit(P.Values(((1,), (2,), (3,)), schema), 2))
    assert isinstance(out2, P.Values) and out2.rows == ((1,), (2,))


def test_dedup_sort_and_join_keys():
    s = P.Sort(_scan(), (P.SortKey(0), P.SortKey(1), P.SortKey(0, False)))
    out = _opt(s)
    assert tuple(k.channel for k in out.keys) == (0, 1)
    j = P.Join("inner", _scan(), _scan(), (0, 1, 0), (0, 1, 0),
               Schema((Field("l0", BIGINT), Field("l1", BIGINT),
                       Field("r0", BIGINT), Field("r1", BIGINT))))
    out2 = _opt(j)
    assert out2.left_keys == (0, 1) and out2.right_keys == (0, 1)


def test_distinct_over_distinct_collapses():
    inner_schema = Schema((Field("a", BIGINT),))
    inner = P.Aggregate(_scan(), (0,), (), inner_schema)
    outer = P.Aggregate(inner, (0,), (), inner_schema)
    out = _opt(outer)
    aggs = _find(out, P.Aggregate)
    assert len(aggs) == 1, "stacked DISTINCT must collapse to one"


def test_push_filter_through_union_with_existing_branch_filter():
    """A branch's own unrelated filter must not block pushing a NEW predicate
    into every branch (round-5 review finding)."""
    u_schema = Schema((Field("a", BIGINT), Field("b", BIGINT)))
    filtered_branch = P.Filter(_scan(), _pred(1, "lt", 100))
    u = P.Union((filtered_branch, _scan()), u_schema)
    out = _opt(P.Filter(u, _pred(0, "gt", 1)))
    assert not isinstance(out, P.Filter), "predicate must push below the union"
    union = _find(out, P.Union)[0]
    for c in union.children:
        preds = repr([f.predicate for f in _find(c, P.Filter)]
                     + ([c.predicate] if isinstance(c, P.Filter) else []))
        assert "gt" in preds, f"branch missing pushed predicate: {preds}"


def test_merge_projects_guards_duplicated_expensive_expr():
    """A non-trivial inner expression referenced twice above must NOT inline
    (exponential-growth guard, InlineProjections analog)."""
    s = _scan()
    inner = P.Project(s, (ir.Call("mul", (ir.FieldRef(0, BIGINT),
                                          ir.FieldRef(1, BIGINT)), BIGINT),),
                      Schema((Field("x", BIGINT),)))
    outer = P.Project(inner, (ir.Call("add", (ir.FieldRef(0, BIGINT),
                                              ir.FieldRef(0, BIGINT)),
                                      BIGINT),),
                      Schema((Field("z", BIGINT),)))
    out = _opt(outer)
    assert len(_find(out, P.Project)) == 2, "double-use inner expr must stay"
