"""Array function batch 2: arrays_overlap, slice, trim_array, array_remove,
array_distinct, array_sort, repeat (reference: operator/scalar/
ArraysOverlapFunction, ArraySliceFunction, ArrayTrimFunction,
ArrayRemoveFunction, ArrayDistinctFunction, ArraySortFunction,
RepeatFunction)."""

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.memory import MemoryConnector


@pytest.fixture(scope="module")
def aeng():
    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table t (a array(bigint), b array(bigint), "
                  "sa array(varchar), n bigint)", s)
    e.execute_sql("insert into t values "
                  "(array[1,2,3], array[3,4], array['b','a','b'], 1), "
                  "(array[5,6], array[7,8], array['z'], 2), "
                  "(null, array[1], null, 3)", s)
    return e, s


def _rows(aeng, sql):
    e, s = aeng
    return e.execute_sql(sql, s).to_pandas()


def test_arrays_overlap(aeng):
    r = _rows(aeng, "select n, arrays_overlap(a, b) o from t order by n")
    assert list(r["o"])[:2] == [True, False]
    assert r["o"].iloc[2] is None or r["o"].isna().iloc[2]


def test_slice(aeng):
    r = _rows(aeng, "select n, slice(a, 2, 2) s, slice(a, -2, 2) s2 "
                    "from t order by n")
    assert r["s"].iloc[0] == [2, 3]
    assert r["s"].iloc[1] == [6]
    assert r["s2"].iloc[0] == [2, 3]
    # start = 0 is invalid -> NULL (reference raises; LUT design yields NULL)
    r = _rows(aeng, "select slice(a, 0, 1) s from t where n = 1")
    assert r["s"].iloc[0] is None or r["s"].isna().iloc[0]


def test_trim_array(aeng):
    r = _rows(aeng, "select n, trim_array(a, 1) tr from t order by n")
    assert r["tr"].iloc[0] == [1, 2]
    assert r["tr"].iloc[1] == [5]
    r = _rows(aeng, "select trim_array(a, 9) tr from t where n = 1")
    assert r["tr"].iloc[0] == []


def test_array_remove(aeng):
    r = _rows(aeng, "select array_remove(a, 3) x from t order by n")
    assert r["x"].iloc[0] == [1, 2]
    assert r["x"].iloc[1] == [5, 6]
    r = _rows(aeng, "select array_remove(sa, 'b') x from t where n = 1")
    assert r["x"].iloc[0] == ["a"]


def test_array_distinct_sort_repeat(aeng):
    r = _rows(aeng, "select array_distinct(array[3,1,3,2,1]) d, "
                    "array_sort(array[3,1,2]) s, "
                    "array_sort(array['b','a','c']) ss, "
                    "repeat(7, 3) rp from t where n = 1")
    assert r["d"].iloc[0] == [3, 1, 2]
    assert r["s"].iloc[0] == [1, 2, 3]
    assert list(r["ss"].iloc[0]) == ["a", "b", "c"]
    assert r["rp"].iloc[0] == [7, 7, 7]


def test_slice_negative_start_past_head(aeng):
    """|negative start| > cardinality selects nothing (code-review catch)."""
    r = _rows(aeng, "select slice(a, -5, 2) s from t where n = 1")
    assert r["s"].iloc[0] == []


def test_array_remove_null_value(aeng):
    """array_remove(arr, NULL) is NULL (code-review catch)."""
    r = _rows(aeng, "select array_remove(a, null) x from t where n = 1")
    assert r["x"].iloc[0] is None or r["x"].isna().iloc[0]


def test_composition_with_lambdas(aeng):
    r = _rows(aeng, "select cardinality(filter(slice(a, 1, 3), x -> x > 1)) c "
                    "from t where n = 1")
    assert r["c"].iloc[0] == 2


def test_map_lambdas(aeng):
    """map_filter / transform_keys / transform_values over plan-time heaps
    (reference: MapFilterFunction, MapTransformKeys/ValuesFunction)."""
    r = _rows(aeng, """select
        transform_values(map(array[1,2,3], array[10,20,30]),
                         (k, v) -> v * k) tv,
        transform_keys(map(array[1,2], array[10,20]), (k, v) -> k + 100) tk,
        map_filter(map(array[1,2,3], array[10,20,30]), (k, v) -> v > 15) mf
      from t where n = 1""")
    assert r["tv"].iloc[0] == {1: 10, 2: 40, 3: 90}
    assert r["tk"].iloc[0] == {101: 10, 102: 20}
    assert r["mf"].iloc[0] == {2: 20, 3: 30}
