"""Scan column pruning (reference: PruneTableScanColumns rule)."""

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.sql import plan as P
from trino_tpu.sql.frontend import compile_sql


def _scans(node, out):
    if isinstance(node, P.TableScan):
        out.append(node)
    for c in node.children:
        _scans(c, out)


def test_q1_scan_reads_only_referenced_columns():
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.001, split_rows=1 << 11))
    s = e.create_session("tpch")
    plan = compile_sql("""
        select l_returnflag, l_linestatus, sum(l_quantity), count(*)
        from lineitem where l_shipdate <= date '1998-09-02'
        group by l_returnflag, l_linestatus order by 1, 2""", e, s)
    scans = []
    _scans(plan, scans)
    assert len(scans) == 1
    assert set(scans[0].columns) == {"l_returnflag", "l_linestatus", "l_quantity",
                                     "l_shipdate"}
    # and the result is still right
    r = e.execute_sql("""select l_returnflag, l_linestatus, sum(l_quantity), count(*)
        from lineitem where l_shipdate <= date '1998-09-02'
        group by l_returnflag, l_linestatus order by 1, 2""", s).rows()
    assert len(r) >= 3 and all(len(row) == 4 for row in r)


def test_join_query_prunes_each_side():
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.001, split_rows=1 << 11))
    s = e.create_session("tpch")
    plan = compile_sql("""
        select o_orderpriority, count(*) from orders, customer
        where o_custkey = c_custkey and c_acctbal > 0
        group by o_orderpriority order by 1""", e, s)
    scans = []
    _scans(plan, scans)
    by_table = {sc.table: set(sc.columns) for sc in scans}
    assert by_table["orders"] <= {"o_custkey", "o_orderpriority"}
    assert by_table["customer"] <= {"c_custkey", "c_acctbal"}


def test_limit_short_circuits_scan():
    """LIMIT over a streaming child stops pulling pages early
    (reference: LimitOperator)."""
    from trino_tpu.connectors.tpch import TpchConnector

    e = Engine()
    conn = TpchConnector(sf=0.1, split_rows=1 << 12)
    calls = []
    orig = conn.generate

    def counting(split, columns=None):
        calls.append(split)
        return orig(split, columns)

    conn.generate = counting
    e.register_catalog("tpch", conn)
    s = e.create_session("tpch")
    nsplits = len(conn.splits("orders"))
    assert nsplits > 8
    r = e.execute_sql("select o_orderkey from orders where o_orderkey > 5 limit 7",
                      s).rows()
    assert len(r) == 7 and all(k > 5 for (k,) in r)
    assert len(calls) <= 2  # stopped after the first page(s)
