"""Scan column pruning (reference: PruneTableScanColumns rule)."""

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.sql import plan as P
from trino_tpu.sql.frontend import compile_sql


def _scans(node, out):
    if isinstance(node, P.TableScan):
        out.append(node)
    for c in node.children:
        _scans(c, out)


def test_q1_scan_reads_only_referenced_columns():
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.001, split_rows=1 << 11))
    s = e.create_session("tpch")
    plan = compile_sql("""
        select l_returnflag, l_linestatus, sum(l_quantity), count(*)
        from lineitem where l_shipdate <= date '1998-09-02'
        group by l_returnflag, l_linestatus order by 1, 2""", e, s)
    scans = []
    _scans(plan, scans)
    assert len(scans) == 1
    assert set(scans[0].columns) == {"l_returnflag", "l_linestatus", "l_quantity",
                                     "l_shipdate"}
    # and the result is still right
    r = e.execute_sql("""select l_returnflag, l_linestatus, sum(l_quantity), count(*)
        from lineitem where l_shipdate <= date '1998-09-02'
        group by l_returnflag, l_linestatus order by 1, 2""", s).rows()
    assert len(r) >= 3 and all(len(row) == 4 for row in r)


def test_join_query_prunes_each_side():
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.001, split_rows=1 << 11))
    s = e.create_session("tpch")
    plan = compile_sql("""
        select o_orderpriority, count(*) from orders, customer
        where o_custkey = c_custkey and c_acctbal > 0
        group by o_orderpriority order by 1""", e, s)
    scans = []
    _scans(plan, scans)
    by_table = {sc.table: set(sc.columns) for sc in scans}
    assert by_table["orders"] <= {"o_custkey", "o_orderpriority"}
    assert by_table["customer"] <= {"c_custkey", "c_acctbal"}


def test_limit_short_circuits_scan():
    """LIMIT over a streaming child stops pulling pages early
    (reference: LimitOperator)."""
    from trino_tpu.connectors.tpch import TpchConnector

    e = Engine()
    conn = TpchConnector(sf=0.1, split_rows=1 << 12)
    calls = []
    orig = conn.generate

    def counting(split, columns=None):
        calls.append(split)
        return orig(split, columns)

    conn.generate = counting
    e.register_catalog("tpch", conn)
    s = e.create_session("tpch")
    nsplits = len(conn.splits("orders"))
    assert nsplits > 8
    r = e.execute_sql("select o_orderkey from orders where o_orderkey > 5 limit 7",
                      s).rows()
    assert len(r) == 7 and all(k > 5 for (k,) in r)
    assert len(calls) <= 2  # stopped after the first page(s)


# ---------------------------------------------------------------------------- CBO
# reference: cost/FilterStatsCalculator.java, cost/JoinStatsRule.java,
# iterative/rule/ReorderJoins.java:98, DetermineJoinDistributionType.java:51


def _sf1_engine():
    from trino_tpu.connectors.tpch import TpchConnector

    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=1))
    return e


Q9 = """
    select nation, o_year, sum(amount) as sum_profit from (
      select n_name as nation, extract(year from o_orderdate) as o_year,
        l_extendedprice * (1 - l_discount) - ps_supplycost * l_quantity as amount
      from part, supplier, lineitem, partsupp, orders, nation
      where s_suppkey = l_suppkey and ps_suppkey = l_suppkey and ps_partkey = l_partkey
        and p_partkey = l_partkey and o_orderkey = l_orderkey
        and s_nationkey = n_nationkey and p_name like '%green%') as profit
    group by nation, o_year order by nation, o_year desc"""


def _join_chain(plan):
    """Innermost-first list of (build table | None, distribution) along the spine."""
    from trino_tpu.sql import plan as P

    chain = []

    def walk(n):
        if isinstance(n, P.Join):
            walk(n.left)
            t = None
            b = n.right
            while b is not None and not isinstance(b, P.TableScan):
                b = b.children[0] if b.children else None
            if isinstance(b, P.TableScan):
                t = b.table
            chain.append((t, n.distribution))
            return
        for c in n.children:
            walk(c)

    walk(plan)
    return chain


def test_cbo_join_order_filters_first():
    """The selective LIKE-filtered part relation joins before the big
    unfiltered orders/partsupp builds: greedy minimum-output ordering over
    connector stats (reference: ReorderJoins over TableStatistics)."""
    from trino_tpu.sql.frontend import compile_sql

    e = _sf1_engine()
    s = e.create_session("tpch")
    chain = _join_chain(compile_sql(Q9, e, s))
    tables = [t for t, _ in chain]
    assert tables.index("part") < tables.index("orders")
    assert tables.index("part") < tables.index("partsupp")


def test_cbo_distribution_hints_scale_with_stats():
    """Big builds (orders at SF1) plan partitioned; small builds (nation,
    filtered part) stay replicated; the session property forces either way."""
    from trino_tpu.sql.frontend import compile_sql

    e = _sf1_engine()
    s = e.create_session("tpch")
    dist = dict(_join_chain(compile_sql(Q9, e, s)))
    assert dist["orders"] == "partitioned"
    assert dist["partsupp"] == "partitioned"
    # round 5: the AddExchanges pass resolves small KNOWN builds against the
    # huge probe side to an explicit broadcast (replicating 25 nations x the
    # mesh beats routing the probe); 'replicated' now only survives where
    # stats are unknown or the traffic model is a wash
    assert dist["nation"] == "broadcast"
    assert dist["part"] in ("replicated", "broadcast")

    q = "select count(*) c from lineitem, orders where l_orderkey = o_orderkey"
    s2 = e.create_session("tpch")
    e.execute_sql("set session join_distribution_type = 'BROADCAST'", s2)
    assert _join_chain(compile_sql(q, e, s2))[0][1] == "broadcast"
    e.execute_sql("set session join_distribution_type = 'PARTITIONED'", s2)
    assert _join_chain(compile_sql(q, e, s2))[0][1] == "partitioned"


def test_filter_selectivity_estimates():
    """Selectivity formulas vs the stats they read (FilterStatsCalculator)."""
    from trino_tpu.spi.statistics import ColumnStats
    from trino_tpu.sql import ir
    from trino_tpu.sql.stats import RelStats, filter_selectivity
    from trino_tpu.types import BIGINT

    stats = RelStats(1000.0, [ColumnStats(ndv=100, lo=0, hi=999)], 1000.0)
    f = ir.FieldRef(0, BIGINT)
    c = lambda v: ir.Constant(v, BIGINT)
    eq = ir.Call("eq", (f, c(5)), BIGINT)
    assert abs(filter_selectivity(eq, stats) - 0.01) < 1e-9
    out_of_range = ir.Call("eq", (f, c(5000)), BIGINT)
    assert filter_selectivity(out_of_range, stats) == 0.0
    rng = ir.Call("lt", (f, c(250)), BIGINT)
    assert 0.2 < filter_selectivity(rng, stats) < 0.3
    both = ir.Call("and", (eq, rng), BIGINT)
    assert abs(filter_selectivity(both, stats)
               - filter_selectivity(eq, stats) * filter_selectivity(rng, stats)) < 1e-12
    bet = ir.Call("between", (f, c(100), c(199)), BIGINT)
    assert 0.05 < filter_selectivity(bet, stats) < 0.15


def test_join_stats_containment_and_ndv():
    """Unique-build joins use FK containment (composite PKs defeat the NDV
    independence assumption); non-unique joins use the NDV formula."""
    from trino_tpu.spi.statistics import ColumnStats
    from trino_tpu.sql.stats import RelStats, join_stats

    lineitem = RelStats(6_000_000.0, [ColumnStats(ndv=200_000),
                                      ColumnStats(ndv=10_000)], 6_000_000.0)
    partsupp = RelStats(800_000.0, [ColumnStats(ndv=200_000),
                                    ColumnStats(ndv=10_000)], 800_000.0)
    out = join_stats(lineitem, partsupp, [0, 1], [0, 1], build_unique=True)
    assert out.rows == 6_000_000.0  # unfiltered PK build keeps every probe row
    filtered = partsupp.scaled(0.1)
    out2 = join_stats(lineitem, filtered, [0, 1], [0, 1], build_unique=True)
    assert abs(out2.rows - 600_000.0) < 1.0
    # non-unique: NDV formula on the dominant clause
    a = RelStats(1000.0, [ColumnStats(ndv=100)], 1000.0)
    b = RelStats(500.0, [ColumnStats(ndv=50)], 500.0)
    out3 = join_stats(a, b, [0], [0])
    assert abs(out3.rows - 1000.0 * 500.0 / 100.0) < 1.0


def test_show_stats_uses_table_stats():
    """SHOW STATS surfaces the same TableStats the CBO reads (tpch analytic
    stats: date ranges, key NDVs)."""
    e = _sf1_engine()
    s = e.create_session("tpch")
    rows = e.execute_sql("show stats for orders", s).rows()
    by_col = {r[0]: r for r in rows}
    assert by_col["o_orderkey"][1] == "1500001" or by_col["o_orderkey"][1] == "1500000"
    assert by_col["o_orderdate"][2] != ""  # date range known
    assert rows[-1][4] == "1500000"  # summary row_count


def test_count_star_pushdown_exact():
    """Global count(*) over a bare scan answers from connector metadata
    (ConnectorMetadata.applyAggregation's count slice) — and must be EXACT,
    including lineitem whose cardinality is data-dependent."""
    import trino_tpu.exec.local_executor as LE
    from trino_tpu import Engine
    from trino_tpu.connectors.tpch import TpchConnector

    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01))
    s = e.create_session("tpch")
    calls = {"n": 0}
    orig = LE.LocalExecutor._run_global_aggregate

    def counting(self, *a, **k):
        calls["n"] += 1
        return orig(self, *a, **k)

    LE.LocalExecutor._run_global_aggregate = counting
    try:
        pushed = int(e.execute_sql("select count(*) from lineitem",
                                   s).rows()[0][0])
        assert calls["n"] == 0, "count(*) should not execute an aggregation"
        # NOTE a '1 = 1' filter no longer works as the control here: round-5
        # constant folding (SimplifyFilterPredicate) erases it at plan time
        # and the pushdown legitimately applies.  A data-dependent filter
        # still disables the pushdown and executes the aggregation.
        real = int(e.execute_sql("select count(*) c from lineitem "
                                 "where l_quantity > -1", s).rows()[0][0])
        assert pushed == real
        # filters disable the pushdown
        assert calls["n"] >= 1
    finally:
        LE.LocalExecutor._run_global_aggregate = orig
