"""Continuous template batching (round 21): N concurrent same-template
requests fused into ONE device dispatch, per-request demux.

Covers the acceptance surface:

- batched-vs-serial BYTE IDENTITY: a deterministically fused window of
  concurrent protocol-parameterized EXECUTEs (distinct bindings, one NULL
  binding, one BindError fallback sharing the window) returns exactly what
  serial execution returns;
- per-request isolation: a batch member that errors (per-lane decode fault
  via the BATCH_LANE_TEST_HOOK seam) fails ONLY its own request — the rest
  of the window gets correct results;
- unbatchable plans (Sort/Limit are outside the fused subset) demote the
  template to serial lanes (``batchable=False``) and every member still
  answers correctly;
- the dispatch amortization claim: a fused window of N bills within 2x of
  ONE request's warm serial dispatch count, not N times it;
- split-union pruning: a fused window whose bindings prune to DIFFERENT
  splits scans the union and stays byte-identical per lane;
- accounting: ``batched_requests`` counts every member (driver + riders,
  totals == sum of per-request snapshots), flight records carry
  ``batched_with``, EXPLAIN ANALYZE prints the "Batched:" line only when
  nonzero, /v1/metrics exports the batch counters + size histogram;
- the TemplateBatcher protocol itself (no engine): leader-runs-serial,
  window fusion via LEADER_EXIT_HOOK, whole-batch failure -> all-serial
  fallback, singleton window -> serial, arity-mismatch -> serial,
  TRINO_TPU_TEMPLATE_BATCH=0 -> pass-through.

Fusion in engine tests is MANUFACTURED, never raced: the template's lane is
marked busy, the window's members enqueue, and a manual handoff promotes
the first to driver — the exact state the wall-clock gather window
produces, minus the timing dependence (same technique as
scripts/query_counters.py --serve-batch).
"""

import threading
import time

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.execution import batcher as BA
from trino_tpu.execution.chaos_matrix import result_signature as _sig

SF, SPLIT_ROWS = 0.01, 1 << 14

POINT = ("select c_name, c_acctbal, c_mktsegment from customer "
         "where c_custkey = ?")


@pytest.fixture(scope="module")
def tpch_conn():
    return TpchConnector(sf=SF, split_rows=SPLIT_ROWS)


@pytest.fixture()
def eng(tpch_conn, monkeypatch):
    """Template+batcher engine; result/page tiers off (the fused win must be
    measured on the execute path, and a result-cache hit would answer a
    member before it ever reaches the lane)."""
    monkeypatch.setenv("TRINO_TPU_RESULT_CACHE", "0")
    monkeypatch.setenv("TRINO_TPU_PAGE_CACHE", "0")
    e = Engine()
    e.register_catalog("tpch", tpch_conn)
    assert e.template_batcher.enabled
    return e


@pytest.fixture()
def baseline(tpch_conn, monkeypatch):
    """Serial oracle: templates on, batcher off — same plans, same binds,
    never fused."""
    monkeypatch.setenv("TRINO_TPU_RESULT_CACHE", "0")
    monkeypatch.setenv("TRINO_TPU_PAGE_CACHE", "0")
    e = Engine()
    e.template_batcher.enabled = False
    e.register_catalog("tpch", tpch_conn)
    return e


def _warm(eng, text, bindings=((42,), (97,))):
    """Create + CONFIRM the template (the batcher only fuses confirmed
    templates) and compile the serial path."""
    s = eng.create_session("tpch")
    for ps in bindings:
        eng.execute_sql(text, s, parameters=list(ps))


def _fused(eng, text, params_list, expect_members=None, timeout=60):
    """Run the requests concurrently as ONE deterministically fused window.
    Returns results (or the exception each request raised) in input order.
    ``expect_members`` caps the enqueue wait when some requests are known
    to bypass the batcher (BindError fallbacks)."""
    bt = eng.template_batcher
    key = eng._template_key(text, eng.create_session("tpch"))
    with bt._lock:
        lane = bt._lanes.setdefault(key, BA._Lane())
        lane.busy = True
    n = len(params_list) if expect_members is None else expect_members
    out = [None] * len(params_list)

    def fire(i, ps):
        s = eng.create_session("tpch")
        try:
            out[i] = eng.execute_sql(text, s, parameters=list(ps))
        except Exception as e:
            out[i] = e

    threads = [threading.Thread(target=fire, args=(i, ps))
               for i, ps in enumerate(params_list)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with bt._lock:
            if len(lane.queue) >= n:
                break
        time.sleep(0.001)
    bt._handoff(lane)
    for t in threads:
        t.join(timeout)
    assert not any(t.is_alive() for t in threads), "fused window hung"
    return out


def _serial_results(eng, text, params_list):
    s = eng.create_session("tpch")
    return [eng.execute_sql(text, s, parameters=list(ps))
            for ps in params_list]


# ------------------------------------------------------- byte identity
def test_fused_window_byte_identity(eng, baseline):
    """The headline contract: distinct bindings + one NULL binding fused
    into one window == serial, and every member is counted + flight-marked."""
    _warm(eng, POINT)
    _warm(baseline, POINT)
    params = [(42,), (97,), (None,), (7,)]
    before = eng.counters_total.as_dict()
    out = _fused(eng, POINT, params)
    ref = _serial_results(baseline, POINT, params)
    for i, (a, b) in enumerate(zip(out, ref)):
        assert not isinstance(a, Exception), f"member {i} raised: {a!r}"
        assert _sig(a) == _sig(b), f"member {i} diverged from serial"
    after = eng.counters_total.as_dict()
    # every member of the fused window counts once — driver and riders
    assert after["batched_requests"] - before.get("batched_requests", 0) \
        == len(params)
    bi = eng.template_batcher.info()
    assert bi["batches_total"] >= 1
    assert bi["sizes"].get(len(params), 0) >= 1
    # flight records: each member's record carries the window size
    recs = [r for r in eng.flight_recorder.snapshot(kind="query")
            if r.get("batched_with") == len(params)]
    assert len(recs) >= len(params)


def test_binderror_fallback_shares_the_window(eng, baseline):
    """A BindError binding (fractional literal in the integer slot) never
    enters the batcher — it substitutes per execution — while the rest of
    the window fuses.  Everyone answers correctly."""
    _warm(eng, POINT)
    _warm(baseline, POINT)
    params = [(42,), (1.5,), (97,), (None,)]  # 1.5 -> BindError -> fallback
    before = eng.counters_total.as_dict()
    out = _fused(eng, POINT, params, expect_members=len(params) - 1)
    ref = _serial_results(baseline, POINT, params)
    for i, (a, b) in enumerate(zip(out, ref)):
        assert not isinstance(a, Exception), f"member {i} raised: {a!r}"
        assert _sig(a) == _sig(b), f"member {i} diverged from serial"
    after = eng.counters_total.as_dict()
    # only the three bindable members batched; the fallback ran substitution
    assert after["batched_requests"] - before.get("batched_requests", 0) \
        == len(params) - 1


def test_fused_window_unions_pruned_splits(monkeypatch):
    """Bindings that prune to DIFFERENT splits: the fused scan takes the
    union of the per-member pruned split lists and each lane still matches
    serial (the predicate masks the other members' rows per lane)."""
    monkeypatch.setenv("TRINO_TPU_RESULT_CACHE", "0")
    monkeypatch.setenv("TRINO_TPU_PAGE_CACHE", "0")
    conn = TpchConnector(sf=SF, split_rows=256)  # 1500 rows -> 6 splits
    e = Engine()
    e.register_catalog("tpch", conn)
    b = Engine()
    b.template_batcher.enabled = False
    b.register_catalog("tpch", conn)
    _warm(e, POINT)
    _warm(b, POINT)
    params = [(5,), (700,), (1400,), (901,)]  # distinct splits
    out = _fused(e, POINT, params)
    ref = _serial_results(b, POINT, params)
    for i, (a, r) in enumerate(zip(out, ref)):
        assert not isinstance(a, Exception), f"member {i} raised: {a!r}"
        assert _sig(a) == _sig(r), f"member {i} diverged across splits"


# ------------------------------------------------------- error isolation
def test_member_error_fails_only_its_own_request(eng, monkeypatch):
    """A per-lane demux fault (injected at the BATCH_LANE_TEST_HOOK seam)
    surfaces on exactly that member; the other members of the same fused
    window still get correct results."""
    from trino_tpu.exec import local_executor as LE

    _warm(eng, POINT)
    ref = _serial_results(eng, POINT, [(42,), (97,), (7,)])

    def hook(lane, nlanes):
        if lane == 1:
            raise RuntimeError("injected lane fault")

    monkeypatch.setattr(LE, "BATCH_LANE_TEST_HOOK", hook)
    out = _fused(eng, POINT, [(42,), (97,), (7,)])
    monkeypatch.setattr(LE, "BATCH_LANE_TEST_HOOK", None)
    assert isinstance(out[1], Exception) \
        and "injected lane fault" in str(out[1])
    assert _sig(out[0]) == _sig(ref[0])
    assert _sig(out[2]) == _sig(ref[2])


def test_unbatchable_template_demotes_to_serial(eng, baseline):
    """Sort/Limit plans are templatable but outside the FUSED subset: the
    first fused attempt raises BatchUnsupported, the template demotes
    (batchable=False), every member of that window re-runs serially with
    correct results, and later windows skip the fused path entirely."""
    text = ("select c_name from customer where c_custkey < ? "
            "order by c_name limit 5")
    bindings = ((100,), (500,))
    s1, s2 = eng.create_session("tpch"), baseline.create_session("tpch")
    for ps in bindings:
        eng.execute_sql(text, s1, parameters=[ps[0]])
        baseline.execute_sql(text, s2, parameters=[ps[0]])
    tpl = next(v[0] for v in eng._template_cache.values()
               if getattr(v[0], "text", None) is not None
               and "order by" in v[0].text)
    assert tpl.batchable
    params = [(100,), (500,), (900,)]
    before = eng.counters_total.as_dict()
    out = _fused(eng, text, params)
    ref = _serial_results(baseline, text, params)
    for i, (a, b) in enumerate(zip(out, ref)):
        assert not isinstance(a, Exception), f"member {i} raised: {a!r}"
        assert _sig(a) == _sig(b), f"member {i} diverged after fallback"
    assert not tpl.batchable
    after = eng.counters_total.as_dict()
    # nothing fused: the serial fallback never stamps batched_requests
    assert after.get("batched_requests", 0) \
        == before.get("batched_requests", 0)
    # a later window goes straight to serial lanes (no BatchUnsupported
    # round-trip) and stays correct
    out2 = _fused(eng, text, [(250,)], expect_members=1)
    assert _sig(out2[0]) == _sig(
        _serial_results(baseline, text, [(250,)])[0])


# ------------------------------------------------------- amortization
def test_fused_dispatches_within_2x_of_one_request(eng):
    """The acceptance ratio: a warm fused window of 4 bills within 2x of
    ONE warm serial request's dispatches — not 4x."""
    _warm(eng, POINT)
    s = eng.create_session("tpch")
    before = eng.counters_total.as_dict()
    eng.execute_sql(POINT, s, parameters=[11])
    mid = eng.counters_total.as_dict()
    serial_d = mid["device_dispatches"] - before["device_dispatches"]
    assert serial_d > 0
    params = [(21,), (31,), (41,), (51,)]
    _fused(eng, POINT, params)          # compiles the rung's bindings jit
    mid2 = eng.counters_total.as_dict()
    out = _fused(eng, POINT, [(22,), (32,), (42,), (52,)])  # warm window
    assert not any(isinstance(r, Exception) for r in out)
    after = eng.counters_total.as_dict()
    fused_d = after["device_dispatches"] - mid2["device_dispatches"]
    assert 0 < fused_d <= 2 * serial_d, \
        f"fused window of 4 cost {fused_d} dispatches vs serial {serial_d}"


# ------------------------------------------------------- observability
def test_explain_analyze_batched_line(eng):
    """format_plan prints "Batched:" only when the counter is nonzero —
    zero-batch statements (the whole budget suite) print byte-unchanged."""
    from trino_tpu.execution.tracing import QueryCounters
    from trino_tpu.sql.planprinter import format_plan

    s = eng.create_session("tpch")
    eng.execute_sql("select c_custkey from customer "
                    "where c_custkey = 42", s)
    res = eng.execute_sql("explain analyze select c_custkey from customer "
                          "where c_custkey = 42", s)
    text = "\n".join(str(row[0]) for row in res.rows())
    assert "Batched:" not in text
    c = QueryCounters()
    c.batched_requests = 5
    # the point lookup auto-parameterized into the template cache
    plan = next(v[0].plan for v in eng._template_cache.values()
                if getattr(v[0], "plan", None) is not None)
    out = format_plan(plan, counters=c)
    assert "Batched: 5 requests" in out
    c.batched_requests = 0
    assert "Batched:" not in format_plan(plan, counters=c)


def test_metrics_export_batch_series(eng):
    from trino_tpu.server.server import CoordinatorServer

    _warm(eng, POINT)
    out = _fused(eng, POINT, [(42,), (97,), (7,)])
    assert not any(isinstance(r, Exception) for r in out)
    body = CoordinatorServer(eng)._metrics_text()
    assert "trino_tpu_template_batches_total 1" in body
    assert "trino_tpu_batched_requests_total 3" in body
    assert 'trino_tpu_template_batch_size_bucket{le="4"} 1' in body
    assert "trino_tpu_template_batch_size_sum 3" in body


# ------------------------------------------------------- batcher protocol
def _mk(window_ms=0.0, max_batch=16, enabled=True):
    return BA.TemplateBatcher(window_ms=window_ms, max_batch=max_batch,
                              enabled=enabled)


def test_batcher_disabled_is_passthrough():
    bt = _mk(enabled=False)
    res, n = bt.execute("k", (1,), lambda rt: ("serial", rt), None)
    assert res == ("serial", (1,)) and n == 0
    assert bt.info()["batches_total"] == 0


def test_batcher_leader_runs_serial_immediately():
    bt = _mk()
    calls = []
    res, n = bt.execute("k", (1,), lambda rt: calls.append(rt) or "ok",
                        lambda rts: pytest.fail("fused on an idle lane"))
    assert res == "ok" and n == 0 and calls == [(1,)]
    assert not bt._lanes["k"].busy  # lane released


def _fuse_via_hook(bt, runtimes, serial_fn, batch_fn, monkeypatch):
    """Real leader->handoff->driver choreography: the leader parks in
    LEADER_EXIT_HOOK until every member is enqueued."""
    ready = threading.Event()
    monkeypatch.setattr(BA, "LEADER_EXIT_HOOK",
                        lambda key: ready.wait(timeout=30))
    out = {}

    def run(name, rt):
        try:
            out[name] = bt.execute("k", rt, serial_fn, batch_fn)
        except Exception as e:
            out[name] = e

    lead = threading.Thread(target=run, args=("leader", ("L",)))
    lead.start()
    t0 = time.monotonic()
    while "k" not in bt._lanes and time.monotonic() - t0 < 10:
        time.sleep(0.001)
    members = [threading.Thread(target=run, args=(f"m{i}", rt))
               for i, rt in enumerate(runtimes)]
    for t in members:
        t.start()
    while time.monotonic() - t0 < 10:
        with bt._lock:
            if len(bt._lanes["k"].queue) >= len(runtimes):
                break
        time.sleep(0.001)
    ready.set()
    for t in [lead] + members:
        t.join(30)
    monkeypatch.setattr(BA, "LEADER_EXIT_HOOK", None)
    return out


def test_batcher_window_fuses_members(monkeypatch):
    bt = _mk(window_ms=5.0)
    fused = []

    def batch_fn(rts):
        fused.append(list(rts))
        return [("batched", rt) for rt in rts]

    out = _fuse_via_hook(bt, [("a",), ("b",), ("c",)],
                         lambda rt: ("serial", rt), batch_fn, monkeypatch)
    assert out["leader"] == (("serial", ("L",)), 0)
    assert len(fused) == 1 and sorted(fused[0]) == [("a",), ("b",), ("c",)]
    for name, rt in (("m0", ("a",)), ("m1", ("b",)), ("m2", ("c",))):
        assert out[name] == (("batched", rt), 3)
    info = bt.info()
    assert info["batches_total"] == 1
    assert info["batched_requests_total"] == 3
    assert info["sizes"] == {3: 1}
    assert not bt._lanes["k"].busy


def test_batcher_whole_batch_failure_falls_back_serial(monkeypatch):
    bt = _mk(window_ms=5.0)

    def batch_fn(rts):
        raise RuntimeError("device fault")

    out = _fuse_via_hook(bt, [("a",), ("b",)],
                         lambda rt: ("serial", rt), batch_fn, monkeypatch)
    for name, rt in (("m0", ("a",)), ("m1", ("b",))):
        assert out[name] == (("serial", rt), 0)
    assert bt.info()["batches_total"] == 0
    assert not bt._lanes["k"].busy


def test_batcher_arity_mismatch_falls_back_serial(monkeypatch):
    bt = _mk(window_ms=5.0)
    out = _fuse_via_hook(bt, [("a",), ("b",)], lambda rt: ("serial", rt),
                         lambda rts: [("only-one", rts[0])], monkeypatch)
    for name, rt in (("m0", ("a",)), ("m1", ("b",))):
        assert out[name] == (("serial", rt), 0)


def test_batcher_member_error_is_its_own(monkeypatch):
    bt = _mk(window_ms=5.0)

    def batch_fn(rts):
        return [ValueError("lane poisoned") if rt == ("b",)
                else ("batched", rt) for rt in rts]

    out = _fuse_via_hook(bt, [("a",), ("b",), ("c",)],
                         lambda rt: ("serial", rt), batch_fn, monkeypatch)
    bad = [v for v in out.values() if isinstance(v, ValueError)]
    assert len(bad) == 1 and "lane poisoned" in str(bad[0])
    good = [v for v in out.values()
            if isinstance(v, tuple) and v[1] == 3]
    assert len(good) == 2


def test_batcher_singleton_window_runs_serial():
    """A driver that gathers nobody runs the serial path — no rung-1 fused
    overhead, batch_fn never called."""
    bt = _mk(window_ms=1.0)
    lane = BA._Lane()
    bt._lanes["k"] = lane
    lane.busy = True
    out = {}

    def member():
        out["m"] = bt.execute("k", ("solo",), lambda rt: ("serial", rt),
                              lambda rts: pytest.fail("fused a singleton"))

    t = threading.Thread(target=member)
    t.start()
    t0 = time.monotonic()
    while not lane.queue and time.monotonic() - t0 < 10:
        time.sleep(0.001)
    bt._handoff(lane)
    t.join(30)
    assert out["m"] == (("serial", ("solo",)), 0)
    assert not lane.busy


def test_batcher_env_disable(monkeypatch):
    monkeypatch.setenv("TRINO_TPU_TEMPLATE_BATCH", "0")
    assert not BA.TemplateBatcher().enabled
    monkeypatch.setenv("TRINO_TPU_TEMPLATE_BATCH", "1")
    assert BA.TemplateBatcher().enabled
