"""Blackhole + ORC connectors.

Reference test models: plugin/trino-blackhole tests, lib/trino-orc reader tests.
"""

import numpy as np
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.blackhole import BlackHoleConnector
from trino_tpu.connectors.orc import OrcConnector
from trino_tpu.page import Field, Schema
from trino_tpu.types import BIGINT, DOUBLE


def test_blackhole_scan_and_insert():
    e = Engine()
    bh = BlackHoleConnector()
    bh.create_table("events", Schema((Field("id", BIGINT), Field("v", DOUBLE))),
                    rows_per_page=100, pages_per_split=2, splits=3)
    e.register_catalog("blackhole", bh)
    s = e.create_session("blackhole")
    r = e.execute_sql("select count(*), min(id), max(id) from events", s).rows()
    assert r[0] == (600, 0, 599)
    # inserts are swallowed
    bh.append("events", [np.arange(5), np.zeros(5)])
    r2 = e.execute_sql("select count(*) from events", s).rows()
    assert r2[0][0] == 600
    assert bh._tables["events"].inserted_rows == 5


def test_orc_connector(tmp_path):
    import pyarrow as pa
    from pyarrow import orc

    n = 3000
    tbl = pa.table({
        "id": pa.array(range(n), pa.int64()),
        "price": pa.array([float(i) * 0.5 for i in range(n)], pa.float64()),
        "tag": pa.array([None if i % 7 == 0 else f"tag{i % 5}" for i in range(n)]),
    })
    orc.write_table(tbl, str(tmp_path / "sales.orc"), stripe_size=64 * 1024)
    e = Engine()
    e.register_catalog("orc", OrcConnector(str(tmp_path)))
    s = e.create_session("orc")
    r = e.execute_sql("select count(*), sum(id) from sales", s).rows()
    assert r[0] == (n, sum(range(n)))
    r2 = e.execute_sql(
        "select tag, count(*) c from sales where id < 700 group by tag order by tag",
        s).rows()
    import collections

    expect = collections.Counter(None if i % 7 == 0 else f"tag{i % 5}"
                                 for i in range(700))
    got = {k: c for k, c in r2}
    assert got == dict(expect)
    r3 = e.execute_sql("select sum(price) from sales where tag = 'tag1'", s).rows()
    expect3 = sum(i * 0.5 for i in range(n) if i % 7 != 0 and i % 5 == 1)
    assert abs(r3[0][0] - expect3) < 1e-6


def test_information_schema_tables_and_columns():
    """ANSI information_schema introspection (reference:
    connector/informationschema) — the surface BI tools query."""
    from trino_tpu import Engine
    from trino_tpu.connectors.memory import MemoryConnector

    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table inv (id bigint, price decimal(10,2), "
                  "name varchar)", s)
    r = e.execute_sql(
        "select table_catalog, table_name from information_schema.tables "
        "where table_catalog = 'mem'", s).to_pandas()
    assert r.values.tolist() == [["mem", "inv"]]
    r = e.execute_sql(
        "select column_name, ordinal_position, data_type "
        "from information_schema.columns where table_name = 'inv' "
        "order by ordinal_position", s).to_pandas()
    assert r["column_name"].tolist() == ["id", "price", "name"]
    assert r["data_type"].tolist() == ["bigint", "decimal(10,2)", "varchar"]
    r = e.execute_sql(
        "select count(*) c from information_schema.schemata", s).to_pandas()
    assert int(r.iloc[0, 0]) >= 3  # mem + system + information_schema

    e.execute_sql("create view v_inv as select id from inv", s)
    r = e.execute_sql(
        "select table_name from information_schema.views", s).to_pandas()
    assert r["table_name"].tolist() == ["v_inv"]


def test_show_create_table():
    from trino_tpu import Engine
    from trino_tpu.connectors.memory import MemoryConnector

    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table t (id bigint, p decimal(10,2), n varchar)", s)
    ddl = e.execute_sql("show create table t", s).to_pandas().iloc[0, 0]
    assert ddl == ("CREATE TABLE mem.t (\n   id bigint,\n"
                   "   p decimal(10,2),\n   n varchar\n)")


def test_orc_write_read_roundtrip_and_ranges(tmp_path):
    """ORC write parity with the parquet connector + file-level column ranges
    feeding CBO/direct-index sizing."""
    from trino_tpu import Engine
    from trino_tpu.connectors.orc import OrcConnector
    from trino_tpu.types import BIGINT, DOUBLE, VarcharType

    conn = OrcConnector(str(tmp_path))
    conn.write_table("t", ["id", "x", "s"],
                     [BIGINT, DOUBLE, VarcharType.of(None)],
                     [[3, 1, 2], [0.5, 1.5, 2.5], ["b", "a", "b"]])
    e = Engine()
    e.register_catalog("orc", conn)
    s = e.create_session("orc")
    r = e.execute_sql("select id, x, s from t order by id", s).to_pandas()
    assert r["id"].tolist() == [1, 2, 3]
    assert r["s"].tolist() == ["a", "b", "b"]
    assert conn.column_range("t", "id") == (1, 3)
    r = e.execute_sql("select count(*) c from t where s = 'b'", s).to_pandas()
    assert int(r.iloc[0, 0]) == 2


def test_describe_and_show_schemas():
    from trino_tpu import Engine
    from trino_tpu.connectors.memory import MemoryConnector

    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table t (id bigint, name varchar)", s)
    r = e.execute_sql("describe t", s).to_pandas()
    assert r.values.tolist() == [["id", "bigint"], ["name", "varchar"]]
    r = e.execute_sql("show schemas", s).to_pandas()
    assert "mem" in r.iloc[:, 0].tolist()
