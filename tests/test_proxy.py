"""HTTP proxy in front of the coordinator (reference: core/trino-proxy's
ProxyResource URI rewriting)."""

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.server.client import Client
from trino_tpu.server.proxy import ProxyServer
from trino_tpu.server.server import CoordinatorServer


@pytest.fixture(scope="module")
def proxied():
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01))
    srv = CoordinatorServer(e, port=0)
    srv.start()
    base = srv.url
    proxy = ProxyServer(base)
    purl = proxy.start()
    yield base, purl
    proxy.stop()
    srv.stop()


def test_query_through_proxy_rewrites_uris(proxied):
    base, purl = proxied
    c = Client(purl, catalog="tpch")
    r = c.execute("select count(*) c from lineitem")
    assert r.rows[0][0] > 0
    # and the client never left the proxy: a paging query's nextUri chain
    # stays on the proxy host
    import json
    import urllib.request

    body = "select l_orderkey from lineitem limit 5".encode()
    req = urllib.request.Request(f"{purl}/v1/statement", data=body,
                                 method="POST",
                                 headers={"X-Trino-User": "user"})
    msg = json.loads(urllib.request.urlopen(req, timeout=30).read())
    uri = msg.get("nextUri")
    assert uri is None or uri.startswith(purl), uri


def test_proxy_backend_down_returns_502():
    proxy = ProxyServer("http://127.0.0.1:1")  # nothing listens there
    purl = proxy.start()
    try:
        import urllib.error
        import urllib.request

        try:
            urllib.request.urlopen(f"{purl}/v1/info", timeout=10)
            assert False, "expected 502"
        except urllib.error.HTTPError as e:
            assert e.code == 502
    finally:
        proxy.stop()
