"""Adaptive execution (round 19, execution/adaptive.py): the feedback loop
from recorded plan-actuals + measured compile costs to plan decisions.

What these tests pin:
- the advisor's decision model at the unit layer: material-misestimate
  gating (EWMA ratio >= threshold, "under" anywhere or "over" on a join
  build, CBO-blind nodes NEVER corrected), win-vs-price arithmetic (unknown
  price = hold), frozen replan tokens, probation -> confirm / regress ->
  demote -> cooldown -> reconsider, failed() demotion;
- the engine loop end-to-end: a join whose build side the CBO under-
  estimates 16x records history on execution 1, re-plans on execution 2
  (broadcast/auto -> partitioned via CONFIDENT observed-rows facts), with
  byte-identical results, the warm corrected dispatch count no worse than
  the uncorrected warm run, and the decision visible in counters, EXPLAIN
  (plain + ANALYZE "Adaptive:" line) and the flight record;
- hold when the compile price outweighs the predicted win (price_scale test
  hook), with warm counters UNCHANGED run-over-run (consult is free at the
  device boundary — the budget suite's ceilings stay pinned with the
  advisor enabled);
- satellite 1: ``adaptive_execution`` is plan-shaping — SET SESSION flips
  the ``_plan_shape_props`` component, so corrected and uncorrected plans
  can never share a plan/result/template cache key.
"""

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.execution import history as H
from trino_tpu.execution.adaptive import (AdaptiveAdvisor, correction_token,
                                          describe_decision)

KEY = ("stmt", "tpch", False, "user", ())


def _store(nodes, fp="fp-base"):
    st = H.PlanHistoryStore(max_plans=8)
    st.record(fp, nodes)
    return st


def _rec(op="Join", est=100.0, actual=1600, wall=0.2, build=False,
         spill=None, splits=0):
    rec = {"op": op, "est_rows": est, "actual_rows": actual, "wall_s": wall,
           "spilled_bytes": 0, "spill_tiers": dict(spill or {}),
           "cache_hits": 0}
    if build:
        rec["build"] = True
    if splits:
        rec["splits"] = splits
    return rec


def _advisor(store, **kw):
    kw.setdefault("threshold", 4.0)
    kw.setdefault("horizon", 8.0)
    kw.setdefault("cooldown", 2)
    return AdaptiveAdvisor(history=store, compile_log=None, **kw)


def _base(adv, key=KEY, fp="fp-base", wall=0.2, compile_s=0.1):
    """One uncorrected completion: anchors base_fp, wall EWMA and the
    observed cold compile price."""
    adv.observe(key, fp, corrected=False, wall_s=wall,
                compiles=1, compile_s=compile_s, sql="select 1")


# ------------------------------------------------------------------ unit layer
def test_token_stable_and_order_independent():
    a = correction_token({"rows": {"Join#0.0": 10.0, "Filter#0.1": 5.0}})
    b = correction_token({"rows": {"Filter#0.1": 5.0, "Join#0.0": 10.0}})
    assert a == b and len(a) == 12
    assert a != correction_token({"rows": {"Join#0.0": 11.0}})


def test_no_history_no_opinion():
    adv = _advisor(_store({"Join#0.0": _rec()}))
    assert adv.consult(KEY) is None  # never observed: no state, no opinion
    disabled = AdaptiveAdvisor(history=H.PlanHistoryStore(max_plans=0))
    assert disabled.consult(KEY) is None


def test_under_misestimate_replans_with_frozen_token():
    adv = _advisor(_store({"Join#0.0": _rec(est=100.0, actual=1600)}))
    _base(adv)
    dec = adv.consult(KEY)
    assert dec is not None and dec["verdict"] == "replan"
    assert dec["corrections"]["rows"]["Join#0.0"] == pytest.approx(1600.0)
    # win = avg wall x (1 - 1/min(ratio, 10)) = 0.2 * 0.9; price = observed
    # cold compile seconds; win x horizon > price -> replan
    assert dec["predicted_win_s"] == pytest.approx(0.18)
    assert dec["compile_price_s"] == pytest.approx(0.1)
    assert dec["token"] and adv.info()["replans_total"] == 1
    # FROZEN: the same token + corrections on every subsequent consult
    again = adv.consult(KEY)
    assert again["token"] == dec["token"]
    assert again["corrections"] == dec["corrections"]
    assert adv.info()["replans_total"] == 1  # no double count
    assert "replan" in describe_decision(dec)
    assert "rows Join#0.0 -> 1600" in describe_decision(dec)


def test_blind_node_never_corrects():
    # CBO-blind (est None) nodes must never fabricate a correction, however
    # large their actuals (satellite 2: "wrong" vs "blind")
    adv = _advisor(_store({"Join#0.0": _rec(est=None, actual=10 ** 6)}))
    _base(adv)
    assert adv.consult(KEY) is None


def test_over_estimate_corrects_only_join_builds():
    # "over" on a non-build node: not actionable (the r15 canonical
    # correlated-filter over-estimate must not trigger wasteful re-plans)
    adv = _advisor(_store({"Filter#0.0": _rec(op="Filter", est=5000.0,
                                              actual=10)}))
    _base(adv)
    assert adv.consult(KEY) is None
    # the same over-estimate on a join BUILD side: a partitioned build that
    # measured tiny should flip back to broadcast
    adv2 = _advisor(_store({"Project#0.1": _rec(op="Project", est=5000.0,
                                                actual=10, build=True)}))
    _base(adv2)
    dec = adv2.consult(KEY)
    assert dec is not None and dec["verdict"] == "replan"
    assert dec["corrections"]["rows"]["Project#0.1"] == pytest.approx(10.0)


def test_hold_when_price_exceeds_win():
    adv = _advisor(_store({"Join#0.0": _rec()}), price_scale=1e9)
    _base(adv)
    dec = adv.consult(KEY)
    assert dec is not None and dec["verdict"] == "hold"
    assert dec["token"] is None
    assert any("compile price" in r for r in dec["reasons"])
    assert adv.info()["holds_total"] == 1 and adv.info()["replans_total"] == 0
    assert describe_decision(dec).startswith("hold")


def test_hold_when_price_unknown():
    adv = _advisor(_store({"Join#0.0": _rec()}))
    # base observation WITHOUT a compile observation, and no compile log:
    # unknown price = assume expensive
    adv.observe(KEY, "fp-base", corrected=False, wall_s=0.2)
    dec = adv.consult(KEY)
    assert dec is not None and dec["verdict"] == "hold"
    assert dec["compile_price_s"] is None
    assert any("unknown" in r for r in dec["reasons"])


def test_peek_consult_transitions_nothing():
    adv = _advisor(_store({"Join#0.0": _rec()}))
    _base(adv)
    dec = adv.consult(KEY, peek=True)
    assert dec is not None and dec["verdict"] == "hold"
    assert any("peek" in r for r in dec["reasons"])
    assert adv.info()["holds_total"] == 0 and adv.info()["replans_total"] == 0
    # the statement is still free to replan on the real consult
    assert adv.consult(KEY)["verdict"] == "replan"


def test_aggregate_capacity_and_grace_corrections():
    adv = _advisor(_store({"Aggregate#0.0": _rec(
        op="Aggregate", est=100.0, actual=50000,
        spill={"host": 1 << 20})}))
    _base(adv)
    corr = adv.consult(KEY)["corrections"]
    # capacity = pow2(2 x observed groups); grace_parts only because the
    # node spilled
    assert corr["capacity"]["Aggregate#0.0"] == 131072
    assert corr["grace_parts"]["Aggregate#0.0"] == 4
    adv2 = _advisor(_store({"Aggregate#0.0": _rec(op="Aggregate", est=100.0,
                                                  actual=50000)}))
    _base(adv2)
    corr2 = adv2.consult(KEY)["corrections"]
    assert corr2["capacity"]["Aggregate#0.0"] == 131072
    assert "grace_parts" not in corr2  # no spill observed: no Grace seed


def test_dispatch_batch_rides_along():
    from trino_tpu.exec.local_executor import _dispatch_batch_default

    cur = _dispatch_batch_default()
    adv = _advisor(_store({
        "Join#0.0": _rec(),
        "TableScan#0.0.0": _rec(op="TableScan", est=None, actual=0, wall=0.0,
                                splits=64)}))
    _base(adv)
    corr = adv.consult(KEY)["corrections"]
    assert corr["dispatch_batch"] == min(16, max(cur, 16))
    assert corr["dispatch_batch"] > cur


def test_probation_confirms_on_warm_no_worse():
    adv = _advisor(_store({"Join#0.0": _rec()}))
    _base(adv)
    assert adv.consult(KEY)["verdict"] == "replan"
    # cold corrected run (compiles > 0): compile-dominated wall, no verdict
    adv.observe(KEY, "fp-corr", corrected=True, wall_s=5.0, compiles=3,
                compile_s=1.0)
    assert adv.decision_trace()[-1]["state"] == "probation"
    # first WARM corrected run, no worse than the base EWMA: confirmed
    adv.observe(KEY, "fp-corr", corrected=True, wall_s=0.15)
    assert adv.decision_trace()[-1]["state"] == "confirmed"
    assert adv.info()["confirms_total"] == 1
    assert adv.consult(KEY)["verdict"] == "replan"  # still frozen


def test_regression_demotes_then_cooldown_reconsiders():
    adv = _advisor(_store({"Join#0.0": _rec()}))
    _base(adv)
    tok = adv.consult(KEY)["token"]
    # warm corrected run REGRESSES past base x 1.5 + floor: demote
    adv.observe(KEY, "fp-corr", corrected=True, wall_s=2.0)
    assert adv.info()["demotions_total"] == 1
    dec = adv.consult(KEY)
    assert dec["verdict"] == "hold" and dec["token"] is None
    assert any("cooling down" in r for r in dec["reasons"])
    # cooldown counts UNCORRECTED executions (cooldown=2 here)
    _base(adv)
    assert adv.consult(KEY)["verdict"] == "hold"
    _base(adv)
    dec2 = adv.consult(KEY)  # cooled down: watching again, re-decides fresh
    assert dec2 is not None and dec2["verdict"] == "replan"
    assert dec2["token"] == tok  # same frozen facts -> same stable token


def test_failed_demotes_immediately():
    adv = _advisor(_store({"Join#0.0": _rec()}))
    _base(adv)
    assert adv.consult(KEY)["verdict"] == "replan"
    adv.failed(KEY)
    assert adv.info()["demotions_total"] == 1
    assert adv.consult(KEY)["verdict"] == "hold"
    adv.failed(KEY)  # idempotent on a non-corrected state
    assert adv.info()["demotions_total"] == 1


def test_decision_trace_shape():
    adv = _advisor(_store({"Join#0.0": _rec()}))
    _base(adv)
    adv.consult(KEY)
    t = adv.decision_trace()
    assert len(t) == 1
    row = t[0]
    assert row["state"] == "probation" and row["last_verdict"] == "replan"
    assert row["sql"] == "select 1" and row["base_executions"] == 1
    assert row["corrections"]["rows"] and row["reasons"]


# ---------------------------------------------------------------- engine layer
# the build side's two expression predicates are always TRUE but
# un-estimatable (COMPARISON_COEFFICIENT each): the CBO estimates
# 1500 x 0.0625 ~ 94 build rows, the executor measures 1500 — a 16x
# UNDER-estimate on a join build, the advisor's canonical trigger
JOIN_Q = ("select count(*) from orders join customer "
          "on o_custkey = c_custkey "
          "where c_custkey * 2 >= c_custkey and c_nationkey + c_custkey >= 0")


def _engine():
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 11))
    return e


@pytest.fixture()
def small_thresholds(monkeypatch):
    """Pull the AddExchanges thresholds under the sf0.01 build size (1500
    rows) so the corrected CONFIDENT estimate crosses them: broadcast is
    blocked by the absolute cap, partitioned engages."""
    from trino_tpu.sql import exchanges as X

    monkeypatch.setattr(X, "BROADCAST_ABS_CAP", 256)
    monkeypatch.setattr(X, "PARTITIONED_JOIN_THRESHOLD", 1024)


def test_misestimated_join_replans_and_improves(small_thresholds):
    e = _engine()
    e.adaptive_advisor.price_scale = 0.0  # test hook: any material win takes
    s = e.create_session("tpch")

    # control: the same statement with adaptive OFF (session property), warm
    ctl = e.create_session("tpch")
    e.execute_sql("set session adaptive_execution = false", ctl)
    expected = e.execute_sql(JOIN_Q, ctl).rows()
    e.execute_sql(JOIN_Q, ctl)
    warm_off = e.last_query_counters.snapshot()
    assert warm_off.adaptive_replans == 0 and warm_off.adaptive_holds == 0

    # before any history: plain EXPLAIN shows the uncorrected placement
    before = "\n".join(r[0] for r in e.execute_sql(
        f"explain {JOIN_Q}", s).rows())
    assert "partitioned" not in before, before
    assert "Adaptive:" not in before

    # execution 1 records the build-side under-estimate; execution 2 diverts
    # to the corrected plan — byte-identical, counted, partitioned
    r1 = e.execute_sql(JOIN_Q, s)
    assert r1.rows() == expected
    c1 = e.last_query_counters.snapshot()
    assert c1.adaptive_replans == 0
    r2 = e.execute_sql(JOIN_Q, s)
    assert r2.rows() == expected
    c2 = e.last_query_counters.snapshot()
    assert c2.adaptive_replans == 1, e.adaptive_advisor.decision_trace()
    assert e.adaptive_advisor.info()["replans_total"] == 1

    # the frozen decision's facts flipped the build distribution: observed
    # 1500 rows is CONFIDENT and past the (shrunk) partitioned threshold
    dec = e.adaptive_advisor.decision_trace()[-1]
    assert dec["state"] in ("probation", "confirmed")
    assert any(v >= 1000 for v in dec["corrections"]["rows"].values()), dec
    after = "\n".join(r[0] for r in e.execute_sql(
        f"explain {JOIN_Q}", s).rows())
    assert "partitioned" in after, after
    assert "Adaptive: replan" in after

    # warm corrected execution: no worse than the uncorrected warm run at
    # the device boundary (the advisor may only SPEND a recompile, never a
    # standing dispatch tax), and the correction confirms
    r3 = e.execute_sql(JOIN_Q, s)
    assert r3.rows() == expected
    c3 = e.last_query_counters.snapshot()
    assert c3.device_dispatches <= warm_off.device_dispatches, \
        (c3.device_dispatches, warm_off.device_dispatches)
    assert c3.host_bytes_pulled <= warm_off.host_bytes_pulled
    assert e.adaptive_advisor.decision_trace()[-1]["state"] == "confirmed"

    # EXPLAIN ANALYZE renders the win-vs-price arithmetic
    text = "\n".join(r[0] for r in e.execute_sql(
        f"explain analyze {JOIN_Q}", s).rows())
    assert "Adaptive: replan" in text, text
    assert "predicted win" in text

    # the decision rides the flight record
    recs = [r for r in e.flight_recorder.snapshot(kind="query")
            if r.get("adaptive")]
    assert recs, "no flight record carried the adaptive decision"
    assert recs[-1]["adaptive"]["verdict"] == "replan"


def test_hold_keeps_plan_and_counters_stable(small_thresholds):
    e = _engine()
    e.adaptive_advisor.price_scale = 1e9  # test hook: price always wins
    s = e.create_session("tpch")
    r1 = e.execute_sql(JOIN_Q, s)
    r2 = e.execute_sql(JOIN_Q, s)
    assert r2.rows() == r1.rows()
    c2 = e.last_query_counters.snapshot()
    assert c2.adaptive_holds == 1 and c2.adaptive_replans == 0
    assert e.adaptive_advisor.info()["replans_total"] == 0
    # consult is free at the device boundary: the held statement's warm
    # counters do not move run-over-run (the budget-suite invariant)
    e.execute_sql(JOIN_Q, s)
    c3 = e.last_query_counters.snapshot()
    assert c3.device_dispatches == c2.device_dispatches
    assert c3.host_transfers == c2.host_transfers
    assert c3.host_bytes_pulled == c2.host_bytes_pulled
    assert c3.adaptive_holds == 1
    # the hold (win-vs-price) is visible without changing the plan
    text = "\n".join(r[0] for r in e.execute_sql(
        f"explain analyze {JOIN_Q}", s).rows())
    assert "Adaptive: hold" in text, text
    assert "partitioned" not in text


def test_adaptive_off_never_consults(small_thresholds):
    e = _engine()
    e.adaptive_advisor.price_scale = 0.0
    s = e.create_session("tpch")
    e.execute_sql("set session adaptive_execution = false", s)
    for _ in range(3):
        e.execute_sql(JOIN_Q, s)
    c = e.last_query_counters.snapshot()
    assert c.adaptive_replans == 0 and c.adaptive_holds == 0
    assert e.adaptive_advisor.info()["replans_total"] == 0
    assert e.adaptive_advisor.decision_trace() == []


# ------------------------------------------------------------------ satellite 1
def test_session_property_is_plan_shaping():
    from trino_tpu.engine import _effective_adaptive, _plan_shape_props

    e = _engine()
    s = e.create_session("tpch")
    on = _plan_shape_props(s)
    assert on[-1] is True and _effective_adaptive(s)
    e.execute_sql("set session adaptive_execution = false", s)
    off = _plan_shape_props(s)
    assert off[-1] is False and off != on
    e.execute_sql("reset session adaptive_execution", s)
    assert _plan_shape_props(s) == on


def test_env_default_off(monkeypatch):
    from trino_tpu.engine import _effective_adaptive, _plan_shape_props

    e = _engine()
    s = e.create_session("tpch")
    monkeypatch.setenv("TRINO_TPU_ADAPTIVE", "0")
    assert not _effective_adaptive(s)
    assert _plan_shape_props(s)[-1] is False
    # the session property overrides the env default in both directions
    e.execute_sql("set session adaptive_execution = true", s)
    assert _effective_adaptive(s)
