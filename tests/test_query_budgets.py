"""Per-query device-boundary BUDGETS over the warm TPC-H north-star queries.

Three rounds of real-TPU captures say warm join queries are bound by
host<->device round-trips over the tunnel, not FLOPs, and the round-5 wins
(_finalize_aggs_device, _topn_page_device) traced a ~40MB -> ~660B transfer
reduction that nothing protected: one stray np.asarray in a loop silently
reverts it.  These tests turn the trace notes into committed invariants —
each warm SF1 query must stay within a dispatch-count and host-bytes ceiling
recorded HERE, from a real capture (reference analog: the zero-per-page
scheduler cost of Trino's driver pump, operator/Driver.java:372-481, enforced
instead of assumed).

Round 9: the budgets pin the DEVICE BUFFER POOL ON (TRINO_TPU_PAGE_CACHE set
by the fixture — the production configuration on device backends).  Each
query's cold run populates the pool; the warm budgeted run serves every scan
as ONE resident page, so the per-split consumer dispatches collapse on top
of the round-6 coalescing win.  Ceilings were re-derived with
scripts/query_counters.py on the 8-device CPU mesh (SF1, split_rows=1<<21,
2026-08-03, `--page-cache 6442450944`) and carry ~25-35% headroom over the
measured warm trace:

    measured warm (cache on):  q1 4/285B   q3  6/258B   q9  7/3057B   q18  6/2831B
    measured warm (cache off): q1 6/285B   q3 10/262B   q9 10/3057B   q18 10/2835B
    measured warm (batch=1):   q1 10/285B  q3 22/278B   q9 29/3077B   q18 20/2851B

The dispatch ceilings now sit BELOW the cache-off trace: losing the pool's
whole-scan hit (a scan source bypassing _scan_pages_source, a put_scan that
stops storing, a key that stops matching across runs) fails this suite just
like losing coalescing or reintroducing a per-split sync would.  Entries are
keyed per (table, splits, columns), and the four queries' scan specs are
pairwise distinct, so the ceilings are test-order independent; 6GB budget
fits the ~2GB SF1 working set with no eviction.  A reintroduced bulk pull
(the device-finalize or device-TopN regressions) overshoots the byte
ceilings by KBs.  Counters are NOT env-dependent beyond the fixture's own
page-cache budget: split geometry is pinned by sf/split_rows and page shapes
are pow2-quantized.

Round 17: the budgets additionally pin warm ``compiles == 0`` (the compile
observatory at the _jit chokepoint — detection is a host-side seen-signature
set lookup, so the dispatch/byte ceilings are UNCHANGED with it enabled).  A
warm compile is the recompile-regression signature: shape churn that used to
ship silently as inflated warm walls now fails this suite by name.  The
observatory's first catch was THIS SUITE's own 2-run structure: with the
page cache on, run 2's whole-scan served page is a new shape class that
recompiles the streams (q1 ~2s, q9 ~4.5s, measured 2026-08-04) — the
budgeted "warm" run is now the THIRD execution, the first that is genuinely
compile-free.  Re-derive with ``scripts/query_counters.py --compiles``.

Re-derive after an intentional executor change (cache-on and off):
    JAX_PLATFORMS=cpu python scripts/query_counters.py --page-cache 6442450944
    JAX_PLATFORMS=cpu python scripts/query_counters.py --page-cache 0
"""

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector

# the bench.py north-star queries (inlined: importing bench.py re-points the
# process-wide XLA compile cache, which tests keep session-private)
QUERIES = {
    "q1": """
    select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
           sum(l_extendedprice) as sum_base_price,
           sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
           sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
           avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price,
           avg(l_discount) as avg_disc, count(*) as count_order
    from lineitem where l_shipdate <= date '1998-12-01' - interval '90' day
    group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus""",
    "q3": """
    select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
           o_orderdate, o_shippriority
    from customer, orders, lineitem
    where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
      and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
      and l_shipdate > date '1995-03-15'
    group by l_orderkey, o_orderdate, o_shippriority
    order by revenue desc, o_orderdate limit 10""",
    "q9": """
    select nation, o_year, sum(amount) as sum_profit from (
      select n_name as nation, extract(year from o_orderdate) as o_year,
        l_extendedprice * (1 - l_discount) - ps_supplycost * l_quantity as amount
      from part, supplier, lineitem, partsupp, orders, nation
      where s_suppkey = l_suppkey and ps_suppkey = l_suppkey and ps_partkey = l_partkey
        and p_partkey = l_partkey and o_orderkey = l_orderkey
        and s_nationkey = n_nationkey and p_name like '%green%') as profit
    group by nation, o_year order by nation, o_year desc""",
    "q18": """
    select c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice, sum(l_quantity)
    from customer, orders, lineitem
    where o_orderkey in (select l_orderkey from lineitem group by l_orderkey
                         having sum(l_quantity) > 300)
      and c_custkey = o_custkey and o_orderkey = l_orderkey
    group by c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
    order by o_totalprice desc, o_orderdate limit 100""",
}

# (max device dispatches, max host bytes pulled) per WARM query with the
# buffer pool on.  Dispatch ceilings enforce the whole-scan cache hit on top
# of coalescing — round-8 ceilings were q1 8, q3 12, q9 15, q18 12; the
# cache-off warm trace (10/10/10 for q3/q9/q18) must now BREACH them, which
# is exactly the protection: a silently dead cache fails the suite.
BUDGETS = {
    "q1": (6, 400),
    "q3": (8, 400),
    "q9": (9, 3400),    # pre-round-6 trace: 4228 bytes — must stay below it
    "q18": (8, 3200),
}


@pytest.fixture(scope="module")
def sf1(request):
    import os

    # round 9: the budgets are pinned WITH the device buffer pool ON (the
    # production configuration on device backends) — the cold run of each
    # query populates the pool, the warm budgeted run serves every scan as
    # one resident page.  6GB comfortably fits the SF1 working set
    # (~2GB of distinct (table, splits, columns) entries), so no eviction
    # perturbs the counters.
    prev = os.environ.get("TRINO_TPU_PAGE_CACHE")
    os.environ["TRINO_TPU_PAGE_CACHE"] = str(6 * 1024 * 1024 * 1024)
    # round 12: the RESULT cache stays OFF here, pinned explicitly.  The
    # budgets measure the EXECUTE path — with the result tier on, the warm
    # budgeted run would be answered whole from the cache (0 dispatches) and
    # the "counters must be live" assertion below would fail.  Re-derive
    # with the same configuration: scripts/query_counters.py keeps the tier
    # off unless --result-cache is passed.
    prev_rc = os.environ.get("TRINO_TPU_RESULT_CACHE")
    os.environ["TRINO_TPU_RESULT_CACHE"] = "0"
    engine = Engine()
    engine.register_catalog("tpch", TpchConnector(sf=1, split_rows=1 << 21))
    session = engine.create_session("tpch")
    yield engine, session
    # SF1 compiled pipelines + build pages + the buffer pool are
    # device-resident: release them before the next module runs
    engine._invalidate()
    if prev is None:
        os.environ.pop("TRINO_TPU_PAGE_CACHE", None)
    else:
        os.environ["TRINO_TPU_PAGE_CACHE"] = prev
    if prev_rc is None:
        os.environ.pop("TRINO_TPU_RESULT_CACHE", None)
    else:
        os.environ["TRINO_TPU_RESULT_CACHE"] = prev_rc


def _sites_table(c) -> str:
    """Per-site attribution dump for budget-failure messages: a tripped
    ceiling names the exact operator/call-site that regressed (re-derive with
    scripts/query_counters.py --sites)."""
    rows = sorted(c.sites.items(),
                  key=lambda kv: (-kv[1]["dispatches"], -kv[1]["bytes"]))
    return "\n".join(f"  {k}: {v['dispatches']} dispatches, "
                     f"{v['transfers']} transfers, {v['bytes']} bytes"
                     for k, v in rows)


@pytest.mark.parametrize("name", sorted(BUDGETS))
def test_warm_query_stays_within_budget(sf1, name):
    engine, session = sf1
    engine.execute_sql(QUERIES[name], session)  # cold: plan + XLA compile
    cold = engine.last_query_counters
    # round 17: the cold run is where the compiles live — the observatory
    # must actually see them (a detection regression would silently pass
    # the warm zero below)
    assert cold.compiles > 0, cold.as_dict()
    # second run: the first CACHE-HIT execution.  The observatory exposed a
    # fact the 2-run structure had hidden: run 1 (cache miss) compiles the
    # per-split page shapes, and run 2's whole-scan served page is a NEW
    # shape class that compiles AGAIN (~2s q1 / ~4.5s q9 on this box,
    # previously invisible inside "warm" wall).  The budgeted run below is
    # therefore the THIRD execution — the first with zero compiles — and
    # its dispatch/byte path is identical to run 2's (same cache-hit plan).
    engine.execute_sql(QUERIES[name], session)
    engine.execute_sql(QUERIES[name], session)  # warm: the budgeted run
    c = engine.last_query_counters
    max_disp, max_bytes = BUDGETS[name]
    # the counters must actually be live (an accounting regression that stops
    # recording would otherwise pass every ceiling)
    assert c.device_dispatches > 0 and c.host_transfers > 0, c
    # round 17: WARM queries compile NOTHING — every dispatch re-uses a
    # seen signature.  A nonzero count here is the recompile-regression
    # signature (shape churn from non-uniform splits, un-quantized size
    # buckets, a cache that stopped keying) that previously shipped
    # silently inside inflated warm walls.
    assert c.compiles == 0, (
        f"{name}: {c.compiles} warm compiles ({c.compile_s:.3f}s) — a "
        f"recompile crept into the warm path; per-site attribution:\n"
        f"{_sites_table(c)}")
    assert c.device_dispatches <= max_disp, (
        f"{name}: {c.device_dispatches} warm device dispatches > budget "
        f"{max_disp} — a per-page/per-split dispatch crept into the warm "
        f"path; per-site attribution:\n{_sites_table(c)}")
    assert c.host_bytes_pulled <= max_bytes, (
        f"{name}: {c.host_bytes_pulled} warm host bytes > budget {max_bytes} "
        f"— a bulk device->host pull crept into the warm path; per-site "
        f"attribution:\n{_sites_table(c)}")


def test_warm_q3_span_tree(sf1):
    """Round-7 acceptance: the warm SF1 q3 span tree — one root, an execution
    span, one dispatch span per counted dispatch, and prefetch-thread spans
    that parent INTO the tree (explicit cross-thread handoff; they were
    orphans when parenting was thread-local)."""
    import time as _time

    engine, session = sf1
    # page_cache=false for THIS session: a buffer-pool hit serves the scan
    # without ever starting a prefetch producer, and this test exists to
    # pin the prefetch-thread span parenting (the property is
    # non-plan-shaping, so the cached plan is reused either way)
    session = engine.create_session("tpch")
    engine.session_properties.set_property(session, "page_cache", False)
    engine.execute_sql(QUERIES["q3"], session)  # plan cache warm (cheap if
    engine.execute_sql(QUERIES["q3"], session)  # the budget tests ran first)
    c = engine.last_query_counters
    t = engine.last_query_trace
    qid = t["query_id"]
    names = [sp["name"] for sp in t["spans"]]
    roots = [sp for sp in t["spans"] if sp["parent_id"] is None]
    assert len(roots) == 1 and roots[0]["name"] == "query"
    assert "execution" in names
    assert names.count("dispatch") == c.device_dispatches
    # per-site sums == totals (the attribution invariant)
    assert sum(v["dispatches"] for v in c.sites.values()) \
        == c.device_dispatches
    assert sum(v["bytes"] for v in c.sites.values()) == c.host_bytes_pulled
    # prefetch spans land slightly after the query returns (producer-thread
    # close): poll the tracer, then check parents resolve inside the trace
    spans = engine.tracer.spans_for(qid)
    for _ in range(50):
        spans = engine.tracer.spans_for(qid)
        if any(sp.name == "prefetch" for sp in spans):
            break
        _time.sleep(0.02)
    prefetch = [sp for sp in spans if sp.name == "prefetch"]
    assert prefetch, \
        f"no prefetch span in {sorted({s.name for s in spans})}"
    ids = {sp.span_id for sp in spans}
    for sp in prefetch:
        assert sp.parent_id in ids, "prefetch span is an orphan"


def test_explain_analyze_q9_per_operator_attribution(sf1):
    """Round-7 acceptance: EXPLAIN ANALYZE on warm SF1 q9 shows per-operator
    and per-site dispatch/byte attribution whose sums equal the query's
    QueryCounters totals exactly."""
    import re

    engine, session = sf1
    r = engine.execute_sql(f"explain analyze {QUERIES['q9']}", session)
    text = "\n".join(str(row[0]) for row in r.rows())
    c = engine.last_query_counters
    m = re.search(r"Device boundary: (\d+) dispatches, (\d+) host transfers, "
                  r"(\d+) bytes pulled", text)
    assert m, text
    assert (int(m.group(1)), int(m.group(2)), int(m.group(3))) == \
        (c.device_dispatches, c.host_transfers, c.host_bytes_pulled), text
    # per-site lines sum to the totals
    sites = re.findall(r"site (\S+): (\d+) dispatches, (\d+) transfers, "
                       r"(\d+) bytes", text)
    assert sites, text
    assert sum(int(d) for _, d, _t, _b in sites) == c.device_dispatches, text
    assert sum(int(b) for _, _d, _t, b in sites) == c.host_bytes_pulled, text
    # per-operator rows attribute the join/aggregate pipeline itself
    op_rows = re.findall(r"\[boundary: (\d+) dispatches, (\d+) transfers, "
                         r"(\d+) bytes\]", text)
    assert op_rows, text
    assert sum(int(d) for d, _t, _b in op_rows) > 0


def test_warm_wall_breakdown_sums_to_wall(sf1):
    """Round-16 acceptance: warm SF1 q3 and q18 wall-breakdown buckets sum
    to within 5% of the measured wall (by construction: disjoint sweep
    attribution + an explicit unattributed remainder), and the flight
    recorder is ENABLED for every budgeted run in this module — its feed
    adds zero dispatches/pulls, so the ceilings above are UNCHANGED."""
    from trino_tpu.execution.tracing import WALL_BUCKETS

    engine, session = sf1
    assert engine.flight_recorder.enabled  # the budget runs record flights
    for name in ("q3", "q18"):
        engine.execute_sql(QUERIES[name], session)  # cold/warm-up
        engine.execute_sql(QUERIES[name], session)  # warm: the measured run
        t = engine.last_query_trace
        bd = t.get("wall_breakdown")
        assert bd, f"{name}: no wall breakdown on the warm trace"
        total = sum(bd[b] for b in WALL_BUCKETS)
        wall = bd["wall_s"]
        assert wall > 0 and abs(total - wall) <= 0.05 * wall, \
            (name, total, wall, bd)
        # the dominant cost is named, not everything dumped in unattributed
        assert bd["device_dispatch"] > 0, bd
        # the statement's flight record carries the same decomposition
        rec = engine.flight_recorder.get(t["query_id"])
        assert rec is not None and rec["wall_breakdown"] == bd


def test_explain_analyze_shows_device_boundary(engine):
    """EXPLAIN ANALYZE surfaces the per-query counters (sql/planprinter)."""
    r = engine.execute_sql(
        "explain analyze select count(*) from nation")
    text = "\n".join(str(row[0]) for row in r.rows())
    assert "Device boundary:" in text
    assert "dispatches" in text and "bytes pulled" in text
