"""TPC-H queries from SQL text, validated against pandas oracles over the same generated
data (SURVEY.md §4: H2QueryRunner cross-check pattern)."""

import numpy as np
import pandas as pd
import pytest


def run(engine, sql):
    return engine.execute_sql(sql, engine.create_session("tpch")).to_pandas()


def assert_frames_close(got: pd.DataFrame, exp: pd.DataFrame, atol=1e-6, rtol=1e-9):
    assert len(got) == len(exp), f"row count {len(got)} != {len(exp)}"
    assert len(got.columns) == len(exp.columns)
    for gcol, ecol in zip(got.columns, exp.columns):
        g, e = got[gcol].to_numpy(), exp[ecol].to_numpy()
        if g.dtype == object or e.dtype == object:
            assert list(g) == list(e), f"column {gcol}"
        else:
            np.testing.assert_allclose(g.astype(np.float64), e.astype(np.float64),
                                       atol=atol, rtol=rtol, err_msg=f"column {gcol}")


D = np.datetime64


def dcol(df, col):
    return df[col].to_numpy().astype("datetime64[D]")


def test_q1(engine, tpch_pandas):
    got = run(engine, """
        select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
               sum(l_extendedprice) as sum_base_price,
               sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
               sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
               avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price,
               avg(l_discount) as avg_disc, count(*) as count_order
        from lineitem
        where l_shipdate <= date '1998-12-01' - interval '90' day
        group by l_returnflag, l_linestatus
        order by l_returnflag, l_linestatus""")
    li = tpch_pandas["lineitem"]
    df = li[dcol(li, "l_shipdate") <= D("1998-12-01") - np.timedelta64(90, "D")].copy()
    df["dp"] = df.l_extendedprice * (1 - df.l_discount)
    df["ch"] = df.dp * (1 + df.l_tax)
    exp = df.groupby(["l_returnflag", "l_linestatus"], as_index=False).agg(
        sum_qty=("l_quantity", "sum"), sum_base=("l_extendedprice", "sum"),
        sum_dp=("dp", "sum"), sum_ch=("ch", "sum"), avg_qty=("l_quantity", "mean"),
        avg_price=("l_extendedprice", "mean"), avg_disc=("l_discount", "mean"),
        cnt=("dp", "size")).sort_values(["l_returnflag", "l_linestatus"]).reset_index(drop=True)
    assert_frames_close(got, exp, atol=0.01)


def test_q6(engine, tpch_pandas):
    got = run(engine, """
        select sum(l_extendedprice * l_discount) as revenue
        from lineitem
        where l_shipdate >= date '1994-01-01'
          and l_shipdate < date '1994-01-01' + interval '1' year
          and l_discount between 0.05 and 0.07
          and l_quantity < 24""")
    li = tpch_pandas["lineitem"]
    m = ((dcol(li, "l_shipdate") >= D("1994-01-01"))
         & (dcol(li, "l_shipdate") < D("1995-01-01"))
         & (li.l_discount >= 0.05) & (li.l_discount <= 0.07) & (li.l_quantity < 24))
    exp = (li[m].l_extendedprice * li[m].l_discount).sum()
    np.testing.assert_allclose(got["revenue"][0], exp, rtol=1e-9)


def test_q3(engine, tpch_pandas):
    got = run(engine, """
        select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
               o_orderdate, o_shippriority
        from customer, orders, lineitem
        where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
          and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
          and l_shipdate > date '1995-03-15'
        group by l_orderkey, o_orderdate, o_shippriority
        order by revenue desc, o_orderdate
        limit 10""")
    c, o, li = tpch_pandas["customer"], tpch_pandas["orders"], tpch_pandas["lineitem"]
    c2 = c[c.c_mktsegment == "BUILDING"]
    o2 = o[dcol(o, "o_orderdate") < D("1995-03-15")]
    l2 = li[dcol(li, "l_shipdate") > D("1995-03-15")].copy()
    j = l2.merge(o2, left_on="l_orderkey", right_on="o_orderkey").merge(
        c2, left_on="o_custkey", right_on="c_custkey")
    j["rev"] = j.l_extendedprice * (1 - j.l_discount)
    exp = (j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"], as_index=False)
           .agg(revenue=("rev", "sum"))
           .sort_values(["revenue", "o_orderdate"], ascending=[False, True])
           .head(10).reset_index(drop=True))
    exp = exp[["l_orderkey", "revenue", "o_orderdate", "o_shippriority"]]
    got2 = got.drop(columns=["o_orderdate"])
    exp2 = exp.drop(columns=["o_orderdate"])
    assert_frames_close(got2, exp2, rtol=1e-9)
    # dates decode to datetime64 at the result surface
    np.testing.assert_array_equal(
        got["o_orderdate"].to_numpy().astype("datetime64[D]"),
        exp["o_orderdate"].to_numpy().astype("datetime64[D]"))


def test_q5(engine, tpch_pandas):
    got = run(engine, """
        select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue
        from customer, orders, lineitem, supplier, nation, region
        where c_custkey = o_custkey and l_orderkey = o_orderkey
          and l_suppkey = s_suppkey and c_nationkey = s_nationkey
          and s_nationkey = n_nationkey and n_regionkey = r_regionkey
          and r_name = 'ASIA' and o_orderdate >= date '1994-01-01'
          and o_orderdate < date '1994-01-01' + interval '1' year
        group by n_name order by revenue desc""")
    t = tpch_pandas
    o2 = t["orders"][(dcol(t["orders"], "o_orderdate") >= D("1994-01-01"))
                     & (dcol(t["orders"], "o_orderdate") < D("1995-01-01"))]
    r2 = t["region"][t["region"].r_name == "ASIA"]
    j = (t["lineitem"].merge(o2, left_on="l_orderkey", right_on="o_orderkey")
         .merge(t["customer"], left_on="o_custkey", right_on="c_custkey")
         .merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey"))
    j = j[j.c_nationkey == j.s_nationkey]
    j = j.merge(t["nation"], left_on="s_nationkey", right_on="n_nationkey")
    j = j.merge(r2, left_on="n_regionkey", right_on="r_regionkey")
    j["rev"] = j.l_extendedprice * (1 - j.l_discount)
    exp = (j.groupby("n_name", as_index=False).agg(revenue=("rev", "sum"))
           .sort_values("revenue", ascending=False).reset_index(drop=True))
    assert_frames_close(got, exp, rtol=1e-9)


def test_q10(engine, tpch_pandas):
    got = run(engine, """
        select c_custkey, c_name, sum(l_extendedprice * (1 - l_discount)) as revenue,
               c_acctbal, n_name
        from customer, orders, lineitem, nation
        where c_custkey = o_custkey and l_orderkey = o_orderkey
          and o_orderdate >= date '1993-10-01'
          and o_orderdate < date '1993-10-01' + interval '3' month
          and l_returnflag = 'R' and c_nationkey = n_nationkey
        group by c_custkey, c_name, c_acctbal, n_name
        order by revenue desc
        limit 20""")
    t = tpch_pandas
    o2 = t["orders"][(dcol(t["orders"], "o_orderdate") >= D("1993-10-01"))
                     & (dcol(t["orders"], "o_orderdate") < D("1994-01-01"))]
    l2 = t["lineitem"][t["lineitem"].l_returnflag == "R"]
    j = (l2.merge(o2, left_on="l_orderkey", right_on="o_orderkey")
         .merge(t["customer"], left_on="o_custkey", right_on="c_custkey")
         .merge(t["nation"], left_on="c_nationkey", right_on="n_nationkey"))
    j["rev"] = j.l_extendedprice * (1 - j.l_discount)
    exp = (j.groupby(["c_custkey", "c_name", "c_acctbal", "n_name"], as_index=False)
           .agg(revenue=("rev", "sum"))
           .sort_values("revenue", ascending=False).head(20).reset_index(drop=True))
    exp = exp[["c_custkey", "c_name", "revenue", "c_acctbal", "n_name"]]
    assert_frames_close(got, exp, rtol=1e-9)


def test_q12(engine, tpch_pandas):
    got = run(engine, """
        select l_shipmode,
               sum(case when o_orderpriority = '1-URGENT' or o_orderpriority = '2-HIGH'
                        then 1 else 0 end) as high_line_count,
               sum(case when o_orderpriority <> '1-URGENT' and o_orderpriority <> '2-HIGH'
                        then 1 else 0 end) as low_line_count
        from orders, lineitem
        where o_orderkey = l_orderkey and l_shipmode in ('MAIL', 'SHIP')
          and l_commitdate < l_receiptdate and l_shipdate < l_commitdate
          and l_receiptdate >= date '1994-01-01'
          and l_receiptdate < date '1994-01-01' + interval '1' year
        group by l_shipmode order by l_shipmode""")
    t = tpch_pandas
    li = t["lineitem"]
    m = (li.l_shipmode.isin(["MAIL", "SHIP"])
         & (dcol(li, "l_commitdate") < dcol(li, "l_receiptdate"))
         & (dcol(li, "l_shipdate") < dcol(li, "l_commitdate"))
         & (dcol(li, "l_receiptdate") >= D("1994-01-01"))
         & (dcol(li, "l_receiptdate") < D("1995-01-01")))
    j = li[m].merge(t["orders"], left_on="l_orderkey", right_on="o_orderkey")
    j["high"] = j.o_orderpriority.isin(["1-URGENT", "2-HIGH"]).astype(int)
    j["low"] = 1 - j.high
    exp = (j.groupby("l_shipmode", as_index=False).agg(
        high_line_count=("high", "sum"), low_line_count=("low", "sum"))
        .sort_values("l_shipmode").reset_index(drop=True))
    assert_frames_close(got, exp)


def test_q14(engine, tpch_pandas):
    got = run(engine, """
        select 100.00 * sum(case when p_type like 'PROMO%'
                                 then l_extendedprice * (1 - l_discount) else 0 end)
               / sum(l_extendedprice * (1 - l_discount)) as promo_revenue
        from lineitem, part
        where l_partkey = p_partkey and l_shipdate >= date '1995-09-01'
          and l_shipdate < date '1995-09-01' + interval '1' month""")
    t = tpch_pandas
    li = t["lineitem"]
    m = (dcol(li, "l_shipdate") >= D("1995-09-01")) & (dcol(li, "l_shipdate") < D("1995-10-01"))
    j = li[m].merge(t["part"], left_on="l_partkey", right_on="p_partkey")
    rev = j.l_extendedprice * (1 - j.l_discount)
    promo = rev.where(j.p_type.str.startswith("PROMO"), 0.0)
    exp = 100.0 * promo.sum() / rev.sum()
    np.testing.assert_allclose(got["promo_revenue"][0], exp, rtol=1e-6)


def test_simple_select_limit(engine, tpch_pandas):
    got = run(engine, "select n_name, n_regionkey from nation order by n_name limit 5")
    exp = tpch_pandas["nation"].sort_values("n_name").head(5).reset_index(drop=True)
    assert list(got["n_name"]) == list(exp["n_name"])
    np.testing.assert_array_equal(got["n_regionkey"].to_numpy(), exp["n_regionkey"].to_numpy())


def test_explicit_join(engine, tpch_pandas):
    got = run(engine, """
        select n_name, count(*) as cnt
        from supplier join nation on s_nationkey = n_nationkey
        group by n_name order by cnt desc, n_name limit 5""")
    t = tpch_pandas
    j = t["supplier"].merge(t["nation"], left_on="s_nationkey", right_on="n_nationkey")
    exp = (j.groupby("n_name", as_index=False).size().rename(columns={"size": "cnt"})
           .sort_values(["cnt", "n_name"], ascending=[False, True]).head(5).reset_index(drop=True))
    assert_frames_close(got, exp)
