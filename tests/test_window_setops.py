"""Window functions and set operations vs pandas oracles."""

import numpy as np
import pandas as pd

from tests.test_sql_tpch import assert_frames_close, run


def test_window_core(engine, tpch_pandas):
    got = run(engine, """
        select o_custkey, o_orderkey,
               row_number() over (partition by o_custkey order by o_orderkey) as rn,
               count(*) over (partition by o_custkey) as cnt,
               sum(o_totalprice) over (partition by o_custkey order by o_orderkey)
                   as running,
               min(o_totalprice) over (partition by o_custkey) as mn,
               max(o_totalprice) over (partition by o_custkey order by o_orderkey)
                   as running_max
        from orders
        order by o_custkey, o_orderkey limit 500""")
    o = tpch_pandas["orders"].sort_values(["o_custkey", "o_orderkey"])
    g = o.groupby("o_custkey")
    exp = pd.DataFrame({
        "o_custkey": o.o_custkey,
        "o_orderkey": o.o_orderkey,
        "rn": g.cumcount() + 1,
        "cnt": g.o_orderkey.transform("count"),
        "running": g.o_totalprice.cumsum(),
        "mn": g.o_totalprice.transform("min"),
        "running_max": g.o_totalprice.cummax(),
    }).head(500).reset_index(drop=True)
    assert_frames_close(got, exp, rtol=1e-9)


def test_window_rank_lag(engine, tpch_pandas):
    got = run(engine, """
        select c_nationkey, c_custkey,
               rank() over (partition by c_nationkey order by c_acctbal desc) as rk,
               dense_rank() over (partition by c_nationkey order by c_acctbal desc)
                   as drk,
               lag(c_custkey) over (partition by c_nationkey order by c_acctbal desc)
                   as prev
        from customer
        order by c_nationkey, rk, c_custkey limit 300""")
    c = tpch_pandas["customer"].copy()
    c["rk"] = c.groupby("c_nationkey").c_acctbal.rank(
        method="min", ascending=False).astype(int)
    c["drk"] = c.groupby("c_nationkey").c_acctbal.rank(
        method="dense", ascending=False).astype(int)
    c = c.sort_values(["c_nationkey", "c_acctbal", "c_custkey"],
                      ascending=[True, False, True])
    c["prev"] = c.groupby("c_nationkey").c_custkey.shift(1)
    exp = (c.sort_values(["c_nationkey", "rk", "c_custkey"])
           [["c_nationkey", "c_custkey", "rk", "drk", "prev"]]
           .head(300).reset_index(drop=True))
    got2 = got.drop(columns=["prev"])
    exp2 = exp.drop(columns=["prev"])
    assert_frames_close(got2, exp2)


def test_union_all_and_distinct(engine, tpch_pandas):
    got = run(engine, """
        select n_regionkey as k from nation
        union all
        select r_regionkey as k from region
        order by k""")
    t = tpch_pandas
    exp = pd.DataFrame({"k": sorted(t["nation"].n_regionkey.tolist()
                                    + t["region"].r_regionkey.tolist())})
    assert_frames_close(got, exp)
    got = run(engine, """
        select n_regionkey as k from nation
        union
        select r_regionkey as k from region
        order by k""")
    exp = pd.DataFrame({"k": sorted(set(t["nation"].n_regionkey)
                                    | set(t["region"].r_regionkey))})
    assert_frames_close(got, exp)


def test_intersect_except(engine, tpch_pandas):
    t = tpch_pandas
    got = run(engine, """
        select c_nationkey as k from customer
        intersect
        select s_nationkey as k from supplier
        order by k""")
    exp = pd.DataFrame({"k": sorted(set(t["customer"].c_nationkey)
                                    & set(t["supplier"].s_nationkey))})
    assert_frames_close(got, exp)
    got = run(engine, """
        select n_nationkey as k from nation
        except
        select c_nationkey as k from customer
        order by k""")
    exp = pd.DataFrame({"k": sorted(set(t["nation"].n_nationkey)
                                    - set(t["customer"].c_nationkey))})
    assert_frames_close(got, exp)


def test_setop_operand_limit(engine):
    r = engine.execute_sql("""
        (select n_nationkey from nation order by n_nationkey limit 2)
        union all
        (select n_nationkey from nation order by n_nationkey desc limit 2)
        order by n_nationkey""")
    assert r.columns[0].tolist() == [0, 1, 23, 24]


def test_explain(engine):
    r = engine.execute_sql(
        "explain select count(*) from lineitem, orders where l_orderkey = o_orderkey")
    text = "\n".join(r.columns[0].tolist())
    assert "TableScan[tpch.lineitem]" in text and "Join" in text, text


def test_window_edge_cases(engine):
    # parenthesized body keeps its own ORDER BY when an outer LIMIT applies
    r = engine.execute_sql("(select n_nationkey from nation order by n_nationkey) limit 3")
    assert r.columns[0].tolist() == [0, 1, 2]
    # DISTINCT window aggregates are rejected, not silently wrong
    import pytest
    from trino_tpu.sql.frontend import SemanticError
    with pytest.raises(SemanticError, match="DISTINCT"):
        engine.execute_sql("select count(distinct l_suppkey) over () from lineitem")
    # lag default fills partition-leading rows instead of NULL
    r = engine.execute_sql(
        "select lag(n_nationkey, 1, -1) over (order by n_nationkey) p "
        "from nation order by n_nationkey limit 2")
    assert r.columns[0].tolist() == [-1, 0]
    # window ORDER BY over a dictionary column uses string collation, not id order
    r = engine.execute_sql(
        "select l_shipmode, row_number() over (order by l_shipmode) rn "
        "from (select l_shipmode from lineitem limit 2000) x order by rn")
    vals = r.columns[0].tolist()
    assert vals == sorted(vals)
    # all-NULL window frames produce NULL, not a sentinel
    r = engine.execute_sql("""
        select n_nationkey, max(o_orderkey) over (partition by n_nationkey) mx
        from nation left outer join orders on n_nationkey = o_custkey
        order by n_nationkey""")
    mx = r.columns[1].tolist()
    assert mx[0] is None  # custkey 0 never exists -> empty frame


def test_rollup_cube_grouping_sets(engine):
    r = engine.execute_sql(
        "select count(*) c from nation group by rollup (n_regionkey, n_nationkey)")
    assert len(r) == 25 + 5 + 1
    r = engine.execute_sql(
        "select n_regionkey, count(*) c from nation "
        "group by grouping sets ((n_regionkey), ()) order by n_regionkey nulls last")
    assert len(r) == 6
    assert r.columns[0][5] is None and r.columns[1][5] == 25
    r = engine.execute_sql("select l_returnflag, l_linestatus, sum(l_quantity) q "
                           "from lineitem group by cube (l_returnflag, l_linestatus)")
    n_pairs = len(engine.execute_sql(
        "select distinct l_returnflag, l_linestatus from lineitem").rows())
    n_rf = len(engine.execute_sql("select distinct l_returnflag from lineitem").rows())
    n_ls = len(engine.execute_sql("select distinct l_linestatus from lineitem").rows())
    assert len(r) == n_pairs + n_rf + n_ls + 1
    # grand total equals ungrouped sum
    total = engine.execute_sql("select sum(l_quantity) q from lineitem").columns[0][0]
    vals = [q for rf, ls, q in r.rows() if rf is None and ls is None]
    assert len(vals) == 1 and abs(vals[0] - total) < 1e-6


def test_cross_and_theta_joins(engine):
    r = engine.execute_sql("select count(*) c from nation, region")
    assert r.columns[0][0] == 125
    r = engine.execute_sql(
        "select count(*) c from nation join region on n_regionkey < r_regionkey")
    per = dict(engine.execute_sql(
        "select n_regionkey, count(*) c from nation group by n_regionkey").rows())
    assert r.columns[0][0] == sum(cnt * (4 - rk) for rk, cnt in per.items())
    r = engine.execute_sql("select count(*) c from nation cross join region "
                           "where n_regionkey = r_regionkey")
    assert r.columns[0][0] == 25


def test_grouping_sets_edge_cases(engine):
    # star expansion over cross/theta joins skips helper key channels
    r = engine.execute_sql("select * from nation, region limit 3")
    assert len(r.names) == 7
    # ordinals and aliases resolve inside grouping elements
    r = engine.execute_sql(
        "select n_regionkey rk, count(*) c from nation group by rollup(1)")
    assert len(r) == 6
    r = engine.execute_sql(
        "select n_regionkey rk, count(*) c from nation group by rollup(rk)")
    assert len(r) == 6
    # rollup/cube/grouping/sets stay valid identifiers
    r = engine.execute_sql("select r_name sets from region order by sets limit 1")
    assert r.names == ("sets",)
    # equi-connected pending pairs join before any cross product
    r = engine.execute_sql("select count(*) c from region, customer, nation "
                           "where c_nationkey = n_nationkey")
    assert r.columns[0][0] == 1500 * 5


def test_ranking_window_additions(engine):
    """ntile / percent_rank / cume_dist / nth_value vs pandas
    (reference: NTileFunction, PercentRankFunction, CumulativeDistributionFunction,
    NthValueFunction)."""
    import numpy as np

    e = engine
    s = e.create_session("tpch")
    rows = e.execute_sql("""
        select n_regionkey, n_nationkey,
               ntile(2) over (partition by n_regionkey order by n_nationkey) b,
               percent_rank() over (partition by n_regionkey order by n_nationkey) pr,
               cume_dist() over (partition by n_regionkey order by n_nationkey) cd,
               nth_value(n_nationkey, 2)
                   over (partition by n_regionkey order by n_nationkey) nv
        from nation order by n_regionkey, n_nationkey""", s).rows()
    import collections

    by_region = collections.defaultdict(list)
    for r in rows:
        by_region[r[0]].append(r)
    for reg, rs in by_region.items():
        size = len(rs)
        assert size == 5  # TPC-H: 5 nations per region
        for i, r in enumerate(rs):
            rn = i + 1
            # ntile(2) over 5 rows: bucket 1 gets 3 rows, bucket 2 gets 2
            assert r[2] == (1 if rn <= 3 else 2), r
            assert abs(r[3] - i / (size - 1)) < 1e-12
            assert abs(r[4] - rn / size) < 1e-12
            # default frame ends at CURRENT ROW: row 1's frame holds one row,
            # so nth_value(x, 2) is NULL there (reference: NthValueFunction)
            assert r[5] == (None if rn < 2 else rs[1][1]), r


def test_window_frames_vs_pandas(engine):
    """ROWS BETWEEN frames (preceding/following/unbounded, empty frames NULL)
    vs direct python evaluation (reference: FramedWindowFunction + the frame
    evaluation in operator/window/WindowPartition.java)."""
    import numpy as np

    e = engine
    s = e.create_session("tpch")
    q = """select n_regionkey rk, n_nationkey nk,
       sum(n_nationkey) over (partition by n_regionkey order by n_nationkey
                              rows between 2 preceding and current row) s3,
       sum(n_nationkey) over (partition by n_regionkey order by n_nationkey
                              rows between 1 preceding and 1 following) sc,
       min(n_nationkey) over (partition by n_regionkey order by n_nationkey
                              rows between 1 following and 2 following) mn,
       avg(n_nationkey) over (partition by n_regionkey order by n_nationkey
                              rows between unbounded preceding and unbounded following) aa,
       first_value(n_nationkey) over (partition by n_regionkey order by n_nationkey
                              rows between 1 preceding and current row) fv,
       count(*) over (partition by n_regionkey order by n_nationkey
                              rows between 3 following and 4 following) cf
       from nation order by rk, nk"""
    rows = e.execute_sql(q, s).to_pandas()
    for rk, g in rows.groupby("rk"):
        nk = g["nk"].to_numpy()
        n = len(nk)
        for i in range(n):
            r = g.iloc[i]
            assert r["s3"] == nk[max(0, i - 2):i + 1].sum()
            assert r["sc"] == nk[max(0, i - 1):min(n, i + 2)].sum()
            win = nk[i + 1:min(n, i + 3)]
            if len(win) == 0:  # empty frame -> NULL
                assert r["mn"] is None or np.isnan(r["mn"])
            else:
                assert r["mn"] == win.min()
            assert abs(r["aa"] - nk.mean()) < 1e-9
            assert r["fv"] == nk[max(0, i - 1)]
            assert r["cf"] == len(nk[i + 3:min(n, i + 5)])


def test_window_range_frame_peers(engine):
    """RANGE UNBOUNDED PRECEDING..CURRENT ROW: peer rows (equal order keys)
    share the frame end — all orders of one custkey see the same running sum."""
    e = engine
    s = e.create_session("tpch")
    rows = e.execute_sql(
        "select o_custkey k, sum(o_totalprice) over (order by o_custkey "
        "range between unbounded preceding and current row) rs "
        "from orders where o_custkey < 50 order by k", s).to_pandas()
    for k, g in rows.groupby("k"):
        assert g["rs"].nunique() == 1  # peers share the value


def test_window_frame_errors(engine):
    from trino_tpu.sql.frontend import SemanticError

    import pytest

    s = engine.create_session("tpch")
    # RANGE offset frames are supported (round 3) — but still require exactly
    # one numeric/date ORDER BY key
    with pytest.raises(SemanticError, match="exactly one ORDER BY"):
        engine.execute_sql(
            "select sum(n_nationkey) over (order by n_regionkey, n_nationkey "
            "range between 2 preceding and current row) from nation", s)
    with pytest.raises(SemanticError, match="numeric or date"):
        engine.execute_sql(
            "select sum(n_nationkey) over (order by n_name "
            "range between 2 preceding and current row) from nation", s)
    rows = engine.execute_sql(
        "select n_nationkey, sum(n_nationkey) over (order by n_nationkey "
        "range between 2 preceding and current row) s "
        "from nation order by n_nationkey", s).rows()
    assert rows[5] == (5, 3 + 4 + 5)
    with pytest.raises(SemanticError, match="reversed"):
        engine.execute_sql(
            "select sum(n_nationkey) over (order by n_nationkey "
            "rows between unbounded following and current row) from nation", s)


def test_right_and_full_outer_joins():
    """RIGHT OUTER plans as a flipped LEFT (re-projected) and FULL OUTER as
    left-join UNION ALL right-anti NULL-padded rows (round 4: these kinds
    previously fell through to the inner-join transform and returned wrong
    rows silently)."""
    from trino_tpu import Engine
    from trino_tpu.connectors.memory import MemoryConnector

    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table ja (k bigint, x bigint)", s)
    e.execute_sql("create table jb (k bigint, y varchar)", s)
    e.execute_sql("insert into ja values (1, 10), (2, 20), (2, 21)", s)
    e.execute_sql("insert into jb values (2, 'two'), (3, 'three'), "
                  "(null, 'none')", s)
    r = e.execute_sql(
        "select ja.k, x, jb.k, y from ja right join jb on ja.k = jb.k "
        "order by y", s).rows()
    assert r == [(None, None, None, "none"), (None, None, 3, "three"),
                 (2, 20, 2, "two"), (2, 21, 2, "two")]
    r = e.execute_sql(
        "select ja.k, x, jb.k, y from ja full outer join jb on ja.k = jb.k "
        "order by coalesce(ja.k, jb.k), x", s).rows()
    assert (1, 10, None, None) in r
    assert (2, 20, 2, "two") in r and (2, 21, 2, "two") in r
    assert (None, None, 3, "three") in r
    assert (None, None, None, "none") in r  # null build key never matches
    assert len(r) == 5
    counts = e.execute_sql(
        "select count(*) c, count(x) cx, count(y) cy from ja "
        "full outer join jb on ja.k = jb.k", s).rows()[0]
    assert tuple(int(v) for v in counts) == (5, 3, 4)


def test_join_using_and_qualified_star():
    """JOIN ... USING (c): equi-join with the column carried ONCE in the
    output scope; alias.* expands one relation's columns (reference:
    StatementAnalyzer joinUsing + qualified asterisk)."""
    from trino_tpu import Engine
    from trino_tpu.connectors.memory import MemoryConnector

    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table ua (k bigint, x bigint)", s)
    e.execute_sql("create table ub (k bigint, y bigint)", s)
    e.execute_sql("insert into ua values (1, 10), (2, 20)", s)
    e.execute_sql("insert into ub values (2, 200), (3, 300)", s)
    r = e.execute_sql("select * from ua join ub using (k)", s).to_pandas()
    assert r.columns.tolist() == ["k", "x", "y"]  # k deduped
    assert r.values.tolist() == [[2, 20, 200]]
    r = e.execute_sql("select k, y from ua left join ub using (k) "
                      "order by k", s).rows()
    assert r == [(1, None), (2, 200)]
    r = e.execute_sql("select ub.*, ua.x from ua join ub on ua.k = ub.k",
                      s).to_pandas()
    assert r.columns.tolist() == ["k", "y", "x"]


def test_grouping_function_rollup():
    """grouping(c...) bitmasks distinguish rollup totals from genuine NULL
    keys (reference: the grouping() rewrite over GroupIdOperator); constant
    per grouping-set branch in the union-of-aggregations planning."""
    from trino_tpu import Engine
    from trino_tpu.connectors.tpch import TpchConnector

    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.001))
    s = e.create_session("tpch")
    r = e.execute_sql(
        "select r_name, n_name, grouping(r_name) gr, "
        "grouping(r_name, n_name) grn, count(*) c "
        "from nation, region where n_regionkey = r_regionkey "
        "group by rollup (r_name, n_name) "
        "order by grn desc, r_name, n_name", s).to_pandas()
    assert len(r) == 25 + 5 + 1
    total = r.iloc[0]
    assert int(total["grn"]) == 3 and int(total["c"]) == 25
    per_region = r[(r["grn"] == 1)]
    assert len(per_region) == 5 and int(per_region["c"].sum()) == 25
    assert (r[r["grn"] == 0]["gr"] == 0).all()
    r2 = e.execute_sql(
        "select r_name, count(*) c from nation, region "
        "where n_regionkey = r_regionkey group by rollup (r_name) "
        "having grouping(r_name) = 1", s).rows()
    assert r2 == [(None, 25)]


def test_intersect_except_all_multiplicity():
    """INTERSECT ALL keeps min(l, r) copies, EXCEPT ALL keeps l - r copies
    (reference: SetOperationNodeTranslator's row_number-based ALL rewrite)."""
    from trino_tpu import Engine
    from trino_tpu.connectors.memory import MemoryConnector

    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table sa (v bigint, w bigint)", s)
    e.execute_sql("create table sb (v bigint, w bigint)", s)
    e.execute_sql("insert into sa values (1, 7), (1, 7), (1, 7), "
                  "(2, 8), (3, 9)", s)
    e.execute_sql("insert into sb values (1, 7), (1, 7), (2, 8), "
                  "(2, 8), (4, 10)", s)
    r = sorted((int(a), int(b)) for a, b in e.execute_sql(
        "select v, w from sa intersect all select v, w from sb", s).rows())
    assert r == [(1, 7), (1, 7), (2, 8)]
    r = sorted((int(a), int(b)) for a, b in e.execute_sql(
        "select v, w from sa except all select v, w from sb", s).rows())
    assert r == [(1, 7), (3, 9)]


def test_string_set_ops_merge_dictionaries():
    """Set operations over string columns from DIFFERENT tables merge the
    dictionaries and remap ids through LUT projections, so equality compares
    values (round 4: previously raised 'differently-encoded')."""
    from trino_tpu import Engine
    from trino_tpu.connectors.memory import MemoryConnector

    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table dx (t varchar)", s)
    e.execute_sql("create table dy (t varchar)", s)
    e.execute_sql("insert into dx values ('a'), ('b'), ('b'), ('c')", s)
    e.execute_sql("insert into dy values ('b'), ('d')", s)
    q = lambda sql: sorted(r[0] for r in e.execute_sql(sql, s).rows())
    assert q("select t from dx union select t from dy") == \
        ["a", "b", "c", "d"]
    assert q("select t from dx union all select t from dy") == \
        ["a", "b", "b", "b", "c", "d"]
    assert q("select t from dx intersect select t from dy") == ["b"]
    assert q("select t from dx except all select t from dy") == \
        ["a", "b", "c"]
