"""Iceberg connector: hadoop-table layout metadata -> manifests -> parquet
data files, with file-level bound pruning (reference:
plugin/trino-iceberg/.../IcebergMetadata.java:466, IcebergSplitSource;
manifest reading via the avro container format).

The fixture fabricates a spec-shaped table: v1 metadata JSON +
version-hint.text, an avro manifest list, an avro manifest whose entries
carry per-file record counts and lower/upper bounds (iceberg single-value
serialization), and parquet data files — including a DELETED entry that must
be skipped and two live files with disjoint key ranges for pruning."""

import json
import os
import struct

import numpy as np
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.iceberg import IcebergConnector
from trino_tpu.formats.avro import write_container

KV = {"type": "record", "name": "kv", "fields": [
    {"name": "key", "type": "int"}, {"name": "value", "type": "bytes"}]}

MANIFEST_ENTRY = {"type": "record", "name": "manifest_entry", "fields": [
    {"name": "status", "type": "int"},
    {"name": "snapshot_id", "type": ["null", "long"]},
    {"name": "data_file", "type": {"type": "record", "name": "r2", "fields": [
        {"name": "content", "type": "int"},
        {"name": "file_path", "type": "string"},
        {"name": "file_format", "type": "string"},
        {"name": "record_count", "type": "long"},
        {"name": "file_size_in_bytes", "type": "long"},
        {"name": "lower_bounds", "type": ["null", {"type": "array",
                                                   "items": KV}]},
        {"name": "upper_bounds", "type": ["null", {"type": "array",
                                                   "items": KV}]},
    ]}},
]}

MANIFEST_FILE = {"type": "record", "name": "manifest_file", "fields": [
    {"name": "manifest_path", "type": "string"},
    {"name": "manifest_length", "type": "long"},
    {"name": "partition_spec_id", "type": "int"},
]}


def _long(v):
    return struct.pack("<q", v)


@pytest.fixture(scope="module")
def warehouse(tmp_path_factory):
    import pyarrow as pa
    import pyarrow.parquet as pq

    root = tmp_path_factory.mktemp("icewh")
    tdir = root / "events"
    (tdir / "metadata").mkdir(parents=True)
    (tdir / "data").mkdir()

    def datafile(name, ids, names, amounts):
        path = tdir / "data" / name
        pq.write_table(pa.table({
            "id": pa.array(ids, pa.int64()),
            "name": pa.array(names),
            "amount": pa.array(amounts, pa.float64()),
        }), path, row_group_size=4)
        return str(path)

    f1 = datafile("f1.parquet", list(range(0, 10)),
                  [f"u{i % 3}" for i in range(10)],
                  [float(i) for i in range(10)])
    f2 = datafile("f2.parquet", list(range(100, 110)),
                  [f"u{i % 5}" for i in range(10)],
                  [float(i) * 2 for i in range(10)])
    f3 = datafile("f3.parquet", [999], ["dead"], [0.0])  # DELETED entry

    def bounds(lo_id, hi_id):
        return ([{"key": 1, "value": _long(lo_id)}],
                [{"key": 1, "value": _long(hi_id)}])

    entries = []
    for status, path, n, (lo, hi) in (
            (1, f1, 10, bounds(0, 9)),
            (1, f2, 10, bounds(100, 109)),
            (2, f3, 1, bounds(999, 999))):  # status 2 = deleted
        entries.append({
            "status": status, "snapshot_id": 7,
            "data_file": {
                "content": 0, "file_path": path, "file_format": "PARQUET",
                "record_count": n,
                "file_size_in_bytes": os.path.getsize(path),
                "lower_bounds": lo, "upper_bounds": hi,
            }})
    mpath = str(tdir / "metadata" / "m1.avro")
    write_container(mpath, MANIFEST_ENTRY, entries, codec="deflate")
    mlist = str(tdir / "metadata" / "snap-7.avro")
    write_container(mlist, MANIFEST_FILE,
                    [{"manifest_path": mpath,
                      "manifest_length": os.path.getsize(mpath),
                      "partition_spec_id": 0}])

    meta = {
        "format-version": 1,
        "table-uuid": "0000-test",
        "location": str(tdir),
        "current-schema-id": 0,
        "schemas": [{"schema-id": 0, "type": "struct", "fields": [
            {"id": 1, "name": "id", "type": "long", "required": True},
            {"id": 2, "name": "name", "type": "string", "required": False},
            {"id": 3, "name": "amount", "type": "double", "required": False},
        ]}],
        "current-snapshot-id": 7,
        "snapshots": [{"snapshot-id": 7, "manifest-list": mlist}],
    }
    with open(tdir / "metadata" / "v3.metadata.json", "w") as f:
        json.dump(meta, f)
    with open(tdir / "metadata" / "version-hint.text", "w") as f:
        f.write("3")
    return str(root)


@pytest.fixture(scope="module")
def ice_engine(warehouse):
    e = Engine()
    e.register_catalog("ice", IcebergConnector(warehouse))
    return e, e.create_session("ice")


def test_iceberg_scan_skips_deleted(ice_engine):
    e, s = ice_engine
    rows = e.execute_sql("select count(*) c, sum(id) si from events", s).rows()
    # 20 live rows; the deleted file's id=999 must not appear
    assert rows == [(20, sum(range(10)) + sum(range(100, 110)))]


def test_iceberg_strings_unified_across_files(ice_engine):
    e, s = ice_engine
    rows = e.execute_sql(
        "select name, count(*) c from events group by name order by name",
        s).rows()
    names = [r[0] for r in rows]
    assert names == sorted(set(f"u{i % 3}" for i in range(10))
                           | set(f"u{i % 5}" for i in range(10)))
    assert sum(r[1] for r in rows) == 20
    assert "dead" not in names


def test_iceberg_file_pruning(ice_engine, warehouse):
    """A selective predicate on id must skip the other file's splits entirely
    (manifest bounds + row-group stats feed tuple-domain split pruning)."""
    e, s = ice_engine
    conn = e.catalogs["ice"]
    generated = []
    orig = conn.generate
    conn.generate = lambda sp, cols: (generated.append(sp), orig(sp, cols))[1]
    try:
        rows = e.execute_sql(
            "select count(*) c from events where id >= 100", s).rows()
    finally:
        del conn.generate
    assert rows == [(10,)]
    assert generated, "expected at least one split scanned"
    assert all(sp.file_index == 1 for sp in generated), \
        "file f1's splits were not pruned"


def test_iceberg_column_range_and_tables(ice_engine, warehouse):
    e, s = ice_engine
    conn = e.catalogs["ice"]
    assert conn.tables() == ["events"]
    assert conn.column_range("events", "id") == (0, 109)
    # joins against other catalogs work through the same page machinery
    rows = e.execute_sql(
        "select count(*) c from events a, events b "
        "where a.id = b.id and a.amount > 3", s).rows()
    # amount > 3: f1 has 6 rows (4..9), f2 has 8 (amounts 4,6,...,18)
    assert rows == [(14,)]
