"""bench_serve.py smoke (round-12 CI satellite, round-14 template phase):
in-process server, tiny load, asserting the JSON-line contract — per-class
p50/p99 for every workload class across the three phases, cache/template
hit rates, the counter-verified zero-dispatch warm repeat hit, and cache-on
results byte-identical to cache-off.

Since round 14 the point/param classes draw per-request DISTINCT constants
(the millions-of-users shape plan templates serve), so the cache-on phase
legitimately dispatches for first-sight bindings — the zero-dispatch
contract is pinned on the REPEAT statement (``warm_hit_zero_dispatches``),
not the whole phase.

The 5x acceptance ratios are NOT asserted here: the 1-core build box's
load makes absolute latency ratios flaky at smoke scale — the ratios are
recorded in the payload (``repeat_p50_speedup``,
``{point,param}_template_qps_speedup``) and captured for real by
scripts/tpu_watch.sh's serve A/B.
"""

import json

import pytest


@pytest.fixture(scope="module")
def serve_payload():
    import contextlib
    import io

    import bench_serve

    # tiny knobs via module attributes (env was read at import time);
    # module-scoped so the ~30s serve run happens ONCE for both tests
    mp = pytest.MonkeyPatch()
    mp.setattr(bench_serve, "SF", 0.01)
    mp.setattr(bench_serve, "DURATION", 1.2)
    mp.setattr(bench_serve, "CLIENTS", 2)
    mp.setattr(bench_serve, "QPS", 3.0)
    mp.setattr(bench_serve, "BATCH_QPS", 48.0)
    mp.setattr(bench_serve, "BUDGET", 480.0)
    mp.setattr(bench_serve, "RESULT_CACHE", 64 << 20)
    mp.setattr(bench_serve, "PAGE_CACHE", 1 << 30)
    mp.setattr(bench_serve, "WORKERS", 0)
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            bench_serve.main()
    finally:
        mp.undo()
    out = buf.getvalue().strip().splitlines()
    # ONE JSON line on stdout — the bench.py contract
    assert len(out) == 1, out
    yield json.loads(out[0])


def test_json_line_contract(serve_payload):
    p = serve_payload
    assert p["metric"].startswith("serve_sf0.01")
    assert p["unit"] == "qps" and p["value"] > 0
    assert "env" in p
    for half in ("templates_off", "cache_off", "cache_on"):
        phase = p["phases"][half]
        classes = phase["closed"]["classes"]
        for cls in ("repeat", "point", "param", "agg", "tpch"):
            assert cls in classes, (half, classes)
            if classes[cls]["count"]:
                assert classes[cls]["p50_ms"] is not None
                assert classes[cls]["p99_ms"] is not None
        assert phase["open"] is not None  # open loop ran too
        # cache hit rates ride each phase's buffer-pool snapshot
        assert "result_hits" in phase["buffer_pool"]
        assert "hits" in phase["buffer_pool"]
    on = p["phases"]["cache_on"]
    assert on["buffer_pool"]["result_hits"] > 0
    assert on["counters"]["result_cache_hits"] > 0


def test_warm_hits_cost_zero_dispatches_and_match(serve_payload):
    p = serve_payload
    # the acceptance contract, counter-verified in-process by bench_serve
    assert p["warm_hit_zero_dispatches"] is True
    assert p["cache_identical"] is True
    # repeats serve from the result tier; DISTINCT point/param bindings
    # execute (each is its own binding-specific entry), so the phase
    # dispatches — but the repeat statement never does, and the tier is live
    on = p["phases"]["cache_on"]["counters"]
    assert on["result_cache_hits"] > 0, on
    # and the off half actually executed (the A/B is a real A/B)
    off = p["phases"]["cache_off"]["counters"]
    assert off["device_dispatches"] > 0
    assert off["result_cache_hits"] == 0


def test_template_phase_contract(serve_payload):
    p = serve_payload
    # the template A/B ran: substitution baseline shows zero template
    # traffic, the template phase shows hits on the point/param classes
    off = p["phases"]["templates_off"]["counters"]
    assert off["plan_template_hits"] == 0, off
    on = p["phases"]["cache_off"]["counters"]
    assert on["plan_template_hits"] > 0, on
    assert p["template_hit_rate"] > 0
