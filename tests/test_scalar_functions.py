"""Scalar function library (reference: operator/scalar/* — the high-traffic subset)."""

import numpy as np
import pytest


def one(engine, sql):
    return engine.execute_sql(sql).rows()[0]


def test_math(engine):
    p, m, s, t, r = one(engine, "select power(2, 10) p, mod(10, 3) m, sign(-5) s, "
                        "trunc(3.9) t, round(2.567, 2) r from region limit 1")
    assert p == 1024.0 and m == 1 and s == -1
    assert abs(t - 3.0) < 1e-9 and abs(r - 2.57) < 1e-9
    l, lt, sn, pi = one(engine, "select ln(exp(2.0)) l, log10(1000) lt, sin(0) s, "
                        "pi() p from region limit 1")
    assert abs(l - 2.0) < 1e-12 and abs(lt - 3.0) < 1e-12 and sn == 0.0
    assert abs(pi - np.pi) < 1e-12


def test_string_functions(engine):
    u, n, rv = one(engine, "select upper(n_name) u, length(n_name) n, "
                   "reverse(n_name) rv from nation where n_nationkey = 0")
    assert (u, n, rv) == ("ALGERIA", 7, "AIREGLA")
    sp, sw, rp = one(engine, "select strpos(n_name, 'GER') sp, "
                     "starts_with(n_name, 'ALG') sw, replace(n_name, 'A', '@') rp "
                     "from nation where n_nationkey = 0")
    assert (sp, bool(sw), rp) == (3, True, "@LGERI@")
    c1, c2 = one(engine, "select concat('pre-', n_name) c1, n_name || '-post' c2 "
                 "from nation where n_nationkey = 0")
    assert (c1, c2) == ("pre-ALGERIA", "ALGERIA-post")
    lp, rp2 = one(engine, "select lpad(n_name, 10, '.') lp, rpad(n_name, 3) rp "
                  "from nation where n_nationkey = 0")
    assert (lp, rp2) == ("...ALGERIA", "ALG")


def test_date_functions(engine, tpch_pandas):
    import pandas as pd

    got = engine.execute_sql(
        "select o_orderdate d, date_trunc('month', o_orderdate) m, "
        "date_trunc('year', o_orderdate) y, quarter(o_orderdate) q, "
        "day_of_week(o_orderdate) dw, day_of_year(o_orderdate) dy "
        "from orders order by o_orderkey limit 50")
    for d, m, y, q, dw, dy in got.rows():
        ts = pd.Timestamp(d)  # dates decode to datetime64 at the surface
        assert pd.Timestamp(m) == ts.replace(day=1)
        assert pd.Timestamp(y) == ts.replace(month=1, day=1)
        assert q == (ts.month - 1) // 3 + 1
        assert dw == ts.isoweekday()
        assert dy == ts.dayofyear


def test_conditional(engine):
    z, nz, i = one(engine, "select nullif(n_nationkey, 0) z, nullif(n_nationkey, 9) nz,"
                   " if(n_nationkey = 0, 'zero', 'other') i "
                   "from nation where n_nationkey = 0")
    assert z is None and nz == 0 and i == "zero"
    r = engine.execute_sql(
        "select case when n_nationkey < 5 then 'low' when n_nationkey < 15 then 'mid' "
        "else 'high' end b, count(*) c from nation group by 1 order by 1")
    assert dict(r.rows()) == {"high": 10, "low": 5, "mid": 10}


def test_string_case_order(engine):
    # CASE-derived string dictionaries sort by collation in ORDER BY
    r = engine.execute_sql(
        "select distinct case when n_nationkey < 5 then 'b-low' else 'a-high' end v "
        "from nation order by v")
    assert r.columns[0].tolist() == ["a-high", "b-low"]


def test_review_fixes(engine):
    # nullif with NULL second argument returns the first argument
    r = engine.execute_sql(
        "select nullif(n_nationkey, nullif(0, 0)) v from nation where n_nationkey = 2")
    assert r.columns[0][0] == 2
    # round half away from zero
    r = engine.execute_sql("select round(0.125, 2) a, round(2.5) b, round(-2.5) c "
                           "from region limit 1")
    a, b, c = r.rows()[0]
    assert abs(a - 0.13) < 1e-9 and b == 3 and c == -3
    # lpad repeating multi-char pattern; empty pad rejected
    r = engine.execute_sql("select lpad(n_name, 12, 'xy') v from nation "
                           "where n_nationkey = 0")
    assert r.columns[0][0] == "xyxyxALGERIA"
    from trino_tpu.sql.frontend import SemanticError
    with pytest.raises(SemanticError, match="must not be empty"):
        engine.execute_sql("select lpad(n_name, 12, '') from nation")
    # width_bucket
    r = engine.execute_sql(
        "select width_bucket(5.5, 0, 10, 5) w from region limit 1")
    assert r.columns[0][0] == 3


def test_string_and_date_function_additions(engine):
    """regexp_like, split_part, position(IN), codepoint, date_add, date_diff
    (reference: JoniRegexpFunctions, StringFunctions, DateTimeFunctions)."""
    s = engine.create_session("tpch")
    e = engine
    assert e.execute_sql(
        "select count(*) from nation where regexp_like(n_name, '^.*IA$')", s
    ).rows()[0][0] == 7
    assert e.execute_sql(
        "select split_part(n_name, 'I', 2) from nation where n_name = 'INDIA'", s
    ).rows() == [("ND",)]
    assert e.execute_sql(
        "select position('I' in n_name) from nation where n_name = 'ALGERIA'", s
    ).rows() == [(6,)]
    assert e.execute_sql("select codepoint('A')", s).rows() == [(65,)]
    assert e.execute_sql(
        "select date_add('month', 2, date '1995-12-31') = date '1996-02-29'", s
    ).rows() == [(True,)]
    assert e.execute_sql(
        "select date_add('year', 1, date '1996-02-29') = date '1997-02-28'", s
    ).rows() == [(True,)]
    assert e.execute_sql(
        "select date_diff('month', date '1995-01-15', date '1995-03-14')", s
    ).rows() == [(1,)]
    assert e.execute_sql(
        "select date_diff('week', date '1995-01-01', date '1995-01-15')", s
    ).rows() == [(2,)]


def test_try_cast():
    """TRY_CAST returns NULL on conversion failure (reference: TryCastFunction)."""
    from trino_tpu import Engine
    from trino_tpu.connectors.memory import MemoryConnector

    e = Engine()
    e.register_catalog("memory", MemoryConnector())
    s = e.create_session("memory")
    e.execute_sql("create table t (v varchar)", s)
    e.execute_sql("insert into t values ('12'), ('x'), ('3.5'), (''), ('  7 ')", s)
    assert e.execute_sql("select try_cast(v as bigint) from t", s).rows() == \
        [(12,), (None,), (None,), (None,), (7,)]
    assert e.execute_sql("select try_cast(v as double) from t", s).rows() == \
        [(12.0,), (None,), (3.5,), (None,), (7.0,)]
    assert e.execute_sql("select count(try_cast(v as bigint)) from t", s
                         ).rows()[0][0] == 2
    # numeric-to-numeric try_cast reduces to plain coercion
    assert e.execute_sql("select try_cast(5 as double)", s).rows() == [(5.0,)]


def test_nullif_string_literal_resolves_dictionary(engine):
    """nullif over a string column and a literal compares VALUES, not raw
    storage ids: the literal's private one-entry dictionary assigns it id 0,
    so the pre-fix raw-id comparison NULLed whichever column value happened
    to hold id 0 (functions._build_nullif now merges both sides into one
    union id space)."""
    r = engine.execute_sql(
        "select n_name, nullif(n_name, 'FRANCE') v from nation order by n_name")
    for name, v in r.rows():
        assert v == (None if name == "FRANCE" else name), (name, v)
    # reversed argument order: the LITERAL is the surviving value
    r = engine.execute_sql(
        "select n_name, nullif('FRANCE', n_name) v from nation order by n_name")
    for name, v in r.rows():
        assert v == (None if name == "FRANCE" else "FRANCE"), (name, v)


def test_nullif_string_literal_absent_from_dictionary(engine):
    """A literal that appears nowhere in the column never equals any value:
    no row may come back NULL (the id-0 bug NULLed one arbitrary value)."""
    r = engine.execute_sql(
        "select n_name, nullif(n_name, 'banana') v from nation order by n_name")
    assert len(r) == 25
    for name, v in r.rows():
        assert v == name, (name, v)


def test_having_string_literal_over_formatter_dict_raises(engine):
    """HAVING <string-agg> = 'lit' over a formatter (non-enumerable)
    dictionary must fail with the analyzer's SemanticError, not a bare
    KeyError from Dictionary.lookup (aggsugar._dict_of filters
    values=None dictionaries)."""
    from trino_tpu.sql.frontend import SemanticError

    with pytest.raises(SemanticError):
        engine.execute_sql(
            "select c_nationkey, min(c_name) m from customer "
            "group by c_nationkey having min(c_name) = 'nobody'")
