"""DB-API 2.0 binding + verifier service.

Reference: client/trino-jdbc driver tests; service/trino-verifier
(Verifier.java:56) replay-and-diff behavior.
"""

import datetime

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.server import dbapi
from trino_tpu.verifier import Verifier, VerifierQuery


def _engine():
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.005, split_rows=1 << 11))
    return e


def test_dbapi_basic():
    conn = dbapi.connect(engine=_engine(), catalog="tpch")
    cur = conn.cursor()
    cur.execute("select n_name, n_regionkey from nation order by n_nationkey limit 3")
    assert [d[0] for d in cur.description] == ["n_name", "n_regionkey"]
    rows = cur.fetchall()
    assert rows[0] == ("ALGERIA", 0) and len(rows) == 3
    assert all(isinstance(v, (str, int)) for r in rows for v in r)  # python scalars
    cur.execute("select count(*) from region")
    assert cur.fetchone() == (5,)
    assert cur.fetchone() is None
    conn.close()
    with pytest.raises(dbapi.InterfaceError):
        conn.cursor()


def test_dbapi_parameters():
    conn = dbapi.connect(engine=_engine(), catalog="tpch")
    cur = conn.cursor()
    cur.execute("select count(*) from orders where o_orderdate < ? and o_orderkey > ?",
                (datetime.date(1995, 3, 15), 100))
    n = cur.fetchone()[0]
    cur.execute("""select count(*) from orders
                   where o_orderdate < date '1995-03-15' and o_orderkey > 100""")
    assert cur.fetchone()[0] == n
    # '?' inside a string literal is data, not a parameter
    cur.execute("select count(*) from nation where n_name = 'what?'")
    assert cur.fetchone()[0] == 0
    with pytest.raises(dbapi.ProgrammingError):
        cur.execute("select ? ", ())


def test_dbapi_fetch_shapes_and_iter():
    conn = dbapi.connect(engine=_engine(), catalog="tpch")
    cur = conn.cursor()
    cur.execute("select n_nationkey from nation order by n_nationkey")
    assert cur.rowcount == 25
    assert len(cur.fetchmany(10)) == 10
    assert len(cur.fetchall()) == 15
    cur.execute("select n_nationkey from nation order by n_nationkey limit 4")
    assert [r[0] for r in cur] == [0, 1, 2, 3]


def test_verifier_match_and_mismatch():
    e = _engine()
    s = e.create_session("tpch")
    control = lambda q: e.execute_sql(q, s).rows()

    def broken(q):
        rows = e.execute_sql(q, s).rows()
        if "region" in q:
            return rows[:-1]  # drop a row
        return rows

    qs = [VerifierQuery("count_nation", "select count(*) from nation"),
          VerifierQuery("regions", "select r_name from region order by r_name"),
          VerifierQuery("bad_sql", "select nope from nowhere")]
    results = Verifier(control, broken).run(qs)
    by = {r.name: r for r in results}
    assert by["count_nation"].status == "MATCH"
    assert by["regions"].status == "MISMATCH"
    assert by["bad_sql"].status == "CONTROL_FAILED"
    rep = Verifier.report(results)
    assert "MISMATCH" in rep and "MATCH=1" in rep


def test_verifier_local_vs_fault_tolerant():
    """The FTE executor is qualified against local execution — the verifier's
    actual job (reference: qualifying a new engine config against control)."""
    e = _engine()
    s = e.create_session("tpch")
    control = lambda q: e.execute_sql(q, s).rows()
    test = lambda q: e.execute_sql(q, s, fault_tolerant=True).rows()
    qs = [VerifierQuery("q1ish", """select l_returnflag, count(*), sum(l_quantity)
                                    from lineitem group by l_returnflag
                                    order by l_returnflag"""),
          VerifierQuery("orders_by_prio", """select o_orderpriority, count(*)
                                             from orders group by o_orderpriority
                                             order by 1""")]
    results = Verifier(control, test).run(qs)
    assert all(r.status == "MATCH" for r in results), Verifier.report(results)
