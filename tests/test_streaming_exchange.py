"""Streaming (pipelined) inter-process exchange: in-memory worker output
buffers with long-poll + token-ack reads replace the spool for nested
single-task fragments (reference: operator/HttpPageBufferClient.java:100,
server/TaskResource.java:331-383, execution/buffer/PartitionedOutputBuffer),
and the worker executes fragments CONCURRENTLY from an executor pool
(reference: execution/executor/TaskExecutor.java — round-3 VERDICT items 5/6).
"""

import json
import os
import pathlib
import pickle
import subprocess
import sys
import time

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.exec.fte import SpoolingExchange, deserialize_fragment_output
from trino_tpu.server.cluster import (ClusterCoordinator, WorkerServer,
                                      _OutputBuffer, _http,
                                      stream_task_pages)

CATALOGS = {"tpch": {"connector": "tpch", "sf": 0.01, "split_rows": 1 << 11}}


def _engine():
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 11))
    return e


# --------------------------------------------------------------- buffer unit
def test_output_buffer_token_ack_frees_memory():
    buf = _OutputBuffer(max_bytes=100)
    buf.add(b"x" * 40)
    buf.add(b"y" * 40)
    page, complete, failed = buf.get(0, max_wait=0.1)
    assert page == b"x" * 40 and not complete and not failed
    # token 1 acknowledges page 0: its bytes free, page 1 served
    page, complete, _ = buf.get(1, max_wait=0.1)
    assert page == b"y" * 40
    assert buf.bytes == 40
    buf.finish()
    page, complete, _ = buf.get(2, max_wait=0.1)
    assert page is None and complete


def test_output_buffer_backpressures_producer():
    import threading

    buf = _OutputBuffer(max_bytes=50)
    buf.add(b"a" * 40)
    state = {"second_added": False}

    def producer():
        buf.add(b"b" * 40)  # blocks: 80 > 50 with unacked page 0
        state["second_added"] = True

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    time.sleep(0.2)
    assert not state["second_added"], "producer must block while full"
    buf.get(1, max_wait=0.1)  # ack page 0 -> frees 40 bytes
    t.join(timeout=2)
    assert state["second_added"]


def test_output_buffer_failure_propagates():
    buf = _OutputBuffer()
    buf.fail("boom: exploded")
    page, complete, failed = buf.get(0, max_wait=0.1)
    assert failed and "boom" in failed


# ------------------------------------------------- worker protocol (in-proc)
def test_streaming_task_roundtrip_no_disk(tmp_path):
    """A fragment task with streaming output serves its pages over the
    long-poll endpoint and never writes a spool file."""
    e = _engine()
    w = WorkerServer(CATALOGS, str(tmp_path / "spool"))
    url = w.start()
    try:
        from trino_tpu.sql.frontend import compile_sql

        plan = compile_sql(
            "select o_orderkey, o_totalprice from orders "
            "order by o_totalprice desc limit 7",
            e, e.create_session("tpch"))
        xdir = str(tmp_path / "x")
        _http(f"{url}/v1/fragment",
              pickle.dumps({"fragment_id": "f1", "plan": plan}))
        _http(f"{url}/v1/task",
              pickle.dumps({"task_id": "t_stream", "fragment_id": "f1",
                            "kind": "fragment", "exchange_dir": xdir,
                            "output": "stream"}))
        chunks = list(stream_task_pages(url, "t_stream", timeout=60))
        assert len(chunks) == 1
        cols, nulls, dicts = deserialize_fragment_output(chunks[0])
        assert len(cols[0]) == 7
        assert not SpoolingExchange(xdir).is_committed("t_stream")
        # buffer is dropped after complete delivery
        time.sleep(0.1)
        assert "t_stream" not in w.out_buffers
    finally:
        w.stop()


def test_worker_concurrent_fragments(tmp_path):
    """Two fragment tasks overlap on one worker (executor pool replaced the
    round-3 global execution lock); peak_concurrency observes it."""
    e = _engine()
    w = WorkerServer(CATALOGS, str(tmp_path / "spool"))
    url = w.start()
    try:
        from trino_tpu.sql.frontend import compile_sql

        sql = ("select l_orderkey, sum(l_extendedprice * (1 - l_discount)) r "
               "from lineitem, orders where l_orderkey = o_orderkey "
               "group by l_orderkey order by r desc limit 5")
        plan = compile_sql(sql, e, e.create_session("tpch"))
        xdir = str(tmp_path / "x")
        _http(f"{url}/v1/fragment",
              pickle.dumps({"fragment_id": "fc", "plan": plan}))
        for tid in ("c1", "c2"):
            _http(f"{url}/v1/task",
                  pickle.dumps({"task_id": tid, "fragment_id": "fc",
                                "kind": "fragment", "exchange_dir": xdir}))
        deadline = time.time() + 120
        while time.time() < deadline:
            states = [json.loads(_http(f"{url}/v1/task/{tid}")).get("state")
                      for tid in ("c1", "c2")]
            if all(s == "done" for s in states):
                break
            assert "failed" not in states, states
            time.sleep(0.1)
        else:
            raise AssertionError(f"tasks did not finish: {states}")
        info = json.loads(_http(f"{url}/v1/info"))
        assert info["peak_concurrency"] >= 2, info
        ex = SpoolingExchange(xdir)
        a = deserialize_fragment_output(ex.read("c1"))
        b = deserialize_fragment_output(ex.read("c2"))
        assert [list(c) for c in a[0]] == [list(c) for c in b[0]]
    finally:
        w.stop()


# ------------------------------------------- cluster plane (OS processes)
def _spawn_worker(tmp_path, coord_url, node_id):
    env = dict(os.environ)
    env["TRINO_TPU_WORKER_CPU"] = "1"
    repo_root = str(pathlib.Path(__file__).resolve().parents[1])
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-m", "trino_tpu.server.cluster",
         "--coordinator", coord_url, "--catalogs", json.dumps(CATALOGS),
         "--spool", str(tmp_path / "spool"), "--node-id", node_id],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)


@pytest.mark.slow
def test_streaming_exchange_worker_to_worker(tmp_path):
    """A join build side (and the whole nested single-task fragment chain)
    streams worker->worker through in-memory buffers — no spool files for the
    streamed producers — and the result matches local execution."""
    e = _engine()
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.3)
    assert coord.stream_exchange  # pipelined plane is the default
    url = coord.start()
    w1 = w2 = None
    sql = """select a.k, a.s, b.c_name from
             (select o_custkey k, sum(o_totalprice) s from orders
              group by o_custkey) a,
             (select c_custkey, c_name, c_acctbal from customer
              order by c_acctbal desc, c_custkey limit 50) b
             where a.k = b.c_custkey order by a.s desc, a.k limit 10"""
    try:
        w1 = _spawn_worker(tmp_path, url, "w1")
        w2 = _spawn_worker(tmp_path, url, "w2")
        coord.wait_for_workers(2, timeout=60)
        expected = e.execute_sql(sql).rows()
        got = coord.execute_sql(sql).rows()
        assert got == expected
        assert coord.streamed_tasks >= 1, \
            "no fragment streamed (pipelined plane did not engage)"
    finally:
        coord.stop()
        for w in (w1, w2):
            if w is not None:
                w.terminate()
                w.wait(timeout=10)
