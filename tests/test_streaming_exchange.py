"""Streaming (pipelined) inter-process exchange: in-memory worker output
buffers with long-poll + token-ack reads replace the spool for nested
single-task fragments (reference: operator/HttpPageBufferClient.java:100,
server/TaskResource.java:331-383, execution/buffer/PartitionedOutputBuffer),
and the worker executes fragments CONCURRENTLY from an executor pool
(reference: execution/executor/TaskExecutor.java — round-3 VERDICT items 5/6).
"""

import json
import os
import pathlib
import pickle
import subprocess
import sys
import time

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.exec.fte import SpoolingExchange, deserialize_fragment_output
from trino_tpu.server.cluster import (ClusterCoordinator, WorkerServer,
                                      _OutputBuffer, _http,
                                      stream_task_pages)

CATALOGS = {"tpch": {"connector": "tpch", "sf": 0.01, "split_rows": 1 << 11}}


def _engine():
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 11))
    return e


# --------------------------------------------------------------- buffer unit
def test_output_buffer_token_ack_frees_memory():
    buf = _OutputBuffer(max_bytes=100)
    buf.add(b"x" * 40)
    buf.add(b"y" * 40)
    page, complete, failed = buf.get(0, max_wait=0.1)
    assert page == b"x" * 40 and not complete and not failed
    # token 1 acknowledges page 0: its bytes free, page 1 served
    page, complete, _ = buf.get(1, max_wait=0.1)
    assert page == b"y" * 40
    assert buf.bytes == 40
    buf.finish()
    page, complete, _ = buf.get(2, max_wait=0.1)
    assert page is None and complete


def test_output_buffer_backpressures_producer():
    import threading

    buf = _OutputBuffer(max_bytes=50)
    buf.add(b"a" * 40)
    state = {"second_added": False}

    def producer():
        buf.add(b"b" * 40)  # blocks: 80 > 50 with unacked page 0
        state["second_added"] = True

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    time.sleep(0.2)
    assert not state["second_added"], "producer must block while full"
    buf.get(1, max_wait=0.1)  # ack page 0 -> frees 40 bytes
    t.join(timeout=2)
    assert state["second_added"]


def test_output_buffer_failure_propagates():
    buf = _OutputBuffer()
    buf.fail("boom: exploded")
    page, complete, failed = buf.get(0, max_wait=0.1)
    assert failed and "boom" in failed


# ------------------------------------------------- worker protocol (in-proc)
def test_streaming_task_roundtrip_no_disk(tmp_path):
    """A fragment task with streaming output serves its pages over the
    long-poll endpoint and never writes a spool file."""
    e = _engine()
    w = WorkerServer(CATALOGS, str(tmp_path / "spool"))
    url = w.start()
    try:
        from trino_tpu.sql.frontend import compile_sql

        plan = compile_sql(
            "select o_orderkey, o_totalprice from orders "
            "order by o_totalprice desc limit 7",
            e, e.create_session("tpch"))
        xdir = str(tmp_path / "x")
        _http(f"{url}/v1/fragment",
              pickle.dumps({"fragment_id": "f1", "plan": plan}))
        _http(f"{url}/v1/task",
              pickle.dumps({"task_id": "t_stream", "fragment_id": "f1",
                            "kind": "fragment", "exchange_dir": xdir,
                            "output": "stream"}))
        chunks = list(stream_task_pages(url, "t_stream", timeout=60))
        assert len(chunks) == 1
        cols, nulls, dicts = deserialize_fragment_output(chunks[0])
        assert len(cols[0]) == 7
        assert not SpoolingExchange(xdir).is_committed("t_stream")
        # buffer is dropped after complete delivery
        time.sleep(0.1)
        assert "t_stream" not in w.out_buffers
    finally:
        w.stop()


def test_worker_concurrent_fragments(tmp_path):
    """Two fragment tasks overlap on one worker (executor pool replaced the
    round-3 global execution lock); peak_concurrency observes it."""
    e = _engine()
    w = WorkerServer(CATALOGS, str(tmp_path / "spool"))
    url = w.start()
    try:
        from trino_tpu.sql.frontend import compile_sql

        sql = ("select l_orderkey, sum(l_extendedprice * (1 - l_discount)) r "
               "from lineitem, orders where l_orderkey = o_orderkey "
               "group by l_orderkey order by r desc limit 5")
        plan = compile_sql(sql, e, e.create_session("tpch"))
        xdir = str(tmp_path / "x")
        _http(f"{url}/v1/fragment",
              pickle.dumps({"fragment_id": "fc", "plan": plan}))
        for tid in ("c1", "c2"):
            _http(f"{url}/v1/task",
                  pickle.dumps({"task_id": tid, "fragment_id": "fc",
                                "kind": "fragment", "exchange_dir": xdir}))
        deadline = time.time() + 120
        while time.time() < deadline:
            states = [json.loads(_http(f"{url}/v1/task/{tid}")).get("state")
                      for tid in ("c1", "c2")]
            if all(s == "done" for s in states):
                break
            assert "failed" not in states, states
            time.sleep(0.1)
        else:
            raise AssertionError(f"tasks did not finish: {states}")
        info = json.loads(_http(f"{url}/v1/info"))
        assert info["peak_concurrency"] >= 2, info
        ex = SpoolingExchange(xdir)
        a = deserialize_fragment_output(ex.read("c1"))
        b = deserialize_fragment_output(ex.read("c2"))
        assert [list(c) for c in a[0]] == [list(c) for c in b[0]]
    finally:
        w.stop()


# ------------------------------------------- cluster plane (OS processes)
def _spawn_worker(tmp_path, coord_url, node_id):
    env = dict(os.environ)
    env["TRINO_TPU_WORKER_CPU"] = "1"
    repo_root = str(pathlib.Path(__file__).resolve().parents[1])
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-m", "trino_tpu.server.cluster",
         "--coordinator", coord_url, "--catalogs", json.dumps(CATALOGS),
         "--spool", str(tmp_path / "spool"), "--node-id", node_id],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)


@pytest.mark.slow
def test_streaming_exchange_worker_to_worker(tmp_path):
    """A join build side (and the whole nested single-task fragment chain)
    streams worker->worker through in-memory buffers — no spool files for the
    streamed producers — and the result matches local execution."""
    e = _engine()
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.3)
    assert coord.stream_exchange  # pipelined plane is the default
    url = coord.start()
    w1 = w2 = None
    sql = """select a.k, a.s, b.c_name from
             (select o_custkey k, sum(o_totalprice) s from orders
              group by o_custkey) a,
             (select c_custkey, c_name, c_acctbal from customer
              order by c_acctbal desc, c_custkey limit 50) b
             where a.k = b.c_custkey order by a.s desc, a.k limit 10"""
    try:
        w1 = _spawn_worker(tmp_path, url, "w1")
        w2 = _spawn_worker(tmp_path, url, "w2")
        coord.wait_for_workers(2, timeout=60)
        expected = e.execute_sql(sql).rows()
        got = coord.execute_sql(sql).rows()
        assert got == expected
        assert coord.streamed_tasks >= 1, \
            "no fragment streamed (pipelined plane did not engage)"
    finally:
        coord.stop()
        for w in (w1, w2):
            if w is not None:
                w.terminate()
                w.wait(timeout=10)


# ------------------------------------------- broadcast buffer (multi-reader)
def test_output_buffer_broadcast_refcounts_readers():
    """Pages free only once EVERY reader slot acknowledged them (reference:
    execution/buffer/BroadcastOutputBuffer.java); an abandoned reader stops
    counting toward retention."""
    buf = _OutputBuffer(max_bytes=1000, n_readers=3)
    buf.add(b"p" * 100)
    buf.finish()
    for r in range(3):
        page, complete, failed = buf.get(0, max_wait=0.1, reader=r)
        assert page == b"p" * 100 and failed is None
    # readers 0/1 complete; page retained for reader 2
    for r in (0, 1):
        _, complete, _ = buf.get(1, max_wait=0.1, reader=r)
        assert complete
    assert buf.bytes == 100 and not buf.fully_delivered
    buf.abandon(2)
    assert buf.bytes == 0 and buf.fully_delivered


def test_output_buffer_unknown_reader_rejected():
    buf = _OutputBuffer(n_readers=2)
    page, complete, failed = buf.get(0, max_wait=0.05, reader=5)
    assert failed and "reader" in failed


# ---------------------------------------- fan-out streaming (cluster plane)
FANOUT_SQL = """select o.o_orderkey, b.c_name from orders o
                join (select c_custkey, c_name, c_acctbal from customer
                      order by c_acctbal desc, c_custkey limit 50) b
                  on o.o_custkey = b.c_custkey
                order by o.o_orderkey limit 20"""


@pytest.mark.slow
def test_fanout_join_streams_build_side(tmp_path):
    """A split-fanout join probe consumes its build-side fragment through a
    BROADCAST streaming buffer (one reader slot per probe task) instead of
    the spool (round-4 verdict item 3: fan-out stages must stream)."""
    e = _engine()
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.3)
    url = coord.start()
    w1 = w2 = None
    try:
        w1 = _spawn_worker(tmp_path, url, "w1")
        w2 = _spawn_worker(tmp_path, url, "w2")
        coord.wait_for_workers(2, timeout=60)
        expected = e.execute_sql(FANOUT_SQL).rows()
        got = coord.execute_sql(FANOUT_SQL).rows()
        assert got == expected
        assert coord.broadcast_streams >= 1, \
            "build side did not broadcast-stream (spool fallback engaged)"
        assert coord.local_fallbacks == 0
    finally:
        coord.stop()
        for w in (w1, w2):
            if w is not None:
                w.terminate()
                w.wait(timeout=10)


@pytest.mark.slow
def test_stream_failure_replays_producers(tmp_path, monkeypatch):
    """An injected consumer-side stream failure retries by REPLAYING the
    producer chain (fresh dedicated producers) instead of degrading the query
    to the local path (round-4 verdict item 3: stream retry)."""
    import trino_tpu.server.cluster as cluster_mod

    e = _engine()
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.3)
    url = coord.start()
    w1 = w2 = None
    real = cluster_mod.stream_task_pages
    tripped = {}

    def flaky(u, task_id, secret=None, timeout=60.0, reader=0):
        # first fetch of each ORIGINAL producer task fails once, mid-protocol
        # (respawned producers carry a "~" suffix and must fetch cleanly)
        if "~" not in task_id and task_id not in tripped:
            tripped[task_id] = True
            raise RuntimeError("injected stream failure (GET_RESULTS)")
        return real(u, task_id, secret=secret, timeout=timeout, reader=reader)

    # patch the COORDINATOR side only: subprocess workers import their own
    # module copy, so the consumer tasks there fetch normally — the injection
    # lands on the coordinator's local finish... which never streams.  Patch
    # instead where consumers run: in-process workers.
    monkeypatch.setattr(cluster_mod, "stream_task_pages", flaky)
    in_w1 = WorkerServer(CATALOGS, str(tmp_path / "spool"), node_id="iw1",
                         coordinator_url=url)
    in_w2 = WorkerServer(CATALOGS, str(tmp_path / "spool"), node_id="iw2",
                         coordinator_url=url)
    in_w1.start()
    in_w2.start()
    try:
        coord.wait_for_workers(2, timeout=60)
        expected = e.execute_sql(FANOUT_SQL).rows()
        got = coord.execute_sql(FANOUT_SQL).rows()
        assert got == expected
        assert tripped, "injection never fired (no consumer streamed)"
        assert coord.stream_retries >= 1, \
            "stream failure did not take the replay path"
        assert coord.local_fallbacks == 0, \
            "query degraded to local instead of replaying the stream"
    finally:
        coord.stop()
        in_w1.stop()
        in_w2.stop()


@pytest.mark.slow
def test_producer_worker_death_mid_stream_recovers(tmp_path):
    """Killing the OS process hosting a streaming producer mid-query: the
    consumer's fetch fails, the coordinator replays the producer chain on a
    surviving worker, and the query completes distributed (no local rerun)."""
    import threading

    e = _engine()
    # max_attempts=6: dispatch offers against the dying (not-yet-gated) worker
    # burn attempts by design, on top of the genuine stream-failure retry
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.2, max_misses=2,
                               max_attempts=6)
    url = coord.start()
    w1 = w2 = w3 = None
    try:
        w1 = _spawn_worker(tmp_path, url, "w1")
        w2 = _spawn_worker(tmp_path, url, "w2")
        w3 = _spawn_worker(tmp_path, url, "w3")
        coord.wait_for_workers(3, timeout=60)
        expected = e.execute_sql(FANOUT_SQL).rows()
        result = {}

        def run_query():
            try:
                result["rows"] = coord.execute_sql(FANOUT_SQL).rows()
            except Exception as ex:  # pragma: no cover - surfaced below
                result["error"] = ex

        t = threading.Thread(target=run_query)
        t.start()
        # the moment a streaming producer is recorded, kill its host process
        deadline = time.time() + 60
        killed = False
        while time.time() < deadline and not killed:
            recs = dict(coord._stream_producers)
            if recs:
                # map producer url -> worker process via the coordinator's
                # registry (node_id order matches spawn order w1/w2/w3)
                with coord._lock:
                    url_to_node = {wi.url: wi.node_id
                                   for wi in coord.workers.values()}
                for rec in recs.values():
                    node = url_to_node.get(rec["url"])
                    proc = {"w1": w1, "w2": w2, "w3": w3}.get(node)
                    if proc is not None and proc.poll() is None:
                        proc.kill()
                        proc.wait(timeout=10)
                        killed = True
                        break
            time.sleep(0.01)
        t.join(timeout=300)
        assert not t.is_alive(), "query wedged after producer death"
        assert "error" not in result, result.get("error")
        assert result["rows"] == expected
        if killed:
            assert coord.local_fallbacks == 0, \
                f"producer death degraded the query to local: " \
                f"{coord.last_fallback_error}"
    finally:
        coord.stop()
        for w in (w1, w2, w3):
            if w is not None and w.poll() is None:
                w.terminate()
                w.wait(timeout=10)
