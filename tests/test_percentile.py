"""approx_percentile via exact sort-based selection (reference:
operator/aggregation/ApproximateLongPercentileAggregations' t-digest,
re-designed as one device lexsort + segmented nth-element gathers — exact
selection is within the function's accuracy contract)."""

import numpy as np
import pandas as pd
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.tpch import TpchConnector


@pytest.fixture(scope="module")
def eng():
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 13))
    return e, e.create_session("tpch")


@pytest.fixture(scope="module")
def lineitem(eng):
    e, _ = eng
    conn = e.catalogs["tpch"]
    parts = [pd.DataFrame(conn.generate(sp).to_numpy(
        conn.dictionaries("lineitem"))) for sp in conn.splits("lineitem")]
    return pd.concat(parts, ignore_index=True)


def _nearest_rank(series, p):
    v = np.sort(series.to_numpy())
    return v[int(np.clip(round(p * (len(v) - 1)), 0, len(v) - 1))]


def test_global_percentiles(eng, lineitem):
    e, s = eng
    r = e.execute_sql(
        "select approx_percentile(l_quantity, 0.5) p50, "
        "approx_percentile(l_quantity, 0.95) p95, "
        "approx_percentile(l_extendedprice, 0.99) p99 from lineitem",
        s).rows()[0]
    assert float(r[0]) == _nearest_rank(lineitem.l_quantity, 0.5)
    assert float(r[1]) == _nearest_rank(lineitem.l_quantity, 0.95)
    assert abs(float(r[2]) - _nearest_rank(lineitem.l_extendedprice, 0.99)) \
        < 0.01


def test_grouped_percentile(eng, lineitem):
    e, s = eng
    got = e.execute_sql(
        "select l_returnflag, approx_percentile(l_extendedprice, 0.5) med "
        "from lineitem group by l_returnflag order by l_returnflag",
        s).to_pandas()
    ref = lineitem.groupby("l_returnflag").l_extendedprice.apply(
        lambda v: _nearest_rank(v, 0.5))
    assert got["l_returnflag"].tolist() == list(ref.index)
    np.testing.assert_allclose(got["med"].astype(float), ref.to_numpy(),
                               atol=0.01)


def test_percentile_with_filter_and_join(eng, lineitem):
    e, s = eng
    got = e.execute_sql(
        "select o_orderpriority, approx_percentile(l_quantity, 0.9) q90 "
        "from lineitem, orders where l_orderkey = o_orderkey "
        "and l_shipdate > date '1995-01-01' "
        "group by o_orderpriority order by o_orderpriority", s).to_pandas()
    assert len(got) >= 2
    assert (got["q90"].astype(float) >= 1).all()
    assert (got["q90"].astype(float) <= 50).all()


def test_percentile_nulls_and_empty_groups():
    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table t (k bigint, v double)", s)
    e.execute_sql("insert into t values (1, 10.0), (1, 20.0), (1, 30.0), "
                  "(2, null), (2, null), (3, 5.0)", s)
    got = e.execute_sql(
        "select k, approx_percentile(v, 0.5) m from t group by k order by k",
        s).to_pandas()
    assert got["k"].tolist() == [1, 2, 3]
    assert float(got["m"].iloc[0]) == 20.0
    assert pd.isna(got["m"].iloc[1])  # all-NULL group -> NULL
    assert float(got["m"].iloc[2]) == 5.0


def test_percentile_mixes_with_hash_aggs(eng):
    """Round 5: sorted-runner aggregates compose with hash aggregates via
    per-part aggregations joined on the group keys (was a rejection)."""
    e, s = eng
    r = e.execute_sql("select approx_percentile(l_quantity, 0.5) p, count(*) c "
                      "from lineitem", s).to_pandas()
    c = e.execute_sql("select count(*) c from lineitem", s).to_pandas()
    assert r["c"].iloc[0] == c["c"].iloc[0]
    assert r["p"].iloc[0] > 0


def test_listagg_grouped_ordered(eng):
    e, s = eng
    r = e.execute_sql(
        "select r_name, listagg(n_name, ', ') within group (order by n_name) "
        "nations from nation, region where n_regionkey = r_regionkey "
        "group by r_name order by r_name", s).to_pandas()
    assert r["nations"].iloc[0] == \
        "ALGERIA, ETHIOPIA, KENYA, MOROCCO, MOZAMBIQUE"
    assert len(r) == 5


def test_listagg_global_desc(eng):
    e, s = eng
    r = e.execute_sql(
        "select listagg(r_name, '|') within group (order by r_name desc) x "
        "from region", s).rows()[0][0]
    assert r == "MIDDLE EAST|EUROPE|ASIA|AMERICA|AFRICA"


def test_listagg_null_values_skipped():
    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table t (k bigint, v varchar)", s)
    e.execute_sql("insert into t values (1, 'b'), (1, null), (1, 'a'), "
                  "(2, null)", s)
    got = e.execute_sql(
        "select k, listagg(v, '+') within group (order by v) x from t "
        "group by k order by k", s).to_pandas()
    assert got["x"].iloc[0] == "a+b"
    assert pd.isna(got["x"].iloc[1])  # all-NULL group -> NULL
